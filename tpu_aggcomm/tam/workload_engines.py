"""Variable-size workload engines: the collective_write family.

The reference ships four generations of its hierarchical exchange engine
(lustre_driver_test.c): ``collective_write`` (944-1309, the production
proxy path), ``collective_write2`` (754-926, two-level local aggregators +
zero-copy derived datatypes), ``collective_write3`` (604-728, MPI-3 shared
-memory windows for the intra-node hop), and ``collective_write_benchmark``
(1311-1330, flat direct exchange).  All four deliver the same bytes — for
every destination ``g`` and source ``s``, ``recv_buf[s] = MAP_DATA(s,g,·)``
— and differ only in the *route*.  Here each engine is

- an **oracle**: an explicit numpy simulation of the route that returns the
  delivered buffers plus per-hop byte accounting (``RouteStats``), so tests
  can pin both delivery and route shape; and
- for the two-level engine, a **JAX mesh program**
  (:func:`cw2_local_agg_jax`) on a ``(node, local)`` mesh — intra-node hops
  ride the inner (ICI) axis, aggregator↔aggregator exchange rides the outer
  (DCN) axis.  The reference's hindexed derived datatypes
  (``create_recv_type``, l_d_t.c:1332-1361; the MPI_BOTTOM sends at
  848-856, 899-902) become static index maps — message sizes are pure
  functions of rank (workload property), so every pack/scatter compiles to
  fixed gathers over padded buffers.

Source ordering: the reference orders a group's sources by the
``aggregator_local_ranks`` array on the send side (l_d_t.c:885-904) but by
ascending rank scan on the receive side (create_recv_type, 1339-1346);
those differ whenever the binding scan inserts the aggregator's own rank
out of order (l_d_t.c:193-229).  Since collective_write2 is dead code in
the reference (call commented out at 1497), we fix the hazard: both sides
use ascending source rank within a group (:func:`recv_index_map`).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tpu_aggcomm.compat import shard_map as _compat_shard_map
from tpu_aggcomm.core.meta import AggregatorMeta
from tpu_aggcomm.core.topology import NodeAssignment
from tpu_aggcomm.core.workload import Workload

__all__ = [
    "RouteStats", "recv_index_map",
    "cw_benchmark", "cw_proxy", "cw2_local_agg", "cw3_shared",
    "cw2_local_agg_jax", "cw3_shared_jax", "cw_proxy_sim",
    "WORKLOAD_ENGINES", "run_workload_engine",
]


@dataclass
class RouteStats:
    """Bytes moved per hop class — the quantities the reference's phase
    timers bracket. ``staged_bytes`` counts shared-memory staging
    (collective_write3's window fill), which crosses no network link."""

    direct_bytes: int = 0        # flat src -> dst messages
    gather_bytes: int = 0        # non-aggregator -> its local aggregator/proxy
    exchange_intra_bytes: int = 0  # agg <-> agg on the same node
    exchange_inter_bytes: int = 0  # agg <-> agg across nodes (the DCN hop)
    delivery_bytes: int = 0      # proxy -> final local destination
    staged_bytes: int = 0        # shared-window staging (no link crossed)

    @property
    def network_bytes(self) -> int:
        return (self.direct_bytes + self.gather_bytes +
                self.exchange_intra_bytes + self.exchange_inter_bytes +
                self.delivery_bytes)


def _empty_recv(wl: Workload) -> dict[int, list[np.ndarray | None]]:
    return {int(g): wl.alloc_recv_bufs(int(g)) for g in wl.aggregators}


def recv_index_map(wl: Workload, meta: AggregatorMeta) -> dict[int, list[tuple[int, int]]]:
    """``create_recv_type`` analog (l_d_t.c:1332-1361): for each local
    aggregator, the ordered ``(source_rank, size)`` runs that make up one
    incoming group message at any destination.  In MPI this list becomes an
    hindexed datatype over scattered ``recv_buf`` pointers; on TPU it is the
    static scatter map from a received packed segment into per-source slots."""
    sizes = wl.msg_size
    out: dict[int, list[tuple[int, int]]] = {}
    for agg in meta.local_aggregators:
        out[int(agg)] = [(int(w), int(sizes[w]))
                         for w in np.nonzero(meta.owner_of == agg)[0]]
    return out


# ---------------------------------------------------------------------------
# collective_write_benchmark (l_d_t.c:1311-1330): flat direct exchange

def cw_benchmark(wl: Workload):
    """Direct Issend/Irecv per (src, dst) pair — the baseline route."""
    recv = _empty_recv(wl)
    stats = RouteStats()
    for dst in wl.aggregators:
        for src in range(wl.nprocs):
            msg = wl.fill(src, int(dst))
            recv[int(dst)][src][:] = msg
            stats.direct_bytes += len(msg)
    return recv, stats


# ---------------------------------------------------------------------------
# collective_write (l_d_t.c:944-1309): proxy path, one relay per node

def cw_proxy(wl: Workload, na: NodeAssignment, corrupt_hook=None):
    """The production 5-phase proxy route with variable sizes.

    P1 (size exchange) is compile-time static here — sizes are pure
    functions of rank (the reference's runtime handshake, l_d_t.c:996-1041,
    carries no extra information for these workloads).  P2: every rank's
    packed sends go to its node proxy; P3: proxies exchange per-node runs;
    P4: destination proxies deliver each local destination its slab;
    P5: local scatter into recv_buf.

    Payload bytes are filled ONCE at the sender (P2) and carried through
    the staging structures to delivery — a routing bug therefore delivers
    wrong bytes and fails ``verify_all``, instead of being masked by a
    delivery-time re-fill (VERDICT r2 item 6). ``corrupt_hook(holdings)``
    is the fault-injection seam: tests corrupt one staged message between
    P2 and P3 and assert verification catches it.
    """
    recv = _empty_recv(wl)
    stats = RouteStats()
    sizes = wl.msg_size
    is_dst = wl.is_aggregator

    # P2: sender pack -> node proxy (self-pack for the proxy, l_d_t.c:1069-1105)
    # holdings[node] = (src, dst, payload) messages staged at the proxy
    holdings: list[list[tuple[int, int, np.ndarray]]] = \
        [[] for _ in range(na.nnodes)]
    for src in range(wl.nprocs):
        node = int(na.node_of[src])
        for d in wl.aggregators:
            holdings[node].append((src, int(d), wl.fill(src, int(d))))
        if not na.is_proxy(src):
            stats.gather_bytes += int(sizes[src]) * len(wl.aggregators)
    if corrupt_hook is not None:
        corrupt_hook(holdings)

    # P3: proxy -> proxy per-destination-node runs (l_d_t.c:1121-1194);
    # the STAGED payload travels, nothing is re-derived
    incoming: list[list[tuple[int, int, np.ndarray]]] = \
        [[] for _ in range(na.nnodes)]
    for node, held in enumerate(holdings):
        for (src, dst, payload) in held:
            dnode = int(na.node_of[dst])
            incoming[dnode].append((src, dst, payload))
            if dnode != node:
                stats.exchange_inter_bytes += int(sizes[src])
            # same-node messages are the memcpy at l_d_t.c:1184 — no link

    # P4/P5: destination proxy re-packs per local destination and delivers
    for node, msgs in enumerate(incoming):
        for (src, dst, payload) in msgs:
            recv[dst][src][:] = payload
            if not na.is_proxy(dst):
                stats.delivery_bytes += int(sizes[src])
    # non-destination ranks receive nothing; is_dst guard for clarity
    assert all(is_dst[d] for d in recv)
    return recv, stats


# ---------------------------------------------------------------------------
# collective_write2 (l_d_t.c:754-926): two-level local aggregators

def cw2_local_agg(wl: Workload, na: NodeAssignment, meta: AggregatorMeta,
                  corrupt_hook=None):
    """Two-level route: rank → its local aggregator (packed hindexed send,
    l_d_t.c:848-856) → per-destination segments → global destination
    (received through the recv_index_map scatter). Payloads are staged at
    the local aggregator and carried into the segments — delivery reads
    the staged bytes, never re-fills (VERDICT r2 item 6);
    ``corrupt_hook(staged)`` injects faults between the hops for tests."""
    recv = _empty_recv(wl)
    stats = RouteStats()
    sizes = wl.msg_size
    rim = recv_index_map(wl, meta)

    # hop 1: gather at local aggregators (skip self, l_d_t.c:829-856):
    # staged[agg][src][dst] = the member's packed block for dst
    staged: dict[int, dict[int, dict[int, np.ndarray]]] = {
        int(a): {} for a in meta.local_aggregators}
    for src in range(wl.nprocs):
        owner = int(meta.owner_of[src])
        staged[owner][src] = {int(d): wl.fill(src, int(d))
                              for d in wl.aggregators}
        if owner != src:
            stats.gather_bytes += int(sizes[src]) * len(wl.aggregators)
    if corrupt_hook is not None:
        corrupt_hook(staged)

    # hop 2: local aggregator -> each global destination, one packed segment
    # per (group, destination); scattered at the destination via the index map
    for agg, group in rim.items():
        for dst in wl.aggregators:
            seg_bytes = 0
            for (src, sz) in group:
                recv[int(dst)][src][:] = staged[agg][src][int(dst)]
                seg_bytes += sz
            if int(na.node_of[agg]) == int(na.node_of[int(dst)]):
                stats.exchange_intra_bytes += seg_bytes
            else:
                stats.exchange_inter_bytes += seg_bytes
    return recv, stats


# ---------------------------------------------------------------------------
# collective_write3 (l_d_t.c:604-728): shared-window intra hop

def cw3_shared(wl: Workload, na: NodeAssignment, meta: AggregatorMeta,
               corrupt_hook=None):
    """Shared-memory route: group members stage [sizes header | packed
    sends] in a shared window (l_d_t.c:647-663); after the fence the local
    aggregator reads every member's staging zero-copy (shared_query,
    667-671) and exchanges hindexed segments directly with the destination
    aggregators (705-711). The window content is what gets delivered —
    no re-fill at delivery; ``corrupt_hook(windows)`` injects faults
    after the fence for tests.

    Requires every destination to be a local aggregator (the reference
    sends only to ``local_aggregators`` — use meta mode 1, which makes
    local aggregators a superset of the global set).  The TPU analog of the
    shared window is staging in same-slice HBM: the inner-axis hop of
    :func:`cw2_local_agg_jax` with zero link cost.
    """
    is_local = meta.is_local_aggregator
    missing = [int(d) for d in wl.aggregators if not is_local[int(d)]]
    if missing:
        raise ValueError(
            f"collective_write3 route requires destinations to be local "
            f"aggregators (meta mode 1); not local: {missing}")
    # shared windows exist per intra-group; groups must not span nodes
    for agg in meta.local_aggregators:
        nodes = {int(na.node_of[w]) for w in meta.owned_ranks(int(agg))}
        nodes.add(int(na.node_of[int(agg)]))
        if len(nodes) > 1:
            raise ValueError(f"group of local aggregator {int(agg)} spans "
                             f"nodes {sorted(nodes)}; shared window invalid")

    recv = _empty_recv(wl)
    stats = RouteStats()
    sizes = wl.msg_size
    rim = recv_index_map(wl, meta)

    # window fill (l_d_t.c:647-663): every member stages its packed sends
    # in its group's shared window; the fence makes them readable
    windows: dict[int, dict[int, dict[int, np.ndarray]]] = {}
    for agg, group in rim.items():
        windows[agg] = {}
        for (src, _sz) in group:
            windows[agg][src] = {int(d): wl.fill(src, int(d))
                                 for d in wl.aggregators}
            stats.staged_bytes += int(sizes[src]) * len(wl.aggregators)
    if corrupt_hook is not None:
        corrupt_hook(windows)

    for agg, group in rim.items():
        for dst in wl.aggregators:
            seg_bytes = 0
            for (src, sz) in group:
                recv[int(dst)][src][:] = windows[agg][src][int(dst)]
                seg_bytes += sz
            if int(agg) == int(dst):
                continue  # self segment: local memcpy
            if int(na.node_of[int(agg)]) == int(na.node_of[int(dst)]):
                stats.exchange_intra_bytes += seg_bytes
            else:
                stats.exchange_inter_bytes += seg_bytes
    return recv, stats


# ---------------------------------------------------------------------------
# JAX mesh engine for the two-level route

def _two_level_mesh_exchange(wl: Workload, na: NodeAssignment,
                             meta: AggregatorMeta, devices, ntimes: int,
                             staging: str, caller: str):
    """Shared body of the two compiled two-level engines.

    Rank ``r`` lives at coordinate ``(r // L, r % L)`` on a
    ``(node, local)`` mesh (contiguous node map). Blocks are padded to the
    workload's max size and carried as uint32 lanes on device (CLAUDE.md:
    uint8 paths are 4-5x slower on TPU); the byte view is restored at the
    host boundary. The engines differ ONLY in how a local aggregator comes
    to hold its group members' blocks — the ``staging`` hop:

    - ``"targeted"`` (collective_write2): one-hot scatter + inner-axis
      ``all_to_all`` — each member's block is *sent* to its owner's
      coordinate (the hindexed gather, l_d_t.c:848-856).
    - ``"shared"`` (collective_write3): inner-axis ``all_gather`` — the
      node's staging is replicated in-slice (the shared window + fence,
      l_d_t.c:647-671) and each owner *reads* the blocks of the ranks it
      owns (shared_query semantics: a read, not a targeted message).

    After that, both run the identical aggregator↔aggregator hindexed
    exchange (l_d_t.c:899-902 / 705-711): outer-axis ``all_to_all`` of
    per-destination-node segments, then an inner-axis hop delivering each
    slot to its destination's local coordinate with recv_index_map
    scattering (recv_types, l_d_t.c:1332-1361).

    Returns ``(recv_by_rank, rep_times)``; recv rows are unpadded to the
    true per-source sizes before being handed back.
    """
    import time as _time

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpu_aggcomm.backends.lanes import (lane_layout, lanes_to_bytes,
                                            to_lanes)

    n = wl.nprocs
    if na.nnodes < 1 or n % na.nnodes:
        raise ValueError(f"{caller} needs equal-size nodes")
    L = n // na.nnodes
    N = na.nnodes
    if not np.array_equal(na.node_of, np.arange(n) // L):
        raise ValueError(f"{caller} needs the contiguous node map "
                         f"(static_node_assignment kind 0)")
    if len(devices) < n:
        raise ValueError(
            f"need {n} devices, have {len(devices)} (hint: JAX_PLATFORMS=cpu "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n})")

    aggs = np.asarray(wl.aggregators, dtype=np.int64)
    G = len(aggs)
    sizes = np.asarray(wl.msg_size)
    S = -(-wl.max_msg_size // 4) * 4     # pad to a whole uint32 lane count
    _, jdt, W = lane_layout(S)

    # destination geometry: node + local coordinate of each destination,
    # grouped per node with K = max destinations on one node
    agg_node = aggs // L
    agg_local = aggs % L
    K = max(1, int(np.bincount(agg_node, minlength=N).max()))
    aggs_of_node = np.full((N, K), -1, dtype=np.int64)   # -> index into aggs
    cnt = np.zeros(N, dtype=np.int64)
    for gi, b in enumerate(agg_node):
        aggs_of_node[b, cnt[b]] = gi
        cnt[b] += 1
    local_of_slot = np.where(aggs_of_node >= 0,
                             agg_local[np.maximum(aggs_of_node, 0)], -1)

    owner_local = (np.asarray(meta.owner_of) % L).astype(np.int64)  # per rank

    # host-side payload: (N, L, G, W) padded send blocks in lane layout
    send_g = np.zeros((n, G, S), dtype=np.uint8)
    for r in range(n):
        m = int(sizes[r])
        for gi, g in enumerate(aggs):
            send_g[r, gi, :m] = wl.fill(r, int(g))
    send_g = to_lanes(send_g, S).reshape(N, L, G, W)

    from tpu_aggcomm.parallel import (host_major_devices,
                                      warn_if_node_straddles_hosts)
    devices = host_major_devices(devices)
    warn_if_node_straddles_hosts(devices[:n], L, caller)
    mesh = Mesh(np.array(devices[:n]).reshape(N, L), ("node", "local"))
    sharding = NamedSharding(mesh, P("node", "local"))
    send_dev = jax.device_put(send_g, sharding)

    owner_local_j = jnp.asarray(owner_local.reshape(N, L))
    aggs_of_node_j = jnp.asarray(aggs_of_node)
    local_of_slot_j = jnp.asarray(local_of_slot)

    def local_fn(send):
        x = send[0, 0]                                   # (G, W) my block
        mynode = lax.axis_index("node")
        mylocal = lax.axis_index("local")

        # staging hop (inner axis): owners end up holding their group
        if staging == "targeted":
            # block -> my local aggregator's coordinate (targeted send)
            my_owner = owner_local_j[mynode, mylocal]    # scalar
            buf1 = jnp.zeros((L + 1, G, W), jdt).at[my_owner].set(x)[:L]
            held = lax.all_to_all(buf1, "local", 0, 0)   # (L, G, W)
            # held[l'] = block of source (mynode, l') iff I am its owner
        else:
            # shared window: the node's staging replicated in-slice; the
            # fence is implicit in the collective, and I *read* exactly
            # the blocks of the ranks I own
            staged = lax.all_gather(x, "local")          # (L, G, W)
            owned = (owner_local_j[mynode] == mylocal)   # (L,)
            held = staged * owned[:, None, None].astype(jdt)

        # exchange hop (outer axis): per-destination-node segments
        # buf2[b', j, l'] = held[l', slot j of node b']
        sel = jnp.maximum(aggs_of_node_j, 0)             # (N, K)
        mask = (aggs_of_node_j >= 0).astype(jdt)[..., None, None]
        byslot = jnp.take(held, sel.reshape(-1), axis=1)  # (L, N*K, W)
        byslot = byslot.reshape(L, N, K, W).transpose(1, 2, 0, 3) * mask
        got2 = lax.all_to_all(byslot, "node", 0, 0)      # (N, K, L, W)
        # got2[b_src, j, l_src] = message (b_src·L+l_src -> my-node slot j)
        # held at the source-side owner's local coordinate (= my coordinate)

        # delivery hop (inner axis): slot j -> destination's local coord
        dl = jnp.where(local_of_slot_j[mynode] >= 0,
                       local_of_slot_j[mynode], L)       # (K,)
        buf3 = jnp.zeros((L + 1, K, N, L, W), jdt)
        buf3 = buf3.at[dl].set(got2.transpose(1, 0, 2, 3))[:L]
        got3 = lax.all_to_all(buf3, "local", 0, 0)       # (L, K, N, L, W)
        # got3[l_holder, j, b_src, l_src]: nonzero only at the destination
        # coordinate of slot j, from the holder that owned (b_src, l_src).
        # Disjoint owners => sum collapses the holder axis losslessly.
        merged = got3.sum(axis=0, dtype=jdt)             # (K, N, L, W)

        # select my slot (at most one destination per (node, local) coord)
        is_mine = (local_of_slot_j[mynode] == mylocal)   # (K,)
        recv = jnp.where(is_mine[:, None, None, None], merged, 0
                         ).sum(axis=0, dtype=jdt)        # (N, L, W)
        return recv.reshape(n, W)[None, None]

    fn = jax.jit(_compat_shard_map(local_fn, mesh=mesh,
                               in_specs=P("node", "local"),
                               out_specs=P("node", "local")))

    fn(send_dev).block_until_ready()                     # warm-up compile
    rep_times = []
    out_dev = None
    for _ in range(max(ntimes, 1)):
        t0 = _time.perf_counter()
        out_dev = fn(send_dev)
        out_dev.block_until_ready()
        rep_times.append(_time.perf_counter() - t0)
    out = lanes_to_bytes(
        np.asarray(jax.device_get(out_dev)).reshape(n, n, W), S)

    is_dst = wl.is_aggregator
    recv_by_rank: dict[int, list[np.ndarray | None]] = {}
    for g in wl.aggregators:
        g = int(g)
        recv_by_rank[g] = [out[g, src, :int(sizes[src])].copy()
                           for src in range(n)]
    assert all(is_dst[g] for g in recv_by_rank)
    return recv_by_rank, rep_times


def cw2_local_agg_jax(wl: Workload, na: NodeAssignment, meta: AggregatorMeta,
                      devices, ntimes: int = 1):
    """Run the collective_write2 route on a ``(node, local)`` mesh: the
    targeted-staging variant of :func:`_two_level_mesh_exchange` (members
    *send* their blocks to their local aggregator — the hindexed gather,
    l_d_t.c:848-856 — then the aggregator↔aggregator exchange)."""
    return _two_level_mesh_exchange(wl, na, meta, devices, ntimes,
                                    "targeted", "cw2_local_agg_jax")


# ---------------------------------------------------------------------------
# JAX mesh engine for the shared-window route (collective_write3)

def cw3_shared_jax(wl: Workload, na: NodeAssignment, meta: AggregatorMeta,
                   devices, ntimes: int = 1):
    """Run the collective_write3 route on a ``(node, local)`` mesh.

    The reference's MPI-3 shared window (l_d_t.c:647-671) lets every rank
    of a node *fill* a staging region and lets its local aggregator *read*
    all members' staging zero-copy after a fence. The same-slice analog:
    the intra-node hop is an inner-axis ``all_gather`` — every chip of the
    slice holds the node's full staging buffer in its HBM, and each local
    aggregator *selects* the blocks of the ranks it owns from that
    replicated staging (a read, not a targeted message: exactly the
    shared-query semantics). The aggregator↔aggregator hindexed exchange
    (l_d_t.c:705-711) then rides the outer (DCN) axis, identical to the
    collective_write2 exchange — which mirrors the reference, where cw2
    and cw3 differ only in how the intra-node gather happens.

    Requires the cw3 preconditions (destinations are local aggregators —
    meta mode 1 — and no group spans nodes); raises like
    :func:`cw3_shared` otherwise. Returns ``(recv_by_rank, rep_times)``.
    """
    # same validity domain as the oracle (shared windows are per node)
    is_local = meta.is_local_aggregator
    missing = [int(d) for d in wl.aggregators if not is_local[int(d)]]
    if missing:
        raise ValueError(
            f"collective_write3 route requires destinations to be local "
            f"aggregators (meta mode 1); not local: {missing}")
    for agg in meta.local_aggregators:
        nodes = {int(na.node_of[w]) for w in meta.owned_ranks(int(agg))}
        nodes.add(int(na.node_of[int(agg)]))
        if len(nodes) > 1:
            raise ValueError(f"group of local aggregator {int(agg)} spans "
                             f"nodes {sorted(nodes)}; shared window invalid")
    return _two_level_mesh_exchange(wl, na, meta, devices, ntimes,
                                    "shared", "cw3_shared_jax")


# ---------------------------------------------------------------------------
# collective_write on ONE chip: the proxy route as compiled byte-permutation
# hops (variable sizes -> byte-granular index maps)

def cw_proxy_sim(wl: Workload, na: NodeAssignment, *, ntimes: int = 1,
                 device=None, chained: bool = False):
    """The 5-phase proxy route compiled for a single device.

    Message sizes vary per sender (1 + src % blocklen), so the static index
    maps are *byte*-granular: the whole exchange is three permutations of
    one flat byte array — P2 staging in proxy-hold order, P3 reorder into
    destination-node runs, P4/P5 delivery into the recv layout — each hop a
    fenced gather, mirroring cw_proxy's walk order exactly (the reference's
    runtime size handshake, l_d_t.c:996-1041, is compile-time here). This is
    the route the ``tam`` subcommand runs compiled on a real TPU chip.

    ``chained=True`` replaces the per-dispatch wall times with the
    serial-chained differenced measurement (harness/chained.py): through
    the TPU tunnel a single dispatch measures the ~60-90 ms RPC, not the
    route (ADVICE r1) — every returned rep time is then the differenced
    per-rep figure.

    Returns (recv dict like the oracle engines, per-rep wall seconds).
    """
    import time

    import jax
    import jax.numpy as jnp
    from jax import lax

    n = wl.nprocs
    sizes = wl.msg_size
    aggs = [int(a) for a in wl.aggregators]

    # flat send stream: src-major, dst in aggregator order (pack layout)
    msg_off: dict[tuple[int, int], int] = {}
    off = 0
    send_parts = []
    for src in range(n):
        for dst in aggs:
            msg_off[(src, dst)] = off
            off += int(sizes[src])
            send_parts.append(wl.fill(src, dst))
    total = off
    send_flat = np.concatenate(send_parts) if send_parts else \
        np.zeros(0, np.uint8)
    assert send_flat.size == total

    def byte_range(start: int, size: int) -> range:
        return range(start, start + size)

    # P2: proxy-hold order (cw_proxy holdings walk, l_d_t.c:1069-1105)
    stage_perm: list[int] = []
    stage_off: dict[tuple[int, int], int] = {}
    stage_order: list[tuple[int, int]] = []
    for node in range(na.nnodes):
        for src in na.local_ranks(node):
            for dst in aggs:
                key = (int(src), dst)
                stage_off[key] = len(stage_perm)
                stage_order.append(key)
                stage_perm.extend(byte_range(msg_off[key], int(sizes[src])))

    # P3: destination-node runs in proxy-hold order (l_d_t.c:1121-1194)
    exch_perm: list[int] = []
    exch_off: dict[tuple[int, int], int] = {}
    for node in range(na.nnodes):
        for (src, dst) in stage_order:
            if int(na.node_of[dst]) != node:
                continue
            exch_off[(src, dst)] = len(exch_perm)
            exch_perm.extend(byte_range(stage_off[(src, dst)],
                                        int(sizes[src])))

    # P4/P5: recv layout — per aggregator (sorted), per source, its message
    recv_perm: list[int] = []
    for dst in aggs:
        for src in range(n):
            recv_perm.extend(byte_range(exch_off[(src, dst)],
                                        int(sizes[src])))

    p1 = jnp.asarray(np.asarray(stage_perm, dtype=np.int32))
    p2 = jnp.asarray(np.asarray(exch_perm, dtype=np.int32))
    p3 = jnp.asarray(np.asarray(recv_perm, dtype=np.int32))

    @jax.jit
    def route(x):
        x = jnp.take(x, p1)                    # P2 gather at proxies
        (x,) = lax.optimization_barrier((x,))
        x = jnp.take(x, p2)                    # P3 proxy <-> proxy
        (x,) = lax.optimization_barrier((x,))
        return jnp.take(x, p3)                 # P4/P5 delivery

    dev = device if device is not None else jax.devices()[0]
    x0 = jax.device_put(jnp.asarray(send_flat), dev)
    route(x0).block_until_ready()              # warm-up compile
    if chained:
        from tpu_aggcomm.harness.chained import differenced_per_rep

        def make_chain(iters: int):
            @jax.jit
            def chain(x):
                def body(x, r):
                    y = jnp.take(x, p1)
                    (y,) = lax.optimization_barrier((y,))
                    y = jnp.take(y, p2)
                    (y,) = lax.optimization_barrier((y,))
                    y = jnp.take(y, p3)
                    # serial dependence: rep r+1 reads rep r's delivery,
                    # XOR-perturbed so iterations cannot fuse or hoist
                    return y ^ r, ()

                xs = (jnp.arange(iters, dtype=jnp.int32)
                      % 251).astype(jnp.uint8)
                x, _ = lax.scan(body, x, xs, unroll=1)
                return x
            return chain

        per_rep = differenced_per_rep(make_chain, x0, iters_small=50,
                                      iters_big=1050)
        times = [per_rep] * max(ntimes, 1)
        out = route(x0)
    else:
        times = []
        out = None
        for _ in range(max(ntimes, 1)):
            t0 = time.perf_counter()
            out = route(x0)
            out.block_until_ready()
            times.append(time.perf_counter() - t0)

    flat = np.asarray(jax.device_get(out))
    recv = _empty_recv(wl)
    pos = 0
    for dst in aggs:
        for src in range(n):
            sz = int(sizes[src])
            recv[dst][src][:] = flat[pos:pos + sz]
            pos += sz
    return recv, times


# ---------------------------------------------------------------------------
# registry

WORKLOAD_ENGINES = {
    "benchmark": cw_benchmark,       # collective_write_benchmark
    "proxy": cw_proxy,               # collective_write
    "local_agg": cw2_local_agg,      # collective_write2
    "shared": cw3_shared,            # collective_write3
}


def run_workload_engine(engine: str, wl: Workload, na: NodeAssignment,
                        meta: AggregatorMeta | None = None):
    """Dispatch one oracle engine by name; verifies nothing — callers run
    ``wl.verify_all`` on the returned buffers (the reference's
    test_correctness step, l_d_t.c:1502)."""
    if engine == "benchmark":
        return cw_benchmark(wl)
    if engine == "proxy":
        return cw_proxy(wl, na)
    if meta is None:
        raise ValueError(f"engine {engine!r} needs aggregator metadata (co)")
    if engine == "local_agg":
        return cw2_local_agg(wl, na, meta)
    if engine == "shared":
        return cw3_shared(wl, na, meta)
    raise ValueError(f"unknown workload engine {engine!r}; "
                     f"choose from {sorted(WORKLOAD_ENGINES)}")
