"""Two-level (TAM) hierarchical exchange engine.

The reference's runtime core is ``collective_write`` and its two sibling
engines (lustre_driver_test.c:944-1309, 754-926, 604-728): all ranks funnel
their aggregator traffic through one *proxy* per node (intra-node gather →
proxy↔proxy inter-node exchange → local delivery). m=15/16 wrap that engine
behind the method registry (mpi_test.c:313-419).

TPU-native redesign — the mesh IS the hierarchy. We map ranks onto a 2-axis
``(node, local)`` mesh (inner axis = ICI slice, outer axis = DCN /
inter-slice, SURVEY.md §2.5 row "Hierarchical 2-level"):

- **two_level** (the default engine for m=15/16 on the jax backend):
  every chip participates in both hops — ``all_to_all`` on the *node* axis
  (slabs grouped by destination node), then ``all_to_all`` on the *local*
  axis (slabs delivered to the owning local aggregator). This is the
  TPU-idiomatic analog of collective_write3 (every rank reachable through
  shared memory ⇒ every chip reachable through ICI): funneling through one
  proxy chip would serialize a node's DCN traffic through a single chip's
  links, which is exactly backwards on TPU hardware. The reference's
  derived-datatype zero-copy tricks (collective_write2's hindexed views,
  l_d_t.c:848-904) become the static slot-index maps that drive the buffer
  packs — computed once on host, compiled into the program.

- **proxy oracle** (the local backend's engine): the faithful 5-phase
  structure — P1 size exchange is compile-time static here (XLA needs
  static shapes anyway; the reference's runtime size handshake,
  l_d_t.c:996-1041, carries no information in the uniform span=1 pattern),
  P2 pack+gather to the proxy, P3 proxy↔proxy runs, P4 local delivery,
  P5 scatter. Produces per-phase byte counts so schedule shape is testable.

Two-level *aggregator metadata* (``co`` local aggregators per node,
collective_write2's architecture) plugs in through
:func:`tpu_aggcomm.core.meta.aggregator_meta_information`; the proxy engine
is its ``co=1`` special case.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tpu_aggcomm.compat import shard_map as _compat_shard_map
from tpu_aggcomm.core.pattern import AggregatorPattern, Direction
from tpu_aggcomm.core.topology import NodeAssignment, static_node_assignment

__all__ = ["TamMethod", "gen_tam_schedule", "padded_mesh_size",
           "tam_oracle", "tam_two_level_jax", "tam_two_level_sharded",
           "tam_two_level_sharded_chained", "sharded_grid",
           "tam_phase_bytes"]


def padded_mesh_size(na: NodeAssignment) -> int:
    """Devices the two-level mesh engine needs: N*L coordinates, where a
    ragged last node is padded with phantom ranks. The single source of
    truth for both the engine and jax_ici's fallback pre-check."""
    return na.nnodes * int(na.node_sizes[0])


@dataclass
class TamMethod:
    """Compiled TAM method — the object compile_method returns for m=15/16.

    Not a generic Schedule: like the reference, TAM is a separate engine
    behind the same registry (mpi_test.c:34-38 extern boundary)."""

    pattern: AggregatorPattern
    method_id: int
    name: str
    assignment: NodeAssignment
    collective = False

    @property
    def nprocs(self) -> int:
        return self.pattern.nprocs


def gen_tam_schedule(p: AggregatorPattern) -> TamMethod:
    """m=15 (all_to_many) / m=16 (many_to_all): simulated contiguous node
    map from proc_node, exactly like the reference wrappers
    (mpi_test.c:395: static_node_assignment type 0)."""
    assignment = static_node_assignment(p.nprocs, p.proc_node, 0)
    if p.direction is Direction.ALL_TO_MANY:
        return TamMethod(p, 15, "All to many TAM", assignment)
    return TamMethod(p, 16, "Many to all TAM", assignment)


# ---------------------------------------------------------------------------
# proxy-path oracle (numpy): faithful 5-phase structure + per-phase volumes

def tam_phase_bytes(p: AggregatorPattern, na: NodeAssignment) -> dict:
    """Byte volumes each phase moves in the proxy engine — the quantities
    the reference's phase timers bracket (l_d_t.c:996-1309). Used by tests
    to pin the schedule *shape* (intra vs inter traffic) independent of
    timing."""
    ds = p.data_size
    node_of = na.node_of
    agg_nodes = node_of[np.asarray(p.rank_list)]
    if p.direction is Direction.ALL_TO_MANY:
        senders, receivers = np.arange(p.nprocs), np.asarray(p.rank_list)
    else:
        senders, receivers = np.asarray(p.rank_list), np.arange(p.nprocs)

    p2 = 0  # non-proxy rank -> its proxy (pack of all its slabs)
    for s in senders:
        if not na.is_proxy(int(s)):
            p2 += len(receivers) * ds if p.direction is Direction.ALL_TO_MANY \
                else p.nprocs * ds
    p3 = 0  # proxy -> proxy (slabs whose destination lives on another node)
    for s in senders:
        for r in receivers:
            if node_of[int(s)] != node_of[int(r)]:
                p3 += ds
    p4 = 0  # proxy -> final non-proxy destination
    for s in senders:
        for r in receivers:
            if not na.is_proxy(int(r)):
                p4 += ds
    return {"intra_gather": p2, "inter_exchange": p3, "local_delivery": p4}


def tam_oracle(tam: TamMethod, iter_: int = 0):
    """Single-process proxy-path execution: pack → gather-at-proxy →
    inter-node runs → local delivery → scatter. Data-identical to the dense
    exchange (the engine only changes the route), so delivery is computed
    through the explicit relay structure and then verified by the caller."""
    from tpu_aggcomm.harness.verify import make_send_slabs

    p = tam.pattern
    na = tam.assignment
    send = make_send_slabs(p, iter_)
    agg_index = p.agg_index

    # staging: per node, the proxy's aggregate buffer of (origin, slot) slabs
    proxy_hold: list[list[tuple[int, int]]] = [[] for _ in range(na.nnodes)]
    if p.direction is Direction.ALL_TO_MANY:
        senders = range(p.nprocs)
        slots = lambda s: range(p.cb_nodes)                  # noqa: E731
        dest_of = lambda s, i: int(p.rank_list[i])           # noqa: E731
    else:
        senders = [int(r) for r in p.rank_list]
        slots = lambda s: range(p.nprocs)                    # noqa: E731
        dest_of = lambda s, i: i                             # noqa: E731

    # P2: every sender's slabs arrive at its node proxy (self-pack for the
    # proxy itself; one packed Issend otherwise — l_d_t.c:1069-1105)
    for s in senders:
        proxy_hold[int(na.node_of[s])].extend((s, i) for i in slots(s))

    # P3: proxies exchange per-destination-node runs (l_d_t.c:1121-1194)
    node_in: list[list[tuple[int, int]]] = [[] for _ in range(na.nnodes)]
    for node, held in enumerate(proxy_hold):
        for (s, i) in held:
            node_in[int(na.node_of[dest_of(s, i)])].append((s, i))

    # P4/P5: destination proxy re-packs per local rank and delivers
    from tpu_aggcomm.backends.local import _alloc_recv
    recv = _alloc_recv(p)
    for node, incoming in enumerate(node_in):
        for (s, i) in incoming:
            d = dest_of(s, i)
            if p.direction is Direction.ALL_TO_MANY:
                recv[d][s] = send[s][i]
            else:
                recv[d][int(agg_index[s])] = send[s][i]
    return recv


# ---------------------------------------------------------------------------
# TPU-native two-level engine (jax): all_to_all on node axis, then local axis

def tam_two_level_jax(tam: TamMethod, devices, iter_: int = 0,
                      ntimes: int = 1, out: str = "host"):
    """Run the two-level exchange on a (node, local) mesh. Returns
    (per-rank recv slabs, per-rep wall times). Rank r lives at mesh
    coordinate (r // L, r % L) with L = ranks per node (contiguous node
    map, the same shape static_node_assignment type 0 fabricates).

    ``out="host"`` materializes every rank's recv slabs on the host —
    the single-process mode. ``out="global"`` returns the raw global
    device array ``(N, L, out_rows, w)`` instead: on a multi-controller
    runtime a process cannot device_get shards it does not own, so the
    caller (parallel/bringup.py:run_tam_across_processes) verifies its
    addressable shards — the per-rank check each reference process runs
    on its own recv buffer (lustre_driver_test.c:214-217 analog).

    A ragged last node (nprocs % proc_node != 0 — the reference supports
    this, l_d_t.c:359-429) is handled by padding the mesh to N*L
    coordinates: the phantom ranks of the last node carry zero slabs and
    their outputs are dropped at the host boundary, so N*L devices are
    required (VERDICT r1 item 5). Raises if the device pool can't host the
    padded mesh; jax_ici then falls back to the single-chip jax_sim route.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpu_aggcomm.harness.verify import make_send_slabs

    p = tam.pattern
    na = tam.assignment
    n, ds = p.nprocs, p.data_size
    L = int(na.node_sizes[0])
    N = na.nnodes
    # the r // L coordinate math requires the contiguous type-0 shape:
    # full nodes of L ranks, optionally one ragged last node
    sizes_ok = (all(int(s) == L for s in na.node_sizes[:-1])
                and int(na.node_sizes[-1]) <= L
                and np.array_equal(na.node_of, np.arange(n) // L))
    if not sizes_ok:
        raise ValueError(
            "two-level mesh needs the contiguous type-0 node map (full "
            f"nodes of {L} ranks + optional ragged last node); got node "
            f"sizes {[int(s) for s in na.node_sizes]}")
    n_pad = N * L            # == n unless the last node is ragged
    if len(devices) < n_pad:
        raise ValueError(
            f"two-level mesh needs {n_pad} devices "
            f"({N} nodes x {L} ranks; ragged last node is padded with "
            f"phantom coordinates), have {len(devices)}")

    # host-major ordering aligns the logical node boundary with the DCN
    # boundary when L divides the chips-per-host (no-op on one host);
    # a straddling split still runs correctly but is flagged because its
    # intra-node phases would ride DCN
    from tpu_aggcomm.parallel import (host_major_devices,
                                      warn_if_node_straddles_hosts)
    devices = host_major_devices(devices)
    warn_if_node_straddles_hosts(devices[:n_pad], L, "tam_two_level_jax")

    mesh = Mesh(np.array(devices[:n_pad]).reshape(N, L), ("node", "local"))
    agg_index = np.asarray(p.agg_index)
    rank_list = np.asarray(p.rank_list)
    agg_node = (rank_list // L).astype(np.int64)
    agg_local = (rank_list % L).astype(np.int64)
    # per node: which aggregator (global slab index) sits at which local
    K = max(int(c) for c in np.bincount(agg_node, minlength=N)) if len(rank_list) else 0
    K = max(K, 1)
    # aggs_of_node[b, j] = global agg index of node b's j-th aggregator (-1 pad)
    aggs_of_node = np.full((N, K), -1, dtype=np.int64)
    cnt = np.zeros(N, dtype=np.int64)
    for gi, b in enumerate(agg_node):
        aggs_of_node[b, cnt[b]] = gi
        cnt[b] += 1
    # local_of_aggslot[b, j] = local coordinate of that aggregator
    local_of_aggslot = np.where(
        aggs_of_node >= 0, agg_local[np.maximum(aggs_of_node, 0)], -1)

    from tpu_aggcomm.backends.lanes import (lane_layout, lanes_to_bytes,
                                            to_lanes)
    _, jdt, w = lane_layout(ds)
    slabs = make_send_slabs(p, iter_)
    # phantom pad ranks (row >= n) and phantom destination slots carry zeros
    send_g = np.zeros(
        (n_pad,
         (p.cb_nodes if p.direction is Direction.ALL_TO_MANY else n_pad),
         ds),
        dtype=np.uint8)
    for r, s in enumerate(slabs):
        if s is not None:
            send_g[r, :s.shape[0]] = s
    send_g = to_lanes(send_g, ds).reshape(N, L, -1, w)

    sharding = NamedSharding(mesh, P("node", "local"))
    # put_global: identical to device_put on one process; contributes
    # addressable shards on a multi-controller runtime (every process
    # holds the same pure-function fill — the MAP_DATA discipline)
    from tpu_aggcomm.backends.jax_ici import put_global
    send_dev = put_global(send_g, sharding)

    aggs_of_node_j = jnp.asarray(aggs_of_node)
    local_of_aggslot_j = jnp.asarray(local_of_aggslot)

    if p.direction is Direction.ALL_TO_MANY:

        def local_fn(send):
            # send: (1, 1, cb, w) — my slab for each global aggregator
            x = send[0, 0]
            # hop 1 (DCN/node axis): group my slabs by destination node:
            # row b = my slabs for node b's aggregators (K-padded)
            sel = jnp.maximum(aggs_of_node_j, 0)              # (N, K)
            mask = (aggs_of_node_j >= 0).astype(jdt)[..., None]
            bynode = jnp.take(x, sel.reshape(-1), axis=0).reshape(N, K, w) * mask
            got1 = lax.all_to_all(bynode, "node", 0, 0)        # (N, K, w)
            # got1[a, j] = slab from source (a, my_local) for my node's agg j
            # hop 2 (ICI/local axis): deliver each agg column j to the local
            # coordinate that hosts that aggregator.
            dst_local = jnp.where(local_of_aggslot_j >= 0, local_of_aggslot_j, L)
            mynode = lax.axis_index("node")
            dl = jnp.take(dst_local, mynode, axis=0)           # (K,)
            # build (L+1, N, w) buffer: row l' = columns j with dl[j] == l'
            # K may exceed 1 per local only if two aggs share a local slot,
            # which cannot happen (distinct ranks -> distinct locals per node)
            buf = jnp.zeros((L + 1, N, w), jdt)
            buf = buf.at[dl].set(jnp.transpose(got1, (1, 0, 2)))
            buf = buf[:L]
            got2 = lax.all_to_all(buf, "local", 0, 0)          # (L, N, w)
            # got2[l', a] = slab from source rank a*L + l' (zeros if I'm not
            # an aggregator). recv[src] ordering: src = a*L + l'.
            recv = jnp.transpose(got2, (1, 0, 2)).reshape(n_pad, w)
            return recv[None, None]

        out_rows = n_pad          # phantom source rows sliced off on host
    else:

        def local_fn(send):
            # send: (1, 1, n, w) — aggregator's slab for each dest rank
            x = send[0, 0]
            # hop 1 (ICI/local axis): split my slabs by destination local.
            # row l' = my slabs for ranks (a, l'), a in [0, N)
            bylocal = x.reshape(N, L, w).transpose(1, 0, 2)    # (L, N, w)
            got1 = lax.all_to_all(bylocal, "local", 0, 0)      # (L, N, w)
            # got1[lg, a] = slab from (my_node, lg) for rank (a, my_local).
            # keep only rows where (my_node, lg) is an aggregator; tag by
            # its per-node agg slot j so hop 2 can address it statically.
            mynode = lax.axis_index("node")
            ls = jnp.take(local_of_aggslot_j, mynode, axis=0)  # (K,) locals
            sel = jnp.minimum(jnp.maximum(ls, 0), L - 1)
            mask = (ls >= 0).astype(jdt)[..., None, None]
            byslot = jnp.take(got1, sel, axis=0) * mask        # (K, N, w)
            # hop 2 (DCN/node axis): send column a to node a
            got2 = lax.all_to_all(jnp.transpose(byslot, (1, 0, 2)),
                                  "node", 0, 0)                # (N, K, w)
            # got2[b, j] = slab from node b's agg j for me -> recv slot =
            # global agg index aggs_of_node[b, j]
            flat_idx = jnp.where(aggs_of_node_j >= 0, aggs_of_node_j,
                                 p.cb_nodes).reshape(-1)       # (N*K,)
            recv = jnp.zeros((p.cb_nodes + 1, w), jdt)
            recv = recv.at[flat_idx].set(got2.reshape(-1, w))
            return recv[:p.cb_nodes][None, None]

        out_rows = p.cb_nodes

    fn = jax.jit(_compat_shard_map(
        local_fn, mesh=mesh, in_specs=P("node", "local"),
        out_specs=P("node", "local")))

    import time as _time
    fn(send_dev).block_until_ready()  # warm-up compile
    rep_times = []
    out_dev = None
    for _ in range(max(ntimes, 1)):
        t0 = _time.perf_counter()
        out_dev = fn(send_dev)
        out_dev.block_until_ready()
        rep_times.append(_time.perf_counter() - t0)
    if out == "global":
        return out_dev, rep_times
    out = lanes_to_bytes(
        np.asarray(jax.device_get(out_dev)).reshape(n_pad, out_rows, w), ds)

    recv_bufs = []
    for rank in range(n):           # phantom pad ranks dropped
        if p.direction is Direction.ALL_TO_MANY:
            # slice each aggregator's rows to the real sources
            recv_bufs.append(out[rank][:n] if agg_index[rank] >= 0 else None)
        else:
            recv_bufs.append(out[rank])
    return recv_bufs, rep_times


# ---------------------------------------------------------------------------
# TPU-native two-level engine at flagship rank counts: B logical ranks per
# device on a (node, local) device grid

def _group_slots(key: np.ndarray) -> tuple[np.ndarray, int]:
    """Per-element slot index within its key group (stable order) and the
    max group size — the vectorized cursor walk that replaces the
    reference proxy's prefix-sum pack cursors (l_d_t.c:1033-1146)."""
    if len(key) == 0:
        return np.zeros(0, dtype=np.int64), 1
    order = np.argsort(key, kind="stable")
    sk = key[order]
    new = np.r_[True, sk[1:] != sk[:-1]]
    starts = np.flatnonzero(new)
    counts = np.diff(np.r_[starts, len(sk)])
    slots = np.empty(len(sk), dtype=np.int64)
    slots[order] = np.arange(len(sk)) - np.repeat(starts, counts)
    return slots, int(counts.max())


def sharded_grid(N: int, L: int, ndev: int) -> tuple[int, int]:
    """Pick the (Dn, Dl) device grid for a (N nodes x L max-ranks/node)
    logical topology on ndev devices: Dn*Dl = ndev, Dn <= N, Dl <= L.
    Non-dividing splits are allowed — blocks pad to Bn = ceil(N/Dn),
    Bl = ceil(L/Dl) and the phantom coordinates ride the engine's zero
    sentinel rows (the device-grid analog of the reference's ragged last
    node, lustre_driver_test.c:374-386). Preference: least padded
    capacity first, then most balanced (largest min(Dn, Dl); ties prefer
    the node axis, which is the DCN boundary worth spreading). Raises
    when no factorization of ndev fits inside (N, L)."""
    best = None
    for dl in range(1, ndev + 1):
        if ndev % dl:
            continue
        dn = ndev // dl
        if dn > N or dl > L:
            continue
        bn, bl = -(-N // dn), -(-L // dl)
        pad = dn * bn * dl * bl - N * L
        cand = (-pad, min(dn, dl), dn, (dn, dl))
        if best is None or cand > best:
            best = cand
    if best is None:
        raise ValueError(
            f"no (Dn, Dl) grid: no factorization of ndev={ndev} fits "
            f"Dn <= {N} nodes and Dl <= {L} ranks-per-node")
    return best[3]


def tam_two_level_sharded(tam: TamMethod, devices, iter_: int = 0,
                          ntimes: int = 1, mesh_shape=None, cache=None,
                          return_state: bool = False):
    """The two-level exchange with **B logical ranks per device** — the
    reference's flagship regime (16,384 ranks on 256 nodes,
    script_theta_all_to_many_256.sh:3,11) on a small device grid.

    Unlike :func:`tam_two_level_jax` (one rank per device) this blocks the
    logical (node, local) topology onto a (Dn, Dl) device grid: device
    (i, j) owns Bn = N/Dn whole logical nodes x Bl = L/Dl locals of each.
    The route is the collective_write relay (l_d_t.c:944-1309) expressed
    as TWO padded block all_to_alls with static index tables:

    - hop 1 (``node`` axis, the DCN hop = P3's proxy<->proxy exchange):
      every slab moves to the device *row* owning its destination's
      logical node, grouped by the host-built pack table;
    - hop 2 (``local`` axis, the ICI hop = P2/P4's intra-node legs):
      slabs move within the row to the destination *column*, then a
      static scatter lands them in the owner's recv arena.

    The reference's derived-datatype views and proxy pack cursors
    (l_d_t.c:848-904, 1033-1146) become three host-built index tables
    (pack1, pack2, scat) computed vectorized over all n*a slabs; padding
    rides zero rows, per-device tables are sharded over the grid, and
    both hops stay single collectives per rep — no per-slab control flow
    reaches the device. Accepts ANY node map: a rank's grid coordinate is
    (its node, its index within that node), which for the contiguous
    type-0 map reduces to (r // L, r % L); ragged last nodes
    (l_d_t.c:374-386) and round-robin maps pad to Bn = ceil(N/Dn) x
    Bl = ceil(Lmax/Dl) blocks whose phantom coordinates simply never
    appear in the tables. Returns (per-rank recv slabs, per-rep seconds).

    ``cache`` (a dict, e.g. the calling backend's compile cache) memoizes
    the iter-independent build — slab enumeration, the three index
    tables, their device uploads, and the jitted program — so an iters
    sweep pays the n*a-slab argsorts and the compile once; only the
    payload arena (a function of ``iter_``) is rebuilt per call.
    """
    import time as _time

    import jax
    from jax import lax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpu_aggcomm.backends.lanes import (lane_layout, lanes_to_bytes,
                                            to_lanes)
    from tpu_aggcomm.harness.verify import make_send_slabs

    p = tam.pattern
    na = tam.assignment
    n, ds, a = p.nprocs, p.data_size, p.cb_nodes
    N = na.nnodes
    node_of = np.asarray(na.node_of, dtype=np.int64)
    # index of each rank within its node (ascending-rank order) — equals
    # r % L on the contiguous map, and is well-defined for ragged and
    # round-robin maps alike
    local_of, Lmax = _group_slots(node_of)
    devices = list(devices)
    Dn, Dl = mesh_shape if mesh_shape is not None else sharded_grid(
        N, Lmax, len(devices))
    if Dn * Dl > len(devices):
        raise ValueError(f"grid {(Dn, Dl)} needs {Dn * Dl} devices, "
                         f"have {len(devices)}")
    if Dn > N or Dl > Lmax:
        raise ValueError(
            f"grid {(Dn, Dl)} exceeds the ({N} nodes x {Lmax} "
            "max-ranks/node) topology")
    Bn, Bl = -(-N // Dn), -(-Lmax // Dl)    # padded block sizes
    R = Bn * Bl                      # logical rank slots per device

    rank_list = np.asarray(p.rank_list, dtype=np.int64)

    def dev_i(r):                    # device row of rank r
        return node_of[r] // Bn

    def dev_j(r):                    # device column of rank r
        return local_of[r] // Bl

    def dev_u(r):                    # local rank slot within its device
        return (node_of[r] % Bn) * Bl + (local_of[r] % Bl)

    from tpu_aggcomm.parallel import host_major_devices
    devs = host_major_devices(devices)[:Dn * Dl]
    key = ("tam2l_sharded", p, tam.method_id, Dn, Dl, tuple(devs),
           node_of.tobytes())
    st = None if cache is None else cache.get(key)
    if st is None:
        # ---- iter-independent build: enumeration, tables, program ----
        # per-device aggregator slots, in global aggregator order
        agg_i, agg_j = dev_i(rank_list), dev_j(rank_list)
        agg_slot, K_agg = _group_slots(agg_i * Dl + agg_j)
        K_agg = max(K_agg, 1)

        # slab enumeration: src rank, dst rank, flat send/recv arena index
        if p.direction is Direction.ALL_TO_MANY:
            # t = s*a + g : rank s's slab for aggregator g
            src = np.repeat(np.arange(n, dtype=np.int64), a)
            g = np.tile(np.arange(a, dtype=np.int64), n)
            dst = rank_list[g]
            send_flat = dev_u(src) * a + g
            recv_flat = agg_slot[g] * n + src
            S_rows, R_rows = R * a, K_agg * n
        else:
            # t = gidx*n + r : aggregator gidx's slab for rank r
            src = np.repeat(rank_list, n)
            g = np.repeat(np.arange(a, dtype=np.int64), n)
            dst = np.tile(np.arange(n, dtype=np.int64), a)
            send_flat = agg_slot[g] * n + dst
            recv_flat = dev_u(dst) * a + g
            S_rows, R_rows = K_agg * n, R * a

        si, sj = dev_i(src), dev_j(src)
        di, dj = dev_i(dst), dev_j(dst)

        # hop-1 slots: within (src device, dst row); hop-2: within
        # (dst row, src column, dst column) — the device holding the slab
        # after hop 1 is (di, sj)
        k1, K1 = _group_slots((si * Dl + sj) * Dn + di)
        k2, K2 = _group_slots((di * Dl + sj) * Dl + dj)

        pack1 = np.full((Dn, Dl, Dn, K1), S_rows, dtype=np.int32)
        pack1[si, sj, di, k1] = send_flat
        pack2 = np.full((Dn, Dl, Dl, K2), Dn * K1, dtype=np.int32)
        pack2[di, sj, dj, k2] = si * K1 + k1
        scat = np.full((Dn, Dl, Dl * K2), R_rows, dtype=np.int32)
        scat[di, dj, sj * K2 + k2] = recv_flat

        _, jdt, w = lane_layout(ds)
        mesh = Mesh(np.array(devs).reshape(Dn, Dl), ("node", "local"))
        shard = NamedSharding(mesh, P("node", "local"))

        from tpu_aggcomm.backends.jax_ici import put_global
        tab_devs = [put_global(t, shard) for t in (pack1, pack2, scat)]

        def _rep_local(x, pk1, pk2, sc):
            # one device's rep: x (S_rows+1, w) -> recv (R_rows, w).
            # Shared by the timed program and the chained-measurement
            # scan so the chained program cannot drift from the program
            # it measures (the rep_body precedent, backends/jax_shard.py)
            b1 = jnp.take(x, pk1, axis=0)                 # (Dn, K1, w)
            g1 = lax.all_to_all(b1, "node", 0, 0)
            f1 = jnp.concatenate(
                [g1.reshape(Dn * K1, w), jnp.zeros((1, w), x.dtype)])
            b2 = jnp.take(f1, pk2, axis=0)                # (Dl, K2, w)
            g2 = lax.all_to_all(b2, "local", 0, 0)
            recv = jnp.zeros((R_rows + 1, w), x.dtype)
            recv = recv.at[sc].set(g2.reshape(Dl * K2, w))
            return recv[:R_rows]

        def local_fn(send, pk1, pk2, sc):
            return _rep_local(send[0, 0], pk1[0, 0], pk2[0, 0],
                              sc[0, 0])[None, None]

        fn = jax.jit(_compat_shard_map(
            local_fn, mesh=mesh, in_specs=(P("node", "local"),) * 4,
            out_specs=P("node", "local")))

        def make_chain(iters: int):
            """The serial-chain scaffold on the (node, local) grid: rep
            r+1's send XOR-perturbed by a psum over BOTH mesh axes of
            rep r's delivered rows — same token formula as every other
            chained backend (harness/chained.py), so chained numbers
            stay comparable across tiers."""
            from tpu_aggcomm.harness.chained import xor_word

            def chain_local(send, pk1, pk2, sc):
                def body(s, r):
                    recv = _rep_local(s, pk1[0, 0], pk2[0, 0], sc[0, 0])
                    tok = (lax.psum(
                        jnp.sum(recv[:, 0].astype(jnp.uint32)),
                        ("node", "local")).astype(jnp.int32) + r) % 251
                    return s ^ xor_word(tok, jdt), ()

                out, _ = lax.scan(body, send[0, 0],
                                  jnp.arange(iters, dtype=jnp.int32),
                                  unroll=1)
                return out[None, None]

            csm = _compat_shard_map(
                chain_local, mesh=mesh, in_specs=(P("node", "local"),) * 4,
                out_specs=P("node", "local"))
            cjf = jax.jit(csm)
            return lambda send: cjf(send, *tab_devs)

        st = dict(fn=fn, tab_devs=tab_devs, shard=shard, si=si, sj=sj,
                  send_flat=send_flat, S_rows=S_rows, R_rows=R_rows,
                  agg_i=agg_i, agg_j=agg_j, agg_slot=agg_slot, w=w,
                  make_chain=make_chain, warm=False)
        if cache is not None:
            cache[key] = st
    fn, tab_devs, shard = st["fn"], st["tab_devs"], st["shard"]
    si, sj, send_flat = st["si"], st["sj"], st["send_flat"]
    S_rows, R_rows, w = st["S_rows"], st["R_rows"], st["w"]
    agg_i, agg_j, agg_slot = st["agg_i"], st["agg_j"], st["agg_slot"]

    # ---- per-iter payload arena (the only iter-dependent piece) ----
    if p.direction is Direction.ALL_TO_MANY:
        payload = np.stack([sl for sl in make_send_slabs(p, iter_)])
    else:
        slabs = make_send_slabs(p, iter_)
        payload = np.stack([slabs[int(r)] for r in rank_list])
    payload = payload.reshape(-1, ds)
    arena = np.zeros((Dn, Dl, S_rows + 1, w),
                     dtype=to_lanes(payload[:1], ds).dtype)
    arena[si, sj, send_flat] = to_lanes(payload, ds)

    from tpu_aggcomm.backends.jax_ici import put_global
    send_dev = put_global(arena, shard)
    st["last_send_dev"] = send_dev     # chain seed (iter-0 convention)

    if not st["warm"]:
        fn(send_dev, *tab_devs).block_until_ready()   # warm-up compile
        st["warm"] = True
    rep_times, out_dev = [], None
    for _ in range(max(ntimes, 1)):
        t0 = _time.perf_counter()
        out_dev = fn(send_dev, *tab_devs)
        out_dev.block_until_ready()
        rep_times.append(_time.perf_counter() - t0)
    out = np.asarray(jax.device_get(out_dev))     # (Dn, Dl, R_rows, w)

    recv_bufs: list = [None] * n
    if p.direction is Direction.ALL_TO_MANY:
        for gi, rg in enumerate(rank_list):
            rows = out[agg_i[gi], agg_j[gi],
                       agg_slot[gi] * n:(agg_slot[gi] + 1) * n]
            recv_bufs[int(rg)] = lanes_to_bytes(rows, ds)
    else:
        for r in range(n):
            rows = out[dev_i(r), dev_j(r),
                       dev_u(r) * a:(dev_u(r) + 1) * a]
            recv_bufs[r] = lanes_to_bytes(rows, ds)
    if return_state:
        return recv_bufs, rep_times, st
    return recv_bufs, rep_times


def tam_two_level_sharded_chained(tam: TamMethod, devices, *,
                                  mesh_shape=None, cache=None,
                                  iters_small: int = 20,
                                  iters_big: int = 220, trials: int = 3,
                                  windows: int = 2) -> float:
    """Serial-chained differenced per-rep seconds of the BLOCKED
    two-level engine — honest flagship-TAM timing through a tunneled or
    contended dispatch path (the last tier that only had per-dispatch
    wall times). One verified rep runs first (build + warm-up + delivery
    check path), then the chain scaffold stashed in the engine state
    measures reps back-to-back with dispatch overhead differenced away
    (harness/chained.py)."""
    from tpu_aggcomm.harness.chained import differenced_per_rep

    cache = {} if cache is None else cache
    _recv, _times, st = tam_two_level_sharded(
        tam, devices, iter_=0, ntimes=1, mesh_shape=mesh_shape,
        cache=cache, return_state=True)
    return differenced_per_rep(st["make_chain"], st["last_send_dev"],
                               iters_small=iters_small,
                               iters_big=iters_big, trials=trials,
                               windows=windows)
