"""TAM — the hierarchical two-level aggregation engine.

TPU-native re-design of the reference's lustre_driver_test.c runtime core
(SURVEY.md §2.2, §3.3). See :mod:`tpu_aggcomm.tam.engine`.
"""

from tpu_aggcomm.tam.engine import TamMethod, gen_tam_schedule

__all__ = ["TamMethod", "gen_tam_schedule"]
