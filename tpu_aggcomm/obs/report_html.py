"""Static HTML dashboard over the bench history and trace files — jax-free.

``cli inspect report [--out report.html] [--history-root DIR] [TRACE...]``
writes ONE self-contained file: no external assets, no CDN, no
dependencies — the data is inlined as JSON and a few hundred lines of
vanilla JS render it. The file must stay viewable from a bare
``file://`` open on a machine with no network, because the TPU build
host is exactly that.

Four panels:

- **trajectory** — the headline metric per growth round from the
  checked-in ``BENCH_r*.json`` artifacts, one SVG polyline per platform
  (the history legitimately mixes TPU ~µs rounds with CPU-fallback
  ~tens-of-µs rounds; plotting them as one line would be the cross-
  platform comparison obs/regress.py exists to refuse). Rounds carrying
  per-trial ``samples`` get min/max whiskers. MULTICHIP status rides
  along as a per-round ok/skip marker row.
- **longitudinal trend** — the seeded multi-round slope gate
  (obs/history.py ``check_trends``) per (metric, platform) series:
  verdict, relative slope per round and its bootstrap CI — the
  trajectory pane's numbers, judged.
- **run ledger** — per-round compile seconds, HBM peak, jax version and
  environment drift vs the previous manifest-carrying round
  (parsed-schema v3, obs/ledger.py); pre-v3 rounds show dashes.
- **per-method skew table** — for every run of every trace file passed
  in: worst-round skew (max/mean over ranks), imbalance share, the
  critical rank, and the dominant (round, phase) cell with its
  PHASE_SOURCES provenance label (obs/metrics.py).
- **straggler heatmap** — the (rank x round) mean-seconds grid per run,
  colored relative to the run's own hottest cell, so the straggler is
  visible at a glance.
- **traffic audit** — per traced run, the static throttle-conformance
  verdict (peak in-flight vs the -c bound, obs/traffic.py, recompiled
  jax-free from the run's recorded config — fault-repaired first when
  the run recorded a fault spec, so the audited program is the detoured
  one that actually ran) and, at n <= 64, the aggregate src→dst byte
  heatmap.
- **fault degradation** — every faulted trace run paired with a healthy
  run of the same (method, n, data size) across the traces passed in:
  the recovery delta (faulted minus healthy critical-path seconds) and
  its percentage, i.e. the measured cost of surviving the fault.
- **monitoring** — every committed ``WATCH_r*.json`` watchtower
  artifact (obs/watch.py): per-objective SLO burn rates over the
  tumbling windows, overall compliance, stream-integrity counters, and
  the confirmed changepoints with their NAMED root-cause verdicts.
- **flow tracing** — every committed ``FLOW_r*.json`` causal-flow
  artifact (obs/flow.py): the warm overhead ledger (mean + seeded CI),
  the per-component warm fraction bars, verdict counts, and per-request
  decomposition rows — where each client-observed wall actually goes,
  with the residual quantified.

Empty inputs degrade to an honest "no data" panel, never a broken page.
"""

from __future__ import annotations

import json
import os

from tpu_aggcomm.obs.history import check_trends, load_history
from tpu_aggcomm.obs.metrics import (cell_means, critical_path, round_stats,
                                     run_events)
from tpu_aggcomm.obs.trace import load_events, round_key

__all__ = ["write_report", "build_payload", "render_html"]


def _history_rows(root: str) -> tuple[list[dict], list[str]]:
    from tpu_aggcomm.obs.ledger import diff_manifests
    errors: list[str] = []
    rows = []
    prev_manifest = None  # latest manifest-carrying round seen so far
    for rnd, path, blob in load_history(root, "BENCH", errors=errors):
        p = blob.get("parsed")
        if not isinstance(p, dict):
            rows.append({"round": rnd, "value": None, "platform": None,
                         "unit": None, "samples": None,
                         "compile_seconds": None, "hbm_peak_bytes": None,
                         "jax": None, "drift": [],
                         "file": os.path.basename(path)})
            continue
        s = p.get("samples")
        # parsed-schema v3 run-ledger fields (obs/ledger.py); pre-v3
        # rounds keep None everywhere and an empty drift list
        m = p.get("manifest")
        m = m if isinstance(m, dict) else None
        drift = [f"{d['key']}: {d['a']} -> {d['b']}"
                 for d in diff_manifests(prev_manifest, m)] \
            if m is not None and prev_manifest is not None else []
        if m is not None:
            prev_manifest = m
        versions = m.get("versions") if m else None
        rows.append({
            "round": rnd,
            "value": p.get("value"),
            "platform": p.get("platform", "unknown"),
            "unit": p.get("unit", "s"),
            "vs_baseline": p.get("vs_baseline"),
            "samples": s if isinstance(s, list) else None,
            "compile_seconds": p.get("compile_seconds"),
            "hbm_peak_bytes": p.get("hbm_peak_bytes"),
            "jax": (versions or {}).get("jax"),
            "drift": drift,
            "file": os.path.basename(path)})
    return rows, errors


def _multichip_rows(root: str, errors: list[str]) -> list[dict]:
    return [{"round": rnd, "ok": blob.get("ok"),
             "skipped": blob.get("skipped"),
             "n_devices": blob.get("n_devices")}
            for rnd, _path, blob in load_history(root, "MULTICHIP",
                                                 errors=errors)]


def _round_label(rnd) -> str:
    from tpu_aggcomm.obs.trace import WHOLE_REP
    if rnd == WHOLE_REP:
        return "whole-rep"
    return str(rnd)


def _run_traffic(run: dict) -> dict | None:
    """Static traffic audit of one traced run, recompiled jax-free from
    the run's recorded config (obs/traffic.py — core.methods imports
    only numpy). Returns the conformance row plus, at n <= 64, the
    aggregate src→dst byte matrix for the heatmap. Runs recorded before
    the config fields existed, or too large to audit in a report, get a
    note instead of a crash."""
    if run.get("cb_nodes") is None:
        return {"verdict": None, "note":
                "trace predates the traffic config fields (re-record)"}
    try:
        from tpu_aggcomm.core.methods import compile_method
        from tpu_aggcomm.core.pattern import AggregatorPattern
        from tpu_aggcomm.obs.traffic import audit_schedule

        n = int(run["nprocs"])
        p = AggregatorPattern(
            nprocs=n, cb_nodes=run["cb_nodes"],
            data_size=run["data_size"], placement=run.get("agg_type", 1),
            proc_node=run.get("proc_node", 1),
            comm_size=run["comm_size"])
        sched = compile_method(run["method"], p)
        if run.get("fault"):
            # audit the program that actually ran: the detoured one
            from tpu_aggcomm.faults import repair_schedule
            sched = repair_schedule(sched, run["fault"])
        if getattr(sched, "collective", False) and n > 256:
            return {"verdict": "EXEMPT", "note":
                    f"dense collective at n={n}: matrix omitted"}
        audit = audit_schedule(sched)
    except Exception as e:  # lint: broad-ok (an unauditable run must not sink the page)
        return {"verdict": None, "note": f"not auditable: {e}"}
    conf = audit["conformance"]
    out = {"verdict": conf["verdict"], "peak": conf["peak"],
           "bound": conf["bound"], "bound_formula": conf["bound_formula"],
           "totals": audit["totals"], "note": None}
    if n <= 64 and not audit["edges_omitted"]:
        grid = [[0] * n for _ in range(n)]
        for r in audit["rounds"]:
            for s, d, b in r.get("edges", []):
                grid[s][d] += b
        out["matrix"] = grid
    elif conf["verdict"] != "EXEMPT":
        out["note"] = f"matrix omitted (n={n} > 64)"
    return out


def _trace_runs(paths: list[str]) -> list[dict]:
    """Per-run analytics bundles for the skew table and heatmap, JSON-
    ready (round keys stringified; grids as row-major lists)."""
    out = []
    for path in paths:
        events = load_events(path)
        for run in run_events(events):
            rid = run["id"]
            stats = round_stats(events, rid)
            cp = critical_path(events, rid)
            grid = cell_means(events, rid)
            ranks = sorted({rank for rank, _ in grid})
            rounds = sorted({rnd for _, rnd in grid}, key=round_key)
            cells = [[grid.get((rank, rnd)) for rnd in rounds]
                     for rank in ranks]
            worst = max(
                (s for s in stats if s["skew"] is not None),
                key=lambda s: s["skew"], default=None)
            out.append({
                "file": path, "run": rid,
                "method": run["method"], "name": run["name"],
                "nprocs": run["nprocs"], "data_size": run["data_size"],
                "fault": run.get("fault") or None,
                "phase_source": run["phase_source"],
                "worst_skew": worst["skew"] if worst else None,
                "worst_skew_round": (_round_label(worst["round"])
                                     if worst else None),
                "imbalance": worst["imbalance"] if worst else None,
                "critical_rank": cp["rank"] if cp else None,
                "total_s": cp["total"] if cp else None,
                "dominant": ({"round": _round_label(
                                  cp["dominant"]["round"]),
                              "bucket": cp["dominant"]["bucket"],
                              "seconds": cp["dominant"]["seconds"],
                              "share": cp["dominant"]["share"]}
                             if cp and cp["dominant"] else None),
                "heat": {"ranks": ranks,
                         "rounds": [_round_label(r) for r in rounds],
                         "cells": cells},
                "traffic": _run_traffic(run)})
    return out


def _degradation_rows(runs: list[dict]) -> list[dict]:
    """Fault-degradation pane data: every faulted trace run paired with
    the first healthy run of the same (method, nprocs, data_size) among
    the traces passed in. The delta is faulted-minus-healthy critical-
    path seconds — the measured cost of surviving the fault. Unpaired
    faulted runs still get a row (null delta) so the scenario stays
    visible."""
    healthy: dict[tuple, dict] = {}
    for r in runs:
        if not r.get("fault") and r.get("total_s") is not None:
            healthy.setdefault(
                (r["method"], r["nprocs"], r["data_size"]), r)
    rows = []
    for r in runs:
        if not r.get("fault"):
            continue
        base = healthy.get((r["method"], r["nprocs"], r["data_size"]))
        delta = (r["total_s"] - base["total_s"]
                 if base is not None and r.get("total_s") is not None
                 else None)
        rows.append({
            "file": r["file"], "run": r["run"], "method": r["method"],
            "name": r["name"], "nprocs": r["nprocs"],
            "fault": r["fault"],
            "faulted_s": r.get("total_s"),
            "healthy_s": base["total_s"] if base is not None else None,
            "healthy_ref": (base["file"] + " #" + str(base["run"])
                            if base is not None else None),
            "delta_s": delta,
            "pct": (delta / base["total_s"] * 100.0
                    if delta is not None and base["total_s"] else None)})
    return rows


def _tune_rows(root: str) -> list[dict]:
    """Tuner pane data from every TUNE_*.json under the history root —
    jax-free (tune/cache.py + statistics): winner per shape, the
    per-candidate pooled medians, and the elimination trace with its CI
    bounds. Schema-invalid artifacts become error rows, not crashes."""
    import statistics

    from tpu_aggcomm.obs.regress import validate_tune
    from tpu_aggcomm.tune.cache import load_tune, tune_paths

    rows = []
    for path in tune_paths(root):
        name = os.path.basename(path)
        try:
            blob = load_tune(path)
        except (OSError, ValueError) as e:
            rows.append({"file": name, "error": f"unparsable JSON ({e})"})
            continue
        errors = validate_tune(blob, name)
        if errors:
            rows.append({"file": name, "error": errors[0]})
            continue
        race = blob["race"]
        samples = race["samples"]
        medians = {cid: statistics.median([x for b in batches for x in b])
                   for cid, batches in samples.items() if any(batches)}
        rows.append({
            "file": name, "error": None, "key": blob["key"],
            "winner_cid": race["winner"], "winner": blob["winner"],
            "synthetic": bool(blob.get("synthetic")),
            "batches_run": race.get("batches_run"),
            "alpha": race.get("alpha"),
            "order": race.get("order") or list(samples),
            "medians": medians,
            "survivors": race.get("survivors") or [],
            "eliminations": [
                {"batch": e.get("batch"), "candidate": e.get("candidate"),
                 "leader": e.get("leader"), "ci_pct": e.get("ci_pct")}
                for e in race.get("eliminations", [])]})
    return rows


def _synth_rows(root: str) -> list[dict]:
    """Synthesis pane data from every SYNTH_r*.json under the history
    root — jax-free (obs/history.py discovery + statistics): the seeded
    search funnel (evaluated vs pruned, by prune class), the finalist
    compositions with their predicted ranks, and the measured race
    outcome. Schema-invalid artifacts become error rows, not crashes."""
    import statistics

    from tpu_aggcomm.obs.history import load_history
    from tpu_aggcomm.obs.regress import validate_synth

    rows = []
    load_errors: list[str] = []
    for _rnd, path, blob in load_history(root, "SYNTH",
                                         errors=load_errors):
        name = os.path.basename(path)
        errors = validate_synth(blob, name)
        if errors:
            rows.append({"file": name, "error": errors[0]})
            continue
        sr = blob["search"]
        race = blob["race"]
        medians = {cid: statistics.median([x for b in batches for x in b])
                   for cid, batches in race["samples"].items()
                   if any(batches)}
        rank_of = {r["composition"]: r.get("rank")
                   for r in sr["rows"] if r.get("rank") is not None}
        reg = blob["registration"]
        finalists = [{"method_id": int(m), "composition": c,
                      "predicted_rank": rank_of.get(c)}
                     for m, c in sorted(((m, e["composition"])
                                         for m, e in reg.items()),
                                        key=lambda t: int(t[0]))]
        rows.append({
            "file": name, "error": None, "config": blob["config"],
            "backend": blob.get("backend"),
            "synthetic": blob.get("synthetic"),
            "seed": blob.get("seed"),
            "space_size": sr.get("space_size"),
            "evaluated": sr.get("evaluated"), "pruned": sr.get("pruned"),
            "finalists": finalists,
            "winner": blob["winner"],
            "winner_cid": race["winner"],
            "batches_run": race.get("batches_run"),
            "order": race.get("order") or list(race["samples"]),
            "medians": medians,
            "eliminations": [
                {"batch": e.get("batch"), "candidate": e.get("candidate"),
                 "leader": e.get("leader"), "ci_pct": e.get("ci_pct")}
                for e in race.get("eliminations", [])]})
    for msg in load_errors:
        rows.append({"file": msg.split(":", 1)[0], "error": msg})
    return rows


def _explain_rows(root: str) -> dict | None:
    """Cost-model pane data from the newest committed ``PREDICT_*.json``
    (model/artifact.py) — jax-free. None when no artifact exists (the
    pane says so); a schema-invalid artifact becomes an error payload,
    never a crash — and never a silently trusted number."""
    from tpu_aggcomm.model.predict import newest_predict_path
    from tpu_aggcomm.obs.regress import validate_predict

    path = newest_predict_path(root)
    if path is None:
        return None
    name = os.path.basename(path)
    try:
        with open(path) as fh:
            blob = json.load(fh)
    except (OSError, ValueError) as e:
        return {"file": name, "error": f"unparsable JSON ({e})"}
    errors = validate_predict(blob, name)
    if errors:
        return {"file": name, "error": errors[0]}
    return {"file": name, "error": None, "seed": blob.get("seed"),
            "platforms": blob["platforms"],
            "validation": blob["validation"],
            "crossover": blob.get("crossover"),
            "explain": blob["explain"]}


def _workload_rows(root: str, errors: list[str]) -> list[dict]:
    """Workload pane data from every ``WORKLOAD_r*.json`` under the
    history root (obs/workload.py, discovered via load_history like
    every other family) — jax-free. A schema-invalid profile becomes
    an error payload, never a silently trusted number."""
    from tpu_aggcomm.obs.history import load_history
    from tpu_aggcomm.obs.regress import validate_workload

    rows: list[dict] = []
    for rnd, path, blob in load_history(root, "WORKLOAD", errors=errors):
        name = os.path.basename(path)
        errs = validate_workload(blob, name)
        if errs:
            rows.append({"round": rnd, "file": name, "error": errs[0]})
            continue
        rows.append({"round": rnd, "file": name, "error": None,
                     "seed": blob.get("seed"),
                     "requests": blob.get("requests"),
                     "phase_totals": blob.get("phase_totals"),
                     "arrivals": {k: v for k, v in
                                  (blob.get("arrivals") or {}).items()
                                  if k != "interarrival_s"},
                     "shape_mix": blob.get("shape_mix"),
                     "batching": {k: v for k, v in
                                  (blob.get("batching") or {}).items()
                                  if k != "per_batch"},
                     "proposals": blob.get("proposals")})
    return rows


def _watch_rows(root: str, errors: list[str]) -> list[dict]:
    """Monitoring pane data from every ``WATCH_r*.json`` under the
    history root (obs/watch.py, discovered via load_history like every
    other family) — jax-free. A schema-invalid watch artifact becomes
    an error payload, never a silently trusted verdict."""
    from tpu_aggcomm.obs.history import load_history
    from tpu_aggcomm.obs.regress import validate_watch

    rows: list[dict] = []
    for rnd, path, blob in load_history(root, "WATCH", errors=errors):
        name = os.path.basename(path)
        errs = validate_watch(blob, name)
        if errs:
            rows.append({"round": rnd, "file": name, "error": errs[0]})
            continue
        ev = blob.get("evaluation") or {}
        rows.append({
            "round": rnd, "file": name, "error": None,
            "seed": blob.get("seed"),
            "slo_source": blob.get("slo_source"),
            "requests": blob.get("requests"),
            "integrity": blob.get("integrity"),
            "compliant": ev.get("compliant"),
            "objectives": [
                {"name": o.get("name"), "kind": o.get("kind"),
                 "target": o.get("target"),
                 "worst_burn": o.get("worst_burn"),
                 "compliant": o.get("compliant"),
                 "windows": {w: [e.get("burn") for e in entries]
                             for w, entries in
                             (o.get("windows") or {}).items()}}
                for o in ev.get("objectives", [])],
            "anomalies": [
                {"stream": a.get("stream"),
                 "at_rid": a.get("at_rid"),
                 "at_round": a.get("at_round"),
                 "detection": {k: (a.get("detection") or {}).get(k)
                               for k in ("before_mean", "after_mean",
                                         "delta_rel", "ci_rel",
                                         "direction")},
                 "cause": a.get("cause"),
                 "evidence": a.get("evidence"),
                 "detail": a.get("detail")}
                for a in blob.get("anomalies", [])]})
    return rows


def _flow_rows(root: str, errors: list[str]) -> list[dict]:
    """Flow pane data from every ``FLOW_r*.json`` under the history
    root (obs/flow.py, discovered via load_history like every other
    family) — jax-free. A schema-invalid flow artifact becomes an
    error payload, never a silently trusted decomposition."""
    from tpu_aggcomm.obs.flow import COMPONENT_ORDER
    from tpu_aggcomm.obs.history import load_history
    from tpu_aggcomm.obs.regress import validate_flow

    rows: list[dict] = []
    for rnd, path, blob in load_history(root, "FLOW", errors=errors):
        name = os.path.basename(path)
        errs = validate_flow(blob, name)
        if errs:
            rows.append({"round": rnd, "file": name, "error": errs[0]})
            continue
        rows.append({
            "round": rnd, "file": name, "error": None,
            "seed": blob.get("seed"),
            "requests": blob.get("requests"),
            "integrity": blob.get("integrity"),
            "verdicts": blob.get("verdicts"),
            "warm_overhead": blob.get("warm_overhead"),
            "warm_components": blob.get("warm_components"),
            "component_order": list(COMPONENT_ORDER),
            "per_request": [
                {"rid": r.get("rid"),
                 "client_wall_s": r.get("client_wall_s"),
                 "cache": r.get("cache"),
                 "verdict": r.get("verdict"),
                 "fractions": r.get("fractions"),
                 "residual_s": r.get("residual_s")}
                for r in (blob.get("per_request") or [])[:12]]})
    return rows


def build_payload(history_root: str = ".",
                  trace_paths: list[str] | None = None) -> dict:
    """The dashboard's inlined data: bench/multichip history + tuner
    cache + per-run trace analytics + any history-load errors (shown,
    not swallowed)."""
    bench, errors = _history_rows(history_root)
    multichip = _multichip_rows(history_root, errors)
    runs = _trace_runs(list(trace_paths or []))
    return {"bench": bench, "multichip": multichip,
            "tune": _tune_rows(history_root),
            "synth": _synth_rows(history_root),
            "runs": runs,
            "degradation": _degradation_rows(runs),
            "explain": _explain_rows(history_root),
            "workload": _workload_rows(history_root, errors),
            "watch": _watch_rows(history_root, errors),
            "flow": _flow_rows(history_root, errors),
            "trend": check_trends(history_root),
            "errors": errors}


_TEMPLATE = """<!DOCTYPE html>
<html lang="en"><head><meta charset="utf-8">
<title>tpu_aggcomm dashboard</title>
<style>
 body {{ font: 13px/1.5 system-ui, sans-serif; margin: 1.5em;
        color: #222; background: #fafafa; }}
 h1 {{ font-size: 1.3em; }} h2 {{ font-size: 1.05em; margin-top: 1.6em; }}
 .note {{ color: #777; }}
 .err {{ color: #a00; }}
 table {{ border-collapse: collapse; background: #fff; }}
 th, td {{ border: 1px solid #ddd; padding: 3px 8px; text-align: right; }}
 th {{ background: #f0f0f0; }}
 td.l, th.l {{ text-align: left; }}
 svg {{ background: #fff; border: 1px solid #ddd; }}
 .heat td {{ width: 34px; height: 18px; padding: 0; text-align: center;
            font-size: 10px; border: 1px solid #eee; }}
 .legend span {{ display: inline-block; margin-right: 1.2em; }}
 .swatch {{ display: inline-block; width: 10px; height: 10px;
           margin-right: 4px; }}
</style></head><body>
<h1>tpu_aggcomm — bench trajectory &amp; straggler dashboard</h1>
<p class="note">Self-contained snapshot: data inlined at generation
time; lower is better everywhere (seconds per rep).</p>
<div id="errors"></div>
<h2>Bench trajectory (per platform)</h2>
<div id="trajectory"></div>
<h2>Longitudinal trend (seeded multi-round slope gate)</h2>
<div id="trend"></div>
<h2>Run ledger (compile / HBM / environment)</h2>
<div id="ledger"></div>
<h2>Autotuner cache (winner per shape)</h2>
<div id="tune"></div>
<h2>Schedule synthesis (searched &rarr; proven &rarr; raced)</h2>
<div id="synth"></div>
<h2>Per-method skew table (trace runs)</h2>
<div id="skew"></div>
<h2>Straggler heatmaps (rank &times; round, mean seconds)</h2>
<div id="heat"></div>
<h2>Traffic audit (static conformance + src &rarr; dst bytes)</h2>
<div id="traffic"></div>
<h2>Fault degradation (recovery deltas)</h2>
<div id="degradation"></div>
<h2>Cost model (predicted vs measured, named verdicts)</h2>
<div id="explain"></div>
<h2>Workload profile (serve request flow)</h2>
<div id="workload"></div>
<h2>Monitoring (watchtower SLO + named anomalies)</h2>
<div id="watch"></div>
<h2>Flow tracing (client &rarr; server &rarr; round decomposition)</h2>
<div id="flow"></div>
<script id="data" type="application/json">{payload}</script>
<script>
"use strict";
var DATA = JSON.parse(document.getElementById("data").textContent);
var COLORS = ["#1668b0", "#c2491d", "#2e7d32", "#7b1fa2", "#8d6e63"];

function el(tag, attrs, text) {{
  var e = document.createElement(tag);
  for (var k in (attrs || {{}})) e.setAttribute(k, attrs[k]);
  if (text !== undefined) e.textContent = text;
  return e;
}}
function fmtS(v) {{
  if (v === null || v === undefined) return "-";
  if (v >= 1) return v.toFixed(3) + " s";
  if (v >= 1e-3) return (v * 1e3).toFixed(3) + " ms";
  return (v * 1e6).toFixed(3) + " \\u00b5s";
}}

(function errors() {{
  var host = document.getElementById("errors");
  (DATA.errors || []).forEach(function (m) {{
    host.appendChild(el("p", {{class: "err"}}, "history error: " + m));
  }});
}})();

(function trajectory() {{
  var host = document.getElementById("trajectory");
  var rows = DATA.bench.filter(function (r) {{
    return r.value !== null && r.value !== undefined; }});
  if (!rows.length) {{
    host.appendChild(el("p", {{class: "note"}},
                        "no measurable bench history"));
    return;
  }}
  var W = 640, H = 260, PAD = 48;
  var rounds = rows.map(function (r) {{ return r.round; }});
  var rmin = Math.min.apply(null, rounds),
      rmax = Math.max.apply(null, rounds);
  var lo = Infinity, hi = 0;
  rows.forEach(function (r) {{
    var vs = (r.samples || []).concat([r.value]);
    vs.forEach(function (v) {{ lo = Math.min(lo, v);
                               hi = Math.max(hi, v); }});
  }});
  // log scale: the history mixes ~us TPU rounds with ~tens-of-us CPU ones
  function x(rnd) {{
    return PAD + (rmax === rmin ? 0.5 : (rnd - rmin) / (rmax - rmin))
               * (W - 2 * PAD);
  }}
  function y(v) {{
    var t = (Math.log(v) - Math.log(lo)) /
            Math.max(1e-12, Math.log(hi) - Math.log(lo));
    return H - PAD - t * (H - 2 * PAD);
  }}
  var NS = "http://www.w3.org/2000/svg";
  var svg = document.createElementNS(NS, "svg");
  svg.setAttribute("width", W); svg.setAttribute("height", H);
  [lo, Math.sqrt(lo * hi), hi].forEach(function (v) {{
    var t = document.createElementNS(NS, "text");
    t.setAttribute("x", 4); t.setAttribute("y", y(v) + 4);
    t.setAttribute("font-size", "10"); t.textContent = fmtS(v);
    svg.appendChild(t);
  }});
  var platforms = [];
  rows.forEach(function (r) {{
    if (platforms.indexOf(r.platform) < 0) platforms.push(r.platform);
  }});
  platforms.forEach(function (plat, pi) {{
    var pts = rows.filter(function (r) {{ return r.platform === plat; }});
    var color = COLORS[pi % COLORS.length];
    var line = document.createElementNS(NS, "polyline");
    line.setAttribute("points", pts.map(function (r) {{
      return x(r.round) + "," + y(r.value); }}).join(" "));
    line.setAttribute("fill", "none");
    line.setAttribute("stroke", color);
    line.setAttribute("stroke-width", "1.5");
    svg.appendChild(line);
    pts.forEach(function (r) {{
      if (r.samples && r.samples.length) {{
        var w = document.createElementNS(NS, "line");
        w.setAttribute("x1", x(r.round)); w.setAttribute("x2", x(r.round));
        w.setAttribute("y1", y(Math.min.apply(null, r.samples)));
        w.setAttribute("y2", y(Math.max.apply(null, r.samples)));
        w.setAttribute("stroke", color); w.setAttribute("stroke-width", "1");
        svg.appendChild(w);
      }}
      var c = document.createElementNS(NS, "circle");
      c.setAttribute("cx", x(r.round)); c.setAttribute("cy", y(r.value));
      c.setAttribute("r", 3); c.setAttribute("fill", color);
      var title = document.createElementNS(NS, "title");
      title.textContent = "r" + r.round + " [" + r.platform + "]: " +
                          fmtS(r.value);
      c.appendChild(title);
      svg.appendChild(c);
      var t = document.createElementNS(NS, "text");
      t.setAttribute("x", x(r.round) - 6);
      t.setAttribute("y", H - PAD + 14);
      t.setAttribute("font-size", "10");
      t.textContent = "r" + r.round;
      svg.appendChild(t);
    }});
  }});
  host.appendChild(svg);
  var legend = el("div", {{class: "legend"}});
  platforms.forEach(function (plat, pi) {{
    var s = el("span");
    var sw = el("span", {{class: "swatch"}});
    sw.style.background = COLORS[pi % COLORS.length];
    s.appendChild(sw);
    s.appendChild(document.createTextNode(plat));
    legend.appendChild(s);
  }});
  host.appendChild(legend);
  if (DATA.multichip.length) {{
    var mc = DATA.multichip.map(function (m) {{
      return "r" + m.round + ":" +
             (m.skipped ? "skip" : (m.ok ? "ok" : "FAIL"));
    }}).join("  ");
    host.appendChild(el("p", {{class: "note"}}, "multichip: " + mc));
  }}
}})();

(function trendPane() {{
  var host = document.getElementById("trend");
  var t = DATA.trend || {{}};
  var keys = Object.keys(t.series || {{}});
  if (!keys.length) {{
    host.appendChild(el("p", {{class: "note"}},
        "no bench series to trend (history too short or unmeasurable)"));
    return;
  }}
  var tbl = el("table");
  var hr = el("tr");
  ["series", "rounds", "verdict", "slope %/round", "95% CI %/round",
   "note"].forEach(function (h, i) {{
    hr.appendChild(el("th", i === 0 || i === 5 ?
                      {{class: "l"}} : {{}}, h)); }});
  tbl.appendChild(hr);
  keys.sort().forEach(function (k) {{
    var g = t.series[k];
    var tr = el("tr");
    tr.appendChild(el("td", {{class: "l"}}, k));
    tr.appendChild(el("td", {{}}, String(g.rounds)));
    var vd = el("td", {{}}, g.verdict.toUpperCase());
    if (g.verdict === "drifting-up") vd.className = "err";
    tr.appendChild(vd);
    tr.appendChild(el("td", {{}},
        g.slope_pct_per_round === null ? "-" :
        (g.slope_pct_per_round >= 0 ? "+" : "") +
        g.slope_pct_per_round.toFixed(1)));
    tr.appendChild(el("td", {{}}, g.ci_pct_per_round ?
        "[" + g.ci_pct_per_round[0].toFixed(1) + ", " +
        g.ci_pct_per_round[1].toFixed(1) + "]" : "-"));
    tr.appendChild(el("td", {{class: "l"}}, g.note || ""));
    tbl.appendChild(tr);
  }});
  host.appendChild(tbl);
  host.appendChild(el("p", {{class: "note"}},
      "seeded bootstrap slope over the whole per-platform series " +
      "(seed " + t.seed + ", tolerance " + t.tolerance_pct +
      "%/round) — the longitudinal extension of --check-regression; " +
      "drifting-up fails the gate"));
}})();

(function ledgerPane() {{
  var host = document.getElementById("ledger");
  var rows = DATA.bench.filter(function (r) {{
    return r.compile_seconds !== null && r.compile_seconds !== undefined
        || r.hbm_peak_bytes !== null && r.hbm_peak_bytes !== undefined
        || r.jax; }});
  if (!rows.length) {{
    host.appendChild(el("p", {{class: "note"}},
        "no run-ledger data in the history (pre-v3 artifacts only)"));
    return;
  }}
  var tbl = el("table");
  var hr = el("tr");
  ["round", "platform", "jax", "compile", "HBM peak", "env drift vs prev"]
    .forEach(function (h, i) {{
      hr.appendChild(el("th", i === 5 ? {{class: "l"}} : {{}}, h)); }});
  tbl.appendChild(hr);
  rows.forEach(function (r) {{
    var tr = el("tr");
    tr.appendChild(el("td", {{}}, "r" + r.round));
    tr.appendChild(el("td", {{}}, r.platform || "-"));
    tr.appendChild(el("td", {{}}, r.jax || "-"));
    tr.appendChild(el("td", {{}}, fmtS(r.compile_seconds)));
    tr.appendChild(el("td", {{}},
        r.hbm_peak_bytes === null || r.hbm_peak_bytes === undefined ? "-" :
        (r.hbm_peak_bytes / 1048576).toFixed(1) + " MiB"));
    var td = el("td", {{class: "l"}});
    if (!r.drift.length) {{
      td.textContent = "none";
    }} else {{
      r.drift.forEach(function (d) {{
        td.appendChild(el("div", {{class: "err"}}, d)); }});
    }}
    tr.appendChild(td);
    tbl.appendChild(tr);
  }});
  host.appendChild(tbl);
}})();

(function tunePane() {{
  var host = document.getElementById("tune");
  var rows = DATA.tune || [];
  if (!rows.length) {{
    host.appendChild(el("p", {{class: "note"}},
        "no TUNE_*.json artifacts under the history root " +
        "(run `cli tune` to populate the tuned-schedule cache)"));
    return;
  }}
  rows.forEach(function (t) {{
    if (t.error) {{
      host.appendChild(el("p", {{class: "err"}},
          "tune artifact error: " + t.error));
      return;
    }}
    var k = t.key;
    var head = el("p", {{}});
    head.appendChild(el("b", {{}}, t.file));
    head.appendChild(document.createTextNode(
        " — n=" + k.nprocs + " d=" + k.data_size + " p=" + k.proc_node +
        " " + k.direction + " [" + k.backend + "]" +
        (t.synthetic ? " (synthetic)" : "") +
        "  winner: " + t.winner_cid +
        " after " + t.batches_run + " batch(es)"));
    host.appendChild(head);
    // elimination order lookup: cid -> batch it fell at
    var elim = {{}};
    (t.eliminations || []).forEach(function (e) {{
      elim[e.candidate] = e; }});
    // CI bar scale: widest upper bound across all eliminations
    var maxHi = 0;
    (t.eliminations || []).forEach(function (e) {{
      if (e.ci_pct && e.ci_pct.length === 2)
        maxHi = Math.max(maxHi, e.ci_pct[1]); }});
    var tbl = el("table");
    var hr = el("tr");
    ["candidate", "median", "status", "CI vs leader (% slower)"]
      .forEach(function (h, i) {{
        hr.appendChild(el("th", i !== 1 ? {{class: "l"}} : {{}}, h)); }});
    tbl.appendChild(hr);
    (t.order || []).forEach(function (cid) {{
      var tr = el("tr");
      tr.appendChild(el("td", {{class: "l"}}, cid));
      var med = t.medians ? t.medians[cid] : null;
      tr.appendChild(el("td", {{}},
          med === null || med === undefined ? "-" : fmtS(med)));
      var e = elim[cid];
      var status = cid === t.winner_cid ? "winner" :
          (e ? "eliminated @ batch " + e.batch + " (vs " + e.leader + ")"
             : "survivor (not separable)");
      tr.appendChild(el("td", {{class: "l"}}, status));
      var td = el("td", {{class: "l"}});
      if (e && e.ci_pct && e.ci_pct.length === 2 && maxHi > 0) {{
        var lo = Math.max(0, e.ci_pct[0]), hi = e.ci_pct[1];
        var wrap = el("span");
        wrap.style.display = "inline-block";
        wrap.style.width = "160px";
        wrap.style.height = "10px";
        wrap.style.background = "#eee";
        wrap.style.position = "relative";
        wrap.style.verticalAlign = "middle";
        var bar = el("span");
        bar.style.display = "inline-block";
        bar.style.position = "absolute";
        bar.style.left = (lo / maxHi * 160).toFixed(1) + "px";
        bar.style.width =
            Math.max(2, (hi - lo) / maxHi * 160).toFixed(1) + "px";
        bar.style.height = "10px";
        bar.style.background = "#c2491d";
        wrap.appendChild(bar);
        td.appendChild(wrap);
        td.appendChild(document.createTextNode(
            " [+" + e.ci_pct[0].toFixed(1) + "%, +" +
            e.ci_pct[1].toFixed(1) + "%]"));
      }} else {{
        td.textContent = "-";
      }}
      tr.appendChild(td);
      tbl.appendChild(tr);
    }});
    host.appendChild(tbl);
  }});
}})();

(function synthPane() {{
  var host = document.getElementById("synth");
  var rows = DATA.synth || [];
  if (!rows.length) {{
    host.appendChild(el("p", {{class: "note"}},
        "no SYNTH_r*.json artifacts under the history root " +
        "(run `cli synth` to search, prove and race new schedules)"));
    return;
  }}
  rows.forEach(function (s) {{
    if (s.error) {{
      host.appendChild(el("p", {{class: "err"}},
          "synth artifact error: " + s.error));
      return;
    }}
    var c = s.config;
    var head = el("p", {{}});
    head.appendChild(el("b", {{}}, s.file));
    head.appendChild(document.createTextNode(
        " — n=" + c.nprocs + " d=" + c.data_size + " a=" + c.cb_nodes +
        " c=" + c.comm_size + " " + c.direction + " [" + s.backend + "]" +
        (s.synthetic ? " (synthetic)" : "") +
        "  seed " + s.seed));
    host.appendChild(head);
    var p = s.pruned || {{}};
    host.appendChild(el("p", {{class: "note"}},
        "search funnel: " + s.evaluated + "/" + s.space_size +
        " compositions evaluated — pruned " +
        (p.invalid || 0) + " invalid, " + (p.check || 0) +
        " check-REFUTED, " + (p.traffic || 0) + " over traffic bound, " +
        (p.dominated || 0) + " dominated; " + s.finalists.length +
        " finalist(s) registered"));
    var ftbl = el("table");
    var fhr = el("tr");
    ["method id", "composition", "predicted rank", "raced"]
      .forEach(function (h, i) {{
        fhr.appendChild(el("th", i === 1 ? {{class: "l"}} : {{}}, h)); }});
    ftbl.appendChild(fhr);
    // raced rank: order of pooled medians over the full field
    var ranked = (s.order || []).slice().sort(function (a, b) {{
      var ma = s.medians[a], mb = s.medians[b];
      return (ma === undefined ? 1e99 : ma) -
             (mb === undefined ? 1e99 : mb); }});
    s.finalists.forEach(function (f) {{
      var tr = el("tr");
      tr.appendChild(el("td", {{}}, "m" + f.method_id));
      tr.appendChild(el("td", {{class: "l"}}, f.composition));
      tr.appendChild(el("td", {{}},
          f.predicted_rank === null || f.predicted_rank === undefined
            ? "-" : "#" + f.predicted_rank));
      var cid = null;
      (s.order || []).forEach(function (o) {{
        if (o.indexOf("m" + f.method_id + ":") === 0) cid = o; }});
      var pos = cid === null ? -1 : ranked.indexOf(cid);
      tr.appendChild(el("td", {{}},
          pos < 0 ? "-" : "#" + (pos + 1) + " of " + ranked.length +
          (s.medians[cid] !== undefined
             ? " (" + fmtS(s.medians[cid]) + ")" : "")));
      ftbl.appendChild(tr);
    }});
    host.appendChild(ftbl);
    var w = s.winner || {{}};
    var wp = el("p", {{}});
    wp.appendChild(el("b", {{}}, "race winner: " + s.winner_cid));
    wp.appendChild(document.createTextNode(
        " after " + s.batches_run + " batch(es)" +
        (w.synthesized
           ? " — SYNTHESIZED (" + w.composition + "), check " +
             w.check_verdict + ", traffic " + w.traffic_verdict +
             ", predicted rank #" + w.predicted_rank
           : " — reference method") +
        (w.median_s !== undefined
           ? ", median " + fmtS(w.median_s) : "")));
    host.appendChild(wp);
  }});
}})();

(function skewTable() {{
  var host = document.getElementById("skew");
  if (!DATA.runs.length) {{
    host.appendChild(el("p", {{class: "note"}},
        "no trace files passed — rerun with trace paths to populate"));
    return;
  }}
  var tbl = el("table");
  var hr = el("tr");
  ["trace", "m", "name", "fault", "n", "total", "worst skew (round)",
   "imbalance", "critical rank", "dominant cell", "provenance"]
    .forEach(function (h, i) {{
      hr.appendChild(el("th", i < 4 ? {{class: "l"}} : {{}}, h)); }});
  tbl.appendChild(hr);
  DATA.runs.forEach(function (r) {{
    var tr = el("tr");
    tr.appendChild(el("td", {{class: "l"}}, r.file + " #" + r.run));
    tr.appendChild(el("td", {{class: "l"}}, String(r.method)));
    tr.appendChild(el("td", {{class: "l"}}, r.name));
    tr.appendChild(el("td", {{class: "l"}}, r.fault || "healthy"));
    tr.appendChild(el("td", {{}}, String(r.nprocs)));
    tr.appendChild(el("td", {{}}, fmtS(r.total_s)));
    tr.appendChild(el("td", {{}}, r.worst_skew === null ? "-" :
        r.worst_skew.toFixed(2) + " (" + r.worst_skew_round + ")"));
    tr.appendChild(el("td", {{}}, r.imbalance === null ? "-" :
        (r.imbalance * 100).toFixed(1) + "%"));
    tr.appendChild(el("td", {{}}, r.critical_rank === null ? "-" :
        String(r.critical_rank)));
    tr.appendChild(el("td", {{class: "l"}}, r.dominant ?
        r.dominant.round + " [" + r.dominant.bucket + "] " +
        fmtS(r.dominant.seconds) +
        (r.dominant.share !== null ?
         " (" + (r.dominant.share * 100).toFixed(0) + "%)" : "")
        : "-"));
    tr.appendChild(el("td", {{class: "l"}}, r.phase_source));
    tbl.appendChild(tr);
  }});
  host.appendChild(tbl);
}})();

(function heatmaps() {{
  var host = document.getElementById("heat");
  var any = false;
  DATA.runs.forEach(function (r) {{
    if (!r.heat.ranks.length) return;
    any = true;
    host.appendChild(el("p", {{}}, r.file + " #" + r.run +
        " — m=" + r.method + " \\"" + r.name + "\\""));
    var mx = 0;
    r.heat.cells.forEach(function (row) {{
      row.forEach(function (v) {{ if (v) mx = Math.max(mx, v); }});
    }});
    var tbl = el("table", {{class: "heat"}});
    var hr = el("tr");
    hr.appendChild(el("th", {{class: "l"}}, "rank\\\\round"));
    r.heat.rounds.forEach(function (rd) {{
      hr.appendChild(el("th", {{}}, rd)); }});
    tbl.appendChild(hr);
    r.heat.ranks.forEach(function (rank, ri) {{
      var tr = el("tr");
      tr.appendChild(el("th", {{class: "l"}}, String(rank)));
      r.heat.cells[ri].forEach(function (v) {{
        var td = el("td");
        if (v === null || v === undefined) {{
          td.style.background = "#f5f5f5";
        }} else {{
          var t = mx > 0 ? v / mx : 0;
          td.style.background =
            "rgba(198, 40, 40," + (0.08 + 0.92 * t).toFixed(3) + ")";
          if (t > 0.55) td.style.color = "#fff";
          td.textContent = (v * 1e3).toFixed(1);
          td.title = fmtS(v);
        }}
        tr.appendChild(td);
      }});
      tbl.appendChild(tr);
    }});
    host.appendChild(tbl);
  }});
  if (!any) host.appendChild(el("p", {{class: "note"}},
      "no per-cell slices in the traces passed (or none passed)"));
}})();

(function trafficPane() {{
  var host = document.getElementById("traffic");
  var runs = (DATA.runs || []).filter(function (r) {{
    return r.traffic; }});
  if (!runs.length) {{
    host.appendChild(el("p", {{class: "note"}},
        "no trace runs to audit (pass trace paths to populate)"));
    return;
  }}
  var tbl = el("table");
  var hr = el("tr");
  ["trace", "m", "name", "verdict", "peak", "bound", "msgs", "bytes",
   "signals"].forEach(function (h, i) {{
    hr.appendChild(el("th", i < 4 ? {{class: "l"}} : {{}}, h)); }});
  tbl.appendChild(hr);
  runs.forEach(function (r) {{
    var t = r.traffic;
    var tr = el("tr");
    tr.appendChild(el("td", {{class: "l"}}, r.file + " #" + r.run));
    tr.appendChild(el("td", {{class: "l"}}, String(r.method)));
    tr.appendChild(el("td", {{class: "l"}}, r.name));
    var vd = el("td", {{class: "l"}}, t.verdict || (t.note || "-"));
    if (t.verdict === "REFUTED") vd.className = "l err";
    tr.appendChild(vd);
    tr.appendChild(el("td", {{}},
        t.peak === null || t.peak === undefined ? "-" : String(t.peak)));
    tr.appendChild(el("td", {{}},
        t.bound === null || t.bound === undefined ? "-" :
        t.bound + " (" + t.bound_formula + ")"));
    ["msgs", "bytes", "signals"].forEach(function (k) {{
      tr.appendChild(el("td", {{}},
          t.totals ? String(t.totals[k]) : "-")); }});
    tbl.appendChild(tr);
  }});
  host.appendChild(tbl);
  runs.forEach(function (r) {{
    var t = r.traffic;
    if (!t.matrix) {{
      if (t.note) host.appendChild(el("p", {{class: "note"}},
          r.file + " #" + r.run + ": " + t.note));
      return;
    }}
    host.appendChild(el("p", {{}}, r.file + " #" + r.run +
        " — src \\u2192 dst bytes, all rounds"));
    var mx = 0;
    t.matrix.forEach(function (row) {{
      row.forEach(function (v) {{ if (v) mx = Math.max(mx, v); }});
    }});
    var mt = el("table", {{class: "heat"}});
    var mh = el("tr");
    mh.appendChild(el("th", {{class: "l"}}, "src\\\\dst"));
    t.matrix.forEach(function (_row, d) {{
      mh.appendChild(el("th", {{}}, String(d))); }});
    mt.appendChild(mh);
    t.matrix.forEach(function (row, s) {{
      var mr = el("tr");
      mr.appendChild(el("th", {{class: "l"}}, String(s)));
      row.forEach(function (v) {{
        var td = el("td");
        if (!v) {{
          td.style.background = "#f5f5f5";
        }} else {{
          var tt = mx > 0 ? v / mx : 0;
          td.style.background =
            "rgba(21, 101, 192," + (0.15 + 0.85 * tt).toFixed(3) + ")";
          if (tt > 0.55) td.style.color = "#fff";
          td.title = v + " B";
        }}
        mr.appendChild(td);
      }});
      mt.appendChild(mr);
    }});
    host.appendChild(mt);
  }});
}})();

(function degradationPane() {{
  var host = document.getElementById("degradation");
  var rows = DATA.degradation || [];
  if (!rows.length) {{
    host.appendChild(el("p", {{class: "note"}},
        "no faulted trace runs passed — record with --fault and pass " +
        "both the healthy and the faulted trace to populate"));
    return;
  }}
  var tbl = el("table");
  var hr = el("tr");
  ["faulted trace", "m", "name", "fault", "n", "healthy", "faulted",
   "recovery delta", "%"].forEach(function (h, i) {{
    hr.appendChild(el("th", i < 4 ? {{class: "l"}} : {{}}, h)); }});
  tbl.appendChild(hr);
  rows.forEach(function (r) {{
    var tr = el("tr");
    tr.appendChild(el("td", {{class: "l"}}, r.file + " #" + r.run));
    tr.appendChild(el("td", {{class: "l"}}, String(r.method)));
    tr.appendChild(el("td", {{class: "l"}}, r.name));
    tr.appendChild(el("td", {{class: "l"}}, r.fault));
    tr.appendChild(el("td", {{}}, String(r.nprocs)));
    tr.appendChild(el("td", {{}},
        r.healthy_s === null || r.healthy_s === undefined ?
        "- (no healthy pair)" : fmtS(r.healthy_s)));
    tr.appendChild(el("td", {{}}, fmtS(r.faulted_s)));
    var dd = el("td", {{}}, r.delta_s === null || r.delta_s === undefined
        ? "-" : (r.delta_s >= 0 ? "+" : "") + fmtS(Math.abs(r.delta_s)));
    if (r.delta_s !== null && r.delta_s !== undefined && r.delta_s > 0)
      dd.className = "err";
    tr.appendChild(dd);
    tr.appendChild(el("td", {{}},
        r.pct === null || r.pct === undefined ? "-" :
        (r.pct >= 0 ? "+" : "") + r.pct.toFixed(1) + "%"));
    tbl.appendChild(tr);
  }});
  host.appendChild(tbl);
  host.appendChild(el("p", {{class: "note"}},
      "recovery delta = faulted critical-path seconds minus the first " +
      "healthy run of the same (method, n, data size) — the measured " +
      "cost of surviving the fault, not a regression"));
}})();

(function explainPane() {{
  var host = document.getElementById("explain");
  var ex = DATA.explain;
  if (!ex) {{
    host.appendChild(el("p", {{class: "note"}},
        "no PREDICT_*.json under the history root (run `cli inspect " +
        "explain --json PREDICT_rNN.json` to calibrate the cost model)"));
    return;
  }}
  if (ex.error) {{
    host.appendChild(el("p", {{class: "err"}},
        "cost-model artifact error: " + ex.error));
    return;
  }}
  var head = el("p", {{}});
  head.appendChild(el("b", {{}}, ex.file));
  var plats = [];
  for (var p in (ex.platforms || {{}})) {{
    var b = ex.platforms[p];
    plats.push(p + ": " + b.granularity + "-fit over " + b.observations +
        " obs, tol ±" + (b.tolerance_rel * 100).toFixed(1) + "%");
  }}
  head.appendChild(document.createTextNode(
      " (seed " + ex.seed + ") — " + plats.join("; ")));
  host.appendChild(head);
  var vlines = [];
  for (var g in (ex.validation || {{}})) {{
    var v = ex.validation[g];
    vlines.push(g + ": tau_b " +
        (v.tau_b === null ? "-" : v.tau_b.toFixed(3)) +
        (v.held_out ? " (HELD-OUT)" : "") + ", top-1 " +
        (v.top1 && v.top1.agree ? "agrees" : "DISAGREES"));
  }}
  if (vlines.length)
    host.appendChild(el("p", {{class: "note"}},
        "rank-order validation — " + vlines.join("; ")));
  (ex.explain || []).forEach(function (t) {{
    (t.runs || []).forEach(function (r) {{
      var cap = el("p", {{}});
      cap.appendChild(el("b", {{}}, t.trace));
      cap.appendChild(document.createTextNode(
          " — run #" + r.run + ": m" + r.method + " n=" + r.nprocs +
          " c=" + r.comm_size +
          (r.fault ? " [fault " + r.fault + "]" : "") +
          " (" + t.platform + ")"));
      host.appendChild(cap);
      var tbl = el("table");
      var hr = el("tr");
      ["round", "predicted", "measured", "deviation", "verdict"]
        .forEach(function (h, i) {{
          hr.appendChild(el("th", i === 0 || i === 4 ?
              {{class: "l"}} : {{}}, h)); }});
      tbl.appendChild(hr);
      var rows = (r.rounds || []).concat(
          r.total ? [Object.assign({{round: "total"}}, r.total)] : []);
      rows.forEach(function (row) {{
        var tr = el("tr");
        tr.appendChild(el("td", {{class: "l"}}, String(row.round)));
        tr.appendChild(el("td", {{}}, fmtS(row.predicted_s)));
        tr.appendChild(el("td", {{}},
            row.measured_s === null || row.measured_s === undefined ?
            "-" : fmtS(row.measured_s)));
        tr.appendChild(el("td", {{}},
            row.deviation_rel === null ||
            row.deviation_rel === undefined ? "-" :
            (row.deviation_rel >= 0 ? "+" : "") +
            (row.deviation_rel * 100).toFixed(1) + "%"));
        var vd = el("td", {{class: "l"}}, row.verdict);
        if (row.verdict && row.verdict.indexOf("UNEXPLAINED") === 0)
          vd.className = "l err";
        tr.appendChild(vd);
        tbl.appendChild(tr);
      }});
      host.appendChild(tbl);
    }});
  }});
  host.appendChild(el("p", {{class: "note"}},
      "predictions come from static op-program features alone " +
      "(tpu_aggcomm/model/, jax-free); verdicts name the dominant " +
      "modeled cost within the calibrated tolerance — advisory only, " +
      "measured rounds stay the source of truth"));
}})();

(function workloadPane() {{
  var host = document.getElementById("workload");
  var rows = DATA.workload || [];
  if (!rows.length) {{
    host.appendChild(el("p", {{class: "note"}},
        "no WORKLOAD_*.json under the history root (run `cli inspect " +
        "workload serve.journal.jsonl --json WORKLOAD_rNN.json` over a " +
        "serve journal)"));
    return;
  }}
  rows.forEach(function (w) {{
    var cap = el("p", {{}});
    cap.appendChild(el("b", {{}}, w.file));
    if (w.error) {{
      host.appendChild(cap);
      host.appendChild(el("p", {{class: "err"}},
          "workload artifact error: " + w.error));
      return;
    }}
    var req = w.requests || {{}};
    var arr = w.arrivals || {{}};
    cap.appendChild(document.createTextNode(
        " (seed " + w.seed + ") — " + req.admitted + " admitted: " +
        req.completed + " done, " + req.failed + " fail, " +
        req.shed + " shed, " + (req.lost || []).length + " lost; " +
        (arr.rps === null || arr.rps === undefined ?
         "single arrival" :
         arr.rps.toFixed(1) + " req/s, interarrival CV " +
         (arr.cv === null || arr.cv === undefined ?
          "-" : arr.cv.toFixed(2)))));
    host.appendChild(cap);
    var tbl = el("table");
    var hr = el("tr");
    ["phase", "n", "mean", "p50", "p95", "max", "total"]
      .forEach(function (h, i) {{
        hr.appendChild(el("th", i === 0 ? {{class: "l"}} : {{}}, h)); }});
    tbl.appendChild(hr);
    var pt = w.phase_totals || {{}};
    ["queue", "batch", "cache", "dispatch", "respond"]
      .forEach(function (ph) {{
        var s = pt[ph];
        if (!s) return;
        var tr = el("tr");
        tr.appendChild(el("td", {{class: "l"}}, ph));
        tr.appendChild(el("td", {{}}, String(s.n)));
        [s.mean_s, s.p50_s, s.p95_s, s.max_s, s.total_s]
          .forEach(function (v) {{
            tr.appendChild(el("td", {{}}, fmtS(v))); }});
        tbl.appendChild(tr);
      }});
    host.appendChild(tbl);
    var mix = (w.shape_mix || []).map(function (m) {{
      var sh = m.shape || {{}};
      return "m" + sh.method + " n=" + sh.nprocs + " d=" + sh.data_size +
          " [" + m.backend + "]: " + m.count + " (" +
          (m.fraction * 100).toFixed(0) + "%)";
    }});
    if (mix.length)
      host.appendChild(el("p", {{class: "note"}},
          "shape mix — " + mix.join("; ")));
    var b = w.batching || {{}};
    if (b.batches)
      host.appendChild(el("p", {{class: "note"}},
          "batching — " + b.batches + " batch(es), " +
          b.requests_batched + " requests in " + b.padded_slots +
          " padded slots (fill " +
          (b.fill_ratio === null || b.fill_ratio === undefined ?
           "-" : (b.fill_ratio * 100).toFixed(0) + "%") +
          ", padding waste " + b.padding_waste_bytes + " B)"));
    (w.proposals || []).forEach(function (p) {{
      host.appendChild(el("p", {{class: "note"}},
          "advisory [" + p.kind + "]: " + p.reason));
    }});
  }});
  host.appendChild(el("p", {{class: "note"}},
      "phase attribution is journal-derived (obs/workload.py over the " +
      "serve journal's boundary stamps, float-exact vs `inspect " +
      "workload`) — proposals are advisory only, nothing here gates"));
}})();

(function watchPane() {{
  var host = document.getElementById("watch");
  var rows = DATA.watch || [];
  if (!rows.length) {{
    host.appendChild(el("p", {{class: "note"}},
        "no WATCH_r*.json under the history root (run `cli inspect " +
        "watch serve.journal.jsonl --json WATCH_rNN.json` over a serve " +
        "journal)"));
    return;
  }}
  rows.forEach(function (w) {{
    var cap = el("p", {{}});
    cap.appendChild(el("b", {{}}, w.file));
    if (w.error) {{
      host.appendChild(cap);
      host.appendChild(el("p", {{class: "err"}},
          "watch artifact error: " + w.error));
      return;
    }}
    var req = w.requests || {{}};
    cap.appendChild(document.createTextNode(
        " (seed " + w.seed + ", slo " + w.slo_source + ") — " +
        req.admitted + " admitted: " + req.completed + " done, " +
        req.failed + " fail, " + req.shed + " shed, " +
        (req.lost || []).length + " lost — SLO " +
        (w.compliant ? "COMPLIANT" : "VIOLATED")));
    if (!w.compliant) cap.appendChild(el("span", {{class: "err"}}, " !"));
    host.appendChild(cap);
    var ig = w.integrity || {{}};
    if (ig.journal_torn_lines || ig.trace_torn_lines ||
        (ig.lost_requests || []).length)
      host.appendChild(el("p", {{class: "err"}},
          "integrity: " + (ig.journal_torn_lines || 0) +
          " torn journal line(s), " + (ig.trace_torn_lines || 0) +
          " torn trace line(s), lost requests [" +
          (ig.lost_requests || []).join(", ") + "]"));
    var tbl = el("table");
    var hr = el("tr");
    ["objective", "kind", "target", "worst burn", "windows (burn \\u00d7 budget)",
     "status"].forEach(function (h, i) {{
      hr.appendChild(el("th", i < 2 || i > 3 ? {{class: "l"}} : {{}}, h));
    }});
    tbl.appendChild(hr);
    (w.objectives || []).forEach(function (o) {{
      var tr = el("tr");
      tr.appendChild(el("td", {{class: "l"}}, o.name));
      tr.appendChild(el("td", {{class: "l"}}, o.kind));
      tr.appendChild(el("td", {{}}, (o.target * 100).toFixed(0) + "%"));
      tr.appendChild(el("td", {{}},
          o.worst_burn === null || o.worst_burn === undefined ?
          "-" : o.worst_burn.toFixed(2) + "x"));
      // sparkline: burn per tumbling window, every configured window size
      var wt = el("td", {{class: "l"}});
      Object.keys(o.windows || {{}}).sort().forEach(function (wn) {{
        var burns = o.windows[wn];
        var txt = burns.map(function (b) {{
          return b === null || b === undefined ? "\\u00b7" : b.toFixed(1);
        }}).join(" ");
        wt.appendChild(el("div", {{}}, wn + ": " + txt));
      }});
      tr.appendChild(wt);
      var st = el("td", {{class: "l"}},
          o.compliant === null || o.compliant === undefined ? "no data" :
          (o.compliant ? "ok" : "BURNING"));
      if (o.compliant === false) st.className = "l err";
      tr.appendChild(st);
      tbl.appendChild(tr);
    }});
    host.appendChild(tbl);
    if (!(w.anomalies || []).length) {{
      host.appendChild(el("p", {{class: "note"}},
          "no confirmed changepoints (seeded detector, seed " +
          w.seed + ")"));
    }} else {{
      var at = el("table");
      var ah = el("tr");
      ["stream", "at", "before", "after", "step", "95% CI", "cause",
       "evidence", "detail"].forEach(function (h, i) {{
        ah.appendChild(el("th", i < 2 || i > 5 ?
            {{class: "l"}} : {{}}, h)); }});
      at.appendChild(ah);
      w.anomalies.forEach(function (a) {{
        var d = a.detection || {{}};
        var tr = el("tr");
        tr.appendChild(el("td", {{class: "l"}}, a.stream));
        tr.appendChild(el("td", {{class: "l"}},
            a.at_rid !== null && a.at_rid !== undefined ?
            "rid " + a.at_rid : "round " + a.at_round));
        tr.appendChild(el("td", {{}}, fmtS(d.before_mean)));
        tr.appendChild(el("td", {{}}, fmtS(d.after_mean)));
        tr.appendChild(el("td", {{}},
            d.delta_rel === null || d.delta_rel === undefined ? "-" :
            (d.delta_rel >= 0 ? "+" : "") +
            (d.delta_rel * 100).toFixed(0) + "%"));
        tr.appendChild(el("td", {{}}, d.ci_rel ?
            "[" + (d.ci_rel[0] * 100).toFixed(0) + "%, " +
            (d.ci_rel[1] * 100).toFixed(0) + "%]" : "-"));
        var cd = el("td", {{class: "l"}}, a.cause);
        if (a.cause === "UNEXPLAINED") cd.className = "l err";
        tr.appendChild(cd);
        tr.appendChild(el("td", {{class: "l"}}, a.evidence));
        tr.appendChild(el("td", {{class: "l"}}, a.detail));
        at.appendChild(tr);
      }});
      host.appendChild(at);
    }}
  }});
  host.appendChild(el("p", {{class: "note"}},
      "SLO burn rates and changepoints are journal/trace-derived " +
      "(obs/watch.py, seeded — float-exact vs `inspect watch`); every " +
      "root-cause verdict names its evidence stream, UNEXPLAINED " +
      "quantifies the residual — advisory only, nothing here gates"));
}})();

(function flowPane() {{
  var host = document.getElementById("flow");
  var rows = DATA.flow || [];
  if (!rows.length) {{
    host.appendChild(el("p", {{class: "note"}},
        "no FLOW_r*.json under the history root (run `cli inspect " +
        "flow CLIENT.journal SERVE.journal TRACE... --json " +
        "FLOW_rNN.json` over a client-journaled loadgen run)"));
    return;
  }}
  function pct(v) {{
    return v === null || v === undefined ? "-" :
        (v * 100).toFixed(1) + "%";
  }}
  rows.forEach(function (f) {{
    var cap = el("p", {{}});
    cap.appendChild(el("b", {{}}, f.file));
    if (f.error) {{
      host.appendChild(cap);
      host.appendChild(el("p", {{class: "err"}},
          "flow artifact error: " + f.error));
      return;
    }}
    var req = f.requests || {{}};
    var wo = f.warm_overhead;
    cap.appendChild(document.createTextNode(
        " (seed " + f.seed + ") — " + req.joined + " joined of " +
        req.client + " client request(s), " +
        (req.lost || []).length + " LOST — warm overhead " +
        (wo ? pct(wo.mean) + " of the warm wall (n=" + wo.n +
              (wo.ci95 ? ", 95% CI [" + pct(wo.ci95[0]) + ", " +
                         pct(wo.ci95[1]) + "]" : "") + ")"
            : "no warm requests")));
    host.appendChild(cap);
    var ig = f.integrity || {{}};
    if (ig.client_torn_lines || ig.journal_torn_lines ||
        ig.trace_torn_lines || (req.lost || []).length)
      host.appendChild(el("p", {{class: "err"}},
          "integrity: " + (ig.client_torn_lines || 0) +
          " torn client line(s), " + (ig.journal_torn_lines || 0) +
          " torn journal line(s), " + (ig.trace_torn_lines || 0) +
          " torn trace line(s), LOST [" +
          (req.lost || []).join(", ") + "]"));
    var verd = f.verdicts || {{}};
    var vtxt = Object.keys(verd).sort(function (a, b) {{
      return verd[b] - verd[a] || (a < b ? -1 : 1);
    }}).map(function (v) {{ return v + " \\u00d7" + verd[v]; }});
    if (vtxt.length)
      host.appendChild(el("p", {{}}, "verdicts: " + vtxt.join(", ")));
    // warm component fractions: where the warm walls go, as bars
    var wc = f.warm_components || {{}};
    var order = f.component_order || Object.keys(wc).sort();
    var any = order.some(function (c) {{ return wc[c]; }});
    if (any) {{
      var ct = el("table");
      var ch = el("tr");
      ["component", "warm mean fraction", "", "n"].forEach(
          function (h, i) {{
        ch.appendChild(el("th", i === 0 || i === 2 ?
            {{class: "l"}} : {{}}, h)); }});
      ct.appendChild(ch);
      order.forEach(function (c) {{
        var b = wc[c];
        if (!b) return;
        var tr = el("tr");
        tr.appendChild(el("td", {{class: "l"}}, c));
        tr.appendChild(el("td", {{}}, pct(b.mean_fraction)));
        var bar = el("td", {{class: "l"}});
        var sw = el("span", {{class: "swatch"}});
        sw.style.width = Math.max(1,
            Math.round((b.mean_fraction || 0) * 160)) + "px";
        sw.style.background = COLORS[0];
        bar.appendChild(sw);
        tr.appendChild(bar);
        tr.appendChild(el("td", {{}}, String(b.n)));
        ct.appendChild(tr);
      }});
      host.appendChild(ct);
    }}
    if ((f.per_request || []).length) {{
      var rt = el("table");
      var rh = el("tr");
      var comps = f.component_order || [];
      ["rid", "client wall", "cache", "verdict"].concat(comps)
          .forEach(function (h, i) {{
        rh.appendChild(el("th", i === 2 || i === 3 ?
            {{class: "l"}} : {{}}, h)); }});
      rt.appendChild(rh);
      f.per_request.forEach(function (r) {{
        var tr = el("tr");
        tr.appendChild(el("td", {{}}, String(r.rid)));
        tr.appendChild(el("td", {{}}, fmtS(r.client_wall_s)));
        tr.appendChild(el("td", {{class: "l"}}, r.cache || "-"));
        tr.appendChild(el("td", {{class: "l"}}, r.verdict || "-"));
        comps.forEach(function (c) {{
          tr.appendChild(el("td", {{}}, pct((r.fractions || {{}})[c])));
        }});
        rt.appendChild(tr);
      }});
      host.appendChild(rt);
    }}
  }});
  host.appendChild(el("p", {{class: "note"}},
      "decompositions join the client stamp journal, the serve " +
      "journal's phase boundaries and the flight-recorder round walls " +
      "by correlation id (obs/flow.py, jax-free — every number " +
      "re-derives float-exactly via `inspect flow --replay`); the " +
      "residual is quantified, never absorbed — advisory only, " +
      "nothing here gates"));
}})();
</script></body></html>
"""


def render_html(payload: dict) -> str:
    """The complete dashboard document for one payload."""
    # "</" must not appear inside the inline <script> JSON block — a
    # trace run name containing "</script>" would end the element early
    blob = json.dumps(payload).replace("</", "<\\/")
    return _TEMPLATE.format(payload=blob)


def write_report(out_path: str, *, history_root: str = ".",
                 trace_paths: list[str] | None = None) -> str:
    """Build the payload and write the dashboard; returns ``out_path``."""
    from tpu_aggcomm.obs.atomic import atomic_write
    doc = render_html(build_payload(history_root, trace_paths))
    with atomic_write(out_path) as fh:
        fh.write(doc)
    return out_path
