"""OpenMetrics export: latency histograms + an optional /metrics thread.

The post-hoc analytics (``inspect trace``, the regression gate) answer
"what happened"; this module answers "what is happening" — the same
numbers, rendered in the OpenMetrics/Prometheus text format so an
external scraper or a plain ``curl`` can watch a long-running sweep or
capture batch live. Three pieces:

- :class:`LatencyHistogram` — HDR-style log-bucketed counts for the
  scrape-friendly cumulative view, PLUS the exact observations, so
  quantiles are reconstructed exactly (``obs.metrics.percentile`` over
  the retained values — the same arithmetic ``round_stats`` uses, so an
  exported p50/p95 matches ``inspect trace`` float-for-float, never a
  bucket-midpoint approximation). Observation counts here are
  per-rep/per-round walls — dozens to thousands of floats — so keeping
  them exact is cheap and honest.
- :class:`MetricsRegistry` + :func:`trace_registry` — counters, gauges
  and histograms rendered as OpenMetrics text. Trace-derived metrics
  come from the attribution cell stream (``round_stats`` /
  ``cell_means`` over recorder events) — NEVER from host callbacks;
  the exporter reads the same events the flight recorder writes.
- :class:`MetricsServer` / :func:`serve_from_env` — a stdlib
  ``http.server`` thread exposing ``/metrics``. OFF by default: it
  exists only when ``TPU_AGGCOMM_METRICS_PORT`` is set (or a CLI flag
  passes a port), and nothing in the hot path imports this module
  otherwise (the zero-cost obs invariant; pinned in tests). Binds
  127.0.0.1 only — telemetry is for the operator's terminal, not the
  network.

The serve layer (behind the same import gate) additionally exports the
batch-efficiency gauges ``tpu_aggcomm_serve_batch_fill_ratio`` and
``tpu_aggcomm_serve_padding_waste_bytes`` — computed with the
``obs.workload`` helpers the profiler itself uses, so the /metrics
numbers equal the ``inspect workload`` batching block float-for-float
(scripts/telemetry_gate.py cross-checks over committed artifacts).

jax-free, stdlib only (obs discipline).
"""

from __future__ import annotations

import math
import threading

__all__ = ["LatencyHistogram", "MetricsRegistry", "MetricsServer",
           "trace_registry", "serve_from_env", "METRICS_PORT_ENV",
           "default_buckets", "PREFIX", "SERVE_STATE_VALUES"]

#: The env var that switches the /metrics endpoint ON (absent/empty =
#: no server, no socket, no thread — the documented default).
METRICS_PORT_ENV = "TPU_AGGCOMM_METRICS_PORT"

#: The serve lifecycle states as gauge values for
#: ``tpu_aggcomm_serve_state`` (serve/server.py SERVE_STATES, in
#: order): a scraper alerts on the NUMBER going up, the state name
#: stays in the server's ``health`` op.
SERVE_STATE_VALUES = {"ready": 0, "degraded": 1, "draining": 2}

#: Metric-name prefix for everything this repo exports.
PREFIX = "tpu_aggcomm"

#: Exact summary quantiles rendered beside every histogram.
QUANTILES = (0.5, 0.95, 0.99)


def default_buckets() -> tuple[float, ...]:
    """HDR-style log bucket upper bounds: 5 per decade from 100 ns to
    1000 s — wide enough for a sub-µs local rep and a tunnel-throttled
    flagship cell on the same axis."""
    return tuple(10.0 ** (-7 + i / 5.0) for i in range(51))


def _fmt(v) -> str:
    """Float formatting that round-trips exactly (``float(repr(x)) ==
    x``) — the exported quantiles must survive parse-and-compare."""
    if isinstance(v, float):
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        return repr(v)
    return str(v)


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class LatencyHistogram:
    """Log-bucketed latency histogram with exact quantile recall."""

    def __init__(self, buckets: tuple[float, ...] | None = None):
        self.bounds = tuple(buckets) if buckets else default_buckets()
        self.counts = [0] * (len(self.bounds) + 1)   # +1: the +Inf bucket
        self.values: list[float] = []                 # exact observations

    def observe(self, value: float) -> None:
        v = float(value)
        self.values.append(v)
        for i, b in enumerate(self.bounds):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    @property
    def count(self) -> int:
        return len(self.values)

    @property
    def sum(self) -> float:
        return math.fsum(self.values)

    def quantile(self, q: float) -> float:
        """EXACT quantile of the observed values — the same
        ``obs.metrics.percentile`` arithmetic ``round_stats`` uses, so
        this matches ``inspect trace`` float-for-float."""
        from tpu_aggcomm.obs.metrics import percentile
        return percentile(self.values, q * 100.0)


class MetricsRegistry:
    """Thread-safe counter/gauge/histogram store with an OpenMetrics
    text renderer. Samples are keyed (name, sorted label items)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = {}
        self._gauges: dict[tuple, float] = {}
        self._hists: dict[tuple, LatencyHistogram] = {}

    @staticmethod
    def _key(name: str, labels: dict) -> tuple:
        return (name, tuple(sorted((str(k), str(v))
                                   for k, v in labels.items())))

    def counter(self, name: str, inc: float = 1.0, **labels) -> None:
        key = self._key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + inc

    def gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges[self._key(name, labels)] = float(value)

    def observe(self, name: str, value: float, **labels) -> None:
        key = self._key(name, labels)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = LatencyHistogram()
            h.observe(value)

    def render(self) -> str:
        """The registry as OpenMetrics text (ends with ``# EOF``).

        Histograms render the cumulative bucket view plus a sibling
        ``<name>_exact`` summary carrying the exact quantiles — a
        scraper gets the standard shape, a human diffing against
        ``inspect trace`` gets the float-exact numbers."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: (h.bounds, list(h.counts), list(h.values))
                     for k, h in self._hists.items()}
        lines: list[str] = []
        for family in sorted({name for name, _ in counters}):
            lines.append(f"# TYPE {family} counter")
            for (name, litems), v in sorted(counters.items()):
                if name == family:
                    lines.append(f"{name}_total"
                                 f"{_labels(dict(litems))} {_fmt(v)}")
        for family in sorted({name for name, _ in gauges}):
            lines.append(f"# TYPE {family} gauge")
            for (name, litems), v in sorted(gauges.items()):
                if name == family:
                    lines.append(f"{name}{_labels(dict(litems))} "
                                 f"{_fmt(v)}")
        from tpu_aggcomm.obs.metrics import percentile
        for family in sorted({name for name, _ in hists}):
            lines.append(f"# TYPE {family} histogram")
            exact: list[str] = []
            for (name, litems), (bounds, counts, values) in \
                    sorted(hists.items()):
                if name != family:
                    continue
                base = dict(litems)
                cum = 0
                for b, c in zip(bounds, counts):
                    cum += c
                    lines.append(
                        f"{name}_bucket"
                        f"{_labels(dict(base, le=_fmt(float(b))))} {cum}")
                cum += counts[-1]
                lines.append(f"{name}_bucket"
                             f"{_labels(dict(base, le='+Inf'))} {cum}")
                lines.append(f"{name}_count{_labels(base)} "
                             f"{len(values)}")
                lines.append(f"{name}_sum{_labels(base)} "
                             f"{_fmt(math.fsum(values))}")
                if values:
                    for q in QUANTILES:
                        exact.append(
                            f"{name}_exact"
                            f"{_labels(dict(base, quantile=_fmt(float(q))))}"
                            f" {_fmt(percentile(values, q * 100.0))}")
            if exact:
                lines.append(f"# TYPE {family}_exact summary")
                lines.extend(exact)
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


def trace_registry(events: list[dict],
                   registry: MetricsRegistry | None = None
                   ) -> MetricsRegistry:
    """Fold one flight-recorder event stream into a registry.

    Everything latency-shaped is derived from the attribution cell
    stream (``round_stats`` / ``cell_means`` replaying the recorded
    Timer arithmetic) — never from host callbacks. Per run:

    - gauges ``<p>_round_{wall,p50,p95}_seconds{run,round}`` — the
      ``round_stats`` values VERBATIM (float-exact vs ``inspect
      trace``);
    - histogram ``<p>_rank_round_seconds{run}`` observing every
      per-(rank, round) mean cell — its exact summary quantiles are the
      same percentile arithmetic over the same values;
    - counters for resilience attempts/retries (``ledger.resilience``
      instants) and gauges for HBM peak and peak incast depth.
    """
    from tpu_aggcomm.obs.metrics import cell_means, round_stats
    reg = registry if registry is not None else MetricsRegistry()
    runs = [e for e in events if e.get("ev") == "run"]
    for run in runs:
        rid = run["id"]
        lab = {"run": rid, "method": run.get("name", "?"),
               "backend": run.get("backend", "?")}
        for rs in round_stats(events, rid):
            rl = dict(lab, round=rs["round"])
            reg.gauge(f"{PREFIX}_round_wall_seconds", rs["wall"], **rl)
            reg.gauge(f"{PREFIX}_round_p50_seconds", rs["p50"], **rl)
            reg.gauge(f"{PREFIX}_round_p95_seconds", rs["p95"], **rl)
        for (_rank, _rnd), secs in sorted(cell_means(events, rid).items()):
            reg.observe(f"{PREFIX}_rank_round_seconds", secs, **lab)
    hbm_peak = None
    for e in events:
        ev = e.get("ev")
        if ev == "hbm" and e.get("peak_bytes") is not None:
            p = int(e["peak_bytes"])
            hbm_peak = p if hbm_peak is None else max(hbm_peak, p)
        elif ev == "instant" and e.get("name") == "ledger.resilience":
            args = e.get("args") or {}
            kind = args.get("kind", "?")
            reg.counter(f"{PREFIX}_resilience_records",
                        site=args.get("site", "?"), kind=kind)
            if kind == "attempt" and args.get("outcome") == "retry":
                reg.counter(f"{PREFIX}_retries",
                            site=args.get("site", "?"))
        elif ev == "counter" and e.get("name") == "traffic_max_incast":
            reg.gauge(f"{PREFIX}_traffic_max_incast", e["value"],
                      run=e.get("run", "?"))
    if hbm_peak is not None:
        reg.gauge(f"{PREFIX}_hbm_peak_bytes", hbm_peak)
    return reg


class MetricsServer:
    """A daemon-thread ``http.server`` serving ``/metrics``.

    ``source`` is a zero-arg callable returning the OpenMetrics text at
    scrape time — the server holds no copy, so a scrape always sees the
    current registry/trace state. Never constructed unless telemetry
    was explicitly enabled (:func:`serve_from_env` or a CLI flag)."""

    CONTENT_TYPE = ("application/openmetrics-text; version=1.0.0; "
                    "charset=utf-8")

    def __init__(self, source, port: int = 0, host: str = "127.0.0.1"):
        import http.server

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 (stdlib API name)
                if self.path.rstrip("/") not in ("/metrics", ""):
                    self.send_error(404)
                    return
                body = server._source().encode()
                self.send_response(200)
                self.send_header("Content-Type", server.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes are not stderr news
                pass

        self._source = source
        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tpu-aggcomm-metrics",
            daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def serve_from_env(source, env=None, *,
                   port: int | None = None) -> MetricsServer | None:
    """Start a :class:`MetricsServer` iff telemetry was asked for.

    ``port`` (a CLI flag) wins; otherwise ``TPU_AGGCOMM_METRICS_PORT``
    in ``env`` (default ``os.environ``). Absent/empty/garbage = None —
    no socket, no thread, nothing. Port 0 binds an ephemeral port:
    the actual bound port is announced on stderr and recorded in the
    ledger (the PORT NUMBER only — same by-name discipline as
    env_summary) so ``inspect live`` and the serve load generator can
    find the endpoint after the fact."""
    if port is None:
        import os
        raw = (env if env is not None else os.environ).get(
            METRICS_PORT_ENV, "").strip()
        if not raw:
            return None
        try:
            port = int(raw)
        except ValueError:
            import sys
            print(f"# telemetry: ignoring non-integer "
                  f"{METRICS_PORT_ENV}={raw!r}", file=sys.stderr)
            return None
    srv = MetricsServer(source, port=port)
    if port == 0:
        import sys

        from tpu_aggcomm.obs import ledger
        print(f"# telemetry: /metrics bound on ephemeral port "
              f"{srv.port} ({srv.url})", file=sys.stderr)
        # kind != "attempt", so replay_attempts ignores this record
        ledger.record_resilience("metrics.endpoint", kind="bind",
                                 port=srv.port)
    return srv
