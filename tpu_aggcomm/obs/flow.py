"""End-to-end causal flow tracing: client → server → round join.

Three crash-safe streams already record a serve request's life, each
from its own vantage point: the loadgen's client-side stamp journal
(``serve_loadgen.py --client-journal`` — send/recv wall stamps per
request), the serve journal's phase-boundary stamps (obs/workload.py
BOUNDARIES, written by serve/server.py at its existing sites), and the
flight recorder's attributed run event per batch dispatch (stamped with
the batch correlation id ``cid`` via ``trace.run_context``). This
module is the **jax-free** causal joiner: it stitches the three into
one per-request end-to-end timeline, so a request's ``client_wall_s``
decomposes as

    wire + queue + batch + cache + (rounds + dispatch overhead) + respond

with every component a NAMED number and the residual quantified, never
silently absorbed. Per request the dominant component yields a NAMED
verdict (wire-bound / queue-bound / batch-wait-bound / compile-bound /
round-bound / dispatch-overhead-bound / respond-bound — a bare number
is a regression), and over the warm (cache-hit) requests the module
keeps the **warm overhead ledger**: the fraction of each client wall
NOT spent in device rounds, with a seeded-bootstrap CI (the regression-
gate seed discipline) — the trend-gated target of the ROADMAP item-1
warm-path work.

Float-exactness discipline: every derived number in a row is defined by
ONE expression in this module (``client_wall_s = t_recv - t_send``;
``server_wall_s`` = the workload profiler's canonical phase sum;
``wire_s = client_wall_s - server_wall_s``; ``residual_s =
phases["dispatch"] - run wall``; fractions = component / client wall),
and ``obs.regress.validate_flow`` re-runs the identical expressions
over a committed artifact's own rows — an artifact its own numbers
contradict is schema-invalid. IEEE addition is not associative, so the
contract is identical-computation equality, never algebraic
re-summation.

Join keys: client recv lines join serve journal records by ``rid``;
serve records join run events by ``cid`` (``b<batch_seq>``). When the
serve journal tail is torn, the ``serve.request`` trace instants (which
carry rid, phases, cache AND cid) stand in as the server-side record —
the joiner works on traces alone. All three streams are tailed
torn-line-tolerantly with the skips COUNTED into ``integrity`` (the
watchtower discipline); a client send with no recv names the request
LOST in flight.

``FLOW_r*.json`` (flow-v1) is written atomically, schema-validated by
``obs.regress.validate_flow``, discovered by ``obs.history``
(``inspect history`` trend-gates the "flow warm overhead fraction"
series), rendered as an ``inspect report`` pane, exported as opt-in
``/metrics`` gauges (:func:`flow_registry`, held float-exact by
telemetry_gate.py), and replays to REPRODUCED from the stream basenames
recorded inside it (:func:`replay_flow` — the tune/PREDICT/WORKLOAD/
WATCH replay discipline).
"""

from __future__ import annotations

import json
import os
import random
import time

from tpu_aggcomm.obs.atomic import atomic_write
from tpu_aggcomm.obs.watch import _tail_trace, tail_journal
from tpu_aggcomm.obs.workload import BOUNDARIES, attribute_phases

__all__ = ["FLOW_SCHEMA", "COMPONENT_ORDER", "VERDICTS", "tail_client",
           "decompose_request", "dominant_component", "flow_streams",
           "write_flow", "replay_flow", "render_flow", "flow_registry"]

FLOW_SCHEMA = "flow-v1"

#: Canonical component order — the decomposition's spine AND the
#: dominant-verdict tie-break (first in this order wins a tie). "round"
#: is the joined dispatch's device-round wall; "overhead" is the
#: quantified residual between the journal's dispatch phase and that
#: wall (retry wrapper, span bookkeeping, result unpacking).
COMPONENT_ORDER = ("wire", "queue", "batch", "cache", "round",
                   "overhead", "respond")

#: Component -> the NAMED per-request verdict (a bare number is a
#: regression). "compile-bound" is the cache component: on a miss the
#: cache phase IS the compile (serve/server.py marks "cache" after the
#: lookup-or-compile resolves).
VERDICTS = {
    "wire": "wire-bound",
    "queue": "queue-bound",
    "batch": "batch-wait-bound",
    "cache": "compile-bound",
    "round": "round-bound",
    "overhead": "dispatch-overhead-bound",
    "respond": "respond-bound",
}

#: Bootstrap resamples for the warm-overhead CI (seeded — same streams
#: + same seed ⟹ same interval byte-for-byte).
N_BOOT = 2000


# ---------------------------------------------------------------------------
# Stream tails (torn lines COUNTED, never absorbed — the watch discipline).

def tail_client(path: str) -> dict:
    """Torn-line-tolerant client stamp-journal tail.

    Returns ``{"sends": {i: rec}, "recvs": {i: rec}, "skipped_lines"}``.
    A ``send`` with no matching ``recv`` is a request LOST in flight
    (SIGKILLed loadgen / server that never answered) — the caller names
    it, this tail only preserves the evidence."""
    sends: dict = {}
    recvs: dict = {}
    skipped = 0
    try:
        fh = open(path)
    except OSError:
        return {"sends": sends, "recvs": recvs, "skipped_lines": 0}
    with fh:
        for line in fh:
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if not isinstance(rec, dict) or not isinstance(
                    rec.get("i"), int):
                skipped += 1
                continue
            if rec.get("ev") == "send":
                sends.setdefault(rec["i"], rec)
            elif rec.get("ev") == "recv":
                recvs.setdefault(rec["i"], rec)
            else:
                skipped += 1
    return {"sends": sends, "recvs": recvs, "skipped_lines": skipped}


def _runs_by_cid(events: list[dict], base: str) -> dict:
    """``cid -> run block`` for one trace tail: the rep-0 envelope wall
    (the measured dispatch host wall the server attributed) plus the
    per-round walls via ``obs.metrics.round_stats`` — the attribution
    cell stream, never host callbacks."""
    from tpu_aggcomm.obs.metrics import round_stats
    out: dict = {}
    for run in (e for e in events if e.get("ev") == "run"
                and e.get("cid") is not None):
        rid = run["id"]
        wall = None
        for e in events:
            if e.get("ev") == "span" and e.get("run") == rid \
                    and e.get("rep") == 0 and e.get("bucket") == "total":
                wall = e["dur_s"]
                break
        rounds = [{"round": s["round"], "wall_s": s["wall"]}
                  for s in round_stats(events, rid)]
        out.setdefault(str(run["cid"]), {
            "trace": base, "run_id": rid, "method": run.get("method"),
            "wall_s": wall, "rounds": rounds,
            "rounds_total_s": sum(r["wall_s"] for r in rounds)})
    return out


def _instants_by_rid(events: list[dict]) -> dict:
    """``rid -> serve.request instant args`` — the trace-side stand-in
    for a torn serve-journal record (the instant carries rid, phases,
    cache AND cid)."""
    out: dict = {}
    for e in events:
        if e.get("ev") != "instant" or e.get("name") != "serve.request":
            continue
        args = e.get("args") or {}
        if args.get("rid") is not None:
            out.setdefault(args["rid"], args)
    return out


# ---------------------------------------------------------------------------
# The decomposition (ONE expression per derived number — validate_flow
# re-runs these exact functions over a committed artifact's rows).

def _server_wall(phases: dict) -> float | None:
    """The workload profiler's canonical wall arithmetic, verbatim."""
    vals = [phases[b] for b in BOUNDARIES if b in phases]
    return sum(vals) if vals else None


def dominant_component(components: dict) -> str | None:
    """Arg-max component in canonical order (strict — an earlier
    component keeps a tie, so two spellings can never alias)."""
    best = None
    for k in COMPONENT_ORDER:
        v = components.get(k)
        if not isinstance(v, (int, float)):
            continue
        if best is None or v > components[best]:
            best = k
    return best


def decompose_request(client: dict, server: dict,
                      run: dict | None) -> dict:
    """One request's end-to-end decomposition from its three joined
    stream records. Pure and blob-representable: the validator re-runs
    this function over the artifact's own (client, server, run) fields
    and demands float-exact agreement with the stored row."""
    t_send, t_recv = client.get("t_send"), client.get("t_recv")
    client_wall = (t_recv - t_send
                   if isinstance(t_send, (int, float))
                   and isinstance(t_recv, (int, float)) else None)
    phases, problems = attribute_phases(server.get("phases"))
    server_wall = _server_wall(phases)
    wire = (client_wall - server_wall
            if client_wall is not None and server_wall is not None
            else None)

    components: dict = {}
    if wire is not None:
        components["wire"] = wire
    for b in ("queue", "batch", "cache", "respond"):
        if b in phases:
            components[b] = phases[b]
    run_wall = run.get("wall_s") if run else None
    residual = None
    if isinstance(run_wall, (int, float)):
        components["round"] = run_wall
        if "dispatch" in phases:
            residual = phases["dispatch"] - run_wall
            components["overhead"] = residual
    elif "dispatch" in phases:
        # no joined run (untraced dispatch): the whole dispatch phase
        # is the round component — the overhead inside it is NOT
        # quantifiable and stays un-split, never silently zeroed
        components["round"] = phases["dispatch"]

    fractions = ({k: v / client_wall for k, v in components.items()}
                 if isinstance(client_wall, (int, float))
                 and client_wall > 0 else {})
    dominant = dominant_component(components)
    if isinstance(wire, (int, float)) and wire < 0:
        problems.append(
            f"client wall {client_wall!r} is smaller than the server "
            f"phase sum {server_wall!r} (wire_s {wire!r} < 0) — the "
            f"two streams disagree about this request")
    if isinstance(residual, (int, float)) and residual < 0:
        problems.append(
            f"journal dispatch phase {phases.get('dispatch')!r} is "
            f"smaller than the joined run wall {run_wall!r} "
            f"(residual_s {residual!r} < 0) — the streams disagree")
    return {
        "t_send": t_send, "t_recv": t_recv,
        "client_wall_s": client_wall,
        "phases": phases, "server_wall_s": server_wall,
        "wire_s": wire,
        "residual_s": residual,
        "components": components,
        "fractions": fractions,
        "dominant": dominant,
        "verdict": VERDICTS[dominant] if dominant is not None else None,
        "problems": problems,
    }


def _boot_ci(vals: list, *, seed: int, n_boot: int = N_BOOT,
             alpha: float = 0.05) -> list | None:
    """Seeded percentile-bootstrap CI on the mean (the regression-gate
    seed discipline: same samples + same seed ⟹ same interval)."""
    if len(vals) < 2:
        return None
    rng = random.Random(int(seed))
    n = len(vals)
    means = sorted(sum(vals[rng.randrange(n)] for _ in range(n)) / n
                   for _ in range(n_boot))
    lo = means[int(n_boot * alpha / 2)]
    hi = means[min(n_boot - 1, int(n_boot * (1 - alpha / 2)))]
    return [lo, hi]


def warm_overhead_block(rows: list[dict], *, seed: int) -> dict | None:
    """The warm overhead ledger over completed cache-hit requests:
    per-request ``1 - round/client`` fractions (row order), their mean,
    and the seeded-bootstrap CI. None when no warm request decomposed.
    THE one arithmetic — ``validate_flow`` and the trend series both
    re-derive through this function."""
    rids, fracs = [], []
    for r in rows:
        if r.get("status") != "done" or r.get("cache") != "hit":
            continue
        w = r.get("client_wall_s")
        rnd = (r.get("components") or {}).get("round")
        if not isinstance(w, (int, float)) or w <= 0 \
                or not isinstance(rnd, (int, float)):
            continue
        rids.append(r["rid"])
        fracs.append((w - rnd) / w)
    if not fracs:
        return None
    return {"n": len(fracs), "rids": rids, "fractions": fracs,
            "mean": sum(fracs) / len(fracs),
            "ci95": _boot_ci(fracs, seed=seed),
            "seed": int(seed)}


def warm_components_block(rows: list[dict]) -> dict:
    """Mean component fraction of the client wall over warm completed
    requests, per component in canonical order — the numbers behind
    "where do the warm milliseconds go" (report pane + /metrics
    gauges)."""
    out: dict = {}
    for comp in COMPONENT_ORDER:
        vals = [r["fractions"][comp] for r in rows
                if r.get("status") == "done" and r.get("cache") == "hit"
                and isinstance((r.get("fractions") or {}).get(comp),
                               (int, float))]
        if vals:
            out[comp] = {"n": len(vals),
                         "mean_fraction": sum(vals) / len(vals)}
    return out


# ---------------------------------------------------------------------------
# The joiner.

def flow_streams(client_path: str, serve_path: str, trace_paths=(), *,
                 seed: int = 0) -> dict:
    """The whole flow pass: tail the three streams, join, decompose.

    Returns the flow-v1 body minus the artifact envelope (schema/
    manifest/created_unix, added by :func:`write_flow`). Deterministic
    by construction: a pure function of (streams, seed) — the replay
    gate depends on it."""
    trace_paths = list(trace_paths)
    client = tail_client(client_path)
    jtail = tail_journal(serve_path)

    # serve-journal side: terminal record per rid (the workload join)
    terminal: dict = {}
    for rec in jtail["records"]:
        rid = (rec.get("key") or {}).get("request")
        if rid is None:
            continue
        if rec.get("status") in ("done", "fail", "shed"):
            terminal.setdefault(rid, rec)

    trace_skipped = 0
    runs_by_cid: dict = {}
    instants: dict = {}
    for path in trace_paths:
        events, skipped = _tail_trace(path)
        trace_skipped += skipped
        base = os.path.basename(path)
        for cid, info in _runs_by_cid(events, base).items():
            runs_by_cid.setdefault(cid, info)
        for rid, args in _instants_by_rid(events).items():
            instants.setdefault(rid, args)

    rows: list[dict] = []
    problems: list[str] = []
    client_only: list = []
    joined_rids: set = set()
    lost = [i for i in sorted(client["sends"])
            if i not in client["recvs"]]
    for i in lost:
        problems.append(
            f"client request i={i} (shape "
            f"{client['sends'][i].get('shape')!r}) has a send stamp but "
            f"no recv — LOST in flight (torn client journal or a "
            f"response that never came)")

    for i in sorted(client["recvs"]):
        crec = client["recvs"][i]
        rid = crec.get("rid")
        server = terminal.get(rid)
        source = "journal"
        if server is None and rid in instants:
            # the torn-journal fallback: the serve.request instant
            # carries the same phases/cache/cid payload
            a = instants[rid]
            server = {"status": "done" if a.get("ok") else "fail",
                      "cache": a.get("cache"), "cid": a.get("cid"),
                      "phases": a.get("phases")}
            source = "trace"
        if rid is None or server is None:
            client_only.append({"i": i, "rid": rid,
                                "shed": crec.get("shed"),
                                "error": crec.get("error")})
            continue
        joined_rids.add(rid)
        cid = server.get("cid")
        run = runs_by_cid.get(cid) if cid is not None else None
        dec = decompose_request(crec, server, run)
        for p in dec.pop("problems"):
            problems.append(f"request rid={rid}: {p}")
        row = {"i": i, "rid": rid, "status": server.get("status"),
               "cache": server.get("cache"), "cid": cid,
               "server_source": source, "run": run, **dec}
        # the stored client wall must equal the stream's own recorded
        # one (the loadgen computed the identical expression)
        if isinstance(crec.get("client_wall_s"), (int, float)) \
                and crec["client_wall_s"] != row["client_wall_s"]:
            problems.append(
                f"request rid={rid}: recorded client_wall_s "
                f"{crec['client_wall_s']!r} != t_recv - t_send "
                f"{row['client_wall_s']!r} — the client journal "
                f"disagrees with itself")
        rows.append(row)

    server_only = sorted(set(terminal) - joined_rids)
    verdicts: dict = {}
    for r in rows:
        if r["verdict"] is not None:
            verdicts[r["verdict"]] = verdicts.get(r["verdict"], 0) + 1

    return {
        "seed": int(seed),
        "client_journal": os.path.basename(client_path),
        "serve_journal": os.path.basename(serve_path),
        "traces": [os.path.basename(p) for p in trace_paths],
        "requests": {"client": len(client["recvs"]),
                     "joined": len(rows),
                     "client_only": client_only,
                     "server_only": server_only,
                     "lost": lost},
        "per_request": rows,
        "verdicts": verdicts,
        "warm_overhead": warm_overhead_block(rows, seed=seed),
        "warm_components": warm_components_block(rows),
        "integrity": {"client_torn_lines": client["skipped_lines"],
                      "journal_torn_lines": jtail["skipped_lines"],
                      "trace_torn_lines": trace_skipped},
        "problems": problems,
    }


# ---------------------------------------------------------------------------
# Artifact I/O (the obs/workload.py replay discipline).

def write_flow(path: str, body: dict) -> dict:
    """Write one flow-v1 artifact atomically (manifest records env var
    NAMES only, the ledger discipline) and return the blob."""
    from tpu_aggcomm.obs import ledger
    blob = dict(body)
    blob["schema"] = FLOW_SCHEMA
    blob["manifest"] = ledger.manifest()
    blob["created_unix"] = time.time()
    with atomic_write(path) as fh:
        json.dump(blob, fh, indent=1)
        fh.write("\n")
    return blob


#: Envelope keys excluded from the replay comparison (environment-
#: dependent by design; everything else must re-derive byte-for-byte).
_ENVELOPE = ("schema", "manifest", "created_unix")


def replay_flow(path: str) -> dict:
    """Re-derive a committed FLOW_r*.json from the stream basenames it
    records (resolved next to the artifact) + its seed, and
    byte-compare minus the envelope. ``{"verdict": "REPRODUCED" |
    "MISMATCH", "problems": [...]}`` with every diverging top-level key
    named."""
    with open(path) as fh:
        blob = json.load(fh)
    problems: list[str] = []
    if blob.get("schema") != FLOW_SCHEMA:
        return {"verdict": "MISMATCH",
                "problems": [f"schema {blob.get('schema')!r} != "
                             f"{FLOW_SCHEMA!r}"]}
    root = os.path.dirname(os.path.abspath(path))

    def _resolve(name, what):
        if name is None:
            problems.append(f"artifact records no {what}")
            return None
        p = name if os.path.isabs(name) else os.path.join(root, name)
        if not os.path.exists(p):
            problems.append(f"recorded {what} {name!r} not found next "
                            f"to the artifact ({root})")
        return p

    cpath = _resolve(blob.get("client_journal"), "client journal")
    spath = _resolve(blob.get("serve_journal"), "serve journal")
    traces = [_resolve(n, "trace") for n in blob.get("traces") or []]
    if problems:
        return {"verdict": "MISMATCH", "problems": problems}
    rederived = flow_streams(cpath, spath, traces,
                             seed=blob.get("seed", 0))
    want = {k: v for k, v in blob.items() if k not in _ENVELOPE}
    for k in sorted(set(want) | set(rederived)):
        a = json.dumps(want.get(k), sort_keys=True)
        b = json.dumps(rederived.get(k), sort_keys=True)
        if a != b:
            problems.append(f"key {k!r} does not re-derive from the "
                            f"recorded streams (artifact {a[:120]}... "
                            f"vs re-derived {b[:120]}...)"
                            if max(len(a), len(b)) > 120 else
                            f"key {k!r}: artifact {a} vs re-derived {b}")
    return {"verdict": "REPRODUCED" if not problems else "MISMATCH",
            "problems": problems}


# ---------------------------------------------------------------------------
# /metrics gauges (the watch_registry fold pattern: artifact numbers
# VERBATIM — telemetry_gate.py re-parses the render and demands
# float-exact agreement).

def flow_registry(blob: dict, registry) -> None:
    """Fold one flow-v1 blob into a MetricsRegistry: the warm overhead
    fraction, per-component warm mean fractions, and the per-verdict
    request counts."""
    wo = blob.get("warm_overhead")
    if wo is not None:
        registry.gauge("tpu_aggcomm_flow_warm_overhead_fraction",
                       wo["mean"])
    for comp, st in (blob.get("warm_components") or {}).items():
        registry.gauge("tpu_aggcomm_flow_warm_component_fraction",
                       st["mean_fraction"], component=comp)
    for verdict, n in (blob.get("verdicts") or {}).items():
        registry.gauge("tpu_aggcomm_flow_requests", float(n),
                       verdict=verdict)


# ---------------------------------------------------------------------------
# Rendering.

def _ms(v) -> str:
    return f"{v * 1e3:9.3f} ms" if isinstance(v, (int, float)) \
        else "      -  "


def render_flow(body: dict) -> str:
    """The ``inspect flow`` text view."""
    r = body["requests"]
    lines = [f"flow trace over {body['client_journal']} + "
             f"{body['serve_journal']}"
             + (f" + {', '.join(body['traces'])}" if body["traces"]
                else "") + f" (seed {body['seed']})",
             f"  requests: {r['client']} client recvs — {r['joined']} "
             f"joined end-to-end, {len(r['client_only'])} client-only, "
             f"{len(r['server_only'])} server-only"
             + (f", LOST in flight: {r['lost']}" if r["lost"] else "")]
    if body["verdicts"]:
        order = sorted(body["verdicts"],
                       key=lambda v: (-body["verdicts"][v], v))
        lines.append("  verdicts: " + ", ".join(
            f"{v} x{body['verdicts'][v]}" for v in order))
    wo = body.get("warm_overhead")
    if wo is not None:
        ci = wo.get("ci95")
        citxt = (f" (seeded 95% CI [{ci[0]:.3f}, {ci[1]:.3f}])"
                 if ci else "")
        lines.append(
            f"  warm overhead ledger: {wo['mean']:.1%} of the warm "
            f"client wall is NOT device rounds over {wo['n']} "
            f"cache-hit request(s){citxt}")
    wc = body.get("warm_components") or {}
    if wc:
        lines.append("  where the warm client wall goes (mean fraction "
                     "per component):")
        for comp in COMPONENT_ORDER:
            st = wc.get(comp)
            if st is None:
                continue
            lines.append(f"    {comp:>9}: {st['mean_fraction']:7.1%}  "
                         f"(n={st['n']}, {VERDICTS[comp]})")
    shown = 0
    for row in body["per_request"]:
        if shown >= 8:
            lines.append(
                f"  ... {len(body['per_request']) - shown} more request(s)")
            break
        shown += 1
        comp = row["components"]
        parts = "  ".join(f"{k} {_ms(comp[k]).strip()}"
                          for k in COMPONENT_ORDER if k in comp)
        run = row.get("run")
        lines.append(
            f"  rid {row['rid']} [{row['status']}/{row['cache']}"
            f"{'/' + str(row['cid']) if row['cid'] else ''}]: client "
            f"{_ms(row['client_wall_s']).strip()} -> {row['verdict']}")
        lines.append(f"      {parts}")
        if run is not None and run.get("rounds"):
            rr = ", ".join(f"r{x['round']} {_ms(x['wall_s']).strip()}"
                           for x in run["rounds"][:6])
            lines.append(f"      rounds ({run['trace']}#run"
                         f"{run['run_id']}): {rr}")
    integ = body["integrity"]
    if integ["client_torn_lines"] or integ["journal_torn_lines"] \
            or integ["trace_torn_lines"]:
        lines.append(
            f"  integrity: skipped {integ['client_torn_lines']} torn "
            f"client line(s), {integ['journal_torn_lines']} torn "
            f"journal line(s), {integ['trace_torn_lines']} torn trace "
            f"line(s) — counted, not silently absorbed")
    for p in body["problems"]:
        lines.append(f"  PROBLEM: {p}")
    return "\n".join(lines) + "\n"
