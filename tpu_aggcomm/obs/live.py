"""Attach to a running sweep from another terminal (``inspect live``).

A long sweep already leaves two crash-safe breadcrumb streams behind as
it runs: the resilience run journal (``<results_csv>.journal.jsonl`` —
one fsync'd line per completed cell, with its wall seconds) and, when
``--trace`` is on, the flight-recorder JSONL. This module tails BOTH
from a second process and renders a progress board: which (fault, comm)
cells are done/failed/remaining, what the running process is currently
inside (the last trace event), and a per-cell ETA built the same way
the watchdog builds its soft deadlines — prior observed walls through
:func:`resilience.watchdog.derive_deadline` (``floor_s=None``: the
roofline floor path imports the jax lowerings, and this module must
work precisely when the tunnel is busy or wedged and ``import jax``
would hang).

Read-only and torn-line tolerant throughout: the journal reader skips
unparseable lines by contract (journal.py), and :func:`tail_events`
does the same for trace JSONL — the writer may be mid-append at any
moment (``trace.load_events`` raises on torn lines by design; a live
tail cannot). NEVER imports jax.
"""

from __future__ import annotations

import json
import os
import time

__all__ = ["tail_events", "tail_events_counted", "sweep_status",
           "render_live", "attach", "THETA_COMM_SIZES"]

#: The default sweep grid (cli.THETA_COMM_SIZES restated here so the
#: monitor stays importable without the CLI module).
THETA_COMM_SIZES = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048,
                    4096, 8192, 999_999_999)


def tail_events_counted(path: str) -> tuple[list[dict], int]:
    """Best-effort read of a trace JSONL that may be mid-append,
    COUNTING what it skips.

    Unlike ``trace.load_events`` (which raises: a COMMITTED artifact
    with a torn line is corrupt), a live tail skips what does not parse
    — the torn final line is the normal case, not an error. But a
    monitor must still SAY how many lines it could not read (the
    recover/workload ``lost`` discipline): silently absorbed torn lines
    hide lost work."""
    events: list[dict] = []
    skipped = 0
    try:
        fh = open(path)
    except OSError:
        return events, 0
    with fh:
        for line in fh:
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if isinstance(rec, dict) and "ev" in rec:
                events.append(rec)
            else:
                skipped += 1
    return events, skipped


def tail_events(path: str) -> list[dict]:
    """:func:`tail_events_counted` without the count (compat shim for
    callers that only want the events)."""
    return tail_events_counted(path)[0]


def _cell_id(key: dict) -> tuple:
    """(fault, comm) — the axes a sweep varies; everything else in the
    journal key is the fixed config."""
    return (key.get("fault"), key.get("comm"))


def sweep_status(results_csv: str, *, comm_sizes=None,
                 trace_paths=()) -> dict:
    """One snapshot of a (possibly running) sweep, from its journal.

    Returns ``{"journal", "cells": [...], "remaining": [...], "eta":
    {...}, "activity": {...}}``. ``cells`` is one row per journal entry
    (latest per (fault, comm) wins): ``{"fault", "comm", "status",
    "wall_s"}``. ``remaining`` is the planned grid minus done cells —
    the grid is ``comm_sizes`` when given, else the Theta default, per
    fault axis already seen in the journal (an attacher who passed a
    custom ``--comm-sizes`` to the sweep passes the same list here).
    ``eta`` carries the watchdog-model estimate: per-cell point
    estimate (median prior wall), soft budget
    (:func:`derive_deadline` over the prior walls plus the cost
    model's jax-free per-rep floor when a committed PREDICT_*.json and
    a traffic-bearing trace tail exist — ``model_floor_s``), and the
    total for what remains. ``activity`` is the tail of the newest
    trace stream, if any."""
    from tpu_aggcomm.resilience.journal import RunJournal
    from tpu_aggcomm.resilience.watchdog import derive_deadline

    journal_path = results_csv + ".journal.jsonl"
    # torn-line + lost-request accounting (the recover/workload `lost`
    # discipline surfaced live): RunJournal skips unreadable lines by
    # contract, so the count comes from the watchtower's counting tail
    # over the SAME file; request-shaped entries (a serve journal
    # pointed at `inspect live`) that were admitted but never reached a
    # terminal status are named, not dropped
    from tpu_aggcomm.obs.watch import tail_journal
    tail = tail_journal(journal_path)
    req_admitted: set = set()
    req_terminal: set = set()
    for rec in tail["records"]:
        rid = (rec.get("key") or {}).get("request")
        if rid is None:
            continue
        if rec.get("status") == "admitted":
            req_admitted.add(rid)
        elif rec.get("status") in ("done", "fail", "shed"):
            req_terminal.add(rid)
    integrity = {"journal_torn_lines": tail["skipped_lines"],
                 "trace_torn_lines": 0,
                 "lost_requests": sorted(req_admitted - req_terminal)}

    latest: dict[tuple, dict] = {}
    for rec in RunJournal(journal_path).entries():
        key = rec.get("key") or {}
        if {"request", "state", "drain"} & key.keys():
            continue  # serve-journal records are not sweep cells
        latest[_cell_id(key)] = {
            "fault": key.get("fault"), "comm": key.get("comm"),
            "status": rec.get("status"), "wall_s": rec.get("wall_s")}
    cells = [latest[k] for k in sorted(
        latest, key=lambda k: (str(k[0] or ""), k[1] or 0))]

    grid = [int(c) for c in comm_sizes] if comm_sizes \
        else list(THETA_COMM_SIZES)
    faults = sorted({c["fault"] for c in cells}, key=lambda f: str(f or "")) \
        or [None]
    done = {(c["fault"], c["comm"]) for c in cells
            if c["status"] == "done"}
    remaining = [{"fault": f, "comm": c}
                 for f in faults for c in grid if (f, c) not in done]

    activity = None
    act_events: list = []
    newest = None
    for p in trace_paths:
        integrity["trace_torn_lines"] += tail_events_counted(p)[1]
        try:
            mt = os.path.getmtime(p)
        except OSError:
            continue
        if newest is None or mt > newest[0]:
            newest = (mt, p)
    if newest is not None:
        act_events = tail_events(newest[1])
        if act_events:
            last = act_events[-1]
            run = next((e for e in reversed(act_events)
                        if e.get("ev") == "run"), None)
            activity = {
                "trace": newest[1], "events": len(act_events),
                "age_s": max(0.0, time.time() - newest[0]),
                "last_ev": last.get("ev"),
                "last_name": last.get("name"),
                "run": (run or {}).get("name"),
                "backend": (run or {}).get("backend")}

    # the analytic cost model's floor (tpu_aggcomm/model/, jax-free by
    # the same contract as this module — it must import with a wedged
    # tunnel): armed only when a committed PREDICT_*.json AND a trace
    # tail with a round_traffic run record exist; with neither, the
    # walls-only deadline model below keeps working unchanged
    floor_s, floor_ntimes = None, 1
    if act_events:
        from tpu_aggcomm.model.artifact import newest_artifact
        from tpu_aggcomm.model.predict import floor_from_trace_events
        root = os.path.dirname(os.path.abspath(results_csv))
        art = newest_artifact(root)
        if art is None and os.path.abspath(root) != os.path.abspath("."):
            art = newest_artifact(".")
        if art is not None:
            floor_s, floor_ntimes = floor_from_trace_events(
                act_events, art.get("platforms") or {})

    walls = [c["wall_s"] for c in cells
             if c["status"] == "done"
             and isinstance(c.get("wall_s"), (int, float))]
    eta = {"per_cell_s": None, "soft_budget_s": None, "total_s": None,
           "model_floor_s": floor_s, "basis": len(walls)}
    if walls or floor_s is not None:
        # the watchdog's deadline model: prior walls, plus the cost
        # model's per-rep floor when one is derivable — this is how a
        # first cell (no prior walls) gets a budget at all
        eta["soft_budget_s"] = derive_deadline(
            floor_s=floor_s, ntimes=floor_ntimes, prior_walls=walls)
    if walls:
        ordered = sorted(walls)
        mid = len(ordered) // 2
        per_cell = (ordered[mid] if len(ordered) % 2
                    else 0.5 * (ordered[mid - 1] + ordered[mid]))
        eta["per_cell_s"] = per_cell
        eta["total_s"] = per_cell * len(remaining)
    return {"journal": journal_path, "cells": cells,
            "remaining": remaining, "eta": eta, "activity": activity,
            "integrity": integrity}


def _fmt_s(s) -> str:
    if s is None:
        return "?"
    if s >= 3600:
        return f"{s / 3600:.1f}h"
    if s >= 60:
        return f"{s / 60:.1f}m"
    return f"{s:.1f}s"


def render_live(status: dict) -> str:
    """The progress board as text (one ``inspect live`` frame)."""
    lines = [f"sweep journal: {status['journal']}"]
    cells = status["cells"]
    if not cells:
        lines.append("  (no journal entries yet — sweep not started, or "
                     "started without --results-csv)")
    for c in cells:
        tag = f" [fault {c['fault']}]" if c["fault"] else ""
        wall = f" ({_fmt_s(c['wall_s'])})" \
            if isinstance(c.get("wall_s"), (int, float)) else ""
        lines.append(f"  {c['status']:>4s}  comm {c['comm']}{wall}{tag}")
    rem = status["remaining"]
    eta = status["eta"]
    lines.append(f"remaining: {len(rem)} cell(s)"
                 + (f" — next comm {rem[0]['comm']}"
                    + (f" [fault {rem[0]['fault']}]"
                       if rem[0]["fault"] else "")
                    if rem else ""))
    floor = eta.get("model_floor_s")
    floor_txt = (f"; cost-model floor {floor * 1e6:.1f}us/rep"
                 if floor is not None else "")
    if eta["per_cell_s"] is not None:
        lines.append(
            f"eta: ~{_fmt_s(eta['per_cell_s'])}/cell (median of "
            f"{eta['basis']} prior wall(s)) -> ~{_fmt_s(eta['total_s'])} "
            f"total; watchdog soft budget "
            f"{_fmt_s(eta['soft_budget_s'])}/cell{floor_txt}")
    elif eta["soft_budget_s"] is not None:
        lines.append(
            f"eta: no completed cells yet; watchdog soft budget "
            f"{_fmt_s(eta['soft_budget_s'])}/cell from the cost-model "
            f"floor{floor_txt}")
    else:
        lines.append("eta: no completed cells yet (no prior walls or "
                     "cost-model floor to model from)")
    act = status["activity"]
    if act is not None:
        lines.append(
            f"activity: {act['trace']} — {act['events']} events, last "
            f"{act['last_ev']}"
            + (f" {act['last_name']}" if act.get("last_name") else "")
            + (f", run {act['run']} ({act['backend']})"
               if act.get("run") else "")
            + f", file age {_fmt_s(act['age_s'])}")
    integ = status.get("integrity") or {}
    if integ.get("journal_torn_lines") or integ.get("trace_torn_lines"):
        lines.append(
            f"integrity: skipped {integ.get('journal_torn_lines', 0)} "
            f"torn journal line(s), {integ.get('trace_torn_lines', 0)} "
            f"torn trace line(s) — a writer may be mid-append; counted, "
            f"never silently absorbed")
    if integ.get("lost_requests"):
        lines.append(
            f"integrity: {len(integ['lost_requests'])} request(s) "
            f"admitted but never terminal (LOST in flight): "
            f"{integ['lost_requests']}")
    return "\n".join(lines)


def attach(results_csv: str, *, comm_sizes=None, trace_paths=(),
           follow: bool = False, interval: float = 2.0,
           out=None) -> int:
    """Print the progress board; with ``follow``, keep reprinting every
    ``interval`` seconds until the grid is complete (or Ctrl-C).

    Exit code 0 when every planned cell is done, 1 while work remains
    (so a one-shot call doubles as a scriptable "is it finished?")."""
    import sys
    stream = out if out is not None else sys.stdout
    while True:
        status = sweep_status(results_csv, comm_sizes=comm_sizes,
                              trace_paths=trace_paths)
        print(render_live(status), file=stream, flush=True)
        if not status["remaining"]:
            return 0
        if not follow:
            return 1
        print("--", file=stream, flush=True)
        try:
            time.sleep(max(float(interval), 0.2))
        except KeyboardInterrupt:
            return 1
