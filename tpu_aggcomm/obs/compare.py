"""Trace diffing: per-cell deltas between two flight-recorder logs, or
between two sweep grids of per-cell logs — jax-free.

``cli inspect compare A.trace.jsonl B.trace.jsonl [--by rank|round|phase]``
pairs the two logs' runs in recording order, refuses to compare runs of
different methods or shapes (a delta between different programs is not a
delta), and reports:

- the max-over-ranks total of each side and its relative delta — the
  headline the reference's MAX-reduce studies;
- the dominant (rank, round) delta cell — WHERE the change happened,
  with the run's PHASE_SOURCES provenance carried through;
- the bytes-weighted round delta: each round's wall delta weighted by
  the payload bytes that round moves (the static ``round_bytes``
  accounting the recorder stores per run, obs/traffic.py universe) —
  rounds that move the traffic dominate the verdict;
- a per-key table (key = rank, round, or phase) with per-cell deltas
  and a sign test over repeated trials: per-dispatch runs record one
  slice set per rep, so paired per-rep deltas exist and the sign test
  says whether a cell moved consistently or just jittered. Chained
  runs combine reps into one recorded set (no pairs — ``p`` is None),
  but when both traces carry ``chained.samples`` instants (the
  differenced per-trial evidence harness/chained.py records) the
  whole-rep delta additionally gets a bootstrap CI.

Directory mode: when both arguments are directories, ``*.trace.jsonl``
files are matched by basename (a sweep grid's per-cell artifacts —
scripts/tpu_sweeps.py writes ``traces/sweep_n*_m*_c*.trace.jsonl``) and
each common cell is diffed; unmatched cells are listed, not ignored.
"""

from __future__ import annotations

import glob
import os

from tpu_aggcomm.obs.metrics import (bootstrap_delta_ci, bucket_cells,
                                     sign_test)
from tpu_aggcomm.obs.trace import aggregate_run, load_events, round_key

__all__ = ["TraceCompareError", "compare_traces", "compare_paths",
           "render_compare", "save_compare", "BY_CHOICES",
           "COMPARE_SCHEMA"]

BY_CHOICES = ("rank", "round", "phase")

#: Schema tag of the machine-readable ``inspect compare --json`` export.
COMPARE_SCHEMA = "compare-v1"


class TraceCompareError(ValueError):
    """The two traces are not comparable (different methods/shapes)."""


def _runs(events):
    return [e for e in events if e["ev"] == "run"]


def _check_pairable(ra: dict, rb: dict, k: int,
                    across_faults: bool = False) -> None:
    """Refuse clearly when run k of the two traces ran different
    programs — method first (the acceptance case), then shape, then
    fault spec (unless ``across_faults`` deliberately crosses them)."""
    if (ra["method"], ra["name"]) != (rb["method"], rb["name"]):
        raise TraceCompareError(
            f"cannot compare traces of different methods: run {k} is "
            f"m={ra['method']} \"{ra['name']}\" in A but "
            f"m={rb['method']} \"{rb['name']}\" in B — diff runs of the "
            f"SAME method (re-run one side, or compare per-cell sweep "
            f"artifacts of matching cells)")
    for field in ("nprocs", "data_size", "ntimes"):
        if ra[field] != rb[field]:
            raise TraceCompareError(
                f"cannot compare run {k} (m={ra['method']} "
                f"\"{ra['name']}\"): {field} differs "
                f"({ra[field]} in A vs {rb[field]} in B)")
    fa, fb = ra.get("fault") or None, rb.get("fault") or None
    if fa != fb and not across_faults:
        raise TraceCompareError(
            f"cannot compare run {k} (m={ra['method']} "
            f"\"{ra['name']}\"): fault specs differ "
            f"(A {fa or 'healthy'} vs B {fb or 'healthy'}) — a delta "
            f"across fault scenarios is a RECOVERY delta, not a "
            f"regression; pass --across-faults to compare them "
            f"deliberately")


def _chained_samples(events) -> list[float] | None:
    """The LAST ``chained.samples`` instant's per-trial seconds, if the
    trace carries differencing evidence (harness/chained.py)."""
    out = None
    for e in events:
        if e["ev"] == "instant" and e["name"] == "chained.samples":
            s = e.get("args", {}).get("samples")
            if isinstance(s, list) and len(s) >= 2:
                out = [float(x) for x in s]
    return out


def _group(cells: dict[tuple, float], by: str) -> dict:
    """Collapse a {(rank, round, bucket): s} rep onto the grouping key."""
    sel = {"rank": 0, "round": 1, "phase": 2}[by]
    out: dict = {}
    for key, secs in cells.items():
        out[key[sel]] = out.get(key[sel], 0.0) + secs
    return out


def _mean_by_key(per_rep: dict[int, dict], keyfn) -> dict:
    acc: dict = {}
    for cells in per_rep.values():
        rep_acc: dict = {}
        for key, secs in cells.items():
            k = keyfn(key)
            rep_acc[k] = rep_acc.get(k, 0.0) + secs
        for k, secs in rep_acc.items():
            acc.setdefault(k, []).append(secs)
    return {k: sum(v) / len(v) for k, v in acc.items()}


def _key_sort(by: str):
    if by == "round":
        return round_key
    if by == "phase":
        return str
    return lambda k: k          # rank: ints


def compare_traces(events_a: list[dict], events_b: list[dict],
                   by: str = "rank", across_faults: bool = False) -> dict:
    """Diff two event logs run-by-run. Raises :class:`TraceCompareError`
    on mismatched runs; see module docstring for the result layout.
    ``across_faults`` allows pairing runs whose fault specs differ — the
    delta is then a RECOVERY delta (faulted+repaired vs healthy) and the
    result names both specs."""
    if by not in BY_CHOICES:
        raise ValueError(f"by must be one of {BY_CHOICES}")
    runs_a, runs_b = _runs(events_a), _runs(events_b)
    if len(runs_a) != len(runs_b):
        raise TraceCompareError(
            f"trace A has {len(runs_a)} runs but B has {len(runs_b)} — "
            f"only same-shaped recordings diff cell-by-cell")
    if not runs_a:
        raise TraceCompareError("no runs recorded in either trace")
    samples_a = _chained_samples(events_a)
    samples_b = _chained_samples(events_b)
    out = {"by": by, "runs": []}
    for k, (ra, rb) in enumerate(zip(runs_a, runs_b)):
        _check_pairable(ra, rb, k, across_faults)
        pa = bucket_cells(events_a, ra["id"])
        pb = bucket_cells(events_b, rb["id"])
        agg_a = aggregate_run(events_a, ra["id"])
        agg_b = aggregate_run(events_b, rb["id"])
        total_a = max((c["total"] for c in agg_a.values()), default=0.0)
        total_b = max((c["total"] for c in agg_b.values()), default=0.0)

        # dominant (rank, round) delta — computed on the full grid
        # regardless of --by, so compare always names WHERE
        ga = _mean_by_key(pa, lambda c: (c[0], c[1]))
        gb = _mean_by_key(pb, lambda c: (c[0], c[1]))
        deltas = {key: gb.get(key, 0.0) - ga.get(key, 0.0)
                  for key in set(ga) | set(gb)}
        dominant = None
        if deltas:
            dkey = max(deltas, key=lambda key: abs(deltas[key]))
            # share denominator: the per-rep max-over-ranks wall delta
            # from the SAME mean-across-reps grid the cell came from (the
            # aggregate totals above are summed/scaled across reps, a
            # different unit)
            wall_a = _wall(ga)
            wall_b = _wall(gb)
            wall_delta = wall_b - wall_a
            dominant = {
                "rank": dkey[0], "round": dkey[1],
                "delta_s": deltas[dkey],
                "a_s": ga.get(dkey, 0.0), "b_s": gb.get(dkey, 0.0),
                "share_of_total_delta": (deltas[dkey] / wall_delta
                                         if wall_delta else None)}

        # bytes-weighted round delta: weight each round's wall delta by
        # the payload bytes that round moves (the run's static
        # round_bytes accounting, obs/traffic.py universe) — the
        # traffic-centric headline, computed on the full grid
        # regardless of --by. None when the trace predates round_bytes
        # or carries no per-round slices.
        rbytes = ra.get("round_bytes") or {}
        bytes_weighted = None
        if rbytes:
            wall_a_r: dict = {}
            wall_b_r: dict = {}
            for (_rank, rnd), secs in ga.items():
                wall_a_r[rnd] = max(wall_a_r.get(rnd, 0.0), secs)
            for (_rank, rnd), secs in gb.items():
                wall_b_r[rnd] = max(wall_b_r.get(rnd, 0.0), secs)
            num = den = 0.0
            for rnd, a_v in wall_a_r.items():
                byts = rbytes.get(str(rnd))
                b_v = wall_b_r.get(rnd)
                if not byts or not a_v or b_v is None:
                    continue
                num += byts * (b_v - a_v) / a_v
                den += byts
            if den:
                bytes_weighted = num / den * 100.0

        # per-key table with sign tests over paired per-rep deltas
        ka = _mean_by_key(pa, lambda c: _one(c, by))
        kb = _mean_by_key(pb, lambda c: _one(c, by))
        table = []
        for key in sorted(set(ka) | set(kb), key=_key_sort(by)):
            a_v, b_v = ka.get(key, 0.0), kb.get(key, 0.0)
            pairs = []
            for rep in sorted(set(pa) & set(pb)):
                av = _group(pa[rep], by).get(key, 0.0)
                bv = _group(pb[rep], by).get(key, 0.0)
                pairs.append(bv - av)
            table.append({
                "key": key, "a_s": a_v, "b_s": b_v, "delta_s": b_v - a_v,
                "delta_pct": ((b_v - a_v) / a_v * 100.0) if a_v else None,
                "sign": sign_test(pairs)})

        rec = {
            "method": ra["method"], "name": ra["name"],
            "nprocs": ra["nprocs"], "data_size": ra["data_size"],
            "phase_source_a": ra["phase_source"],
            "phase_source_b": rb["phase_source"],
            "fault_a": ra.get("fault") or None,
            "fault_b": rb.get("fault") or None,
            "total_a_s": total_a, "total_b_s": total_b,
            "total_delta_pct": ((total_b - total_a) / total_a * 100.0
                                if total_a else None),
            "dominant": dominant,
            "bytes_weighted_delta_pct": bytes_weighted, "table": table}
        if (len(runs_a) == 1 and samples_a and samples_b):
            lo, hi = bootstrap_delta_ci(samples_a, samples_b)
            rec["total_ci_pct"] = [lo * 100.0, hi * 100.0]
        out["runs"].append(rec)
    return out


def _one(cell: tuple, by: str):
    return cell[{"rank": 0, "round": 1, "phase": 2}[by]]


def _wall(grid: dict) -> float:
    """Max-over-ranks total of a {(rank, round): s} mean grid."""
    per_rank: dict = {}
    for (rank, _rnd), secs in grid.items():
        per_rank[rank] = per_rank.get(rank, 0.0) + secs
    return max(per_rank.values(), default=0.0)


def compare_paths(path_a: str, path_b: str, by: str = "rank",
                  across_faults: bool = False) -> dict:
    """Diff two trace files, or two directories of per-cell traces
    (matched by basename). Returns the compare result with source
    labels attached; directory mode returns
    ``{"grid": [...], "only_a": [...], "only_b": [...]}``."""
    if os.path.isdir(path_a) and os.path.isdir(path_b):
        names_a = {os.path.basename(p)
                   for p in glob.glob(os.path.join(path_a,
                                                   "*.trace.jsonl"))}
        names_b = {os.path.basename(p)
                   for p in glob.glob(os.path.join(path_b,
                                                   "*.trace.jsonl"))}
        common = sorted(names_a & names_b)
        if not common:
            raise TraceCompareError(
                f"no matching *.trace.jsonl basenames between "
                f"{path_a} and {path_b}")
        grid = []
        for name in common:
            res = compare_traces(
                load_events(os.path.join(path_a, name)),
                load_events(os.path.join(path_b, name)), by=by,
                across_faults=across_faults)
            res["a"], res["b"] = (os.path.join(path_a, name),
                                  os.path.join(path_b, name))
            res["cell"] = name
            grid.append(res)
        return {"by": by, "grid": grid,
                "only_a": sorted(names_a - names_b),
                "only_b": sorted(names_b - names_a)}
    res = compare_traces(load_events(path_a), load_events(path_b), by=by,
                         across_faults=across_faults)
    res["a"], res["b"] = path_a, path_b
    return res


def _fmt_round(rnd) -> str:
    from tpu_aggcomm.obs.trace import WHOLE_REP
    if rnd == WHOLE_REP:
        return "whole-rep"
    return f"round {rnd}" if isinstance(rnd, int) else str(rnd)


def _render_one(res: dict, by: str, lines: list) -> None:
    for rec in res["runs"]:
        lines.append(
            f"run: m={rec['method']} \"{rec['name']}\" "
            f"n={rec['nprocs']} d={rec['data_size']}")
        fa, fb = rec.get("fault_a"), rec.get("fault_b")
        if fa != fb:
            lines.append(
                f"  RECOVERY delta: A fault={fa or 'healthy'} vs "
                f"B fault={fb or 'healthy'} — the total delta below is "
                f"the cost of surviving the fault, not a regression")
        elif fa:
            lines.append(f"  fault: {fa} (both sides)")
        dp = rec["total_delta_pct"]
        lines.append(
            f"  max-over-ranks total: A {rec['total_a_s']:.6f} s  "
            f"B {rec['total_b_s']:.6f} s"
            + (f"  delta {dp:+.1f}%" if dp is not None else ""))
        bw = rec.get("bytes_weighted_delta_pct")
        if bw is not None:
            lines.append(
                f"  bytes-weighted round delta: {bw:+.1f}% "
                f"(each round's wall delta weighted by its payload "
                f"bytes)")
        if "total_ci_pct" in rec:
            lo, hi = rec["total_ci_pct"]
            lines.append(
                f"  bootstrap 95% CI on whole-rep delta "
                f"(chained trials): [{lo:+.1f}%, {hi:+.1f}%]")
        d = rec["dominant"]
        if d is not None:
            share = d["share_of_total_delta"]
            lines.append(
                f"  dominant delta cell: rank {d['rank']}, "
                f"{_fmt_round(d['round'])}: "
                f"{d['delta_s']:+.6f} s "
                f"({d['a_s']:.6f} -> {d['b_s']:.6f})"
                + (f", {share * 100:.0f}% of total delta"
                   if share is not None else "")
                + f"  [src: A {rec['phase_source_a']}, "
                  f"B {rec['phase_source_b']}]")
        lines.append(f"  by {by}:")
        for row in rec["table"]:
            key = (_fmt_round(row["key"]) if by == "round"
                   else f"rank {row['key']}" if by == "rank"
                   else row["key"])
            pct = (f"{row['delta_pct']:+.1f}%"
                   if row["delta_pct"] is not None else "   n/a")
            sg = row["sign"]
            sig = (f"  sign p={sg['p']:.3f} (n={sg['n']})"
                   if sg["p"] is not None else "")
            lines.append(
                f"    {key!s:>14}: A {row['a_s']:.6f}  "
                f"B {row['b_s']:.6f}  {pct}{sig}")


def save_compare(path: str, res: dict) -> str:
    """Write a :func:`compare_paths` result as a ``compare-v1`` JSON
    artifact (atomic_write; validated by ``obs.regress.validate_compare``
    and scripts/check_bench_schema.py). The payload is the result dict
    VERBATIM under ``"result"`` — the numbers ``render_compare`` prints
    and the export must never diverge."""
    import json
    import time

    from tpu_aggcomm.obs.atomic import atomic_write

    blob = {"schema": COMPARE_SCHEMA, "result": res,
            "created_unix": time.time()}
    with atomic_write(path) as fh:
        json.dump(blob, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path


def render_compare(res: dict) -> str:
    """Human-readable report of a :func:`compare_paths` result."""
    lines = []
    if "grid" in res:
        lines.append(f"sweep-grid compare ({len(res['grid'])} matched "
                     f"cells, by {res['by']}):")
        for cell in res["grid"]:
            lines.append(f"-- cell {cell['cell']} --")
            _render_one(cell, res["by"], lines)
        for side, names in (("A", res["only_a"]), ("B", res["only_b"])):
            if names:
                lines.append(f"only in {side}: {', '.join(names)}")
    else:
        lines.append(f"compare: {res['a']} vs {res['b']}")
        _render_one(res, res["by"], lines)
    return "\n".join(lines) + "\n"
