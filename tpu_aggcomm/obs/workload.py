"""Workload profiler: see the traffic the serve layer actually serves.

The serve journal (resilience/journal.py, written by serve/server.py)
records every request's life as phase-boundary timestamps — admit →
queue → batch → cache → dispatch → respond, all ``time.monotonic()``
stamps relative to the request's admission — plus the queue depth at
admission and the batch membership/padded-slot counts. This module is
the read side: a **jax-free**, torn-line-tolerant profiler that
re-derives from those records alone

- per-request **phase attribution** — durations between consecutive
  recorded boundaries, float-exact against the journal stamps: a
  request's ``wall_s`` is DEFINED as the sum of its phase durations in
  canonical boundary order, and ``validate_workload`` recomputes that
  identical sum (the validate_serve percentile discipline: float-exact
  by identical computation, never by tolerance);
- **shape-mix and arrival-process statistics** — per-shape req/s,
  interarrival quantiles (``obs.metrics.percentile`` arithmetic, like
  every exposition in this repo), burstiness (the coefficient of
  variation of interarrivals), hot-shape ranking;
- **batch-efficiency accounting** — fill ratio, padding-waste bytes
  from the power-of-two batch padding, static fence counts per request
  (``len(schedule.rounds())`` over the SAME jax-free compile path the
  server admits through);
- seeded **hot-shape/skew detection** — the ``resilience/detect.py``
  pattern applied to request streams: ADVISORY ONLY, proposes tune/
  synth targets by name, never changes what ran.

Everything here derives from the journal/trace streams — never from
host callbacks, never from ad-hoc timing added for the profiler's
benefit (the flight-recorder discipline one level up). The server-side
counters behind the ``/metrics`` fill-ratio and padding-waste gauges
use :func:`padded_slots` / :func:`payload_bytes` /
:func:`batch_fill_ratio` from THIS module, so the exported numbers and
the profiler's re-derivation cannot drift (telemetry_gate.py holds the
line float-exactly).

``WORKLOAD_r*.json`` (workload-v1) is written atomically, schema-
validated by ``obs.regress.validate_workload`` (self-contradiction =
invalid), discovered by ``obs.history.load_history``, and replays to
REPRODUCED from the recorded journals alone (:func:`replay_workload`).
:func:`workload_scenario` closes the loop: the measured shape mix and
arrival process become a seeded synthetic scenario for
``serve_loadgen.py --workload`` — same artifact + seed in ⟹ same
request sequence out.
"""

from __future__ import annotations

import json
import os
import random
import statistics
import time

from tpu_aggcomm.obs.atomic import atomic_write
from tpu_aggcomm.obs.metrics import percentile
from tpu_aggcomm.resilience.journal import RunJournal

__all__ = ["WORKLOAD_SCHEMA", "BOUNDARIES", "attribute_phases",
           "padded_slots", "payload_bytes", "batch_fill_ratio",
           "aggregate_rows", "profile_journal", "workload_scenario",
           "write_workload", "replay_workload", "render_workload"]

WORKLOAD_SCHEMA = "workload-v1"

#: Canonical phase-boundary order, as stamped by serve/server.py
#: (_Pending.marks). "admit" is always 0.0 (stamps are relative to the
#: admission monotonic clock read); each later boundary's phase
#: duration is the time since the PREVIOUS RECORDED boundary, so a
#: request shed mid-flight attributes honestly over the prefix it
#: actually traversed.
BOUNDARIES = ("admit", "queue", "batch", "cache", "dispatch", "respond")

#: What each boundary's duration means (the interval ENDING at it).
PHASE_MEANING = {
    "queue": "waiting in the admission queue",
    "batch": "batch formation (the --batch-window-ms gather)",
    "cache": "cache lookup + compile (zero-ish on a warm hit)",
    "dispatch": "device dispatch (execute_batch wall)",
    "respond": "result post-processing + response assembly",
}

# -- detection thresholds (the resilience/detect.py discipline:
# conservative, named, advisory) ------------------------------------------
#: A shape is "hot" when it exceeds this fraction of admitted requests.
HOT_SHARE = 0.5
#: Interarrival coefficient of variation above this = bursty arrivals
#: (a Poisson process has CV 1.0; 2x that is unambiguous burstiness).
SKEW_CV = 2.0
#: Below this many admitted requests every verdict is "insufficient".
MIN_REQUESTS = 8


# ---------------------------------------------------------------------------
# The shared batch arithmetic (server gauges == profiler re-derivation).

def padded_slots(n: int, backend_name: str) -> int:
    """Padded batch size for an ``n``-request batch on ``backend_name``
    — MUST mirror serve/executor.py exactly: jax_sim batches >1 pad to
    the next power of two; pallas_fused (and singletons) execute
    unpadded."""
    if backend_name != "jax_sim" or n <= 1:
        return n
    p = 1
    while p < n:
        p *= 2
    return p


def payload_bytes(shape: dict) -> int:
    """Declared per-request payload bytes for one shape-fields dict
    (``nprocs * data_size`` — the global send footprint, a documented
    PROXY for the padded device slab, not an HBM measurement)."""
    return int(shape.get("nprocs", 0) or 0) * \
        int(shape.get("data_size", 2048) or 0)


def batch_fill_ratio(batched: int, padded: int) -> float | None:
    """Requests per padded slot (1.0 = no padding waste); None when
    nothing has been dispatched yet."""
    if padded <= 0:
        return None
    return batched / padded


# ---------------------------------------------------------------------------
# Phase attribution.

def attribute_phases(stamps) -> tuple[dict, list[str]]:
    """``(phases, problems)`` for one request's boundary stamps.

    ``phases`` maps each recorded boundary (after the first) to the
    seconds since the PREVIOUS recorded boundary, in canonical
    :data:`BOUNDARIES` order. Problems (non-monotone stamps, unknown
    boundary names, non-numeric values) are named, never silently
    absorbed — serve/recover.py uses the same check to refuse
    reordered journal lines."""
    problems: list[str] = []
    phases: dict = {}
    if not isinstance(stamps, dict):
        return phases, ["phase stamps are not a dict"]
    for k in stamps:
        if k not in BOUNDARIES:
            problems.append(f"unknown phase boundary {k!r} (canonical "
                            f"order: {', '.join(BOUNDARIES)})")
    prev_name = prev_t = None
    for b in BOUNDARIES:
        if b not in stamps:
            continue
        t = stamps[b]
        if isinstance(t, bool) or not isinstance(t, (int, float)):
            problems.append(f"boundary {b!r} stamp {t!r} is not a number")
            continue
        if prev_t is not None:
            d = t - prev_t
            if d < 0:
                problems.append(
                    f"boundary {b!r} at {t!r} precedes {prev_name!r} at "
                    f"{prev_t!r} — phase stamps must be monotone")
            phases[b] = d
        prev_name, prev_t = b, t
    return phases, problems


def _wall_of(phases: dict) -> float | None:
    """The request wall as THE canonical sum (validate_workload
    recomputes this identical expression — float-exactness by identical
    computation)."""
    vals = [phases[b] for b in BOUNDARIES if b in phases]
    return sum(vals) if vals else None


# ---------------------------------------------------------------------------
# The profiler.

def _shape_sig(shape: dict | None, backend) -> str:
    return json.dumps({"shape": shape, "backend": backend},
                      sort_keys=True)


def _fence_count(shape: dict) -> int | None:
    """Static per-request fence count: the schedule's data-edge round
    count through the SAME jax-free compile path the server admits
    through (serve/protocol.request_schedule)."""
    try:
        from tpu_aggcomm.serve.protocol import parse_request, \
            request_schedule
        return len(request_schedule(parse_request(dict(shape))).rounds())
    except Exception:  # lint: broad-ok (fence counts are advisory static enrichment; a recorded shape that no longer compiles must not sink the profile)
        return None


def _stats_block(vals: list) -> dict:
    return {"n": len(vals), "total_s": sum(vals),
            "mean_s": sum(vals) / len(vals),
            "p50_s": percentile(vals, 50.0),
            "p95_s": percentile(vals, 95.0),
            "max_s": max(vals)}


def profile_journal(paths, *, seed: int = 0) -> dict:
    """Re-derive the workload profile from serve journal(s).

    Torn lines were already skipped by the journal reader; admitted
    requests with no terminal record are named ``lost`` (the crash ate
    them — serve/recover.py semantics). The returned dict is the
    workload-v1 body minus the artifact envelope (schema/manifest/
    created_unix, added by :func:`write_workload`); ``problems`` names
    every self-contradiction found (a non-empty list should fail the
    caller, the journal disagrees with itself)."""
    paths = list(paths)
    admitted: dict = {}
    terminal: dict = {}
    problems: list[str] = []
    for path in paths:
        for rec in RunJournal(path).entries():
            key = rec.get("key") or {}
            rid = key.get("request")
            if rid is None:
                continue
            status = rec.get("status")
            if status == "admitted":
                admitted.setdefault(rid, rec)
            elif status in ("done", "fail", "shed"):
                terminal.setdefault(rid, rec)

    rows: list[dict] = []
    counts = {"done": 0, "fail": 0, "shed": 0}
    lost: list = []
    for rid in sorted(set(admitted) | set(terminal)):
        adm = admitted.get(rid)
        term = terminal.get(rid)
        status = term.get("status") if term is not None else "lost"
        if term is None:
            lost.append(rid)
        else:
            counts[status] += 1
        phases: dict = {}
        wall = None
        if term is not None and "phases" in term:
            phases, pp = attribute_phases(term.get("phases"))
            for p in pp:
                problems.append(f"request {rid}: {p}")
            wall = _wall_of(phases)
        batch = None
        if term is not None and term.get("batch_seq") is not None:
            batch = {"seq": term["batch_seq"],
                     "n": term.get("batch_n"),
                     "padded": term.get("batch_padded")}
        rows.append({
            "rid": rid, "status": status,
            "shape": (adm or {}).get("shape"),
            "backend": (adm or {}).get("backend")
            or (term or {}).get("backend"),
            "arrival_unix": (adm or {}).get("t_unix"),
            "queue_depth": (adm or {}).get("queue_depth"),
            "phases": phases, "wall_s": wall,
            "latency_s": (term or {}).get("latency_s"),
            "cache": (term or {}).get("cache"),
            "shed_reason": (term or {}).get("reason")
            if status == "shed" else None,
            "batch": batch,
        })

    agg = aggregate_rows(rows)
    problems.extend(agg.pop("problems"))
    profile = {
        "seed": int(seed),
        "journals": [os.path.basename(p) for p in paths],
        "requests": {"admitted": len(admitted),
                     "completed": counts["done"],
                     "failed": counts["fail"],
                     "shed": counts["shed"],
                     "lost": lost},
        "per_request": rows,
        **agg,
        "proposals": [],
        "problems": problems,
    }
    profile["proposals"] = _detect(profile)
    return profile


def aggregate_rows(rows: list[dict], *, fences: dict | None = None) -> dict:
    """The aggregate blocks (phase_totals / arrivals / queue_depth /
    shape_mix / batching) re-derived from per-request rows alone.

    This is THE one aggregation arithmetic: :func:`profile_journal`
    builds artifacts through it, and ``obs.regress.validate_workload``
    re-runs it over a committed artifact's ``per_request`` rows and
    demands float-exact agreement — an aggregate its own rows
    contradict is schema-invalid. ``fences`` (shape sig -> fence count)
    skips the static schedule compile when the caller already has the
    counts (the validator trusts the recorded ones; freshness is the
    replay gate's job)."""
    problems: list[str] = []

    # -- phase totals (rid order, so the sums re-derive byte-for-byte)
    phase_totals: dict = {}
    for b in BOUNDARIES[1:]:
        vals = [r["phases"][b] for r in rows if b in r["phases"]]
        if vals:
            phase_totals[b] = _stats_block(vals)

    # -- arrival process ---------------------------------------------------
    arr = sorted((r["arrival_unix"], r["rid"]) for r in rows
                 if isinstance(r["arrival_unix"], (int, float)))
    inter = [b[0] - a[0] for a, b in zip(arr, arr[1:])]
    duration = arr[-1][0] - arr[0][0] if len(arr) > 1 else None
    mean_ia = sum(inter) / len(inter) if inter else None
    cv = None
    if inter and mean_ia and mean_ia > 0:
        cv = statistics.pstdev(inter) / mean_ia
    arrivals = {
        "n": len(arr),
        "duration_s": duration,
        "rps": (len(arr) / duration if duration else None),
        "interarrival_s": inter,
        "quantiles": ({"p50": percentile(inter, 50.0),
                       "p95": percentile(inter, 95.0),
                       "p99": percentile(inter, 99.0)} if inter else None),
        "mean_s": mean_ia,
        "cv": cv,
    }

    # -- queue depth at admission ------------------------------------------
    depths = [r["queue_depth"] for r in rows
              if isinstance(r["queue_depth"], int)]
    queue_depth = ({"n": len(depths), "mean": sum(depths) / len(depths),
                    "max": max(depths), "p95": percentile(depths, 95.0)}
                   if depths else None)

    # -- shape mix (hot-shape ranking: count desc, then canonical sig) -----
    groups: dict = {}
    for r in rows:
        if not isinstance(r["shape"], dict):
            continue
        sig = _shape_sig(r["shape"], r["backend"])
        g = groups.setdefault(sig, {"shape": r["shape"],
                                    "backend": r["backend"],
                                    "count": 0, "arrivals": []})
        g["count"] += 1
        if isinstance(r["arrival_unix"], (int, float)):
            g["arrivals"].append(r["arrival_unix"])
    n_shaped = sum(g["count"] for g in groups.values())
    fences = dict(fences or {})
    shape_mix: list[dict] = []
    for sig in sorted(groups, key=lambda s: (-groups[s]["count"], s)):
        g = groups[sig]
        if sig not in fences:
            fences[sig] = _fence_count(g["shape"])
        ts = sorted(g["arrivals"])
        ia = [b - a for a, b in zip(ts, ts[1:])]
        shape_mix.append({
            "shape": g["shape"], "backend": g["backend"],
            "count": g["count"],
            "fraction": g["count"] / n_shaped,
            "rps": (g["count"] / duration if duration else None),
            "fences_per_request": fences[sig],
            "interarrival_s": ({"n": len(ia),
                                "p50": percentile(ia, 50.0),
                                "p95": percentile(ia, 95.0)}
                               if ia else None),
        })

    # -- batch efficiency (only batches that reached dispatch carry a
    # padded count; a compile-fail batch has batch_padded null) ------------
    by_seq: dict = {}
    for r in rows:
        b = r["batch"]
        if not b or b.get("padded") is None:
            continue
        seq = b["seq"]
        e = by_seq.get(seq)
        if e is None:
            by_seq[seq] = {"seq": seq, "n": b["n"], "padded": b["padded"],
                           "payload_bytes": payload_bytes(r["shape"] or {}),
                           "members": 1}
        else:
            e["members"] += 1
            if (b["n"], b["padded"]) != (e["n"], e["padded"]):
                problems.append(
                    f"batch {seq}: request {r['rid']} records "
                    f"n={b['n']}/padded={b['padded']} but an earlier "
                    f"member recorded n={e['n']}/padded={e['padded']}")
    per_batch = []
    for seq in sorted(by_seq):
        e = by_seq[seq]
        if e["members"] != e["n"]:
            problems.append(
                f"batch {seq}: {e['members']} member records vs "
                f"recorded batch_n={e['n']} — the journal disagrees "
                f"with itself")
        e = dict(e)
        e.pop("members")
        e["waste_bytes"] = (e["padded"] - e["n"]) * e["payload_bytes"]
        per_batch.append(e)
    req_batched = sum(e["n"] for e in per_batch)
    slots = sum(e["padded"] for e in per_batch)
    batching = {
        "batches": len(per_batch),
        "requests_batched": req_batched,
        "padded_slots": slots,
        "fill_ratio": batch_fill_ratio(req_batched, slots),
        "padding_waste_bytes": sum(e["waste_bytes"] for e in per_batch),
        "per_batch": per_batch,
    }

    return {"phase_totals": phase_totals, "arrivals": arrivals,
            "queue_depth": queue_depth, "shape_mix": shape_mix,
            "batching": batching, "problems": problems}


# ---------------------------------------------------------------------------
# Seeded hot-shape / skew detection (advisory; resilience/detect.py).

def _shape_flags(shape: dict, backend) -> str:
    return (f"-n {shape.get('nprocs')} -d {shape.get('data_size')} "
            f"--methods {shape.get('method')} "
            f"--cb-nodes {shape.get('cb_nodes')} "
            f"--comm-sizes {shape.get('comm_size')} "
            f"--backend {backend or 'jax_sim'}")


def _detect(profile: dict) -> list[dict]:
    """Advisory proposals from the measured stream — named tune/synth
    targets, never a behavior change. Conservative by construction:
    below MIN_REQUESTS everything is insufficient evidence."""
    out: list[dict] = []
    n = profile["requests"]["admitted"]
    if n < MIN_REQUESTS:
        return out
    mix = profile["shape_mix"]
    if mix and mix[0]["count"] > HOT_SHARE * n:
        top = mix[0]
        out.append({
            "kind": "hot-shape", "target": "tune",
            "shape": top["shape"], "backend": top["backend"],
            "share": top["fraction"],
            "reason": (f"one shape serves {top['count']}/{n} requests "
                       f"({top['fraction']:.0%} > {HOT_SHARE:.0%}) — "
                       f"worth a tuned winner"),
            "cli": ("python -m tpu_aggcomm.cli tune "
                    + _shape_flags(top["shape"], top["backend"])),
        })
    cv = (profile["arrivals"] or {}).get("cv")
    if mix and cv is not None and cv > SKEW_CV:
        top = mix[0]
        shape = top["shape"]
        out.append({
            "kind": "bursty-arrivals", "target": "synth",
            "shape": shape, "backend": top["backend"], "cv": cv,
            "reason": (f"interarrival CV {cv:.2f} > {SKEW_CV:.1f} — "
                       f"bursty incast on the hot shape; a synthesized "
                       f"schedule tuned for the burst window may beat "
                       f"the reference"),
            "cli": (f"python -m tpu_aggcomm.cli synth "
                    f"-n {shape.get('nprocs')} "
                    f"-a {shape.get('cb_nodes')} "
                    f"-c {shape.get('comm_size')} "
                    f"-d {shape.get('data_size')} "
                    f"--seed {profile['seed']}"),
        })
    return out


# ---------------------------------------------------------------------------
# The replay scenario (serve_loadgen --workload).

def workload_scenario(blob: dict, *, seed=None, requests=None) -> list[dict]:
    """The measured mix + arrival process as a seeded synthetic request
    plan: ``[{"i", "at_s", "shape", "backend"}, ...]``.

    Shapes are drawn weighted by measured count; interarrival gaps are
    resampled from the measured samples — both through ONE
    ``random.Random(seed)``, so the same artifact + seed yields the
    byte-identical sequence (the tune/regress seed discipline)."""
    mix = [m for m in (blob.get("shape_mix") or [])
           if isinstance(m.get("shape"), dict) and m.get("count", 0) > 0]
    if not mix:
        raise ValueError("workload artifact has no shape mix to replay "
                         "(profile a journal with admitted requests)")
    samples = [s for s in ((blob.get("arrivals") or {})
                           .get("interarrival_s") or [])
               if isinstance(s, (int, float)) and s >= 0]
    if seed is None:
        seed = blob.get("seed", 0)
    if requests is None:
        requests = (blob.get("requests") or {}).get("admitted") \
            or sum(m["count"] for m in mix)
    rng = random.Random(int(seed))
    weights = [m["count"] for m in mix]
    plan: list[dict] = []
    at = 0.0
    for i in range(int(requests)):
        if i and samples:
            at += samples[rng.randrange(len(samples))]
        m = rng.choices(mix, weights=weights)[0]
        plan.append({"i": i, "at_s": at, "shape": dict(m["shape"]),
                     "backend": m.get("backend")})
    return plan


# ---------------------------------------------------------------------------
# Artifact I/O.

def write_workload(path: str, profile: dict) -> dict:
    """Write one workload-v1 artifact atomically (manifest records env
    var NAMES only, the ledger discipline) and return the blob."""
    from tpu_aggcomm.obs import ledger
    blob = dict(profile)
    blob["schema"] = WORKLOAD_SCHEMA
    blob["manifest"] = ledger.manifest()
    blob["created_unix"] = time.time()
    with atomic_write(path) as fh:
        json.dump(blob, fh, indent=1)
        fh.write("\n")
    return blob


#: Envelope keys excluded from the replay comparison (environment-
#: dependent by design; everything else must re-derive byte-for-byte).
_ENVELOPE = ("schema", "manifest", "created_unix")


def replay_workload(path: str) -> dict:
    """Re-derive a committed WORKLOAD_r*.json from its recorded
    journals alone and byte-compare (minus the envelope).

    Journal paths resolve relative to the artifact's directory (the
    artifact records basenames). Returns ``{"verdict": "REPRODUCED" |
    "MISMATCH", "problems": [...]}`` with every diverging top-level key
    named."""
    with open(path) as fh:
        blob = json.load(fh)
    problems: list[str] = []
    if blob.get("schema") != WORKLOAD_SCHEMA:
        return {"verdict": "MISMATCH",
                "problems": [f"schema {blob.get('schema')!r} != "
                             f"{WORKLOAD_SCHEMA!r}"]}
    root = os.path.dirname(os.path.abspath(path))
    journals = []
    for name in blob.get("journals", []):
        jp = name if os.path.isabs(name) else os.path.join(root, name)
        if not os.path.exists(jp):
            problems.append(f"recorded journal {name!r} not found "
                            f"next to the artifact ({root})")
        journals.append(jp)
    if problems:
        return {"verdict": "MISMATCH", "problems": problems}
    rederived = profile_journal(journals, seed=blob.get("seed", 0))
    want = {k: v for k, v in blob.items() if k not in _ENVELOPE}
    for k in sorted(set(want) | set(rederived)):
        a = json.dumps(want.get(k), sort_keys=True)
        b = json.dumps(rederived.get(k), sort_keys=True)
        if a != b:
            problems.append(f"key {k!r} does not re-derive from the "
                            f"journal (artifact {a[:120]}... vs "
                            f"re-derived {b[:120]}...)"
                            if max(len(a), len(b)) > 120 else
                            f"key {k!r}: artifact {a} vs re-derived {b}")
    return {"verdict": "REPRODUCED" if not problems else "MISMATCH",
            "problems": problems}


# ---------------------------------------------------------------------------
# Rendering.

def _fmt_s(v) -> str:
    return f"{v * 1e3:9.3f} ms" if isinstance(v, (int, float)) else "      -  "


def render_workload(profile: dict) -> str:
    """The ``inspect workload`` text view."""
    r = profile["requests"]
    lines = [f"workload profile over {', '.join(profile['journals'])} "
             f"(seed {profile['seed']})",
             f"  requests: {r['admitted']} admitted — {r['completed']} "
             f"completed, {r['failed']} failed, {r['shed']} shed"
             + (f", LOST in flight: {r['lost']}" if r["lost"] else "")]
    a = profile["arrivals"]
    if a["n"] > 1 and a["duration_s"] is not None:
        cv = f"{a['cv']:.2f}" if a["cv"] is not None else "-"
        q = a["quantiles"] or {}
        lines.append(
            f"  arrivals: {a['n']} over {a['duration_s']:.3f} s "
            f"({a['rps']:.1f} req/s), interarrival p50 "
            f"{_fmt_s(q.get('p50')).strip()} p95 "
            f"{_fmt_s(q.get('p95')).strip()}, burstiness CV {cv}")
    qd = profile.get("queue_depth")
    if qd:
        lines.append(f"  queue depth at admit: mean {qd['mean']:.1f}, "
                     f"p95 {qd['p95']:.1f}, max {qd['max']}")
    if profile["phase_totals"]:
        lines.append("  phase attribution (mean over requests that "
                     "reached the boundary):")
        for b in BOUNDARIES[1:]:
            st = profile["phase_totals"].get(b)
            if st is None:
                continue
            lines.append(f"    {b:>9}: {_fmt_s(st['mean_s'])} mean  "
                         f"{_fmt_s(st['p95_s'])} p95  "
                         f"(n={st['n']}; {PHASE_MEANING.get(b, '')})")
    if profile["shape_mix"]:
        lines.append("  shape mix (hot first):")
        for m in profile["shape_mix"][:8]:
            s = m["shape"]
            rps = f"{m['rps']:.1f} req/s" if m["rps"] is not None else "-"
            fen = (f", {m['fences_per_request']} fences/req"
                   if m["fences_per_request"] is not None else "")
            lines.append(
                f"    m={s.get('method')} n={s.get('nprocs')} "
                f"a={s.get('cb_nodes')} c={s.get('comm_size')} "
                f"d={s.get('data_size')} [{m['backend']}]: "
                f"{m['count']} ({m['fraction']:.0%}), {rps}{fen}")
        if len(profile["shape_mix"]) > 8:
            lines.append(f"    ... {len(profile['shape_mix']) - 8} more")
    b = profile["batching"]
    if b["batches"]:
        fill = f"{b['fill_ratio']:.2f}" if b["fill_ratio"] is not None \
            else "-"
        lines.append(
            f"  batching: {b['batches']} dispatched batches, "
            f"{b['requests_batched']} requests in {b['padded_slots']} "
            f"padded slots (fill {fill}), padding waste "
            f"{b['padding_waste_bytes']} B")
    for p in profile["proposals"]:
        lines.append(f"  ADVISORY [{p['kind']} -> {p['target']}]: "
                     f"{p['reason']}")
        lines.append(f"    {p['cli']}")
    if not profile["proposals"] and r["admitted"] >= MIN_REQUESTS:
        lines.append("  detection: no hot-shape/skew proposals "
                     "(balanced mix, steady arrivals)")
    for p in profile["problems"]:
        lines.append(f"  PROBLEM: {p}")
    return "\n".join(lines) + "\n"
