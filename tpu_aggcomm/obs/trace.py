"""The flight recorder: structured span/counter events for every run.

Two kinds of time live here, and the recorder never mixes them up:

- **host spans** — real ``perf_counter`` windows measured on the host
  (dispatch loops, chained differencing windows, the oracle's delivery
  instants). These are honest wall measurements of HOST-visible
  boundaries.
- **reconstructed rank/round slices** — the per-rank per-round bucket
  slices of a rep. On the compiled backends phases cannot be bracketed
  individually inside one XLA program (harness/attribution.py module
  docstring), so these slices are rebuilt from the attribution cell
  stream (``harness.attribution.cell_recording``): every slice carries
  the EXACT seconds the attribution charged to the rank's Timer bucket,
  plus the run's column-accurate provenance label
  (``report.py:PHASE_SOURCES``) so a reconstructed slice can never be
  read as a measured one.

The cell stream mirrors the arithmetic of the ``Timer.add`` calls it
shadows — same expressions, same order — and :func:`aggregate_run`
replays the backend's own combine step (sequential accumulation for
per-dispatch reps, ``array * ntimes`` for chained/measured reps), so a
trace re-aggregates FLOAT-EXACTLY to the Timer columns the run reported
(the round-trip tests pin this). Span events are therefore written in
cell order; the timeline geometry (``ts``) is computed separately and
never feeds aggregation.

Tracing is off by default and zero-cost when off: the module-level
:func:`span` returns a shared no-op context manager and :func:`instant`
is a single ``is None`` check. Nothing in this module imports jax.
"""

from __future__ import annotations

import contextlib
import json
import time

__all__ = ["TraceRecorder", "aggregate_run", "current", "disable", "enable",
           "enabled", "flush", "hbm_sample", "instant", "run_context",
           "span", "summarize_trace", "summarize_events", "load_events",
           "round_key", "WHOLE_REP", "BUCKET_FIELDS"]

#: ``round`` value of a slice that covers the whole rep (attributions with
#: no per-round decomposition: attribute_total, the measured post/deliver
#: split's post window, TAM byte-split totals).
WHOLE_REP = -1

#: Timer-column label -> the Timer fields it charges. "recv+send_wait"
#: charges BOTH wait columns — the reference brackets a non-aggregator's
#: Waitall once and adds it to both fields (mpi_test.c:1505-1510);
#: re-aggregation must preserve that or column sums drift.
BUCKET_FIELDS = {
    "post": ("post",),
    "send_wait": ("send_wait",),
    "recv_wait": ("recv_wait",),
    "recv+send_wait": ("recv_wait", "send_wait"),
    "barrier": ("barrier",),
}

_TIMER_COLS = ("post", "send_wait", "recv_wait", "barrier", "total")


class _NoopSpan:
    """Shared do-nothing context manager returned when tracing is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _HostSpan:
    """A real perf_counter window appended to the event log on exit."""

    __slots__ = ("_rec", "_name", "_args", "_t0")

    def __init__(self, rec: "TraceRecorder", name: str, args: dict):
        self._rec = rec
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._rec._events.append({
            "ev": "host_span", "name": self._name,
            "ts": (self._t0 - self._rec._t0) * 1e6,
            "dur": (t1 - self._t0) * 1e6, "args": self._args})
        return False


def round_key(rnd):
    """Program-order sort key over mixed round labels: the whole-rep
    pseudo-round first, then integer throttle rounds, then the TAM hop
    labels ("P2" < "P3" < "P4"). Public — the analytics layer
    (obs/metrics.py, obs/compare.py) orders its tables with the exact
    key the recorder laid slices out with."""
    if rnd is None:
        return (-1,)
    if isinstance(rnd, int):
        return (0, rnd) if rnd == WHOLE_REP else (1, rnd)
    return (2, str(rnd))


_round_key = round_key


class TraceRecorder:
    """In-memory event log; one per enabled tracing session.

    Events are plain dicts (one JSONL line each on flush):

    - ``{"ev": "meta", ...}`` — one per recorder, schema version.
    - ``{"ev": "run", "id": k, ...}`` — one per (iter, method) backend
      run: config, provenance, the combine mode, and per-round payload
      bytes (the bytes-in-flight counter input).
    - ``{"ev": "span", "run": k, "rep": r, "rank": q, "round": rnd,
      "bucket": b, "ts": µs, "dur": µs, "dur_s": exact_seconds,
      "src": provenance}`` — one reconstructed slice. ``bucket ==
      "total"`` is the rep envelope; other buckets are Timer columns;
      ``round`` is an int throttle round, a TAM hop label, ``-1`` for a
      whole-rep attribution, or ``None`` on the envelope.
    - ``{"ev": "counter", ...}`` — bytes-in-flight samples on the
      reconstructed timeline.
    - ``{"ev": "timer", "run": k, "rank": q, ...}`` — the FINAL Timer
      columns the run reported, per rank (the round-trip ground truth).
    - ``{"ev": "host_span" | "instant", ...}`` — measured host windows.
    - ``{"ev": "ledger", "manifest": {...}}`` — the run-ledger preamble
      (obs/ledger.py): the environment manifest this trace was recorded
      under. Written at enable time, refreshed on flush so device facts
      recorded mid-run (platform, device kind) are included.
    - ``{"ev": "hbm", "ts": µs, "bytes_in_use": n, "peak_bytes": n}`` —
      ``device.memory_stats()`` samples (HBM counter track in the
      Perfetto export). Host-sampled OUTSIDE the timed path.
    """

    SCHEMA_VERSION = 1

    def __init__(self):
        self._t0 = time.perf_counter()
        self._events: list[dict] = [
            {"ev": "meta", "schema": self.SCHEMA_VERSION,
             "created_unix": time.time()}]
        # the run-ledger preamble rides in every trace; ledger failure
        # (e.g. a sandboxed git) must never break tracing itself
        try:
            from tpu_aggcomm.obs import ledger
            self._events.append({"ev": "ledger",
                                 "manifest": ledger.manifest()})
        except Exception:  # lint: broad-ok (ledger enrichment must never sink a trace)
            pass
        self._cursor_us = 0.0           # reconstructed-timeline cursor
        self._next_run = 0

    # -- host-side API ---------------------------------------------------
    def span(self, name: str, **args):
        return _HostSpan(self, name, args)

    def instant(self, name: str, **args):
        self._events.append({
            "ev": "instant", "name": name,
            "ts": (time.perf_counter() - self._t0) * 1e6, "args": args})

    def hbm_sample(self, *, bytes_in_use=None, peak_bytes=None) -> None:
        """One HBM usage sample on the host timeline (sampled after a
        dispatch returns — never inside the timed path)."""
        self._events.append({
            "ev": "hbm", "ts": (time.perf_counter() - self._t0) * 1e6,
            "bytes_in_use": bytes_in_use, "peak_bytes": peak_bytes})

    # -- reconstructed-timeline API --------------------------------------
    def record_method_run(self, schedule, *, method: int, name: str,
                          iter_: int, ntimes: int, requested: str,
                          executed: str, phase_source: str, timers,
                          calls, rep_timers=None, fault=None) -> int:
        """Append the run/span/counter/timer events for one backend run.

        ``calls`` is the attribution cell stream captured around
        ``backend.run`` (``harness.attribution.cell_recording``); when it
        is empty (local/native measure reps directly, no attribution
        runs) the slices are rebuilt from ``rep_timers``
        (``backend.last_rep_timers``) instead.
        """
        run_id = self._next_run
        self._next_run += 1
        p = schedule.pattern
        if calls:
            combine = ("sum" if len(calls) == ntimes
                       else "scale" if len(calls) == 1
                       else "mixed")
        else:
            combine = "sum"
        round_bytes = _round_bytes(schedule)
        round_traffic = _round_traffic(schedule)
        run_event = {
            "ev": "run", "id": run_id, "method": method, "name": name,
            "iter": iter_, "ntimes": ntimes, "nprocs": p.nprocs,
            "data_size": p.data_size, "comm_size": p.comm_size,
            "cb_nodes": p.cb_nodes, "proc_node": p.proc_node,
            "agg_type": int(p.placement),
            "backend": requested, "executed": executed,
            "phase_source": phase_source, "combine": combine,
            "round_bytes": round_bytes, "round_traffic": round_traffic,
            "fault": fault}
        for k, v in _RUN_EXTRA.items():
            run_event.setdefault(k, v)   # context extras never shadow core
        self._events.append(run_event)

        if calls:
            for rep in range(ntimes):
                call = calls[rep] if combine != "scale" else calls[0]
                if combine == "mixed" and rep >= len(calls):
                    break
                self._emit_rep(run_id, rep, call, phase_source, p.nprocs,
                               round_bytes, round_traffic)
        else:
            self._emit_timer_reps(run_id, ntimes, timers, rep_timers,
                                  phase_source, p.nprocs)

        for rank, t in enumerate(timers):
            self._events.append({
                "ev": "timer", "run": run_id, "rank": rank,
                "post": t.post_request_time,
                "send_wait": t.send_wait_all_time,
                "recv_wait": t.recv_wait_all_time,
                "barrier": t.barrier_time, "total": t.total_time})
        return run_id

    def _emit_rep(self, run_id: int, rep: int, call: dict, src: str,
                  nprocs: int, round_bytes, round_traffic=None) -> None:
        """One rep's slices from one attribution call's cells.

        Geometry: every rank shares the rep envelope (on a fused program
        all ranks share wall windows — attribution.py); within the rep,
        round windows are laid out sequentially in program order, each
        as wide as its slowest rank (the wall view); within a round, a
        rank's bucket slices run back-to-back from the round start.
        Span EVENTS are appended in original cell order (aggregation
        order must match the ``Timer.add`` order); only ``ts`` uses the
        grouped geometry.
        """
        rep_start = self._cursor_us
        cells = call["cells"]
        rounds: list = []
        by_round: dict = {}
        for (rank, rnd, _bucket, secs) in cells:
            if rnd not in by_round:
                by_round[rnd] = {}
                rounds.append(rnd)
            per_rank = by_round[rnd]
            per_rank[rank] = per_rank.get(rank, 0.0) + secs
        rounds.sort(key=_round_key)

        # round window starts on the shared timeline
        round_start: dict = {}
        cursor = rep_start
        for rnd in rounds:
            round_start[rnd] = cursor
            if round_bytes is not None:
                self._events.append({
                    "ev": "counter", "run": run_id, "rep": rep,
                    "name": "bytes_in_flight", "ts": cursor,
                    "value": round_bytes.get(str(rnd), 0)})
            if round_traffic is not None:
                rt = round_traffic.get(str(rnd), {})
                for cname, ckey in (("traffic_msgs", "msgs"),
                                    ("traffic_max_incast", "max_incast")):
                    self._events.append({
                        "ev": "counter", "run": run_id, "rep": rep,
                        "name": cname, "ts": cursor,
                        "value": rt.get(ckey, 0)})
            cursor += max(by_round[rnd].values()) * 1e6

        rep_total = call["total"]
        rep_dur = max(rep_total * 1e6, cursor - rep_start)
        for rank in range(nprocs):
            self._events.append({
                "ev": "span", "run": run_id, "rep": rep, "rank": rank,
                "round": None, "bucket": "total", "ts": rep_start,
                "dur": rep_dur, "dur_s": rep_total, "src": src})

        # bucket slices, in cell order; per-(round, rank) running offset
        offs: dict = {}
        for (rank, rnd, bucket, secs) in cells:
            key = (rnd, rank)
            ts = offs.get(key, round_start[rnd])
            self._events.append({
                "ev": "span", "run": run_id, "rep": rep, "rank": rank,
                "round": rnd, "bucket": bucket, "ts": ts,
                "dur": secs * 1e6, "dur_s": secs, "src": src})
            offs[key] = ts + secs * 1e6
        if rounds and round_bytes is not None:
            self._events.append({
                "ev": "counter", "run": run_id, "rep": rep,
                "name": "bytes_in_flight", "ts": rep_start + rep_dur,
                "value": 0})
        if rounds and round_traffic is not None:
            for cname in ("traffic_msgs", "traffic_max_incast"):
                self._events.append({
                    "ev": "counter", "run": run_id, "rep": rep,
                    "name": cname, "ts": rep_start + rep_dur, "value": 0})
        self._cursor_us = rep_start + rep_dur

    def _emit_timer_reps(self, run_id: int, ntimes: int, timers,
                         rep_timers, src: str, nprocs: int) -> None:
        """Slices for backends that never ran the attribution: rebuild
        them from the per-rep Timer rows (local: total-only envelopes;
        native: per-op measured columns become one slice per nonzero
        column per rep)."""
        for rep in range(ntimes):
            rep_start = self._cursor_us
            if rep_timers is not None and rep < len(rep_timers):
                row = rep_timers[rep]
            else:
                # degenerate fallback: equal shares of the accumulated
                # totals (aggregation exactness is not claimed here)
                row = None
            wall = 0.0
            for rank in range(nprocs):
                if row is not None:
                    t = row[rank]
                    cols = [("post", t.post_request_time),
                            ("send_wait", t.send_wait_all_time),
                            ("recv_wait", t.recv_wait_all_time),
                            ("barrier", t.barrier_time)]
                    total = t.total_time
                else:
                    cols = []
                    total = timers[rank].total_time / ntimes
                wall = max(wall, total)
                self._events.append({
                    "ev": "span", "run": run_id, "rep": rep, "rank": rank,
                    "round": None, "bucket": "total", "ts": rep_start,
                    "dur": total * 1e6, "dur_s": total, "src": src})
                ts = rep_start
                for bucket, secs in cols:
                    if secs == 0.0:
                        continue
                    self._events.append({
                        "ev": "span", "run": run_id, "rep": rep,
                        "rank": rank, "round": WHOLE_REP, "bucket": bucket,
                        "ts": ts, "dur": secs * 1e6, "dur_s": secs,
                        "src": src})
                    ts += secs * 1e6
            self._cursor_us = rep_start + wall * 1e6

    # -- output ----------------------------------------------------------
    @property
    def events(self) -> list[dict]:
        return self._events

    def flush(self, prefix: str) -> tuple[str, str]:
        """Write ``<prefix>.trace.jsonl`` (the event log) and
        ``<prefix>.trace.json`` (Chrome/Perfetto). Returns both paths."""
        from tpu_aggcomm.obs.perfetto import to_chrome_trace
        # refresh the ledger preamble: device facts (platform, kind) are
        # recorded by jax-side code after the recorder was created
        try:
            from tpu_aggcomm.obs import ledger
            for e in self._events:
                if e.get("ev") == "ledger":
                    e["manifest"] = ledger.manifest()
                    break
        except Exception:  # lint: broad-ok (ledger enrichment must never sink a trace)
            pass
        from tpu_aggcomm.obs.atomic import atomic_write
        jsonl = f"{prefix}.trace.jsonl"
        with atomic_write(jsonl) as fh:
            for e in self._events:
                fh.write(json.dumps(e) + "\n")
        pft = f"{prefix}.trace.json"
        with atomic_write(pft) as fh:
            json.dump(to_chrome_trace(self._events), fh)
        return jsonl, pft


def _round_bytes(schedule) -> dict | None:
    """Payload bytes entering flight per round, ``{str(round): bytes}``
    — the bytes-in-flight counter input. None when the schedule has no
    edge list to count (dense collectives, the TAM relay)."""
    if getattr(schedule, "assignment", None) is not None:
        return None
    if getattr(schedule, "collective", False):
        return None
    try:
        edges = schedule.data_edges()
    except Exception:  # lint: broad-ok (static shape summary optional; TAM has none)
        return None
    ds = schedule.pattern.data_size
    out: dict[str, int] = {}
    for e in edges:
        rnd = str(int(e[4]))
        out[rnd] = out.get(rnd, 0) + ds
    return out


def _round_traffic(schedule) -> dict | None:
    """Per-round msgs/bytes/max-incast summary for the ``traffic_*``
    counter tracks (obs.traffic.round_traffic, static accounting from
    the op programs — never from measured callbacks). None when the
    schedule has no edge list to count (dense collectives — their
    matrix is O(n^2) dense and belongs in `inspect traffic`, not in
    every traced run — and the TAM relay), mirroring _round_bytes."""
    if getattr(schedule, "assignment", None) is not None:
        return None
    if getattr(schedule, "collective", False):
        return None
    try:
        from tpu_aggcomm.obs.traffic import round_traffic
        return round_traffic(schedule)
    except Exception:  # lint: broad-ok (static shape summary optional; TAM has none)
        return None


# ---------------------------------------------------------------------------
# Re-aggregation: trace -> Timer columns (the round-trip contract).

def aggregate_run(events: list[dict], run_id: int):
    """Rebuild the per-rank Timer columns of one run from its span events.

    Mirrors the backend arithmetic exactly: bucket slices accumulate into
    their Timer fields sequentially in event order (the order the
    attribution's ``Timer.add`` calls ran), per rep; per-rep results
    combine by the run's recorded mode — ``sum`` adds rep columns
    sequentially (per-dispatch/profiled backends and the per-rep-timer
    backends), ``scale`` multiplies rep 0 by ntimes (chained/measured
    backends, which build their final timers as ``rep_array * ntimes``).
    Float-exact by construction on both paths.

    Returns ``{rank: {"post": s, "send_wait": s, "recv_wait": s,
    "barrier": s, "total": s}}``. A run that recorded no span events at
    all (zero rounds AND zero rep envelopes — e.g. an aborted dispatch)
    re-aggregates to the empty dict rather than raising: there is
    nothing to rebuild, and the analytics layer treats {} as "no data".
    """
    run = next(e for e in events
               if e["ev"] == "run" and e["id"] == run_id)
    ntimes, combine = run["ntimes"], run["combine"]
    reps: dict[int, dict[int, dict[str, float]]] = {}
    for e in events:
        if e["ev"] != "span" or e["run"] != run_id:
            continue
        per_rank = reps.setdefault(e["rep"], {})
        cols = per_rank.setdefault(
            e["rank"], {k: 0.0 for k in _TIMER_COLS})
        if e["bucket"] == "total":
            cols["total"] = e["dur_s"]
        else:
            for field in BUCKET_FIELDS[e["bucket"]]:
                cols[field] += e["dur_s"]

    out: dict[int, dict[str, float]] = {}
    if not reps:
        return out
    if combine == "scale":
        for rank, cols in reps.get(0, {}).items():
            out[rank] = {k: v * ntimes for k, v in cols.items()}
        return out
    for rep in sorted(reps):
        for rank, cols in reps[rep].items():
            acc = out.setdefault(rank, {k: 0.0 for k in _TIMER_COLS})
            for k, v in cols.items():
                acc[k] += v
    return out


def load_events(path: str) -> list[dict]:
    """Read a ``*.trace.jsonl`` event log."""
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


def summarize_trace(path: str) -> str:
    """Round/rank critical-path summary of a trace file
    (``cli inspect trace <file>``). Works on the JSONL log; a Perfetto
    ``.trace.json`` should be opened in the Perfetto UI instead.
    Multiple files merge via :func:`tpu_aggcomm.obs.metrics
    .summarize_traces`."""
    return summarize_events(load_events(path))


def summarize_events(events: list[dict]) -> str:
    """The per-run summary body of :func:`summarize_trace`, over an
    already-loaded event list (so the multi-file merge can prefix each
    file's section without re-reading)."""
    runs = [e for e in events if e["ev"] == "run"]
    lines = []
    for run in runs:
        rid = run["id"]
        lines.append(
            f"run {rid}: m={run['method']} \"{run['name']}\" "
            f"iter={run['iter']} n={run['nprocs']} d={run['data_size']} "
            f"ntimes={run['ntimes']}")
        lines.append(
            f"  backend {run['backend']} -> executed {run['executed']}; "
            f"phase columns: {run['phase_source']}")
        spans = [e for e in events
                 if e["ev"] == "span" and e["run"] == rid
                 and e["bucket"] != "total" and e["rep"] == 0]
        rounds: dict = {}
        for e in spans:
            r = rounds.setdefault(e["round"], {})
            r[e["rank"]] = r.get(e["rank"], 0.0) + e["dur_s"]
        rbytes = run.get("round_bytes") or {}
        if rounds:
            lines.append("  rep 0 rounds (wall = slowest rank):")
            for rnd in sorted(rounds, key=_round_key):
                per_rank = rounds[rnd]
                crit = max(per_rank, key=per_rank.get)
                label = ("whole-rep" if rnd == WHOLE_REP
                         else f"round {rnd}")
                nb = rbytes.get(str(rnd))
                lines.append(
                    f"    {label:>10}: wall {per_rank[crit] * 1e3:9.3f} ms"
                    f"  critical rank {crit}"
                    + (f"  bytes {nb}" if nb is not None else ""))
        agg = aggregate_run(events, rid)
        if agg:
            crit = max(agg, key=lambda r: agg[r]["total"])
            c = agg[crit]
            lines.append(
                f"  critical rank {crit}: post {c['post']:.6f}  "
                f"send_wait {c['send_wait']:.6f}  "
                f"recv_wait {c['recv_wait']:.6f}  "
                f"barrier {c['barrier']:.6f}  total {c['total']:.6f}")
    hosts = sum(1 for e in events if e["ev"] == "host_span")
    insts = sum(1 for e in events if e["ev"] == "instant")
    if hosts or insts:
        lines.append(f"host-measured events: {hosts} spans, "
                     f"{insts} instants")
    if not runs:
        lines.append("no runs recorded in trace")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Module-level recorder (one active tracing session, like logging's root).

_RECORDER: TraceRecorder | None = None

#: Extra key/value pairs merged into run events recorded while a
#: :func:`run_context` block is active — the causal-correlation channel:
#: the serve layer stamps its batch correlation id (``cid``) here so the
#: flow joiner (obs/flow.py) can tie a request's journal record to the
#: run event of the dispatch that served it. Extras never shadow core
#: run-event fields (``setdefault`` merge).
_RUN_EXTRA: dict = {}


@contextlib.contextmanager
def run_context(**extra):
    """Merge ``extra`` into every run event recorded inside the block.

    Works whether or not tracing is armed (the recorder reads the module
    dict at record time); nested contexts stack, innermost wins, and the
    previous extras are restored on exit — the same discipline as
    ``harness.attribution.cell_recording``."""
    global _RUN_EXTRA
    prev = _RUN_EXTRA
    _RUN_EXTRA = {**prev, **extra}
    try:
        yield
    finally:
        _RUN_EXTRA = prev


def enable() -> TraceRecorder:
    """Switch tracing on; returns the fresh recorder."""
    global _RECORDER
    _RECORDER = TraceRecorder()
    return _RECORDER


def disable() -> None:
    global _RECORDER
    _RECORDER = None


def enabled() -> bool:
    return _RECORDER is not None


def current() -> TraceRecorder | None:
    return _RECORDER


def span(name: str, **args):
    """A host-measured span when tracing is on; a shared no-op otherwise
    (zero allocation, zero timing calls)."""
    rec = _RECORDER
    return _NOOP if rec is None else rec.span(name, **args)


def instant(name: str, **args) -> None:
    rec = _RECORDER
    if rec is not None:
        rec.instant(name, **args)


def hbm_sample(**kwargs) -> None:
    """An HBM usage sample when tracing is on; a single ``is None``
    check otherwise (callers may skip even querying memory_stats when
    tracing is off — see harness/runner.py)."""
    rec = _RECORDER
    if rec is not None:
        rec.hbm_sample(**kwargs)


def flush(prefix: str):
    """Flush the active recorder to ``<prefix>.trace.{jsonl,json}``; no-op
    (returns None) when tracing is off."""
    rec = _RECORDER
    return None if rec is None else rec.flush(prefix)
