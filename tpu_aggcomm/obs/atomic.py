"""Atomic artifact writes: tmp file + ``os.replace``, same directory.

Every committed artifact in this repo (TUNE_*.json, TRAFFIC_*.json,
``*.trace.{jsonl,json}``, report.html) is evidence that later rounds
replay verdicts from — a half-written file is worse than a missing one,
because the schema checkers and replay paths would fail on it long after
the writer died. The tunnel host kills jobs routinely (OOM, timeouts),
so every whole-file artifact writer goes through :func:`atomic_write`:
the content lands in a same-directory temp file (``os.replace`` is only
atomic within a filesystem), is flushed AND fsynced, and only then
renamed over the target. A writer killed at ANY instant leaves the
target either absent or fully intact, never truncated.

Append-mode logs (the sweep sidecar, the resilience run journal) are a
different contract — they stay append+fsync and their READERS skip a
torn final line (resilience/journal.py) — so this helper is deliberately
whole-file only.

jax-free, stdlib only (obs discipline): bench.py's supervisor and the
replay CLIs import through here where ``import jax`` may hang.
"""

from __future__ import annotations

import contextlib
import os
import tempfile

__all__ = ["atomic_write"]


@contextlib.contextmanager
def atomic_write(path: str, mode: str = "w", **open_kwargs):
    """Yield a handle onto ``<dir(path)>/<tmp>``; on clean exit the temp
    file is fsynced and ``os.replace``d over ``path``; on any error (or
    a kill before the rename) ``path`` is untouched and the temp file is
    unlinked where possible."""
    target = os.path.abspath(path)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(target) or ".",
                               prefix=os.path.basename(target) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, mode, **open_kwargs) as fh:
            yield fh
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, target)
    except BaseException:  # lint: broad-ok (tmp cleanup; re-raised below)
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise
