"""Traffic auditor: static communication-matrix accounting and throttle
conformance, derived ONLY from compiled op programs.

The flight recorder / straggler analytics / run ledger observe the *time*
domain; this module observes the *traffic* domain — which bytes cross
which (src, dst) edge in which round, how deep the incast fan-in at each
aggregator is, and whether a method's posting discipline actually bounds
in-flight messages to the ``-c`` limit the whole benchmark studies
(mpi_test.c's comm_size throttle).

Everything here is STATIC analysis over ``Schedule.programs``:

- :func:`round_edges` — per-round (src, dst) → bytes matrices, with
  0-byte SIGNAL handshakes counted on a separate channel and COPY
  memcpys (local, never on the wire) tracked apart from network edges.
- :func:`incast_depths` — per-round per-destination distinct-source
  counts (COPY excluded; MPI self-sends included — the reference posts
  them through the same transport).
- :func:`inflight_audit` — simulates each rank's nonblocking
  post/WAITALL token lifetimes and records the peak number of
  outstanding payload requests (sends + recvs; SIGNAL_SEND tokens are
  tracked separately — they carry no payload and the reference does not
  throttle them).
- :func:`documented_bound` — the per-method closed-form bound the
  ``-c`` throttle implies; :func:`audit_schedule` proves (CONFORMS) or
  refutes (REFUTED, naming the offending rank/round/count) it, and
  marks methods with no rank op programs (vendor collectives, the
  hierarchical TAM engine) EXEMPT.
- :func:`conformance_sweep` — the jax-free static gate over every
  method in ``core/methods.py:METHODS`` (wired into scripts/ci_tier1.sh).
- :func:`measured_overlay` — joins the static matrix with
  flight-recorder round walls (``obs.metrics.round_stats``, reused
  verbatim so the times match the trace float-exactly) and
  ``harness/roofline.py`` floors: per-round effective bytes/s,
  fraction-of-roofline, and incast-vs-straggler rank correlation.

Invariant: traffic accounting is derived from op programs, never from
measured callbacks, and this module must stay importable without jax
(tests/test_obs.py pins the whole obs package; core.schedule /
core.methods import only numpy).
"""

from __future__ import annotations

import json
import math

__all__ = ["TrafficError", "TRAFFIC_SCHEMA", "round_edges", "incast_depths",
           "inflight_audit", "documented_bound", "audit_schedule",
           "round_traffic", "conformance_sweep", "measured_overlay",
           "render_audit", "render_sweep", "pearson"]

TRAFFIC_SCHEMA = "traffic-v1"

# payload edge lists above this total are dropped from the JSON artifact
# (the per-round msgs/bytes/incast summaries always stay)
MAX_ARTIFACT_EDGES = 20_000


class TrafficError(ValueError):
    """A schedule/trace cannot be audited as asked (no op programs, no
    matching run, no per-round slices)."""


def _op_kinds():
    from tpu_aggcomm.core.schedule import OpKind
    return OpKind


# ---------------------------------------------------------------------------
# Matrix accounting

def round_edges(schedule) -> dict:
    """Per-round traffic of one compiled schedule.

    Returns ``{round: {"edges": {(src, dst): bytes}, "signals":
    {(src, dst): count}, "copies": {(src, dst): bytes}}}``. ``edges``
    are network payload messages (send-side ISEND/ISSEND/SEND with
    nbytes > 0 plus the send half of SENDRECV — MPI self-sends
    included); ``copies`` are COPY memcpys (payload that never crosses
    the wire); ``signals`` are 0-byte SIGNAL_SEND handshakes.

    Dense collectives (m=5/8) post ONE ALLTOALLW op per rank; their
    matrix is rebuilt from ``pattern.dense_counts()`` in round 0.
    Schedules with no rank op programs (the TAM relay) raise
    :class:`TrafficError`.
    """
    OpKind = _op_kinds()
    programs = getattr(schedule, "programs", None)
    if programs is None or getattr(schedule, "assignment", None) is not None:
        raise TrafficError(
            f"{getattr(schedule, 'name', schedule)}: hierarchical TAM "
            f"engine has no rank op programs to audit")
    out: dict[int, dict] = {}

    def cell(rnd):
        if rnd not in out:
            out[rnd] = {"edges": {}, "signals": {}, "copies": {}}
        return out[rnd]

    if getattr(schedule, "collective", False):
        # one dense vendor call: the whole pattern's matrix, round 0
        send, _recv = schedule.pattern.dense_counts()
        c = cell(0)
        n = schedule.pattern.nprocs
        for s in range(n):
            for d in range(n):
                b = int(send[s][d])
                if b:
                    c["edges"][(s, d)] = c["edges"].get((s, d), 0) + b
        return out

    for rank, prog in enumerate(programs):
        for op in prog:
            if (op.kind in (OpKind.ISEND, OpKind.ISSEND, OpKind.SEND)
                    and op.nbytes > 0):
                c = cell(op.round)["edges"]
                c[(rank, op.peer)] = c.get((rank, op.peer), 0) + op.nbytes
            elif op.kind is OpKind.SENDRECV and op.nbytes > 0:
                c = cell(op.round)["edges"]
                c[(rank, op.peer)] = c.get((rank, op.peer), 0) + op.nbytes
            elif op.kind is OpKind.COPY:
                c = cell(op.round)["copies"]
                b = schedule.pattern.data_size
                c[(rank, rank)] = c.get((rank, rank), 0) + b
            elif op.kind is OpKind.SIGNAL_SEND:
                c = cell(op.round)["signals"]
                c[(rank, op.peer)] = c.get((rank, op.peer), 0) + 1
    return out


def incast_depths(edges: dict) -> dict:
    """Per-destination distinct-source counts from one round's ``edges``
    dict — the fan-in each receiver must absorb in that round. COPY
    never appears here (it is a memcpy, not incast); MPI self-sends do.
    """
    by_dst: dict[int, set] = {}
    for (src, dst) in edges:
        by_dst.setdefault(dst, set()).add(src)
    return {dst: len(srcs) for dst, srcs in by_dst.items()}


def round_traffic(schedule) -> dict | None:
    """Compact per-round summary ``{str(round): {"msgs", "bytes",
    "max_incast"}}`` for the flight recorder's counter tracks.

    ``msgs``/``bytes`` cover the same payload universe as
    ``Schedule.data_edges()`` (network edges + COPY self-edges, so the
    bytes agree with the existing ``bytes_in_flight`` counter);
    ``max_incast`` is network-only. None when there is nothing to count.
    """
    try:
        per_round = round_edges(schedule)
    except TrafficError:
        return None
    out: dict[str, dict] = {}
    for rnd, c in sorted(per_round.items()):
        inc = incast_depths(c["edges"])
        out[str(rnd)] = {
            "msgs": len(c["edges"]) + len(c["copies"]),
            "bytes": sum(c["edges"].values()) + sum(c["copies"].values()),
            "max_incast": max(inc.values()) if inc else 0}
    return out


# ---------------------------------------------------------------------------
# Static in-flight accounting

def inflight_audit(schedule) -> list[dict]:
    """Simulate every rank's nonblocking post/WAITALL token lifetimes.

    A token goes live at its posting op (ISEND/ISSEND → send, IRECV →
    recv, SIGNAL_SEND → signal) and dies at the WAITALL that lists it;
    a token never waited stays live to the end (conservative). Blocking
    ops hold no token and do not count — the ``-c`` throttle governs
    *posted nonblocking requests* (mpi_test.c's request arrays).

    Returns one dict per rank: ``{"rank", "peak", "round", "sends",
    "recvs", "peak_signals"}`` where ``peak`` is the max simultaneous
    payload tokens (sends + recvs), ``round`` the round tag of the op
    at which that peak was first reached, and ``sends``/``recvs`` its
    split. Signal tokens are tracked apart (0-byte, unthrottled).
    """
    OpKind = _op_kinds()
    programs = getattr(schedule, "programs", None)
    if programs is None or getattr(schedule, "assignment", None) is not None:
        raise TrafficError(
            f"{getattr(schedule, 'name', schedule)}: hierarchical TAM "
            f"engine has no rank op programs to audit")
    out = []
    for rank, prog in enumerate(programs):
        live: dict[int, str] = {}
        nsend = nrecv = nsig = 0
        peak = 0
        peak_round = 0
        peak_parts = (0, 0)
        sig_peak = 0
        for op in prog:
            if op.kind is OpKind.WAITALL:
                for t in op.tokens:
                    cls = live.pop(t, None)
                    if cls == "send":
                        nsend -= 1
                    elif cls == "recv":
                        nrecv -= 1
                    elif cls == "signal":
                        nsig -= 1
                continue
            if op.token < 0:
                continue
            if op.kind in (OpKind.ISEND, OpKind.ISSEND):
                live[op.token] = "send"
                nsend += 1
            elif op.kind is OpKind.IRECV:
                live[op.token] = "recv"
                nrecv += 1
            elif op.kind is OpKind.SIGNAL_SEND:
                live[op.token] = "signal"
                nsig += 1
            else:
                continue
            sig_peak = max(sig_peak, nsig)
            cur = nsend + nrecv
            if cur > peak:
                peak = cur
                peak_round = op.round
                peak_parts = (nsend, nrecv)
        out.append({"rank": rank, "peak": peak, "round": peak_round,
                    "sends": peak_parts[0], "recvs": peak_parts[1],
                    "peak_signals": sig_peak})
    return out


def documented_bound(method_id: int, pattern) -> tuple[int | None, str]:
    """The per-method closed-form peak-in-flight bound the ``-c``
    throttle implies, as ``(bound, formula)``. ``None`` ⇒ EXEMPT (no
    rank op programs to audit: vendor collectives m=5/8, the TAM engine
    m=15/16).

    Derivation (w = min(c, n), c = comm_size, n = nprocs, cb = cb_nodes):
    fully blocking methods (6, 9, 10) post no nonblocking requests at
    all; m=7 throttles aggregator-*classes*, each of size ceil(n/cb);
    m=12 posts at most min(c, cb) sends per block with blocking recvs;
    m=11 posts at most w aggregator sends per round; the dead m=22
    ignores -c by construction (unthrottled m=2: n sends + cb recvs);
    every other rank-program method bounds per-round posts by w with at
    most cb requests carried across rounds (pre-posted sends / recvs).
    """
    n = pattern.nprocs
    cb = pattern.cb_nodes
    c = pattern.comm_size
    w = min(c, n)
    if method_id in (5, 8, 15, 16):
        return None, "no rank op programs"
    if method_id in (6, 9, 10):
        return 0, "0 (fully blocking)"
    if method_id == 7:
        return min(c, cb) * math.ceil(n / cb), "min(c,cb)*ceil(n/cb)"
    if method_id == 12:
        return min(c, cb), "min(c,cb)"
    if method_id == 11:
        return w, "min(c,n)"
    if method_id == 22:
        return n + cb, "n+cb (ignores -c by construction)"
    return w + cb, "min(c,n)+cb"


# ---------------------------------------------------------------------------
# The audit artifact (traffic-v1)

def audit_schedule(schedule, max_edges: int = MAX_ARTIFACT_EDGES) -> dict:
    """Full static audit of one compiled schedule → a traffic-v1 dict.

    Combines the per-round matrix, incast depths, barrier signature and
    the in-flight conformance verdict. Never touches a backend or a
    measured callback; ``obs.regress.validate_traffic`` pins the shape.
    """
    from tpu_aggcomm.core.schedule import barrier_rounds_of

    p = schedule.pattern
    cfg = {"method": schedule.method_id, "name": schedule.name,
           "nprocs": p.nprocs, "cb_nodes": p.cb_nodes,
           "data_size": p.data_size, "comm_size": p.comm_size,
           "proc_node": p.proc_node, "agg_type": int(p.placement),
           "direction": p.direction.value}
    if getattr(schedule, "fault", None):
        # fault-repaired schedule: the audit covers the DETOURED program
        # (relay hops included) — the artifact must say so
        cfg["fault"] = schedule.fault
    base = {"schema": TRAFFIC_SCHEMA, "config": cfg}

    if getattr(schedule, "assignment", None) is not None:
        base.update({
            "rounds": [], "edges_omitted": False, "barrier_rounds": {},
            "totals": {"msgs": 0, "bytes": 0, "signals": 0, "copies": 0},
            "conformance": {
                "verdict": "EXEMPT", "bound": None,
                "bound_formula": "no rank op programs",
                "peak": None, "offenders": [],
                "note": "hierarchical TAM engine: traffic rides mesh "
                        "collectives, no rank op programs to audit"}})
        return base

    per_round = round_edges(schedule)
    bound, formula = documented_bound(schedule.method_id, p)

    rounds = []
    tot_msgs = tot_bytes = tot_sig = tot_cp = 0
    n_edges = sum(len(c["edges"]) + len(c["copies"])
                  for c in per_round.values())
    omit = n_edges > max_edges
    for rnd, c in sorted(per_round.items()):
        inc = incast_depths(c["edges"])
        msgs = len(c["edges"])
        byts = sum(c["edges"].values()) + sum(c["copies"].values())
        sigs = sum(c["signals"].values())
        max_inc = max(inc.values()) if inc else 0
        inc_rank = (min(d for d, v in inc.items() if v == max_inc)
                    if inc else -1)
        row = {"round": rnd, "msgs": msgs, "bytes": byts,
               "signals": sigs, "copies": len(c["copies"]),
               "max_incast": max_inc, "incast_rank": inc_rank,
               "incast": {str(d): v for d, v in sorted(inc.items())}}
        if not omit:
            row["edges"] = [[s, d, b]
                            for (s, d), b in sorted(c["edges"].items())]
        rounds.append(row)
        tot_msgs += msgs
        tot_bytes += byts
        tot_sig += sigs
        tot_cp += len(c["copies"])

    if getattr(schedule, "collective", False):
        conf = {"verdict": "EXEMPT", "bound": None,
                "bound_formula": "no rank op programs",
                "peak": None, "offenders": [],
                "note": "dense vendor collective: the library schedules "
                        "in-flight messages, not the rank programs"}
    else:
        ranks = inflight_audit(schedule)
        peak_row = max(ranks, key=lambda r: r["peak"])
        offenders = sorted(
            ({"rank": r["rank"], "round": r["round"], "count": r["peak"]}
             for r in ranks if r["peak"] > bound),
            key=lambda o: -o["count"])[:10]
        verdict = "REFUTED" if offenders else "CONFORMS"
        note = (f"peak {peak_row['peak']} outstanding payload requests "
                f"({peak_row['sends']} sends + {peak_row['recvs']} recvs) "
                f"at rank {peak_row['rank']} round {peak_row['round']}; "
                f"signal peak "
                f"{max(r['peak_signals'] for r in ranks)}")
        conf = {"verdict": verdict, "bound": bound,
                "bound_formula": formula, "peak": peak_row["peak"],
                "peak_rank": peak_row["rank"],
                "peak_round": peak_row["round"],
                "peak_sends": peak_row["sends"],
                "peak_recvs": peak_row["recvs"],
                "peak_signals": max(r["peak_signals"] for r in ranks),
                "offenders": offenders, "note": note}

    base.update({
        "rounds": rounds, "edges_omitted": omit,
        "barrier_rounds": {str(k): v for k, v
                           in sorted(barrier_rounds_of(schedule).items())},
        "totals": {"msgs": tot_msgs, "bytes": tot_bytes,
                   "signals": tot_sig, "copies": tot_cp},
        "conformance": conf})
    return base


def conformance_sweep(nprocs: int, cb_nodes: int, comm_size: int,
                      data_size: int = 2048, proc_node: int = 1,
                      agg_type: int = 1, include_dead: bool = True) -> list:
    """Audit every method in METHODS at one shape — the jax-free static
    gate. Returns one row per method: ``{"method", "name", "verdict",
    "peak", "bound", "bound_formula"}``."""
    from tpu_aggcomm.core.methods import METHODS, compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern

    p = AggregatorPattern(nprocs=nprocs, cb_nodes=cb_nodes,
                          data_size=data_size, placement=agg_type,
                          proc_node=proc_node, comm_size=comm_size)
    rows = []
    for mid in sorted(METHODS):
        if not include_dead and not METHODS[mid].dispatched:
            continue
        sched = compile_method(mid, p)
        audit = audit_schedule(sched, max_edges=0)
        conf = audit["conformance"]
        rows.append({"method": mid, "name": METHODS[mid].name,
                     "verdict": conf["verdict"], "peak": conf["peak"],
                     "bound": conf["bound"],
                     "bound_formula": conf["bound_formula"],
                     "offenders": conf["offenders"]})
    return rows


# ---------------------------------------------------------------------------
# Measured overlay (trace join)

def pearson(xs, ys) -> float | None:
    """Pearson correlation of two equal-length vectors; None when
    either side is constant or fewer than two points."""
    n = len(xs)
    if n < 2 or n != len(ys):
        return None
    mx = sum(xs) / n
    my = sum(ys) / n
    sxx = sum((x - mx) ** 2 for x in xs)
    syy = sum((y - my) ** 2 for y in ys)
    if sxx == 0.0 or syy == 0.0:
        return None
    sxy = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
    return sxy / math.sqrt(sxx * syy)


def _find_run(events: list, cfg: dict, run_id=None) -> dict:
    runs = [e for e in events if e.get("ev") == "run"]
    if run_id is not None:
        for r in runs:
            if r["id"] == run_id:
                return r
        raise TrafficError(f"no run {run_id} in trace")
    for r in runs:
        if (r.get("method") == cfg["method"]
                and r.get("nprocs") == cfg["nprocs"]
                and r.get("data_size") == cfg["data_size"]
                and r.get("comm_size") == cfg["comm_size"]):
            return r
    raise TrafficError(
        f"trace has no run matching m={cfg['method']} n={cfg['nprocs']} "
        f"d={cfg['data_size']} c={cfg['comm_size']} "
        f"(runs: {[(r.get('method'), r.get('nprocs')) for r in runs]})")


def measured_overlay(audit: dict, events: list, run_id=None) -> dict:
    """Join a static audit with one traced run's round walls.

    Round walls come from ``obs.metrics.round_stats`` VERBATIM (the same
    mean-across-reps, max-over-ranks arithmetic the straggler summary
    prints), so the overlay's times match the trace float-exactly.
    ``eff_bps = bytes / wall``; ``frac_roofline =
    floor_seconds(bytes) / wall`` (HBM floor from harness/roofline.py —
    floor/wall, i.e. achieved fraction of the roofline rate).

    Also reports the incast-vs-straggler join: Pearson correlation of
    per-rank received bytes (static, all rounds) against per-rank total
    seconds (``aggregate_run``), plus the max-incast vs critical rank.
    """
    from tpu_aggcomm.harness.roofline import floor_seconds
    from tpu_aggcomm.obs.metrics import critical_path, round_stats
    from tpu_aggcomm.obs.trace import aggregate_run

    run = _find_run(events, audit["config"], run_id)
    rid = run["id"]
    stats = {s["round"]: s for s in round_stats(events, rid)
             if isinstance(s["round"], int) and s["round"] >= 0}
    rows = []
    for r in audit["rounds"]:
        s = stats.get(r["round"])
        if s is None or s["wall"] <= 0.0:
            continue
        wall = s["wall"]
        rows.append({"round": r["round"], "bytes": r["bytes"],
                     "wall_s": wall, "eff_bps": r["bytes"] / wall,
                     "frac_roofline": floor_seconds(r["bytes"]) / wall})
    note = None
    if not rows:
        note = ("trace carries no per-round slices for this run "
                "(whole-rep envelopes only); overlay limited to totals")

    # per-rank received bytes (network edges, all rounds) vs rank totals
    n = audit["config"]["nprocs"]
    recv_bytes = [0] * n
    for r in audit["rounds"]:
        for e in r.get("edges", []):
            recv_bytes[e[1]] += e[2]
    agg = aggregate_run(events, rid)
    # the "total" column is the shared rep envelope (identical across
    # ranks on fused programs) — the straggler signal lives in the
    # per-rank attributed phase columns
    totals = ([agg[r]["post"] + agg[r]["send_wait"]
               + agg[r]["recv_wait"] + agg[r]["barrier"]
               for r in range(n)]
              if set(agg) >= set(range(n)) else [])
    corr = (pearson(recv_bytes, totals)
            if len(totals) == n and not audit["edges_omitted"] else None)
    inc_peak = max(audit["rounds"], key=lambda r: r["max_incast"],
                   default=None)
    crit = critical_path(events, rid)
    out = {"run": rid, "backend": run.get("executed"),
           "rounds": rows,
           "incast_straggler": {
               "pearson_recv_bytes_vs_total_s": corr,
               "max_incast_rank": (inc_peak["incast_rank"]
                                   if inc_peak else None),
               "critical_rank": crit.get("rank") if crit else None}}
    if note:
        out["note"] = note
    return out


# ---------------------------------------------------------------------------
# Renderers

def _fmt_srcs(srcs: list) -> str:
    if len(srcs) <= 8:
        return ",".join(str(s) for s in srcs)
    return (",".join(str(s) for s in srcs[:8])
            + f",... ({len(srcs)} sources)")


def render_audit(audit: dict, overlay: dict | None = None,
                 max_dst_rows: int = 48) -> str:
    """Text report: per-round matrix (grouped by destination — the
    incast view), totals, barrier signature, conformance verdict, and
    the measured columns when an overlay is given."""
    cfg = audit["config"]
    head0 = (f"traffic audit: m={cfg['method']} \"{cfg['name']}\" "
             f"({cfg['direction']}) n={cfg['nprocs']} a={cfg['cb_nodes']} "
             f"c={cfg['comm_size']} d={cfg['data_size']} B")
    if cfg.get("fault"):
        head0 += f" [fault-repaired: {cfg['fault']}]"
    lines = [head0]
    ov_rounds = ({r["round"]: r for r in overlay["rounds"]}
                 if overlay else {})
    for r in audit["rounds"]:
        head = (f"  round {r['round']:3d}: {r['msgs']:5d} msgs, "
                f"{r['bytes']:10d} B, {r['signals']:4d} signals, "
                f"max incast {r['max_incast']:3d}")
        if r["max_incast"]:
            head += f" @ rank {r['incast_rank']}"
        ov = ov_rounds.get(r["round"])
        if ov is not None:
            head += (f" | wall {ov['wall_s'] * 1e6:10.1f} us, "
                     f"eff {ov['eff_bps'] / 1e9:8.3f} GB/s, "
                     f"{ov['frac_roofline'] * 100:6.2f}% of roofline")
        lines.append(head)
        by_dst: dict[int, list] = {}
        for e in r.get("edges", []):
            by_dst.setdefault(e[1], []).append(e)
        for i, dst in enumerate(sorted(by_dst)):
            if i >= max_dst_rows:
                lines.append(f"    ... ({len(by_dst) - max_dst_rows} "
                             f"more destinations)")
                break
            es = by_dst[dst]
            b = sum(e[2] for e in es)
            lines.append(f"    dst {dst:4d} <- "
                         f"{_fmt_srcs(sorted(e[0] for e in es))} "
                         f"({len(es)} x msg, {b} B)")
        if r.get("copies"):
            lines.append(f"    + {r['copies']} local copy(ies) "
                         f"(memcpy, not on the wire)")
    if audit.get("edges_omitted"):
        lines.append("  (edge lists omitted: too many edges; "
                     "per-round summaries above are complete)")
    t = audit["totals"]
    lines.append(f"totals: {t['msgs']} msgs, {t['bytes']} B, "
                 f"{t['signals']} signals, {t['copies']} copies over "
                 f"{len(audit['rounds'])} rounds")
    if audit["barrier_rounds"]:
        sig = ", ".join(f"r{k}: {v}"
                        for k, v in audit["barrier_rounds"].items())
        lines.append(f"barriers: {sig}")
    conf = audit["conformance"]
    if conf["verdict"] == "EXEMPT":
        lines.append(f"conformance: EXEMPT — {conf['note']}")
    else:
        lines.append(f"in-flight accounting: {conf['note']}")
        tail = (f"peak {conf['peak']} <= bound {conf['bound']} "
                f"({conf['bound_formula']})")
        if conf["verdict"] == "CONFORMS":
            lines.append(f"conformance: CONFORMS — {tail}")
        else:
            lines.append(f"conformance: REFUTED — peak {conf['peak']} > "
                         f"bound {conf['bound']} "
                         f"({conf['bound_formula']}); offenders:")
            for o in conf["offenders"]:
                lines.append(f"  rank {o['rank']:4d} round {o['round']:3d}: "
                             f"{o['count']} outstanding")
    if overlay is not None:
        isj = overlay["incast_straggler"]
        corr = isj["pearson_recv_bytes_vs_total_s"]
        corr_s = f"{corr:+.3f}" if corr is not None else "n/a"
        lines.append(f"incast vs straggler: pearson(recv bytes, total s) "
                     f"= {corr_s}; max-incast rank "
                     f"{isj['max_incast_rank']}, critical rank "
                     f"{isj['critical_rank']}")
        if overlay.get("note"):
            lines.append(f"overlay note: {overlay['note']}")
    return "\n".join(lines) + "\n"


def render_sweep(rows: list, nprocs: int, cb_nodes: int,
                 comm_size: int) -> str:
    lines = [f"conformance sweep: {len(rows)} methods at n={nprocs} "
             f"a={cb_nodes} c={comm_size}"]
    n_ref = 0
    for r in rows:
        if r["verdict"] == "EXEMPT":
            lines.append(f"  m={r['method']:2d} {r['name']:34s} EXEMPT    "
                         f"({r['bound_formula']})")
        elif r["verdict"] == "CONFORMS":
            lines.append(f"  m={r['method']:2d} {r['name']:34s} CONFORMS  "
                         f"peak {r['peak']:4d} <= {r['bound']:4d} "
                         f"({r['bound_formula']})")
        else:
            n_ref += 1
            o = r["offenders"][0] if r["offenders"] else {}
            lines.append(f"  m={r['method']:2d} {r['name']:34s} REFUTED   "
                         f"peak {r['peak']:4d} >  {r['bound']:4d} "
                         f"({r['bound_formula']}) — rank {o.get('rank')} "
                         f"round {o.get('round')}: {o.get('count')} "
                         f"outstanding")
    lines.append(f"REFUTED: {n_ref} of {len(rows)}")
    return "\n".join(lines) + "\n"


def write_artifact(path: str, audit: dict,
                   overlay: dict | None = None) -> str:
    """Write a traffic-v1 JSON artifact (schema-checked by
    ``scripts/check_bench_schema.py`` when committed as TRAFFIC_*.json)."""
    from tpu_aggcomm.obs.atomic import atomic_write
    blob = dict(audit)
    if overlay is not None:
        blob["overlay"] = overlay
    with atomic_write(path) as fh:
        json.dump(blob, fh, indent=1, sort_keys=False)
        fh.write("\n")
    return path
