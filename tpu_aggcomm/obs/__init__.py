"""Flight recorder: per-round structured tracing for every backend.

The observability subsystem of the framework (ISSUE 1):

- :mod:`tpu_aggcomm.obs.trace` — the span/counter recorder plus the
  reconstruction of per-rank per-round slices from the attribution
  machinery (harness/attribution.py cell sink); JSONL event log;
  round/rank critical-path summary (``cli inspect trace``).
- :mod:`tpu_aggcomm.obs.perfetto` — Chrome/Perfetto ``trace.json``
  export (one track per logical rank, one slice per throttle round,
  counter track for bytes in flight).
- :mod:`tpu_aggcomm.obs.regress` — BENCH_r*.json / MULTICHIP_r*.json
  schema validation and round-over-round regression checking with a
  bootstrap statistical gate over per-trial samples
  (``bench.py --check-regression``).
- :mod:`tpu_aggcomm.obs.metrics` — straggler analytics: per-round
  p50/p95/max/skew/imbalance over ranks, critical-path attribution to
  (rank, round, phase) with PHASE_SOURCES provenance, and the seeded
  bootstrap/sign-test statistical kernel (``cli inspect trace``).
- :mod:`tpu_aggcomm.obs.compare` — trace diffing: per-cell deltas
  between two recordings or two sweep-trace directories
  (``cli inspect compare``).
- :mod:`tpu_aggcomm.obs.report_html` — self-contained static HTML
  dashboard over the bench history and trace files
  (``cli inspect report``).
- :mod:`tpu_aggcomm.obs.ledger` — run ledger (ISSUE 3): environment
  manifest (versions, git sha, scrubbed env, device identity, tunnel
  RPC probe), per-method compile/first-dispatch telemetry, HBM peak,
  opt-in device-profiler cross-check (``--xprof``), and manifest drift
  detection across artifacts (``cli inspect ledger``).
- :mod:`tpu_aggcomm.obs.export` — live telemetry (ISSUE 8): log-bucketed
  latency histograms with exact quantile reconstruction, OpenMetrics
  text rendering, and the env/flag-gated stdlib ``/metrics`` endpoint
  (``sweep --metrics-port`` / ``TPU_AGGCOMM_METRICS_PORT``; OFF by
  default, never imported unless armed).
- :mod:`tpu_aggcomm.obs.live` — attachable sweep monitor: tails the
  crash-safe resilience journal + trace JSONL of a sweep running in
  another process, torn-line tolerant (``cli inspect live``).
- :mod:`tpu_aggcomm.obs.history` — longitudinal history store: unified
  artifact discovery (BENCH/MULTICHIP/TUNE/TRAFFIC/traces), per-(metric,
  platform) time series, and the seeded multi-round trend gate
  (``cli inspect history``; feeds ``bench.py --check-regression``).

Tracing is OFF by default and zero-cost when off: ``trace.span(...)``
returns a shared no-op context manager, and nothing here imports jax, so
importing the package never changes bench.py's output.
"""

from tpu_aggcomm.obs.atomic import atomic_write
from tpu_aggcomm.obs.trace import (TraceRecorder, current, disable, enable,
                                   enabled, flush, instant, span)

__all__ = ["TraceRecorder", "atomic_write", "current", "disable", "enable",
           "enabled", "flush", "instant", "span"]
