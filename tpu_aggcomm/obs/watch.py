"""Watchtower: streaming SLO engine + anomaly root-cause attribution.

Every prior observability surface judges COMMITTED artifacts after the
fact (regression gate, trend gate, replay gates). This module watches
the serve layer's LIVE streams — the crash-safe request journal
(resilience/journal.py) and the flight-recorder trace JSONL — and says,
continuously and by name, whether traffic is inside its SLOs and *why*
it is not:

- **tail** — torn-line-tolerant reads (the obs/live.py discipline; a
  writer may be mid-append at any moment), with every skipped line and
  every admitted-but-unterminated request COUNTED and named, never
  silently absorbed.
- **evaluate** — a declarative slo-v1 spec (obs/slo.py) judged over
  tumbling request-count windows as error-budget burn rates;
  :func:`measure_window` is THE one window arithmetic, shared by this
  evaluator, the server's live gauges (:class:`LiveSlo`) and the
  telemetry gate, so the numbers cannot drift apart.
- **detect** — a seeded-bootstrap changepoint scan over per-request
  walls and per-run round walls (:func:`detect_changepoint`): the same
  double gate as the regression/trend verdicts (point jump beyond
  tolerance AND bootstrap CI excluding zero), same seed discipline —
  same streams in ⟹ same anomalies out, byte-for-byte.
- **attribute** — each anomaly is joined against evidence the repo
  already records, in a fixed order, and the verdict NAMES its
  evidence stream: cache-eviction/compile-storm (``ledger``: journal
  cache dispositions + manifest drift between session headers),
  tunnel-degradation (``resilience``: degraded-state records + retry
  attempts), shed-cascade (``shed``: serve-v2 shed reasons), incast/
  bandwidth/fence-bound (``explain``: cost-model verdicts over the
  trace), else ``UNEXPLAINED`` with the residual quantified. A bare
  "ANOMALY" is a regression by contract.

``WATCH_r*.json`` (watch-v1) embeds the SLO spec, the per-request rows
and the evidence blocks, is written atomically, schema-validated by
``obs.regress.validate_watch`` (an artifact its own rows contradict is
invalid), discovered by ``obs.history.load_history``, and replays to
REPRODUCED from the recorded stream basenames alone
(:func:`replay_watch`). Everything is ADVISORY (the resilience/
detect.py pattern): verdicts name suspects for a later actuator,
nothing here changes what runs.

jax-free throughout (obs discipline; the ``explain`` join uses only the
jax-free tpu_aggcomm/model package): the watchtower must answer
precisely where a wedged tunnel hangs ``import jax``.
"""

from __future__ import annotations

import json
import os
import random
import statistics
import time

from tpu_aggcomm.obs.atomic import atomic_write
from tpu_aggcomm.obs.metrics import percentile
from tpu_aggcomm.obs.slo import (DEFAULT_SLO, burn_rate, objective_budget,
                                 validate_slo)
from tpu_aggcomm.obs.workload import BOUNDARIES, attribute_phases

__all__ = ["WATCH_SCHEMA", "EVIDENCE_STREAMS", "tail_journal",
           "measure_window", "evaluate_slo", "detect_changepoint",
           "attribute_anomaly", "watch_streams", "write_watch",
           "replay_watch", "render_watch", "watch_registry", "LiveSlo"]

WATCH_SCHEMA = "watch-v1"

#: Every evidence stream an attribution verdict may cite. "none" is the
#: UNEXPLAINED residual — still a named verdict, never a bare anomaly.
#: "flow" is the causal-flow join (obs/flow.py): a committed FLOW_r*
#: artifact's per-request dominant-component verdicts, consulted when a
#: request-wall step coincides with a dominant-component shift.
EVIDENCE_STREAMS = ("ledger", "resilience", "shed", "explain", "flow",
                    "none")

# -- detection constants (the trend-gate discipline: conservative,
# seeded, documented) -------------------------------------------------------
#: Fewest samples on each side of a candidate changepoint.
MIN_SEGMENT = 4
#: Relative step (fraction of the stream median) that counts as an
#: anomaly when the bootstrap CI confirms it.
CHANGE_TOLERANCE = 0.25
#: Bootstrap resamples for the changepoint CI (seeded).
N_BOOT = 800
#: Cache-miss-fraction rise (after minus before) that implicates the
#: compiled-chain cache.
MISS_RISE = 0.25
#: Shed-fraction rise that implicates a shed cascade.
SHED_RISE = 0.10
#: Mean cache-phase-seconds ratio (after/before) that implicates a
#: compile storm even when the miss fraction held steady.
COMPILE_RATIO = 1.5


# ---------------------------------------------------------------------------
# Tailing (torn lines and lost requests are COUNTED, never absorbed).

def tail_journal(path: str) -> dict:
    """Torn-line-tolerant serve-journal tail that counts what it skips.

    Unlike ``resilience.journal.RunJournal._scan`` (which silently
    skips unparseable lines by contract), a watchtower must surface the
    skip count — a torn tail is normal, but an unseen one hides lost
    work. Returns ``{"sessions": [{"fingerprint", "manifest"}...],
    "records": [...], "skipped_lines": int}``."""
    sessions: list[dict] = []
    records: list[dict] = []
    skipped = 0
    try:
        fh = open(path)
    except OSError:
        return {"sessions": sessions, "records": records,
                "skipped_lines": 0}
    with fh:
        for line in fh:
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if not isinstance(rec, dict):
                skipped += 1
                continue
            if "journal" in rec and "fingerprint" in rec:
                sessions.append({"fingerprint": rec.get("fingerprint"),
                                 "manifest": rec.get("manifest")})
            elif "key" in rec:
                records.append(rec)
            else:
                skipped += 1
    return {"sessions": sessions, "records": records,
            "skipped_lines": skipped}


def _tail_trace(path: str) -> tuple[list[dict], int]:
    """Torn-tolerant trace tail (obs/live.tail_events semantics) that
    also counts the skipped lines."""
    events: list[dict] = []
    skipped = 0
    try:
        fh = open(path)
    except OSError:
        return events, 0
    with fh:
        for line in fh:
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if isinstance(rec, dict) and "ev" in rec:
                events.append(rec)
            else:
                skipped += 1
    return events, skipped


def _scan_requests(journal_paths) -> dict:
    """Per-request rows + lifecycle/evidence records from the serve
    journal(s) — the obs/workload.py join (admitted + terminal), kept
    to the fields the SLO evaluator and the attribution checks consume.
    ``wall_s`` is the canonical phase-duration sum via the SAME
    ``attribute_phases`` arithmetic the workload profiler uses."""
    admitted: dict = {}
    terminal: dict = {}
    sessions: list[dict] = []
    states: list[dict] = []
    drain = None
    problems: list[str] = []
    skipped = 0
    for path in journal_paths:
        tail = tail_journal(path)
        skipped += tail["skipped_lines"]
        sessions.extend(tail["sessions"])
        for rec in tail["records"]:
            key = rec.get("key") or {}
            rid = key.get("request")
            if rid is not None:
                status = rec.get("status")
                if status == "admitted":
                    admitted.setdefault(rid, rec)
                elif status in ("done", "fail", "shed"):
                    terminal.setdefault(rid, rec)
                continue
            if rec.get("status") == "state":
                states.append({"state": rec.get("state"),
                               "prev": rec.get("prev"),
                               "reason": rec.get("reason")})
            elif rec.get("status") == "drain":
                drain = {k: rec.get(k) for k in
                         ("completed", "failed", "shed", "lost")}

    rows: list[dict] = []
    counts = {"done": 0, "fail": 0, "shed": 0}
    lost: list = []
    for rid in sorted(set(admitted) | set(terminal)):
        adm = admitted.get(rid)
        term = terminal.get(rid)
        status = term.get("status") if term is not None else "lost"
        if term is None:
            lost.append(rid)
        else:
            counts[status] += 1
        phases: dict = {}
        wall = None
        if term is not None and "phases" in term:
            phases, pp = attribute_phases(term.get("phases"))
            for p in pp:
                problems.append(f"request {rid}: {p}")
            vals = [phases[b] for b in BOUNDARIES if b in phases]
            wall = sum(vals) if vals else None
        batch = None
        if term is not None and term.get("batch_seq") is not None:
            batch = {"seq": term["batch_seq"], "n": term.get("batch_n"),
                     "padded": term.get("batch_padded")}
        rows.append({
            "rid": rid, "status": status,
            "wall_s": wall, "phases": phases,
            "cache": (term or {}).get("cache"),
            "shed_reason": (term or {}).get("reason")
            if status == "shed" else None,
            "deadline_ms": (adm or {}).get("deadline_ms"),
            "arrival_unix": (adm or {}).get("t_unix"),
            "batch": batch,
        })
    return {"rows": rows, "sessions": sessions, "states": states,
            "drain": drain, "problems": problems,
            "skipped_lines": skipped,
            "requests": {"admitted": len(admitted),
                         "completed": counts["done"],
                         "failed": counts["fail"],
                         "shed": counts["shed"],
                         "lost": lost}}


# ---------------------------------------------------------------------------
# SLO evaluation (obs/slo.py specs over request rows).

def _deadline_missed(r: dict) -> bool:
    dl = r.get("deadline_ms")
    if not isinstance(dl, (int, float)) or isinstance(dl, bool):
        return False
    if r.get("status") == "shed" and "deadline" in str(
            r.get("shed_reason") or ""):
        return True
    w = r.get("wall_s")
    return isinstance(w, (int, float)) and w > dl / 1e3


def measure_window(rows: list[dict], obj: dict) -> dict:
    """THE one per-window SLI/burn arithmetic — the evaluator, the
    server's live gauges (:class:`LiveSlo`) and the telemetry gate all
    call this, so exported numbers equal artifact numbers float-exactly
    (identical computation, the obs/workload ``padded_slots``
    precedent). Returns ``{"n", "sli", "bad", "total", "burn",
    "compliant"}``; a vacuous window (no qualifying events) has burn
    ``None`` and compliant ``None`` — absence of evidence is not a
    violation."""
    kind = obj["kind"]
    budget = objective_budget(obj)
    bad = total = 0
    sli = None
    if kind == "warm-latency":
        walls = [r["wall_s"] for r in rows
                 if r.get("status") == "done" and r.get("cache") == "hit"
                 and isinstance(r.get("wall_s"), (int, float))]
        total = len(walls)
        bad = sum(1 for w in walls if w > obj["threshold_s"])
        sli = percentile(walls, 50.0) if walls else None
    elif kind == "goodput":
        total = len(rows)
        bad = sum(1 for r in rows if r.get("status") != "done")
        sli = (total - bad) / total if total else None
    elif kind == "shed-rate":
        total = len(rows)
        bad = sum(1 for r in rows if r.get("status") == "shed")
        sli = bad / total if total else None
    elif kind == "deadline-miss":
        scoped = [r for r in rows
                  if isinstance(r.get("deadline_ms"), (int, float))
                  and not isinstance(r.get("deadline_ms"), bool)]
        total = len(scoped)
        bad = sum(1 for r in scoped if _deadline_missed(r))
        sli = bad / total if total else None
    elif kind == "padding-waste":
        seen: dict = {}
        for r in rows:
            b = r.get("batch")
            if isinstance(b, dict) and b.get("padded") is not None:
                seen[b["seq"]] = (b.get("n") or 0, b["padded"])
        total = sum(p for _n, p in seen.values())
        bad = sum(p - n for n, p in seen.values())
        sli = (total - bad) / total if total else None
    else:
        raise ValueError(f"unknown SLO objective kind {kind!r}")
    burn = burn_rate(bad, total, budget)
    return {"n": len(rows), "sli": sli, "bad": bad, "total": total,
            "burn": burn,
            "compliant": None if burn is None else burn <= 1.0}


def evaluate_slo(rows: list[dict], slo: dict) -> dict:
    """The whole spec over the whole stream: tumbling request-count
    windows per window spec (the final partial window included — the
    live tail is exactly the window a watcher cares about) plus one
    whole-stream "overall" measurement per objective."""
    objectives = []
    for obj in slo["objectives"]:
        windows: dict = {}
        for w in slo["windows"]:
            size = w["requests"]
            entries = []
            for lo in range(0, max(len(rows), 1), size):
                chunk = rows[lo:lo + size]
                if not chunk:
                    continue
                e = measure_window(chunk, obj)
                e["start_rid"] = chunk[0]["rid"]
                e["end_rid"] = chunk[-1]["rid"]
                entries.append(e)
            windows[w["name"]] = entries
        overall = measure_window(rows, obj)
        burns = [e["burn"] for es in windows.values() for e in es
                 if e["burn"] is not None]
        if overall["burn"] is not None:
            burns.append(overall["burn"])
        out = {"name": obj["name"], "kind": obj["kind"],
               "target": obj["target"], "budget": objective_budget(obj),
               "windows": windows, "overall": overall,
               "worst_burn": max(burns) if burns else None,
               "compliant": all(b <= 1.0 for b in burns)}
        if "threshold_s" in obj:
            out["threshold_s"] = obj["threshold_s"]
        objectives.append(out)
    return {"objectives": objectives,
            "compliant": all(o["compliant"] for o in objectives)}


# ---------------------------------------------------------------------------
# Seeded changepoint detection.

def detect_changepoint(values, *, seed: int = 0,
                       tolerance: float = CHANGE_TOLERANCE,
                       n_boot: int = N_BOOT,
                       min_segment: int = MIN_SEGMENT) -> dict | None:
    """The strongest mean-shift in one series, confirmed or discarded.

    Scans every split with >= ``min_segment`` samples a side for the
    largest mean step relative to the series median, then confirms it
    with a seeded within-segment bootstrap: anomaly only when the point
    step exceeds ``tolerance`` AND the 95% CI excludes zero — the same
    double gate as the regression and trend verdicts, same determinism
    contract (same values + seed ⟹ same verdict byte-for-byte).
    Returns ``None`` (no confirmed changepoint) or the detection dict
    (split index, segment means, relative step, CI, direction)."""
    vals = [float(v) for v in values]
    n = len(vals)
    if n < 2 * min_segment:
        return None
    med = statistics.median(vals)
    if med == 0:
        return None
    best_k, best_rel = None, 0.0
    for k in range(min_segment, n - min_segment + 1):
        before = vals[:k]
        after = vals[k:]
        rel = (sum(after) / len(after) - sum(before) / len(before)) \
            / abs(med)
        if best_k is None or abs(rel) > abs(best_rel):
            best_k, best_rel = k, rel
    if best_k is None or abs(best_rel) <= tolerance:
        return None
    before, after = vals[:best_k], vals[best_k:]
    rng = random.Random(seed)
    boots: list[float] = []
    for _ in range(n_boot):
        b = [before[rng.randrange(len(before))] for _ in before]
        a = [after[rng.randrange(len(after))] for _ in after]
        boots.append((sum(a) / len(a) - sum(b) / len(b)) / abs(med))
    boots.sort()
    lo = percentile(boots, 2.5)
    hi = percentile(boots, 97.5)
    if not (lo > 0 or hi < 0):
        return None
    return {"index": best_k, "n": n,
            "before_mean": sum(before) / len(before),
            "after_mean": sum(after) / len(after),
            "delta_rel": best_rel, "ci_rel": [lo, hi],
            "direction": "up" if best_rel > 0 else "down",
            "tolerance": tolerance, "seed": seed}


# ---------------------------------------------------------------------------
# Root-cause attribution (every verdict names its evidence stream).

def _cache_phase_mean(rows: list[dict]) -> float | None:
    vals = [r["phases"]["cache"] for r in rows
            if r.get("status") == "done"
            and isinstance(r.get("phases"), dict)
            and isinstance(r["phases"].get("cache"), (int, float))]
    return sum(vals) / len(vals) if vals else None


def _frac(rows: list[dict], pred) -> float | None:
    return sum(1 for r in rows if pred(r)) / len(rows) if rows else None


def attribute_anomaly(detection: dict, *, rows: list[dict],
                      evidence: dict, split_rid=None,
                      explain_rounds: list[dict] | None = None) -> dict:
    """One NAMED root-cause verdict for one confirmed changepoint.

    Evidence is consulted in a fixed order (ledger → resilience → shed
    → explain → flow), each check derived from blob-representable inputs
    only,
    so ``validate_watch`` re-runs this exact function over a committed
    artifact's own rows + evidence blocks and refuses a verdict they
    contradict. The fallback is ``UNEXPLAINED`` with the residual step
    quantified — never a bare anomaly."""
    before = after = None
    if split_rid is not None:
        before = [r for r in rows if r["rid"] < split_rid]
        after = [r for r in rows if r["rid"] >= split_rid]

    # -- ledger: manifest drift + cache dispositions -----------------------
    drift = [d for s in evidence.get("sessions", [])
             for d in (s.get("drift") or [])]
    if drift:
        return {"cause": "cache-eviction/compile-storm",
                "evidence": "ledger",
                "detail": ("manifest drift across journal sessions "
                           "forces compiled-chain re-keying: "
                           + "; ".join(drift[:4]))}
    if after is not None:
        evicts = sum(1 for r in after if r.get("cache") == "evict")
        if evicts:
            return {"cause": "cache-eviction/compile-storm",
                    "evidence": "ledger",
                    "detail": (f"{evicts} cache eviction(s) among the "
                               f"{len(after)} requests after the step "
                               f"(journal cache dispositions)")}
        is_miss = lambda r: r.get("cache") in ("miss", "evict")
        mb, ma = _frac(before, is_miss), _frac(after, is_miss)
        if mb is not None and ma is not None and ma - mb > MISS_RISE:
            return {"cause": "cache-eviction/compile-storm",
                    "evidence": "ledger",
                    "detail": (f"cache-miss fraction rose "
                               f"{mb:.0%} -> {ma:.0%} across the step "
                               f"(journal cache dispositions)")}
        cb, ca = _cache_phase_mean(before), _cache_phase_mean(after)
        if cb is not None and ca is not None and cb > 0 \
                and ca / cb > COMPILE_RATIO \
                and any(is_miss(r) for r in after):
            return {"cause": "cache-eviction/compile-storm",
                    "evidence": "ledger",
                    "detail": (f"mean cache-phase wall rose "
                               f"{cb * 1e3:.1f} ms -> {ca * 1e3:.1f} ms "
                               f"({ca / cb:.1f}x) with fresh misses "
                               f"after the step — compile time, not "
                               f"transport")}

    # -- resilience: degraded lifecycle + retry attempts -------------------
    degraded = [s for s in evidence.get("states", [])
                if s.get("state") == "degraded"]
    if degraded:
        return {"cause": "tunnel-degradation", "evidence": "resilience",
                "detail": (f"server entered DEGRADED "
                           f"({degraded[0].get('reason')!r} — journal "
                           f"lifecycle records)")}
    retries = evidence.get("resilience_retries") or {}
    if retries.get("count"):
        sites = ", ".join(retries.get("sites", [])[:3])
        return {"cause": "tunnel-degradation", "evidence": "resilience",
                "detail": (f"{retries['count']} tunnel-class retry "
                           f"attempt(s) in the trace resilience records "
                           f"({sites})")}

    # -- shed: cascade in the serve shed reasons ---------------------------
    if after is not None:
        is_shed = lambda r: r.get("status") == "shed"
        sb, sa = _frac(before, is_shed), _frac(after, is_shed)
        if sb is not None and sa is not None and sa - sb > SHED_RISE:
            reasons = sorted({str(r.get("shed_reason"))
                              for r in after if is_shed(r)})
            return {"cause": "shed-cascade", "evidence": "shed",
                    "detail": (f"shed fraction rose {sb:.0%} -> "
                               f"{sa:.0%} across the step (reasons: "
                               f"{', '.join(reasons)})")}

    # -- explain: cost-model verdicts over the trace rounds ----------------
    if explain_rounds:
        k = detection["index"]
        scoped = [r for r in explain_rounds if r.get("round") is not None
                  and r["round"] >= k] or explain_rounds
        named = [r["verdict"] for r in scoped
                 if r.get("verdict") in ("incast-bound", "bandwidth-bound",
                                         "fence-bound", "slow-injected")]
        if named:
            top = max(("incast-bound", "bandwidth-bound", "fence-bound",
                       "slow-injected"),
                      key=lambda v: (named.count(v), -len(v)))
            rounds = [r["round"] for r in scoped
                      if r.get("verdict") == top]
            return {"cause": top, "evidence": "explain",
                    "detail": (f"cost-model explain names {top} on "
                               f"round(s) {rounds[:6]} after the step "
                               f"(tpu_aggcomm/model verdicts)")}
        unexp = [r for r in scoped
                 if str(r.get("verdict", "")).startswith("UNEXPLAINED")]
        if unexp:
            dev = unexp[0].get("deviation_rel")
            devtxt = f" (model deviation {dev:+.0%})" \
                if isinstance(dev, (int, float)) else ""
            return {"cause": "UNEXPLAINED", "evidence": "explain",
                    "detail": (f"residual {detection['delta_rel']:+.0%} "
                               f"step; the cost model also calls these "
                               f"rounds UNEXPLAINED{devtxt} — outside "
                               f"its physics")}

    # -- flow: dominant-component shift across the step --------------------
    fl = evidence.get("flow") or {}
    doms = fl.get("dominants") or []
    if after is not None and doms:
        def _mode(rids):
            vals = [d.get("verdict") for d in doms
                    if d.get("rid") in rids and d.get("verdict")]
            return (max(sorted(set(vals)), key=vals.count)
                    if vals else None)
        mb = _mode({r["rid"] for r in before})
        ma = _mode({r["rid"] for r in after})
        if mb is not None and ma is not None and mb != ma:
            return {"cause": f"dominant-shift:{mb}->{ma}",
                    "evidence": "flow",
                    "detail": (f"the flow decomposition's modal "
                               f"dominant component shifts {mb} -> {ma} "
                               f"across the step ({fl.get('artifact')} "
                               f"per-request verdicts)")}

    # the fallback detail keeps naming the original four streams
    # verbatim: committed WATCH artifacts pin this string byte-for-byte
    # (replay_watch), and "flow" only ever fires above when its
    # evidence block is present
    return {"cause": "UNEXPLAINED", "evidence": "none",
            "detail": (f"residual {detection['delta_rel']:+.0%} step in "
                       f"the {detection['direction']} direction — no "
                       f"ledger/resilience/shed/explain evidence "
                       f"matches")}


# ---------------------------------------------------------------------------
# The pipeline.

def _trace_round_walls(events: list[dict]) -> list[tuple[dict, list]]:
    """``(run_record, [round walls])`` per run of one trace tail — the
    attribution cell stream via ``obs.metrics.round_stats``, never host
    callbacks."""
    from tpu_aggcomm.obs.metrics import round_stats
    out = []
    for run in (e for e in events if e.get("ev") == "run"):
        stats = [s for s in round_stats(events, run["id"])
                 if isinstance(s["round"], int) and s["round"] >= 0]
        stats.sort(key=lambda s: s["round"])
        out.append((run, [s["wall"] for s in stats if s["wall"]]))
    return out


def _explain_rounds(path: str, predict_path: str) -> dict:
    """Per-run explain verdicts for one trace, keyed by run id — slim
    ``{"round", "verdict", "deviation_rel"}`` rows, blob-representable
    so the validator can re-run attribution from the artifact alone.
    Unexplainable traces degrade to an empty dict (the join is
    evidence, not a gate)."""
    try:
        from tpu_aggcomm.model.artifact import load_artifact
        from tpu_aggcomm.model.explain import explain_trace
        art = load_artifact(predict_path)
        explained = explain_trace(path, art.get("platforms") or {})
    except Exception:  # lint: broad-ok (the explain join is advisory evidence enrichment; a trace the model cannot price must not sink the watch)
        return {}
    out = {}
    for run in explained.get("runs", []):
        out[run["run"]] = [{"round": r.get("round"),
                            "verdict": r.get("verdict"),
                            "deviation_rel": r.get("deviation_rel")}
                           for r in run.get("rounds", [])]
    return out


def watch_streams(journal_paths, trace_paths=(), *, slo: dict | None = None,
                  slo_source: str = "default", seed: int = 0,
                  predict_path: str | None = None,
                  flow_path: str | None = None) -> dict:
    """The whole watchtower pass: tail → evaluate → detect → attribute.

    Returns the watch-v1 body minus the artifact envelope (schema/
    manifest/created_unix, added by :func:`write_watch`). Deterministic
    by construction: a pure function of (streams, slo, seed, predict
    artifact, flow artifact) — the replay gate depends on it.
    ``flow_path`` joins a committed FLOW_r*.json's per-request dominant
    verdicts as the ``flow`` evidence stream (a request-wall step that
    coincides with a dominant-component shift attributes by name
    instead of UNEXPLAINED); the evidence block is only present when
    the artifact was given, so flow-less artifacts stay byte-stable."""
    journal_paths = list(journal_paths)
    trace_paths = list(trace_paths)
    if slo is None:
        slo = DEFAULT_SLO
    errs = validate_slo(slo)
    if errs:
        raise ValueError("invalid SLO spec: " + "; ".join(errs))

    scan = _scan_requests(journal_paths)
    rows = scan["rows"]

    # evidence blocks (blob-representable: validate_watch re-runs the
    # attribution from exactly these)
    sessions = []
    prev = None
    from tpu_aggcomm.obs.ledger import diff_manifests
    for s in scan["sessions"]:
        m = s.get("manifest") if isinstance(s.get("manifest"), dict) \
            else None
        drift = [f"{d['key']}: {d['a']} -> {d['b']}"
                 for d in diff_manifests(prev, m)] \
            if prev is not None and m is not None else []
        sessions.append({"fingerprint": s.get("fingerprint"),
                         "drift": drift})
        if m is not None:
            prev = m
    trace_skipped = 0
    retries = {"count": 0, "sites": []}
    trace_tails: list[tuple[str, list[dict]]] = []
    for path in trace_paths:
        events, skipped = _tail_trace(path)
        trace_skipped += skipped
        trace_tails.append((path, events))
        for e in events:
            if e.get("ev") != "instant" \
                    or e.get("name") != "ledger.resilience":
                continue
            args = e.get("args") or {}
            if args.get("kind") == "attempt" \
                    and args.get("outcome") == "retry":
                retries["count"] += 1
                site = str(args.get("site"))
                if site not in retries["sites"]:
                    retries["sites"].append(site)
    evidence = {"sessions": sessions, "states": scan["states"],
                "resilience_retries": retries}
    if flow_path is not None:
        with open(flow_path) as fh:
            fblob = json.load(fh)
        evidence["flow"] = {
            "artifact": os.path.basename(flow_path),
            "dominants": [{"rid": r.get("rid"),
                           "verdict": r.get("verdict")}
                          for r in fblob.get("per_request") or []
                          if isinstance(r, dict) and r.get("verdict")]}

    explain: dict = {}
    if predict_path is not None:
        for path, _events in trace_tails:
            per_run = _explain_rounds(path, predict_path)
            for run_id, rounds in per_run.items():
                explain[f"{os.path.basename(path)}#run{run_id}"] = rounds
    evidence["explain"] = explain

    # detection: per-request walls, then per-run round walls
    anomalies: list[dict] = []
    walls_rows = [r for r in rows
                  if isinstance(r.get("wall_s"), (int, float))]
    det = detect_changepoint([r["wall_s"] for r in walls_rows], seed=seed)
    if det is not None:
        split_rid = walls_rows[det["index"]]["rid"]
        verdict = attribute_anomaly(det, rows=rows, evidence=evidence,
                                    split_rid=split_rid)
        anomalies.append({"stream": "request-walls",
                          "at_rid": split_rid, "detection": det,
                          **verdict})
    for path, events in trace_tails:
        base = os.path.basename(path)
        for run, walls in _trace_round_walls(events):
            det = detect_changepoint(walls, seed=seed)
            if det is None:
                continue
            key = f"{base}#run{run['id']}"
            verdict = attribute_anomaly(
                det, rows=rows, evidence=evidence,
                explain_rounds=explain.get(key))
            anomalies.append({"stream": f"round-walls:{key}",
                              "at_round": det["index"],
                              "detection": det, **verdict})

    return {
        "seed": int(seed),
        "journals": [os.path.basename(p) for p in journal_paths],
        "traces": [os.path.basename(p) for p in trace_paths],
        "predict": os.path.basename(predict_path)
        if predict_path is not None else None,
        "flow": os.path.basename(flow_path)
        if flow_path is not None else None,
        "slo": slo, "slo_source": slo_source,
        "requests": scan["requests"],
        "integrity": {"journal_torn_lines": scan["skipped_lines"],
                      "trace_torn_lines": trace_skipped,
                      "lost_requests": scan["requests"]["lost"]},
        "per_request": rows,
        "evidence": evidence,
        "evaluation": evaluate_slo(rows, slo),
        "anomalies": anomalies,
        "drain": scan["drain"],
        "problems": scan["problems"],
    }


# ---------------------------------------------------------------------------
# Artifact I/O (the obs/workload.py replay discipline).

def write_watch(path: str, body: dict) -> dict:
    """Write one watch-v1 artifact atomically (manifest records env var
    NAMES only, the ledger discipline) and return the blob."""
    from tpu_aggcomm.obs import ledger
    blob = dict(body)
    blob["schema"] = WATCH_SCHEMA
    blob["manifest"] = ledger.manifest()
    blob["created_unix"] = time.time()
    with atomic_write(path) as fh:
        json.dump(blob, fh, indent=1)
        fh.write("\n")
    return blob


#: Envelope keys excluded from the replay comparison (environment-
#: dependent by design; everything else must re-derive byte-for-byte).
_ENVELOPE = ("schema", "manifest", "created_unix")


def replay_watch(path: str) -> dict:
    """Re-derive a committed WATCH_r*.json from the stream basenames it
    records (resolved next to the artifact, the workload-replay
    contract) + its embedded SLO spec + seed, and byte-compare minus
    the envelope. ``{"verdict": "REPRODUCED" | "MISMATCH", "problems":
    [...]}`` with every diverging top-level key named."""
    with open(path) as fh:
        blob = json.load(fh)
    problems: list[str] = []
    if blob.get("schema") != WATCH_SCHEMA:
        return {"verdict": "MISMATCH",
                "problems": [f"schema {blob.get('schema')!r} != "
                             f"{WATCH_SCHEMA!r}"]}
    root = os.path.dirname(os.path.abspath(path))

    def _resolve(names, what):
        out = []
        for name in names or []:
            p = name if os.path.isabs(name) else os.path.join(root, name)
            if not os.path.exists(p):
                problems.append(f"recorded {what} {name!r} not found "
                                f"next to the artifact ({root})")
            out.append(p)
        return out

    journals = _resolve(blob.get("journals"), "journal")
    traces = _resolve(blob.get("traces"), "trace")
    predict = None
    if blob.get("predict") is not None:
        predict = _resolve([blob["predict"]], "predict artifact")[0]
    flow = None
    if blob.get("flow") is not None:
        flow = _resolve([blob["flow"]], "flow artifact")[0]
    if problems:
        return {"verdict": "MISMATCH", "problems": problems}
    rederived = watch_streams(
        journals, traces, slo=blob.get("slo"),
        slo_source=blob.get("slo_source", "default"),
        seed=blob.get("seed", 0), predict_path=predict,
        flow_path=flow)
    want = {k: v for k, v in blob.items() if k not in _ENVELOPE}
    for k in sorted(set(want) | set(rederived)):
        a = json.dumps(want.get(k), sort_keys=True)
        b = json.dumps(rederived.get(k), sort_keys=True)
        if a != b:
            problems.append(f"key {k!r} does not re-derive from the "
                            f"recorded streams (artifact {a[:120]}... "
                            f"vs re-derived {b[:120]}...)"
                            if max(len(a), len(b)) > 120 else
                            f"key {k!r}: artifact {a} vs re-derived {b}")
    return {"verdict": "REPRODUCED" if not problems else "MISMATCH",
            "problems": problems}


# ---------------------------------------------------------------------------
# /metrics gauges (shared names between LiveSlo and the artifact fold).

def _burn_gauges(registry, objective_name: str, burns: dict,
                 compliant: bool | None) -> None:
    """One objective's gauge set — THE shared exposition arithmetic for
    the live server and the committed-artifact fold (telemetry_gate.py
    holds renders of both float-exact against artifact numbers)."""
    for window, burn in burns.items():
        if burn is not None:
            registry.gauge("tpu_aggcomm_slo_burn_rate", burn,
                           objective=objective_name, window=window)
    if compliant is not None:
        registry.gauge("tpu_aggcomm_slo_compliant",
                       1.0 if compliant else 0.0,
                       objective=objective_name)


def watch_registry(blob: dict, registry) -> None:
    """Fold one watch-v1 blob into a MetricsRegistry: per-objective
    burn-rate gauges (latest window per window spec + overall),
    compliance flags, and the anomaly count. Values are the artifact's
    own numbers VERBATIM — telemetry_gate.py re-parses the render and
    demands float-exact agreement."""
    ev = blob.get("evaluation") or {}
    for obj in ev.get("objectives", []):
        burns: dict = {}
        for wname, entries in (obj.get("windows") or {}).items():
            live = [e["burn"] for e in entries if e.get("burn") is not None]
            if live:
                burns[wname] = live[-1]
        overall = (obj.get("overall") or {}).get("burn")
        if overall is not None:
            burns["overall"] = overall
        _burn_gauges(registry, obj["name"], burns, obj.get("compliant"))
    registry.gauge("tpu_aggcomm_slo_compliant_all",
                   1.0 if ev.get("compliant") else 0.0)
    registry.gauge("tpu_aggcomm_watch_anomalies",
                   float(len(blob.get("anomalies") or [])))


class LiveSlo:
    """The server-side hook: rolling SLO windows over live terminal
    events, exported through the SAME gauge names and burn arithmetic
    as the committed artifact (:func:`measure_window`).

    Constructed by serve/server.py ONLY when ``/metrics`` is armed (the
    import-level gate — this module never loads otherwise) and fed one
    :meth:`record` per terminal request; the hot path pays one
    ``is not None`` check. Gauges are derived from the journal-visible
    event fields alone — never from hook-private timing."""

    def __init__(self, registry, slo: dict | None = None):
        self._registry = registry
        self._slo = slo if slo is not None else DEFAULT_SLO
        errs = validate_slo(self._slo)
        if errs:
            raise ValueError("invalid SLO spec: " + "; ".join(errs))
        self._events: list[dict] = []
        self._max = max(w["requests"] for w in self._slo["windows"])

    def record(self, *, status: str, wall_s=None, cache=None,
               shed_reason=None, deadline_ms=None, batch=None) -> None:
        """One terminal request event (done/fail/shed), journal-field
        shaped; updates every objective's burn/compliance gauges."""
        self._events.append({"rid": len(self._events), "status": status,
                             "wall_s": wall_s, "phases": {},
                             "cache": cache, "shed_reason": shed_reason,
                             "deadline_ms": deadline_ms, "batch": batch})
        if len(self._events) > self._max:
            del self._events[:len(self._events) - self._max]
        for obj in self._slo["objectives"]:
            burns: dict = {}
            oks: list = []
            for w in self._slo["windows"]:
                m = measure_window(self._events[-w["requests"]:], obj)
                burns[w["name"]] = m["burn"]
                if m["compliant"] is not None:
                    oks.append(m["compliant"])
            _burn_gauges(self._registry, obj["name"], burns,
                         all(oks) if oks else None)


# ---------------------------------------------------------------------------
# Rendering (``cli inspect watch``).

def _fmt_burn(b) -> str:
    return f"{b:6.2f}" if isinstance(b, (int, float)) else "     -"


def render_watch(body: dict) -> str:
    """The ``inspect watch`` text view: SLO verdicts, burn timeline,
    anomalies with named causes, stream integrity."""
    r = body["requests"]
    lines = [f"watchtower over {', '.join(body['journals'])}"
             + (f" + {', '.join(body['traces'])}" if body["traces"]
                else "")
             + f" (seed {body['seed']}, slo: {body['slo_source']})",
             f"  requests: {r['admitted']} admitted — {r['completed']} "
             f"completed, {r['failed']} failed, {r['shed']} shed"
             + (f", LOST in flight: {r['lost']}" if r["lost"] else "")]
    integ = body["integrity"]
    if integ["journal_torn_lines"] or integ["trace_torn_lines"]:
        lines.append(f"  integrity: skipped {integ['journal_torn_lines']} "
                     f"torn journal line(s), {integ['trace_torn_lines']} "
                     f"torn trace line(s) — counted, not silently "
                     f"absorbed")
    ev = body["evaluation"]
    lines.append(f"  SLO: {'COMPLIANT' if ev['compliant'] else 'VIOLATED'}"
                 f" ({sum(1 for o in ev['objectives'] if o['compliant'])}"
                 f"/{len(ev['objectives'])} objectives inside budget)")
    for o in ev["objectives"]:
        tag = "ok " if o["compliant"] else "HOT"
        worst = _fmt_burn(o["worst_burn"]).strip()
        th = f" <= {o['threshold_s']:g}s" if "threshold_s" in o else ""
        lines.append(f"    [{tag}] {o['name']} (target "
                     f"{o['target']:.0%}{th}): worst burn {worst}")
        for wname, entries in o["windows"].items():
            burns = " ".join(_fmt_burn(e["burn"]).strip()
                             for e in entries[-8:])
            if burns.strip("- "):
                lines.append(f"          {wname:>6} windows: {burns}")
    for a in body["anomalies"]:
        d = a["detection"]
        at = f"rid {a['at_rid']}" if "at_rid" in a \
            else f"round {a['at_round']}"
        lines.append(
            f"  ANOMALY [{a['stream']}] at {at}: "
            f"{d['before_mean'] * 1e3:.1f} ms -> "
            f"{d['after_mean'] * 1e3:.1f} ms ({d['delta_rel']:+.0%}, "
            f"95% CI [{d['ci_rel'][0]:+.0%}, {d['ci_rel'][1]:+.0%}])")
        lines.append(f"    cause: {a['cause']} [evidence: "
                     f"{a['evidence']}] — {a['detail']}")
    if not body["anomalies"]:
        lines.append("  anomalies: none confirmed (seeded changepoint "
                     "scan over request + round walls)")
    for s in body["evidence"]["states"]:
        lines.append(f"  lifecycle: {s['prev']} -> {s['state']} "
                     f"({s['reason']})")
    if body.get("drain"):
        d = body["drain"]
        lines.append(f"  drain record: {d.get('completed')} completed, "
                     f"{d.get('failed')} failed, {d.get('shed')} shed, "
                     f"lost {d.get('lost')}")
    for p in body["problems"]:
        lines.append(f"  PROBLEM: {p}")
    return "\n".join(lines) + "\n"
