"""The run ledger: environment provenance + compile/HBM telemetry.

Every headline number in this repo is a past-vs-present comparison —
against the reference baseline, against prior rounds (``bench.py
--check-regression``), between traces (``inspect compare``) — and such
a delta is only auditable when both sides record what produced them.
This module is that record, in three parts:

- **manifest** — environment provenance captured once per process:
  python/jax/jaxlib/libtpu versions (from package *metadata*, never by
  importing jax), git sha, the scrubbed env summary
  (``harness.hostenv.env_summary`` — arming variables by name only),
  plus device facts (platform, device kind, tunnel RPC-latency probe)
  recorded by the jax-side callers via :func:`record_device`.
- **compile records** — wall times bracketing compilation measured by
  ``harness/chained.py`` (chain warmup + ``lower()`` walls + HLO cost
  stats) and ``harness/runner.py`` (schedule build, first dispatch),
  appended via :func:`record_compile`. These are honest HOST walls
  around compile-containing boundaries; a "compile+warmup" record means
  compile AND one execution — the label never oversells
  (report.py:PHASE_SOURCES discipline).
- **xprof cross-check** — the ``--xprof`` divergence report between an
  independently profiled rep (``jax.profiler.trace``) and the
  reconstructed attribution total. Cross-check ONLY: reconstructed
  cells stay the source of truth; the report exists to catch the
  reconstruction drifting from device reality, not to replace it. The
  device timeline total is parsed out of the profiler's ``*.xplane.pb``
  with a minimal stdlib protobuf wire-format reader (no tensorboard /
  tensorflow dependency — the container has neither).

No jax anywhere here (like obs/metrics.py): bench.py's jax-free
supervisor and the ``inspect ledger`` CLI import this on a machine
where ``import jax`` may hang on a dead tunnel. Versions come from
``importlib.metadata``, which reads dist-info without importing.
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import time

from tpu_aggcomm.harness.hostenv import env_summary

__all__ = ["SCHEMA_VERSION", "collect_manifest", "manifest",
           "record_device", "record_compile", "compile_records",
           "total_compile_seconds", "record_hbm_peak", "hbm_peak",
           "record_resilience", "resilience_records",
           "render_resilience", "diff_resilience", "reset",
           "diff_manifests", "DRIFT_IGNORE",
           "load_ledger", "render_manifest", "render_ledgers",
           "xprof_report", "xprof_reports", "render_xprof",
           "xplane_device_seconds"]

#: The bench parsed-schema version this ledger feeds: v3 = v2 (samples)
#: + ``manifest`` + ``compile_seconds`` + ``hbm_peak_bytes``
#: (obs/regress.py validates all three).
SCHEMA_VERSION = 3

_MANIFEST: dict | None = None
_COMPILES: list[dict] = []
_XPROF: list[dict] = []
_RESILIENCE: list[dict] = []
_HBM_PEAK: int | None = None


def _pkg_version(name: str) -> str | None:
    try:
        from importlib import metadata
        return metadata.version(name)
    except Exception:  # lint: broad-ok (provenance best-effort; None = unknown)
        return None


def _git_sha() -> str | None:
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    try:
        r = subprocess.run(["git", "rev-parse", "--short=12", "HEAD"],
                           cwd=root, capture_output=True, text=True,
                           timeout=10)
    except Exception:  # lint: broad-ok (provenance best-effort; None = unknown)
        return None
    return r.stdout.strip() or None if r.returncode == 0 else None


def collect_manifest() -> dict:
    """The process manifest, captured once and cached (the LIVE dict —
    :func:`record_device` mutates it; external consumers should call
    :func:`manifest` for a copy)."""
    global _MANIFEST
    if _MANIFEST is None:
        _MANIFEST = {
            "schema": SCHEMA_VERSION,
            "python": "%d.%d.%d" % sys.version_info[:3],
            "versions": {
                "jax": _pkg_version("jax"),
                "jaxlib": _pkg_version("jaxlib"),
                "libtpu": (_pkg_version("libtpu")
                           or _pkg_version("libtpu-nightly")),
            },
            "git_sha": _git_sha(),
            "env": env_summary(),
            "platform": None,
            "device_kind": None,
            "rpc_probe_s": None,
            "created_unix": time.time(),
        }
    return _MANIFEST


def manifest() -> dict:
    """A JSON-able copy of the process manifest (device facts included
    if a jax-side caller has recorded them)."""
    m = collect_manifest()
    out = dict(m)
    out["versions"] = dict(m["versions"])
    out["env"] = dict(m["env"])
    return out


def record_device(*, platform: str | None = None,
                  device_kind: str | None = None,
                  rpc_probe_s: float | None = None) -> None:
    """Fill the manifest's device facts. Called from jax-side code
    (bench.py's measure child, harness/runner.py) — the ledger itself
    never touches jax, so these arrive as plain values."""
    m = collect_manifest()
    if platform is not None:
        m["platform"] = str(platform)
    if device_kind is not None:
        m["device_kind"] = str(device_kind)
    if rpc_probe_s is not None:
        m["rpc_probe_s"] = float(rpc_probe_s)


def record_compile(label: str, *, seconds: float, kind: str = "compile",
                   **extra) -> dict:
    """Append one compile-telemetry record (``seconds`` is a host wall
    around a compile-containing boundary; ``kind`` says which boundary:
    "schedule-build", "first-dispatch", "compile+warmup", "lower").
    Extra keys (lower_seconds, cost, iters, backend...) ride along;
    None values are dropped."""
    rec = {"label": str(label), "seconds": float(seconds),
           "kind": str(kind)}
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    _COMPILES.append(rec)
    return rec


def compile_records() -> list[dict]:
    return list(_COMPILES)


def total_compile_seconds() -> float:
    """Total wall seconds across every compile record — the one number
    the bench artifact carries (``compile_seconds``) and the regression
    compile gate compares."""
    return sum(r["seconds"] for r in _COMPILES)


def record_hbm_peak(nbytes) -> None:
    """Track the worst HBM peak a jax-side caller observed
    (``device.memory_stats()['peak_bytes_in_use']``)."""
    global _HBM_PEAK
    if nbytes is None:
        return
    n = int(nbytes)
    _HBM_PEAK = n if _HBM_PEAK is None else max(_HBM_PEAK, n)


def hbm_peak() -> int | None:
    return _HBM_PEAK


def record_resilience(site: str, *, kind: str, **extra) -> dict:
    """Append one resilience record (tpu_aggcomm/resilience/):
    ``kind`` in {"attempt", "suppressed", "deadline", "preflight",
    "cancel"} — plus the serve lifecycle kinds {"shed", "state",
    "drain", "bind"} (serve/server.py), all ignored by
    ``replay_attempts`` because they are not attempts. Attempt records
    carry the full retry-policy fields so the backoff timeline replays
    deterministically from the artifact alone
    (resilience/policy.replay_attempts). None extras are dropped,
    record_compile discipline."""
    rec = {"site": str(site), "kind": str(kind)}
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    _RESILIENCE.append(rec)
    return rec


def resilience_records() -> list[dict]:
    return list(_RESILIENCE)


def xprof_reports() -> list[dict]:
    return list(_XPROF)


def reset() -> None:
    """Forget everything (tests only — the whole point of the ledger is
    that production processes never clear it)."""
    global _MANIFEST, _HBM_PEAK
    _MANIFEST = None
    _HBM_PEAK = None
    _COMPILES.clear()
    _XPROF.clear()
    _RESILIENCE.clear()


# ---------------------------------------------------------------------------
# Manifest diffing (environment drift between artifacts).

#: Flattened-key prefixes that are EXPECTED to differ between rounds and
#: therefore never count as environment drift: timestamps, the tunnel's
#: per-run RPC latency, and the git sha (every round is a new commit by
#: construction — code change is what the round IS, not drift).
DRIFT_IGNORE = ("created_unix", "rpc_probe_s", "git_sha")


def _flatten(d: dict, prefix: str = "") -> dict:
    out: dict = {}
    for k, v in (d or {}).items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        else:
            out[key] = v
    return out


def diff_manifests(a: dict | None, b: dict | None) -> list[dict]:
    """Environment drift between two manifests: ``[{"key", "a", "b"}]``
    for every flattened key that differs, DRIFT_IGNORE keys excluded.
    Either side None (a pre-v3 artifact) yields no drift — absence of
    evidence is reported by the caller, not invented here."""
    if not isinstance(a, dict) or not isinstance(b, dict):
        return []
    fa, fb = _flatten(a), _flatten(b)
    drift = []
    for k in sorted(set(fa) | set(fb)):
        if k.startswith(DRIFT_IGNORE):
            continue
        va, vb = fa.get(k), fb.get(k)
        if va != vb:
            drift.append({"key": k, "a": va, "b": vb})
    return drift


# ---------------------------------------------------------------------------
# Loading ledgers back out of artifacts.

def load_ledger(path: str) -> dict:
    """The ledger view of one artifact: ``{"file", "manifest",
    "compile_seconds", "hbm_peak_bytes", "platform", "value"}`` (missing
    fields None). Accepts a driver-wrapped ``BENCH_rNN.json``
    (``{"parsed": {...}}``), a bare bench JSON line, or a
    ``*.trace.jsonl`` event log (the ledger preamble event; resilience
    records come back out of the ``ledger.resilience`` instants)."""
    out = {"file": path, "manifest": None, "compile_seconds": None,
           "hbm_peak_bytes": None, "platform": None, "value": None,
           "resilience": []}
    if path.endswith(".jsonl"):
        with open(path) as fh:
            for line in fh:
                if not line.strip():
                    continue
                e = json.loads(line)
                if e.get("ev") == "ledger" and out["manifest"] is None:
                    out["manifest"] = e.get("manifest")
                    m = out["manifest"] or {}
                    out["platform"] = m.get("platform")
                elif e.get("ev") == "instant" \
                        and e.get("name") == "ledger.resilience" \
                        and isinstance(e.get("args"), dict):
                    out["resilience"].append(e["args"])
        return out
    with open(path) as fh:
        blob = json.load(fh)
    parsed = blob.get("parsed") if isinstance(blob.get("parsed"), dict) \
        else blob if isinstance(blob, dict) else {}
    if isinstance(parsed, dict):
        out["manifest"] = parsed.get("manifest") \
            if isinstance(parsed.get("manifest"), dict) else None
        for k in ("compile_seconds", "hbm_peak_bytes", "platform", "value"):
            out[k] = parsed.get(k, out[k])
        if isinstance(parsed.get("resilience"), list):
            out["resilience"] = [r for r in parsed["resilience"]
                                 if isinstance(r, dict)]
    return out


def _fmt(v, unit: str = "") -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.6g}{unit}"
    return f"{v}{unit}"


def render_manifest(m: dict | None, indent: str = "  ") -> str:
    """Human block for one manifest (``inspect ledger``)."""
    if not isinstance(m, dict):
        return f"{indent}(no ledger: pre-v3 artifact)\n"
    v = m.get("versions") or {}
    e = m.get("env") or {}
    lines = [
        f"{indent}platform {_fmt(m.get('platform'))}  "
        f"device_kind {_fmt(m.get('device_kind'))}  "
        f"rpc probe {_fmt(m.get('rpc_probe_s'), ' s')}",
        f"{indent}jax {_fmt(v.get('jax'))}  jaxlib {_fmt(v.get('jaxlib'))}  "
        f"libtpu {_fmt(v.get('libtpu'))}  python {_fmt(m.get('python'))}  "
        f"git {_fmt(m.get('git_sha'))}",
        f"{indent}env: JAX_PLATFORMS={_fmt(e.get('jax_platforms'))}  "
        f"tunnel_armed={e.get('tunnel_armed')}  "
        f"armed_vars={e.get('armed_vars')}",
    ]
    return "\n".join(lines) + "\n"


def render_resilience(records: list[dict], indent: str = "  ") -> str:
    """Human lines for one artifact's resilience records (``inspect
    ledger``): attempt timelines grouped per retry site, the other
    record kinds one line each. Empty string when there are none — a
    pre-resilience artifact renders exactly as before."""
    if not records:
        return ""
    lines: list[str] = []
    sites: dict[str, list[dict]] = {}
    for r in records:
        if r.get("kind") == "attempt":
            sites.setdefault(str(r.get("site")), []).append(r)
    for site, recs in sites.items():
        retried = [r for r in recs if r.get("outcome") == "retry"]
        last = max(recs, key=lambda r: r.get("attempt", 0))
        classes = sorted({r.get("error_class") for r in recs
                          if r.get("error_class")})
        status = ("converged" if last.get("outcome") == "ok"
                  else f"gave up ({last.get('error_class', '?')})")
        lines.append(
            f"{indent}resilience {site}: {len(recs)} attempt"
            f"{'s' if len(recs) != 1 else ''}"
            + (f", {len(retried)} retried"
               f" [{', '.join(classes)}]" if retried else "")
            + f" -> {status}")
    for r in records:
        kind = r.get("kind")
        if kind == "attempt":
            continue
        if kind == "deadline":
            lines.append(f"{indent}resilience {r.get('site')}: soft "
                         f"deadline overrun — wall "
                         f"{_fmt(r.get('wall_s'), ' s')} > "
                         f"{_fmt(r.get('deadline_s'), ' s')} (advisory)")
        elif kind == "suppressed":
            lines.append(f"{indent}resilience {r.get('site')}: "
                         f"suppressed {r.get('error_class', '?')} error "
                         f"({str(r.get('error', ''))[:80]})")
        elif kind == "preflight":
            lines.append(f"{indent}resilience {r.get('site')}: preflight "
                         f"rpc probe "
                         f"{_fmt(r.get('rpc_probe_s'), ' s')}")
        elif kind == "cancel":
            lines.append(f"{indent}resilience {r.get('site')}: cancelled "
                         f"at round boundary (deferred "
                         f"{r.get('signal', '?')})")
        else:
            lines.append(f"{indent}resilience {r.get('site')}: {kind}")
    return "\n".join(lines) + "\n"


def _resilience_summary(records) -> tuple[dict, dict]:
    """(retries per site, suppressed counts per error class) for one
    artifact's resilience records — the two tunnel-health signals worth
    diffing round-over-round."""
    retries: dict[str, int] = {}
    suppressed: dict[str, int] = {}
    for r in records or []:
        kind = r.get("kind")
        if kind == "attempt" and r.get("outcome") == "retry":
            site = str(r.get("site"))
            retries[site] = retries.get(site, 0) + 1
        elif kind == "suppressed":
            cls = str(r.get("error_class") or "?")
            suppressed[cls] = suppressed.get(cls, 0) + 1
    return retries, suppressed


def diff_resilience(a, b) -> list[str]:
    """Tunnel-health drift between two artifacts' resilience records:
    one line per site whose retry count changed and per suppressed
    error class whose count changed. Empty when both rounds look
    equally healthy — two clean rounds add no noise to a DRIFT block;
    a round that suddenly needed retries shows up right next to the
    manifest drift that may explain it."""
    ra, sa = _resilience_summary(a)
    rb, sb = _resilience_summary(b)
    lines: list[str] = []
    for site in sorted(set(ra) | set(rb)):
        if ra.get(site, 0) != rb.get(site, 0):
            lines.append(f"retries at {site}: "
                         f"{ra.get(site, 0)} -> {rb.get(site, 0)}")
    for cls in sorted(set(sa) | set(sb)):
        if sa.get(cls, 0) != sb.get(cls, 0):
            lines.append(f"suppressed {cls} errors: "
                         f"{sa.get(cls, 0)} -> {sb.get(cls, 0)}")
    return lines


def render_ledgers(paths: list[str]) -> str:
    """``inspect ledger [FILE...]``: per-artifact manifest blocks plus
    DRIFT lines between each consecutive pair that both carry a
    manifest — differing jax versions, platforms, or armed environments
    between compared rounds must jump off the page. The same pairwise
    blocks carry RESIL lines (``diff_resilience``) when the rounds'
    retry/suppression profiles differ — a tunnel-health regression
    lands beside the environment change that may explain it."""
    entries = [load_ledger(p) for p in paths]
    lines: list[str] = []
    for ent in entries:
        lines.append(f"== {os.path.basename(ent['file'])} ==")
        lines.append(render_manifest(ent["manifest"]).rstrip("\n"))
        if ent["compile_seconds"] is not None \
                or ent["hbm_peak_bytes"] is not None:
            lines.append(
                f"  compile {_fmt(ent['compile_seconds'], ' s')}  "
                f"hbm peak {_fmt(ent['hbm_peak_bytes'], ' B')}")
        res = render_resilience(ent.get("resilience") or [])
        if res:
            lines.append(res.rstrip("\n"))
    prev = None
    for ent in entries:
        if ent["manifest"] is None:
            continue
        if prev is not None:
            drift = diff_manifests(prev["manifest"], ent["manifest"])
            a = os.path.basename(prev["file"])
            b = os.path.basename(ent["file"])
            lines.append(f"-- {a} -> {b} --")
            if drift:
                for d in drift:
                    lines.append(f"  DRIFT {d['key']}: "
                                 f"{_fmt(d['a'])} -> {_fmt(d['b'])}")
            else:
                lines.append("  no environment drift")
            for r in diff_resilience(prev.get("resilience"),
                                     ent.get("resilience")):
                lines.append(f"  RESIL {r}")
        prev = ent
    if not entries:
        lines.append("no artifacts given")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# xprof cross-check: device timeline vs reconstructed attribution rounds.
#
# jax.profiler.trace writes XSpace protobufs (*.xplane.pb). The repo may
# not install tensorboard/tensorflow, so the device timeline total is
# recovered with a minimal protobuf wire-format walk over the stable
# XSpace/XPlane/XLine/XEvent field numbers (xplane.proto):
#   XSpace.planes=1; XPlane.name=2 .lines=3;
#   XLine.timestamp_ns=3 .events=4; XEvent.offset_ps=2 .duration_ps=3.

def _read_varint(buf: bytes, i: int) -> tuple[int, int]:
    shift = result = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _walk(buf: bytes, start: int, end: int):
    """Yield (field_number, wire_type, value) over one message's bytes;
    length-delimited values come as (start, end) slices."""
    i = start
    while i < end:
        tag, i = _read_varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
            yield field, wt, v
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            yield field, wt, (i, i + ln)
            i += ln
        elif wt == 5:
            yield field, wt, None
            i += 4
        elif wt == 1:
            yield field, wt, None
            i += 8
        else:
            return  # unknown wire type: stop rather than misparse


def xplane_device_seconds(path: str) -> dict | None:
    """The device-plane timeline span of one ``*.xplane.pb``:
    ``{"plane", "span_s", "events"}`` for the device plane (name
    containing "/device:") with the widest event span, or None when the
    profile has no device plane (CPU-only profiles often don't) or the
    file does not parse."""
    try:
        with open(path, "rb") as fh:
            buf = fh.read()
    except OSError:
        return None
    best = None
    try:
        for f, wt, v in _walk(buf, 0, len(buf)):
            if f != 1 or wt != 2:
                continue
            ps, pe = v
            name = ""
            line_slices = []
            for f2, wt2, v2 in _walk(buf, ps, pe):
                if f2 == 2 and wt2 == 2:
                    name = buf[v2[0]:v2[1]].decode(errors="replace")
                elif f2 == 3 and wt2 == 2:
                    line_slices.append(v2)
            if "/device:" not in name:
                continue
            lo = hi = None
            nev = 0
            for (ls, le) in line_slices:
                ts_ns = 0
                ev_slices = []
                for f3, wt3, v3 in _walk(buf, ls, le):
                    if f3 == 3 and wt3 == 0:
                        ts_ns = v3
                    elif f3 == 4 and wt3 == 2:
                        ev_slices.append(v3)
                for (es, ee) in ev_slices:
                    off = dur = None
                    for f4, wt4, v4 in _walk(buf, es, ee):
                        if f4 == 2 and wt4 == 0:
                            off = v4
                        elif f4 == 3 and wt4 == 0:
                            dur = v4
                    if off is None:
                        continue
                    start_ps = ts_ns * 1000 + off
                    end_ps = start_ps + (dur or 0)
                    lo = start_ps if lo is None else min(lo, start_ps)
                    hi = end_ps if hi is None else max(hi, end_ps)
                    nev += 1
            if nev and hi is not None:
                span = (hi - lo) / 1e12
                if best is None or span > best["span_s"]:
                    best = {"plane": name, "span_s": span, "events": nev}
    except (IndexError, ValueError):
        return None
    return best


def xprof_report(*, label: str, logdir: str,
                 profiled_wall_s: float | None,
                 reconstructed_s: float | None,
                 error: str | None = None,
                 error_class: str | None = None) -> dict:
    """Build (and record) the divergence report for one profiled rep.

    ``source`` is column-accurate about what the profiled side IS:
    "xplane-device-span" when a device plane parsed out of the profile,
    "host-wall(profiled)" when only the host wall around the profiled
    dispatch exists (a tunneled dispatch makes that an overestimate —
    the report says which it is, never overselling). The reconstructed
    side stays the source of truth either way."""
    device = None
    try:
        pbs = sorted(glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                               recursive=True), key=os.path.getmtime)
        if pbs:
            device = xplane_device_seconds(pbs[-1])
    except OSError:
        device = None
    if device is not None:
        total, source = device["span_s"], "xplane-device-span"
    elif profiled_wall_s is not None:
        total, source = profiled_wall_s, "host-wall(profiled)"
    else:
        total = source = None
    div = None
    if total is not None and reconstructed_s:
        div = (total - reconstructed_s) / reconstructed_s * 100.0
    report = {
        "label": label, "logdir": logdir,
        "profiled_wall_s": profiled_wall_s,
        "device_span_s": device["span_s"] if device else None,
        "device_plane": device["plane"] if device else None,
        "reconstructed_s": reconstructed_s,
        "total_s": total, "source": source,
        "divergence_pct": div, "error": error,
        "error_class": error_class,
    }
    _XPROF.append(report)
    return report


def render_xprof(report: dict) -> str:
    if report.get("error"):
        cls = report.get("error_class")
        cls_s = f" [{cls}]" if cls else ""
        return (f"xprof {report['label']}: unavailable{cls_s} "
                f"({report['error']})")
    div = report.get("divergence_pct")
    div_s = f"{div:+.1f}%" if div is not None else "n/a"
    total = report.get("total_s")
    recon = report.get("reconstructed_s")
    return (f"xprof {report['label']}: profiled "
            f"{_fmt(total, ' s')} [{report.get('source')}] vs "
            f"reconstructed rep {_fmt(recon, ' s')} -> divergence "
            f"{div_s} (cross-check only; reconstructed cells remain "
            f"the source of truth)")
