"""Chrome/Perfetto export of a flight-recorder event log.

Produces the Chrome Trace Event JSON format (the ``traceEvents`` array
form), loadable in ``ui.perfetto.dev`` or ``chrome://tracing``:

- pid 1, "host (measured)": real perf_counter windows (``host_span``)
  and instants — dispatch loops, chained differencing windows, oracle
  delivery events — plus the ``hbm`` counter tracks
  (``device.memory_stats()`` samples, host-sampled outside the timed
  path).
- pid 2, "ranks (reconstructed)": one thread (track) per logical rank.
  Rep envelopes and per-round bucket slices from the attribution cell
  stream. Every slice's args carry the exact attributed seconds
  (``dur_s``) and the run's column-accurate provenance label
  (``phase_source``) — the UI can never present a reconstructed slice
  as a measurement.
- pid 2, tid 0: the ``bytes_in_flight`` counter track (payload bytes
  entering flight per throttle round) plus the ``traffic_msgs`` /
  ``traffic_max_incast`` tracks (per-round message count and incast
  fan-in depth, static accounting from obs/traffic.py — args key
  ``value``, since they count messages, not bytes), plus the
  ``latency_p50_ms`` / ``latency_p95_ms`` / ``latency_p99_ms`` tracks:
  per-round rank-latency quantiles (obs/metrics.py over the
  reconstructed cell means — p50/p95 are ``round_stats`` VERBATIM, p99
  the same percentile arithmetic), one sample per (run, round) at the
  round's first slice timestamp, so the tail shows ON the timeline.
- pid 3, "serve requests (journal-derived)": one thread per serve
  request, phase slices (queue/batch/cache/dispatch/respond) between
  the boundary stamps each ``serve.request`` instant carries
  (obs/workload.py BOUNDARIES order) — the ``inspect workload``
  attribution projected onto the timeline, never ad-hoc host timing.
  Request slices carry the batch correlation id (``cid``), and Chrome
  flow events (``ph`` "s"/"f") link each request's ``dispatch`` slice
  to the first round slice of the cid-matched attributed run in pid 2 —
  the ``inspect flow`` causal join drawn as arrows on the timeline.

Multi-run legibility: the process names carry the backend(s) and the
``process_labels`` metadata lists every run (``m<id> <method name>
[backend]``), so a sweep export's tracks are identifiable in the UI
without opening a slice. The run-ledger preamble (obs/ledger.py) lands
as a ``ledger.manifest`` instant at ts 0 with the manifest in its args.

Slices within each track are sorted by timestamp, so ``ts`` is
monotonically non-decreasing per track (pinned by the round-trip
tests). Timestamps are microseconds (the format's unit).
"""

from __future__ import annotations

__all__ = ["to_chrome_trace", "HOST_PID", "RANKS_PID", "SERVE_PID",
           "HBM_TID"]

HOST_PID = 1
RANKS_PID = 2

#: Serve request-flow tracks: one thread per request id, phase slices
#: synthesized from the ``serve.request`` instants' recorded boundary
#: stamps (obs/workload.py BOUNDARIES order) — journal-derived timing,
#: never ad-hoc host callbacks.
SERVE_PID = 3

#: Host-process thread id of the HBM counter tracks (tid 1 is the host
#: span/instant timeline).
HBM_TID = 2


def _meta(pid: int, tid: int, what: str, name: str) -> dict:
    return {"ph": "M", "pid": pid, "tid": tid, "name": what,
            "args": {"name": name}}


def _run_label(run: dict) -> str:
    return f"m{run.get('method')} {run.get('name')} [{run.get('backend')}]"


def to_chrome_trace(events: list[dict]) -> dict:
    """Convert flight-recorder events to a Chrome trace dict."""
    runs = {e["id"]: e for e in events if e["ev"] == "run"}
    backends = sorted({str(r.get("backend")) for r in runs.values()})
    ranks_name = "ranks (reconstructed)"
    if backends:
        ranks_name += " — " + "/".join(backends)
    run_labels = ", ".join(_run_label(runs[k]) for k in sorted(runs))
    out: list[dict] = [
        _meta(HOST_PID, 0, "process_name", "host (measured)"),
        _meta(HOST_PID, 1, "thread_name", "host timeline"),
        _meta(RANKS_PID, 0, "process_name", ranks_name),
        _meta(RANKS_PID, 0, "thread_name",
              "counters (bytes_in_flight, traffic_*, latency_*)"),
    ]
    if run_labels:
        for pid in (HOST_PID, RANKS_PID):
            out.append({"ph": "M", "pid": pid, "tid": 0,
                        "name": "process_labels",
                        "args": {"labels": run_labels}})
    ranks_seen: set[int] = set()
    hbm_seen = False
    slices: list[dict] = []
    for e in events:
        ev = e["ev"]
        if ev == "host_span":
            slices.append({
                "ph": "X", "pid": HOST_PID, "tid": 1, "name": e["name"],
                "cat": "host", "ts": e["ts"], "dur": e["dur"],
                "args": e.get("args", {})})
        elif ev == "instant":
            slices.append({
                "ph": "i", "pid": HOST_PID, "tid": 1, "name": e["name"],
                "cat": "host", "ts": e["ts"], "s": "t",
                "args": e.get("args", {})})
        elif ev == "ledger":
            # the run-ledger preamble: environment manifest as an
            # instant at the origin, args carry the whole manifest
            slices.append({
                "ph": "i", "pid": HOST_PID, "tid": 1,
                "name": "ledger.manifest", "cat": "ledger", "ts": 0.0,
                "s": "p", "args": {"manifest": e.get("manifest")}})
        elif ev == "hbm":
            hbm_seen = True
            for key in ("bytes_in_use", "peak_bytes"):
                if e.get(key) is None:
                    continue
                slices.append({
                    "ph": "C", "pid": HOST_PID, "tid": HBM_TID,
                    "name": f"hbm_{key}", "ts": e["ts"],
                    "args": {"bytes": e[key]}})
        elif ev == "span":
            run = runs.get(e["run"], {})
            rank = e["rank"]
            ranks_seen.add(rank)
            rnd = e["round"]
            if e["bucket"] == "total":
                name = f"rep {e['rep']}"
            elif rnd is None or rnd == -1:
                name = e["bucket"]
            else:
                name = f"round {rnd}: {e['bucket']}" \
                    if isinstance(rnd, int) else f"{rnd}: {e['bucket']}"
            slices.append({
                "ph": "X", "pid": RANKS_PID, "tid": rank + 1,
                "name": name, "cat": run.get("name", "run"),
                "ts": e["ts"], "dur": e["dur"],
                "args": {"run": e["run"], "rep": e["rep"],
                         "round": rnd, "bucket": e["bucket"],
                         "dur_s": e["dur_s"],
                         "phase_source": e["src"],
                         "method": run.get("name")}})
        elif ev == "counter":
            # bytes_in_flight samples bytes; the traffic_* tracks
            # (msgs, incast depth) are counts, not bytes
            key = "bytes" if e["name"] == "bytes_in_flight" else "value"
            slices.append({
                "ph": "C", "pid": RANKS_PID, "tid": 0,
                "name": e["name"], "ts": e["ts"],
                "args": {key: e["value"]}})
        # "run"/"timer"/"meta" events carry no timeline geometry

    # per-round latency quantile counters: the histogram view
    # (obs/export.py) projected onto the timeline. p50/p95 are the
    # round_stats values VERBATIM and p99 is the same percentile
    # arithmetic over the same per-rank cell means — derived from the
    # attribution cell stream like every reconstructed slice, never
    # from host callbacks. Emitted at each round's first slice
    # timestamp so the counter sample sits where the round starts.
    from tpu_aggcomm.obs.metrics import cell_means, percentile, round_stats
    for rid in sorted(runs):
        round_ts: dict = {}
        for e in events:
            if e["ev"] == "span" and e["run"] == rid \
                    and e["bucket"] != "total":
                rnd = e["round"]
                if rnd not in round_ts or e["ts"] < round_ts[rnd]:
                    round_ts[rnd] = e["ts"]
        means = cell_means(events, rid)
        for rs in round_stats(events, rid):
            rnd = rs["round"]
            ts = round_ts.get(rnd)
            if ts is None:
                continue
            vals = sorted(s for (_rank, r), s in means.items()
                          if r == rnd)
            for name, v in (("latency_p50_ms", rs["p50"]),
                            ("latency_p95_ms", rs["p95"]),
                            ("latency_p99_ms",
                             percentile(vals, 99.0) if vals else None)):
                if v is None:
                    continue
                slices.append({
                    "ph": "C", "pid": RANKS_PID, "tid": 0,
                    "name": name, "ts": ts,
                    "args": {"value": v * 1e3}})

    # serve request-flow tracks: each `serve.request` instant carries
    # the request's full boundary-stamp dict (relative to admission);
    # the instant itself was emitted at the respond boundary, so
    # admit_ts = instant_ts - respond_stamp re-anchors the request on
    # the host clock. One slice per consecutive recorded boundary pair,
    # one thread per request id — the same journal-derived attribution
    # `inspect workload` prints, projected onto the timeline.
    from tpu_aggcomm.obs.workload import BOUNDARIES
    serve_seen: set[int] = set()
    # (rid, cid) -> the request's dispatch-slice start ts: the anchor
    # each flow arrow departs from (obs/flow.py joins on the same cid)
    dispatch_anchor: dict[tuple[int, str], float] = {}
    for e in events:
        if e["ev"] != "instant" or e.get("name") != "serve.request":
            continue
        args = e.get("args", {})
        phases = args.get("phases")
        rid = args.get("rid")
        if not isinstance(phases, dict) or not isinstance(rid, int):
            continue
        stamps = [(b, phases[b]) for b in BOUNDARIES
                  if isinstance(phases.get(b), (int, float))]
        if len(stamps) < 2:
            continue
        t0 = e["ts"] - stamps[-1][1] * 1e6
        serve_seen.add(rid)
        cid = args.get("cid")
        for (_b0, s0), (b1, s1) in zip(stamps, stamps[1:]):
            if b1 == "dispatch" and isinstance(cid, str):
                dispatch_anchor[(rid, cid)] = t0 + s0 * 1e6
            slices.append({
                "ph": "X", "pid": SERVE_PID, "tid": rid + 1,
                "name": b1, "cat": "serve",
                "ts": t0 + s0 * 1e6, "dur": (s1 - s0) * 1e6,
                "args": {"rid": rid, "phase": b1, "dur_s": s1 - s0,
                         "ok": args.get("ok"),
                         "backend": args.get("backend"),
                         "cache": args.get("cache"),
                         "cid": cid,
                         "batch_seq": args.get("batch_seq"),
                         "batch_n": args.get("batch_n")}})

    # flow links: request dispatch slice -> first round slice of the
    # cid-matched attributed run (the obs/flow.py causal join as Chrome
    # flow events). "s" binds to the enclosing dispatch slice; "f" with
    # bp "e" binds to the enclosing round slice in the ranks process.
    if dispatch_anchor:
        run_by_cid = {e["cid"]: e["id"] for e in events
                      if e["ev"] == "run" and isinstance(e.get("cid"), str)}
        first_round: dict = {}   # run id -> (rank tid, ts) of first slice
        for e in events:
            if e["ev"] == "span" and e["bucket"] != "total":
                cur = first_round.get(e["run"])
                if cur is None or e["ts"] < cur[1]:
                    first_round[e["run"]] = (e["rank"] + 1, e["ts"])
        flow_id = 0
        for (rid, cid), ts in sorted(dispatch_anchor.items()):
            target = first_round.get(run_by_cid.get(cid))
            if target is None:
                continue
            flow_id += 1
            common = {"cat": "flow", "name": "dispatch",
                      "id": flow_id, "args": {"rid": rid, "cid": cid}}
            slices.append({"ph": "s", "pid": SERVE_PID, "tid": rid + 1,
                           "ts": ts, **common})
            slices.append({"ph": "f", "bp": "e", "pid": RANKS_PID,
                           "tid": target[0], "ts": target[1], **common})
    if serve_seen:
        out.append(_meta(SERVE_PID, 0, "process_name",
                         "serve requests (journal-derived)"))
        for rid in sorted(serve_seen):
            out.append(_meta(SERVE_PID, rid + 1, "thread_name",
                             f"request {rid}"))

    if hbm_seen:
        out.append(_meta(HOST_PID, HBM_TID, "thread_name", "hbm"))
    for rank in sorted(ranks_seen):
        out.append(_meta(RANKS_PID, rank + 1, "thread_name",
                         f"rank {rank}"))
    slices.sort(key=lambda s: (s["pid"], s["tid"], s["ts"]))
    out.extend(slices)
    return {"traceEvents": out, "displayTimeUnit": "ms"}
