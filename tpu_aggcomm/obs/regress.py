"""Bench-history schema validation and regression tracking.

The driver snapshots one ``BENCH_rNN.json`` and one ``MULTICHIP_rNN.json``
per growth round (round = NN). This module is the single definition of
their schemas — used by ``scripts/check_bench_schema.py`` (and its test)
to validate every artifact in the repo, and by ``bench.py
--check-regression`` to compare the newest round's headline metric
against the best prior round.

Regression comparisons are grouped per (metric, platform): the history
legitimately mixes TPU rounds (~µs/rep) with CPU-fallback rounds
(~tens of µs/rep), and a cross-platform delta would flag a 40x
"regression" that is just the fallback path. Lower is better (the
headline metric is seconds per rep).

The gate is statistical when the artifacts allow it: bench.py records
the per-trial differenced samples (harness/chained.py) as a ``samples``
list in its JSON line (parsed-schema v2; v1 artifacts simply lack the
key), and when BOTH the newest round and its baseline carry at least
``MIN_GATE_SAMPLES`` trials, the verdict uses a seeded percentile-
bootstrap CI on the relative median delta (obs/metrics.py): a
regression is flagged only when the point delta exceeds the tolerance
AND the CI excludes zero — a noisy 30% blip with a CI straddling zero
is jitter, not a regression. Without samples on either side the gate
falls back to the point-estimate delta, and says so in the verdict
(``gate: "point"`` + ``gate_note``).

Parsed-schema v3 (obs/ledger.py) adds a ``manifest`` block plus
``compile_seconds`` and ``hbm_peak_bytes`` to the bench line, and the
verdict grows a compile-time gate beside the runtime gate: a
point-estimate comparison of total compile wall seconds against the
same baseline round the runtime gate chose, active only when BOTH
rounds carry ``compile_seconds`` (the checked-in v1/v2 history is
unaffected). Compile walls through the tunnel jitter far more than
differenced runtimes, so the compile tolerance is wider
(``COMPILE_TOLERANCE``). Manifest drift between the compared rounds is
reported in the verdict (informational — drift explains a delta, it is
not itself a failure).

Beyond the pairwise gate, the verdict carries a ``trend`` block: the
seeded multi-round slope test from obs/history.py over the current
(metric, platform) series — "is this metric drifting across the WHOLE
history", not just "vs the best prior round". A drifting-up trend
fails the gate like a pairwise regression does; the committed history
is the input either way, so both verdicts are reproducible from the
same artifacts.

Artifact discovery itself (``load_history``) lives in obs/history.py —
the ONE scanner every consumer (this module, report_html, the schema
checker, ``inspect history``) shares, re-exported here for
compatibility.

This module also hosts the OpenMetrics text parser/validator
(``parse_openmetrics`` / ``validate_openmetrics``) used by the CI
telemetry gate: the text obs/export.py renders must parse, its
histogram buckets must be cumulative with ``+Inf`` matching ``_count``,
and its exact-quantile summaries must be internally consistent.

No jax anywhere here — bench.py's supervisor process imports this.
"""

from __future__ import annotations

import os
import re

from tpu_aggcomm.obs.history import load_history

__all__ = ["validate_bench", "validate_multichip", "validate_tune",
           "validate_traffic", "load_history", "check_regression",
           "parse_openmetrics", "validate_openmetrics",
           "parsed_schema_version", "DEFAULT_TOLERANCE",
           "MIN_GATE_SAMPLES", "COMPILE_TOLERANCE", "TUNE_SCHEMAS",
           "TRAFFIC_SCHEMAS", "PREDICT_SCHEMAS", "COMPARE_SCHEMAS",
           "SERVE_SCHEMAS", "SYNTH_SCHEMAS", "WORKLOAD_SCHEMAS",
           "WATCH_SCHEMAS", "PILOT_SCHEMAS", "FLOW_SCHEMAS",
           "validate_predict", "validate_compare", "validate_serve",
           "validate_synth", "validate_workload", "validate_watch",
           "validate_pilot", "validate_flow"]

#: Relative slowdown vs the best prior same-platform round that counts as
#: a regression. Differenced-chain numbers jitter a few percent
#: (harness/chained.py); 25% headroom keeps noise out of the signal.
DEFAULT_TOLERANCE = 0.25

#: Fewest per-trial samples per side for the bootstrap gate — below
#: this a CI over resamples is theater, so the gate falls back to the
#: point estimate (and notes it in the verdict).
MIN_GATE_SAMPLES = 3

#: Relative compile-time slowdown that counts as a compile regression.
#: Compile walls include one-off XLA autotuning and (on TPU) tunnel
#: RPCs, so they jitter far more than differenced runtimes — 50%
#: headroom flags real compile blowups without crying wolf.
COMPILE_TOLERANCE = 0.50


def _require(obj: dict, key: str, types, errors: list[str],
             where: str, *, nullable: bool = False) -> None:
    if key not in obj:
        errors.append(f"{where}: missing required key {key!r}")
        return
    v = obj[key]
    if v is None and nullable:
        return
    if not isinstance(v, types):
        tn = types.__name__ if isinstance(types, type) else \
            "/".join(t.__name__ for t in types)
        errors.append(f"{where}: key {key!r} must be {tn}, "
                      f"got {type(v).__name__}")


def validate_bench(obj, where: str = "BENCH") -> list[str]:
    """Schema errors (empty list = valid) for one BENCH_rNN.json blob:
    ``{n:int, cmd:str, rc:int, tail:str, parsed: null | {metric:str,
    value:number|null, unit:str, ...}}``. ``parsed`` is the bench.py
    one-JSON-line output when rc==0 and the line parsed; extra keys
    (vs_baseline, platform, tpu_error, tpu_attempts, error) are typed
    but optional, as is ``samples`` (parsed-schema v2: the per-trial
    differenced seconds behind ``value`` — must be a non-empty list of
    numbers when present; v1 artifacts predate it)."""
    errors: list[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: top level must be an object"]
    _require(obj, "n", int, errors, where)
    _require(obj, "cmd", str, errors, where)
    _require(obj, "rc", int, errors, where)
    _require(obj, "tail", str, errors, where)
    if "parsed" not in obj:
        errors.append(f"{where}: missing required key 'parsed'")
        return errors
    parsed = obj["parsed"]
    if parsed is None:
        return errors
    if not isinstance(parsed, dict):
        errors.append(f"{where}: 'parsed' must be null or an object")
        return errors
    w = f"{where}.parsed"
    _require(parsed, "metric", str, errors, w)
    _require(parsed, "value", (int, float), errors, w, nullable=True)
    _require(parsed, "unit", str, errors, w)
    for opt, types in (("vs_baseline", (int, float)), ("platform", str),
                       ("tpu_error", str), ("tpu_attempts", int),
                       ("error", str), ("fault", str)):
        if opt in parsed and parsed[opt] is not None \
                and not isinstance(parsed[opt], types):
            errors.append(f"{w}: optional key {opt!r} has wrong type "
                          f"{type(parsed[opt]).__name__}")
    if "samples" in parsed and parsed["samples"] is not None:
        s = parsed["samples"]
        if not isinstance(s, list) or not s or not all(
                isinstance(x, (int, float)) and not isinstance(x, bool)
                for x in s):
            errors.append(f"{w}: optional key 'samples' must be a "
                          f"non-empty list of numbers")
    # parsed-schema v3 (obs/ledger.py): manifest + compile/HBM telemetry
    if "manifest" in parsed and parsed["manifest"] is not None:
        m = parsed["manifest"]
        if not isinstance(m, dict):
            errors.append(f"{w}: optional key 'manifest' must be an "
                          f"object")
        else:
            for k, types in (("schema", int), ("versions", dict),
                             ("env", dict), ("python", str)):
                if k in m and m[k] is not None \
                        and not isinstance(m[k], types):
                    errors.append(
                        f"{w}.manifest: key {k!r} must be "
                        f"{types.__name__}, got {type(m[k]).__name__}")
    if "compile_seconds" in parsed and parsed["compile_seconds"] is not None:
        c = parsed["compile_seconds"]
        if not isinstance(c, (int, float)) or isinstance(c, bool) or c < 0:
            errors.append(f"{w}: optional key 'compile_seconds' must be "
                          f"a non-negative number")
    if "hbm_peak_bytes" in parsed and parsed["hbm_peak_bytes"] is not None:
        h = parsed["hbm_peak_bytes"]
        if not isinstance(h, int) or isinstance(h, bool) or h < 0:
            errors.append(f"{w}: optional key 'hbm_peak_bytes' must be "
                          f"a non-negative integer or null")
    # resilience records (tpu_aggcomm/resilience/policy.py): each must at
    # least carry its site and kind or the jax-free replay cannot group it
    if "resilience" in parsed and parsed["resilience"] is not None:
        r = parsed["resilience"]
        if not isinstance(r, list) or not all(
                isinstance(x, dict) and isinstance(x.get("site"), str)
                and isinstance(x.get("kind"), str) for x in r):
            errors.append(f"{w}: optional key 'resilience' must be a "
                          f"list of objects with str 'site' and 'kind'")
    return errors


def parsed_schema_version(parsed) -> int:
    """Which parsed-schema generation a bench line belongs to: 3 when it
    carries any ledger field (manifest/compile_seconds/hbm_peak_bytes),
    2 when it carries per-trial samples, 1 otherwise (including the
    degenerate parsed=null artifacts of failed rounds)."""
    if not isinstance(parsed, dict):
        return 1
    if any(parsed.get(k) is not None
           for k in ("manifest", "compile_seconds", "hbm_peak_bytes")):
        return 3
    return 2 if parsed.get("samples") is not None else 1


def validate_multichip(obj, where: str = "MULTICHIP") -> list[str]:
    """Schema errors for one MULTICHIP_rNN.json blob:
    ``{n_devices:int, rc:int, ok:bool, skipped:bool, tail:str}``."""
    errors: list[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: top level must be an object"]
    _require(obj, "n_devices", int, errors, where)
    _require(obj, "rc", int, errors, where)
    _require(obj, "ok", bool, errors, where)
    _require(obj, "skipped", bool, errors, where)
    _require(obj, "tail", str, errors, where)
    return errors


#: Accepted TUNE artifact schema tags (versioned like the bench
#: parsed-schema generations: a new tag is a new entry here, old tags
#: stay valid forever).
TUNE_SCHEMAS = ("tune-v1",)


def _is_num(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def validate_tune(obj, where: str = "TUNE") -> list[str]:
    """Schema errors (empty list = valid) for one ``TUNE_*.json``
    tuned-schedule cache artifact (tune/cache.py). A corrupt or stale
    artifact must FAIL here so ``--auto`` falls back loudly instead of
    being silently steered by garbage: the winner must be a recorded
    candidate, every sample batch must be a non-empty list of numbers,
    and every elimination must name candidate + leader present in the
    sample record."""
    errors: list[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: top level must be an object"]
    schema = obj.get("schema")
    if schema not in TUNE_SCHEMAS:
        errors.append(f"{where}: unknown schema tag {schema!r} "
                      f"(expected one of {list(TUNE_SCHEMAS)})")
        return errors
    key = obj.get("key")
    if not isinstance(key, dict):
        errors.append(f"{where}: missing/invalid 'key' object")
    else:
        for k, types in (("nprocs", int), ("data_size", int),
                         ("proc_node", int), ("direction", str),
                         ("backend", str), ("fingerprint", str)):
            _require(key, k, types, errors, f"{where}.key")
        if key.get("direction") not in ("all_to_many", "many_to_all",
                                        None):
            errors.append(f"{where}.key: direction must be "
                          f"'all_to_many' or 'many_to_all', got "
                          f"{key.get('direction')!r}")
    if "manifest" in obj and obj["manifest"] is not None \
            and not isinstance(obj["manifest"], dict):
        errors.append(f"{where}: 'manifest' must be null or an object")
    race = obj.get("race")
    if not isinstance(race, dict):
        errors.append(f"{where}: missing/invalid 'race' object")
        return errors
    w = f"{where}.race"
    for k, types in (("seed", int), ("alpha", float), ("n_boot", int),
                     ("max_batches", int), ("winner", str),
                     ("batches_run", int)):
        _require(race, k, types, errors, w)
    samples = race.get("samples")
    if not isinstance(samples, dict) or not samples:
        errors.append(f"{w}: 'samples' must be a non-empty object "
                      f"(cid -> list of batches)")
        samples = {}
    for cid, batches in samples.items():
        if not isinstance(batches, list) or not all(
                isinstance(b, list) and b and all(_is_num(x) for x in b)
                for b in batches):
            errors.append(f"{w}.samples[{cid!r}]: every batch must be "
                          f"a non-empty list of numbers")
    order = race.get("order")
    if order is not None:
        if not isinstance(order, list) \
                or sorted(order) != sorted(samples):
            errors.append(f"{w}: 'order' must list exactly the sampled "
                          f"candidate ids")
    winner = race.get("winner")
    if samples and isinstance(winner, str) and winner not in samples:
        errors.append(f"{w}: winner {winner!r} has no recorded samples")
    elims = race.get("eliminations")
    if not isinstance(elims, list):
        errors.append(f"{w}: 'eliminations' must be a list")
    else:
        for i, e in enumerate(elims):
            if not isinstance(e, dict):
                errors.append(f"{w}.eliminations[{i}]: must be an object")
                continue
            for k in ("batch", "candidate", "leader", "ci_pct"):
                if k not in e:
                    errors.append(f"{w}.eliminations[{i}]: missing {k!r}")
            for k in ("candidate", "leader"):
                if samples and e.get(k) is not None \
                        and e.get(k) not in samples:
                    errors.append(f"{w}.eliminations[{i}]: {k} "
                                  f"{e.get(k)!r} has no recorded samples")
            ci = e.get("ci_pct")
            if ci is not None and (not isinstance(ci, list)
                                   or len(ci) != 2
                                   or not all(_is_num(x) for x in ci)):
                errors.append(f"{w}.eliminations[{i}]: ci_pct must be "
                              f"[lo, hi]")
    win = obj.get("winner")
    if not isinstance(win, dict):
        errors.append(f"{where}: missing/invalid 'winner' object")
    else:
        for k in ("method", "cb_nodes", "comm_size", "agg_type"):
            _require(win, k, int, errors, f"{where}.winner")
        if isinstance(race.get("winner"), str) \
                and all(isinstance(win.get(k), int)
                        for k in ("method", "cb_nodes", "comm_size",
                                  "agg_type")):
            cid = (f"m{win['method']}:a{win['cb_nodes']}:"
                   f"c{win['comm_size']}:t{win['agg_type']}")
            if cid != race["winner"]:
                errors.append(f"{where}: winner object {cid} disagrees "
                              f"with race.winner {race['winner']!r}")
    if "synthetic" in obj and not isinstance(obj["synthetic"], bool):
        errors.append(f"{where}: 'synthetic' must be a bool")
    mp = obj.get("model_prune")
    if mp is not None:
        # optional --model-prune record (cli._model_prune): the split
        # must be internally consistent — raced order == kept, pruned
        # candidates priced, nothing both kept and pruned — because
        # tune --replay re-derives it from these fields alone
        w = f"{where}.model_prune"
        if not isinstance(mp, dict):
            errors.append(f"{w}: must be an object")
        else:
            for k, types in (("artifact", str), ("platform", str),
                             ("margin", (int, float)), ("best", str)):
                _require(mp, k, types, errors, w)
            preds = mp.get("predictions")
            if not isinstance(preds, dict) or not preds or not all(
                    v is None or _is_num(v) for v in preds.values()):
                errors.append(f"{w}: 'predictions' must be a non-empty "
                              f"object (cid -> seconds or null)")
                preds = {}
            kept, pruned = mp.get("kept"), mp.get("pruned")
            if not isinstance(kept, list) or not isinstance(pruned, list):
                errors.append(f"{w}: 'kept' and 'pruned' must be lists")
            else:
                if set(kept) & set(pruned):
                    errors.append(f"{w}: candidates both kept and "
                                  f"pruned: "
                                  f"{sorted(set(kept) & set(pruned))}")
                if preds and sorted(set(kept) | set(pruned)) \
                        != sorted(preds):
                    errors.append(f"{w}: kept+pruned must partition "
                                  f"the predicted candidates")
                if isinstance(race.get("order"), list) \
                        and race["order"] != kept:
                    errors.append(f"{w}: race.order must be exactly "
                                  f"the kept list — the race must run "
                                  f"precisely the survivors the prune "
                                  f"recorded")
                for cid in pruned:
                    if preds and not _is_num(preds.get(cid)):
                        errors.append(f"{w}: pruned candidate {cid!r} "
                                      f"has no recorded prediction — "
                                      f"an unpriced candidate must be "
                                      f"raced, never pruned")
    return errors


#: Accepted TRAFFIC artifact schema tags (obs/traffic.py audits, the
#: ``cli inspect traffic --json`` output) — versioned like TUNE_SCHEMAS.
TRAFFIC_SCHEMAS = ("traffic-v1",)

_TRAFFIC_VERDICTS = ("CONFORMS", "REFUTED", "EXEMPT")


def validate_traffic(obj, where: str = "TRAFFIC") -> list[str]:
    """Schema errors (empty list = valid) for one ``TRAFFIC_*.json``
    static-audit artifact (obs/traffic.py, written by ``cli inspect
    traffic --json``). The verdict must be internally consistent: a
    REFUTED audit must name at least one offender, a CONFORMS audit's
    peak must actually respect its bound — a committed artifact whose
    verdict its own numbers contradict must fail here."""
    errors: list[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: top level must be an object"]
    schema = obj.get("schema")
    if schema not in TRAFFIC_SCHEMAS:
        errors.append(f"{where}: unknown schema tag {schema!r} "
                      f"(expected one of {list(TRAFFIC_SCHEMAS)})")
        return errors
    cfg = obj.get("config")
    if not isinstance(cfg, dict):
        errors.append(f"{where}: missing/invalid 'config' object")
    else:
        for k in ("method", "nprocs", "cb_nodes", "data_size",
                  "comm_size", "proc_node", "agg_type"):
            _require(cfg, k, int, errors, f"{where}.config")
        _require(cfg, "name", str, errors, f"{where}.config")
        _require(cfg, "direction", str, errors, f"{where}.config")
        # optional fault-repaired provenance (audits of detoured schedules)
        if "fault" in cfg and cfg["fault"] is not None \
                and not isinstance(cfg["fault"], str):
            errors.append(f"{where}.config: optional key 'fault' must be "
                          f"a string")
    rounds = obj.get("rounds")
    if not isinstance(rounds, list):
        errors.append(f"{where}: 'rounds' must be a list")
        rounds = []
    for i, r in enumerate(rounds):
        w = f"{where}.rounds[{i}]"
        if not isinstance(r, dict):
            errors.append(f"{w}: must be an object")
            continue
        for k in ("round", "msgs", "bytes", "signals", "copies",
                  "max_incast", "incast_rank"):
            _require(r, k, int, errors, w)
        if "edges" in r and (not isinstance(r["edges"], list) or not all(
                isinstance(e, list) and len(e) == 3
                and all(isinstance(x, int) for x in e)
                for e in r["edges"])):
            errors.append(f"{w}: 'edges' must be a list of "
                          f"[src, dst, bytes] int triples")
    if not isinstance(obj.get("edges_omitted"), bool):
        errors.append(f"{where}: 'edges_omitted' must be a bool")
    tot = obj.get("totals")
    if not isinstance(tot, dict):
        errors.append(f"{where}: missing/invalid 'totals' object")
    else:
        for k in ("msgs", "bytes", "signals", "copies"):
            _require(tot, k, int, errors, f"{where}.totals")
    br = obj.get("barrier_rounds")
    if not isinstance(br, dict) or not all(
            isinstance(v, int) for v in br.values()):
        errors.append(f"{where}: 'barrier_rounds' must be an object of "
                      f"round -> barrier count")
    conf = obj.get("conformance")
    if not isinstance(conf, dict):
        errors.append(f"{where}: missing/invalid 'conformance' object")
        return errors
    w = f"{where}.conformance"
    verdict = conf.get("verdict")
    if verdict not in _TRAFFIC_VERDICTS:
        errors.append(f"{w}: verdict must be one of "
                      f"{list(_TRAFFIC_VERDICTS)}, got {verdict!r}")
    _require(conf, "bound", int, errors, w, nullable=True)
    _require(conf, "bound_formula", str, errors, w)
    _require(conf, "peak", int, errors, w, nullable=True)
    offenders = conf.get("offenders")
    if not isinstance(offenders, list):
        errors.append(f"{w}: 'offenders' must be a list")
        offenders = []
    for i, o in enumerate(offenders):
        if not isinstance(o, dict) or not all(
                isinstance(o.get(k), int)
                for k in ("rank", "round", "count")):
            errors.append(f"{w}.offenders[{i}]: must be an object with "
                          f"int rank/round/count")
    # verdict consistency — the artifact must not contradict itself
    bound, peak = conf.get("bound"), conf.get("peak")
    if verdict == "REFUTED" and not offenders:
        errors.append(f"{w}: REFUTED verdict with no offenders")
    if verdict == "CONFORMS" and isinstance(bound, int) \
            and isinstance(peak, int) and peak > bound:
        errors.append(f"{w}: CONFORMS verdict but peak {peak} exceeds "
                      f"bound {bound}")
    if verdict == "EXEMPT" and (bound is not None or offenders):
        errors.append(f"{w}: EXEMPT verdict must carry a null bound "
                      f"and no offenders")
    return errors


# ---------------------------------------------------------------------------
# OpenMetrics text parsing — the CI telemetry gate's validator for what
# obs/export.py renders. Deliberately small: it understands the subset
# this repo emits (TYPE lines; counter/gauge/histogram/summary samples
# with optional labels), not the full exposition grammar.

_SAMPLE_RE = re.compile(
    r'^([A-Za-z_:][A-Za-z0-9_:]*)(\{(.*)\})?\s+(\S+)\s*$')
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_value(raw: str) -> float:
    if raw == "+Inf":
        return float("inf")
    if raw == "-Inf":
        return float("-inf")
    return float(raw)


def parse_openmetrics(text: str) -> dict:
    """Parse OpenMetrics text into ``{"families": {name: type},
    "samples": [{"name", "labels", "value"}], "eof": bool}``.

    Raises ``ValueError`` on a line that is neither a comment, blank,
    TYPE declaration nor a well-formed sample — a torn or hand-mangled
    exposition must fail loudly, not half-parse."""
    families: dict[str, str] = {}
    samples: list[dict] = []
    eof = False
    for lineno, line in enumerate(text.splitlines(), 1):
        if eof:
            raise ValueError(f"line {lineno}: content after # EOF")
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            if parts[:2] == ["#", "EOF"] and len(parts) == 2:
                eof = True
            elif parts[:2] == ["#", "TYPE"]:
                if len(parts) != 4:
                    raise ValueError(f"line {lineno}: malformed TYPE "
                                     f"line {line!r}")
                families[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: not a metric sample: "
                             f"{line!r}")
        name, _, rawlabels, rawvalue = m.groups()
        labels = {k: v.replace('\\"', '"').replace("\\n", "\n")
                  .replace("\\\\", "\\")
                  for k, v in _LABEL_RE.findall(rawlabels or "")}
        try:
            value = _parse_value(rawvalue)
        except ValueError:
            raise ValueError(f"line {lineno}: unparseable value "
                             f"{rawvalue!r}")
        samples.append({"name": name, "labels": labels, "value": value})
    return {"families": families, "samples": samples, "eof": eof}


_SUFFIXES = ("_bucket", "_count", "_sum", "_total")


def _family_of(name: str, families: dict) -> str | None:
    """The declared family a sample belongs to (longest match wins:
    ``x_exact`` summary samples must bind to the ``x_exact`` family,
    not to histogram ``x`` via a bogus suffix split)."""
    candidates = [name] + [name[:-len(s)] for s in _SUFFIXES
                           if name.endswith(s)]
    for cand in sorted(candidates, key=len, reverse=True):
        if cand in families:
            return cand
    return None


def validate_openmetrics(text: str) -> list[str]:
    """Schema errors (empty list = valid) for an OpenMetrics exposition
    as obs/export.py renders it: must end with ``# EOF``; every sample
    must belong to a declared TYPE family; histogram buckets must be
    cumulative (non-decreasing in ``le`` order) with the ``+Inf``
    bucket equal to ``_count``; summary quantile labels must lie in
    [0, 1]. A parse failure is a single-error verdict."""
    try:
        parsed = parse_openmetrics(text)
    except ValueError as e:
        return [f"openmetrics: {e}"]
    errors: list[str] = []
    if not parsed["eof"]:
        errors.append("openmetrics: missing # EOF terminator")
    families = parsed["families"]
    hists: dict[tuple, dict] = {}
    for s in parsed["samples"]:
        fam = _family_of(s["name"], families)
        if fam is None:
            errors.append(f"openmetrics: sample {s['name']!r} has no "
                          f"TYPE declaration")
            continue
        ftype = families[fam]
        if ftype == "summary" and "quantile" in s["labels"]:
            try:
                q = float(s["labels"]["quantile"])
            except ValueError:
                q = -1.0
            if not 0.0 <= q <= 1.0:
                errors.append(f"openmetrics: {fam}: quantile label "
                              f"{s['labels']['quantile']!r} outside "
                              f"[0, 1]")
        if ftype != "histogram":
            continue
        key = (fam, tuple(sorted((k, v) for k, v in s["labels"].items()
                                 if k != "le")))
        h = hists.setdefault(key, {"buckets": [], "count": None,
                                   "sum_seen": False})
        if s["name"] == fam + "_bucket":
            le = s["labels"].get("le")
            if le is None:
                errors.append(f"openmetrics: {fam}: bucket without an "
                              f"'le' label")
                continue
            h["buckets"].append((_parse_value(le), s["value"]))
        elif s["name"] == fam + "_count":
            h["count"] = s["value"]
        elif s["name"] == fam + "_sum":
            h["sum_seen"] = True
    for (fam, labels), h in sorted(hists.items()):
        where = f"openmetrics: {fam}{dict(labels) if labels else ''}"
        buckets = sorted(h["buckets"])
        if not buckets:
            errors.append(f"{where}: histogram with no buckets")
            continue
        counts = [c for _le, c in buckets]
        if any(b > a for a, b in zip(counts[1:], counts)):
            errors.append(f"{where}: bucket counts not cumulative")
        if buckets[-1][0] != float("inf"):
            errors.append(f"{where}: missing le=+Inf bucket")
        elif h["count"] is not None and buckets[-1][1] != h["count"]:
            errors.append(f"{where}: +Inf bucket {buckets[-1][1]} != "
                          f"_count {h['count']}")
        if h["count"] is None:
            errors.append(f"{where}: missing _count sample")
        if not h["sum_seen"]:
            errors.append(f"{where}: missing _sum sample")
    return errors


def _gate_samples(parsed: dict):
    """The parsed blob's per-trial samples if usable for the bootstrap
    gate (a list of >= MIN_GATE_SAMPLES numbers), else None."""
    s = parsed.get("samples")
    if (isinstance(s, list) and len(s) >= MIN_GATE_SAMPLES
            and all(isinstance(x, (int, float))
                    and not isinstance(x, bool) for x in s)):
        return [float(x) for x in s]
    return None


def check_regression(root: str = ".",
                     tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Compare the newest round's parsed metric against the best prior
    same-(metric, platform) round.

    Returns a JSON-able verdict::

        {"check": "regression", "ok": bool, "rounds": N,
         "schema_errors": [...], "current": {...} | null,
         "baseline": {...} | null, "delta_pct": float | null,
         "tolerance_pct": float, "gate": "bootstrap"|"point"|null,
         "gate_note": str | null, "ci_delta_pct": [lo, hi] | null,
         "compile_delta_pct": float | null,
         "compile_tolerance_pct": float, "compile_note": str | null,
         "manifest_drift": [{"key","a","b"}, ...],
         "trend": {"verdict", "slope_pct_per_round", ...} | null,
         "history": [...]}

    ``ok`` is False only when the newest measurable round regresses
    against the best prior comparable round, or when any artifact fails
    schema validation (an unparsable artifact counts as a schema
    error). The regression test itself: with >= MIN_GATE_SAMPLES
    per-trial samples on BOTH sides, the point delta must exceed
    ``tolerance`` AND the seeded 95% bootstrap CI on the relative
    median delta must exclude zero (``gate: "bootstrap"``); otherwise
    the point delta alone decides and ``gate_note`` records which side
    lacked samples (``gate: "point"``). No prior comparable round (or
    no measurable current round) is ok=True with delta_pct null — a
    missing or empty history is not a regression. Independently, a
    ``drifting-up`` verdict from the longitudinal trend gate
    (obs/history.py, seeded) over the current (metric, platform)
    series also fails the check.
    """
    schema_errors: list[str] = []
    history = load_history(root, "BENCH", errors=schema_errors)
    for rnd, path, blob in history:
        schema_errors += validate_bench(blob, os.path.basename(path))
    for rnd, path, blob in load_history(root, "MULTICHIP",
                                        errors=schema_errors):
        schema_errors += validate_multichip(blob, os.path.basename(path))

    measurable = [
        (rnd, path, blob["parsed"]) for rnd, path, blob in history
        if isinstance(blob.get("parsed"), dict)
        and isinstance(blob["parsed"].get("value"), (int, float))]
    def _compile_s(p):
        c = p.get("compile_seconds")
        return float(c) if isinstance(c, (int, float)) \
            and not isinstance(c, bool) else None

    rows = [{"round": rnd, "metric": p["metric"],
             "platform": p.get("platform", "unknown"),
             "value": p["value"], "unit": p.get("unit", ""),
             "samples": _gate_samples(p),
             "compile_seconds": _compile_s(p)}
            for rnd, _path, p in measurable]
    # manifests looked up per round when the compile gate fires — kept
    # OUT of the verdict rows (the one-JSON-line contract should not
    # ship whole env blocks per round)
    manifests = {rnd: p.get("manifest") for rnd, _path, p in measurable
                 if isinstance(p.get("manifest"), dict)}

    verdict: dict = {"check": "regression", "ok": True,
                     "rounds": len(history),
                     "schema_errors": schema_errors,
                     "current": None, "baseline": None,
                     "delta_pct": None,
                     "tolerance_pct": tolerance * 100.0,
                     "gate": None, "gate_note": None,
                     "ci_delta_pct": None,
                     "compile_delta_pct": None,
                     "compile_tolerance_pct": COMPILE_TOLERANCE * 100.0,
                     "compile_note": None,
                     "manifest_drift": [],
                     "trend": None,
                     "history": rows}
    if schema_errors:
        verdict["ok"] = False
    if not rows:
        verdict["gate_note"] = "no measurable bench history"
        return verdict
    cur = rows[-1]
    verdict["current"] = cur

    # longitudinal trend gate (obs/history.py): the seeded bootstrap
    # slope test over the WHOLE (metric, platform) series the current
    # round belongs to — catches a slow creep the pairwise gate never
    # sees (each round within tolerance of the best prior, yet the
    # series marching up). Same determinism contract as the pairwise
    # bootstrap: seeded, so the same artifacts reproduce the verdict.
    from tpu_aggcomm.obs.history import trend_gate
    series = [(r["round"], r["value"]) for r in rows
              if r["metric"] == cur["metric"]
              and r["platform"] == cur["platform"]]
    trend = trend_gate(series)
    trend["series"] = f"{cur['metric']} | {cur['platform']}"
    verdict["trend"] = trend
    if trend["verdict"] == "drifting-up":
        verdict["ok"] = False
    prior = [r for r in rows[:-1]
             if r["metric"] == cur["metric"]
             and r["platform"] == cur["platform"]]
    if not prior:
        verdict["gate_note"] = "no prior comparable round"
        return verdict
    best = min(prior, key=lambda r: r["value"])
    verdict["baseline"] = best
    delta = (cur["value"] - best["value"]) / best["value"]
    verdict["delta_pct"] = delta * 100.0

    if cur["samples"] and best["samples"]:
        from tpu_aggcomm.obs.metrics import bootstrap_delta_ci
        lo, hi = bootstrap_delta_ci(best["samples"], cur["samples"],
                                    relative=True, seed=0)
        verdict["gate"] = "bootstrap"
        verdict["ci_delta_pct"] = [lo * 100.0, hi * 100.0]
        # statistically significant (CI excludes zero on the slow side)
        # AND practically significant (beyond the noise tolerance)
        if delta > tolerance and lo > 0:
            verdict["ok"] = False
        elif delta > tolerance:
            verdict["gate_note"] = (
                "point delta exceeds tolerance but bootstrap CI "
                "includes zero — not flagged")
    else:
        missing = ("baseline" if cur["samples"] else
                   "current" if best["samples"] else
                   "current and baseline")
        verdict["gate"] = "point"
        verdict["gate_note"] = (
            f"samples missing on {missing} round(s); "
            f"point-estimate delta only")
        if delta > tolerance:
            verdict["ok"] = False

    # compile-time gate (parsed-schema v3): one total per round, so this
    # is always a deterministic point comparison against the SAME
    # baseline round the runtime gate chose — one coherent verdict, and
    # reproducible from the same artifacts by construction.
    ccur, cbase = cur["compile_seconds"], best["compile_seconds"]
    if ccur is not None and cbase is not None and cbase > 0:
        cdelta = (ccur - cbase) / cbase
        verdict["compile_delta_pct"] = cdelta * 100.0
        if cdelta > COMPILE_TOLERANCE:
            verdict["ok"] = False
            verdict["compile_note"] = (
                f"compile time regressed: {ccur:.3f}s vs baseline "
                f"{cbase:.3f}s")
    else:
        missing = ("baseline" if ccur is not None else
                   "current" if cbase is not None else
                   "current and baseline")
        verdict["compile_note"] = (
            f"compile_seconds missing on {missing} round(s) "
            f"(pre-v3 artifacts); compile gate inactive")

    # environment drift between the compared rounds — informational:
    # drift EXPLAINS a delta (different jax, different platform knobs),
    # it is not itself a regression
    from tpu_aggcomm.obs.ledger import diff_manifests
    verdict["manifest_drift"] = diff_manifests(
        manifests.get(best["round"]), manifests.get(cur["round"]))
    return verdict


PREDICT_SCHEMAS = ("predict-v1",)
COMPARE_SCHEMAS = ("compare-v1",)


def validate_predict(obj, where: str = "PREDICT") -> list[str]:
    """Schema errors (empty list = valid) for one ``PREDICT_*.json``
    cost-model artifact (model/artifact.py). Beyond shape, this checks
    the artifact against ITSELF: every explain run's tolerance must be
    its platform block's tolerance verbatim, and an UNEXPLAINED round
    verdict whose own recorded deviation sits inside that tolerance is
    a contradiction — an artifact whose verdicts its own numbers
    contradict must fail, the same discipline as validate_traffic."""
    errors: list[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: top level must be an object"]
    schema = obj.get("schema")
    if schema not in PREDICT_SCHEMAS:
        errors.append(f"{where}: unknown schema tag {schema!r} "
                      f"(expected one of {list(PREDICT_SCHEMAS)})")
        return errors
    _require(obj, "seed", int, errors, where)
    _require(obj, "created_unix", (int, float), errors, where)

    inputs = obj.get("inputs")
    if not isinstance(inputs, dict):
        errors.append(f"{where}: missing/invalid 'inputs' object")
    else:
        _require(inputs, "results_md", str, errors, f"{where}.inputs")
        traces = inputs.get("traces")
        if not isinstance(traces, list) or not traces \
                or not all(isinstance(t, str) for t in traces):
            errors.append(f"{where}.inputs: 'traces' must be a "
                          f"non-empty list of file names")
        excl = inputs.get("excluded")
        if not isinstance(excl, list) or not all(
                isinstance(e, dict) and isinstance(e.get("artifact"), str)
                and isinstance(e.get("reason"), str) for e in excl):
            errors.append(f"{where}.inputs: 'excluded' must be a list "
                          f"of {{artifact, reason}} records — every "
                          f"deliberate calibration exclusion must name "
                          f"its reason")

    from tpu_aggcomm.model.features import PARAM_NAMES
    platforms = obj.get("platforms")
    tol_by_platform: dict = {}
    if not isinstance(platforms, dict) or not platforms:
        errors.append(f"{where}: 'platforms' must be a non-empty "
                      f"object of calibrated blocks")
        platforms = {}
    for plat, block in platforms.items():
        w = f"{where}.platforms[{plat!r}]"
        if not isinstance(block, dict):
            errors.append(f"{w}: must be an object")
            continue
        for k, types in (("granularity", str), ("observations", int),
                         ("seed", int)):
            _require(block, k, types, errors, w)
        if block.get("granularity") not in ("cell", "round", None):
            errors.append(f"{w}: granularity must be 'cell' or "
                          f"'round', got {block.get('granularity')!r}")
        params = block.get("params")
        if not isinstance(params, dict):
            errors.append(f"{w}: missing/invalid 'params' object")
        else:
            for name in PARAM_NAMES:
                v = params.get(name)
                if not _is_num(v) or v < 0:
                    errors.append(f"{w}.params: {name!r} must be a "
                                  f"non-negative number (a fitted cost "
                                  f"is physics, not noise), got {v!r}")
        tol = block.get("tolerance_rel")
        if not _is_num(tol) or tol <= 0:
            errors.append(f"{w}: 'tolerance_rel' must be a positive "
                          f"number, got {tol!r}")
        else:
            tol_by_platform[plat] = float(tol)
        resid = block.get("residual_rel")
        if not isinstance(resid, list) or not all(
                _is_num(x) for x in resid):
            errors.append(f"{w}: 'residual_rel' must be a list of "
                          f"numbers")
        elif isinstance(block.get("observations"), int) \
                and len(resid) != block["observations"]:
            errors.append(f"{w}: {len(resid)} residuals recorded for "
                          f"{block['observations']} observations — the "
                          f"fit evidence must match the fit")

    val = obj.get("validation")
    if not isinstance(val, dict) or not val:
        errors.append(f"{where}: 'validation' must be a non-empty "
                      f"object (one rank-order report per grid)")
        val = {}
    for name, v in val.items():
        w = f"{where}.validation[{name!r}]"
        if not isinstance(v, dict):
            errors.append(f"{w}: must be an object")
            continue
        _require(v, "cells", int, errors, w)
        _require(v, "held_out", bool, errors, w)
        if "tau_b" not in v or (v["tau_b"] is not None
                                and not _is_num(v["tau_b"])):
            errors.append(f"{w}: 'tau_b' must be a number or null")
        t1 = v.get("top1")
        if not isinstance(t1, dict) \
                or not isinstance(t1.get("agree"), bool) \
                or not isinstance(t1.get("predicted_class"), list) \
                or not t1.get("predicted_class"):
            errors.append(f"{w}: 'top1' must carry bool 'agree' and a "
                          f"non-empty 'predicted_class'")

    expl = obj.get("explain")
    if not isinstance(expl, list) or not expl:
        errors.append(f"{where}: 'explain' must be a non-empty list "
                      f"(the verdict taxonomy demonstrated on the "
                      f"committed traces)")
        expl = []
    for i, exp in enumerate(expl):
        w = f"{where}.explain[{i}]"
        if not isinstance(exp, dict):
            errors.append(f"{w}: must be an object")
            continue
        _require(exp, "trace", str, errors, w)
        plat = exp.get("platform")
        if plat not in platforms:
            errors.append(f"{w}: platform {plat!r} has no calibrated "
                          f"block in 'platforms'")
        runs = exp.get("runs")
        if not isinstance(runs, list) or not runs:
            errors.append(f"{w}: 'runs' must be a non-empty list")
            continue
        for j, run in enumerate(runs):
            rw = f"{w}.runs[{j}]"
            if not isinstance(run, dict):
                errors.append(f"{rw}: must be an object")
                continue
            tol = run.get("tolerance_rel")
            want = tol_by_platform.get(plat)
            if want is not None and tol != want:
                errors.append(f"{rw}: tolerance_rel {tol!r} is not the "
                              f"{plat} block's {want!r} — verdicts must "
                              f"be judged at the calibrated tolerance")
            rounds = run.get("rounds")
            if not isinstance(rounds, list) or not rounds:
                errors.append(f"{rw}: 'rounds' must be a non-empty "
                              f"list")
                rounds = []
            for row in rounds:
                if not isinstance(row, dict) \
                        or not isinstance(row.get("verdict"), str) \
                        or not _is_num(row.get("predicted_s")):
                    errors.append(f"{rw}: every round row needs a "
                                  f"string 'verdict' and numeric "
                                  f"'predicted_s'")
                    continue
                dev = row.get("deviation_rel")
                if row["verdict"].startswith("UNEXPLAINED") \
                        and _is_num(dev) and _is_num(tol) \
                        and abs(dev) <= tol:
                    errors.append(
                        f"{rw} round {row.get('round')}: verdict says "
                        f"UNEXPLAINED but its own deviation "
                        f"{dev:+.3f} sits inside tolerance {tol:.3f} — "
                        f"the verdict contradicts its numbers")
            total = run.get("total")
            if not isinstance(total, dict) \
                    or not isinstance(total.get("verdict"), str) \
                    or not _is_num(total.get("predicted_s")):
                errors.append(f"{rw}: 'total' must carry a string "
                              f"'verdict' and numeric 'predicted_s'")
    return errors


def validate_compare(obj, where: str = "COMPARE") -> list[str]:
    """Schema errors (empty list = valid) for one ``compare-v1``
    artifact (``inspect compare --json``, obs/compare.py). The payload
    is the compare result verbatim; this pins the shape downstream
    tooling may rely on: every run delta names both sides' totals, and
    a grid export lists its unmatched cells instead of dropping them."""
    errors: list[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: top level must be an object"]
    schema = obj.get("schema")
    if schema not in COMPARE_SCHEMAS:
        errors.append(f"{where}: unknown schema tag {schema!r} "
                      f"(expected one of {list(COMPARE_SCHEMAS)})")
        return errors
    _require(obj, "created_unix", (int, float), errors, where)
    res = obj.get("result")
    if not isinstance(res, dict):
        errors.append(f"{where}: missing/invalid 'result' object")
        return errors
    if res.get("by") not in ("rank", "round", "phase"):
        errors.append(f"{where}.result: 'by' must be rank/round/phase, "
                      f"got {res.get('by')!r}")

    def _check_runs(runs, w):
        if not isinstance(runs, list) or not runs:
            errors.append(f"{w}: 'runs' must be a non-empty list")
            return
        for j, run in enumerate(runs):
            rw = f"{w}.runs[{j}]"
            if not isinstance(run, dict):
                errors.append(f"{rw}: must be an object")
                continue
            for k in ("total_a_s", "total_b_s", "total_delta_pct"):
                if not _is_num(run.get(k)):
                    errors.append(f"{rw}: {k!r} must be a number")
            if not isinstance(run.get("method"), int):
                errors.append(f"{rw}: 'method' must be an int")
            table = run.get("table")
            if not isinstance(table, list):
                errors.append(f"{rw}: 'table' must be a list")
                continue
            for row in table:
                if not isinstance(row, dict) \
                        or not _is_num(row.get("a_s")) \
                        or not _is_num(row.get("b_s")):
                    errors.append(f"{rw}: every table row needs "
                                  f"numeric 'a_s' and 'b_s'")
                    break
            dom = run.get("dominant")
            if dom is not None and (not isinstance(dom, dict)
                                    or not _is_num(dom.get("delta_s"))):
                errors.append(f"{rw}: 'dominant' must be null or an "
                              f"object with numeric 'delta_s'")

    if "grid" in res:
        grid = res.get("grid")
        if not isinstance(grid, list):
            errors.append(f"{where}.result: 'grid' must be a list")
            grid = []
        for cell in grid:
            if not isinstance(cell, dict) \
                    or not isinstance(cell.get("cell"), str):
                errors.append(f"{where}.result.grid: every cell must "
                              f"name its trace basename")
                continue
            _check_runs(cell.get("runs"), f"{where}.result."
                        f"grid[{cell['cell']!r}]")
        for k in ("only_a", "only_b"):
            if not isinstance(res.get(k), list):
                errors.append(f"{where}.result: {k!r} must be a list "
                              f"(unmatched cells are reported, never "
                              f"dropped)")
    else:
        _check_runs(res.get("runs"), f"{where}.result")
    return errors


SERVE_SCHEMAS = ("serve-v1", "serve-v2")


def validate_serve(obj, where: str = "SERVE") -> list[str]:
    """Schema errors (empty list = valid) for one ``SERVE_r*.json``
    load-generator artifact (scripts/serve_loadgen.py). Beyond shape,
    this checks the artifact against ITSELF, the validate_traffic /
    validate_predict discipline: every latency quantile must be
    ``obs.metrics.percentile`` over the recorded samples float-exactly,
    the warm/cold split must partition the completed samples, and the
    request accounting must add up — a summary its own samples
    contradict is schema-invalid."""
    from tpu_aggcomm.obs.metrics import percentile

    errors: list[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: top level must be an object"]
    schema = obj.get("schema")
    if schema not in SERVE_SCHEMAS:
        errors.append(f"{where}: unknown schema tag {schema!r} "
                      f"(expected one of {list(SERVE_SCHEMAS)})")
        return errors
    _require(obj, "created_unix", (int, float), errors, where)
    _require(obj, "backend", str, errors, where)
    _require(obj, "duration_s", (int, float), errors, where)
    for k in ("requests", "completed", "errors", "verified"):
        _require(obj, k, int, errors, where)
        v = obj.get(k)
        if isinstance(v, int) and not isinstance(v, bool) and v < 0:
            errors.append(f"{where}: {k!r} must be non-negative, "
                          f"got {v}")
    man = obj.get("manifest")
    if man is not None and not isinstance(man, dict):
        errors.append(f"{where}: 'manifest' must be an object or null")
    shapes = obj.get("shapes")
    if not isinstance(shapes, list) or not shapes \
            or not all(isinstance(s, str) for s in shapes):
        errors.append(f"{where}: 'shapes' must be a non-empty list of "
                      f"shape-spec strings")

    shed = 0
    if schema == "serve-v2":
        # v2 (overload-aware): shed requests are accounted separately
        # from errors, and goodput is the completed rate
        for k in ("shed", "deadline_missed"):
            _require(obj, k, int, errors, where)
            v = obj.get(k)
            if isinstance(v, int) and not isinstance(v, bool) and v < 0:
                errors.append(f"{where}: {k!r} must be non-negative, "
                              f"got {v}")
        shed = obj.get("shed") if isinstance(obj.get("shed"), int) else 0
        sr = obj.get("shed_reasons")
        if sr is not None:
            if not isinstance(sr, dict) or not all(
                    isinstance(k, str) and isinstance(v, int)
                    for k, v in sr.items()):
                errors.append(f"{where}: 'shed_reasons' must map reason "
                              f"-> count")
            elif sum(sr.values()) != shed:
                errors.append(f"{where}: shed_reasons sum to "
                              f"{sum(sr.values())} but shed is {shed} — "
                              f"every shed must carry a reason")

    req, comp, errs = obj.get("requests"), obj.get("completed"), \
        obj.get("errors")
    if isinstance(req, int) and isinstance(comp, int) \
            and isinstance(errs, int) and comp + errs + shed != req:
        parts = f"completed {comp} + errors {errs}"
        if schema == "serve-v2":
            parts += f" + shed {shed}"
        errors.append(f"{where}: {parts} != requests {req} — every "
                      f"request must be accounted for")
    if isinstance(comp, int) and isinstance(obj.get("verified"), int) \
            and obj["verified"] > comp:
        errors.append(f"{where}: verified {obj['verified']} > "
                      f"completed {comp}")

    samples = obj.get("samples")
    if not isinstance(samples, list) or not samples \
            or not all(_is_num(s) for s in samples):
        errors.append(f"{where}: 'samples' must be a non-empty list of "
                      f"per-request latency seconds")
        samples = None
    elif isinstance(comp, int) and len(samples) != comp:
        errors.append(f"{where}: {len(samples)} samples recorded for "
                      f"{comp} completed requests — the evidence must "
                      f"match the count")

    lat = obj.get("latency_s")
    if not isinstance(lat, dict):
        errors.append(f"{where}: 'latency_s' must be an object")
    elif samples:
        for qk, q in (("p50", 50.0), ("p95", 95.0), ("p99", 99.0)):
            want = percentile(samples, q)
            got = lat.get(qk)
            if not _is_num(got) or got != want:
                errors.append(f"{where}.latency_s: {qk} {got!r} is not "
                              f"percentile(samples, {q:g}) == {want!r} "
                              f"— quantiles must be re-derivable from "
                              f"the samples float-exactly")

    split_n = 0
    for part in ("warm", "cold"):
        blk = obj.get(part)
        w = f"{where}.{part}"
        if not isinstance(blk, dict):
            errors.append(f"{w}: must be an object")
            continue
        _require(blk, "n", int, errors, w)
        psamp = blk.get("samples")
        if not isinstance(psamp, list) or not all(
                _is_num(s) for s in psamp):
            errors.append(f"{w}: 'samples' must be a list of numbers")
            continue
        if isinstance(blk.get("n"), int) and blk["n"] != len(psamp):
            errors.append(f"{w}: n {blk['n']} != {len(psamp)} samples")
        split_n += len(psamp)
        p50 = blk.get("p50")
        if psamp:
            want = percentile(psamp, 50.0)
            if not _is_num(p50) or p50 != want:
                errors.append(f"{w}: p50 {p50!r} is not "
                              f"percentile(samples, 50) == {want!r}")
        elif p50 is not None:
            errors.append(f"{w}: p50 must be null with no samples, "
                          f"got {p50!r}")
    if samples and isinstance(obj.get("warm"), dict) \
            and isinstance(obj.get("cold"), dict) \
            and split_n != len(samples):
        errors.append(f"{where}: warm+cold split carries {split_n} "
                      f"samples for {len(samples)} completed — the "
                      f"split must partition the samples")

    dur, rps = obj.get("duration_s"), obj.get("rps")
    if _is_num(dur) and dur <= 0:
        errors.append(f"{where}: duration_s must be positive, "
                      f"got {dur!r}")
    if _is_num(dur) and dur > 0 and isinstance(comp, int):
        want = comp / dur
        if not _is_num(rps) or abs(rps - want) > 1e-9 * max(1.0, want):
            errors.append(f"{where}: rps {rps!r} != completed/"
                          f"duration_s == {want!r}")
        if schema == "serve-v2":
            gp = obj.get("goodput_rps")
            if not _is_num(gp) or abs(gp - want) > 1e-9 * max(1.0, want):
                errors.append(f"{where}: goodput_rps {gp!r} != "
                              f"completed/duration_s == {want!r}")

    cache = obj.get("cache")
    if not isinstance(cache, dict):
        errors.append(f"{where}: 'cache' must be an object")
    else:
        for k in ("entries", "hits", "misses", "evictions", "compiles"):
            _require(cache, k, int, errors, f"{where}.cache")
    batch = obj.get("batch")
    if not isinstance(batch, dict):
        errors.append(f"{where}: 'batch' must be an object")
    else:
        for k in ("batches", "max_batch", "batched_requests"):
            _require(batch, k, int, errors, f"{where}.batch")
    return errors


#: Accepted SYNTH artifact schema tags (tpu_aggcomm/synth/artifact.py,
#: the ``cli synth`` output) — versioned like TUNE_SCHEMAS.
SYNTH_SCHEMAS = ("synth-v1",)

_SYNTH_ROW_VERDICTS = ("PROVEN", "REFUTED", "INVALID")


def validate_synth(obj, where: str = "SYNTH") -> list[str]:
    """Schema errors (empty list = valid) for one ``SYNTH_r*.json``
    synthesis artifact (tpu_aggcomm/synth/). The internal-consistency
    rule is the traffic/predict one, applied three times over: the
    finalists must be the top of the PROVEN survivor ranking, the
    registration block must bind exactly the finalists, and the winner
    must be SYNTHESIZED, carry PROVEN/CONFORMS verdicts, match
    ``race.winner``, and have the smallest pooled sample median among
    the non-eliminated survivors — a winner whose own recorded race
    contradicts it is schema-invalid."""
    import statistics

    errors: list[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: top level must be an object"]
    schema = obj.get("schema")
    if schema not in SYNTH_SCHEMAS:
        errors.append(f"{where}: unknown schema tag {schema!r} "
                      f"(expected one of {list(SYNTH_SCHEMAS)})")
        return errors
    for k, types in (("seed", int), ("backend", str)):
        _require(obj, k, types, errors, where)
    if "synthetic" in obj and obj["synthetic"] is not None \
            and not isinstance(obj["synthetic"], str):
        errors.append(f"{where}: 'synthetic' must be null or the spec "
                      f"string")
    cfg = obj.get("config")
    if not isinstance(cfg, dict):
        errors.append(f"{where}: missing/invalid 'config' object")
    else:
        for k in ("nprocs", "cb_nodes", "comm_size", "data_size",
                  "proc_node", "agg_type"):
            _require(cfg, k, int, errors, f"{where}.config")
        _require(cfg, "direction", str, errors, f"{where}.config")
    if "manifest" in obj and obj["manifest"] is not None \
            and not isinstance(obj["manifest"], dict):
        errors.append(f"{where}: 'manifest' must be null or an object")

    # --- search block: rows, prune accounting, survivor ranking -------
    sr = obj.get("search")
    if not isinstance(sr, dict):
        errors.append(f"{where}: missing/invalid 'search' object")
        return errors
    w = f"{where}.search"
    for k in ("seed", "space_size", "evaluated", "init", "mutate_rounds",
              "beam", "top_k"):
        _require(sr, k, int, errors, w)
    rows = sr.get("rows")
    if not isinstance(rows, list) or not rows:
        errors.append(f"{w}: 'rows' must be a non-empty list")
        rows = []
    by_comp: dict = {}
    for i, r in enumerate(rows):
        if not isinstance(r, dict):
            errors.append(f"{w}.rows[{i}]: must be an object")
            continue
        comp = r.get("composition")
        if not isinstance(comp, str) or not comp:
            errors.append(f"{w}.rows[{i}]: missing composition string")
            continue
        by_comp[comp] = r
        if r.get("verdict") not in _SYNTH_ROW_VERDICTS:
            errors.append(f"{w}.rows[{i}]: verdict must be one of "
                          f"{_SYNTH_ROW_VERDICTS}, got "
                          f"{r.get('verdict')!r}")
        pruned_by = r.get("pruned_by")
        if pruned_by is not None and not isinstance(pruned_by, str):
            errors.append(f"{w}.rows[{i}]: pruned_by must be null or "
                          f"a named reason")
        if r.get("verdict") in ("REFUTED", "INVALID") and not pruned_by:
            errors.append(f"{w}.rows[{i}]: a {r.get('verdict')} row "
                          f"must name its prune reason")
    survivors = sr.get("survivors")
    finalists = sr.get("finalists")
    if not isinstance(survivors, list) or not isinstance(finalists, list):
        errors.append(f"{w}: 'survivors' and 'finalists' must be lists")
        survivors, finalists = [], []
    for comp in survivors:
        r = by_comp.get(comp)
        if r is None:
            errors.append(f"{w}: survivor {comp!r} has no row")
        elif r.get("verdict") != "PROVEN" or r.get("pruned_by"):
            errors.append(f"{w}: survivor {comp!r} is not an unpruned "
                          f"PROVEN row — the ranking contradicts the "
                          f"rows")
    top_k = sr.get("top_k")
    if isinstance(top_k, int) and finalists != survivors[:top_k]:
        errors.append(f"{w}: finalists must be survivors[:top_k] "
                      f"(ranked prefix), got {finalists}")
    pruned = sr.get("pruned")
    if not isinstance(pruned, dict):
        errors.append(f"{w}: missing/invalid 'pruned' counters")
    elif rows and all(isinstance(r, dict) for r in rows):
        for kind, prefix in (("invalid", "build:"), ("check", "check:"),
                             ("traffic", "traffic:"),
                             ("dominated", "dominated:")):
            n = sum(1 for r in rows
                    if isinstance(r.get("pruned_by"), str)
                    and r["pruned_by"].startswith(prefix))
            if pruned.get(kind) != n:
                errors.append(f"{w}.pruned[{kind!r}]: counter "
                              f"{pruned.get(kind)!r} != {n} rows with "
                              f"'{prefix}' reasons")

    # --- registration block: exactly the finalists, ids > 100 ---------
    reg = obj.get("registration")
    if not isinstance(reg, dict) or not reg:
        errors.append(f"{where}: missing/invalid 'registration' object")
        reg = {}
    mids = []
    for mid_text, entry in reg.items():
        try:
            mid = int(mid_text)
        except (TypeError, ValueError):
            errors.append(f"{where}.registration: id {mid_text!r} is "
                          f"not an int")
            continue
        mids.append(mid)
        if mid <= 100:
            errors.append(f"{where}.registration: id {mid} is outside "
                          f"the reserved synthesized range (> 100)")
        if not isinstance(entry, dict) \
                or not isinstance(entry.get("composition"), str):
            errors.append(f"{where}.registration[{mid_text}]: missing "
                          f"composition")
    reg_comps = [reg[str(m)].get("composition") for m in sorted(mids)
                 if isinstance(reg.get(str(m)), dict)]
    if finalists and reg_comps != finalists:
        errors.append(f"{where}: registration compositions {reg_comps} "
                      f"!= search finalists {finalists}")

    # --- race block: the tune-v1 discipline ---------------------------
    race = obj.get("race")
    if not isinstance(race, dict):
        errors.append(f"{where}: missing/invalid 'race' object")
        return errors
    w = f"{where}.race"
    for k, types in (("seed", int), ("alpha", float), ("n_boot", int),
                     ("max_batches", int), ("winner", str),
                     ("batches_run", int)):
        _require(race, k, types, errors, w)
    samples = race.get("samples")
    if not isinstance(samples, dict) or not samples:
        errors.append(f"{w}: 'samples' must be a non-empty object "
                      f"(cid -> list of batches)")
        samples = {}
    for cid, batches in samples.items():
        if not isinstance(batches, list) or not all(
                isinstance(b, list) and b and all(_is_num(x) for x in b)
                for b in batches):
            errors.append(f"{w}.samples[{cid!r}]: every batch must be "
                          f"a non-empty list of numbers")
    order = race.get("order")
    if order is not None:
        if not isinstance(order, list) \
                or sorted(order) != sorted(samples):
            errors.append(f"{w}: 'order' must list exactly the sampled "
                          f"candidate ids")
    winner_cid = race.get("winner")
    if samples and isinstance(winner_cid, str) \
            and winner_cid not in samples:
        errors.append(f"{w}: winner {winner_cid!r} has no recorded "
                      f"samples")
    elims = race.get("eliminations")
    eliminated: set = set()
    if not isinstance(elims, list):
        errors.append(f"{w}: 'eliminations' must be a list")
        elims = []
    for i, e in enumerate(elims):
        if not isinstance(e, dict):
            errors.append(f"{w}.eliminations[{i}]: must be an object")
            continue
        for k in ("batch", "candidate", "leader", "ci_pct"):
            if k not in e:
                errors.append(f"{w}.eliminations[{i}]: missing {k!r}")
        eliminated.add(e.get("candidate"))
        for k in ("candidate", "leader"):
            if samples and e.get(k) is not None \
                    and e.get(k) not in samples:
                errors.append(f"{w}.eliminations[{i}]: {k} "
                              f"{e.get(k)!r} has no recorded samples")

    # --- winner: synthesized, verdicts carried, race-consistent -------
    win = obj.get("winner")
    if not isinstance(win, dict):
        errors.append(f"{where}: missing/invalid 'winner' object")
        return errors
    w = f"{where}.winner"
    for k, types in (("cid", str), ("method_id", int),
                     ("median_s", (int, float)), ("synthesized", bool)):
        _require(win, k, types, errors, w)
    if isinstance(win.get("cid"), str) and isinstance(winner_cid, str) \
            and win["cid"] != winner_cid:
        errors.append(f"{w}: cid {win['cid']!r} disagrees with "
                      f"race.winner {winner_cid!r}")
    if win.get("synthesized") is not True:
        errors.append(f"{w}: a committed artifact's winner must be "
                      f"synthesized — a reference-method win is not an "
                      f"artifact")
    else:
        mid = win.get("method_id")
        entry = reg.get(str(mid)) if isinstance(mid, int) else None
        if not isinstance(entry, dict):
            errors.append(f"{w}: method_id {mid!r} is not in the "
                          f"registration block")
        elif entry.get("composition") != win.get("composition"):
            errors.append(f"{w}: composition {win.get('composition')!r} "
                          f"!= registration[{mid}] "
                          f"{entry.get('composition')!r}")
        if win.get("check_verdict") != "PROVEN":
            errors.append(f"{w}: check_verdict must be 'PROVEN', got "
                          f"{win.get('check_verdict')!r}")
        if win.get("traffic_verdict") != "CONFORMS":
            errors.append(f"{w}: traffic_verdict must be 'CONFORMS', "
                          f"got {win.get('traffic_verdict')!r}")
    # the race must actually support the winner: smallest pooled median
    # among the non-eliminated candidates, and median_s must BE that
    # pooled median of its own samples
    if isinstance(winner_cid, str) and winner_cid in samples:
        try:
            meds = {cid: statistics.median(
                        [x for b in batches for x in b])
                    for cid, batches in samples.items()
                    if isinstance(batches, list) and any(
                        isinstance(b, list) and b for b in batches)}
        except (TypeError, statistics.StatisticsError):
            meds = {}
        if meds:
            if winner_cid in meds and _is_num(win.get("median_s")) \
                    and abs(win["median_s"] - meds[winner_cid]) \
                    > 1e-12 * max(1.0, abs(meds[winner_cid])):
                errors.append(f"{w}: median_s {win.get('median_s')!r} "
                              f"!= pooled sample median "
                              f"{meds[winner_cid]!r}")
            for cid, m in meds.items():
                if cid in eliminated or cid == winner_cid:
                    continue
                if winner_cid in meds and m < meds[winner_cid]:
                    errors.append(
                        f"{w}: non-eliminated candidate {cid!r} has a "
                        f"smaller pooled median ({m!r}) than the "
                        f"winner ({meds[winner_cid]!r}) — the verdict "
                        f"contradicts its own samples")
    return errors


#: Accepted WORKLOAD artifact schema tags (obs/workload.py, the
#: ``cli inspect workload --json`` output) — versioned like TUNE_SCHEMAS.
WORKLOAD_SCHEMAS = ("workload-v1",)

_WORKLOAD_STATUSES = ("done", "fail", "shed", "lost")


def validate_workload(obj, where: str = "WORKLOAD") -> list[str]:
    """Schema errors (empty list = valid) for one ``WORKLOAD_r*.json``
    workload-profile artifact (obs/workload.py).

    The self-consistency bar is the strongest in the repo: every
    aggregate block (phase totals, arrival process, queue depth, shape
    mix, batching) is RE-DERIVED from the artifact's own ``per_request``
    rows through the same ``obs.workload.aggregate_rows`` arithmetic and
    compared float-exactly, each request's ``wall_s`` must equal the sum
    of its phase durations in canonical boundary order (the identical-
    computation discipline — never a tolerance), and the advisory
    proposals must re-derive from the aggregates + seed. An artifact its
    own rows contradict is schema-invalid. Freshness against the source
    journal is the separate ``replay_workload`` gate."""
    import json as _json

    from tpu_aggcomm.obs import workload as _wl

    errors: list[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: top level must be an object"]
    schema = obj.get("schema")
    if schema not in WORKLOAD_SCHEMAS:
        errors.append(f"{where}: unknown schema tag {schema!r} "
                      f"(expected one of {list(WORKLOAD_SCHEMAS)})")
        return errors
    _require(obj, "created_unix", (int, float), errors, where)
    _require(obj, "seed", int, errors, where)
    man = obj.get("manifest")
    if man is not None and not isinstance(man, dict):
        errors.append(f"{where}: 'manifest' must be an object or null")
    journals = obj.get("journals")
    if not isinstance(journals, list) or not journals \
            or not all(isinstance(j, str) for j in journals):
        errors.append(f"{where}: 'journals' must be a non-empty list of "
                      f"journal basenames")
    probs = obj.get("problems")
    if not isinstance(probs, list):
        errors.append(f"{where}: 'problems' must be a list")
    elif probs:
        errors.append(f"{where}: artifact carries {len(probs)} profiler "
                      f"problem(s) (first: {probs[0]!r}) — a journal "
                      f"that disagrees with itself must not be "
                      f"committed as an artifact")

    rows = obj.get("per_request")
    if not isinstance(rows, list):
        return errors + [f"{where}: 'per_request' must be a list"]
    counts = {"done": 0, "fail": 0, "shed": 0}
    lost_rows: list = []
    shaped = 0
    prev_rid = None
    for i, r in enumerate(rows):
        w = f"{where}.per_request[{i}]"
        if not isinstance(r, dict):
            errors.append(f"{w}: must be an object")
            continue
        _require(r, "rid", int, errors, w)
        rid = r.get("rid")
        if isinstance(rid, int) and prev_rid is not None \
                and rid <= prev_rid:
            errors.append(f"{w}: rows must be sorted by rid "
                          f"({rid} after {prev_rid})")
        prev_rid = rid if isinstance(rid, int) else prev_rid
        status = r.get("status")
        if status not in _WORKLOAD_STATUSES:
            errors.append(f"{w}: status {status!r} not in "
                          f"{_WORKLOAD_STATUSES}")
        elif status == "lost":
            lost_rows.append(rid)
        else:
            counts[status] += 1
        if isinstance(r.get("shape"), dict):
            shaped += 1
        phases = r.get("phases")
        if not isinstance(phases, dict):
            errors.append(f"{w}: 'phases' must be an object")
            continue
        for b, v in phases.items():
            if b not in _wl.BOUNDARIES[1:]:
                errors.append(f"{w}: unknown phase boundary {b!r}")
            elif not _is_num(v) or v < 0:
                errors.append(f"{w}: phase {b!r} duration must be a "
                              f"non-negative number, got {v!r}")
        # wall_s is DEFINED as the canonical-order sum — re-derive the
        # identical expression (float-exact by identical computation)
        want_wall = [phases[b] for b in _wl.BOUNDARIES if b in phases]
        want_wall = sum(want_wall) if want_wall else None
        if r.get("wall_s") != want_wall:
            errors.append(f"{w}: wall_s {r.get('wall_s')!r} != sum of "
                          f"phase durations in canonical order "
                          f"== {want_wall!r}")

    req = obj.get("requests")
    if not isinstance(req, dict):
        errors.append(f"{where}: 'requests' must be an object")
    else:
        for k in ("admitted", "completed", "failed", "shed"):
            _require(req, k, int, errors, f"{where}.requests")
            v = req.get(k)
            if isinstance(v, int) and not isinstance(v, bool) and v < 0:
                errors.append(f"{where}.requests: {k!r} must be "
                              f"non-negative, got {v}")
        for k, have in (("completed", counts["done"]),
                        ("failed", counts["fail"]),
                        ("shed", counts["shed"])):
            want = req.get(k)
            if isinstance(want, int) and want != have:
                errors.append(f"{where}: requests.{k} claims {want} but "
                              f"the per_request rows re-derive {have}")
        lost = req.get("lost")
        if not isinstance(lost, list):
            errors.append(f"{where}.requests: 'lost' must be a list")
        elif sorted(lost, key=repr) != sorted(lost_rows, key=repr):
            errors.append(f"{where}: requests.lost claims {lost} but the "
                          f"per_request rows re-derive {sorted(lost_rows, key=repr)}")
        adm = req.get("admitted")
        if isinstance(adm, int) and adm != shaped:
            errors.append(f"{where}: requests.admitted claims {adm} but "
                          f"{shaped} rows carry an admission shape — "
                          f"every admitted request records its shape")

    # -- re-derive every aggregate block from the rows themselves ----------
    fences = {}
    for m in (obj.get("shape_mix") or []):
        if isinstance(m, dict) and isinstance(m.get("shape"), dict):
            sig = _json.dumps({"shape": m["shape"],
                               "backend": m.get("backend")},
                              sort_keys=True)
            fences[sig] = m.get("fences_per_request")
    try:
        agg = _wl.aggregate_rows(rows, fences=fences)
    except Exception as e:  # lint: broad-ok (validation must report malformed rows as schema errors, not crash the checker)
        return errors + [f"{where}: per_request rows do not aggregate: "
                         f"{type(e).__name__}: {e}"]
    for p in agg.pop("problems"):
        errors.append(f"{where}: rows are self-contradictory: {p}")
    for key, want in agg.items():
        got = obj.get(key)
        if _json.dumps(got, sort_keys=True) \
                != _json.dumps(want, sort_keys=True):
            errors.append(f"{where}: '{key}' does not re-derive from "
                          f"per_request rows float-exactly (the "
                          f"aggregate_rows arithmetic)")

    # -- proposals must re-derive from the aggregates + seed ---------------
    props = obj.get("proposals")
    if not isinstance(props, list):
        errors.append(f"{where}: 'proposals' must be a list")
    elif isinstance(req, dict) and not errors:
        pseudo = {"seed": obj.get("seed", 0), "requests": req,
                  "shape_mix": agg.get("shape_mix", []),
                  "arrivals": agg.get("arrivals", {})}
        want = _wl._detect(pseudo)
        if _json.dumps(props, sort_keys=True) \
                != _json.dumps(want, sort_keys=True):
            errors.append(f"{where}: 'proposals' do not re-derive from "
                          f"the aggregates + seed (detection must be "
                          f"deterministic and advisory)")
    return errors


WATCH_SCHEMAS = ("watch-v1",)

_WATCH_STATUSES = ("done", "fail", "shed", "lost")


def validate_watch(obj, where: str = "WATCH") -> list[str]:
    """Schema errors (empty list = valid) for one ``WATCH_r*.json``
    watchtower artifact (obs/watch.py).

    The validate_workload discipline applied to verdicts: every
    request's ``wall_s`` must equal its canonical phase sum, the whole
    SLO evaluation must re-derive from the artifact's own ``per_request``
    rows + embedded spec through the same ``evaluate_slo`` arithmetic
    (float-exact by identical computation), the request-walls
    changepoint must re-derive from the rows + seed, and EVERY anomaly's
    root-cause verdict must re-derive from the blob's own rows +
    evidence blocks through the same ``attribute_anomaly`` chain —
    naming an evidence stream the blob does not support, or a bare
    unquantified UNEXPLAINED, is schema-invalid. Freshness against the
    source streams is the separate ``replay_watch`` gate."""
    import json as _json

    from tpu_aggcomm.obs import watch as _watch
    from tpu_aggcomm.obs.slo import validate_slo as _validate_slo

    errors: list[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: top level must be an object"]
    schema = obj.get("schema")
    if schema not in WATCH_SCHEMAS:
        errors.append(f"{where}: unknown schema tag {schema!r} "
                      f"(expected one of {list(WATCH_SCHEMAS)})")
        return errors
    _require(obj, "created_unix", (int, float), errors, where)
    _require(obj, "seed", int, errors, where)
    man = obj.get("manifest")
    if man is not None and not isinstance(man, dict):
        errors.append(f"{where}: 'manifest' must be an object or null")
    journals = obj.get("journals")
    if not isinstance(journals, list) or not journals \
            or not all(isinstance(j, str) for j in journals):
        errors.append(f"{where}: 'journals' must be a non-empty list of "
                      f"journal basenames")
    traces = obj.get("traces")
    if not isinstance(traces, list) \
            or not all(isinstance(t, str) for t in traces):
        errors.append(f"{where}: 'traces' must be a list of trace "
                      f"basenames")
    probs = obj.get("problems")
    if not isinstance(probs, list):
        errors.append(f"{where}: 'problems' must be a list")
    elif probs:
        errors.append(f"{where}: artifact carries {len(probs)} "
                      f"problem(s) (first: {probs[0]!r}) — a journal "
                      f"that disagrees with itself must not be "
                      f"committed as an artifact")
    slo = obj.get("slo")
    slo_errs = _validate_slo(slo, where=f"{where}.slo")
    errors.extend(slo_errs)

    rows = obj.get("per_request")
    if not isinstance(rows, list):
        return errors + [f"{where}: 'per_request' must be a list"]
    counts = {"done": 0, "fail": 0, "shed": 0}
    lost_rows: list = []
    prev_rid = None
    from tpu_aggcomm.obs.workload import BOUNDARIES as _BOUNDS
    for i, r in enumerate(rows):
        w = f"{where}.per_request[{i}]"
        if not isinstance(r, dict):
            errors.append(f"{w}: must be an object")
            continue
        rid = r.get("rid")
        if prev_rid is not None and isinstance(rid, int) \
                and rid <= prev_rid:
            errors.append(f"{w}: rows must be sorted by rid "
                          f"({rid} after {prev_rid})")
        prev_rid = rid if isinstance(rid, int) else prev_rid
        status = r.get("status")
        if status not in _WATCH_STATUSES:
            errors.append(f"{w}: status {status!r} not in "
                          f"{_WATCH_STATUSES}")
        elif status == "lost":
            lost_rows.append(rid)
        else:
            counts[status] += 1
        phases = r.get("phases")
        if not isinstance(phases, dict):
            errors.append(f"{w}: 'phases' must be an object")
            continue
        # wall_s is DEFINED as the canonical-order sum (the
        # validate_workload discipline — identical computation)
        want_wall = [phases[b] for b in _BOUNDS if b in phases]
        want_wall = sum(want_wall) if want_wall else None
        if r.get("wall_s") != want_wall:
            errors.append(f"{w}: wall_s {r.get('wall_s')!r} != sum of "
                          f"phase durations in canonical order "
                          f"== {want_wall!r}")

    req = obj.get("requests")
    if not isinstance(req, dict):
        errors.append(f"{where}: 'requests' must be an object")
    else:
        for k, have in (("completed", counts["done"]),
                        ("failed", counts["fail"]),
                        ("shed", counts["shed"])):
            want = req.get(k)
            if isinstance(want, int) and want != have:
                errors.append(f"{where}: requests.{k} claims {want} but "
                              f"the per_request rows re-derive {have}")
        lost = req.get("lost")
        if not isinstance(lost, list):
            errors.append(f"{where}.requests: 'lost' must be a list")
        elif sorted(lost, key=repr) != sorted(lost_rows, key=repr):
            errors.append(f"{where}: requests.lost claims {lost} but "
                          f"the per_request rows re-derive "
                          f"{sorted(lost_rows, key=repr)}")
    integ = obj.get("integrity")
    if not isinstance(integ, dict):
        errors.append(f"{where}: 'integrity' must be an object")
    else:
        for k in ("journal_torn_lines", "trace_torn_lines"):
            v = integ.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(f"{where}.integrity: {k!r} must be a "
                              f"non-negative int, got {v!r}")
        if isinstance(req, dict) and isinstance(req.get("lost"), list) \
                and integ.get("lost_requests") != req["lost"]:
            errors.append(f"{where}: integrity.lost_requests "
                          f"{integ.get('lost_requests')!r} != "
                          f"requests.lost {req['lost']!r}")

    # -- the SLO evaluation must re-derive from rows + embedded spec -------
    if not slo_errs:
        try:
            want_eval = _watch.evaluate_slo(rows, slo)
        except Exception as e:  # lint: broad-ok (validation must report malformed rows as schema errors, not crash the checker)
            return errors + [f"{where}: per_request rows do not "
                             f"evaluate: {type(e).__name__}: {e}"]
        if _json.dumps(obj.get("evaluation"), sort_keys=True) \
                != _json.dumps(want_eval, sort_keys=True):
            errors.append(f"{where}: 'evaluation' does not re-derive "
                          f"from per_request rows + the embedded SLO "
                          f"spec float-exactly (the evaluate_slo "
                          f"arithmetic) — burn rates and compliance "
                          f"flags its own rows contradict")

    # -- anomalies: detection + attribution must re-derive -----------------
    anomalies = obj.get("anomalies")
    evidence = obj.get("evidence")
    if not isinstance(anomalies, list):
        errors.append(f"{where}: 'anomalies' must be a list")
        anomalies = []
    if not isinstance(evidence, dict):
        errors.append(f"{where}: 'evidence' must be an object")
        evidence = {}
    seed = obj.get("seed", 0)
    walls_rows = [r for r in rows if isinstance(r, dict)
                  and isinstance(r.get("wall_s"), (int, float))]
    want_det = None
    if isinstance(seed, int):
        try:
            want_det = _watch.detect_changepoint(
                [r["wall_s"] for r in walls_rows], seed=seed)
        except Exception as e:  # lint: broad-ok (validation must report malformed rows as schema errors, not crash the checker)
            errors.append(f"{where}: request walls do not scan: "
                          f"{type(e).__name__}: {e}")
    req_anoms = [a for a in anomalies if isinstance(a, dict)
                 and a.get("stream") == "request-walls"]
    if want_det is None and req_anoms:
        errors.append(f"{where}: a request-walls anomaly is recorded "
                      f"but the rows + seed re-derive no confirmed "
                      f"changepoint")
    if want_det is not None and not req_anoms and not probs:
        errors.append(f"{where}: the rows + seed re-derive a confirmed "
                      f"request-walls changepoint (index "
                      f"{want_det['index']}) the artifact omits")
    for i, a in enumerate(anomalies):
        w = f"{where}.anomalies[{i}]"
        if not isinstance(a, dict):
            errors.append(f"{w}: must be an object")
            continue
        det = a.get("detection")
        if not isinstance(det, dict):
            errors.append(f"{w}: 'detection' must be an object")
            continue
        if a.get("evidence") not in _watch.EVIDENCE_STREAMS:
            errors.append(f"{w}: evidence {a.get('evidence')!r} not in "
                          f"{_watch.EVIDENCE_STREAMS} — every verdict "
                          f"must name its evidence stream")
        if not isinstance(a.get("cause"), str) or not a.get("cause"):
            errors.append(f"{w}: 'cause' must be a non-empty string — "
                          f"a bare ANOMALY is a regression")
        if a.get("cause") == "UNEXPLAINED" \
                and "%" not in str(a.get("detail", "")):
            errors.append(f"{w}: an UNEXPLAINED verdict must quantify "
                          f"the residual")
        if a.get("stream") == "request-walls":
            if want_det is not None and _json.dumps(det, sort_keys=True) \
                    != _json.dumps(want_det, sort_keys=True):
                errors.append(f"{w}: detection does not re-derive from "
                              f"the rows + seed (seeded changepoint "
                              f"verdicts must be reproducible)")
            split_rid, expl = a.get("at_rid"), None
        else:
            stream = str(a.get("stream", ""))
            key = stream.split(":", 1)[1] if ":" in stream else None
            split_rid = None
            expl = (evidence.get("explain") or {}).get(key)
        try:
            want_v = _watch.attribute_anomaly(
                det, rows=rows, evidence=evidence, split_rid=split_rid,
                explain_rounds=expl)
        except Exception as e:  # lint: broad-ok (validation must report malformed evidence as schema errors, not crash the checker)
            errors.append(f"{w}: evidence does not attribute: "
                          f"{type(e).__name__}: {e}")
            continue
        got_v = {k: a.get(k) for k in ("cause", "evidence", "detail")}
        if _json.dumps(got_v, sort_keys=True) \
                != _json.dumps(want_v, sort_keys=True):
            errors.append(f"{w}: the root-cause verdict does not "
                          f"re-derive from the blob's own rows + "
                          f"evidence blocks (attribute_anomaly): "
                          f"artifact {got_v} vs re-derived {want_v}")
    return errors


#: Valid ``schema`` tags for FLOW_r*.json (obs/flow.py — the
#: ``cli inspect flow`` output) — versioned like TUNE_SCHEMAS.
FLOW_SCHEMAS = ("flow-v1",)

_FLOW_STATUSES = ("done", "fail", "shed")


def validate_flow(obj, where: str = "FLOW") -> list[str]:
    """Schema errors (empty list = valid) for one ``FLOW_r*.json``
    causal-flow artifact (obs/flow.py).

    The validate_workload/validate_watch discipline applied to the
    end-to-end decomposition: every derived number in every row must
    re-derive from the row's OWN fields through the identical
    expressions obs/flow.py used to produce it — ``client_wall_s ==
    t_recv - t_send``, ``server_wall_s`` == the canonical phase sum,
    ``wire_s == client_wall_s - server_wall_s``, the round component ==
    the joined run's wall (else the journal dispatch phase), the
    overhead component == the quantified residual, every fraction ==
    component / client wall, the dominant verdict == the canonical-order
    arg-max's NAMED verdict — and the summary blocks (verdict counts,
    warm overhead ledger with its seeded CI, warm component means) must
    recount/re-derive from the rows + seed. An artifact its own numbers
    contradict is schema-invalid. Freshness against the source streams
    is the separate ``replay_flow`` gate."""
    import json as _json

    from tpu_aggcomm.obs import flow as _flow

    errors: list[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: top level must be an object"]
    schema = obj.get("schema")
    if schema not in FLOW_SCHEMAS:
        errors.append(f"{where}: unknown schema tag {schema!r} "
                      f"(expected one of {list(FLOW_SCHEMAS)})")
        return errors
    _require(obj, "created_unix", (int, float), errors, where)
    _require(obj, "seed", int, errors, where)
    man = obj.get("manifest")
    if man is not None and not isinstance(man, dict):
        errors.append(f"{where}: 'manifest' must be an object or null")
    for k in ("client_journal", "serve_journal"):
        _require(obj, k, str, errors, where)
    traces = obj.get("traces")
    if not isinstance(traces, list) \
            or not all(isinstance(t, str) for t in traces):
        errors.append(f"{where}: 'traces' must be a list of trace "
                      f"basenames")
    probs = obj.get("problems")
    if not isinstance(probs, list):
        errors.append(f"{where}: 'problems' must be a list")
    elif probs:
        errors.append(f"{where}: artifact carries {len(probs)} "
                      f"problem(s) (first: {probs[0]!r}) — streams "
                      f"that disagree with each other must not be "
                      f"committed as an artifact")

    rows = obj.get("per_request")
    if not isinstance(rows, list):
        return errors + [f"{where}: 'per_request' must be a list"]
    from tpu_aggcomm.obs.workload import BOUNDARIES as _BOUNDS
    verdict_counts: dict = {}
    for i, r in enumerate(rows):
        w = f"{where}.per_request[{i}]"
        if not isinstance(r, dict):
            errors.append(f"{w}: must be an object")
            continue
        if r.get("status") not in _FLOW_STATUSES:
            errors.append(f"{w}: status {r.get('status')!r} not in "
                          f"{_FLOW_STATUSES}")
        if r.get("server_source") not in ("journal", "trace"):
            errors.append(f"{w}: server_source "
                          f"{r.get('server_source')!r} must be "
                          f"'journal' or 'trace'")
        phases = r.get("phases")
        if not isinstance(phases, dict):
            errors.append(f"{w}: 'phases' must be an object")
            continue
        comp = r.get("components")
        frac = r.get("fractions")
        if not isinstance(comp, dict) or not isinstance(frac, dict):
            errors.append(f"{w}: 'components' and 'fractions' must be "
                          f"objects")
            continue
        # -- the decomposition, identical expression by expression ----
        t_send, t_recv = r.get("t_send"), r.get("t_recv")
        want_cw = (t_recv - t_send if _is_num(t_send) and _is_num(t_recv)
                   else None)
        if r.get("client_wall_s") != want_cw:
            errors.append(f"{w}: client_wall_s {r.get('client_wall_s')!r}"
                          f" != t_recv - t_send == {want_cw!r}")
        vals = [phases[b] for b in _BOUNDS if b in phases]
        want_sw = sum(vals) if vals else None
        if r.get("server_wall_s") != want_sw:
            errors.append(f"{w}: server_wall_s {r.get('server_wall_s')!r}"
                          f" != canonical phase sum == {want_sw!r}")
        want_wire = (want_cw - want_sw
                     if want_cw is not None and want_sw is not None
                     else None)
        if r.get("wire_s") != want_wire:
            errors.append(f"{w}: wire_s {r.get('wire_s')!r} != "
                          f"client_wall_s - server_wall_s == "
                          f"{want_wire!r}")
        run = r.get("run")
        if run is not None and not isinstance(run, dict):
            errors.append(f"{w}: 'run' must be an object or null")
            run = None
        run_wall = run.get("wall_s") if run else None
        want_comp: dict = {}
        if want_wire is not None:
            want_comp["wire"] = want_wire
        for b in ("queue", "batch", "cache", "respond"):
            if b in phases:
                want_comp[b] = phases[b]
        want_res = None
        if _is_num(run_wall):
            want_comp["round"] = run_wall
            if "dispatch" in phases:
                want_res = phases["dispatch"] - run_wall
                want_comp["overhead"] = want_res
        elif "dispatch" in phases:
            want_comp["round"] = phases["dispatch"]
        if r.get("residual_s") != want_res:
            errors.append(f"{w}: residual_s {r.get('residual_s')!r} != "
                          f"dispatch phase - run wall == {want_res!r}")
        if _json.dumps(comp, sort_keys=True) \
                != _json.dumps(want_comp, sort_keys=True):
            errors.append(f"{w}: 'components' does not re-derive from "
                          f"the row's own phases/run fields: artifact "
                          f"{comp} vs re-derived {want_comp}")
        want_frac = ({k: v / want_cw for k, v in want_comp.items()}
                     if _is_num(want_cw) and want_cw > 0 else {})
        if _json.dumps(frac, sort_keys=True) \
                != _json.dumps(want_frac, sort_keys=True):
            errors.append(f"{w}: 'fractions' do not re-derive as "
                          f"component / client_wall_s float-exactly")
        want_dom = _flow.dominant_component(want_comp)
        if r.get("dominant") != want_dom:
            errors.append(f"{w}: dominant {r.get('dominant')!r} != the "
                          f"canonical-order arg-max {want_dom!r}")
        want_verdict = (_flow.VERDICTS[want_dom]
                        if want_dom is not None else None)
        if r.get("verdict") != want_verdict:
            errors.append(f"{w}: verdict {r.get('verdict')!r} != "
                          f"{want_verdict!r} — every dominant component "
                          f"maps to its NAMED verdict")
        elif want_verdict is not None:
            verdict_counts[want_verdict] = \
                verdict_counts.get(want_verdict, 0) + 1
        if run is not None:
            rounds = run.get("rounds")
            if not isinstance(rounds, list) or not all(
                    isinstance(x, dict) and _is_num(x.get("wall_s"))
                    for x in rounds):
                errors.append(f"{w}.run: 'rounds' must be a list of "
                              f"objects with numeric wall_s")
            else:
                want_rt = sum(x["wall_s"] for x in rounds)
                if run.get("rounds_total_s") != want_rt:
                    errors.append(f"{w}.run: rounds_total_s "
                                  f"{run.get('rounds_total_s')!r} != sum "
                                  f"of round walls == {want_rt!r}")

    # -- summary blocks must recount/re-derive from the rows ----------
    if _json.dumps(obj.get("verdicts"), sort_keys=True) \
            != _json.dumps(verdict_counts, sort_keys=True):
        errors.append(f"{where}: 'verdicts' {obj.get('verdicts')!r} "
                      f"does not recount from the per_request rows "
                      f"== {verdict_counts!r}")
    seed = obj.get("seed")
    if isinstance(seed, int):
        try:
            want_wo = _flow.warm_overhead_block(rows, seed=seed)
            want_wc = _flow.warm_components_block(rows)
        except Exception as e:  # lint: broad-ok (validation must report malformed rows as schema errors, not crash the checker)
            errors.append(f"{where}: per_request rows do not fold into "
                          f"the warm ledger: {type(e).__name__}: {e}")
        else:
            if _json.dumps(obj.get("warm_overhead"), sort_keys=True) \
                    != _json.dumps(want_wo, sort_keys=True):
                errors.append(f"{where}: 'warm_overhead' does not "
                              f"re-derive from the rows + seed (the "
                              f"warm_overhead_block arithmetic, seeded "
                              f"CI included)")
            if _json.dumps(obj.get("warm_components"), sort_keys=True) \
                    != _json.dumps(want_wc, sort_keys=True):
                errors.append(f"{where}: 'warm_components' does not "
                              f"re-derive from the rows (the "
                              f"warm_components_block arithmetic)")

    req = obj.get("requests")
    if not isinstance(req, dict):
        errors.append(f"{where}: 'requests' must be an object")
    else:
        if isinstance(req.get("joined"), int) \
                and req["joined"] != len(rows):
            errors.append(f"{where}: requests.joined claims "
                          f"{req['joined']} but the artifact carries "
                          f"{len(rows)} per_request row(s)")
        for k in ("client_only", "server_only", "lost"):
            if not isinstance(req.get(k), list):
                errors.append(f"{where}.requests: {k!r} must be a list")
    integ = obj.get("integrity")
    if not isinstance(integ, dict):
        errors.append(f"{where}: 'integrity' must be an object")
    else:
        for k in ("client_torn_lines", "journal_torn_lines",
                  "trace_torn_lines"):
            v = integ.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(f"{where}.integrity: {k!r} must be a "
                              f"non-negative int, got {v!r}")
    return errors


#: Valid ``schema`` tags for PILOT_r*.json (tpu_aggcomm/pilot/ — the
#: ``cli pilot`` output) — versioned like TUNE_SCHEMAS.
PILOT_SCHEMAS = ("pilot-v1",)


def validate_pilot(obj, where: str = "PILOT") -> list[str]:
    """Validate one PILOT_r*.json blob (pilot-v1) and re-derive every
    claim re-derivable from the artifact ALONE: each campaign's race
    verdict from its recorded samples, the win CI and improvement flag
    from the recorded numbers (pilot/campaign.replay_campaign with the
    search left to ``pilot --replay`` — re-running the seeded search
    per artifact is the stream-level gate's job), every decision from
    the one decision arithmetic over the recorded swap evidence, every
    promotion record through validate_promotion_record, and each
    demotion action against its own recorded detection. An artifact
    whose own rows contradict a promotion it claims is schema-invalid —
    the zero-silent-method-changes contract, enforced at validation
    time. jax-free."""
    import json as _json

    from tpu_aggcomm.pilot.artifact import derive_decision
    from tpu_aggcomm.pilot.campaign import replay_campaign
    from tpu_aggcomm.pilot.promote import validate_promotion_record

    errors: list[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: artifact must be a JSON object, got "
                f"{type(obj).__name__}"]
    w = where
    schema = obj.get("schema")
    if schema not in PILOT_SCHEMAS:
        errors.append(f"{w}: unknown schema {schema!r} "
                      f"(expected one of {list(PILOT_SCHEMAS)})")
        return errors
    _require(obj, "manifest", dict, errors, w)
    _require(obj, "created_unix", (int, float), errors, w)
    _require(obj, "seed", int, errors, w)
    _require(obj, "mode", str, errors, w)
    _require(obj, "journals", list, errors, w)
    _require(obj, "fingerprint", str, errors, w)
    _require(obj, "requests", dict, errors, w)
    _require(obj, "proposals", list, errors, w)
    _require(obj, "targets", list, errors, w)
    _require(obj, "demotions", list, errors, w)
    _require(obj, "campaigns", list, errors, w)
    _require(obj, "decisions", list, errors, w)
    _require(obj, "promotions", list, errors, w)
    _require(obj, "race_opts", dict, errors, w)
    _require(obj, "per_shape", dict, errors, w, nullable=True)
    if errors:
        return errors
    if obj["mode"] not in ("live", "dry-run"):
        errors.append(f"{w}: mode must be 'live' or 'dry-run', got "
                      f"{obj['mode']!r}")
    for i, ent in enumerate(obj["journals"]):
        if not isinstance(ent, dict) or not isinstance(
                ent.get("name"), str) or not isinstance(
                ent.get("lines"), int):
            errors.append(f"{w}: journals[{i}] must be "
                          f"{{name: str, lines: int}}, got {ent!r}")

    inputs = obj.get("inputs") or {}
    for i, c in enumerate(obj["campaigns"]):
        for p in replay_campaign(c, params=inputs.get("params"),
                                 params_source=inputs.get("params_source"),
                                 rerun_search=False):
            errors.append(f"{w}: campaigns[{i}]: {p}")

    # the decision arithmetic over the artifact's own evidence
    active = [t for t in obj["targets"]
              if isinstance(t, dict) and t.get("skipped") is None]
    if len(active) != len(obj["campaigns"]) \
            or len(obj["campaigns"]) != len(obj["decisions"]):
        errors.append(f"{w}: {len(active)} active target(s) vs "
                      f"{len(obj['campaigns'])} campaign(s) vs "
                      f"{len(obj['decisions'])} decision(s) — the "
                      f"decision trace is truncated")
    else:
        for t, c, d_rec in zip(active, obj["campaigns"],
                               obj["decisions"]):
            try:
                want = derive_decision(
                    t, c, mode=obj["mode"],
                    fingerprint=obj["fingerprint"],
                    swap=(d_rec or {}).get("swap"))
            except Exception as e:  # lint: broad-ok (validation must report a malformed campaign as a schema error, not crash the checker)
                errors.append(f"{w}: decision for "
                              f"{c.get('incumbent_cid')} does not "
                              f"re-derive: {type(e).__name__}: {e}")
                continue
            if _json.dumps(want, sort_keys=True) \
                    != _json.dumps(d_rec, sort_keys=True):
                errors.append(
                    f"{w}: decision for {c.get('incumbent_cid')} "
                    f"contradicts the one decision arithmetic over its "
                    f"own campaign + swap evidence (recorded "
                    f"{(d_rec or {}).get('action')!r})")
        want_promos = [d["record"] for d in obj["decisions"]
                       if isinstance(d, dict)
                       and d.get("action") == "promote"]
        if _json.dumps(want_promos, sort_keys=True) \
                != _json.dumps(obj["promotions"], sort_keys=True):
            errors.append(f"{w}: promotions must be exactly the "
                          f"promote-decision records")

    for i, rec in enumerate(obj["promotions"]):
        for p in validate_promotion_record(rec):
            errors.append(f"{w}: promotions[{i}]: {p}")

    for i, row in enumerate(obj["demotions"]):
        if not isinstance(row, dict):
            errors.append(f"{w}: demotions[{i}] must be an object")
            continue
        det = row.get("detection")
        regressed = isinstance(det, dict) \
            and det.get("direction") == "up"
        want_action = "demote" if regressed else "hold"
        if row.get("action") != want_action:
            errors.append(
                f"{w}: demotions[{i}] action {row.get('action')!r} "
                f"contradicts its own recorded detection "
                f"({'confirmed up-step' if regressed else 'no confirmed regression'})")
        for p in validate_promotion_record(row.get("record")):
            errors.append(f"{w}: demotions[{i}].record: {p}")
    return errors
