"""Declarative SLO specs for the serve watchtower (obs/watch.py).

A spec (slo-v1) names the objectives the serve layer is held to and the
request-count windows they are judged over. Objectives are evaluated as
**error-budget burn rates**: an objective with success target ``t`` has
error budget ``1 - t``; a window whose bad-event fraction is ``f``
burns at ``f / (1 - t)``. Burn <= 1 means the window lived inside its
budget; burn 2 means the budget is being spent twice as fast as
provisioned. :func:`burn_rate` is THE one burn arithmetic — the
watchtower evaluator, the server's live ``/metrics`` gauges
(obs/watch.LiveSlo) and the telemetry gate's re-render all call it, so
the numbers cannot drift apart (the ``padded_slots`` precedent from
obs/workload.py).

Objective kinds, all derived from serve-journal records alone (never
host callbacks — the obs discipline):

- ``warm-latency`` — completed warm-cache (``cache == "hit"``) requests
  whose wall exceeds ``threshold_s`` are bad; the window SLI is the
  warm p50 wall.
- ``goodput`` — any non-``done`` outcome (fail, shed, lost) is bad; the
  SLI is the completed fraction.
- ``shed-rate`` — shed requests are bad; the SLI is the shed fraction.
- ``deadline-miss`` — among requests that declared ``deadline_ms``: a
  deadline shed or a wall past the deadline is bad. A window with no
  deadline-carrying requests is vacuous (burn ``None``), never counted
  as a violation.
- ``padding-waste`` — padded batch slots that carried no request are
  bad (the power-of-two batching overhead); the SLI is the fill ratio.

jax-free by contract: the whole ``obs`` package is in PURE_PACKAGES
(analysis/lint.py), and the watchtower must evaluate precisely where a
wedged tunnel hangs ``import jax``.
"""

from __future__ import annotations

import json

__all__ = ["SLO_SCHEMA", "OBJECTIVE_KINDS", "DEFAULT_SLO", "SloError",
           "burn_rate", "objective_budget", "validate_slo", "load_slo"]

SLO_SCHEMA = "slo-v1"

#: Every objective kind the evaluator implements — a spec naming any
#: other kind is refused by name (validate_slo), never silently skipped.
OBJECTIVE_KINDS = ("warm-latency", "goodput", "shed-rate",
                   "deadline-miss", "padding-waste")

#: The spec used when ``inspect watch`` is given no ``--slo`` file.
#: Deliberately lenient: defaults must hold on the committed healthy
#: exemplar journal; a deployment tightens them with its own slo-v1
#: file. (Dict literal, embedded verbatim in WATCH_r*.json so replay
#: needs no side channel.)
DEFAULT_SLO = {
    "schema": SLO_SCHEMA,
    "windows": [{"name": "fast", "requests": 8},
                {"name": "slow", "requests": 32}],
    "objectives": [
        {"name": "warm-p50", "kind": "warm-latency",
         "threshold_s": 2.0, "target": 0.9},
        {"name": "goodput", "kind": "goodput", "target": 0.9},
        {"name": "shed-rate", "kind": "shed-rate", "target": 0.9},
        {"name": "deadline-miss", "kind": "deadline-miss", "target": 0.9},
        {"name": "padding-waste", "kind": "padding-waste", "target": 0.5},
    ],
}


class SloError(ValueError):
    """A malformed SLO spec, refused with the defect named."""


def objective_budget(obj: dict) -> float:
    """The error budget of one objective: ``1 - target``."""
    return 1.0 - float(obj["target"])


def burn_rate(bad, total, budget: float):
    """THE one burn arithmetic: bad-fraction over error budget.

    ``None`` when the window is vacuous (``total`` 0) — no evidence is
    not a violation. Float-exactness across the evaluator, the live
    gauges and the telemetry gate comes from everyone calling THIS
    function (identical computation, never a re-implementation)."""
    if not total:
        return None
    return (bad / total) / budget


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_slo(obj, where: str = "SLO") -> list[str]:
    """Schema errors (empty list = valid) for one slo-v1 spec."""
    errors: list[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: top level must be an object"]
    if obj.get("schema") != SLO_SCHEMA:
        errors.append(f"{where}: unknown schema tag "
                      f"{obj.get('schema')!r} (expected {SLO_SCHEMA!r})")
        return errors
    wins = obj.get("windows")
    if not isinstance(wins, list) or not wins:
        errors.append(f"{where}: 'windows' must be a non-empty list")
        wins = []
    seen: set = set()
    for i, w in enumerate(wins):
        ww = f"{where}.windows[{i}]"
        if not isinstance(w, dict):
            errors.append(f"{ww}: must be an object")
            continue
        name = w.get("name")
        if not isinstance(name, str) or not name:
            errors.append(f"{ww}: 'name' must be a non-empty string")
        elif name in seen:
            errors.append(f"{ww}: duplicate window name {name!r}")
        else:
            seen.add(name)
        n = w.get("requests")
        if not isinstance(n, int) or isinstance(n, bool) or n < 1:
            errors.append(f"{ww}: 'requests' must be a positive int, "
                          f"got {n!r}")
    objs = obj.get("objectives")
    if not isinstance(objs, list) or not objs:
        errors.append(f"{where}: 'objectives' must be a non-empty list")
        objs = []
    seen = set()
    for i, o in enumerate(objs):
        ww = f"{where}.objectives[{i}]"
        if not isinstance(o, dict):
            errors.append(f"{ww}: must be an object")
            continue
        name = o.get("name")
        if not isinstance(name, str) or not name:
            errors.append(f"{ww}: 'name' must be a non-empty string")
        elif name in seen:
            errors.append(f"{ww}: duplicate objective name {name!r}")
        else:
            seen.add(name)
        kind = o.get("kind")
        if kind not in OBJECTIVE_KINDS:
            errors.append(f"{ww}: unknown kind {kind!r} (one of "
                          f"{list(OBJECTIVE_KINDS)})")
        t = o.get("target")
        if not _is_num(t) or not (0.0 < t < 1.0):
            errors.append(f"{ww}: 'target' must be a number in (0, 1) — "
                          f"target 1.0 leaves a zero error budget and "
                          f"an undefined burn rate — got {t!r}")
        if kind == "warm-latency":
            th = o.get("threshold_s")
            if not _is_num(th) or th <= 0:
                errors.append(f"{ww}: warm-latency needs a positive "
                              f"'threshold_s', got {th!r}")
    return errors


def load_slo(path: str) -> dict:
    """One slo-v1 spec from disk, validated; defects raise
    :class:`SloError` with every problem named."""
    try:
        with open(path) as fh:
            obj = json.load(fh)
    except OSError as e:
        raise SloError(f"{path}: unreadable SLO spec: {e}")
    except ValueError as e:
        raise SloError(f"{path}: unparsable SLO spec: {e}")
    errors = validate_slo(obj, where=path)
    if errors:
        raise SloError("; ".join(errors))
    return obj
