"""Longitudinal artifact history: one index over every committed family.

Every observability surface so far is pairwise or single-artifact: the
regression gate compares the newest round against the best prior one,
``inspect ledger`` diffs consecutive manifests, ``inspect compare``
diffs two traces. This module is the longitudinal view those tools
implicitly assume:

- **artifact discovery** — :func:`load_history` is THE definition of
  "the committed ``<KIND>_rNN.json`` history" (round parsing, ordering,
  corrupt-artifact handling). It lives here so ``obs/regress.py``,
  ``obs/report_html.py`` and ``scripts/check_bench_schema.py`` all read
  the same file set in the same order — three private copies of the
  scan logic is how two tools silently disagree about what round N is.
- **index** — :func:`build_index` folds every artifact family
  (BENCH_r*/MULTICHIP_r*/TUNE_*/TRAFFIC_*/``*.trace.jsonl``) into one
  JSON-able longitudinal record: per-(metric, platform) bench time
  series, multichip verdicts, tuner winners, traffic verdicts, and
  per-(method, backend, fault) trace critical-path totals.
  :func:`write_index` persists it through ``obs.atomic_write`` — the
  index is evidence, and a kill mid-write must not tear it.
- **trend gate** — :func:`trend_gate` extends the pairwise regression
  question ("slower than the best prior round?") to the longitudinal
  one ("is this metric drifting across the whole history?"): an OLS
  slope over >= ``MIN_TREND_ROUNDS`` rounds, significance-tested with a
  seeded pair-resampling bootstrap (same seed discipline as
  ``obs/regress.py`` and ``tune --replay``: same artifacts in ⟹ same
  verdict out). ``bench.py --check-regression`` and ``cli inspect
  history`` both consume it.

jax-free throughout (obs discipline): the supervisor, the replay CLIs
and ``inspect history`` import this where ``import jax`` may hang on a
dead tunnel.
"""

from __future__ import annotations

import glob
import json
import os
import random
import re
import statistics

__all__ = ["load_history", "build_index", "write_index", "trend_gate",
           "check_trends", "bench_series", "workload_series",
           "watch_series", "pilot_series", "flow_series",
           "render_history", "MIN_TREND_ROUNDS", "TREND_TOLERANCE",
           "HISTORY_SCHEMA"]

#: Schema tag of the persisted index artifact (versioned like
#: TUNE_SCHEMAS / TRAFFIC_SCHEMAS: new tag = new entry, old tags stay
#: readable forever).
HISTORY_SCHEMA = "history-v1"

#: Fewest measurable rounds in a series before a slope means anything —
#: below this the gate reports "insufficient" instead of inventing a
#: trend from two points (which is just the pairwise delta again).
MIN_TREND_ROUNDS = 3

#: Relative slope (fraction of the series median, per round) that
#: counts as drift. Differenced-chain numbers jitter a few percent
#: round-to-round; 10%/round sustained across the history is a real
#: trajectory, not noise.
TREND_TOLERANCE = 0.10

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def load_history(root: str = ".", kind: str = "BENCH", *,
                 errors: list[str] | None = None
                 ) -> list[tuple[int, str, dict]]:
    """All ``<kind>_rNN.json`` under ``root`` as (round, path, blob),
    sorted by round. A missing or empty directory is an empty history,
    not an error. Unparsable JSON raises by default — a corrupt
    artifact should fail loudly, not vanish from the history — unless
    the caller passes an ``errors`` list, in which case the corruption
    is recorded there (one message per bad artifact) and the rest of
    the history still loads: ``check_regression`` uses this so a single
    mangled artifact yields a schema-error verdict (one JSON line,
    nonzero exit) instead of a naked traceback."""
    out = []
    for path in glob.glob(os.path.join(root, f"{kind}_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as fh:
                out.append((int(m.group(1)), path, json.load(fh)))
        except ValueError as e:
            if errors is None:
                raise
            errors.append(f"{os.path.basename(path)}: unparsable JSON "
                          f"({e})")
    out.sort(key=lambda t: t[0])
    return out


# ---------------------------------------------------------------------------
# The longitudinal index.

def bench_series(root: str = ".", *,
                 errors: list[str] | None = None
                 ) -> dict[str, list[dict]]:
    """Per-(metric, platform) bench time series from the committed
    history: ``{"<metric> | <platform>": [{"round", "value", "unit",
    "samples_n", "compile_seconds", "hbm_peak_bytes", "file"}, ...]}``,
    rounds ascending, unmeasurable rounds (parsed null / value null)
    excluded — a failed round is not a data point on a latency curve."""
    series: dict[str, list[dict]] = {}
    for rnd, path, blob in load_history(root, "BENCH", errors=errors):
        p = blob.get("parsed")
        if not isinstance(p, dict) or not isinstance(
                p.get("value"), (int, float)) or isinstance(
                p.get("value"), bool):
            continue
        key = f"{p.get('metric', '?')} | {p.get('platform', 'unknown')}"
        s = p.get("samples")
        series.setdefault(key, []).append({
            "round": rnd, "value": float(p["value"]),
            "unit": p.get("unit", "s"),
            "samples_n": len(s) if isinstance(s, list) else 0,
            "compile_seconds": p.get("compile_seconds"),
            "hbm_peak_bytes": p.get("hbm_peak_bytes"),
            "file": os.path.basename(path)})
    return series


def serve_series(root: str = ".", *,
                 errors: list[str] | None = None
                 ) -> dict[str, list[dict]]:
    """Per-backend serving time series from the committed
    ``SERVE_r*.json`` history (scripts/serve_loadgen.py): the headline
    is warm-cache p50 request latency (the compiled-chain cache's whole
    point), falling back to the overall p50 when a round recorded no
    warm hits. Keyed ``"serve warm p50 | <backend>"`` so the trend gate
    treats each backend as its own series, exactly like the bench
    metric/platform split.

    serve-v2 rounds additionally contribute ``"serve inverse goodput |
    <backend>"``: seconds per completed request (``1/goodput_rps``) —
    inverted so the shared "drifting-up = worse" trend verdict applies
    (goodput FALLING makes this series RISE, which the gate fails)."""
    series: dict[str, list[dict]] = {}
    for rnd, path, blob in load_history(root, "SERVE", errors=errors):
        warm = blob.get("warm") if isinstance(blob.get("warm"), dict) \
            else {}
        lat = blob.get("latency_s") if isinstance(
            blob.get("latency_s"), dict) else {}
        value = warm.get("p50")
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            value = lat.get("p50")
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        key = f"serve warm p50 | {blob.get('backend', 'unknown')}"
        s = blob.get("samples")
        series.setdefault(key, []).append({
            "round": rnd, "value": float(value), "unit": "s",
            "samples_n": len(s) if isinstance(s, list) else 0,
            "compile_seconds": None, "hbm_peak_bytes": None,
            "rps": blob.get("rps"),
            "file": os.path.basename(path)})
        gp = blob.get("goodput_rps")
        if isinstance(gp, (int, float)) and not isinstance(gp, bool) \
                and gp > 0:
            gkey = f"serve inverse goodput | {blob.get('backend', 'unknown')}"
            series.setdefault(gkey, []).append({
                "round": rnd, "value": 1.0 / float(gp), "unit": "s/req",
                "samples_n": len(s) if isinstance(s, list) else 0,
                "compile_seconds": None, "hbm_peak_bytes": None,
                "rps": blob.get("rps"),
                "file": os.path.basename(path)})
    return series


def workload_series(root: str = ".", *,
                    errors: list[str] | None = None
                    ) -> dict[str, list[dict]]:
    """The padding-waste time series from the committed
    ``WORKLOAD_r*.json`` history (obs/workload.py): bytes of padded-slot
    waste per profiled round — the power-of-two batching overhead the
    profiler accounts. Keyed ``"workload padding waste"`` (one series;
    a profile spans whatever the server served), fed to the same seeded
    trend gate as bench/serve: padding waste drifting UP means the
    served shape mix is fragmenting against the batch axis, and the
    gate fails the build on a confirmed trajectory."""
    series: dict[str, list[dict]] = {}
    for rnd, path, blob in load_history(root, "WORKLOAD", errors=errors):
        b = blob.get("batching") if isinstance(blob.get("batching"),
                                               dict) else {}
        waste = b.get("padding_waste_bytes")
        if not isinstance(waste, (int, float)) or isinstance(waste, bool):
            continue
        series.setdefault("workload padding waste", []).append({
            "round": rnd, "value": float(waste), "unit": "B",
            "samples_n": b.get("requests_batched") or 0,
            "compile_seconds": None, "hbm_peak_bytes": None,
            "fill_ratio": b.get("fill_ratio"),
            "file": os.path.basename(path)})
    return series


def watch_series(root: str = ".", *,
                 errors: list[str] | None = None
                 ) -> dict[str, list[dict]]:
    """The SLO-compliance time series from the committed
    ``WATCH_r*.json`` history (obs/watch.py): the worst error-budget
    burn rate across every objective and window per watched round.
    Keyed ``"slo worst burn"`` (cannot collide with bench
    ``"<metric> | <platform>"``, serve or workload keys), fed to the
    same seeded trend gate: burn drifting UP means the serve layer is
    spending its error budgets faster round over round, and the gate
    fails the build on a confirmed trajectory."""
    series: dict[str, list[dict]] = {}
    for rnd, path, blob in load_history(root, "WATCH", errors=errors):
        ev = blob.get("evaluation") if isinstance(blob.get("evaluation"),
                                                  dict) else {}
        burns = [o.get("worst_burn") for o in ev.get("objectives", [])
                 if isinstance(o, dict)
                 and isinstance(o.get("worst_burn"), (int, float))]
        if not burns:
            continue
        req = blob.get("requests") or {}
        series.setdefault("slo worst burn", []).append({
            "round": rnd, "value": float(max(burns)), "unit": "x",
            "samples_n": req.get("admitted") or 0,
            "compile_seconds": None, "hbm_peak_bytes": None,
            "compliant": ev.get("compliant"),
            "anomalies": len(blob.get("anomalies") or []),
            "file": os.path.basename(path)})
    return series


def pilot_series(root: str = ".", *,
                 errors: list[str] | None = None
                 ) -> dict[str, list[dict]]:
    """The promotion-win time series from the committed
    ``PILOT_r*.json`` history (tpu_aggcomm/pilot/): per piloted round,
    the reciprocal of the BEST confirmed win's CI lower bound among
    that round's improved campaigns (``1 / lo%``) — inverted so the
    shared "drifting-up = worse" trend verdict applies: the autopilot
    finding smaller and smaller proven wins round over round makes
    this series RISE, and the gate fails the build on a confirmed
    trajectory. Keyed ``"pilot inverse promotion win"`` (cannot collide
    with bench ``"<metric> | <platform>"``, serve, workload or watch
    keys). Rounds with no improved campaign contribute nothing — an
    idle pilot is not a trend."""
    series: dict[str, list[dict]] = {}
    for rnd, path, blob in load_history(root, "PILOT", errors=errors):
        los = []
        for d in blob.get("decisions") or []:
            ci = d.get("win_ci_pct") if isinstance(d, dict) else None
            if d.get("improved") and isinstance(ci, list) \
                    and len(ci) == 2 \
                    and isinstance(ci[0], (int, float)) \
                    and not isinstance(ci[0], bool) and ci[0] > 0:
                los.append(float(ci[0]))
        if not los:
            continue
        req = blob.get("requests") or {}
        series.setdefault("pilot inverse promotion win", []).append({
            "round": rnd, "value": 1.0 / max(los), "unit": "1/%",
            "samples_n": req.get("admitted") or 0,
            "compile_seconds": None, "hbm_peak_bytes": None,
            "best_win_lo_pct": max(los),
            "promotions": len(blob.get("promotions") or []),
            "file": os.path.basename(path)})
    return series


def flow_series(root: str = ".", *,
                errors: list[str] | None = None
                ) -> dict[str, list[dict]]:
    """The warm-overhead time series from the committed ``FLOW_r*.json``
    history (obs/flow.py): per flow-traced round, the mean fraction of
    the warm (cache-hit) client wall NOT spent in device rounds — the
    end-to-end overhead the ROADMAP item-1 warm-path work must drive
    down. Keyed ``"flow warm overhead fraction"`` (cannot collide with
    bench ``"<metric> | <platform>"``, serve, workload, watch or pilot
    keys), fed to the same seeded trend gate: overhead drifting UP
    means the serve path is growing fat around the kernels, and the
    gate fails the build on a confirmed trajectory."""
    series: dict[str, list[dict]] = {}
    for rnd, path, blob in load_history(root, "FLOW", errors=errors):
        wo = blob.get("warm_overhead") if isinstance(
            blob.get("warm_overhead"), dict) else {}
        mean = wo.get("mean")
        if not isinstance(mean, (int, float)) or isinstance(mean, bool):
            continue
        series.setdefault("flow warm overhead fraction", []).append({
            "round": rnd, "value": float(mean), "unit": "frac",
            "samples_n": wo.get("n") or 0,
            "compile_seconds": None, "hbm_peak_bytes": None,
            "ci95": wo.get("ci95"),
            "file": os.path.basename(path)})
    return series


def _tail_jsonl(path: str) -> list[dict]:
    """Torn-line-tolerant JSONL read (a live trace may be mid-append)."""
    out: list[dict] = []
    try:
        fh = open(path)
    except OSError:
        return out
    with fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def _trace_rows(root: str) -> list[dict]:
    """One row per run of every ``*.trace.jsonl`` under ``root``: the
    run's shape/fault identity plus the max-over-ranks critical total
    (re-aggregated from the attribution cell stream — never a host
    callback)."""
    from tpu_aggcomm.obs.trace import aggregate_run
    rows: list[dict] = []
    for path in sorted(glob.glob(os.path.join(root, "*.trace.jsonl"))):
        events = _tail_jsonl(path)
        for run in (e for e in events if e.get("ev") == "run"):
            agg = aggregate_run(events, run["id"])
            total = max((a["total"] for a in agg.values()), default=None)
            rows.append({
                "file": os.path.basename(path), "run": run["id"],
                "method": run.get("method"), "name": run.get("name"),
                "backend": run.get("backend"),
                "fault": run.get("fault"),
                "nprocs": run.get("nprocs"),
                "comm_size": run.get("comm_size"),
                "critical_total_s": total})
    return rows


def build_index(root: str = ".") -> dict:
    """The unified longitudinal index over every artifact family under
    ``root``. Load errors land in ``errors`` (shown, not swallowed)."""
    errors: list[str] = []
    bench = bench_series(root, errors=errors)
    multichip = [{"round": rnd, "ok": blob.get("ok"),
                  "skipped": blob.get("skipped"),
                  "n_devices": blob.get("n_devices"),
                  "file": os.path.basename(path)}
                 for rnd, path, blob in load_history(root, "MULTICHIP",
                                                     errors=errors)]
    tune = []
    for path in sorted(glob.glob(os.path.join(root, "TUNE_*.json"))):
        try:
            with open(path) as fh:
                blob = json.load(fh)
        except (OSError, ValueError) as e:
            errors.append(f"{os.path.basename(path)}: {e}")
            continue
        tune.append({"file": os.path.basename(path),
                     "key": blob.get("key"),
                     "winner": (blob.get("race") or {}).get("winner"),
                     "batches_run": (blob.get("race") or {}).get(
                         "batches_run")})
    traffic = []
    for path in sorted(glob.glob(os.path.join(root, "TRAFFIC_*.json"))):
        try:
            with open(path) as fh:
                blob = json.load(fh)
        except (OSError, ValueError) as e:
            errors.append(f"{os.path.basename(path)}: {e}")
            continue
        cfg = blob.get("config") or {}
        conf = blob.get("conformance") or {}
        traffic.append({"file": os.path.basename(path),
                        "method": cfg.get("method"),
                        "fault": cfg.get("fault"),
                        "verdict": conf.get("verdict"),
                        "peak": conf.get("peak"),
                        "bound": conf.get("bound")})
    synth = []
    for rnd, path, blob in load_history(root, "SYNTH", errors=errors):
        sr = blob.get("search") or {}
        win = blob.get("winner") or {}
        synth.append({"round": rnd, "file": os.path.basename(path),
                      "config": blob.get("config"),
                      "evaluated": sr.get("evaluated"),
                      "pruned": sr.get("pruned"),
                      "winner": win.get("cid"),
                      "composition": win.get("composition"),
                      "median_s": win.get("median_s"),
                      "predicted_rank": win.get("predicted_rank")})
    workload = []
    for rnd, path, blob in load_history(root, "WORKLOAD", errors=errors):
        req = blob.get("requests") or {}
        b = blob.get("batching") or {}
        workload.append({"round": rnd, "file": os.path.basename(path),
                         "admitted": req.get("admitted"),
                         "completed": req.get("completed"),
                         "shed": req.get("shed"),
                         "fill_ratio": b.get("fill_ratio"),
                         "padding_waste_bytes": b.get(
                             "padding_waste_bytes"),
                         "proposals": len(blob.get("proposals") or [])})
    watch = []
    for rnd, path, blob in load_history(root, "WATCH", errors=errors):
        ev = blob.get("evaluation") or {}
        req = blob.get("requests") or {}
        watch.append({"round": rnd, "file": os.path.basename(path),
                      "admitted": req.get("admitted"),
                      "compliant": ev.get("compliant"),
                      "anomalies": len(blob.get("anomalies") or []),
                      "causes": sorted({a.get("cause") for a in
                                        blob.get("anomalies") or []
                                        if isinstance(a, dict)})})
    pilot = []
    for rnd, path, blob in load_history(root, "PILOT", errors=errors):
        req = blob.get("requests") or {}
        pilot.append({"round": rnd, "file": os.path.basename(path),
                      "mode": blob.get("mode"),
                      "admitted": req.get("admitted"),
                      "targets": len(blob.get("targets") or []),
                      "promotions": len(blob.get("promotions") or []),
                      "demotions": sum(
                          1 for d in blob.get("demotions") or []
                          if isinstance(d, dict)
                          and d.get("action") == "demote"),
                      "actions": sorted({d.get("action") for d in
                                         blob.get("decisions") or []
                                         if isinstance(d, dict)})})
    flow = []
    for rnd, path, blob in load_history(root, "FLOW", errors=errors):
        req = blob.get("requests") or {}
        wo = blob.get("warm_overhead") or {}
        flow.append({"round": rnd, "file": os.path.basename(path),
                     "joined": req.get("joined"),
                     "lost": len(req.get("lost") or []),
                     "warm_overhead_mean": wo.get("mean"),
                     "verdicts": blob.get("verdicts")})
    return {"schema": HISTORY_SCHEMA, "root": os.path.abspath(root),
            "bench": bench, "multichip": multichip, "tune": tune,
            "traffic": traffic, "serve": serve_series(root, errors=errors),
            "synth": synth, "workload": workload,
            "workload_series": workload_series(root, errors=errors),
            "watch": watch,
            "watch_series": watch_series(root, errors=errors),
            "pilot": pilot,
            "pilot_series": pilot_series(root, errors=errors),
            "flow": flow,
            "flow_series": flow_series(root, errors=errors),
            "traces": _trace_rows(root), "errors": errors}


def write_index(path: str, index: dict) -> str:
    """Persist one index through ``obs.atomic_write`` (a kill mid-write
    must leave ``path`` absent or intact, never torn)."""
    from tpu_aggcomm.obs.atomic import atomic_write
    with atomic_write(path) as fh:
        json.dump(index, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path


# ---------------------------------------------------------------------------
# The multi-round trend gate.

def _ols_slope(points: list[tuple[float, float]]) -> float | None:
    """Least-squares slope of value vs round; None when degenerate
    (fewer than two distinct rounds)."""
    n = len(points)
    if n < 2:
        return None
    mx = sum(p[0] for p in points) / n
    my = sum(p[1] for p in points) / n
    var = sum((p[0] - mx) ** 2 for p in points)
    if var == 0:
        return None
    return sum((p[0] - mx) * (p[1] - my) for p in points) / var


def trend_gate(points, *, tolerance: float = TREND_TOLERANCE,
               n_boot: int = 2000, alpha: float = 0.05,
               seed: int = 0, min_rounds: int = MIN_TREND_ROUNDS) -> dict:
    """Is one (round, value) series drifting across its whole history?

    The point estimate is the OLS slope normalized by the series median
    (fraction-of-median per round; the headline metric is seconds per
    rep, so POSITIVE slope = regressing). Significance: a seeded
    pair-resampling bootstrap (resample the (round, value) points with
    replacement, re-fit the slope; degenerate resamples with fewer than
    two distinct rounds are discarded and redrawn, boundedly) gives a
    ``1 - alpha`` CI on the relative slope — the same
    point-beyond-tolerance AND CI-excludes-zero double gate the
    pairwise regression check uses, so a two-round blip cannot fake a
    trajectory. Verdicts::

        insufficient   fewer than ``min_rounds`` measurable rounds
        stable         no confirmed drift either way
        drifting-up    slope > tolerance and CI above zero (REGRESSING)
        drifting-down  slope < -tolerance and CI below zero (improving)

    Deterministic by construction: same points + same seed ⟹ same
    verdict byte-for-byte (regression-gate seed discipline)."""
    pts = [(float(r), float(v)) for r, v in points]
    out = {"verdict": "insufficient", "rounds": len(pts),
           "slope_pct_per_round": None, "ci_pct_per_round": None,
           "tolerance_pct": tolerance * 100.0, "seed": seed,
           "note": None}
    if len(pts) < min_rounds:
        out["note"] = (f"{len(pts)} measurable round(s) < {min_rounds}; "
                       f"trend gate inactive")
        return out
    med = statistics.median(v for _r, v in pts)
    if med == 0:
        out["note"] = "series median is zero; relative slope undefined"
        return out
    slope = _ols_slope(pts)
    if slope is None:
        out["note"] = "degenerate series (single distinct round)"
        return out
    rel = slope / abs(med)
    out["slope_pct_per_round"] = rel * 100.0

    rng = random.Random(seed)
    n = len(pts)
    slopes: list[float] = []
    draws = 0
    while len(slopes) < n_boot and draws < 10 * n_boot:
        draws += 1
        sample = [pts[rng.randrange(n)] for _ in range(n)]
        s = _ols_slope(sample)
        if s is not None:
            slopes.append(s / abs(med))
    if not slopes:
        out["note"] = "bootstrap degenerate (no resample with two rounds)"
        out["verdict"] = "stable"
        return out
    from tpu_aggcomm.obs.metrics import percentile
    slopes.sort()
    lo = percentile(slopes, 100.0 * (alpha / 2))
    hi = percentile(slopes, 100.0 * (1 - alpha / 2))
    out["ci_pct_per_round"] = [lo * 100.0, hi * 100.0]
    if rel > tolerance and lo > 0:
        out["verdict"] = "drifting-up"
    elif rel < -tolerance and hi < 0:
        out["verdict"] = "drifting-down"
    else:
        out["verdict"] = "stable"
        if abs(rel) > tolerance:
            out["note"] = ("point slope exceeds tolerance but bootstrap "
                           "CI includes zero — not flagged")
    return out


def check_trends(root: str = ".", *, tolerance: float = TREND_TOLERANCE,
                 seed: int = 0) -> dict:
    """The trend gate over every per-(metric, platform) bench series,
    every per-backend serve series, the workload padding-waste series
    the watchtower SLO burn series AND the autopilot promotion-win
    series under ``root``. ``ok`` is False only on a confirmed
    ``drifting-up`` verdict — improvement and insufficient history are
    not failures. (Key formats cannot collide: bench keys are
    ``"<metric> | <platform>"``, serve keys ``"serve warm p50 |
    <backend>"``, the workload key is ``"workload padding waste"``, the
    watch key is ``"slo worst burn"``, the pilot key is ``"pilot
    inverse promotion win"``, the flow key is ``"flow warm overhead
    fraction"``.)"""
    errors: list[str] = []
    series = dict(bench_series(root, errors=errors))
    series.update(serve_series(root, errors=errors))
    series.update(workload_series(root, errors=errors))
    series.update(watch_series(root, errors=errors))
    series.update(pilot_series(root, errors=errors))
    series.update(flow_series(root, errors=errors))
    gates = {key: trend_gate([(r["round"], r["value"]) for r in rows],
                             tolerance=tolerance, seed=seed)
             for key, rows in sorted(series.items())}
    return {"check": "trend", "ok": not errors and not any(
                g["verdict"] == "drifting-up" for g in gates.values()),
            "tolerance_pct": tolerance * 100.0, "seed": seed,
            "series": gates, "errors": errors}


# ---------------------------------------------------------------------------
# Rendering (``cli inspect history``).

def _fmt_val(v, unit: str) -> str:
    return f"{v:.6g} {unit}" if isinstance(v, (int, float)) else "-"


def render_history(root: str = ".") -> str:
    """The ``inspect history`` text view: every bench series with its
    trend verdict, then one summary line per other artifact family."""
    index = build_index(root)
    trends = check_trends(root)
    lines: list[str] = []
    for key, rows in sorted(index["bench"].items()):
        gate = trends["series"].get(key, {})
        lines.append(f"== {key} ({len(rows)} measurable rounds) ==")
        for r in rows:
            extras = []
            if r["samples_n"]:
                extras.append(f"{r['samples_n']} samples")
            if r["compile_seconds"] is not None:
                extras.append(f"compile {r['compile_seconds']:.3g} s")
            ex = f"  [{', '.join(extras)}]" if extras else ""
            lines.append(f"  r{r['round']:02d}: "
                         f"{_fmt_val(r['value'], r['unit'])}{ex}")
        slope = gate.get("slope_pct_per_round")
        ci = gate.get("ci_pct_per_round")
        detail = []
        if slope is not None:
            detail.append(f"slope {slope:+.1f}%/round")
        if ci is not None:
            detail.append(f"95% CI [{ci[0]:+.1f}%, {ci[1]:+.1f}%]")
        detail.append(f"tolerance {gate.get('tolerance_pct', 0):.0f}%/round"
                      f" (seed {gate.get('seed')})")
        lines.append(f"  trend: {gate.get('verdict', '?').upper()} — "
                     + ", ".join(detail))
        if gate.get("note"):
            lines.append(f"  note: {gate['note']}")
    if not index["bench"]:
        lines.append("no measurable bench history")
    for key, rows in sorted(index["serve"].items()):
        gate = trends["series"].get(key, {})
        lines.append(f"== {key} ({len(rows)} measurable rounds) ==")
        for r in rows:
            extras = []
            if r["samples_n"]:
                extras.append(f"{r['samples_n']} samples")
            if isinstance(r.get("rps"), (int, float)):
                extras.append(f"{r['rps']:.3g} req/s")
            ex = f"  [{', '.join(extras)}]" if extras else ""
            lines.append(f"  r{r['round']:02d}: "
                         f"{_fmt_val(r['value'], r['unit'])}{ex}")
        detail = []
        if gate.get("slope_pct_per_round") is not None:
            detail.append(f"slope {gate['slope_pct_per_round']:+.1f}%"
                          f"/round")
        if gate.get("ci_pct_per_round") is not None:
            ci = gate["ci_pct_per_round"]
            detail.append(f"95% CI [{ci[0]:+.1f}%, {ci[1]:+.1f}%]")
        detail.append(f"tolerance {gate.get('tolerance_pct', 0):.0f}%"
                      f"/round (seed {gate.get('seed')})")
        lines.append(f"  trend: {gate.get('verdict', '?').upper()} — "
                     + ", ".join(detail))
        if gate.get("note"):
            lines.append(f"  note: {gate['note']}")
    for key, rows in sorted(index["workload_series"].items()):
        gate = trends["series"].get(key, {})
        lines.append(f"== {key} ({len(rows)} profiled rounds) ==")
        for r in rows:
            extras = []
            if r["samples_n"]:
                extras.append(f"{r['samples_n']} batched requests")
            if isinstance(r.get("fill_ratio"), (int, float)):
                extras.append(f"fill {r['fill_ratio']:.2f}")
            ex = f"  [{', '.join(extras)}]" if extras else ""
            lines.append(f"  r{r['round']:02d}: "
                         f"{_fmt_val(r['value'], r['unit'])}{ex}")
        detail = []
        if gate.get("slope_pct_per_round") is not None:
            detail.append(f"slope {gate['slope_pct_per_round']:+.1f}%"
                          f"/round")
        if gate.get("ci_pct_per_round") is not None:
            ci = gate["ci_pct_per_round"]
            detail.append(f"95% CI [{ci[0]:+.1f}%, {ci[1]:+.1f}%]")
        detail.append(f"tolerance {gate.get('tolerance_pct', 0):.0f}%"
                      f"/round (seed {gate.get('seed')})")
        lines.append(f"  trend: {gate.get('verdict', '?').upper()} — "
                     + ", ".join(detail))
        if gate.get("note"):
            lines.append(f"  note: {gate['note']}")
    for key, rows in sorted(index["watch_series"].items()):
        gate = trends["series"].get(key, {})
        lines.append(f"== {key} ({len(rows)} watched rounds) ==")
        for r in rows:
            extras = []
            if r["samples_n"]:
                extras.append(f"{r['samples_n']} requests")
            extras.append("compliant" if r.get("compliant")
                          else "VIOLATED")
            if r.get("anomalies"):
                extras.append(f"{r['anomalies']} anomaly(ies)")
            ex = f"  [{', '.join(extras)}]" if extras else ""
            lines.append(f"  r{r['round']:02d}: "
                         f"{_fmt_val(r['value'], r['unit'])}{ex}")
        detail = []
        if gate.get("slope_pct_per_round") is not None:
            detail.append(f"slope {gate['slope_pct_per_round']:+.1f}%"
                          f"/round")
        if gate.get("ci_pct_per_round") is not None:
            ci = gate["ci_pct_per_round"]
            detail.append(f"95% CI [{ci[0]:+.1f}%, {ci[1]:+.1f}%]")
        detail.append(f"tolerance {gate.get('tolerance_pct', 0):.0f}%"
                      f"/round (seed {gate.get('seed')})")
        lines.append(f"  trend: {gate.get('verdict', '?').upper()} — "
                     + ", ".join(detail))
        if gate.get("note"):
            lines.append(f"  note: {gate['note']}")
    for key, rows in sorted(index["pilot_series"].items()):
        gate = trends["series"].get(key, {})
        lines.append(f"== {key} ({len(rows)} piloted rounds) ==")
        for r in rows:
            extras = [f"best win lo {r['best_win_lo_pct']:.1f}%"]
            if r.get("promotions"):
                extras.append(f"{r['promotions']} promotion(s)")
            lines.append(f"  r{r['round']:02d}: "
                         f"{_fmt_val(r['value'], r['unit'])}"
                         f"  [{', '.join(extras)}]")
        detail = []
        if gate.get("slope_pct_per_round") is not None:
            detail.append(f"slope {gate['slope_pct_per_round']:+.1f}%"
                          f"/round")
        if gate.get("ci_pct_per_round") is not None:
            ci = gate["ci_pct_per_round"]
            detail.append(f"95% CI [{ci[0]:+.1f}%, {ci[1]:+.1f}%]")
        detail.append(f"tolerance {gate.get('tolerance_pct', 0):.0f}%"
                      f"/round (seed {gate.get('seed')})")
        lines.append(f"  trend: {gate.get('verdict', '?').upper()} — "
                     + ", ".join(detail))
        if gate.get("note"):
            lines.append(f"  note: {gate['note']}")
    for key, rows in sorted(index["flow_series"].items()):
        gate = trends["series"].get(key, {})
        lines.append(f"== {key} ({len(rows)} flow-traced rounds) ==")
        for r in rows:
            extras = []
            if r["samples_n"]:
                extras.append(f"{r['samples_n']} warm requests")
            if isinstance(r.get("ci95"), list) and len(r["ci95"]) == 2:
                extras.append(f"95% CI [{r['ci95'][0]:.3f}, "
                              f"{r['ci95'][1]:.3f}]")
            ex = f"  [{', '.join(extras)}]" if extras else ""
            lines.append(f"  r{r['round']:02d}: "
                         f"{_fmt_val(r['value'], r['unit'])}{ex}")
        detail = []
        if gate.get("slope_pct_per_round") is not None:
            detail.append(f"slope {gate['slope_pct_per_round']:+.1f}%"
                          f"/round")
        if gate.get("ci_pct_per_round") is not None:
            ci = gate["ci_pct_per_round"]
            detail.append(f"95% CI [{ci[0]:+.1f}%, {ci[1]:+.1f}%]")
        detail.append(f"tolerance {gate.get('tolerance_pct', 0):.0f}%"
                      f"/round (seed {gate.get('seed')})")
        lines.append(f"  trend: {gate.get('verdict', '?').upper()} — "
                     + ", ".join(detail))
        if gate.get("note"):
            lines.append(f"  note: {gate['note']}")
    for w in index["workload"]:
        props = f", {w['proposals']} advisory proposal(s)" \
            if w["proposals"] else ""
        lines.append(f"workload: {w['file']} — {w['admitted']} admitted, "
                     f"{w['completed']} completed, {w['shed']} shed"
                     f"{props}")
    for w in index["watch"]:
        causes = f" — causes: {', '.join(w['causes'])}" \
            if w["causes"] else ""
        lines.append(f"watch: {w['file']} — {w['admitted']} requests, "
                     f"SLO {'compliant' if w['compliant'] else 'VIOLATED'}"
                     f", {w['anomalies']} anomaly(ies){causes}")
    for p in index["pilot"]:
        acts = f" — actions: {', '.join(p['actions'])}" \
            if p["actions"] else ""
        lines.append(f"pilot: {p['file']} ({p['mode']}) — "
                     f"{p['admitted']} requests profiled, "
                     f"{p['targets']} target(s), "
                     f"{p['promotions']} promotion(s), "
                     f"{p['demotions']} demotion(s){acts}")
    for f in index["flow"]:
        verd = ", ".join(f"{v} x{n}" for v, n in sorted(
            (f.get("verdicts") or {}).items())) or "none"
        lost = f", {f['lost']} LOST" if f["lost"] else ""
        lines.append(f"flow: {f['file']} — {f['joined']} joined "
                     f"request(s){lost}, verdicts: {verd}")
    mc = index["multichip"]
    if mc:
        ok = sum(1 for m in mc if m.get("ok"))
        lines.append(f"multichip: {len(mc)} rounds, {ok} ok, "
                     f"{sum(1 for m in mc if m.get('skipped'))} skipped")
    if index["tune"]:
        winners = ", ".join(f"{t['file']}={t['winner']}"
                            for t in index["tune"])
        lines.append(f"tune cache: {winners}")
    if index["traffic"]:
        verd = ", ".join(f"{t['file']}={t['verdict']}"
                         for t in index["traffic"])
        lines.append(f"traffic audits: {verd}")
    for s in index["synth"]:
        lines.append(f"synth: {s['file']} winner {s['winner']} "
                     f"({s['composition']}) over {s['evaluated']} "
                     f"composition(s), predicted rank "
                     f"{s['predicted_rank']}")
    tr = index["traces"]
    if tr:
        faulted = sum(1 for t in tr if t.get("fault"))
        lines.append(f"traces: {len(tr)} runs across "
                     f"{len({t['file'] for t in tr})} files "
                     f"({faulted} faulted)")
    for e in index["errors"]:
        lines.append(f"ERROR: {e}")
    return "\n".join(lines) + "\n"
