"""Straggler analytics over flight-recorder event logs — jax-free.

The reference benchmark's headline metric is the max-over-ranks
completion time (the per-phase ``MPI_Reduce`` MAX, mpi_test.c:2184), so
the scientifically interesting question is always *which rank/round is
the straggler and by how much*. This module answers it from the trace
stream the flight recorder already captures:

- :func:`round_stats` — per-round distributions over ranks (p50/p95/max,
  skew = max/mean, imbalance share = the fraction of the round's wall
  time attributable to rank skew);
- :func:`critical_path` — attributes the max-over-ranks critical path to
  concrete (rank, round, phase) cells, with the run's column-accurate
  ``PHASE_SOURCES`` provenance label carried through so an attributed
  decomposition can never be read as a measured one;
- :func:`summarize_traces` — the ``cli inspect trace`` view over one or
  MANY trace files (a sweep's per-cell artifacts merge into one
  straggler table instead of erroring on the second file);
- :func:`bootstrap_ci` / :func:`bootstrap_delta_ci` / :func:`sign_test`
  — the statistical kernel shared with the regression gate
  (obs/regress.py) and trace diffing (obs/compare.py). Pure python,
  deterministic (seeded), so verdicts are reproducible byte-for-byte.

Everything here consumes the JSONL event vocabulary of obs/trace.py
(span ``dur_s`` is the EXACT attributed seconds; aggregation replays the
Timer arithmetic via :func:`tpu_aggcomm.obs.trace.aggregate_run`).
Nothing imports jax — bench.py's supervisor may import this freely.
"""

from __future__ import annotations

import math
import random
import statistics

from tpu_aggcomm.obs.trace import (BUCKET_FIELDS, aggregate_run, load_events,
                                   round_key, summarize_events)

__all__ = ["percentile", "bootstrap_ci", "bootstrap_delta_ci", "sign_test",
           "run_events", "bucket_cells", "cell_means", "round_stats",
           "critical_path", "summarize_run", "render_run_analytics",
           "summarize_traces", "PHASE_ORDER"]

#: Phase (bucket) display order — the Timer-column vocabulary in the
#: order obs/trace.py defines it (post, send_wait, recv_wait,
#: recv+send_wait, barrier).
PHASE_ORDER = tuple(BUCKET_FIELDS)


# ---------------------------------------------------------------------------
# Statistical kernel (pure python, deterministic).

def percentile(values, q: float) -> float:
    """Linear-interpolated percentile (``q`` in [0, 100]) of a non-empty
    sequence — the numpy 'linear' method, without numpy."""
    vs = sorted(values)
    if not vs:
        raise ValueError("percentile of empty sequence")
    if len(vs) == 1:
        return float(vs[0])
    pos = (len(vs) - 1) * q / 100.0
    lo = math.floor(pos)
    hi = math.ceil(pos)
    frac = pos - lo
    return float(vs[lo]) * (1.0 - frac) + float(vs[hi]) * frac


def bootstrap_ci(samples, stat=statistics.median, *, n_boot: int = 2000,
                 alpha: float = 0.05, seed: int = 0) -> tuple[float, float]:
    """Percentile-bootstrap ``1 - alpha`` confidence interval for
    ``stat(samples)``. Seeded — the regression gate's verdict must be
    reproducible from the same artifacts."""
    xs = list(samples)
    if not xs:
        raise ValueError("bootstrap_ci of empty sample")
    rng = random.Random(seed)
    n = len(xs)
    stats = sorted(stat([xs[rng.randrange(n)] for _ in range(n)])
                   for _ in range(n_boot))
    return (percentile(stats, 100.0 * (alpha / 2)),
            percentile(stats, 100.0 * (1 - alpha / 2)))


def bootstrap_delta_ci(baseline, current, stat=statistics.median, *,
                       relative: bool = True, n_boot: int = 2000,
                       alpha: float = 0.05, seed: int = 0
                       ) -> tuple[float, float]:
    """Percentile-bootstrap CI on ``stat(current) - stat(baseline)``
    (independent resampling of the two trial sets — bench trials are
    unpaired across rounds). With ``relative`` the delta is divided by
    ``stat(baseline)``, i.e. the CI is on the relative slowdown the
    regression gate thresholds. Positive = current slower."""
    xs, ys = list(baseline), list(current)
    if not xs or not ys:
        raise ValueError("bootstrap_delta_ci needs non-empty samples")
    rng = random.Random(seed)
    nx, ny = len(xs), len(ys)
    deltas = []
    for _ in range(n_boot):
        bx = stat([xs[rng.randrange(nx)] for _ in range(nx)])
        by = stat([ys[rng.randrange(ny)] for _ in range(ny)])
        d = by - bx
        deltas.append(d / bx if relative else d)
    deltas.sort()
    return (percentile(deltas, 100.0 * (alpha / 2)),
            percentile(deltas, 100.0 * (1 - alpha / 2)))


def sign_test(deltas) -> dict:
    """Two-sided exact sign test over paired deltas (zeros dropped).

    Returns ``{"n": usable pairs, "pos": #positive, "neg": #negative,
    "p": two-sided p-value | None}`` — ``p`` is None when fewer than two
    usable pairs exist (a chained trace has one combined rep; no
    repeated trials means no test, not a fake certainty)."""
    pos = sum(1 for d in deltas if d > 0)
    neg = sum(1 for d in deltas if d < 0)
    n = pos + neg
    if n < 2:
        return {"n": n, "pos": pos, "neg": neg, "p": None}
    k = min(pos, neg)
    # two-sided exact binomial(n, 0.5) tail, doubled and clamped
    tail = sum(math.comb(n, i) for i in range(k + 1)) / 2.0 ** n
    return {"n": n, "pos": pos, "neg": neg, "p": min(1.0, 2.0 * tail)}


# ---------------------------------------------------------------------------
# Trace tables.

def run_events(events: list[dict]) -> list[dict]:
    """The run records of an event log, in recording order."""
    return [e for e in events if e["ev"] == "run"]


def bucket_cells(events: list[dict], run_id: int
                 ) -> dict[int, dict[tuple, float]]:
    """``{rep: {(rank, round, bucket): seconds}}`` from one run's
    reconstructed bucket slices (rep envelopes excluded). ``dur_s`` is
    the exact attributed seconds, so sums here stay float-faithful to
    the Timer columns."""
    out: dict[int, dict[tuple, float]] = {}
    for e in events:
        if e["ev"] != "span" or e["run"] != run_id \
                or e["bucket"] == "total":
            continue
        per = out.setdefault(e["rep"], {})
        key = (e["rank"], e["round"], e["bucket"])
        per[key] = per.get(key, 0.0) + e["dur_s"]
    return out


def cell_means(events: list[dict], run_id: int) -> dict[tuple, float]:
    """``{(rank, round): mean seconds across recorded reps}`` — the
    bucket-summed straggler grid one run induces."""
    per_rep = bucket_cells(events, run_id)
    acc: dict[tuple, list[float]] = {}
    for cells in per_rep.values():
        rep_acc: dict[tuple, float] = {}
        for (rank, rnd, _bucket), secs in cells.items():
            rep_acc[(rank, rnd)] = rep_acc.get((rank, rnd), 0.0) + secs
        for key, secs in rep_acc.items():
            acc.setdefault(key, []).append(secs)
    return {k: sum(v) / len(v) for k, v in acc.items()}


def round_stats(events: list[dict], run_id: int) -> list[dict]:
    """Per-round distribution over ranks of the mean-across-reps cell
    grid, in program order. Each entry::

        {"round", "ranks", "wall", "mean", "p50", "p95", "max",
         "skew", "imbalance", "critical_rank"}

    ``wall`` (= ``max``) is the round's wall time under the recorder's
    geometry (each round as wide as its slowest rank); ``skew`` is
    max/mean; ``imbalance`` = (max - mean) / max, the share of the
    round's wall time that pure rank balance would reclaim."""
    grid = cell_means(events, run_id)
    by_round: dict = {}
    for (rank, rnd), secs in grid.items():
        by_round.setdefault(rnd, {})[rank] = secs
    out = []
    for rnd in sorted(by_round, key=round_key):
        per_rank = by_round[rnd]
        vals = list(per_rank.values())
        mx = max(vals)
        mean = sum(vals) / len(vals)
        crit = max(per_rank, key=per_rank.get)
        out.append({
            "round": rnd, "ranks": len(vals), "wall": mx, "mean": mean,
            "p50": percentile(vals, 50), "p95": percentile(vals, 95),
            "max": mx,
            "skew": (mx / mean) if mean > 0 else None,
            "imbalance": ((mx - mean) / mx) if mx > 0 else 0.0,
            "critical_rank": crit})
    return out


def critical_path(events: list[dict], run_id: int) -> dict | None:
    """Attribute the max-over-ranks critical path of one run.

    The critical rank is the arg-max of the re-aggregated Timer totals
    (exactly the rank the reference's MAX-reduce reports); its time is
    then decomposed into (round, phase) cells (mean across reps),
    largest first. Returns None when the run recorded no slices.
    ``phase_source`` is the run's column-accurate PHASE_SOURCES label —
    the provenance of every cell below it."""
    run = next((e for e in events
                if e["ev"] == "run" and e["id"] == run_id), None)
    agg = aggregate_run(events, run_id)
    if run is None or not agg:
        return None
    crit = max(agg, key=lambda r: agg[r]["total"])
    total = agg[crit]["total"]
    per_rep = bucket_cells(events, run_id)
    acc: dict[tuple, list[float]] = {}
    for cells in per_rep.values():
        for (rank, rnd, bucket), secs in cells.items():
            if rank == crit:
                acc.setdefault((rnd, bucket), []).append(secs)
    cells_out = sorted(
        ({"round": rnd, "bucket": bucket,
          "seconds": sum(v) / len(v),
          "share": (sum(v) / len(v)) / total if total > 0 else None}
         for (rnd, bucket), v in acc.items()),
        key=lambda c: -c["seconds"])
    return {"rank": crit, "total": total,
            "phase_source": run["phase_source"],
            "method": run["method"], "name": run["name"],
            "dominant": cells_out[0] if cells_out else None,
            "cells": cells_out}


def summarize_run(events: list[dict], run_id: int) -> dict:
    """One run's full analytics bundle: the run record, per-round
    distributions, and the critical-path attribution."""
    run = next(e for e in events
               if e["ev"] == "run" and e["id"] == run_id)
    return {"run": run, "rounds": round_stats(events, run_id),
            "critical": critical_path(events, run_id)}


def _fmt_round(rnd) -> str:
    from tpu_aggcomm.obs.trace import WHOLE_REP
    if rnd == WHOLE_REP:
        return "whole-rep"
    return f"round {rnd}" if isinstance(rnd, int) else str(rnd)


def render_run_analytics(events: list[dict], run_id: int) -> str:
    """Per-round skew table + critical-path attribution, as text lines
    (appended under each run's base summary by ``inspect trace``)."""
    lines = []
    for rs in round_stats(events, run_id):
        skew = f"{rs['skew']:.2f}" if rs["skew"] is not None else "-"
        lines.append(
            f"    {_fmt_round(rs['round']):>10}: "
            f"p50 {rs['p50'] * 1e3:9.3f}  p95 {rs['p95'] * 1e3:9.3f}  "
            f"max {rs['max'] * 1e3:9.3f} ms  skew {skew}  "
            f"imbalance {rs['imbalance'] * 100:4.1f}%  "
            f"critical rank {rs['critical_rank']}")
    cp = critical_path(events, run_id)
    if cp is not None and cp["dominant"] is not None:
        d = cp["dominant"]
        lines.append(
            f"  critical path: rank {cp['rank']} "
            f"({cp['total'] * 1e3:.3f} ms total), dominant cell "
            f"{_fmt_round(d['round'])} [{d['bucket']}] = "
            f"{d['seconds'] * 1e3:.3f} ms "
            f"({d['share'] * 100:.0f}% of total)  "
            f"[src: {cp['phase_source']}]")
    return "\n".join(lines)


def summarize_traces(paths: list[str]) -> str:
    """``cli inspect trace`` over one or many trace files.

    One file reproduces the single-file summary plus the skew/critical-
    path analytics. Many files (a sweep's per-cell artifacts) get one
    section per file and a merged straggler table across every run of
    every file — the cross-cell view a sweep exists to produce."""
    sections = []
    merged: list[tuple] = []            # (file, run_id, critical dict)
    for path in paths:
        events = load_events(path)
        body = summarize_events(events).rstrip("\n")
        extra = []
        for run in run_events(events):
            block = render_run_analytics(events, run["id"])
            if block:
                extra.append(f"run {run['id']} straggler analytics "
                             f"(over ranks, mean across reps):")
                extra.append(block)
            cp = critical_path(events, run["id"])
            if cp is not None:
                merged.append((path, run["id"], cp))
        head = f"== {path} ==" if len(paths) > 1 else None
        sections.append("\n".join(
            ([head] if head else []) + [body] + extra))
    if len(paths) > 1:
        lines = [f"== merged straggler summary: {len(paths)} files, "
                 f"{len(merged)} runs =="]
        for path, rid, cp in merged:
            d = cp["dominant"]
            dom = (f"{_fmt_round(d['round'])} [{d['bucket']}] "
                   f"{d['seconds'] * 1e3:.3f} ms "
                   f"({d['share'] * 100:.0f}%)"
                   if d is not None else "-")
            lines.append(
                f"  {path}: run {rid} m={cp['method']} "
                f"\"{cp['name']}\"  critical rank {cp['rank']} "
                f"total {cp['total'] * 1e3:.3f} ms  dominant {dom}")
        if merged:
            worst = max(merged, key=lambda t: t[2]["total"])
            lines.append(
                f"  slowest critical path: {worst[0]} run {worst[1]} "
                f"(rank {worst[2]['rank']}, "
                f"{worst[2]['total'] * 1e3:.3f} ms)")
        sections.append("\n".join(lines))
    return "\n".join(sections) + "\n"
