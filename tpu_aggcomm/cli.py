"""Command-line interface, flag-compatible with the reference ``./test``.

Reference grammar ``"hp:c:m:d:a:i:k:t:r:b:"`` (mpi_test.c:2130-2166) plus
the TPU-framework extensions: ``-n`` rank count (the reference gets it from
``mpiexec -n``), ``--backend``, ``--verify``, ``--profile-rounds``. The
``pt2pt`` subcommand reproduces mpi_sendrecv_test.c (grammar ``hk:d:i:``).

Examples::

    python -m tpu_aggcomm.cli -n 8 -m 1 -a 3 -d 2048 -c 3 -i 2 --backend local --verify
    python -m tpu_aggcomm.cli -n 8 -m 0 -a 3 -d 256 --backend jax_ici
    python -m tpu_aggcomm.cli pt2pt -d 2048 -k 10 -i 100
"""

from __future__ import annotations

import argparse
import sys

from tpu_aggcomm.backends.registry import (BACKENDS, DEVICE_FREE_BACKENDS,
                                           SHARDED_RANK_BACKENDS,
                                           SINGLE_DEVICE_BACKENDS)

__all__ = ["main", "build_parser"]


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="tpu_aggcomm",
        description="TPU-native aggregator-communication benchmark "
                    "(capabilities of the reference MPI ./test harness)")
    sub = ap.add_subparsers(dest="command")

    bench = ap  # main command keeps reference flags at top level
    bench.add_argument("-n", "--nprocs", type=int, default=None,
                       help="logical ranks (reference: mpiexec -n; default: "
                            "number of visible devices for device backends, "
                            "32 for the device-free local/native backends)")
    bench.add_argument("-m", dest="method", type=int, default=0,
                       help="method id 0-20 (0 = all; mpi_test.c usage)")
    bench.add_argument("-a", dest="cb_nodes", type=int, default=1,
                       help="number of aggregators (cb_nodes)")
    bench.add_argument("-d", dest="data_size", type=int, default=0,
                       help="message size in bytes")
    bench.add_argument("-c", dest="comm_size", type=int, default=200_000_000,
                       help="max in-flight messages per round (throttle)")
    bench.add_argument("-i", dest="iters", type=int, default=1,
                       help="outer experiment repetitions (fresh buffers)")
    bench.add_argument("-k", dest="ntimes", type=int, default=1,
                       help="timed reps inside one window (no resync)")
    bench.add_argument("-p", dest="proc_node", type=int, default=1,
                       help="ranks per (simulated) node")
    bench.add_argument("-t", dest="agg_type", type=int, default=1,
                       help="aggregator placement policy 0-3")
    bench.add_argument("-r", dest="prefix", type=str, default="",
                       help="per-rank CSV filename prefix")
    bench.add_argument("-b", dest="barrier_type", type=int, default=0,
                       help="barrier mode for m=13 (0 none, 1 per rep, 2 per block)")
    bench.add_argument("--backend", choices=BACKENDS, default="local")
    bench.add_argument("--verify", action="store_true",
                       help="deterministic-fill verification (first-class "
                            "version of the reference's commented-out checks)")
    bench.add_argument("--profile-rounds", action="store_true",
                       help="jax_ici: time each throttle round separately")
    bench.add_argument("--chained", action="store_true",
                       help="jax_sim/jax_shard/jax_ici: serial-chained on-device per-rep "
                            "measurement (cancels dispatch RPC overhead — "
                            "the honest mode on a tunneled TPU)")
    bench.add_argument("--measured-phases", action="store_true",
                       help="jax_sim/jax_shard/jax_ici, round-structured "
                            "methods (+ TAM m=15/16 on jax_sim): MEASURED "
                            "per-round / per-hop durations via chained "
                            "prefix-truncation differencing (no model "
                            "parameter; single-round schedules fall back "
                            "to the measured post/deliver split on "
                            "jax_sim, attributed-chained elsewhere); "
                            "phase columns marked 'measured-rounds/-hops/"
                            "-split...+attributed(...)' in the "
                            "provenance sidecar")
    bench.add_argument("--auto", action="store_true",
                       help="resolve -m/-a/-c/-t from the tuned-schedule "
                            "cache (TUNE_*.json under --tune-root, written "
                            "by 'tune') for this shape/backend; explicit "
                            "warning + fallback to the given flags on a "
                            "cache miss, schema failure, or environment "
                            "drift vs the tuning manifest")
    bench.add_argument("--tune-root", default=".",
                       help="directory holding TUNE_*.json artifacts "
                            "(default: .)")
    bench.add_argument("--synth-root", metavar="DIR", default=None,
                       help="register the synthesized methods recorded in "
                            "DIR's committed SYNTH_r*.json artifacts "
                            "before resolving -m (tpu_aggcomm/synth/); "
                            "implied with root '.' when -m falls in the "
                            "reserved id range (> 100). Without it, "
                            "output is byte-identical to a synth-less "
                            "build")
    bench.add_argument("--results-csv", default="results.csv")
    bench.add_argument("--trace", metavar="PREFIX", default=None,
                       help="flight recorder: write PREFIX.trace.jsonl "
                            "(structured events; inspect with 'inspect "
                            "trace') and PREFIX.trace.json (Chrome/"
                            "Perfetto). Results CSVs and console output "
                            "are unchanged; off = zero overhead")
    bench.add_argument("--xprof", metavar="LOGDIR", default=None,
                       help="profile ONE extra rep per method under "
                            "jax.profiler.trace into LOGDIR and print a "
                            "divergence report: device timeline (or "
                            "profiled host wall) vs the reconstructed "
                            "attribution rep — a cross-check only; the "
                            "timed path and the reconstructed cells are "
                            "untouched")
    bench.add_argument("--fault", metavar="SPEC", default=None,
                       help="fault-injection scenario "
                            "'slow:rR*F,deadlink:S>D,deadagg:aI' "
                            "(comma-separated clauses, any mix): schedules "
                            "are repaired around dead links/aggregators "
                            "(relay detour / fallback election, "
                            "faults/repair.py) before dispatch, and slow "
                            "ranks get injected busy work; --verify still "
                            "checks byte-exact delivery and 'inspect "
                            "traffic --fault' re-proves the -c bound "
                            "statically")

    pt = sub.add_parser("pt2pt", help="2-rank latency microbenchmark "
                                      "(mpi_sendrecv_test.c)")
    pt.add_argument("-d", dest="data_size", type=int, default=0)
    pt.add_argument("-k", dest="ntimes", type=int, default=0)
    pt.add_argument("-i", dest="runs", type=int, default=0)
    pt.add_argument("--chained", action="store_true",
                    help="serial-chained differenced per-transfer timing "
                         "(honest through the TPU tunnel)")

    # TAM workload harness — the reference's DEBUG driver
    # (lustre_driver_test.c:1417-1509, grammar "hp:b:n:t:r:c:")
    tam = sub.add_parser(
        "tam", help="hierarchical-engine workload harness: topology -> "
                    "synthetic workload -> aggregator metadata -> engine -> "
                    "correctness check (the reference's DEBUG driver)")
    tam.add_argument("-n", "--nprocs", type=int, default=8,
                     help="logical ranks (reference: mpiexec -n)")
    tam.add_argument("-p", dest="proc_node", type=int, default=4,
                     help="ranks per (simulated) node")
    tam.add_argument("-b", dest="blocklen", type=int, default=16,
                     help="message block unit size (sizes are 1 + rank %% b)")
    tam.add_argument("-t", dest="stripe", type=int, default=0, choices=[0, 1, 2, 3],
                     help="workload type: 0 SAME (node proxies), 1 GREATER "
                          "(odd ranks), 2 LESS (first half), 3 ALL")
    tam.add_argument("-r", dest="rank_assignment", type=int, default=0,
                     choices=[0, 1], help="node map: 0 contiguous, 1 round-robin")
    tam.add_argument("-c", dest="co", type=int, default=1,
                     help="local aggregators per node")
    tam.add_argument("-k", dest="ntimes", type=int, default=1,
                     help="timed engine repetitions")
    tam.add_argument("--mode", type=int, default=0, choices=[0, 1],
                     help="local-aggregator selection: 0 even spread, "
                          "1 superset of global aggregators")
    tam.add_argument("--engine",
                     choices=("proxy", "local_agg", "shared", "benchmark",
                              "jax", "shared_jax", "sim", "native",
                              "native2", "native3"),
                     default="proxy",
                     help="route: collective_write / _2 / _3 / _benchmark "
                          "oracles, the compiled mesh programs (jax = "
                          "two-level, shared_jax = shared-window staging "
                          "via in-slice all_gather), the compiled "
                          "single-chip proxy route (sim — runs on one "
                          "real TPU), or the C++ threaded engines "
                          "(native = proxy route, native2 = two-level "
                          "local-aggregator route, native3 = shared-"
                          "window route)")
    tam.add_argument("--chained", action="store_true",
                     help="engine sim only: serial-chained differenced "
                          "per-rep timing (honest through the TPU tunnel)")
    tam.add_argument("--reorder", action="store_true",
                     help="apply reorder_ranklist before the engine: deal "
                          "the destination list round-robin across nodes "
                          "so consecutive destinations sit on distinct "
                          "nodes (the reference driver's commented-out "
                          "flow, lustre_driver_test.c:1495-1499 — an "
                          "optional extension, not dispatched there)")

    # sweep — the Theta job scripts (script_theta_*.sh:33-106)
    sw = sub.add_parser(
        "sweep", help="throttle sweep over the reference job-script grid "
                      "(-c in 1,2,4,...,8192,unthrottled)")
    sw.add_argument("-n", "--nprocs", type=int, default=None)
    sw.add_argument("-m", dest="method", type=int, default=1,
                    help="method id (scripts use 1 / 2)")
    sw.add_argument("-a", dest="cb_nodes", type=int, default=4)
    sw.add_argument("-d", dest="data_size", type=int, default=2048)
    sw.add_argument("-i", dest="iters", type=int, default=5)
    sw.add_argument("-k", dest="ntimes", type=int, default=1)
    sw.add_argument("-p", dest="proc_node", type=int, default=1)
    sw.add_argument("-t", dest="agg_type", type=int, default=1)
    sw.add_argument("--backend", choices=BACKENDS, default="local")
    sw.add_argument("--verify", action="store_true")
    sw.add_argument("--measured-phases", action="store_true",
                    help="jax_sim/jax_shard/jax_ici: measured per-round/"
                         "per-hop rows per sweep cell (one prefix-chain "
                         "compile per round per cell — meaningful compile "
                         "cost on deep-throttle cells; cells beyond "
                         "MAX_MEASURED_ROUNDS fail upfront)")
    sw.add_argument("--chained", action="store_true",
                    help="jax_sim/jax_shard/jax_ici: serial-chained per-rep "
                         "measurement")
    sw.add_argument("--resume", action="store_true",
                    help="skip throttle values already recorded in the "
                         "results CSV for this config (an interrupted sweep "
                         "picks up where it stopped)")
    sw.add_argument("--results-csv", default="results.csv")
    sw.add_argument("--trace", metavar="PREFIX", default=None,
                    help="flight recorder over the whole sweep: one "
                         "PREFIX.trace.{jsonl,json} pair covering every "
                         "cell")
    sw.add_argument("--comm-sizes", type=str, default=None,
                    help="comma-separated throttle values (default: the "
                         "Theta grid 1,2,4,...,8192,999999999)")
    sw.add_argument("--auto", action="store_true",
                    help="resolve the METHOD from the tuned-schedule "
                         "cache for this shape/backend (the throttle "
                         "axis is what the sweep itself varies); "
                         "warning + fallback to -m on a miss or drift")
    sw.add_argument("--tune-root", default=".",
                    help="directory holding TUNE_*.json (default: .)")
    sw.add_argument("--metrics-port", type=int, default=None,
                    metavar="PORT",
                    help="serve live OpenMetrics at http://127.0.0.1:"
                         "PORT/metrics for the duration of the sweep "
                         "(0 = ephemeral port, printed to stderr); "
                         "equivalent to TPU_AGGCOMM_METRICS_PORT; OFF "
                         "by default — no thread, no socket, no import")
    sw.add_argument("--fault", action="append", default=None,
                    metavar="SPEC",
                    help="fault scenario as an extra sweep axis "
                         "(repeatable): each occurrence reruns the whole "
                         "throttle grid under that scenario; the literal "
                         "'none' is the healthy baseline cell; recorded "
                         "in the resume sidecar")

    # tune — statistical racing search + persistent tuned-schedule cache
    tn = sub.add_parser(
        "tune", help="statistical racing search over (method, cb_nodes, "
                     "-c, -t) for one fixed shape/backend: batches of "
                     "chained differenced trials per surviving candidate; "
                     "elimination only when the seeded bootstrap CI on "
                     "the median delta vs the leader excludes zero "
                     "(obs/metrics.py — same samples in, same winner "
                     "out). Persists TUNE_*.json keyed by (shape, "
                     "direction, backend, manifest fingerprint); "
                     "--replay re-derives the verdict jax-free from the "
                     "recorded samples")
    tn.add_argument("-n", "--nprocs", type=int, default=32)
    tn.add_argument("-d", dest="data_size", type=int, default=2048)
    tn.add_argument("-p", dest="proc_node", type=int, default=1)
    tn.add_argument("--backend", choices=BACKENDS, default="jax_sim",
                    help="measured tuning rides the chained jax_sim, "
                         "pallas_fused or jax_shard (sharded-rank "
                         "tier) scaffolds; other values are only "
                         "meaningful with --synthetic")
    tn.add_argument("--methods", default="1,3",
                    help="comma-separated method ids (one direction "
                         "only; dead ids m=21/22 refused by name)")
    tn.add_argument("--cb-nodes", default="4",
                    help="comma-separated aggregator counts (-a axis)")
    tn.add_argument("--comm-sizes", default="8",
                    help="comma-separated throttle values (-c axis)")
    tn.add_argument("--agg-types", default="1",
                    help="comma-separated placement policies (-t axis)")
    tn.add_argument("--batch-trials", type=int, default=3,
                    help="chained differenced trials per candidate per "
                         "racing batch")
    tn.add_argument("--max-batches", type=int, default=6,
                    help="racing rounds before declaring the surviving "
                         "leader the winner")
    tn.add_argument("--alpha", type=float, default=0.05,
                    help="CI level for elimination (bootstrap 1-alpha)")
    tn.add_argument("--seed", type=int, default=0,
                    help="bootstrap + synthetic-sampler seed (recorded "
                         "in the artifact: verdicts are reproducible)")
    tn.add_argument("--iters-small", type=int, default=50)
    tn.add_argument("--iters-big", type=int, default=1050)
    tn.add_argument("--windows", type=int, default=1,
                    help="timing windows per trial (min taken)")
    tn.add_argument("--include-tam", action="store_true",
                    help="allow the hierarchical-engine methods "
                         "m=15/16 in the grid")
    tn.add_argument("--tune-root", default=".",
                    help="where TUNE_*.json is written/kept (default: .)")
    tn.add_argument("--synthetic", metavar="SPEC", default=None,
                    help="race a seeded synthetic latency model instead "
                         "of measuring: 'BASE_US[,mID*FACTOR]...' (e.g. "
                         "'100,m3*0.5' makes m=3 the 2x-faster oracle); "
                         "jax-free, deterministic — the artifact it "
                         "writes replays like a measured one")
    tn.add_argument("--model-prune", nargs="?", const=1.5, type=float,
                    default=None, metavar="MARGIN",
                    help="multi-fidelity prune: before racing, price "
                         "every candidate with the newest committed "
                         "PREDICT_*.json cost model (jax-free, static "
                         "features only) and drop those predicted worse "
                         "than MARGIN x the best prediction (default "
                         "1.5). Advisory-by-margin, never alone: the "
                         "survivors are still RACED on fresh samples, "
                         "candidates the model cannot price are kept, "
                         "and the whole prune (artifact, params, "
                         "predictions, margin) is recorded in "
                         "TUNE_*.json and re-derived by --replay")
    tn.add_argument("--replay", metavar="TUNE_JSON", default=None,
                    help="re-derive the elimination order and winner "
                         "from a TUNE_*.json's recorded samples (no "
                         "backend, no jax); exits nonzero unless the "
                         "re-derivation matches the stored record "
                         "byte-for-byte")
    tn.add_argument("--synth-root", metavar="DIR", default=None,
                    help="register the synthesized methods recorded in "
                         "DIR's SYNTH_r*.json before building the "
                         "candidate space, so --methods may name them "
                         "(implied with root '.' when a requested id "
                         "is > 100)")

    # synth — the schedule synthesizer (tpu_aggcomm/synth/)
    sy = sub.add_parser(
        "synth", help="schedule synthesizer (ROADMAP item 2): seeded "
                      "search over primitive compositions (fan-in "
                      "trees, multicast orders, relay staging, "
                      "throttled chunking) pruned by the model checker "
                      "and the static traffic audit, priced by the "
                      "committed cost model, then RACED measured "
                      "against every dispatched reference method of "
                      "the same direction at the same cell. Writes a "
                      "committed SYNTH_r*.json only when a synthesized "
                      "schedule wins; --replay re-derives a committed "
                      "artifact jax-free (the ci_tier1.sh gate)")
    sy.add_argument("-n", "--nprocs", type=int, default=32)
    sy.add_argument("-d", dest="data_size", type=int, default=2048)
    sy.add_argument("-p", dest="proc_node", type=int, default=1)
    sy.add_argument("-a", dest="cb_nodes", type=int, default=8,
                    help="aggregator count of the synthesis cell "
                         "(single value — one cell per artifact)")
    sy.add_argument("-c", dest="comm_size", type=int, default=4,
                    help="throttle of the synthesis cell (single value)")
    sy.add_argument("-t", dest="agg_type", type=int, default=1)
    sy.add_argument("--direction", choices=("a2m", "m2a"), default="a2m",
                    help="schedule direction (default: a2m)")
    sy.add_argument("--seed", type=int, default=0,
                    help="search-sample + race-bootstrap seed (recorded; "
                         "same config + seed = same artifact modulo "
                         "timestamps)")
    sy.add_argument("--backend", choices=("jax_sim",), default="jax_sim",
                    help="measured racing rides the chained jax_sim "
                         "scaffold (or pass --synthetic for jax-free)")
    sy.add_argument("--init", type=int, default=32,
                    help="seeded initial sample size from the "
                         "composition space (default 32)")
    sy.add_argument("--mutate-rounds", type=int, default=3,
                    help="beam-mutation rounds after the initial sample")
    sy.add_argument("--beam", type=int, default=4,
                    help="survivors whose neighborhoods each mutation "
                         "round expands")
    sy.add_argument("--top-k", type=int, default=3,
                    help="ranked finalists registered and raced "
                         "(default 3)")
    sy.add_argument("--fanins", default="2,4",
                    help="comma-separated tree fan-in axis (default 2,4)")
    sy.add_argument("--relays", default="0,2",
                    help="comma-separated relay-staging axis "
                         "(default 0,2)")
    sy.add_argument("--max-batches", type=int, default=6)
    sy.add_argument("--batch-trials", type=int, default=3)
    sy.add_argument("--alpha", type=float, default=0.05)
    sy.add_argument("--iters-small", type=int, default=50)
    sy.add_argument("--iters-big", type=int, default=1050)
    sy.add_argument("--windows", type=int, default=1)
    sy.add_argument("--predict-root", metavar="DIR", default=".",
                    help="where the newest committed PREDICT_*.json "
                         "lives: its calibration prices the survivors "
                         "(ranking prior only — the race decides; no "
                         "artifact = structural ranking, recorded)")
    sy.add_argument("--synth-root", metavar="DIR", default=".",
                    help="where committed SYNTH_r*.json artifacts live: "
                         "their ids are registered FIRST so a new run "
                         "never reuses one, and the new artifact is "
                         "written there (default: .)")
    sy.add_argument("--out", metavar="PATH", default=None,
                    help="artifact path (default: the first unused "
                         "SYNTH_rNN.json under --synth-root)")
    sy.add_argument("--id-base", type=int, default=None,
                    help="first method id for this run's finalists "
                         "(default: one past the highest registered "
                         "synthesized id)")
    sy.add_argument("--synthetic", metavar="SPEC", default=None,
                    help="race a seeded synthetic latency model instead "
                         "of measuring ('BASE_US[,mID*FACTOR]...', the "
                         "tune flag): jax-free, CPU-smoke only — the "
                         "artifact records it and replays identically")
    sy.add_argument("--replay", metavar="SYNTH_JSON", default=None,
                    help="re-derive a committed artifact jax-free: the "
                         "search block from (config, seed, embedded "
                         "params) and the race verdict from the "
                         "recorded samples; exits nonzero unless both "
                         "match byte-for-byte")

    # pilot — the online control loop (tpu_aggcomm/pilot/)
    pl = sub.add_parser(
        "pilot", help="autopilot: tail a serve journal, fold the "
                      "workload profiler's seeded proposals into "
                      "(shape, method) targets, run a synth/race "
                      "campaign per target (checker-pruned search, "
                      "seeded-bootstrap eliminations on fresh samples), "
                      "and — live, behind byte-exact --verify parity "
                      "plus a win CI excluding zero — promote the "
                      "winner into the serving cache as a NAMED, "
                      "journaled, reversible record. Writes "
                      "PILOT_r*.json; --replay re-derives the whole "
                      "decision trace jax-free (the ci_tier1.sh gate)")
    pl.add_argument("journals", nargs="*", metavar="JOURNAL",
                    help="serve journal(s) to profile (JSONL; distinct "
                         "basenames — they are recorded by name for "
                         "replay)")
    pl.add_argument("--seed", type=int, default=0,
                    help="proposal + search + race-bootstrap seed "
                         "(recorded; same streams + seed = same "
                         "artifact modulo timestamps)")
    pl.add_argument("--serve-port", type=int, default=None,
                    help="a running serve port: stats feed the fold "
                         "(per-shape latency ranks targets) and "
                         "promotions go through its framed swap op; "
                         "absent = advisory-only pass")
    pl.add_argument("--dry-run", action="store_true",
                    help="with --serve-port: read stats but never swap "
                         "(decisions become would-promote)")
    pl.add_argument("--synthetic", metavar="SPEC", default=None,
                    help="race a seeded synthetic latency model instead "
                         "of measuring ('BASE_US[,mID*FACTOR]...'): "
                         "jax-free, CPU-smoke only — recorded and "
                         "replayed identically")
    pl.add_argument("--max-batches", type=int, default=6)
    pl.add_argument("--batch-trials", type=int, default=3)
    pl.add_argument("--alpha", type=float, default=0.05)
    pl.add_argument("--n-boot", type=int, default=2000)
    pl.add_argument("--id-base", type=int, default=None,
                    help="first method id for campaign finalists "
                         "(default: one past the highest registered "
                         "synthesized id)")
    pl.add_argument("--predict-root", metavar="DIR", default=".",
                    help="where the newest committed PREDICT_*.json "
                         "lives: its calibration prices campaign "
                         "survivors (ranking prior only — the race "
                         "decides)")
    pl.add_argument("--synth-root", metavar="DIR", default=".",
                    help="committed SYNTH_r*.json ids are registered "
                         "FIRST so campaign finalists never collide "
                         "(default: .)")
    pl.add_argument("--out", metavar="PATH", default=None,
                    help="artifact path (default: the first unused "
                         "PILOT_rNN.json under --synth-root)")
    pl.add_argument("--replay", metavar="PILOT_JSON", default=None,
                    help="re-derive a committed artifact jax-free from "
                         "the journal basenames + evidence recorded "
                         "inside it; exits nonzero unless every "
                         "derivation matches byte-for-byte")

    # serve — the persistent aggregation server (tpu_aggcomm/serve/)
    sv = sub.add_parser(
        "serve", help="aggregation-as-a-service: a long-lived server "
                      "holding a compiled-chain cache (schedule_shape_key "
                      "+ backend + manifest fingerprint; drift = named "
                      "eviction + recompile) and batching same-shape "
                      "requests onto a leading request axis (vmap; rounds "
                      "stay fenced). Binds 127.0.0.1 only; prints ONE "
                      "ready JSON line with the bound port, then serves "
                      "until a shutdown request. Drive it with "
                      "scripts/serve_loadgen.py")
    sv.add_argument("--backend", default="jax_sim",
                    choices=("jax_sim", "pallas_fused"),
                    help="default chain backend for requests that do not "
                         "name one (default: jax_sim; pallas_fused "
                         "entries always execute per-request)")
    sv.add_argument("--port", type=int, default=0,
                    help="listen port (default 0 = ephemeral, read it "
                         "from the ready line)")
    sv.add_argument("--max-batch", type=int, default=8,
                    help="max same-shape requests fused onto the leading "
                         "request axis (default 8)")
    sv.add_argument("--batch-window-ms", type=float, default=5.0,
                    help="how long the executor waits for same-shape "
                         "stragglers before dispatching a batch "
                         "(default 5 ms)")
    sv.add_argument("--journal", metavar="PATH", default=None,
                    help="crash-safe per-request JSONL journal "
                         "(resilience/journal.py discipline)")
    sv.add_argument("--max-queue", type=int, default=256,
                    help="admission bound: over this many queued "
                         "requests, new ones get a framed SHED[queue-"
                         "full] response naming depth and limit "
                         "(default 256)")
    sv.add_argument("--max-conns", type=int, default=64,
                    help="bounded handler-thread pool; a connection "
                         "beyond it gets a framed SHED[connection-"
                         "limit] line (default 64)")
    sv.add_argument("--recover", metavar="JOURNAL", default=None,
                    help="replay a previous run's journal at startup: "
                         "report completed/lost requests by name and "
                         "pre-warm the compiled-chain cache from its "
                         "shape records (manifest drift = named skip)")
    sv.add_argument("--predict-root", metavar="DIR", default=".",
                    help="where to find the newest PREDICT_*.json for "
                         "the advisory deadline_floor pre-shed "
                         "(default: .)")
    sv.add_argument("--metrics-port", type=int, default=None,
                    help="opt-in OpenMetrics /metrics endpoint "
                         "(obs/export.py; 0 = ephemeral port, announced "
                         "on stderr; also via TPU_AGGCOMM_METRICS_PORT)")
    sv.add_argument("--trace", metavar="PREFIX", default=None,
                    help="flight recorder: batch spans + resilience "
                         "attempts to PREFIX.trace.jsonl")

    # inspect — print a compiled schedule's round structure
    ins = sub.add_parser(
        "inspect", help="show how a method compiles for a pattern: rounds, "
                        "edges and ppermute colors per round, bytes moved, "
                        "barriers, rendezvous mode — or, with 'inspect "
                        "trace FILE...', the merged round/rank straggler "
                        "summary of flight-recorder traces; 'inspect "
                        "compare A B [--by ...]' diffs two traces (or two "
                        "sweep-trace directories) cell-by-cell; 'inspect "
                        "report' writes a self-contained HTML dashboard "
                        "over the BENCH_r*/MULTICHIP_r* history plus any "
                        "trace files; 'inspect ledger [FILE...]' prints "
                        "the run-ledger manifests of bench artifacts / "
                        "traces and flags environment drift between "
                        "consecutive ones")
    ins.add_argument("what", nargs="?", choices=["trace", "compare",
                                                 "report", "ledger",
                                                 "traffic", "check",
                                                 "live", "history",
                                                 "explain", "workload",
                                                 "watch", "flow"],
                     default=None,
                     help="'trace' to summarize *.trace.jsonl files, "
                          "'compare' to diff two of them, 'report' for "
                          "the HTML dashboard, 'ledger' for run-ledger "
                          "manifests + environment drift, 'traffic' for "
                          "the static communication-matrix / incast / "
                          "throttle-conformance audit (-m 0 sweeps every "
                          "method as a pass/fail gate), 'check' for the "
                          "schedule model checker (analysis/check.py, "
                          "jax-free): deadlock-freedom, recv-slot "
                          "race-freedom, byte conservation, barrier "
                          "symmetry, round monotonicity — PROVEN or "
                          "REFUTED with a named witness (-m 0 sweeps "
                          "every method as a gate), 'live' to attach "
                          "to a running sweep from another terminal "
                          "(tails the crash-safe journal + trace JSONL, "
                          "jax-free), 'history' for the longitudinal "
                          "artifact index + seeded multi-round trend "
                          "gate, 'explain' for the analytic cost model "
                          "(tpu_aggcomm/model/, jax-free): "
                          "predicted-vs-measured round walls with NAMED "
                          "divergence verdicts over flight-recorder "
                          "traces — instead of a compiled schedule, "
                          "'workload' for the serve-journal workload "
                          "profiler (obs/workload.py, jax-free): "
                          "per-request phase attribution, shape mix, "
                          "arrival process, batch efficiency, advisory "
                          "hot-shape/skew proposals, 'watch' for the "
                          "streaming SLO watchtower (obs/watch.py, "
                          "jax-free): error-budget burn rates over the "
                          "serve journal, seeded changepoint anomalies "
                          "over request + round walls, NAMED root-cause "
                          "verdicts joined from ledger/resilience/shed/"
                          "explain evidence, 'flow' for the end-to-end "
                          "causal joiner (obs/flow.py, jax-free): "
                          "CLIENT.journal SERVE.journal [TRACE...] — "
                          "client walls decomposed as wire + server "
                          "phases + device rounds + quantified residual "
                          "with NAMED dominant-component verdicts and "
                          "the warm overhead ledger")
    ins.add_argument("trace_file", nargs="*", default=[],
                     help="trace files: one or more to summarize "
                          "('trace'), exactly two files or directories to "
                          "diff ('compare'), zero or more to embed in the "
                          "dashboard ('report'); for 'ledger': "
                          "BENCH_r*.json and/or *.trace.jsonl artifacts "
                          "(default: every BENCH_r*.json under "
                          "--history-root); for 'workload': one or more "
                          "serve journals (*.journal.jsonl); for "
                          "'watch': serve journals plus optional "
                          "*.trace.jsonl (split by suffix)")
    ins.add_argument("--by", choices=["rank", "round", "phase"],
                     default="rank",
                     help="compare grouping key (default: rank)")
    ins.add_argument("--across-faults", action="store_true",
                     help="'compare' only: allow diffing traces whose "
                          "fault specs differ (healthy vs "
                          "faulted+repaired); the delta is reported as a "
                          "RECOVERY delta naming both specs")
    ins.add_argument("--fault", metavar="SPEC", default=None,
                     help="'traffic'/'check' only: audit or model-check "
                          "the FAULT-REPAIRED schedule (faults/repair.py) "
                          "instead of the healthy one — the static "
                          "re-proof that the relay detour still honors "
                          "the -c bound / stays deadlock-free; 'check' "
                          "-m 0 sweeps every repairable method under the "
                          "spec (repair refusals are SKIPPED, not failed)")
    ins.add_argument("--fused-export", action="store_true",
                     help="'traffic'/'check' only: also cross-check the "
                          "pallas_fused step export (native/fuse.py, "
                          "jax-free) against the op-program accounting — "
                          "per-round src->dst byte matrices and fence "
                          "structure must be identical (DRIFT fails; "
                          "unfusable schedules are SKIPPED by design); "
                          "-m 0 sweeps every method")
    ins.add_argument("--out", default="report.html",
                     help="output path for 'inspect report' "
                          "(default: report.html)")
    ins.add_argument("--history-root", default=".",
                     help="directory holding BENCH_r*/MULTICHIP_r*.json "
                          "for 'inspect report' (default: .)")
    ins.add_argument("-n", "--nprocs", type=int, default=32)
    ins.add_argument("-m", dest="method", type=int, default=None)
    ins.add_argument("-a", dest="cb_nodes", type=int, default=1)
    ins.add_argument("-d", dest="data_size", type=int, default=2048)
    ins.add_argument("-c", dest="comm_size", type=int, default=200_000_000)
    ins.add_argument("-p", dest="proc_node", type=int, default=1)
    ins.add_argument("-t", dest="agg_type", type=int, default=1)
    ins.add_argument("-b", dest="barrier_type", type=int, default=0)
    ins.add_argument("--ndev", type=int, default=0,
                     help="also show the jax_shard block-table view over "
                          "this many devices (block M, padding factor)")
    ins.add_argument("--roofline", action="store_true",
                     help="bytes-touched model per rep + HBM floors "
                          "(harness/roofline.py): the time the measured "
                          "numbers are judged against (RESULTS_TPU.md)")
    ins.add_argument("--waves", action="store_true",
                     help="pallas_dma wave accounting, lockstep vs "
                          "concurrent: in-flight DMAs per wave — where "
                          "the -c throttle becomes physical concurrency")
    ins.add_argument("--trace", metavar="FILE", default=None,
                     help="'traffic' only: join the static matrix with "
                          "this flight-recorder trace's round walls — "
                          "per-round effective bytes/s, fraction of the "
                          "HBM roofline, incast-vs-straggler correlation")
    ins.add_argument("--json", metavar="PATH", default=None,
                     help="'traffic': also write the audit as a "
                          "traffic-v1 JSON artifact (TRAFFIC_*.json is "
                          "schema-checked by scripts/check_bench_schema."
                          "py); 'check': write the check-v1 report; "
                          "'history': also write the longitudinal "
                          "history-v1 index (atomic_write); 'explain': "
                          "write the calibrated predict-v1 artifact "
                          "(PREDICT_*.json); 'compare': write the "
                          "machine-readable compare-v1 delta; "
                          "'workload': write the workload-v1 profile "
                          "(WORKLOAD_*.json); 'flow': write the flow-v1 "
                          "decomposition (FLOW_*.json)")
    ins.add_argument("--replay", metavar="ARTIFACT_JSON", default=None,
                     help="'explain': re-derive the committed "
                          "predict-v1 artifact from its recorded inputs "
                          "+ seed and byte-compare (REPRODUCED or "
                          "MISMATCH naming the divergent keys — the "
                          "same contract as tune --replay; ci_tier1.sh "
                          "gates every committed PREDICT_*.json); "
                          "'workload': re-derive WORKLOAD_r*.json from "
                          "the journals recorded next to it (same "
                          "contract; ci_tier1.sh gates the committed "
                          "exemplar); 'watch': re-derive WATCH_r*.json "
                          "from the streams + embedded SLO spec + seed "
                          "recorded inside it (same contract; "
                          "ci_tier1.sh gates the committed exemplar); "
                          "'flow': re-derive FLOW_r*.json from the "
                          "client journal + serve journal + trace "
                          "basenames recorded inside it (same contract; "
                          "ci_tier1.sh gates every committed artifact)")
    ins.add_argument("--seed", type=int, default=0,
                     help="'workload'/'watch'/'flow': seed recorded in "
                          "the artifact and used by the advisory "
                          "detector / changepoint / warm-overhead "
                          "bootstrap (default: 0)")
    ins.add_argument("--slo", metavar="FILE", default=None,
                     help="'watch' only: slo-v1 spec file (objectives + "
                          "windows); default: the built-in lenient spec "
                          "(obs/slo.DEFAULT_SLO), embedded verbatim in "
                          "the artifact either way")
    ins.add_argument("--flow", metavar="FLOW_rNN.json", default=None,
                     help="'watch' only: join this committed flow "
                          "artifact's per-request dominant verdicts as "
                          "the 'flow' evidence stream — a request-wall "
                          "step coinciding with a dominant-component "
                          "shift (e.g. round-bound -> compile-bound) "
                          "attributes by name instead of UNEXPLAINED")
    ins.add_argument("--results-csv", default="results.csv",
                     help="'live' only: the running sweep's results CSV "
                          "— its crash-safe journal "
                          "(<csv>.journal.jsonl) is what gets tailed "
                          "(default: results.csv)")
    ins.add_argument("--follow", action="store_true",
                     help="'live' only: keep refreshing every --interval "
                          "seconds until the grid completes (Ctrl-C to "
                          "detach; read-only either way)")
    ins.add_argument("--interval", type=float, default=2.0,
                     help="'live' --follow refresh period in seconds "
                          "(default: 2)")
    ins.add_argument("--comm-sizes", type=str, default=None,
                     help="'live' only: the --comm-sizes grid the sweep "
                          "was launched with, so remaining-cell ETA "
                          "counts the right cells (default: the Theta "
                          "grid)")
    ins.add_argument("--synth-root", metavar="DIR", default=None,
                     help="'traffic'/'check': register the synthesized "
                          "methods recorded in DIR's SYNTH_r*.json "
                          "first, so -m may name one and the -m 0 "
                          "sweeps include them (implied with root '.' "
                          "when -m > 100); without it, output is "
                          "byte-identical to a synth-less build")

    # analyze — summarize accumulated results.csv rows
    an = sub.add_parser(
        "analyze", help="summarize results.csv: per (method, config) the "
                        "best max-total-time and the throttle that won")
    an.add_argument("--results-csv", default="results.csv")
    return ap


THETA_COMM_SIZES = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048,
                    4096, 8192, 999_999_999)  # script_theta_*.sh:33-106


def _tracing(prefix):
    """Context manager enabling the flight recorder for one CLI run and
    flushing ``<prefix>.trace.{jsonl,json}`` on exit. ``prefix=None``
    (tracing off) is a no-op."""
    import contextlib

    @contextlib.contextmanager
    def cm():
        if not prefix:
            yield
            return
        from tpu_aggcomm.obs import trace
        trace.enable()
        try:
            yield
        finally:
            paths = trace.flush(prefix)
            trace.disable()
            if paths:
                print(f"trace written: {paths[0]} (events), "
                      f"{paths[1]} (Perfetto)")

    return cm()


def _run_tam(args) -> int:
    """The DEBUG-driver flow (lustre_driver_test.c:1417-1509):
    static_node_assignment -> initialize_setting -> aggregator_meta_information
    -> engine -> test_correctness."""
    import time

    from tpu_aggcomm.core.meta import aggregator_meta_information
    from tpu_aggcomm.core.topology import static_node_assignment
    from tpu_aggcomm.core.workload import StripeType, initialize_setting
    from tpu_aggcomm.tam.workload_engines import (cw2_local_agg_jax,
                                                  run_workload_engine)

    na = static_node_assignment(args.nprocs, args.proc_node,
                                args.rank_assignment)
    wl = initialize_setting(na, args.blocklen, StripeType(args.stripe))
    # the reference's rank-0 config banner — FIRST line, byte-identical
    # to the DEBUG driver's printf (l_d_t.c:1454)
    print(f"blocklen = {args.blocklen}, nprocs_node = {args.proc_node}, "
          f"rank_assignment = {args.rank_assignment}, type = {args.stripe}, "
          f"co = {args.co}")
    if getattr(args, "reorder", False):
        # reorder_ranklist before the engine (the reference driver's
        # commented-out call site, l_d_t.c:1495-1499): same destination
        # SET, node-interleaved ORDER — engines must handle an unsorted
        # destination list; the round-robin deal is what the reference's
        # I/O phase would use to balance file domains across nodes
        from dataclasses import replace as _replace

        from tpu_aggcomm.core.pattern import reorder_ranklist
        new_order = reorder_ranklist(na.node_of, wl.aggregators, na.nnodes)
        wl = _replace(wl, aggregators=new_order)
        print(f"| reordered aggregators = "
              f"{', '.join(str(int(r)) for r in new_order)}")
    meta = aggregator_meta_information(na, wl.aggregators, args.co, args.mode)
    print(f"| nprocs = {args.nprocs}, nodes = {na.nnodes}, "
          f"aggregators = {len(wl.aggregators)}, "
          f"local aggregators = {len(meta.local_aggregators)}, "
          f"total bytes = {wl.total_bytes}")

    if args.engine == "jax":
        import jax
        recv, times = cw2_local_agg_jax(wl, na, meta, jax.devices(),
                                        ntimes=args.ntimes)
        wl.verify_all(recv)
        print(f"| engine = two-level mesh (compiled), reps = {len(times)}, "
              f"min rep = {min(times):.6f} s")
    elif args.engine == "shared_jax":
        import jax

        from tpu_aggcomm.tam.workload_engines import cw3_shared_jax
        recv, times = cw3_shared_jax(wl, na, meta, jax.devices(),
                                     ntimes=args.ntimes)
        wl.verify_all(recv)
        print(f"| engine = shared-window mesh (compiled, in-slice "
              f"all_gather staging), reps = {len(times)}, "
              f"min rep = {min(times):.6f} s")
    elif args.engine == "native3":
        from tpu_aggcomm.backends.native import run_workload_cw3
        recv, times = run_workload_cw3(wl, na, meta, ntimes=args.ntimes)
        wl.verify_all(recv)
        print(f"| engine = native shared-window (C++ threads), "
              f"reps = {len(times)}, min rep = {min(times):.6f} s")
    elif args.engine == "sim":
        from tpu_aggcomm.tam.workload_engines import cw_proxy_sim
        recv, times = cw_proxy_sim(wl, na, ntimes=args.ntimes,
                                   chained=args.chained)
        wl.verify_all(recv)
        kind = "chained differenced" if args.chained else "per-dispatch"
        print(f"| engine = single-chip proxy route (compiled, {kind}), "
              f"reps = {len(times)}, min rep = {min(times):.6f} s")
    elif args.engine == "native":
        from tpu_aggcomm.backends.native import run_workload_proxy
        recv, times = run_workload_proxy(wl, na, ntimes=args.ntimes)
        wl.verify_all(recv)
        print(f"| engine = native proxy (C++ threads), reps = {len(times)}, "
              f"min rep = {min(times):.6f} s")
    elif args.engine == "native2":
        from tpu_aggcomm.backends.native import run_workload_cw2
        recv, times = run_workload_cw2(wl, meta, ntimes=args.ntimes)
        wl.verify_all(recv)
        print(f"| engine = native two-level (C++ threads), "
              f"reps = {len(times)}, min rep = {min(times):.6f} s")
    else:
        times = []
        stats = None
        for _ in range(max(args.ntimes, 1)):
            t0 = time.perf_counter()
            recv, stats = run_workload_engine(args.engine, wl, na, meta)
            times.append(time.perf_counter() - t0)
        wl.verify_all(recv)
        print(f"| engine = {args.engine}, reps = {len(times)}, "
              f"min rep = {min(times):.6f} s")
        print(f"| route bytes: gather = {stats.gather_bytes}, "
              f"exchange intra/inter = {stats.exchange_intra_bytes}/"
              f"{stats.exchange_inter_bytes}, "
              f"delivery = {stats.delivery_bytes}, "
              f"direct = {stats.direct_bytes}, staged = {stats.staged_bytes}")
    print("| correctness: PASSED")
    return 0


def _default_nprocs(backend: str) -> int:
    """Rank count when -n is omitted: the reference README example's 32 for
    backends that do not need one device per rank, the visible device count
    otherwise."""
    if (backend in DEVICE_FREE_BACKENDS
            or backend in SINGLE_DEVICE_BACKENDS
            or backend in SHARDED_RANK_BACKENDS):
        return 32
    import jax
    return len(jax.devices())


def _sweep_sidecar(csv_path: str) -> str:
    return csv_path + ".sweep.jsonl"


def _sweep_journal(csv_path: str) -> str:
    """The resilience run journal riding next to the legacy sidecar."""
    return csv_path + ".journal.jsonl"


def _sweep_key(nprocs, cb_nodes, data_size, method, iters, ntimes, agg_type,
               proc_node, backend, chained, measured_phases=False,
               fault=None) -> dict:
    key = {"nprocs": nprocs, "cb_nodes": cb_nodes, "data_size": data_size,
           "method": method, "iters": iters, "ntimes": ntimes,
           "agg_type": agg_type, "proc_node": proc_node,
           "backend": backend, "chained": bool(chained)}
    if measured_phases:
        # only stamped when set: older sidecar records (no key) keep
        # matching their non-measured sweeps exactly
        key["measured_phases"] = True
    if fault:
        # same back-compat rule: healthy cells never stamp the key
        key["fault"] = fault
    return key


def _completed_throttles(csv_path: str, nprocs: int, cb_nodes: int,
                         data_size: int, method: int, iters: int,
                         ntimes: int, agg_type: int, proc_node: int = 1,
                         backend: str = "local",
                         chained: bool = False,
                         measured_phases: bool = False,
                         fault: str | None = None) -> set:
    """Throttle values already fully recorded for this sweep config.

    Primary source: the sweep sidecar (``<results_csv>.sweep.jsonl``, one
    JSON line per completed throttle carrying the FULL run config —
    including proc_node, backend, chained and measured_phases, which the
    reference CSV format cannot record; ADVICE r1). When the sidecar
    exists, only its exact-config matches count. Fallback for pre-sidecar
    CSVs: every required method name has >= iters rows at that comm size
    matching the parameters the reference CSV does carry (nprocs,
    cb_nodes, data_size, ntimes, agg_type) — rows from a sweep differing
    only in proc_node, backend, chained, or measured_phases are
    indistinguishable there, which is exactly why the sidecar is
    written."""
    import csv
    import json
    import os
    from collections import Counter

    from tpu_aggcomm.core.methods import METHODS, method_ids

    ids = method_ids() if method == 0 else [method]
    unknown = [m for m in ids if m not in METHODS]
    if unknown:
        raise SystemExit(f"unknown method id {unknown[0]}; valid ids: "
                         f"{sorted(METHODS)}")

    sidecar = _sweep_sidecar(csv_path)
    if os.path.exists(sidecar):
        key = _sweep_key(nprocs, cb_nodes, data_size, method, iters, ntimes,
                         agg_type, proc_node, backend, chained,
                         measured_phases, fault)
        family = (nprocs, cb_nodes, data_size, ntimes, agg_type)
        family_seen = False
        done = set()
        with open(sidecar) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                comm = rec.pop("comm", None)
                if comm is None:
                    continue
                try:
                    rec_family = (rec["nprocs"], rec["cb_nodes"],
                                  rec["data_size"], rec["ntimes"],
                                  rec["agg_type"])
                except KeyError:
                    continue
                family_seen = family_seen or rec_family == family
                if rec == key:
                    done.add(int(comm))
        # the sidecar is authoritative only for configs it has seen: a
        # sweep recorded before the sidecar existed lives only in the CSV,
        # and another config's sidecar lines must not erase it — fall
        # through to the CSV heuristic in that case
        if family_seen:
            return done

    if fault:
        # the reference CSV format cannot record a fault spec — healthy
        # rows must never be credited to a faulted sweep
        return set()
    names = {METHODS[m].name for m in ids}
    try:
        with open(csv_path, newline="") as f:
            rows = list(csv.DictReader(f))
    except FileNotFoundError:
        return set()
    cfg = (nprocs, cb_nodes, data_size, ntimes, agg_type)
    cnt: Counter = Counter()
    comms = set()
    for r in rows:
        try:
            row_cfg = (int(r["# of processes"]), int(r["# of aggregators"]),
                       int(r["data size"]), int(r["ntimes"]),
                       int(r["aggregator type"]))
            name, comm = r["Method"], int(r["max comm"])
        except (KeyError, ValueError, TypeError):
            continue
        if row_cfg == cfg:
            cnt[(name, comm)] += 1
            comms.add(comm)
    return {c for c in comms if all(cnt[(n, c)] >= iters for n in names)}


def _run_sweep(args) -> int:
    """One experiment per throttle value over the Theta grid; rows
    accumulate in results.csv exactly like repeated ./test invocations."""
    from tpu_aggcomm.harness.runner import ExperimentConfig, run_experiment

    nprocs = args.nprocs if args.nprocs is not None \
        else _default_nprocs(args.backend)
    if getattr(args, "auto", False):
        _resolve_auto(args, nprocs, sweep=True)
    if args.comm_sizes:
        grid = [int(x) for x in args.comm_sizes.split(",") if x.strip()]
        if not grid:
            raise SystemExit("--comm-sizes: no valid throttle values")
    else:
        grid = list(THETA_COMM_SIZES)
    faults: list = [None]
    if getattr(args, "fault", None):
        from tpu_aggcomm.faults import FaultSpecError, parse_fault
        faults = []
        for fs in args.fault:
            if fs.strip().lower() in ("", "none", "healthy"):
                faults.append(None)
                continue
            try:
                spec = parse_fault(fs)
            except FaultSpecError as e:
                raise SystemExit(f"sweep --fault: {e}")
            faults.append(None if spec.empty else spec.canonical())
    if args.measured_phases and any(faults):
        raise SystemExit("sweep: --measured-phases is not supported with "
                         "--fault (round-prefix truncation would replay "
                         "the injected delay once per prefix); use "
                         "--chained for faulted cells")
    if args.measured_phases:
        # validate the WHOLE grid's round depth before any cell runs — a
        # mid-grid ValueError after earlier cells recorded rows is the
        # partial-CSV failure the upfront guards exist to prevent
        from tpu_aggcomm.core.methods import METHODS, compile_method
        from tpu_aggcomm.core.pattern import AggregatorPattern
        from tpu_aggcomm.harness.chained import MAX_MEASURED_ROUNDS
        ids = ([args.method] if args.method else
               [m for m in METHODS if METHODS[m].dispatched])
        for c in grid:
            for m in ids:
                if METHODS[m].tam:
                    continue
                sched = compile_method(m, AggregatorPattern(
                    nprocs=nprocs, cb_nodes=args.cb_nodes,
                    data_size=max(args.data_size, 1),
                    proc_node=args.proc_node, comm_size=c,
                    placement=args.agg_type))
                if sched.collective:
                    continue
                depth = len({int(e[4]) for e in sched.data_edges()})
                if depth > MAX_MEASURED_ROUNDS:
                    raise SystemExit(
                        f"--measured-phases: grid cell c={c} method {m} "
                        f"has {depth} throttle rounds (> "
                        f"{MAX_MEASURED_ROUNDS}); trim --comm-sizes or "
                        f"use --chained for the deep cells")
    import json
    import os
    import sys
    import time

    from tpu_aggcomm.faults import FaultSpecError, RepairError
    from tpu_aggcomm.obs import ledger
    from tpu_aggcomm.resilience import (CancelledAtBoundary, RunJournal,
                                        safe_cancellation)

    def cell_key(fs, c) -> dict:
        key = _sweep_key(nprocs, args.cb_nodes, args.data_size,
                         args.method, args.iters, args.ntimes,
                         args.agg_type, args.proc_node, args.backend,
                         args.chained, args.measured_phases, fs)
        key["comm"] = c
        return key

    # crash-safe run journal (resilience/journal.py) next to the legacy
    # sweep sidecar: entries carry the manifest fingerprint, so --resume
    # re-runs (and NAMES the drift) after an environment change — the
    # tune-cache semantics applied to sweep cells
    journal = fp = man = None
    if args.results_csv:
        journal = RunJournal(_sweep_journal(args.results_csv))
        man = ledger.manifest()
        fp = journal.begin_session(man)
    # live OpenMetrics endpoint (obs/export.py) — OFF by default: the
    # import itself sits behind the flag/env gate, so a plain sweep
    # never loads the telemetry code (zero-cost obs invariant). State
    # the hot path touches is one `is not None` check per cell.
    metrics_server = None
    metrics_state = None
    if getattr(args, "metrics_port", None) is not None \
            or os.environ.get("TPU_AGGCOMM_METRICS_PORT", "").strip():
        from tpu_aggcomm.obs import export
        from tpu_aggcomm.obs import trace as obstrace
        metrics_state = {"done": 0, "fail": 0, "walls": []}

        def _metrics_text(state=metrics_state):
            # built fresh per scrape: sweep progress + cell-wall
            # histogram from the supervisor state, everything latency-
            # shaped from the attribution cell stream when tracing is on
            reg = export.MetricsRegistry()
            reg.counter(f"{export.PREFIX}_sweep_cells", state["done"],
                        status="done")
            reg.counter(f"{export.PREFIX}_sweep_cells", state["fail"],
                        status="fail")
            for w in state["walls"]:
                reg.observe(f"{export.PREFIX}_sweep_cell_wall_seconds", w)
            if obstrace.enabled():
                export.trace_registry(list(obstrace.current().events),
                                      reg)
            return reg.render()

        metrics_server = export.serve_from_env(
            _metrics_text, port=getattr(args, "metrics_port", None))
        if metrics_server is not None:
            print(f"# metrics endpoint: {metrics_server.url}",
                  file=sys.stderr, flush=True)
    try:
        with _tracing(getattr(args, "trace", None)), safe_cancellation():
            for fs in faults:
                cells = grid
                if args.resume:
                    done = _completed_throttles(
                        args.results_csv, nprocs, args.cb_nodes,
                        args.data_size, args.method, args.iters,
                        args.ntimes, args.agg_type, args.proc_node,
                        args.backend, args.chained, args.measured_phases,
                        fs)
                    skipped, todo, drift_msgs = [], [], []
                    for c in cells:
                        # the journal is authoritative for cells it has
                        # seen (fingerprint-checked); legacy sidecar/CSV
                        # completion covers pre-journal sweeps unchanged
                        if journal is not None \
                                and journal.seen(cell_key(fs, c)):
                            ok, reason = journal.completed(
                                cell_key(fs, c), fingerprint=fp,
                                manifest=man)
                            (skipped if ok else todo).append(c)
                            if reason:
                                drift_msgs.append(
                                    f"resume: comm size {c}: {reason}")
                        elif c in done:
                            skipped.append(c)
                        else:
                            todo.append(c)
                    cells = todo
                    if skipped:
                        tag = f" [fault {fs}]" if fs else ""
                        print(f"resume: skipping already-recorded comm "
                              f"sizes {skipped}{tag}")
                    for msg in drift_msgs:
                        print(msg)
                for c in cells:
                    ftag = f" --fault {fs}" if fs else ""
                    print(f"RUN_OPTS: -a {args.cb_nodes} "
                          f"-d {args.data_size} -c {c} -m {args.method} "
                          f"-i {args.iters}{ftag}")
                    cfg = ExperimentConfig(
                        nprocs=nprocs, cb_nodes=args.cb_nodes,
                        method=args.method, data_size=args.data_size,
                        comm_size=c, iters=args.iters, ntimes=args.ntimes,
                        proc_node=args.proc_node, agg_type=args.agg_type,
                        backend=args.backend, verify=args.verify,
                        results_csv=args.results_csv, chained=args.chained,
                        measured_phases=args.measured_phases, fault=fs)
                    t_cell = time.perf_counter()
                    try:
                        records = run_experiment(cfg)
                    except (FaultSpecError, RepairError) as e:
                        raise SystemExit(f"sweep --fault: {e}")
                    if args.results_csv:
                        # checkpoint: record the completed throttle with
                        # its FULL config
                        rec = cell_key(fs, c)
                        with open(_sweep_sidecar(args.results_csv),
                                  "a") as f:
                            f.write(json.dumps(rec) + "\n")
                        journal.record(
                            cell_key(fs, c), fingerprint=fp,
                            status="done",
                            shape_keys=sorted({r["shape_key"]
                                               for r in records}),
                            artifacts=[args.results_csv],
                            wall_s=time.perf_counter() - t_cell)
                    if metrics_state is not None:
                        metrics_state["done"] += 1
                        metrics_state["walls"].append(
                            time.perf_counter() - t_cell)
    except CancelledAtBoundary as e:
        print(f"sweep: {e}", file=sys.stderr)
        return 130
    finally:
        if metrics_server is not None:
            metrics_server.close()
    return 0


def _ints(csv_text: str) -> list[int]:
    try:
        vals = [int(x) for x in str(csv_text).split(",") if x.strip()]
    except ValueError:
        raise SystemExit(f"tune: not a comma-separated int list: "
                         f"{csv_text!r}")
    if not vals:
        raise SystemExit(f"tune: empty axis value {csv_text!r}")
    return vals


def _ensure_synth(args, methods=()) -> None:
    """Register the synthesized methods committed under ``--synth-root``
    before any METHODS lookup: explicitly when the flag was passed,
    implicitly (root '.') when a requested id falls in the reserved
    range. Without either, nothing is imported and every command's
    output stays byte-identical to a synth-less build."""
    root = getattr(args, "synth_root", None)
    if root is None and not any(m is not None and m > 100 for m in methods):
        return
    from tpu_aggcomm.synth import ensure_registered
    ensure_registered(root or ".")


def _model_prune(args, cands):
    """The ``tune --model-prune`` block: price every candidate with the
    newest committed PREDICT_*.json and split the grid into kept/pruned
    at ``margin x best``. Returns the JSON-able record ``{"artifact",
    "platform", "params", "margin", "predictions", "best", "pruned",
    "kept"}`` (recorded verbatim in TUNE_*.json so ``--replay`` can
    re-derive the split), or None with a stderr warning when no usable
    artifact exists — a missing model must degrade to the full race,
    never block tuning."""
    import os

    from tpu_aggcomm.model.artifact import load_artifact
    from tpu_aggcomm.model.predict import (newest_predict_path,
                                           predict_candidates)
    from tpu_aggcomm.obs.ledger import manifest

    margin = float(args.model_prune)
    if margin < 1.0:
        raise SystemExit(f"tune --model-prune: margin must be >= 1.0 "
                         f"(got {margin:g}) — a margin below 1 would "
                         f"prune the predicted best itself")
    path = newest_predict_path(args.tune_root)
    if path is None and os.path.abspath(args.tune_root) \
            != os.path.abspath("."):
        path = newest_predict_path(".")
    if path is None:
        print("tune --model-prune: no committed PREDICT_*.json found — "
              "racing the full space", file=sys.stderr)
        return None
    try:
        art = load_artifact(path)
    except (OSError, ValueError) as e:
        print(f"tune --model-prune: unreadable {path}: {e} — racing "
              f"the full space", file=sys.stderr)
        return None
    env = (manifest().get("env") or {})
    platform = "tpu" if env.get("tunnel_armed") \
        and env.get("jax_platforms") != "cpu" else "cpu"
    block = (art.get("platforms") or {}).get(platform)
    if not block:
        print(f"tune --model-prune: {os.path.basename(path)} has no "
              f"{platform!r} calibration — racing the full space",
              file=sys.stderr)
        return None
    preds = predict_candidates(cands, block["params"],
                               nprocs=args.nprocs,
                               data_size=args.data_size,
                               proc_node=args.proc_node)
    priced = {cid: s for cid, s in preds.items() if s is not None}
    if not priced:
        print(f"tune --model-prune: no candidate is priceable by the "
              f"model — racing the full space", file=sys.stderr)
        return None
    best = min(priced, key=lambda cid: (priced[cid], cid))
    cut = priced[best] * margin
    pruned = sorted(cid for cid, s in priced.items() if s > cut)
    kept = [c.cid for c in cands if c.cid not in set(pruned)]
    return {"artifact": os.path.basename(path), "platform": platform,
            "params": dict(block["params"]), "margin": margin,
            "predictions": preds, "best": best,
            "pruned": pruned, "kept": kept}


def _run_tune(args) -> int:
    """The autotuner: racing search (measured or synthetic) persisting a
    TUNE_*.json, or --replay re-deriving a stored verdict jax-free."""
    import json
    import os

    from tpu_aggcomm.tune import cache
    from tpu_aggcomm.tune import race as race_mod
    from tpu_aggcomm.tune import space as space_mod

    if args.replay:
        from tpu_aggcomm.obs.regress import validate_tune
        try:
            entry = cache.load_tune(args.replay)
        except (OSError, ValueError) as e:
            raise SystemExit(f"tune --replay: cannot read "
                             f"{args.replay}: {e}")
        errors = validate_tune(entry, os.path.basename(args.replay))
        if errors:
            for e in errors:
                print(f"FAIL {e}", file=sys.stderr)
            raise SystemExit(f"tune --replay: {args.replay} failed "
                             f"schema validation ({len(errors)} "
                             f"error(s))")
        rec = entry["race"]
        try:
            res = race_mod.replay_record(rec)
        except race_mod.RaceError as e:
            raise SystemExit(f"tune --replay: {e}")
        # byte-for-byte: the derived eliminations (every field, CI
        # bounds included — floats round-trip JSON exactly) and winner
        # must equal the stored record, or the artifact is inconsistent
        # with its own samples
        same = (res.winner == rec.get("winner")
                and json.loads(json.dumps(res.eliminations))
                == rec.get("eliminations"))
        mp = entry.get("model_prune")
        if mp is not None:
            # re-derive the --model-prune split from the recorded
            # predictions + margin alone (no model import, no PREDICT
            # artifact): same cut rule as cli._model_prune, and the
            # raced order must be exactly the kept list
            priced = {cid: s for cid, s in mp["predictions"].items()
                      if s is not None}
            best = min(priced, key=lambda cid: (priced[cid], cid))
            cut = priced[best] * float(mp["margin"])
            pruned = sorted(cid for cid, s in priced.items() if s > cut)
            mp_same = (best == mp.get("best")
                       and pruned == mp.get("pruned")
                       and rec.get("order") == mp.get("kept"))
            print(f"  model-prune: {len(pruned)} pruned by "
                  f"{mp.get('artifact')} [{mp.get('platform')}] at "
                  f"margin {mp.get('margin'):g} -> "
                  f"{'REPRODUCED' if mp_same else 'MISMATCH vs stored record'}")
            same = same and mp_same
        print(f"replay {os.path.basename(args.replay)}: winner "
              f"{res.winner} after {len(res.eliminations)} "
              f"elimination(s) over {res.batches_run} batch(es) -> "
              f"{'REPRODUCED' if same else 'MISMATCH vs stored record'}")
        for e in res.eliminations:
            print(f"  batch {e['batch']}: {e['candidate']} out vs "
                  f"leader {e['leader']} "
                  f"(CI [{e['ci_pct'][0]:+.1f}%, {e['ci_pct'][1]:+.1f}%])")
        return 0 if same else 1

    methods = _ints(args.methods)
    cbs = _ints(args.cb_nodes)
    comms = _ints(args.comm_sizes)
    aggs = _ints(args.agg_types)
    _ensure_synth(args, methods)
    try:
        cands = space_mod.build_space(methods, cbs, comms, aggs,
                                      nprocs=args.nprocs,
                                      include_tam=args.include_tam)
    except space_mod.SpaceError as e:
        raise SystemExit(f"tune: {e}")
    cids = [c.cid for c in cands]

    # --model-prune: multi-fidelity gate — price the grid with the
    # committed cost model (static features, jax-free) and skip racing
    # candidates predicted hopeless by a wide margin. The model never
    # decides alone: survivors are raced on fresh samples, unpriceable
    # candidates are kept, and the full prune is recorded so --replay
    # re-derives it from the artifact with no model import.
    prune_rec = None
    if args.model_prune is not None:
        prune_rec = _model_prune(args, cands)
        if prune_rec is not None and prune_rec["pruned"]:
            kept = set(prune_rec["kept"])
            cands = [c for c in cands if c.cid in kept]
            cids = [c.cid for c in cands]
            print(f"tune --model-prune: {len(prune_rec['pruned'])} "
                  f"candidate(s) predicted worse than "
                  f"{prune_rec['margin']:g}x the best "
                  f"({prune_rec['best']}) by {prune_rec['artifact']} "
                  f"[{prune_rec['platform']}] — racing "
                  f"{len(cids)} survivor(s)")

    if args.synthetic:
        try:
            sampler = race_mod.make_synthetic_sampler(
                args.synthetic, batch_trials=args.batch_trials,
                seed=args.seed)
        except race_mod.RaceError as e:
            raise SystemExit(f"tune --synthetic: {e}")
    else:
        if args.backend not in SINGLE_DEVICE_BACKENDS \
                and args.backend != "jax_shard":
            raise SystemExit(
                f"tune: measured tuning rides the chained single-device "
                f"scaffold (got --backend {args.backend}); pass "
                f"--backend jax_sim, pallas_fused or jax_shard, or "
                f"--synthetic SPEC for a backend-free run")
        if args.backend == "jax_shard":
            # the 16,384-rank-class tier: same chained differenced
            # discipline, rank axis sharded over the device mesh
            from tpu_aggcomm.tune.measure import make_jax_shard_sampler
            sampler = make_jax_shard_sampler(
                nprocs=args.nprocs, data_size=args.data_size,
                proc_node=args.proc_node, iters_small=args.iters_small,
                iters_big=args.iters_big, batch_trials=args.batch_trials,
                windows=args.windows)
        elif args.backend == "pallas_fused":
            from tpu_aggcomm.tune.measure import make_pallas_fused_sampler
            sampler = make_pallas_fused_sampler(
                nprocs=args.nprocs, data_size=args.data_size,
                proc_node=args.proc_node, iters_small=args.iters_small,
                iters_big=args.iters_big, batch_trials=args.batch_trials,
                windows=args.windows)
        else:
            from tpu_aggcomm.tune.measure import make_jax_sim_sampler
            sampler = make_jax_sim_sampler(
                nprocs=args.nprocs, data_size=args.data_size,
                proc_node=args.proc_node, iters_small=args.iters_small,
                iters_big=args.iters_big, batch_trials=args.batch_trials,
                windows=args.windows)

    print(f"tune: racing {len(cids)} candidate(s) "
          f"({'synthetic ' + args.synthetic if args.synthetic else 'measured, chained ' + args.backend}), "
          f"n={args.nprocs} d={args.data_size} p={args.proc_node}, "
          f"batches of {args.batch_trials} trial(s), seed {args.seed}")
    res = race_mod.race(cids, sampler, max_batches=args.max_batches,
                        alpha=args.alpha, seed=args.seed)

    from tpu_aggcomm.obs.ledger import manifest
    man = manifest()
    direction = space_mod.space_direction(methods)
    key = cache.tune_key(nprocs=args.nprocs, data_size=args.data_size,
                         proc_node=args.proc_node, direction=direction,
                         backend=args.backend, manifest=man)
    win = space_mod.parse_cid(res.winner)
    race_rec = {"seed": int(args.seed), "alpha": float(args.alpha),
                "n_boot": 2000, "max_batches": int(args.max_batches),
                "batch_trials": int(args.batch_trials), "order": cids,
                "samples": res.samples,
                "eliminations": res.eliminations, "winner": res.winner,
                "batches_run": res.batches_run,
                "survivors": res.survivors}
    path = cache.save_tune(
        args.tune_root, key=key, manifest=man,
        space={"methods": methods, "cb_nodes": cbs,
               "comm_sizes": comms, "agg_types": aggs},
        race=race_rec,
        winner={"method": win.method, "cb_nodes": win.cb_nodes,
                "comm_size": win.comm_size, "agg_type": win.agg_type},
        synthetic=bool(args.synthetic), model_prune=prune_rec)

    meds = res.medians()
    for e in res.eliminations:
        print(f"  batch {e['batch']}: {e['candidate']} out vs leader "
              f"{e['leader']} "
              f"(CI [{e['ci_pct'][0]:+.1f}%, {e['ci_pct'][1]:+.1f}%])")
    for cid in res.survivors:
        if cid != res.winner:
            print(f"  survivor (not separable from winner at "
                  f"alpha={args.alpha:g}): {cid} "
                  f"median {meds[cid] * 1e6:.2f} us")
    print(f"winner: {res.winner} (median {meds[res.winner] * 1e6:.2f} "
          f"us/rep) after {res.batches_run} batch(es)")
    print(f"tuned cache written: {path}")
    return 0


def _synth_params(args):
    """Pricing inputs for the synth search: the newest committed
    PREDICT_*.json's calibration for this platform (the _model_prune
    platform pick), or (None, None) with a stderr note — an absent
    model degrades to structural ranking, never blocks synthesis."""
    import os

    from tpu_aggcomm.model.artifact import load_artifact
    from tpu_aggcomm.model.predict import newest_predict_path
    from tpu_aggcomm.obs.ledger import manifest

    path = newest_predict_path(args.predict_root)
    if path is None:
        print("synth: no committed PREDICT_*.json — ranking finalists "
              "structurally", file=sys.stderr)
        return None, None
    try:
        art = load_artifact(path)
    except (OSError, ValueError) as e:
        print(f"synth: unreadable {path}: {e} — ranking finalists "
              f"structurally", file=sys.stderr)
        return None, None
    env = (manifest().get("env") or {})
    platform = "tpu" if env.get("tunnel_armed") \
        and env.get("jax_platforms") != "cpu" else "cpu"
    block = (art.get("platforms") or {}).get(platform)
    if not block:
        print(f"synth: {os.path.basename(path)} has no {platform!r} "
              f"calibration — ranking finalists structurally",
              file=sys.stderr)
        return None, None
    return dict(block["params"]), \
        f"{os.path.basename(path)} [{platform}]"


def _run_synth(args) -> int:
    """The schedule synthesizer (tpu_aggcomm/synth/): search -> register
    -> measured race vs the reference field, or --replay re-deriving a
    committed SYNTH_r*.json jax-free (the ci_tier1.sh gate)."""
    import os

    from tpu_aggcomm.synth import (SearchError, ensure_registered,
                                   load_artifact, next_artifact_path,
                                   replay_artifact, run_synth,
                                   save_artifact)

    if args.replay:
        from tpu_aggcomm.obs.regress import validate_synth
        try:
            blob = load_artifact(args.replay)
        except (OSError, ValueError) as e:
            raise SystemExit(f"synth --replay: cannot read "
                             f"{args.replay}: {e}")
        errors = validate_synth(blob, os.path.basename(args.replay))
        if errors:
            for e in errors:
                print(f"FAIL {e}", file=sys.stderr)
            raise SystemExit(f"synth --replay: {args.replay} failed "
                             f"schema validation ({len(errors)} "
                             f"error(s))")
        same, diffs = replay_artifact(args.replay)
        win = (blob.get("winner") or {}).get("cid")
        print(f"replay {os.path.basename(args.replay)}: "
              f"{blob['search']['evaluated']} composition(s) "
              f"re-searched, winner {win} -> "
              f"{'REPRODUCED' if same else 'MISMATCH vs stored record'}")
        for d in diffs:
            print(f"  {d}")
        return 0 if same else 1

    from tpu_aggcomm.tune import race as race_mod

    # committed ids first, so this run's finalists never collide
    ensure_registered(args.synth_root)
    params, params_source = _synth_params(args)

    if args.synthetic:
        try:
            sampler = race_mod.make_synthetic_sampler(
                args.synthetic, batch_trials=args.batch_trials,
                seed=args.seed)
        except race_mod.RaceError as e:
            raise SystemExit(f"synth --synthetic: {e}")
    else:
        from tpu_aggcomm.tune.measure import make_jax_sim_sampler
        sampler = make_jax_sim_sampler(
            nprocs=args.nprocs, data_size=args.data_size,
            proc_node=args.proc_node, iters_small=args.iters_small,
            iters_big=args.iters_big, batch_trials=args.batch_trials,
            windows=args.windows)

    try:
        art = run_synth(
            nprocs=args.nprocs, cb_nodes=args.cb_nodes,
            comm_size=args.comm_size, data_size=args.data_size,
            proc_node=args.proc_node, agg_type=args.agg_type,
            direction=args.direction, seed=args.seed, params=params,
            params_source=params_source, init=args.init,
            mutate_rounds=args.mutate_rounds, beam=args.beam,
            top_k=args.top_k, fanins=tuple(_ints(args.fanins)),
            relays=tuple(_ints(args.relays)), id_base=args.id_base,
            sampler=sampler, backend=args.backend,
            synthetic=args.synthetic, max_batches=args.max_batches,
            batch_trials=args.batch_trials, alpha=args.alpha, log=print)
    except SearchError as e:
        raise SystemExit(f"synth: {e}")

    race = art["race"]
    for e in race["eliminations"]:
        print(f"  batch {e['batch']}: {e['candidate']} out vs leader "
              f"{e['leader']} "
              f"(CI [{e['ci_pct'][0]:+.1f}%, {e['ci_pct'][1]:+.1f}%])")
    for cid in race["survivors"]:
        if cid != race["winner"]:
            print(f"  survivor (not separable from winner at "
                  f"alpha={args.alpha:g}): {cid}")
    win = art["winner"]
    print(f"winner: {win['cid']} (median {win['median_s'] * 1e6:.2f} "
          f"us/rep) after {race['batches_run']} batch(es)")
    if not win["synthesized"]:
        print(f"synth: the reference method m={win['method_id']} won "
              f"the race — no synthesized schedule beat the field at "
              f"this cell, so no artifact is written (try another "
              f"cell/seed)", file=sys.stderr)
        return 1
    print(f"  composition: {win['composition']} "
          f"(predicted rank {win['predicted_rank']})")
    out = args.out or next_artifact_path(args.synth_root)
    save_artifact(out, art)
    print(f"synth artifact written: {out}")
    return 0


def _run_pilot(args) -> int:
    """The autopilot control loop (tpu_aggcomm/pilot/): profile ->
    fold -> campaigns -> named decisions (-> swap), or --replay
    re-deriving a committed PILOT_r*.json jax-free (the ci_tier1.sh
    gate)."""
    import os

    from tpu_aggcomm.pilot import (PilotError, next_pilot_path,
                                   render_pilot, replay_pilot,
                                   run_pilot, write_pilot)

    if args.replay:
        import json as _json

        from tpu_aggcomm.obs.regress import validate_pilot
        try:
            with open(args.replay) as fh:
                blob = _json.load(fh)
        except (OSError, ValueError) as e:
            raise SystemExit(f"pilot --replay: cannot read "
                             f"{args.replay}: {e}")
        errors = validate_pilot(blob, os.path.basename(args.replay))
        if errors:
            for e in errors:
                print(f"FAIL {e}", file=sys.stderr)
            raise SystemExit(f"pilot --replay: {args.replay} failed "
                             f"schema validation ({len(errors)} "
                             f"error(s))")
        res = replay_pilot(args.replay)
        print(f"replay {os.path.basename(args.replay)}: "
              f"{len(blob.get('campaigns') or [])} campaign(s), "
              f"{len(blob.get('promotions') or [])} promotion(s) -> "
              f"{res['verdict']}")
        for p in res["problems"]:
            print(f"  {p}")
        return 0 if res["verdict"] == "REPRODUCED" else 1

    if not args.journals:
        raise SystemExit("pilot: name at least one serve journal "
                         "(or --replay a committed artifact)")
    from tpu_aggcomm.synth import ensure_registered
    # committed ids first, so campaign finalists never collide
    ensure_registered(args.synth_root)
    params, params_source = _synth_params(args)
    try:
        body = run_pilot(
            args.journals, seed=args.seed, serve_port=args.serve_port,
            dry_run=args.dry_run, synthetic=args.synthetic,
            params=params, params_source=params_source,
            max_batches=args.max_batches,
            batch_trials=args.batch_trials, alpha=args.alpha,
            n_boot=args.n_boot, id_base=args.id_base, log=print)
    except PilotError as e:
        raise SystemExit(f"pilot: {e}")
    out = args.out or next_pilot_path(args.synth_root)
    write_pilot(out, body)
    print(render_pilot(body))
    print(f"pilot artifact written: {out}")
    from tpu_aggcomm.obs.regress import validate_pilot
    import json as _json
    with open(out) as fh:
        errors = validate_pilot(_json.load(fh), os.path.basename(out))
    if errors:
        for e in errors:
            print(f"FAIL {e}", file=sys.stderr)
        return 1
    return 0


def _resolve_auto(args, nprocs: int, *, sweep: bool = False) -> None:
    """--auto: swap the explicit -m (and for run: -a/-c/-t) for the
    tuned winner of this (shape, direction, backend), when a
    fingerprint-valid cache entry exists. Any miss — no artifact,
    schema failure, manifest drift, un-directed -m 0 — warns on stderr
    and keeps the explicit flags: the cache may steer, never strand."""
    from tpu_aggcomm.core.methods import METHODS
    from tpu_aggcomm.obs.ledger import manifest
    from tpu_aggcomm.tune import cache

    if args.method not in METHODS:
        print(f"auto: -m {args.method} does not name a direction "
              f"(m=0 runs all methods); keeping explicit flags",
              file=sys.stderr)
        return
    direction = METHODS[args.method].direction.value
    if args.backend not in DEVICE_FREE_BACKENDS:
        # device facts (platform/device_kind) are part of the tuning
        # fingerprint; record them before computing ours so a valid
        # entry is not rejected for an asymmetry we created
        try:
            from tpu_aggcomm.tune.measure import record_device_facts
            record_device_facts()
        except Exception:  # lint: broad-ok (device-facts cache is advisory)
            pass
    man = manifest()
    key = cache.tune_key(nprocs=nprocs, data_size=args.data_size,
                         proc_node=args.proc_node, direction=direction,
                         backend=args.backend, manifest=man)
    entry, note = cache.lookup(args.tune_root, key, manifest=man)
    if entry is None:
        if not sweep and _auto_fault_advise(args, nprocs, note):
            return
        print(f"auto: {note}; falling back to -m {args.method}",
              file=sys.stderr)
        return
    win = entry["winner"]
    tag = " [synthetic tune]" if entry.get("synthetic") else ""
    src = cache.artifact_path(args.tune_root, key)
    if sweep:
        args.method = int(win["method"])
        print(f"auto: tuned method -m {args.method}{tag} from {src}")
    else:
        args.method = int(win["method"])
        args.cb_nodes = int(win["cb_nodes"])
        args.comm_size = int(win["comm_size"])
        args.agg_type = int(win["agg_type"])
        print(f"auto: tuned -m {args.method} -a {args.cb_nodes} "
              f"-c {args.comm_size} -t {args.agg_type}{tag} from {src}")


def _auto_fault_advise(args, nprocs: int, note: str) -> bool:
    """Fault-aware --auto fallback: on a tune-cache miss UNDER AN
    ACTIVE --fault spec, rank the repaired same-direction candidates
    with the newest committed ``PREDICT_*.json`` and apply the model's
    pick — an stderr ADVISORY, never a verdict: measured rounds stay
    the source of truth, and a missing/unusable artifact falls back to
    the explicit flags exactly like a plain cache miss. Returns True
    iff a model pick was applied."""
    fault = getattr(args, "fault", None)
    if not isinstance(fault, str):
        return False
    from tpu_aggcomm.faults.spec import FaultSpecError, parse_fault
    try:
        spec = parse_fault(fault)
    except FaultSpecError:
        return False          # run() will surface the malformed spec
    if spec.empty:
        return False
    from tpu_aggcomm.model.artifact import load_artifact
    from tpu_aggcomm.model.predict import newest_predict_path
    path = newest_predict_path(getattr(args, "tune_root", ".") or ".") \
        or newest_predict_path(".")
    if path is None:
        print(f"auto: no PREDICT_*.json to rank repaired candidates "
              f"under --fault {spec.canonical()}; keeping explicit "
              f"flags", file=sys.stderr)
        return False
    try:
        art = load_artifact(path)
    except (OSError, ValueError) as e:
        print(f"auto: unreadable {path} ({e}); keeping explicit flags",
              file=sys.stderr)
        return False
    from tpu_aggcomm.obs.ledger import manifest
    env = manifest().get("env") or {}
    platform = "tpu" if env.get("tunnel_armed") \
        and env.get("jax_platforms") != "cpu" else "cpu"
    block = (art.get("platforms") or {}).get(platform)
    params = (block or {}).get("params") if isinstance(block, dict) \
        else None
    if not params:
        print(f"auto: {path} has no calibrated {platform!r} "
              f"parameters; keeping explicit flags", file=sys.stderr)
        return False
    from tpu_aggcomm.core.methods import METHODS, compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern
    from tpu_aggcomm.faults.repair import repair_schedule
    from tpu_aggcomm.model.predict import predict_schedule
    direction = METHODS[args.method].direction
    pattern = AggregatorPattern(
        nprocs=nprocs, cb_nodes=args.cb_nodes,
        data_size=args.data_size, placement=args.agg_type,
        proc_node=args.proc_node, comm_size=args.comm_size)
    best = None
    for m, info in sorted(METHODS.items()):
        if info.direction is not direction or info.tam:
            continue
        try:
            sched = compile_method(m, pattern,
                                   barrier_type=args.barrier_type)
            repaired = repair_schedule(sched, spec,
                                       barrier_type=args.barrier_type)
            cost = predict_schedule(repaired, params,
                                    fault=spec)["total_s"]
        except Exception:  # lint: broad-ok (unrepairable/unpriceable candidates are skipped — the model advises, never strands)
            continue
        if best is None or cost < best[1]:
            best = (m, cost)
    if best is None:
        print(f"auto: model could not price any repaired "
              f"{direction.value} candidate under --fault "
              f"{spec.canonical()}; keeping explicit flags",
              file=sys.stderr)
        return False
    print(f"auto: {note}; under --fault {spec.canonical()} the model "
          f"({path}, {platform}) ranks repaired -m {best[0]} best "
          f"(predicted {best[1]:.6f} s/rep) — ADVISORY pick; measured "
          f"rounds stay the source of truth", file=sys.stderr)
    args.method = int(best[0])
    return True


def _fused_export_sweep(args) -> int:
    """Cross-check every method's pallas_fused step export against the
    op-program traffic accounting (native/fuse.py, jax-free). DRIFT is
    the failure; unfusable schedules are SKIPPED by design."""
    from tpu_aggcomm.native.fuse import export_sweep, render_export_sweep

    fault = getattr(args, "fault", None)
    rows = export_sweep(args.nprocs, args.cb_nodes, args.comm_size,
                        data_size=args.data_size,
                        proc_node=args.proc_node, agg_type=args.agg_type,
                        fault=fault, barrier_type=args.barrier_type)
    print(render_export_sweep(rows, fault=fault), end="")
    return 1 if any(r["status"] == "DRIFT" for r in rows) else 0


def _fused_export_one(sched) -> int:
    """Single-schedule fused-export cross-check; prints one verdict
    line. The schedule is whatever the caller audited (repaired when
    --fault was given), so the two accountings see the same program."""
    from tpu_aggcomm.native.fuse import FusedExportError, cross_check_export

    try:
        rep = cross_check_export(sched)
    except FusedExportError as e:
        print(f"fused export: DRIFT: {e}")
        return 1
    if rep["status"] == "MATCH":
        print(f"fused export: MATCH ({rep['rounds']} rounds, "
              f"{rep['edges']} edges, {rep['fences']} fences, "
              f"{rep['bytes']} B — identical to the op-program matrices)")
    else:
        print(f"fused export: SKIPPED: {rep['reason']}")
    return 0


def _run_inspect_traffic(args) -> int:
    """Static traffic audit (obs/traffic.py, jax-free): the per-round
    communication matrix, incast depths, and the -c throttle-conformance
    verdict, derived ONLY from the compiled op programs. ``-m 0`` sweeps
    every method in METHODS as a pass/fail gate (scripts/ci_tier1.sh
    runs exactly that); ``--trace FILE`` joins the matrix with a
    flight-recorder trace's round walls for the measured overlay."""
    from tpu_aggcomm.obs import traffic as tr

    if args.method is None:
        raise SystemExit("inspect traffic: -m is required "
                         "(-m 0 sweeps every method as a gate)")
    _ensure_synth(args, [args.method])
    if args.method == 0:
        if args.json or args.trace or args.fault:
            raise SystemExit("inspect traffic: --json/--trace/--fault "
                             "apply to a single-method audit, not the "
                             "-m 0 sweep")
        rows = tr.conformance_sweep(
            args.nprocs, args.cb_nodes, args.comm_size,
            data_size=args.data_size, proc_node=args.proc_node,
            agg_type=args.agg_type)
        print(tr.render_sweep(rows, args.nprocs, args.cb_nodes,
                              args.comm_size), end="")
        rc = 1 if any(r["verdict"] == "REFUTED" for r in rows) else 0
        if args.fused_export:
            rc = max(rc, _fused_export_sweep(args))
        return rc

    from tpu_aggcomm.core.methods import METHODS, compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern

    if args.method not in METHODS:
        raise SystemExit(f"inspect traffic: unknown method {args.method} "
                         f"(known: {sorted(METHODS)})")
    p = AggregatorPattern(
        nprocs=args.nprocs, cb_nodes=args.cb_nodes,
        data_size=args.data_size, placement=args.agg_type,
        proc_node=args.proc_node, comm_size=args.comm_size)
    sched = compile_method(args.method, p, barrier_type=args.barrier_type)
    if args.fault:
        from tpu_aggcomm.faults import (FaultSpecError, RepairError,
                                        repair_schedule)
        try:
            sched = repair_schedule(sched, args.fault,
                                    barrier_type=args.barrier_type)
        except (FaultSpecError, RepairError) as e:
            raise SystemExit(f"inspect traffic --fault: {e}")
    audit = tr.audit_schedule(sched)
    overlay = None
    if args.trace:
        from tpu_aggcomm.obs.trace import load_events
        try:
            events = load_events(args.trace)
        except (OSError, ValueError) as e:
            raise SystemExit(f"inspect traffic: unreadable trace "
                             f"{args.trace}: {e}")
        try:
            overlay = tr.measured_overlay(audit, events)
        except (tr.TrafficError, KeyError) as e:
            raise SystemExit(f"inspect traffic: {e}")
    print(tr.render_audit(audit, overlay), end="")
    if args.json:
        path = tr.write_artifact(args.json, audit, overlay)
        print(f"traffic artifact written: {path}")
    rc = 1 if audit["conformance"]["verdict"] == "REFUTED" else 0
    if args.fused_export:
        rc = max(rc, _fused_export_one(sched))
    return rc


def _run_inspect_check(args) -> int:
    """Schedule model checker (analysis/check.py, jax-free): prove
    deadlock-freedom, recv-slot race-freedom, byte conservation, barrier
    SPMD symmetry, and round-fence monotonicity from the compiled op
    programs alone. ``-m 0`` sweeps every method in METHODS as a
    pass/fail gate (scripts/ci_tier1.sh runs exactly that, healthy and
    under the committed fault spec); ``--fault SPEC`` checks the
    REPAIRED schedule — the liveness complement of the traffic
    auditor's -c re-proof."""
    from tpu_aggcomm.analysis import check as ck

    if args.method is None:
        raise SystemExit("inspect check: -m is required "
                         "(-m 0 sweeps every method as a gate)")
    _ensure_synth(args, [args.method])
    if args.method == 0:
        if args.json or args.trace:
            raise SystemExit("inspect check: --json/--trace apply to a "
                             "single-method check, not the -m 0 sweep")
        rows = ck.check_sweep(
            args.nprocs, args.cb_nodes, args.comm_size,
            data_size=args.data_size, proc_node=args.proc_node,
            agg_type=args.agg_type, fault=args.fault,
            barrier_type=args.barrier_type)
        print(ck.render_check_sweep(rows, args.nprocs, args.cb_nodes,
                                    args.comm_size, fault=args.fault),
              end="")
        rc = 1 if any(r["verdict"] == "REFUTED" for r in rows) else 0
        if args.fused_export:
            rc = max(rc, _fused_export_sweep(args))
        return rc

    from tpu_aggcomm.core.methods import METHODS, compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern

    if args.method not in METHODS:
        raise SystemExit(f"inspect check: unknown method {args.method} "
                         f"(known: {sorted(METHODS)})")
    p = AggregatorPattern(
        nprocs=args.nprocs, cb_nodes=args.cb_nodes,
        data_size=args.data_size, placement=args.agg_type,
        proc_node=args.proc_node, comm_size=args.comm_size)
    sched = compile_method(args.method, p, barrier_type=args.barrier_type)
    if args.fault:
        from tpu_aggcomm.faults import (FaultSpecError, RepairError,
                                        repair_schedule)
        try:
            sched = repair_schedule(sched, args.fault,
                                    barrier_type=args.barrier_type)
        except (FaultSpecError, RepairError) as e:
            raise SystemExit(f"inspect check --fault: {e}")
    report = ck.check_schedule(sched)
    print(ck.render_check(report), end="")
    if args.json:
        path = ck.write_artifact(args.json, report)
        print(f"check artifact written: {path}")
    rc = 1 if report["verdict"] == "REFUTED" else 0
    if args.fused_export:
        rc = max(rc, _fused_export_one(sched))
    return rc


def _run_inspect_explain(args) -> int:
    """The analytic cost model (tpu_aggcomm/model/, jax-free).

    Three modes: ``--replay PREDICT_*.json`` re-derives a committed
    artifact to REPRODUCED/MISMATCH (the ci_tier1.sh gate);
    ``explain TRACE...`` prints predicted-vs-measured round walls with
    named divergence verdicts (preferring the committed artifact's
    calibration, else calibrating fresh); bare ``explain`` calibrates,
    validates rank-order on the committed grids, and prints the
    summary (``--json PATH`` writes the predict-v1 artifact).

    Verdicts are advisory: the model names suspects, measured walls
    stay the source of truth — predictions never gate alone."""
    from tpu_aggcomm.model import (ModelError, build_artifact,
                                   explain_trace, load_artifact,
                                   render_explain, replay_artifact)
    from tpu_aggcomm.model.predict import newest_predict_path

    if args.replay:
        try:
            same, diffs = replay_artifact(args.replay)
        except (ModelError, OSError, ValueError, KeyError) as e:
            raise SystemExit(f"inspect explain --replay: {e}")
        if same:
            print(f"explain replay: REPRODUCED ({args.replay})")
            return 0
        print(f"explain replay: MISMATCH vs {args.replay} "
              f"(divergent keys: {', '.join(diffs)})")
        return 1

    try:
        newest = newest_predict_path(".")
        if newest is not None:
            art = load_artifact(newest)
            src = newest
        else:
            art = build_artifact(".")
            src = "fresh calibration (no committed PREDICT_*.json)"
    except (ModelError, OSError, ValueError, KeyError) as e:
        raise SystemExit(f"inspect explain: cannot calibrate: {e}")

    if args.trace_file:
        rc = 0
        for path in args.trace_file:
            try:
                print(render_explain(
                    explain_trace(path, art["platforms"])))
            except (ModelError, OSError, ValueError, KeyError) as e:
                print(f"inspect explain: {path}: {e}")
                rc = 1
        print(f"[calibration: {src}]")
        return rc

    # bare: calibration + validation summary
    print(f"cost model [{src}]")
    for plat, block in sorted(art["platforms"].items()):
        params = ", ".join(f"{k}={v * 1e6:.4g}us"
                           for k, v in block["params"].items())
        print(f"  {plat} ({block['granularity']}-fit, "
              f"{block['observations']} obs, "
              f"tol=±{block['tolerance_rel']:.0%}): {params}")
    for name, v in sorted(art["validation"].items()):
        t1 = v["top1"]
        tau = "n/a" if v["tau_b"] is None else f"{v['tau_b']:.3f}"
        held = " HELD-OUT" if v["held_out"] else ""
        print(f"  {name}{held}: tau_b={tau} over {v['cells']} cells; "
              f"top-1 {'AGREES' if t1['agree'] else 'disagrees'} "
              f"(measured best m={t1['measured_best']['method']} "
              f"c={t1['measured_best']['comm']}, predicted class of "
              f"{len(t1['predicted_class'])})")
    cx = art.get("crossover") or {}
    if "crossover_max_comm" in cx:
        print(f"  fused-vs-fenced crossover ({cx['grid']}, noise floor "
              f"{cx['noise_floor_rel']:.0%}): "
              f"{cx['crossover_max_comm']}")
    if args.json:
        from tpu_aggcomm.model import save_artifact
        save_artifact(args.json, art if newest is None
                      else build_artifact("."))
        print(f"predict artifact written: {args.json}")
    return 0


def _run_inspect_workload(args) -> int:
    """The serve-journal workload profiler (obs/workload.py, jax-free).

    Two modes: ``--replay WORKLOAD_r*.json`` re-derives a committed
    artifact from the journals recorded next to it (REPRODUCED or
    MISMATCH with the diverging keys named — the ci_tier1.sh gate);
    ``workload JOURNAL...`` profiles one or more serve journals
    (``--json PATH`` writes the workload-v1 artifact, refused while the
    journal disagrees with itself). Detection is advisory: proposals
    name tune/synth targets, nothing changes behavior. Exit 1 on any
    profiler problem — a journal that contradicts itself must fail
    loudly, never average the contradiction away."""
    from tpu_aggcomm.obs.workload import (profile_journal, render_workload,
                                          replay_workload, write_workload)
    if args.replay:
        try:
            res = replay_workload(args.replay)
        except (OSError, ValueError, KeyError) as e:
            raise SystemExit(f"inspect workload --replay: {e}")
        if res["verdict"] == "REPRODUCED":
            print(f"workload replay: REPRODUCED ({args.replay})")
            return 0
        print(f"workload replay: MISMATCH vs {args.replay}")
        for p in res["problems"]:
            print(f"  {p}")
        return 1

    if not args.trace_file:
        raise SystemExit("inspect workload: missing serve journal(s) "
                         "(*.journal.jsonl written by `cli serve "
                         "--journal` / serve_loadgen.py)")
    try:
        profile = profile_journal(args.trace_file, seed=args.seed)
    except OSError as e:
        raise SystemExit(f"inspect workload: unreadable journal: {e}")
    print(render_workload(profile), end="")
    if profile["problems"]:
        # never commit an artifact its own journal contradicts
        if args.json:
            print(f"workload artifact NOT written ({args.json}): "
                  f"{len(profile['problems'])} problem(s) above")
        return 1
    if args.json:
        write_workload(args.json, profile)
        print(f"workload artifact written: {args.json}")
    return 0


def _run_inspect_watch(args) -> int:
    """The streaming SLO watchtower (obs/watch.py, jax-free).

    Three modes: ``--replay WATCH_r*.json`` re-derives a committed
    artifact from the stream basenames + embedded SLO spec + seed
    recorded inside it (REPRODUCED or MISMATCH with the diverging keys
    named — the ci_tier1.sh gate); ``watch JOURNAL... [TRACE...]``
    runs one tail→evaluate→detect→attribute pass (``--json PATH``
    writes the watch-v1 artifact, refused while the journal disagrees
    with itself); ``--follow`` re-renders every ``--interval`` seconds
    (read-only, Ctrl-C to detach — the live tail the SLO windows were
    built for). Verdicts are advisory (the resilience/detect.py
    pattern): anomalies name suspects, nothing changes what runs."""
    import os
    import time as _time

    from tpu_aggcomm.obs.slo import DEFAULT_SLO, SloError, load_slo
    from tpu_aggcomm.obs.watch import (render_watch, replay_watch,
                                       watch_streams, write_watch)
    if args.replay:
        try:
            res = replay_watch(args.replay)
        except (OSError, ValueError, KeyError) as e:
            raise SystemExit(f"inspect watch --replay: {e}")
        if res["verdict"] == "REPRODUCED":
            print(f"watch replay: REPRODUCED ({args.replay})")
            return 0
        print(f"watch replay: MISMATCH vs {args.replay}")
        for p in res["problems"]:
            print(f"  {p}")
        return 1

    journals = [p for p in args.trace_file
                if not p.endswith(".trace.jsonl")]
    traces = [p for p in args.trace_file if p.endswith(".trace.jsonl")]
    if not journals:
        raise SystemExit("inspect watch: missing serve journal(s) "
                         "(*.journal.jsonl written by `cli serve "
                         "--journal` / serve_loadgen.py; *.trace.jsonl "
                         "files join as round-wall streams)")
    if args.follow and args.json:
        raise SystemExit("inspect watch: --follow with --json is "
                         "refused — an artifact is one deterministic "
                         "pass over closed streams, not a moving tail "
                         "(run --json after the workload completes)")
    slo, slo_source = DEFAULT_SLO, "default"
    if args.slo:
        try:
            slo = load_slo(args.slo)
        except SloError as e:
            raise SystemExit(f"inspect watch: {e}")
        slo_source = os.path.basename(args.slo)

    def one_pass():
        try:
            return watch_streams(journals, traces, slo=slo,
                                 slo_source=slo_source, seed=args.seed,
                                 flow_path=args.flow)
        except OSError as e:
            raise SystemExit(f"inspect watch: unreadable stream: {e}")
        except ValueError as e:
            raise SystemExit(f"inspect watch: {e}")

    body = one_pass()
    print(render_watch(body), end="")
    while args.follow:
        try:
            _time.sleep(args.interval)
        except KeyboardInterrupt:
            print("watch: detached (read-only; the workload is "
                  "unaffected)")
            return 0
        body = one_pass()
        print(render_watch(body), end="")
    if body["problems"]:
        # never commit an artifact its own journal contradicts
        if args.json:
            print(f"watch artifact NOT written ({args.json}): "
                  f"{len(body['problems'])} problem(s) above")
        return 1
    if args.json:
        write_watch(args.json, body)
        print(f"watch artifact written: {args.json}")
    return 0


def _run_inspect_flow(args) -> int:
    """The end-to-end causal flow joiner (obs/flow.py, jax-free).

    Two modes: ``--replay FLOW_r*.json`` re-derives a committed
    artifact from the client journal + serve journal + trace basenames
    recorded inside it (REPRODUCED or MISMATCH with the diverging keys
    named — the ci_tier1.sh gate); ``flow CLIENT.journal SERVE.journal
    [TRACE...]`` runs one join+decompose pass (``--json PATH`` writes
    the flow-v1 artifact, refused while the streams disagree with each
    other). Positional order is CLIENT then SERVE; *.trace.jsonl files
    may appear anywhere (split by suffix). Exit 1 on any join problem —
    streams that contradict each other must fail loudly, never average
    the contradiction away."""
    from tpu_aggcomm.obs.flow import (flow_streams, render_flow,
                                      replay_flow, write_flow)
    if args.replay:
        try:
            res = replay_flow(args.replay)
        except (OSError, ValueError, KeyError) as e:
            raise SystemExit(f"inspect flow --replay: {e}")
        if res["verdict"] == "REPRODUCED":
            print(f"flow replay: REPRODUCED ({args.replay})")
            return 0
        print(f"flow replay: MISMATCH vs {args.replay}")
        for p in res["problems"]:
            print(f"  {p}")
        return 1

    journals = [p for p in args.trace_file
                if not p.endswith(".trace.jsonl")]
    traces = [p for p in args.trace_file if p.endswith(".trace.jsonl")]
    if len(journals) != 2:
        raise SystemExit("inspect flow: need exactly two journals — "
                         "CLIENT.journal (serve_loadgen.py "
                         "--client-journal) then SERVE.journal (`cli "
                         "serve --journal`); *.trace.jsonl files join "
                         "as dispatch round streams")
    try:
        body = flow_streams(journals[0], journals[1], traces,
                            seed=args.seed)
    except OSError as e:
        raise SystemExit(f"inspect flow: unreadable stream: {e}")
    print(render_flow(body), end="")
    if body["problems"]:
        # never commit an artifact its own streams contradict
        if args.json:
            print(f"flow artifact NOT written ({args.json}): "
                  f"{len(body['problems'])} problem(s) above")
        return 1
    if args.json:
        write_flow(args.json, body)
        print(f"flow artifact written: {args.json}")
    return 0


def _run_inspect(args) -> int:
    """Schedule-shape report: what the -c/-m/-t choices actually compile
    to. This is the question the per-phase timers approximate at runtime,
    answered statically."""
    if args.what == "trace":
        if not args.trace_file:
            raise SystemExit("inspect trace: missing trace file(s) "
                             "(*.trace.jsonl written by --trace)")
        from tpu_aggcomm.obs.metrics import summarize_traces
        from tpu_aggcomm.obs.trace import load_events
        from tpu_aggcomm.resilience import propose_fault_specs
        from tpu_aggcomm.resilience.detect import render_proposals
        # a missing/corrupt/truncated artifact must exit with one line
        # on stderr, not a traceback (json decode errors are ValueError)
        try:
            print(summarize_traces(args.trace_file), end="")
            # advisory fault detection (resilience/detect.py): the same
            # round_stats, matched against the PR 6 slow-rank signature;
            # an extra output line only — never a behavior change
            for path in args.trace_file:
                print(render_proposals(
                    propose_fault_specs(load_events(path))), end="")
        except (OSError, ValueError, KeyError) as e:
            raise SystemExit(f"inspect trace: unreadable trace file: {e}")
        return 0
    if args.what == "compare":
        if len(args.trace_file) != 2:
            raise SystemExit("inspect compare: need exactly two trace "
                             "files (or two sweep-trace directories)")
        from tpu_aggcomm.obs.compare import (TraceCompareError,
                                             compare_paths, render_compare,
                                             save_compare)
        try:
            res = compare_paths(args.trace_file[0], args.trace_file[1],
                                by=args.by,
                                across_faults=args.across_faults)
        except TraceCompareError as e:
            raise SystemExit(f"inspect compare: {e}")
        except (OSError, ValueError, KeyError) as e:
            raise SystemExit(f"inspect compare: unreadable trace file: {e}")
        print(render_compare(res), end="")
        if args.json:
            path = save_compare(args.json, res)
            print(f"compare artifact written: {path}")
        return 0
    if args.what == "explain":
        return _run_inspect_explain(args)
    if args.what == "workload":
        return _run_inspect_workload(args)
    if args.what == "watch":
        return _run_inspect_watch(args)
    if args.what == "flow":
        return _run_inspect_flow(args)
    if args.what == "traffic":
        return _run_inspect_traffic(args)
    if args.what == "check":
        return _run_inspect_check(args)
    if args.what == "report":
        from tpu_aggcomm.obs.report_html import write_report
        path = write_report(args.out, history_root=args.history_root,
                            trace_paths=args.trace_file)
        print(f"report written: {path}")
        return 0
    if args.what == "ledger":
        import glob
        import os

        from tpu_aggcomm.obs import ledger
        paths = args.trace_file or sorted(
            glob.glob(os.path.join(args.history_root, "BENCH_r*.json")))
        if not paths:
            raise SystemExit(
                "inspect ledger: no artifacts found (pass BENCH_r*.json / "
                "*.trace.jsonl files, or point --history-root at a "
                "directory holding BENCH_r*.json)")
        try:
            print(ledger.render_ledgers(paths), end="")
        except (OSError, ValueError, KeyError) as e:
            raise SystemExit(f"inspect ledger: unreadable artifact: {e}")
        return 0
    if args.what == "live":
        # attachable sweep monitor (obs/live.py): tails the crash-safe
        # resilience journal + trace JSONL of a sweep running in ANOTHER
        # process — jax-free by design, so it works while that process
        # owns the only TPU client (or while a dead tunnel would hang
        # `import jax` here)
        from tpu_aggcomm.obs.live import attach
        comm_sizes = None
        if args.comm_sizes:
            try:
                comm_sizes = [int(x) for x in args.comm_sizes.split(",")
                              if x.strip()]
            except ValueError:
                raise SystemExit(
                    f"inspect live: malformed --comm-sizes "
                    f"{args.comm_sizes!r} (want e.g. 4,8,16)")
        return attach(args.results_csv, comm_sizes=comm_sizes,
                      trace_paths=args.trace_file, follow=args.follow,
                      interval=args.interval)
    if args.what == "history":
        from tpu_aggcomm.obs.history import (build_index, check_trends,
                                             render_history, write_index)
        print(render_history(args.history_root), end="")
        if args.json:
            path = write_index(args.json, build_index(args.history_root))
            print(f"history index written: {path}")
        return 0 if check_trends(args.history_root)["ok"] else 1
    if args.method is None:
        raise SystemExit("inspect: -m is required "
                         "(or use 'inspect trace <file>')")

    from tpu_aggcomm.core.methods import METHODS, compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern

    p = AggregatorPattern(
        nprocs=args.nprocs, cb_nodes=args.cb_nodes,
        data_size=args.data_size, placement=args.agg_type,
        proc_node=args.proc_node, comm_size=args.comm_size)
    sched = compile_method(args.method, p, barrier_type=args.barrier_type)
    spec = METHODS[args.method]
    print(f"method {args.method} ({spec.name}), direction = "
          f"{spec.direction.value}, nprocs = {args.nprocs}, "
          f"cb_nodes = {args.cb_nodes}, comm_size = {args.comm_size}")

    from tpu_aggcomm.tam.engine import TamMethod
    if isinstance(sched, TamMethod):
        from tpu_aggcomm.tam.engine import tam_phase_bytes
        vols = tam_phase_bytes(sched.pattern, sched.assignment)
        print(f"hierarchical engine over {sched.assignment.nnodes} nodes "
              f"({args.proc_node} ranks/node); phase bytes:")
        for k, v in vols.items():
            print(f"  {k:16s} {v} B")
        if args.roofline:
            from tpu_aggcomm.harness.roofline import (HBM_V5E_GBPS,
                                                      tam_rep_bytes)
            rb = tam_rep_bytes(sched)
            print(f"roofline (jax_sim 3-hop route, floors at "
                  f"{HBM_V5E_GBPS:.0f} GB/s HBM): "
                  f"{rb.total() / 1e6:.2f} MB/rep "
                  f"({rb.edges} slabs x 2 hops materialized) -> floor "
                  f"{rb.floor_seconds() * 1e6:.1f} us/rep; measured hop "
                  f"times via --measured-phases --backend jax_sim")
        if args.waves:
            print("waves: n/a for TAM (the hierarchical engine rides "
                  "mesh collectives, not the pallas_dma transport)")
        return 0

    def _print_roofline():
        # bytes-touched model + HBM floors (harness/roofline.py): the
        # optimistic/fenced window a measured per-rep time is judged
        # against. jax_sim always; jax_shard at --ndev (default 1, the
        # single-chip flagship tier with the fused single-dev rounds)
        from tpu_aggcomm.harness.roofline import HBM_V5E_GBPS, rep_bytes
        # the jax_shard backend refuses non-dividing device counts — a
        # floor for an unrunnable configuration would judge nothing
        nd = args.ndev if (args.ndev and p.nprocs % args.ndev == 0) else 1
        print(f"roofline (floors at {HBM_V5E_GBPS:.0f} GB/s HBM):")
        for lowering, ndv in (("jax_sim", 1), ("jax_shard", nd)):
            rb = rep_bytes(sched, lowering=lowering, ndev=ndv)
            lo = rb.floor_seconds()
            hi = rb.floor_seconds(fenced=True)
            print(f"  {lowering}(ndev={ndv}): {rb.total() / 1e6:.2f} MB "
                  f"optimistic / {rb.total(fenced=True) / 1e6:.2f} MB "
                  f"fenced ({rb.rounds} rounds) -> floors "
                  f"[{lo * 1e6:.1f}, {hi * 1e6:.1f}] us/rep")

    if sched.collective:
        e = len(p.senders) * len(p.receivers)
        print(f"dense vendor collective (alltoallw analog): "
              f"{e} messages x {p.data_size} B in ONE call")
        if args.roofline:
            _print_roofline()
        if args.waves:
            print("waves: n/a for dense collectives (they lower to the "
                  "vendor all_to_all, not the pallas_dma transport)")
        return 0

    from tpu_aggcomm.backends.jax_ici import lower_schedule
    low = lower_schedule(sched)
    edges = sched.data_edges()
    print(f"rendezvous sends: {sched.uses_rendezvous}; "
          f"{len(edges)} messages over "
          f"{int(edges[:, 4].max()) + 1 if len(edges) else 0} rounds, "
          f"{low.n_colors} ppermute color steps")
    n_rounds = int(edges[:, 4].max()) + 1 if len(edges) else 0
    for r in range(n_rounds):
        sel = edges[edges[:, 4] == r]
        if len(sel) == 0:
            continue
        colors = sum(1 for c in low.round_of_color if c == r)
        nbar = low.barrier_rounds.get(r, 0)
        bar = f", {nbar} barrier(s)" if nbar else ""
        print(f"  round {r:3d}: {len(sel):5d} msgs, {colors:3d} colors, "
              f"{len(sel) * p.data_size:9d} B{bar}")

    if getattr(args, "ndev", 0):
        # jax_shard view: per-round block-all_to_all tables over an
        # --ndev-device mesh — block size M and the padding overhead the
        # flagship tier actually ships (DISTRIBUTED.md)
        from tpu_aggcomm.backends.jax_shard import (_schedule_edges,
                                                    block_round_tables,
                                                    recv_layout)
        from tpu_aggcomm.harness.verify import recv_slot_counts
        import numpy as np
        ndev = args.ndev
        if p.nprocs % ndev:
            print(f"(ndev {ndev} does not divide nprocs {p.nprocs}; "
                  f"no shard view)")
            ndev = 0
    if getattr(args, "ndev", 0) and ndev:
        bsz = p.nprocs // ndev
        counts = np.asarray(recv_slot_counts(p))
        recv_base, F = recv_layout(counts, ndev, bsz)
        from tpu_aggcomm.core.pattern import Direction as _D
        if p.direction is _D.ALL_TO_MANY:
            scounts = np.full(p.nprocs, p.cb_nodes, dtype=np.int64)
        else:
            scounts = np.where(np.asarray(p.agg_index) >= 0, p.nprocs, 0)
        send_base, _Fs = recv_layout(scounts, ndev, bsz)
        tabs = block_round_tables(_schedule_edges(sched), ndev=ndev,
                                  bsz=bsz, send_base=send_base,
                                  recv_base=recv_base, F=F)
        print(f"jax_shard over {ndev} devices ({bsz} ranks/device): "
              f"one block all_to_all per round")
        for (r, pk, _sc, M) in tabs:
            real = int((pk >= 0).sum())
            shipped = ndev * ndev * M
            print(f"  round {r:3d}: block M = {M:5d}, real msgs = "
                  f"{real:6d}, shipped slots = {shipped:6d} "
                  f"(padding x{shipped / max(real, 1):.2f})")

    if args.roofline:
        _print_roofline()
    if args.waves:
        # wave accounting: in-flight DMAs per wave, the quantity the
        # posting discipline controls (RESULTS_TPU.md wave table)
        from tpu_aggcomm.backends.pallas_dma import PallasDmaBackend
        for label, b in (("lockstep", PallasDmaBackend()),
                         ("concurrent", PallasDmaBackend(concurrent=True))):
            w = b.wave_profile(sched)
            print(f"pallas_dma {label:10s}: {w['steps']} DMA steps in "
                  f"{w['n_waves']} waves, max in-flight = "
                  f"{w['max_in_flight']}")
    return 0


def _run_analyze(args) -> int:
    """Winner table from accumulated sweep rows — the question the
    reference's whole harness exists to answer: which schedule / throttle
    minimizes max-over-ranks completion time for a pattern."""
    import csv

    try:
        with open(args.results_csv, newline="") as f:
            rows = list(csv.DictReader(f))
    except FileNotFoundError:
        raise SystemExit(f"no such file: {args.results_csv} "
                         f"(run a sweep or benchmark first)")
    if not rows:
        raise SystemExit(f"{args.results_csv} has no data rows")

    # provenance sidecar (results row index -> executed backend, phase
    # source): the winner table says not just WHICH schedule won but how
    # trustworthy each row's phase columns are — a measured-rounds row
    # and an attributed row must not read as equals
    from tpu_aggcomm.harness.report import PHASE_SOURCES, provenance_path
    prov: dict[int, tuple[str, str]] = {}
    try:
        with open(provenance_path(args.results_csv), newline="") as f:
            for pr in csv.DictReader(f):
                try:
                    idx = int(pr["results row"])
                    executed, phases = (pr["backend executed"],
                                        pr["phase columns"])
                except (KeyError, ValueError, TypeError):
                    continue
                # reject truncated rows (restval None) and labels outside
                # the vocabulary (e.g. comma-split fragments from sidecars
                # written before the quoting fix) — a garbled tag defeats
                # the trust annotation this join exists to provide
                if executed is None or phases not in PHASE_SOURCES:
                    continue
                prov[idx] = (executed, phases)
    except FileNotFoundError:
        pass

    # config = (procs, aggregators, data size); best row per (config, method)
    best: dict[tuple, dict] = {}
    best_idx: dict[tuple, int] = {}
    for i, r in enumerate(rows):
        try:
            # numeric keys: sort naturally AND reject truncated rows (a
            # sweep killed mid-append leaves None trailing fields)
            key = (int(r["# of processes"]), int(r["# of aggregators"]),
                   int(r["data size"]), r["Method"])
            t = float(r["max total time"])
        except (KeyError, ValueError, TypeError):
            continue
        if key not in best or t < float(best[key]["max total time"]):
            best[key] = r
            best_idx[key] = i + 1           # sidecar rows are 1-based
    if not best:
        raise SystemExit(
            f"{args.results_csv}: no parseable result rows (expected the "
            f"summarize_results schema with 'max total time' etc.)")
    configs = sorted({k[:3] for k in best})
    for cfg in configs:
        print(f"config: procs={cfg[0]} aggregators={cfg[1]} "
              f"data_size={cfg[2]}")
        ranked = sorted((k for k in best if k[:3] == cfg),
                        key=lambda k: float(best[k]["max total time"]))
        for k in ranked:
            r = best[k]
            pv = prov.get(best_idx[k])
            tag = f"  [{pv[0]}, {pv[1]}]" if pv else ""
            print(f"  {k[3]:34s} best max total = "
                  f"{float(r['max total time']):.6f} s  "
                  f"(comm_size = {r['max comm']}){tag}")
        print(f"  winner: {ranked[0][3]}")
    return 0


def _run_serve(args) -> int:
    """``serve``: run the persistent aggregation server until a client
    sends a shutdown op (or SIGINT). Prints exactly ONE ready JSON line
    on stdout — the machine-readable attach point (port, pid, backend)
    the load generator parses; everything else goes to stderr."""
    import json as _json

    from tpu_aggcomm.serve import ScheduleServer

    srv = ScheduleServer(
        backend=args.backend, port=args.port, max_batch=args.max_batch,
        batch_window_s=args.batch_window_ms / 1e3,
        max_queue=args.max_queue, max_conns=args.max_conns,
        journal_path=args.journal, metrics_port=args.metrics_port,
        recover=args.recover, predict_root=args.predict_root)
    print(_json.dumps(srv.ready_info()), flush=True)
    try:
        with _tracing(args.trace):
            srv.serve_forever()
    except KeyboardInterrupt:
        srv.stop()
        srv.close()
    st = srv.stats()
    print(f"serve: stopped after {st['completed']} completed / "
          f"{st['errors']} error(s); cache {st['cache']}; "
          f"batch {st['batch']}", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.command == "pt2pt":
        from tpu_aggcomm.harness.pt2pt import pt2pt_statistics
        pt2pt_statistics(max(args.data_size, 1), max(args.ntimes, 1),
                         max(args.runs, 1), chained=args.chained)
        return 0
    if args.command == "tam":
        return _run_tam(args)
    if args.command == "sweep":
        return _run_sweep(args)
    if args.command == "inspect":
        return _run_inspect(args)
    if args.command == "analyze":
        return _run_analyze(args)
    if args.command == "tune":
        return _run_tune(args)
    if args.command == "synth":
        return _run_synth(args)
    if args.command == "pilot":
        return _run_pilot(args)
    if args.command == "serve":
        return _run_serve(args)

    from tpu_aggcomm.harness.runner import ExperimentConfig, run_experiment
    _ensure_synth(args, [args.method])
    nprocs = args.nprocs if args.nprocs is not None \
        else _default_nprocs(args.backend)
    if args.auto:
        _resolve_auto(args, nprocs)
    cfg = ExperimentConfig(
        nprocs=nprocs, cb_nodes=args.cb_nodes, method=args.method,
        data_size=args.data_size, comm_size=args.comm_size, iters=args.iters,
        ntimes=args.ntimes, proc_node=args.proc_node, agg_type=args.agg_type,
        prefix=args.prefix, barrier_type=args.barrier_type,
        backend=args.backend, verify=args.verify,
        results_csv=args.results_csv, profile_rounds=args.profile_rounds,
        chained=args.chained, measured_phases=args.measured_phases,
        xprof=args.xprof, fault=args.fault)
    from tpu_aggcomm.faults import FaultSpecError, RepairError
    try:
        with _tracing(args.trace):
            run_experiment(cfg)
    except (FaultSpecError, RepairError) as e:
        # a malformed spec or an unrepairable fault is a usage error:
        # one line naming the offending token/edge, never a traceback
        raise SystemExit(f"--fault: {e}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
