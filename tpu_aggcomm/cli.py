"""Command-line interface, flag-compatible with the reference ``./test``.

Reference grammar ``"hp:c:m:d:a:i:k:t:r:b:"`` (mpi_test.c:2130-2166) plus
the TPU-framework extensions: ``-n`` rank count (the reference gets it from
``mpiexec -n``), ``--backend``, ``--verify``, ``--profile-rounds``. The
``pt2pt`` subcommand reproduces mpi_sendrecv_test.c (grammar ``hk:d:i:``).

Examples::

    python -m tpu_aggcomm.cli -n 8 -m 1 -a 3 -d 2048 -c 3 -i 2 --backend local --verify
    python -m tpu_aggcomm.cli -n 8 -m 0 -a 3 -d 256 --backend jax_ici
    python -m tpu_aggcomm.cli pt2pt -d 2048 -k 10 -i 100
"""

from __future__ import annotations

import argparse
import sys

from tpu_aggcomm.backends.registry import BACKENDS, DEVICE_FREE_BACKENDS

__all__ = ["main", "build_parser"]


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="tpu_aggcomm",
        description="TPU-native aggregator-communication benchmark "
                    "(capabilities of the reference MPI ./test harness)")
    sub = ap.add_subparsers(dest="command")

    bench = ap  # main command keeps reference flags at top level
    bench.add_argument("-n", "--nprocs", type=int, default=None,
                       help="logical ranks (reference: mpiexec -n; default: "
                            "number of visible devices for device backends, "
                            "32 for the device-free local/native backends)")
    bench.add_argument("-m", dest="method", type=int, default=0,
                       help="method id 0-20 (0 = all; mpi_test.c usage)")
    bench.add_argument("-a", dest="cb_nodes", type=int, default=1,
                       help="number of aggregators (cb_nodes)")
    bench.add_argument("-d", dest="data_size", type=int, default=0,
                       help="message size in bytes")
    bench.add_argument("-c", dest="comm_size", type=int, default=200_000_000,
                       help="max in-flight messages per round (throttle)")
    bench.add_argument("-i", dest="iters", type=int, default=1,
                       help="outer experiment repetitions (fresh buffers)")
    bench.add_argument("-k", dest="ntimes", type=int, default=1,
                       help="timed reps inside one window (no resync)")
    bench.add_argument("-p", dest="proc_node", type=int, default=1,
                       help="ranks per (simulated) node")
    bench.add_argument("-t", dest="agg_type", type=int, default=1,
                       help="aggregator placement policy 0-3")
    bench.add_argument("-r", dest="prefix", type=str, default="",
                       help="per-rank CSV filename prefix")
    bench.add_argument("-b", dest="barrier_type", type=int, default=0,
                       help="barrier mode for m=13 (0 none, 1 per rep, 2 per block)")
    bench.add_argument("--backend", choices=BACKENDS, default="local")
    bench.add_argument("--verify", action="store_true",
                       help="deterministic-fill verification (first-class "
                            "version of the reference's commented-out checks)")
    bench.add_argument("--profile-rounds", action="store_true",
                       help="jax_ici: time each throttle round separately")
    bench.add_argument("--results-csv", default="results.csv")

    pt = sub.add_parser("pt2pt", help="2-rank latency microbenchmark "
                                      "(mpi_sendrecv_test.c)")
    pt.add_argument("-d", dest="data_size", type=int, default=0)
    pt.add_argument("-k", dest="ntimes", type=int, default=0)
    pt.add_argument("-i", dest="runs", type=int, default=0)
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.command == "pt2pt":
        from tpu_aggcomm.harness.pt2pt import pt2pt_statistics
        pt2pt_statistics(max(args.data_size, 1), max(args.ntimes, 1),
                         max(args.runs, 1))
        return 0

    from tpu_aggcomm.harness.runner import ExperimentConfig, run_experiment
    nprocs = args.nprocs
    if nprocs is None:
        if args.backend in DEVICE_FREE_BACKENDS:
            # device-free backends: the reference README example's rank count
            nprocs = 32
        else:
            import jax
            nprocs = len(jax.devices())
    cfg = ExperimentConfig(
        nprocs=nprocs, cb_nodes=args.cb_nodes, method=args.method,
        data_size=args.data_size, comm_size=args.comm_size, iters=args.iters,
        ntimes=args.ntimes, proc_node=args.proc_node, agg_type=args.agg_type,
        prefix=args.prefix, barrier_type=args.barrier_type,
        backend=args.backend, verify=args.verify,
        results_csv=args.results_csv, profile_rounds=args.profile_rounds)
    run_experiment(cfg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
