"""Target folding: measured traffic -> concrete improvement targets.

The planner's input is evidence other subsystems already record — the
workload profiler's seeded hot-shape/burstiness proposals
(``obs/workload.py:_detect``) and the serve layer's per-shape_key
counters (the ``stats`` op) — and its output is a deterministic,
ranked list of (shape, incumbent method) campaign targets.
:func:`fold_targets` is a PURE function of (proposals, per_shape): the
same profile + the same stats snapshot fold to the byte-identical
target list, which is what lets ``pilot --replay`` and
``obs/regress.validate_pilot`` re-derive it from the artifact's own
rows. jax-free (core + serve/protocol only — the checker discipline:
planning must work where a wedged tunnel hangs ``import jax``).
"""

from __future__ import annotations

__all__ = ["PilotError", "shape_stats_key", "fold_targets"]


class PilotError(ValueError):
    """Unusable pilot input (malformed proposal shape, unknown method),
    with the offending field named."""


def _require_shape(shape, where: str) -> dict:
    if not isinstance(shape, dict):
        raise PilotError(f"{where}: proposal shape must be the serve "
                         f"journal's shape-fields dict, got {shape!r}")
    for f in ("method", "nprocs", "cb_nodes", "comm_size"):
        if not isinstance(shape.get(f), int):
            raise PilotError(f"{where}: proposal shape is missing an "
                             f"integer {f!r} field ({shape!r})")
    return shape


def shape_stats_key(shape: dict, backend: str) -> str | None:
    """The per-shape stats key the server uses — ``repr(shape_key)`` of
    the compiled (and, under a fault spec, repaired) schedule. Built
    through the SAME ``request_schedule`` path as the server
    (serve/protocol.py), so the planner joins stats rows by identity,
    never by guesswork. None when the shape no longer compiles (a
    stats row we cannot join is skipped, not fabricated)."""
    from tpu_aggcomm.core.schedule import schedule_shape_key
    from tpu_aggcomm.serve.protocol import parse_request, request_schedule
    try:
        req = parse_request(dict(shape))
        return repr(schedule_shape_key(request_schedule(req)))
    except Exception:  # lint: broad-ok (stats join is advisory: an uncompilable recorded shape means no stats row, never a planner death)
        return None


def _direction_of(method: int) -> str:
    from tpu_aggcomm.core.methods import METHODS
    spec = METHODS.get(method)
    if spec is None:
        raise PilotError(
            f"proposal names method {method}, which is not registered "
            f"(a synthesized id needs --synth-root to re-register the "
            f"committed winner first)")
    return spec.direction.value


def fold_targets(profile: dict, per_shape: dict | None = None
                 ) -> list[dict]:
    """Fold the profile's proposals (+ optional per-shape serve stats)
    into ranked campaign targets.

    One target per (kind, shape signature) — a shape that is both hot
    and bursty gets BOTH a tune-field target and a synth-augmented
    target (different campaign recipes). Ranking: measured latency mass
    first (the per-shape ``latency_sum`` from serve ``stats``, largest
    first — time spent is time winnable), proposal order as the
    deterministic tie-break."""
    import json as _json

    from tpu_aggcomm.tune.space import Candidate

    proposals = profile.get("proposals") or []
    per_shape = per_shape or {}
    targets: list[dict] = []
    seen: set[tuple] = set()
    for i, p in enumerate(proposals):
        shape = _require_shape(p.get("shape"), f"proposal[{i}]")
        kind = p.get("kind")
        dedup = (kind, _json.dumps(shape, sort_keys=True),
                 p.get("backend"))
        if dedup in seen:
            continue
        seen.add(dedup)
        backend = p.get("backend") or "jax_sim"
        incumbent = Candidate(method=shape["method"],
                              cb_nodes=shape["cb_nodes"],
                              comm_size=shape["comm_size"],
                              agg_type=shape.get("agg_type", 0))
        key = shape_stats_key(shape, backend)
        stats = per_shape.get(key) if key is not None else None
        if stats is not None and not isinstance(stats, dict):
            raise PilotError(f"per_shape[{key!r}] must be a counter "
                             f"dict, got {stats!r}")
        targets.append({
            "index": i, "kind": kind, "shape": dict(shape),
            "backend": backend,
            "incumbent_cid": incumbent.cid,
            "direction": _direction_of(shape["method"]),
            "reason": p.get("reason"),
            "stats_key": key,
            "stats": dict(stats) if stats else None,
        })
    # largest measured latency mass first; proposal order breaks ties
    targets.sort(key=lambda t: (-(t["stats"] or {}).get("latency_sum",
                                                        0.0),
                                t["index"]))
    for rank, t in enumerate(targets):
        t["rank"] = rank
    return targets
