"""PILOT_r*.json — the committed autopilot artifact (schema pilot-v1).

One artifact is one complete control-loop pass: the journal basenames
tailed, the workload profile's proposals, the serve layer's per-shape
stats snapshot (the ranking evidence), the folded targets, every
campaign (search + race + win CI, sample-complete), every promotion/
demotion DECISION with the server's response recorded as evidence, and
the promotion records that were actually applied.

Determinism contract (the tune/SYNTH/WORKLOAD/WATCH discipline): the
journals + the recorded evidence blocks (per-shape snapshot, installed
promotions, swap/demote responses) + the seed re-derive the ENTIRE
decision trace — profile, targets, search, race verdicts, win CIs and
every action — byte-for-byte, jax-free (:func:`replay_pilot`, the
ci_tier1.sh gate). The server's responses are EVIDENCE (they happened;
a replay cannot re-contact a dead server), but the decision LOGIC over
that evidence re-derives — so a promotion the artifact's own numbers
contradict is a MISMATCH, never quietly cited.
"""

from __future__ import annotations

import glob
import json
import os
import time

from tpu_aggcomm.pilot.campaign import replay_campaign, run_campaign
from tpu_aggcomm.pilot.plan import PilotError, fold_targets
from tpu_aggcomm.pilot.promote import make_promotion_record

__all__ = ["PILOT_SCHEMA", "next_pilot_path", "mark_skips",
           "demotion_rows", "derive_decision", "run_pilot",
           "write_pilot", "load_pilot", "replay_pilot", "render_pilot"]

PILOT_SCHEMA = "pilot-v1"

#: Envelope keys excluded from the replay comparison (environment-
#: dependent by design; everything else must re-derive byte-for-byte).
_ENVELOPE = ("schema", "manifest", "created_unix")


def next_pilot_path(root: str = ".") -> str:
    """First unused ``PILOT_rNN.json`` under ``root`` (NN = 01, 02, …)."""
    taken = set(os.path.basename(p)
                for p in glob.glob(os.path.join(root, "PILOT_r*.json")))
    n = 1
    while f"PILOT_r{n:02d}.json" in taken:
        n += 1
    return os.path.join(root, f"PILOT_r{n:02d}.json")


def _shape_json(shape) -> str:
    return json.dumps(shape, sort_keys=True)


def mark_skips(targets: list[dict], installed: list[dict]) -> list[dict]:
    """Mark targets whose shape already carries an installed promotion
    (campaigning a shape mid-promotion would race against a method that
    no longer serves it). Pure function of (targets, installed) — part
    of the replayable decision trace."""
    promoted = {_shape_json((p.get("record") or {}).get("shape"))
                for p in installed}
    out = []
    for t in targets:
        t = dict(t)
        t["skipped"] = ("already-promoted"
                        if _shape_json(t["shape"]) in promoted else None)
        out.append(t)
    return out


def demotion_rows(installed: list[dict], rows: list[dict], *,
                  seed: int = 0) -> list[dict]:
    """The demotion half of the loop, derived (no server contact): for
    every installed promotion, a seeded changepoint detection
    (``obs/watch.py:detect_changepoint`` — the watchtower verdict
    kernel) over the promoted shape's completed request walls in rid
    order. A CONFIRMED step UP after the promotion is a regression
    verdict and the action is ``demote`` with the watch evidence named;
    anything else holds. Pure function of (installed, rows, seed)."""
    from tpu_aggcomm.obs.watch import detect_changepoint

    out: list[dict] = []
    for p in installed:
        record = p.get("record") or {}
        sig = _shape_json(record.get("shape"))
        walls = [r["wall_s"] for r in rows
                 if r.get("status") == "done"
                 and _shape_json(r.get("shape")) == sig
                 and isinstance(r.get("wall_s"), (int, float))]
        det = detect_changepoint(walls, seed=seed)
        if det is not None and det["direction"] == "up":
            action = "demote"
            reason = (f"watch: confirmed request-wall step up "
                      f"{det['delta_rel'] * 100.0:+.1f}% at index "
                      f"{det['index']}/{det['n']} (seeded changepoint, "
                      f"CI [{det['ci_rel'][0] * 100.0:.1f}%, "
                      f"{det['ci_rel'][1] * 100.0:.1f}%]) after "
                      f"promotion m{record.get('old_method')} -> "
                      f"m{record.get('new_method')}")
        else:
            action = "hold"
            reason = ("watch: no confirmed request-wall regression on "
                      "the promoted shape"
                      if det is None else
                      f"watch: confirmed step is DOWN "
                      f"({det['delta_rel'] * 100.0:+.1f}%) — the "
                      f"promotion is helping")
        out.append({"seq": p.get("seq"), "record": record,
                    "n_walls": len(walls), "detection": det,
                    "action": action, "reason": reason})
    return out


def derive_decision(target: dict, campaign: dict, *, mode: str,
                    fingerprint: str, swap: dict | None) -> dict:
    """The one decision arithmetic — run_pilot applies it live and
    replay/validate re-run it over the recorded evidence. ``swap`` is
    the server's recorded response (None when nothing was attempted)."""
    winner = campaign["winner"]["cid"]
    d = {"target_index": target["index"],
         "incumbent_cid": campaign["incumbent_cid"],
         "winner_cid": winner,
         "win_ci_pct": campaign["win_ci_pct"],
         "improved": campaign["improved"],
         "record": None, "swap": swap}
    if winner == campaign["incumbent_cid"]:
        d["action"] = "keep-incumbent"
    elif not campaign["improved"]:
        d["action"] = "no-win"
    else:
        d["record"] = make_promotion_record(target, campaign,
                                            fingerprint=fingerprint)
        if mode != "live":
            d["action"] = "would-promote"
        elif swap is None:
            d["action"] = "swap-unattempted"
        elif swap.get("ok") and swap.get("verified") is True:
            d["action"] = "promote"
        elif swap.get("ok"):
            d["action"] = "verify-failed"
        else:
            d["action"] = "swap-refused"
    return d


def _default_sampler_factory(*, synthetic: str | None, seed: int,
                             batch_trials: int):
    """Per-target sampler: the seeded synthetic model when a spec is
    given (jax-free smoke), else tune/measure.py's fresh-sample jax_sim
    sampler — the one jax door, guarded against serve contention."""
    def factory(target: dict):
        if synthetic is not None:
            from tpu_aggcomm.tune.race import make_synthetic_sampler
            return make_synthetic_sampler(synthetic, seed=seed,
                                          batch_trials=batch_trials)
        from tpu_aggcomm.tune.measure import make_jax_sim_sampler
        shape = target["shape"]
        return make_jax_sim_sampler(
            nprocs=shape["nprocs"],
            data_size=shape.get("data_size", 2048),
            proc_node=shape.get("proc_node", 1),
            batch_trials=batch_trials)
    return factory


def _snapshot_journals(journals: list[str]):
    """Freeze the tailed journal lines before profiling. The pilot's
    decisions must re-derive from EXACTLY the bytes it read, but the
    serve journal keeps growing underneath it — the swap op's verify
    leg itself appends records. So: read each journal once, drop an
    in-flight torn final line (it would complete by commit time and
    poison the replay), and profile the frozen copy; the artifact
    records the basename + consumed line count and :func:`replay_pilot`
    truncates the committed journal to the same prefix. Returns
    ``(meta, tmpdir, paths)`` — caller removes ``tmpdir``."""
    import tempfile
    names = [os.path.basename(p) for p in journals]
    if len(set(names)) != len(names):
        raise PilotError(f"journal basenames must be distinct (replay "
                         f"resolves by basename): {names}")
    tmp = tempfile.mkdtemp(prefix="tpu-aggcomm-pilot-")
    meta, paths = [], []
    for p, name in zip(journals, names):
        with open(p, encoding="utf-8") as fh:
            lines = fh.readlines()
        if lines and not lines[-1].endswith("\n"):
            lines = lines[:-1]
        sp = os.path.join(tmp, name)
        with open(sp, "w", encoding="utf-8") as fh:
            fh.writelines(lines)
        meta.append({"name": name, "lines": len(lines)})
        paths.append(sp)
    return meta, tmp, paths


def run_pilot(journals, *, seed: int = 0, serve_port: int | None = None,
              serve_host: str = "127.0.0.1", dry_run: bool = False,
              synthetic: str | None = None, sampler_factory=None,
              params: dict | None = None,
              params_source: str | None = None, max_batches: int = 6,
              batch_trials: int = 3, alpha: float = 0.05,
              n_boot: int = 2000, id_base: int | None = None,
              log=None) -> dict:
    """One control-loop pass: profile -> (demote?) -> fold ->
    campaigns -> decisions (-> swap). Returns the pilot-v1 body (no
    envelope — :func:`write_pilot` adds it)."""
    from tpu_aggcomm.obs.workload import profile_journal

    say = log or (lambda *_: None)
    journals = list(journals)
    if not journals:
        raise PilotError("pilot needs at least one serve journal to tail")
    journals_meta, snap_dir, snap_paths = _snapshot_journals(journals)
    try:
        profile = profile_journal(snap_paths, seed=seed)
    finally:
        import shutil
        shutil.rmtree(snap_dir, ignore_errors=True)
    say(f"pilot: profiled {profile['requests']['admitted']} request(s) "
        f"from {len(journals)} journal(s), "
        f"{len(profile['proposals'])} proposal(s)")

    mode = "live" if serve_port is not None and not dry_run else "dry-run"
    per_shape = None
    installed: list[dict] = []
    client = None
    if serve_port is not None:
        from tpu_aggcomm.serve.protocol import ServeClient
        client = ServeClient(serve_port, host=serve_host)
        stats = client.stats()
        fingerprint = str(stats.get("fingerprint"))
        per_shape = stats.get("per_shape") or {}
        installed = stats.get("promotions") or []
    else:
        from tpu_aggcomm.obs import ledger
        from tpu_aggcomm.tune.cache import manifest_fingerprint
        fingerprint = manifest_fingerprint(ledger.manifest())

    try:
        demotions = demotion_rows(installed, profile["per_request"],
                                  seed=seed)
        for row in demotions:
            if row["action"] == "demote" and mode == "live":
                say(f"pilot: demoting promotion seq {row['seq']} — "
                    f"{row['reason']}")
                row["outcome"] = client.demote(row["record"],
                                               row["reason"])
            else:
                row["outcome"] = None

        targets = mark_skips(fold_targets(profile, per_shape), installed)
        active = [t for t in targets if t["skipped"] is None]
        say(f"pilot: {len(targets)} target(s), {len(active)} active "
            f"({mode})")
        factory = sampler_factory or _default_sampler_factory(
            synthetic=synthetic, seed=seed, batch_trials=batch_trials)
        campaigns: list[dict] = []
        decisions: list[dict] = []
        for t in active:
            c = run_campaign(t, factory(t), seed=seed,
                             max_batches=max_batches,
                             batch_trials=batch_trials, alpha=alpha,
                             n_boot=n_boot, params=params,
                             params_source=params_source,
                             id_base=id_base, log=log)
            campaigns.append(c)
            swap = None
            if (mode == "live" and c["improved"]
                    and c["winner"]["cid"] != c["incumbent_cid"]):
                record = make_promotion_record(t, c,
                                               fingerprint=fingerprint)
                say(f"pilot: promoting {record['old_cid']} -> "
                    f"{record['new_cid']} (win CI "
                    f"[{record['win_ci_pct'][0]:.1f}%, "
                    f"{record['win_ci_pct'][1]:.1f}%])")
                swap = client.swap(record)
            d = derive_decision(t, c, mode=mode,
                                fingerprint=fingerprint, swap=swap)
            decisions.append(d)
            say(f"pilot: decision for {d['incumbent_cid']}: "
                f"{d['action']}")
    finally:
        if client is not None:
            client.close()

    return {
        "seed": int(seed), "mode": mode,
        "journals": journals_meta,
        "synthetic": synthetic, "fingerprint": fingerprint,
        "requests": profile["requests"],
        "proposals": profile["proposals"],
        "per_shape": per_shape,
        "installed_promotions": installed,
        "demotions": demotions,
        "targets": targets,
        "campaigns": campaigns,
        "decisions": decisions,
        "promotions": [d["record"] for d in decisions
                       if d["action"] == "promote"],
        "inputs": {"params": params, "params_source": params_source},
        "race_opts": {"max_batches": int(max_batches),
                      "batch_trials": int(batch_trials),
                      "alpha": float(alpha), "n_boot": int(n_boot)},
        "problems": profile["problems"],
    }


def write_pilot(path: str, body: dict) -> dict:
    """Write one pilot-v1 artifact atomically (manifest records env var
    NAMES only, the ledger discipline) and return the blob."""
    from tpu_aggcomm.obs import atomic_write, ledger
    blob = dict(body)
    blob["schema"] = PILOT_SCHEMA
    blob["manifest"] = ledger.manifest()
    blob["created_unix"] = time.time()
    with atomic_write(path) as fh:
        json.dump(blob, fh, indent=1)
        fh.write("\n")
    return blob


def load_pilot(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def _jeq(a, b) -> bool:
    return json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def replay_pilot(path: str) -> dict:
    """Re-derive a committed PILOT_r*.json from the journal basenames it
    records (resolved next to the artifact) + its recorded evidence
    blocks + seed, and byte-compare minus the envelope. ``{"verdict":
    "REPRODUCED" | "MISMATCH", "problems": [...]}`` — jax-free."""
    from tpu_aggcomm.obs.workload import profile_journal

    blob = load_pilot(path)
    problems: list[str] = []
    if blob.get("schema") != PILOT_SCHEMA:
        return {"verdict": "MISMATCH",
                "problems": [f"schema {blob.get('schema')!r} != "
                             f"{PILOT_SCHEMA!r}"]}
    root = os.path.dirname(os.path.abspath(path))
    import shutil
    import tempfile
    snap_dir = tempfile.mkdtemp(prefix="tpu-aggcomm-pilot-")
    journals = []
    try:
        for ent in blob.get("journals") or []:
            if not isinstance(ent, dict) or "name" not in ent \
                    or "lines" not in ent:
                problems.append(f"journal entry {ent!r} must be "
                                f"{{name, lines}}")
                continue
            name, n = ent["name"], int(ent["lines"])
            p = os.path.join(root, name)
            if not os.path.exists(p):
                problems.append(f"recorded journal {name!r} not found "
                                f"next to the artifact ({root})")
                continue
            with open(p, encoding="utf-8") as fh:
                lines = fh.readlines()
            if len(lines) < n:
                problems.append(
                    f"journal {name!r} has {len(lines)} line(s) but the "
                    f"artifact consumed {n} — the journal shrank after "
                    f"the pilot pass")
                continue
            sp = os.path.join(snap_dir, name)
            with open(sp, "w", encoding="utf-8") as fh:
                fh.writelines(lines[:n])
            journals.append(sp)
        if problems:
            return {"verdict": "MISMATCH", "problems": problems}

        seed = int(blob.get("seed", 0))
        profile = profile_journal(journals, seed=seed)
    finally:
        shutil.rmtree(snap_dir, ignore_errors=True)
    installed = blob.get("installed_promotions") or []
    inputs = blob.get("inputs") or {}

    # pure derivations re-run from streams + recorded evidence
    try:
        targets = mark_skips(fold_targets(profile,
                                          blob.get("per_shape")),
                             installed)
    except PilotError as e:
        return {"verdict": "MISMATCH",
                "problems": [f"target fold replay failed: {e}"]}
    demos = demotion_rows(installed, profile["per_request"], seed=seed)
    for i, row in enumerate(demos):
        rec = (blob.get("demotions") or [])
        row["outcome"] = rec[i].get("outcome") if i < len(rec) else None

    rederived = {
        "requests": profile["requests"],
        "proposals": profile["proposals"],
        "targets": targets,
        "demotions": demos,
        "problems": profile["problems"],
    }
    for k, v in rederived.items():
        if not _jeq(v, blob.get(k)):
            problems.append(f"key {k!r} does not re-derive from the "
                            f"recorded streams")

    # campaigns: internal consistency (search from config+seed, race
    # from samples, win CI + improved from the recorded numbers)
    campaigns = blob.get("campaigns") or []
    for i, c in enumerate(campaigns):
        for p in replay_campaign(c, params=inputs.get("params"),
                                 params_source=inputs.get(
                                     "params_source")):
            problems.append(f"campaign[{i}]: {p}")

    # decisions: the one decision arithmetic over recorded evidence
    active = [t for t in targets if t["skipped"] is None]
    decisions_rec = blob.get("decisions") or []
    if len(active) != len(campaigns) or len(campaigns) \
            != len(decisions_rec):
        problems.append(
            f"{len(active)} active target(s) vs {len(campaigns)} "
            f"campaign(s) vs {len(decisions_rec)} decision(s) — the "
            f"trace is truncated")
    else:
        decisions = []
        broken = False
        for t, c, d_rec in zip(active, campaigns, decisions_rec):
            try:
                decisions.append(derive_decision(
                    t, c, mode=blob.get("mode", "dry-run"),
                    fingerprint=str(blob.get("fingerprint")),
                    swap=(d_rec or {}).get("swap")))
            except Exception as e:  # lint: broad-ok (replay must name a malformed decision, not die on it)
                broken = True
                problems.append(f"decision for {c.get('incumbent_cid')} "
                                f"does not re-derive: "
                                f"{type(e).__name__}: {e}")
        if not broken:
            if not _jeq(decisions, decisions_rec):
                problems.append("key 'decisions' does not re-derive "
                                "from the campaigns + recorded swap "
                                "evidence")
            promoted = [d["record"] for d in decisions
                        if d["action"] == "promote"]
            if not _jeq(promoted, blob.get("promotions")):
                problems.append("key 'promotions' is not exactly the "
                                "promote-decision records")

    return {"verdict": "REPRODUCED" if not problems else "MISMATCH",
            "problems": problems}


def render_pilot(body: dict) -> str:
    """Human summary (stderr/stdout; the artifact carries the machine
    form)."""
    req = body.get("requests") or {}
    lines = [f"pilot pass ({body.get('mode')}): "
             f"{req.get('admitted', '?')} request(s) profiled, "
             f"{len(body.get('proposals') or [])} proposal(s), "
             f"{len(body.get('targets') or [])} target(s)"]
    for row in body.get("demotions") or []:
        lines.append(f"  demotion check seq {row.get('seq')}: "
                     f"{row['action']} — {row['reason']}")
    for d in body.get("decisions") or []:
        ci = d.get("win_ci_pct")
        ci_txt = (f", win CI [{ci[0]:.1f}%, {ci[1]:.1f}%]"
                  if ci else "")
        lines.append(f"  {d['incumbent_cid']} -> {d['winner_cid']}: "
                     f"{d['action']}{ci_txt}")
    if not body.get("decisions"):
        lines.append("  no campaigns ran (no active targets)")
    return "\n".join(lines)
