"""One campaign: candidates -> race -> measured win vs the incumbent.

A campaign takes one folded target (pilot/plan.py) and produces the
evidence a promotion needs, under the PR 15 synthesis discipline
unchanged:

- **Candidates** — the dispatched reference field of the incumbent's
  direction, references first (ties break toward the field, so a
  challenger never wins on order). A ``bursty-arrivals`` target
  additionally runs the seeded synth search at the target cell
  (checker refutations = hard pruning, static ``-c`` conformance, cost
  model only ORDERS — synth/search.py verbatim) and registers the
  finalists into the reserved id range before racing them.
- **Race** — ``tune.race.race`` on FRESH samples (the caller supplies
  the sampler: tune/measure.py's jax_sim sampler for measured runs —
  the one jax door — or the seeded synthetic sampler for smoke), with
  seeded-bootstrap eliminations.
- **Win CI** — the promotion evidence is a DIRECT seeded-bootstrap CI
  of the winner's pooled samples vs the incumbent's
  (``obs.metrics.bootstrap_delta_ci``, the regression-gate kernel) —
  never an elimination side-effect. ``improved`` is True only when the
  winner differs from the incumbent AND the CI excludes zero.

Everything lands in the campaign row; :func:`replay_campaign`
re-derives the search block from (config, seed, params), the race
verdict from the recorded samples and the win CI + improved flag from
the recorded numbers — byte-for-byte, jax-free.
"""

from __future__ import annotations

import json

from tpu_aggcomm.tune import race as race_mod
from tpu_aggcomm.tune.space import Candidate, parse_cid

__all__ = ["CampaignError", "run_campaign", "replay_campaign"]

#: Search knobs for campaign-embedded synthesis — smaller than the
#: offline `cli synth` defaults (a campaign prices many targets per
#: pilot pass), recorded in the search block so replay re-runs the
#: same search.
SEARCH_OPTS = {"init": 16, "mutate_rounds": 2, "beam": 3, "top_k": 2}


class CampaignError(ValueError):
    """Unusable campaign input (no candidates, a target whose incumbent
    cannot be raced), with the field named."""


def _pooled(samples: dict, cid: str) -> list[float]:
    return [x for b in samples.get(cid) or [] for x in b]


def _win_ci(samples: dict, winner: str, incumbent: str, *,
            alpha: float, seed: int, n_boot: int) -> list[float] | None:
    """The promotion evidence: CI on the incumbent's relative slowdown
    vs the winner (positive = incumbent slower = winner's win), in
    percent. None when the winner IS the incumbent."""
    from tpu_aggcomm.obs.metrics import bootstrap_delta_ci
    if winner == incumbent:
        return None
    lo, hi = bootstrap_delta_ci(_pooled(samples, winner),
                                _pooled(samples, incumbent),
                                relative=True, alpha=alpha, seed=seed,
                                n_boot=n_boot)
    return [lo * 100.0, hi * 100.0]


def _candidates(target: dict, registration: dict) -> list[str]:
    """Race order: reference field first (method-id order), synthesized
    finalists last, and the incumbent prepended when it is in neither
    (a TAM or an unregistered-synth incumbent must still be raced —
    a win over an absent incumbent is not a win)."""
    from tpu_aggcomm.synth.artifact import reference_methods

    inc = parse_cid(target["incumbent_cid"])
    cell = dict(cb_nodes=inc.cb_nodes, comm_size=inc.comm_size,
                agg_type=inc.agg_type)
    methods = reference_methods(target["direction"])
    methods += sorted(int(k) for k in registration)
    cids = [Candidate(method=m, **cell).cid for m in methods]
    if target["incumbent_cid"] not in cids:
        cids.insert(0, target["incumbent_cid"])
    if len(cids) < 2:
        raise CampaignError(
            f"target {target['incumbent_cid']}: only {len(cids)} "
            f"candidate(s) at this cell — nothing to race")
    return cids


def run_campaign(target: dict, sampler, *, seed: int = 0,
                 max_batches: int = 6, batch_trials: int = 3,
                 alpha: float = 0.05, n_boot: int = 2000,
                 params: dict | None = None,
                 params_source: str | None = None,
                 id_base: int | None = None, log=None) -> dict:
    """Run one campaign and return its artifact row. ``sampler`` follows
    the tuner contract (``sampler(cid, batch) -> [seconds]``)."""
    from tpu_aggcomm.synth.register import (SYNTH_ID_BASE,
                                            register_composition,
                                            registered_synth_ids)
    from tpu_aggcomm.synth.search import search

    say = log or (lambda *_: None)
    shape = target["shape"]
    sr = None
    registration: dict[str, dict] = {}
    base = None
    if target.get("kind") == "bursty-arrivals":
        sr = search(nprocs=shape["nprocs"], cb_nodes=shape["cb_nodes"],
                    comm_size=shape["comm_size"],
                    data_size=shape.get("data_size", 2048),
                    proc_node=shape.get("proc_node", 1),
                    agg_type=shape.get("agg_type", 0),
                    direction=target["direction"], seed=seed,
                    params=params, params_source=params_source,
                    **SEARCH_OPTS)
        say(f"pilot: campaign {target['incumbent_cid']}: searched "
            f"{sr['evaluated']}/{sr['space_size']} compositions, "
            f"{len(sr['finalists'])} finalist(s)")
        base = id_base if id_base is not None else \
            max([SYNTH_ID_BASE] + registered_synth_ids()) + 1
        for i, canon in enumerate(sr["finalists"]):
            spec = register_composition(canon, method_id=base + i,
                                        direction=target["direction"])
            registration[str(spec.method_id)] = {
                "composition": canon,
                "direction": target["direction"], "name": spec.name}

    cids = _candidates(target, registration)
    say(f"pilot: campaign {target['incumbent_cid']}: racing "
        f"{len(cids)} candidate(s), seed {seed}")
    res = race_mod.race(cids, sampler, max_batches=max_batches,
                        alpha=alpha, seed=seed, n_boot=n_boot)
    race_rec = {"seed": int(seed), "alpha": float(alpha),
                "n_boot": int(n_boot), "max_batches": int(max_batches),
                "batch_trials": int(batch_trials), "order": cids,
                "samples": res.samples,
                "eliminations": res.eliminations, "winner": res.winner,
                "batches_run": res.batches_run,
                "survivors": res.survivors}
    win_ci = _win_ci(res.samples, res.winner, target["incumbent_cid"],
                     alpha=alpha, seed=seed, n_boot=n_boot)
    improved = win_ci is not None and win_ci[0] > 0
    win_mid = parse_cid(res.winner).method
    meds = res.medians()
    winner = {"cid": res.winner, "method_id": win_mid,
              "median_s": meds[res.winner],
              "synthesized": win_mid > SYNTH_ID_BASE}
    if winner["synthesized"] and str(win_mid) in registration:
        winner["composition"] = registration[str(win_mid)]["composition"]
    return {"target_index": target["index"], "seed": int(seed),
            "incumbent_cid": target["incumbent_cid"],
            "direction": target["direction"],
            "search": sr, "registration": registration or None,
            "id_base": base, "race": race_rec, "winner": winner,
            "win_ci_pct": win_ci, "improved": improved}


def replay_campaign(campaign: dict, *, params: dict | None = None,
                    params_source: str | None = None,
                    rerun_search: bool = True) -> list[str]:
    """Re-derive one campaign row from its own record. Returns the
    named problems (empty = REPRODUCED): the search block from
    (config, seed, params) when ``rerun_search``, the race verdict from
    the recorded samples, the win CI from the recorded samples and the
    improvement flag from the recorded CI — the tune/SYNTH replay
    discipline, jax-free."""
    from tpu_aggcomm.synth.search import SearchError, search

    problems: list[str] = []
    sr_rec = campaign.get("search")
    if sr_rec is not None and rerun_search:
        cfg = dict(sr_rec.get("config") or {})
        try:
            sr_new = search(
                nprocs=cfg["nprocs"], cb_nodes=cfg["cb_nodes"],
                comm_size=cfg["comm_size"], data_size=cfg["data_size"],
                proc_node=cfg["proc_node"], agg_type=cfg["agg_type"],
                direction=cfg["direction"],
                seed=campaign.get("seed", 0), params=params,
                params_source=params_source,
                init=sr_rec.get("init", 32),
                mutate_rounds=sr_rec.get("mutate_rounds", 3),
                beam=sr_rec.get("beam", 4),
                top_k=sr_rec.get("top_k", 3),
                fanins=sr_rec.get("fanins", (2, 4)),
                relays=sr_rec.get("relays", (0, 2)))
            if json.loads(json.dumps(sr_new)) != sr_rec:
                for key in sr_new:
                    if json.loads(json.dumps(sr_new[key])) \
                            != sr_rec.get(key):
                        problems.append(f"search.{key} does not "
                                        f"re-derive")
        except (KeyError, SearchError) as e:
            problems.append(f"search replay failed: {e}")
        reg = campaign.get("registration") or {}
        mids = sorted(int(k) for k in reg)
        got = [reg[str(m)]["composition"] for m in mids]
        if got != (sr_rec.get("finalists") or []):
            problems.append(f"registration compositions {got} != "
                            f"search finalists {sr_rec.get('finalists')}")

    rec = campaign.get("race") or {}
    try:
        res = race_mod.replay_record(rec)
        if res.winner != rec.get("winner"):
            problems.append(f"race winner re-derives to {res.winner}, "
                            f"recorded {rec.get('winner')}")
        if json.loads(json.dumps(res.eliminations)) \
                != rec.get("eliminations"):
            problems.append("race eliminations do not re-derive")
    except (KeyError, race_mod.RaceError) as e:
        problems.append(f"race replay failed: {e}")
        return problems

    win_ci = _win_ci(rec.get("samples") or {}, rec.get("winner"),
                     campaign.get("incumbent_cid"),
                     alpha=float(rec.get("alpha", 0.05)),
                     seed=int(rec.get("seed", 0)),
                     n_boot=int(rec.get("n_boot", 2000)))
    if json.loads(json.dumps(win_ci)) != campaign.get("win_ci_pct"):
        problems.append(f"win_ci_pct re-derives to {win_ci}, recorded "
                        f"{campaign.get('win_ci_pct')}")
    improved = win_ci is not None and win_ci[0] > 0
    if improved != bool(campaign.get("improved")):
        problems.append(f"improved re-derives to {improved}, recorded "
                        f"{campaign.get('improved')} — the artifact "
                        f"contradicts its own win CI")
    return problems
