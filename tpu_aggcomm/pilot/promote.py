"""Promotion records: the ONLY currency a live method swap accepts.

A promotion is data, not a side effect: one validated record carries
everything needed to apply it, audit it and REVERSE it — the old and
new method ids and cids, the canonical composition string (when the
winner is synthesized), the seeded-bootstrap win CI, and the manifest
fingerprint of the environment that measured the win. The serve layer's
``swap`` op refuses anything that fails :func:`validate_promotion_record`
(and re-verifies the new method byte-exact through its normal queue
before installing); ``demote`` re-installs the old entry by the SAME
record. jax-free on both sides — the server's control plane and the
planner share this module.
"""

from __future__ import annotations

import json

__all__ = ["PromotionError", "make_promotion_record",
           "validate_promotion_record", "promotion_sig_fields",
           "records_equal"]


class PromotionError(ValueError):
    """A promotion record the server must refuse, with the field named."""


#: (key, required type(s)) — the record schema both sides enforce.
_RECORD_FIELDS = (
    ("shape", dict), ("backend", str),
    ("old_method", int), ("old_cid", str),
    ("new_method", int), ("new_cid", str),
    ("win_ci_pct", list), ("seed", int),
    ("alpha", float), ("n_boot", int),
    ("fingerprint", str),
)


def promotion_sig_fields(record: dict) -> dict:
    """The request-shape dict a promotion overrides — exactly the serve
    journal's admitted ``shape`` block (protocol shape_fields)."""
    return dict(record["shape"])


def make_promotion_record(target: dict, campaign: dict, *,
                          fingerprint: str,
                          artifact: str | None = None) -> dict:
    """Build the record for one improved campaign. Raises
    :class:`PromotionError` when the campaign does not support one
    (no win, winner == incumbent) — a record must never exist without
    its evidence."""
    if not campaign.get("improved"):
        raise PromotionError(
            f"campaign for {campaign.get('incumbent_cid')} is not an "
            f"improvement (win CI {campaign.get('win_ci_pct')}) — no "
            f"promotion record to make")
    winner = campaign["winner"]
    record = {
        "shape": dict(target["shape"]),
        "backend": target["backend"],
        "old_method": int(target["shape"]["method"]),
        "old_cid": campaign["incumbent_cid"],
        "new_method": int(winner["method_id"]),
        "new_cid": winner["cid"],
        "composition": winner.get("composition"),
        "win_ci_pct": list(campaign["win_ci_pct"]),
        "seed": int(campaign["race"]["seed"]),
        "alpha": float(campaign["race"]["alpha"]),
        "n_boot": int(campaign["race"]["n_boot"]),
        "fingerprint": str(fingerprint),
        "artifact": artifact,
    }
    problems = validate_promotion_record(record)
    if problems:
        raise PromotionError("; ".join(problems))
    return record


def validate_promotion_record(record) -> list[str]:
    """Every reason this record must be refused, by name (empty = ok).
    Pure structural+logical validation — fingerprint drift vs a LIVE
    server is the server's own check (it knows its fingerprint)."""
    if not isinstance(record, dict):
        return [f"promotion record must be a JSON object, got "
                f"{type(record).__name__}"]
    problems: list[str] = []
    for key, typ in _RECORD_FIELDS:
        v = record.get(key)
        if isinstance(v, bool) or not isinstance(
                v, (int, float) if typ is float else typ):
            problems.append(f"record field {key!r} must be "
                            f"{typ.__name__}, got {v!r}")
    if problems:
        return problems
    shape = record["shape"]
    for f in ("method", "nprocs", "cb_nodes", "comm_size"):
        if not isinstance(shape.get(f), int):
            problems.append(f"record shape is missing an integer "
                            f"{f!r} field")
    if not problems and shape["method"] != record["old_method"]:
        problems.append(
            f"record shape carries method {shape['method']} but "
            f"old_method is {record['old_method']} — the override must "
            f"key the OLD request shape")
    if record["new_method"] == record["old_method"]:
        problems.append(f"new_method == old_method "
                        f"({record['old_method']}) — a no-op swap is "
                        f"refused, not silently applied")
    ci = record["win_ci_pct"]
    if len(ci) != 2 or not all(isinstance(x, (int, float))
                               and not isinstance(x, bool) for x in ci):
        problems.append(f"win_ci_pct must be [lo, hi] numbers, got "
                        f"{ci!r}")
    elif not ci[0] > 0:
        problems.append(
            f"win CI [{ci[0]:.3f}%, {ci[1]:.3f}%] does not exclude "
            f"zero on the win side — an unproven win never promotes "
            f"(the seeded-bootstrap gate, obs.metrics.bootstrap_delta_ci)")
    from tpu_aggcomm.synth.register import SYNTH_ID_BASE
    comp = record.get("composition")
    if record["new_method"] > SYNTH_ID_BASE:
        if not isinstance(comp, str) or not comp:
            problems.append(
                f"new_method {record['new_method']} is synthesized "
                f"(> SYNTH_ID_BASE={SYNTH_ID_BASE}) but the record "
                f"carries no canonical composition string — an "
                f"unregisterable promotion cannot be reversed or "
                f"re-applied")
    elif comp is not None:
        problems.append(f"new_method {record['new_method']} is a "
                        f"reference id but the record carries "
                        f"composition {comp!r}")
    return problems


def records_equal(a: dict, b: dict) -> bool:
    """Byte-level record identity (demotion must present the SAME
    record that promoted — never a lookalike)."""
    return json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
