"""Autopilot: the online control loop over the serve layer.

The pilot closes the loop the offline tools left open: the workload
profiler (obs/workload.py) *measures* traffic, the watchtower
(obs/watch.py) *judges* it, the synthesizer (tpu_aggcomm/synth/)
*invents* schedules and the tuner (tpu_aggcomm/tune/) *races* them —
the pilot chains those into detection → campaign → promotion, every
step a recorded, replayable artifact (``PILOT_r*.json``, pilot-v1).

Discipline (the whole package is in ``analysis/lint.PURE_PACKAGES``):

- **jax-free planner** — tailing, target folding, campaign search,
  promotion records and artifact replay never import jax; only the
  measured race's sampler goes through ``tune/measure.py``, the one
  declared jax door (and a synthetic sampler covers the smoke path).
- **Advisory until proven** — a campaign winner changes NOTHING until
  (a) its seeded-bootstrap latency win's CI excludes zero and (b) the
  serve layer verified the new method byte-exact against the local
  oracle through its normal queue. Predictions and proposals never
  gate; measured, verified wins do.
- **Named, reversible promotions** — every cache swap traces to a
  validated promotion record (old id, new id, composition, win CI,
  manifest fingerprint) journaled by the server; demotion re-installs
  the old entry by the same record. Zero silent method changes.
"""

from tpu_aggcomm.pilot.artifact import (PILOT_SCHEMA, load_pilot,
                                        next_pilot_path, render_pilot,
                                        replay_pilot, run_pilot,
                                        write_pilot)
from tpu_aggcomm.pilot.campaign import CampaignError, run_campaign
from tpu_aggcomm.pilot.plan import PilotError, fold_targets
from tpu_aggcomm.pilot.promote import (PromotionError,
                                       make_promotion_record,
                                       validate_promotion_record)

__all__ = ["PILOT_SCHEMA", "PilotError", "CampaignError",
           "PromotionError", "fold_targets", "run_campaign",
           "make_promotion_record", "validate_promotion_record",
           "run_pilot", "write_pilot", "replay_pilot",
           "load_pilot", "render_pilot", "next_pilot_path"]
