"""Local event-driven oracle backend.

Executes a Schedule's per-rank op programs in a single process with a
discrete-event scheduler that models MPI semantics precisely enough to serve
as a correctness *and liveness* oracle:

- ISSEND (MPI_Issend) completes only when the matching receive is posted
  (rendezvous — the reference uses Issend deliberately to expose
  congestion, SURVEY.md §5.8).
- ISEND completes immediately (eager).
- RECV/SEND block; SENDRECV posts both sides then blocks on both.
- WAITALL blocks until all listed tokens are complete.
- BARRIER blocks until every rank arrives.
- Messages match by directed (src, dst) pair within one rep — unique in all
  reference methods (tag = src+dst per edge, mpi_test.c:1776).

If no rank can advance and the programs are unfinished, the schedule
deadlocks under MPI semantics: we raise with a per-rank stuck-op dump. This
makes the oracle a schedule-semantics validator, not just a data checker —
something the reference never had (its only guard was "it hung on Theta").
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from tpu_aggcomm.core.pattern import AggregatorPattern, Direction
from tpu_aggcomm.core.schedule import Op, OpKind, Schedule
from tpu_aggcomm.harness.timer import Timer
from tpu_aggcomm.harness.verify import make_send_slabs
from tpu_aggcomm.obs import trace

__all__ = ["LocalBackend", "DeadlockError", "run_schedule_local"]


class DeadlockError(RuntimeError):
    pass


@dataclass
class _RankState:
    prog: list[Op]
    pc: int = 0
    # tokens completed so far
    done: set = field(default_factory=set)
    # pending nonblocking sends: token -> (dst, slot, rendezvous)
    blocked: bool = False


class LocalBackend:
    """Single-process oracle executor. ``run`` returns (recv_bufs, timers)."""

    name = "local"

    def run(self, schedule, *, ntimes: int = 1, iter_: int = 0,
            verify: bool = False):
        from tpu_aggcomm.tam.engine import TamMethod, tam_oracle
        # rep wall time only; phase columns stay zero (the oracle times
        # whole reps, not ops) — recorded so report sidecars can't read
        # the zeros as measured phases
        self.last_provenance = ("local", "total-only")
        p = schedule.pattern
        if isinstance(schedule, TamMethod):
            run_rep = lambda bufs: tam_oracle(schedule, iter_)  # noqa: E731
            recv_bufs = None
        else:
            recv_bufs = _alloc_recv(p, getattr(schedule, "n_staging", 0))
            send_slabs = make_send_slabs(p, iter_)  # same every rep

            def run_rep(bufs):
                _run_one_rep(schedule, bufs, send_slabs)
                return bufs

        self.last_rep_timers = []  # [rep][rank] -> Timer (save_all_timing)
        for rep in range(ntimes):
            with trace.span("local.rep", rep=rep, method=schedule.name):
                t0 = time.perf_counter()
                recv_bufs = run_rep(recv_bufs)
                dt = time.perf_counter() - t0
            self.last_rep_timers.append(
                [Timer(total_time=dt) for _ in range(p.nprocs)])
        if getattr(schedule, "n_staging", 0) and recv_bufs is not None:
            # relay staging rows are repair plumbing, not pattern data —
            # strip them so verify and callers see the healthy layout
            from tpu_aggcomm.harness.verify import recv_slot_counts
            recv_bufs = [b[:c] if c else None
                         for b, c in zip(recv_bufs, recv_slot_counts(p))]
        if verify:
            from tpu_aggcomm.harness.verify import verify_recv
            verify_recv(p, recv_bufs, iter_)
        timers = [Timer() for _ in range(p.nprocs)]
        for rep in self.last_rep_timers:
            for t, rt in zip(timers, rep):
                t += rt
        return recv_bufs, timers


def _alloc_recv(p: AggregatorPattern,
                n_staging: int = 0) -> list[np.ndarray | None]:
    from tpu_aggcomm.harness.verify import recv_slot_counts
    # with staging (dead-link repair), EVERY rank gets the extra rows past
    # its pattern slots — any live rank can be elected relay intermediate
    return [np.zeros((c + n_staging, p.data_size), dtype=np.uint8)
            if c + n_staging else None
            for c in recv_slot_counts(p)]


def _run_one_rep(schedule: Schedule, recv_bufs, send_slabs) -> None:
    p = schedule.pattern
    n = p.nprocs

    if schedule.collective:
        _run_alltoallw(p, send_slabs, recv_bufs)
        return

    states = [_RankState(prog) for prog in schedule.programs]
    # flight recorder: every delivery emits a host-measured instant with
    # its throttle round — the oracle's real per-round boundary events
    # (the compiled backends reconstruct theirs from attribution instead)
    rec = trace.current()
    # fault plumbing (faults/): staging row base per rank for relay hops,
    # and the dead chan-0 edges whose payload the link drops. A REPAIRED
    # schedule has no chan-0 op left on a dead edge (the detour replaced
    # it); an UNREPAIRED faulted schedule loses the message here — eager
    # sends complete but bytes never land (verify fails), rendezvous
    # sends never match (DeadlockError) — which is the injection working.
    n_staging = getattr(schedule, "n_staging", 0)
    stage_base = None
    if n_staging:
        from tpu_aggcomm.harness.verify import recv_slot_counts
        stage_base = recv_slot_counts(p)
    dead_edges: set = set()
    fault = getattr(schedule, "fault", None)
    if fault:
        from tpu_aggcomm.faults.spec import parse_fault
        dead_edges = set(parse_fault(fault).deadlinks)
    # message plumbing, keyed by (src, dst, chan) — chan 0 is the pattern
    # data channel; nonzero channels carry repair relay hops:
    #  sends_posted[key] = (slot, token|None, rendezvous, nbytes, round,
    #                       from_stage)
    #  recvs_posted[key] = (row, token|None)  [row = resolved buffer row]
    sends_posted: dict = {}
    recvs_posted: dict = {}
    delivered: set = set()
    signals_posted: set = set()
    # barriers are SPMD-symmetric: rank r waits at its g-th barrier; release
    # when all n ranks sit at the same generation (guards against mixing
    # distinct barrier instances when ranks run ahead).
    barrier_waiting: dict = {}
    barrier_gen = [0] * n
    in_collective: set = set()

    def try_deliver(key):
        if key in delivered:
            return
        if key in sends_posted and key in recvs_posted:
            src, dst, chan = key
            if chan == 0 and (src, dst) in dead_edges:
                return  # the link drops it: no delivery, no completion
            sslot, stok, rendezvous, nbytes, rnd, from_stage = \
                sends_posted[key]
            rslot, rtok = recvs_posted[key]
            if nbytes > 0:
                if from_stage:
                    # relay forward hop: source bytes come from the relay
                    # rank's staging row (.copy(): both live in recv_bufs)
                    src_bytes = recv_bufs[src][
                        stage_base[src] + sslot].copy()
                else:
                    src_bytes = send_slabs[src][sslot]
                recv_bufs[dst][rslot] = src_bytes
            delivered.add(key)
            if rec is not None:
                rec.instant("local.deliver", src=src, dst=dst,
                            round=rnd, nbytes=nbytes)
            # completion: send token completes (rendezvous satisfied), recv
            # token completes.
            if stok is not None:
                states[src].done.add(stok)
            if rtok is not None:
                states[dst].done.add(rtok)

    def send_complete(key) -> bool:
        return key in delivered

    def recv_complete(key) -> bool:
        return key in delivered

    def step(rank: int) -> bool:
        """Try to advance rank by one op. Returns True if progress was made."""
        st = states[rank]
        if st.pc >= len(st.prog):
            return False
        op = st.prog[st.pc]
        k = op.kind
        if k is OpKind.ISSEND or k is OpKind.ISEND:
            key = (rank, op.peer, op.chan)
            sends_posted[key] = (op.slot, op.token, k is OpKind.ISSEND,
                                 op.nbytes, op.round, op.from_stage)
            if k is OpKind.ISEND:
                # eager: complete at post time; delivery happens at match
                states[rank].done.add(op.token)
            try_deliver(key)
            st.pc += 1
            return True
        if k is OpKind.IRECV:
            key = (op.peer, rank, op.chan)
            row = (stage_base[rank] + op.slot if op.to_stage else op.slot)
            recvs_posted[key] = (row, op.token)
            try_deliver(key)
            st.pc += 1
            return True
        if k is OpKind.SEND:
            # Blocking MPI_Send completes once the message is buffered; for
            # benchmark-sized payloads MPICH sends eagerly, and the reference's
            # sync methods (m=6/7) NEED that: under strict rendezvous their
            # send→recv chains deadlock (verified by this oracle). Model SEND
            # as eager; only Issend keeps rendezvous semantics.
            key = (rank, op.peer, op.chan)
            if key not in sends_posted:
                sends_posted[key] = (op.slot, None, False, op.nbytes,
                                     op.round, op.from_stage)
                try_deliver(key)
            st.pc += 1
            return True
        if k is OpKind.RECV:
            key = (op.peer, rank, op.chan)
            if key not in recvs_posted:
                recvs_posted[key] = (op.slot, None)
                try_deliver(key)
            if recv_complete(key):
                st.pc += 1
                return True
            return False
        if k is OpKind.SENDRECV:
            # The send half is a standard-mode send (eager, like SEND above);
            # the call blocks only until the receive half completes.
            skey = (rank, op.peer, 0)
            rkey = (op.peer2, rank, 0)
            if skey not in sends_posted:
                sends_posted[skey] = (op.slot, None, False, op.nbytes,
                                      op.round, False)
                try_deliver(skey)
            if rkey not in recvs_posted:
                recvs_posted[rkey] = (op.slot2, None)
                try_deliver(rkey)
            if recv_complete(rkey):
                st.pc += 1
                return True
            return False
        if k is OpKind.WAITALL:
            if all(t in st.done for t in op.tokens):
                st.pc += 1
                return True
            return False
        if k is OpKind.BARRIER:
            barrier_waiting[rank] = barrier_gen[rank]
            if len(barrier_waiting) == n:
                gens = set(barrier_waiting.values())
                assert len(gens) == 1, f"barrier generation skew: {gens}"
                for r in list(barrier_waiting):
                    states[r].pc += 1
                    barrier_gen[r] += 1
                barrier_waiting.clear()
                return True
            return False
        if k is OpKind.COPY:
            recv_bufs[rank][op.slot2] = send_slabs[rank][op.slot]
            st.pc += 1
            return True
        if k is OpKind.SIGNAL_SEND:
            signals_posted.add((rank, op.peer))
            if op.token >= 0:
                st.done.add(op.token)  # 0-byte eager Isend completes immediately
            st.pc += 1
            return True
        if k is OpKind.SIGNAL_RECV:
            if (op.peer, rank) in signals_posted:
                signals_posted.discard((op.peer, rank))
                st.pc += 1
                return True
            return False
        if k is OpKind.ALLTOALLW:
            in_collective.add(rank)
            if len(in_collective) == n:
                _run_alltoallw(p, send_slabs, recv_bufs)
                for r in list(in_collective):
                    states[r].pc += 1
                in_collective.clear()
                return True
            return False
        raise AssertionError(f"unknown op kind {k}")

    # round-robin until quiescent
    while True:
        progress = False
        all_done = True
        for rank in range(n):
            while step(rank):
                progress = True
            if states[rank].pc < len(states[rank].prog):
                all_done = False
        if all_done:
            break
        if not progress:
            stuck = {r: str(states[r].prog[states[r].pc])
                     for r in range(n) if states[r].pc < len(states[r].prog)}
            raise DeadlockError(
                f"schedule '{schedule.name}' deadlocks under MPI semantics; "
                f"stuck ops: {dict(list(stuck.items())[:4])}")


def _run_alltoallw(p: AggregatorPattern, send_slabs, recv_bufs) -> None:
    """Dense delivery of the whole pattern (MPI_Alltoallw analog)."""
    agg_index = p.agg_index
    if p.direction is Direction.ALL_TO_MANY:
        for g in p.rank_list:
            g = int(g)
            slot = int(agg_index[g])
            for src in range(p.nprocs):
                recv_bufs[g][src] = send_slabs[src][slot]
    else:
        for rank in range(p.nprocs):
            for i, g in enumerate(p.rank_list):
                recv_bufs[rank][i] = send_slabs[int(g)][rank]


def run_schedule_local(schedule: Schedule, **kw):
    return LocalBackend().run(schedule, **kw)
