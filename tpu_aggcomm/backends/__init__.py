"""Schedule executors.

The ``--backend`` plugin boundary (BASELINE.md north star): every backend
executes the same compiled :class:`~tpu_aggcomm.core.schedule.Schedule` and
returns delivered recv slabs plus per-rank timers.

- ``local``  — single-process event-driven oracle (numpy). Validates
  delivery AND liveness (detects schedule deadlock under rendezvous
  semantics). The correctness reference for every other backend.
- ``jax_ici`` — rounds lowered to masked `lax.ppermute` / `lax.all_to_all`
  steps over a `jax.sharding.Mesh` (ICI on TPU).
- ``pallas_dma`` — one-sided remote-DMA kernels with semaphores, expressing
  Issend rendezvous for the sync/half-sync methods.
- ``native`` — C++ threaded rank runtime (rendezvous queues, real blocking),
  the parity analog of the reference's MPI execution.
"""

from tpu_aggcomm.backends.registry import BACKENDS, get_backend

__all__ = ["BACKENDS", "get_backend"]
