"""Single-chip vectorized backend: every logical rank lives on ONE device.

The reference runs its whole multi-node topology inside one process when no
cluster is available (``static_node_assignment``, lustre_driver_test.c:359-429
— "processes are not necessarily physically placed on different nodes").
This backend is the TPU analog of that strategy at the *execution* level:
the full rank set is carried as the leading axis of on-device arrays, so any
compiled schedule — all 22 methods, every placement policy, the Theta sweep
grid — runs and is *timed* on a single real TPU chip. (The jax_ici /
pallas_dma backends need one device per rank; with one tunneled chip only
this backend exercises the method registry on real hardware.)

Lowering: one throttle round = one gather + one scatter over the rank axis
(``vals = send[srcs, sslots]; recv[dsts, dslots] = vals``) — exactly the
round's message set, nothing dense. Rounds are fenced with
``lax.optimization_barrier`` so XLA cannot fuse or reorder across the ``-c``
boundaries (SURVEY.md §7 hard part (2)); reference MPI_Barrier rounds become
a live reduction over the recv state written to the trash row, keeping the
data dependency a real barrier has. Dense methods (m=5/8 Alltoallw) lower to
the transpose+placement-gather exchange. The semantic difference vs. MPI
(deterministic on-chip data movement instead of per-rank unordered network
completion) is the documented jax-backend trade (core/schedule.py).

Timing: the per-dispatch RPC to a tunneled TPU is ~60-90 ms — far larger
than a rep — so ``run()`` wall times are dispatch-bound there (fine on local
devices/CPU). For honest per-rep numbers on the tunnel, ``measure_per_rep``
chains reps strictly serially inside one program via ``lax.scan`` (rep r+1's
send is derived from rep r's recv, so iterations cannot be fused, hoisted,
or elided) and cancels the fixed dispatch overhead by differencing two rep
counts — the same methodology as bench.py, shared here for every method.
"""

from __future__ import annotations

import statistics
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from tpu_aggcomm.backends.lanes import (lane_layout, lanes_to_bytes,
                                        to_lanes)
from tpu_aggcomm.core.pattern import AggregatorPattern, Direction
from tpu_aggcomm.core.schedule import Schedule
from tpu_aggcomm.harness.attribution import (attribute_rounds,
                                             attribute_total, weights_for)
from tpu_aggcomm.harness.chained import (MAX_MEASURED_ROUNDS,
                                         differenced_per_rep,
                                         differenced_trials)
from tpu_aggcomm.harness.timer import Timer
from tpu_aggcomm.harness.verify import make_send_slabs, recv_slot_counts
from tpu_aggcomm.obs import trace

__all__ = ["JaxSimBackend", "dense_send_lanes"]


def dense_send_lanes(p: AggregatorPattern, iter_: int) -> np.ndarray:
    """Dense (nprocs, n_send_slots, w) send payload in the device lane
    layout — the global-slab-index addressing the rank-axis reps use
    (shared with jax_shard's TAM route, which runs the same rep)."""
    from tpu_aggcomm.harness.verify import slot_shapes
    n_send_slots, _ = slot_shapes(p)
    slabs = make_send_slabs(p, iter_)
    out = np.zeros((p.nprocs, n_send_slots, p.data_size), dtype=np.uint8)
    for r, s in enumerate(slabs):
        if s is not None:
            out[r, :s.shape[0]] = s
    return to_lanes(out, p.data_size)


def _round_tables(schedule: Schedule):
    """Per-round (srcs, sslots, dsts, dslots) int32 arrays + barrier counts.

    Within one round every (src, dst) edge is unique and each receiver slot
    is written by exactly one edge (recv_slot_table is keyed by directed
    pair), so a single scatter per round is exact — and it models what a
    round IS: all of its messages in flight concurrently between two
    Waitall fences (e.g. mpi_test.c:1795-1811).
    """
    from tpu_aggcomm.core.schedule import OpKind

    if getattr(schedule, "n_staging", 0):
        raise ValueError(
            f"schedule {schedule.name!r} carries dead-link relay staging; "
            f"the healthy round tables cannot represent it (the faulted "
            f"lowering builds its own from data_edges_ext)")
    edges = schedule.data_edges()
    rtable = schedule.recv_slot_table()
    rounds = []
    n_rounds = int(edges[:, 4].max()) + 1 if len(edges) else 0
    for r in range(n_rounds):
        sel = edges[edges[:, 4] == r]
        if len(sel) == 0:
            continue
        srcs = sel[:, 0].astype(np.int32)
        dsts = sel[:, 1].astype(np.int32)
        sslots = sel[:, 2].astype(np.int32)
        dslots = np.array([rtable[(int(s), int(d))]
                           for s, d in zip(srcs, dsts)], dtype=np.int32)
        rounds.append((r, srcs, sslots, dsts, dslots))

    from tpu_aggcomm.core.schedule import barrier_rounds_of
    barrier_rounds = barrier_rounds_of(schedule)
    # every METHODS generator attaches barriers to rounds that also move
    # data; a barrier-only round would be silently dropped by the data-edge
    # loop above and its fence lost — fail loudly instead (ADVICE r1)
    kept = {r for r, *_ in rounds}
    orphans = set(barrier_rounds) - kept
    if orphans:
        raise ValueError(
            f"schedule {schedule.name!r} has barrier-only rounds "
            f"{sorted(orphans)} with no data edges; the jax_sim round "
            f"lowering cannot represent a standalone fence")
    return rounds, barrier_rounds


def _scan_lowered(tabs, barrier_rounds) -> bool:
    """THE lowering predicate: many-round barrier-light schedules ride
    one lax.scan, everything else unrolls. ONE definition shared by
    _one_rep, every truncation builder, and run()'s measured-phases
    dispatch — the prefix families difference against the full rep, so
    a drifted copy of this predicate would measure the lowering
    asymmetry instead of the truncated rounds."""
    return (len(tabs) >= 32
            and all(v <= 1 for v in barrier_rounds.values()))


def _tam_tables(tam):
    """Static index maps for the single-chip TAM route (the analog of
    collective_write2's hindexed views, l_d_t.c:848-904: datatype tricks
    become index maps). Three hops over flattened slab arrays:

    P2 staging:   staged[k]    = send_flat[stage_idx[k]]   (gather at proxy)
    P3 exchange:  exch[k]      = staged[exch_idx[k]]       (proxy <-> proxy)
    P4/P5 deliver recv[recv_dst[k], recv_slot[k]] = exch[k]

    Orders mirror tam_oracle's proxy_hold / node_in walks, so the staged
    layout is the aggregate-buffer layout of the reference engine.
    """
    from tpu_aggcomm.tam.engine import TamMethod  # noqa: F401 (typing aid)

    p = tam.pattern
    na = tam.assignment
    if p.direction is Direction.ALL_TO_MANY:
        senders = list(range(p.nprocs))
        nslots = p.cb_nodes
        dest_of = lambda s, i: int(p.rank_list[i])           # noqa: E731
        slot_of = lambda s, i: s                             # noqa: E731
    else:
        senders = [int(r) for r in p.rank_list]
        nslots = p.nprocs
        dest_of = lambda s, i: i                             # noqa: E731
        agg_index = p.agg_index
        slot_of = lambda s, i: int(agg_index[s])             # noqa: E731

    # P2: proxy_hold order — per node, each resident sender's slabs packed
    stage: list[tuple[int, int]] = []
    stage_pos: dict[tuple[int, int], int] = {}
    for node in range(na.nnodes):
        for s in senders:
            if int(na.node_of[s]) != node:
                continue
            for i in range(nslots):
                stage_pos[(s, i)] = len(stage)
                stage.append((s, i))
    stage_idx = np.array([s * nslots + i for (s, i) in stage], dtype=np.int32)

    # P3: node_in order — per destination node, arrivals in proxy_hold order
    exch_idx, recv_dst, recv_slot = [], [], []
    for node in range(na.nnodes):
        for (s, i) in stage:
            d = dest_of(s, i)
            if int(na.node_of[d]) != node:
                continue
            exch_idx.append(stage_pos[(s, i)])
            recv_dst.append(d)
            recv_slot.append(slot_of(s, i))
    return (stage_idx, np.array(exch_idx, dtype=np.int32),
            np.array(recv_dst, dtype=np.int32),
            np.array(recv_slot, dtype=np.int32))


def _apply_round(send, recv, srcs, ss, dsts, ds_, nbar: int,
                 n_recv_slots: int, jdt):
    """One throttle round: gather the round's messages, land them in their
    recv slots, then emit its barriers. A barrier's observable effect is an
    ordering dependency on everyone's state: reduce live recv lanes into
    the trash row so the fence can neither fold nor be DCE'd. Shared by the
    whole-rep program and the profile_rounds segments so the profiled
    decomposition cannot drift from the program it decomposes."""
    vals = send[jnp.asarray(srcs), jnp.asarray(ss)]
    recv = recv.at[jnp.asarray(dsts), jnp.asarray(ds_)].set(vals)
    for _ in range(nbar):
        tok = jnp.sum(recv[:, :n_recv_slots, 0].astype(jnp.int32))
        recv = recv.at[:, n_recv_slots, 0].set(tok.astype(jdt))
    return recv


class JaxSimBackend:
    """Executes schedules on one device with ranks as an array axis."""

    name = "jax_sim"

    def __init__(self, device=None):
        self._device = device
        self._cache: dict = {}
        self._chain_cache: dict = {}   # schedule key -> measured per-rep s
        #: Per-trial differenced seconds behind the last measure_per_rep
        #: result (cache hits included) — sweep scripts thread these into
        #: compare-ready artifacts; None before any chained measurement.
        self.last_samples: list[float] | None = None

    def _dev(self):
        return self._device if self._device is not None else jax.devices()[0]

    # ------------------------------------------------------------------
    def _slots(self, p: AggregatorPattern) -> tuple[int, int]:
        from tpu_aggcomm.harness.verify import slot_shapes
        return slot_shapes(p)

    @staticmethod
    def _words(p: AggregatorPattern):
        """Lane layout for this pattern's slabs (backends/lanes.py)."""
        return lane_layout(p.data_size)

    def one_rep(self, schedule):
        """Public rep builder: rep(send) -> recv, a pure jittable function
        over the dense rank-axis layout (``dense_send_lanes``). External
        consumers: the driver's ``entry()`` and jax_shard's sharded TAM
        route."""
        return self._one_rep(schedule)

    def _one_rep(self, schedule, upto: int | None = None):
        """Build rep(send) -> recv, a pure jittable function.

        ``upto`` truncates the rep to its first ``upto`` throttle rounds
        at FULL fidelity (every kept round gathers and scatters exactly
        as in the whole rep) — the prefix programs ``measure_round_times``
        differences. The lowering choice (scan vs unrolled) is made on
        the FULL round table so every prefix and the full rep share one
        lowering; differencing across lowerings would measure the
        asymmetry, not the dropped rounds."""
        from tpu_aggcomm.tam.engine import TamMethod

        p = schedule.pattern
        n = p.nprocs
        n_send_slots, n_recv_slots = self._slots(p)
        if upto is not None and (isinstance(schedule, TamMethod)
                                 or schedule.collective):
            raise ValueError(
                "round-prefix truncation needs a round-structured "
                "schedule (TAM prefixes are _tam_rep(upto_hop=...); the "
                "dense collectives have no throttle rounds to truncate)")

        if (getattr(schedule, "fault", None)
                or getattr(schedule, "n_staging", 0)) \
                and not (isinstance(schedule, TamMethod)
                         or schedule.collective):
            if upto is not None:
                raise ValueError(
                    "round-prefix truncation is not supported on "
                    "fault-injected schedules (the injected delay work "
                    "and relay rounds are not part of the healthy "
                    "prefix family)")
            return self._one_rep_faulted(schedule)

        if isinstance(schedule, TamMethod):
            return self._tam_rep(schedule)

        if schedule.collective:
            # m=5/8: the whole pattern as one dense exchange — dst-major
            # rows built per rank, exchanged by transpose, scattered into
            # recv slots (the sdispls/rdispls analog; uniform sizes make
            # the zero-masked form exact, mpi_test.c:98)
            agg_index = np.asarray(p.agg_index)
            if p.direction is Direction.ALL_TO_MANY:
                sslot_of, rslot_of = agg_index, np.arange(n)
            else:
                sslot_of, rslot_of = np.arange(n), agg_index
            ndt, jdt, w = self._words(p)
            sslot_c = jnp.asarray(np.maximum(sslot_of, 0), dtype=jnp.int32)
            smask = jnp.asarray((sslot_of >= 0).astype(ndt))[None, :, None]
            rslot_c = jnp.asarray(
                np.where(rslot_of >= 0, rslot_of, n_recv_slots),
                dtype=jnp.int32)

            def rep(send):
                rows = jnp.take(send, sslot_c, axis=1) * smask  # (n, n, w)
                got = jnp.transpose(rows, (1, 0, 2))            # got[d, s]
                recv = jnp.zeros((n, n_recv_slots + 1, w), dtype=jdt)
                return recv.at[:, rslot_c].set(got)

            return rep

        rounds, barrier_rounds = _round_tables(schedule)
        tabs = [(srcs, ss, dsts, ds_)
                for (_r, srcs, ss, dsts, ds_) in rounds]
        round_ids = [r for (r, *_rest) in rounds]

        _, jdt, w = self._words(p)

        # Many-round schedules (n=1024 at c=1 is 1024 throttle rounds)
        # compile O(rounds) when unrolled; pad the per-round tables to a
        # uniform width and drive ONE lax.scan instead — compile cost
        # becomes O(1) in the round count while rounds remain strictly
        # sequential program steps (the scan carry is the fence: iteration
        # k+1 reads iteration k's recv, so XLA cannot fuse or reorder
        # across the -c boundaries). Pad entries scatter into the trash
        # row. Barrier rounds fold in as a selected token write; a round
        # with >1 barriers (no current method emits one) keeps the
        # unrolled path.
        scan_ok = _scan_lowered(tabs, barrier_rounds)
        if scan_ok:
            R = len(tabs)
            E = max(len(srcs) for (srcs, _ss, _ds, _dl) in tabs)
            srcs_t = np.zeros((R, E), dtype=np.int32)
            ss_t = np.zeros((R, E), dtype=np.int32)
            dsts_t = np.zeros((R, E), dtype=np.int32)
            dslt_t = np.full((R, E), n_recv_slots, dtype=np.int32)  # trash
            nbar_t = np.zeros((R,), dtype=np.int32)
            for k, (srcs, ss, dsts, ds_) in enumerate(tabs):
                e = len(srcs)
                srcs_t[k, :e] = srcs
                ss_t[k, :e] = ss
                dsts_t[k, :e] = dsts
                dslt_t[k, :e] = ds_
                nbar_t[k] = barrier_rounds.get(round_ids[k], 0)
            xs = tuple(jnp.asarray(t[:upto] if upto is not None else t)
                       for t in (srcs_t, ss_t, dsts_t, dslt_t, nbar_t))

            def rep(send):
                recv0 = jnp.zeros((n, n_recv_slots + 1, w), dtype=jdt)

                def body(recv, x):
                    srcs, ss, dsts, ds_, nbar = x
                    vals = send[srcs, ss]
                    recv = recv.at[dsts, ds_].set(vals)
                    tok = jnp.sum(recv[:, :n_recv_slots, 0]
                                  .astype(jnp.int32)).astype(jdt)
                    cur = recv[:, n_recv_slots, 0]
                    recv = recv.at[:, n_recv_slots, 0].set(
                        jnp.where(nbar > 0, tok, cur))
                    return recv, ()

                recv, _ = lax.scan(body, recv0, xs, unroll=1)
                return recv

            return rep

        kept = tabs if upto is None else tabs[:upto]

        def rep(send):
            recv = jnp.zeros((n, n_recv_slots + 1, w), dtype=jdt)
            for k, (srcs, ss, dsts, ds_) in enumerate(kept):
                recv = _apply_round(send, recv, srcs, ss, dsts, ds_,
                                    barrier_rounds.get(round_ids[k], 0),
                                    n_recv_slots, jdt)
                if k + 1 < len(kept):
                    send, recv = lax.optimization_barrier((send, recv))
            return recv

        return rep

    def _one_rep_faulted(self, schedule):
        """The faulted-schedule lowering (faults/): same round structure,
        three additions over the healthy ``_one_rep``:

        - **staging rows**: the recv arena grows to ``n_recv_slots + S + 1``
          rows per rank (S relay staging rows from dead-link repair, then
          the trash row); relay hops address them via the
          ``data_edges_ext`` flags — a ``from_stage`` gather reads the
          source rank's staging row of ``recv`` instead of ``send``
          (the relay's forward hop, strictly a later round than the hop
          that filled it, so the sequential-round lowering delivers it
          correctly);
        - **dead-edge masking**: chan-0 edges named dead by an UNREPAIRED
          fault drop out of the tables — the payload is lost and
          ``--verify`` fails, which is the injection demonstrating the
          fault is real (a repaired schedule has no such edge left);
        - **slow-rank work**: after the rounds, each slow rank r runs a
          delay loop of ``faults/inject.delay_iters`` iterations whose
          body reduces r's live send row (data-dependent: XLA cannot
          hoist or fold it) and whose provably-zero parity product lands
          in r's recv state — so chained measurement serializes the
          delay into every rep while the received bytes stay exact.

        Round semantics are untouched: rounds remain fenced sequential
        steps, and ``run()``'s ``[:, :n_recv_slots, :]`` slice drops the
        staging rows before verification."""
        from tpu_aggcomm.core.schedule import barrier_rounds_of
        from tpu_aggcomm.faults.inject import (dead_edge_mask,
                                               slow_iter_table)
        from tpu_aggcomm.faults.spec import parse_fault

        p = schedule.pattern
        n = p.nprocs
        _, n_recv_slots = self._slots(p)
        _, jdt, w = self._words(p)
        S = int(getattr(schedule, "n_staging", 0))
        F = n_recv_slots + S          # trash row; staging rows before it
        spec = parse_fault(getattr(schedule, "fault", None))
        ext = schedule.data_edges_ext()
        ext = ext[dead_edge_mask(ext, spec)]
        barrier_rounds = barrier_rounds_of(schedule)
        rounds = []
        n_rounds = int(ext[:, 4].max()) + 1 if len(ext) else 0
        for r in range(n_rounds):
            sel = ext[ext[:, 4] == r]
            if len(sel) == 0:
                continue
            from_stage = (sel[:, 6] & 1) != 0
            to_stage = (sel[:, 6] & 2) != 0
            rounds.append((
                r,
                sel[:, 0].astype(np.int32),
                np.where(from_stage, n_recv_slots + sel[:, 2],
                         sel[:, 2]).astype(np.int32),
                sel[:, 1].astype(np.int32),
                np.where(to_stage, n_recv_slots + sel[:, 3],
                         sel[:, 3]).astype(np.int32),
                from_stage))
        orphans = set(barrier_rounds) - {r for r, *_ in rounds}
        if orphans:
            raise ValueError(
                f"schedule {schedule.name!r} has barrier-only rounds "
                f"{sorted(orphans)} with no data edges; the jax_sim round "
                f"lowering cannot represent a standalone fence")
        slow = slow_iter_table(spec, n, max(n_rounds, 1))
        slow_ranks = [(r, int(it)) for r, it in enumerate(slow) if it > 0]
        round_ids = [r for (r, *_rest) in rounds]

        def add_slow(send, recv):
            for r, iters in slow_ranks:
                row = send[r, 0].astype(jnp.int32)

                def body(i, acc):
                    return acc + jnp.sum((row + i) % 251)

                tok = lax.fori_loop(0, iters, body, jnp.int32(0))
                # parity(tok) * parity(tok+1) == 0 always, but XLA cannot
                # prove it: the loop survives, the bytes do not change
                delta = ((tok & 1) * ((tok + 1) & 1)).astype(jdt)
                recv = recv.at[r, 0, 0].add(delta)
            return recv

        tabs = [(srcs, ss, dsts, ds_)
                for (_r, srcs, ss, dsts, ds_, _fm) in rounds]
        if _scan_lowered(tabs, barrier_rounds):
            R = len(rounds)
            E = max(len(srcs) for (srcs, _ss, _ds, _dl) in tabs)
            srcs_t = np.zeros((R, E), dtype=np.int32)
            ss_t = np.zeros((R, E), dtype=np.int32)
            dsts_t = np.zeros((R, E), dtype=np.int32)
            dslt_t = np.full((R, E), F, dtype=np.int32)  # pad -> trash
            fm_t = np.zeros((R, E), dtype=bool)
            nbar_t = np.zeros((R,), dtype=np.int32)
            for k, (_r, srcs, ss, dsts, ds_, fm) in enumerate(rounds):
                e = len(srcs)
                srcs_t[k, :e] = srcs
                ss_t[k, :e] = ss
                dsts_t[k, :e] = dsts
                dslt_t[k, :e] = ds_
                fm_t[k, :e] = fm
                nbar_t[k] = barrier_rounds.get(round_ids[k], 0)
            xs = tuple(jnp.asarray(t) for t in
                       (srcs_t, ss_t, dsts_t, dslt_t, fm_t, nbar_t))

            def rep(send):
                recv0 = jnp.zeros((n, F + 1, w), dtype=jdt)

                def body(recv, x):
                    srcs, ss, dsts, ds_, fm, nbar = x
                    vals = jnp.where(fm[:, None], recv[srcs, ss],
                                     send[srcs, ss])
                    recv = recv.at[dsts, ds_].set(vals)
                    tok = jnp.sum(recv[:, :n_recv_slots, 0]
                                  .astype(jnp.int32)).astype(jdt)
                    cur = recv[:, F, 0]
                    recv = recv.at[:, F, 0].set(
                        jnp.where(nbar > 0, tok, cur))
                    return recv, ()

                recv, _ = lax.scan(body, recv0, xs, unroll=1)
                return add_slow(send, recv)

            return rep

        def rep(send):
            recv = jnp.zeros((n, F + 1, w), dtype=jdt)
            for k, (_r, srcs, ss, dsts, ds_, fm) in enumerate(rounds):
                if fm.any():
                    vals = jnp.where(jnp.asarray(fm)[:, None],
                                     recv[jnp.asarray(srcs),
                                          jnp.asarray(ss)],
                                     send[jnp.asarray(srcs),
                                          jnp.asarray(ss)])
                else:
                    vals = send[jnp.asarray(srcs), jnp.asarray(ss)]
                recv = recv.at[jnp.asarray(dsts), jnp.asarray(ds_)].set(vals)
                for _ in range(barrier_rounds.get(round_ids[k], 0)):
                    tok = jnp.sum(recv[:, :n_recv_slots, 0]
                                  .astype(jnp.int32))
                    recv = recv.at[:, F, 0].set(tok.astype(jdt))
                if k + 1 < len(rounds):
                    send, recv = lax.optimization_barrier((send, recv))
            return add_slow(send, recv)

        return rep

    def _key(self, schedule):
        from tpu_aggcomm.core.schedule import schedule_shape_key
        return schedule_shape_key(schedule)

    def _compiled(self, schedule: Schedule):
        key = self._key(schedule)
        if key not in self._cache:
            self._cache[key] = jax.jit(self._one_rep(schedule))
        return self._cache[key]

    def _attr_weights(self, schedule):
        """Attribution weights (harness/attribution.py) — the TimerBucket
        structure the measured wall times are mapped onto."""
        return weights_for(schedule)

    # ------------------------------------------------------------------
    def _global_send(self, p: AggregatorPattern, iter_: int) -> np.ndarray:
        return dense_send_lanes(p, iter_)

    def _to_bytes(self, p: AggregatorPattern, arr: np.ndarray) -> np.ndarray:
        """Device lane layout back to the byte layout the verifier speaks."""
        return lanes_to_bytes(arr, p.data_size)

    def _split_recv(self, p: AggregatorPattern, recv_np: np.ndarray):
        counts = recv_slot_counts(p)
        return [recv_np[r] if counts[r] else None for r in range(p.nprocs)]

    def run(self, schedule, *, ntimes: int = 1, iter_: int = 0,
            verify: bool = False, chained: bool = False,
            profile_rounds: bool = False, measured_phases: bool = False):
        if ntimes < 1:
            raise ValueError("ntimes must be >= 1")
        if chained and profile_rounds:
            raise ValueError("chained and profile_rounds are exclusive "
                             "(one program vs per-round programs)")
        if measured_phases and profile_rounds:
            raise ValueError("measured_phases and profile_rounds are "
                             "exclusive (truncation-differenced split vs "
                             "per-round dispatch timing)")
        if measured_phases and (getattr(schedule, "fault", None)
                                or getattr(schedule, "n_staging", 0)):
            raise ValueError(
                "measured_phases is not supported on fault-injected "
                "schedules (the prefix families decompose the healthy "
                "program; injected delay loops and relay rounds are not "
                "in it) — use --chained timing for faulted runs")
        p = schedule.pattern
        dev = self._dev()
        send_dev = jax.device_put(self._global_send(p, iter_), dev)
        # profile_rounds with a round structure never runs the monolithic
        # program — don't compile it (22 wasted compiles on a method sweep)
        profiled_segs = (self._round_segments(schedule) if profile_rounds
                         else None)
        # "attributed-rounds" only when a real multi-round split was
        # measured — a single segment is whole-rep attribution whatever
        # machinery ran it (same downgrade rule on jax_ici/jax_shard).
        # measured_phases provenance is column-accurate (VERDICT r4
        # item 7b) and finalized below once the round count is known.
        self.last_provenance = (
            self.name,
            "attributed-chained" if chained
            else "attributed-rounds" if (profiled_segs is not None
                                         and len(profiled_segs[0]) > 1)
            else "attributed")
        out = None
        if not (profile_rounds and profiled_segs is not None):
            fn = self._compiled(schedule)
            out = fn(send_dev)
            out.block_until_ready()        # warm-up compile

        timers = [Timer() for _ in range(p.nprocs)]
        self.last_rep_timers = []
        self.last_round_times = []         # [rep] -> [per-round seconds]
        attr_w = self._attr_weights(schedule)
        if measured_phases:
            # multi-round schedules: per-round durations are MEASURED by
            # prefix truncation (measure_round_times); only the split of
            # a round's time among the buckets charged in that round is
            # structural. TAM schedules: the 3-hop relay is the
            # decomposition — per-hop durations measured by the same
            # trick (measure_tam_hops). Single-round schedules keep the
            # 2-way measured post/deliver boundary (measure_phase_split)
            # — there the prefix decomposition is trivial and the
            # gather/scatter boundary is the strictly more informative
            # measurement.
            from tpu_aggcomm.harness.attribution import (
                attribute_measured_split, attribute_round_splits,
                attribute_tam_hops)
            from tpu_aggcomm.tam.engine import TamMethod
            if not (isinstance(schedule, TamMethod)
                    or schedule.collective):
                rounds_tab, bars = _round_tables(schedule)
            if schedule.collective:
                raise ValueError(
                    "measured phases need a round-structured schedule "
                    "(TAM's 3-hop decomposition is measured by "
                    "measure_tam_hops; the dense collectives have none)")
            if isinstance(schedule, TamMethod):
                hops = self.measure_tam_hops(schedule)
                rep_attr = attribute_tam_hops(
                    schedule, hops["p2"], hops["p3"], hops["p4"],
                    weights=attr_w)
                self.last_provenance = (
                    self.name, "measured-hops(P2,P3,P4)+attributed(ranks)")
                self.last_round_times = [
                    [hops["p2"], hops["p3"], hops["p4"]]
                    for _ in range(ntimes)]
            elif (len(rounds_tab) >= 2
                  and not _scan_lowered(rounds_tab, bars)):
                # unrolled multi-round: the FULL 2-D measurement — per
                # round, post AND deliver windows measured
                splits = self.measure_round_splits(schedule)
                rep_attr = attribute_round_splits(schedule, splits,
                                                  weights=attr_w)
                self.last_provenance = (
                    self.name,
                    "measured-rounds(post,deliver)+attributed(waits)")
                self.last_round_times = [
                    [p_ + d_ for (p_, d_) in splits.values()]
                    for _ in range(ntimes)]
            elif len(rt := self.measure_round_times(schedule)) >= 2:
                # deep scan-lowered schedules: per-round totals measured
                rep_attr = attribute_rounds(schedule, rt, weights=attr_w)
                self.last_provenance = (
                    self.name, "measured-rounds+attributed(buckets)")
                self.last_round_times = [list(rt.values())
                                         for _ in range(ntimes)]
            else:
                split = self.measure_phase_split(schedule)
                rep_attr = attribute_measured_split(
                    schedule, split["post"], split["deliver"],
                    weights=attr_w)
                self.last_provenance = (
                    self.name,
                    "measured-split(post,deliver)+attributed(waits)")
            for r, t in enumerate(timers):
                t += Timer.from_array(rep_attr[r].as_array() * ntimes)
            # fresh Timer objects per rep — rep rows must not alias
            self.last_rep_timers = [
                [Timer.from_array(t.as_array()) for t in rep_attr]
                for _ in range(ntimes)]
        elif chained:
            per_rep = self.measure_per_rep(schedule)
            rep_attr = attribute_total(schedule, per_rep, weights=attr_w)
            for r, t in enumerate(timers):
                t += Timer.from_array(rep_attr[r].as_array() * ntimes)
            self.last_rep_timers = [
                [Timer.from_array(t.as_array()) for t in rep_attr]
                for _ in range(ntimes)]
        elif profile_rounds:
            out = self._run_profiled(schedule, send_dev, ntimes, timers,
                                     profiled_segs)
        else:
            for rep in range(ntimes):
                with trace.span(f"{self.name}.dispatch", rep=rep,
                                method=schedule.name):
                    t0 = time.perf_counter()
                    out = fn(send_dev)
                    out.block_until_ready()
                    dt = time.perf_counter() - t0
                rep_attr = attribute_total(schedule, dt, weights=attr_w)
                for r, t in enumerate(timers):
                    t += rep_attr[r]
                self.last_rep_timers.append(rep_attr)

        _, n_recv_slots = self._slots(p)
        recv_words = np.asarray(jax.device_get(out))[:, :n_recv_slots, :]
        recv_np = self._to_bytes(p, recv_words)
        recv_bufs = self._split_recv(p, recv_np)
        if verify:
            from tpu_aggcomm.harness.verify import verify_recv
            verify_recv(p, recv_bufs, iter_)
        return recv_bufs, timers

    # ------------------------------------------------------------------
    def _round_segments(self, schedule):
        """Per-round jitted (send, recv) -> recv programs plus their round
        ids, for profiling. None when the schedule has no round structure
        to split (dense collective methods and the 3-hop TAM route)."""
        from tpu_aggcomm.tam.engine import TamMethod
        if isinstance(schedule, TamMethod) or schedule.collective:
            return None
        if (getattr(schedule, "fault", None)
                or getattr(schedule, "n_staging", 0)):
            return None  # profile_rounds falls back to the monolithic rep
        key = (self._key(schedule), "segments")
        if key in self._cache:
            return self._cache[key]
        p = schedule.pattern
        _, n_recv_slots = self._slots(p)
        _, jdt, _w = self._words(p)
        rounds, barrier_rounds = _round_tables(schedule)

        def make_seg(srcs, ss, dsts, ds_, nbar):
            @jax.jit
            def seg(send, recv):
                return _apply_round(send, recv, srcs, ss, dsts, ds_, nbar,
                                    n_recv_slots, jdt)

            return seg

        segs = [make_seg(srcs, ss, dsts, ds_, barrier_rounds.get(r, 0))
                for (r, srcs, ss, dsts, ds_) in rounds]
        round_ids = [r for (r, *_rest) in rounds]
        self._cache[key] = (segs, round_ids)
        return self._cache[key]

    def _run_profiled(self, schedule, send_dev, ntimes: int, timers, segs):
        """profile_rounds execution: one dispatch per throttle round, each
        synced and timed — schedule-shape analysis, not headline numbers
        (per-dispatch sync overhead is included, as on jax_ici). Per-round
        times land in ``last_round_times`` and are mapped onto each rank's
        TimerBucket structure (harness/attribution.py): the measured time
        of round k is split among the post/wait/barrier buckets the rank's
        ops charge in round k — the fenced-segment approximation of the
        reference's per-phase MPI_Wtime brackets (mpi_test.c:1768-1815)."""
        p = schedule.pattern
        dev = self._dev()
        _, n_recv_slots = self._slots(p)
        _, jdt, w = self._words(p)
        attr_w = self._attr_weights(schedule)

        if segs is None:
            segs_run, round_ids = None, None
        else:
            segs_run, round_ids = segs
            # warm-up compile every segment
            recv_w = jnp.zeros((p.nprocs, n_recv_slots + 1, w), dtype=jdt)
            recv_w = jax.device_put(recv_w, dev)
            for seg in segs_run:
                recv_w = seg(send_dev, recv_w)
            recv_w.block_until_ready()

        out = None
        for _ in range(ntimes):
            if segs_run is None:
                fn = self._compiled(schedule)
                t0 = time.perf_counter()
                out = fn(send_dev)
                out.block_until_ready()
                dt = time.perf_counter() - t0
                self.last_round_times.append([dt])
                rep_attr = attribute_total(schedule, dt, weights=attr_w)
            else:
                recv = jax.device_put(
                    jnp.zeros((p.nprocs, n_recv_slots + 1, w), dtype=jdt),
                    dev)
                round_times = []
                for rnd, seg in zip(round_ids, segs_run):
                    with trace.span("jax_sim.round", round=rnd,
                                    method=schedule.name):
                        ts = time.perf_counter()
                        recv = seg(send_dev, recv)
                        recv.block_until_ready()
                        round_times.append(time.perf_counter() - ts)
                out = recv
                self.last_round_times.append(round_times)
                rep_attr = attribute_rounds(
                    schedule, dict(zip(round_ids, round_times)),
                    weights=attr_w)
            for r, t in enumerate(timers):
                t += rep_attr[r]
            self.last_rep_timers.append(rep_attr)
        return out

    # ------------------------------------------------------------------
    def _one_rep_scatters(self, schedule):
        """The rep truncated to its delivery side: every round scatters
        into exactly the recv slots the real round writes, but the
        scattered values are ONE gathered row broadcast across the
        round's edges — the per-edge gather (message preparation) never
        runs. ``measure_phase_split`` differences this against the full
        rep: T(full) - T(scatters) is the measured preparation-side
        time. (The inverse truncation — gathers consumed by a reduce —
        is confounded: the reduce costs as much as the scatter it
        replaces, measured on both CPU and TPU tiers.) The single-row
        read keeps the chain's serial dependence on ``send``."""
        from tpu_aggcomm.tam.engine import TamMethod

        if isinstance(schedule, TamMethod) or schedule.collective:
            raise ValueError(
                "measured phase split needs a round-structured schedule "
                "(TAM's 3-hop decomposition is measured by "
                "measure_tam_hops; the dense collectives have none)")
        p = schedule.pattern
        n = p.nprocs
        _, n_recv_slots = self._slots(p)
        _, jdt, w = self._words(p)
        rounds, barrier_rounds = _round_tables(schedule)
        tabs = [(srcs, ss, dsts, ds_)
                for (_r, srcs, ss, dsts, ds_) in rounds]
        round_ids = [r for (r, *_rest) in rounds]

        # mirror _one_rep's lowering choice EXACTLY (scan for many-round
        # schedules, unrolled otherwise): differencing a scan-lowered
        # full rep against an unrolled truncation would measure the
        # lowering asymmetry, not the removed gathers
        scan_ok = _scan_lowered(tabs, barrier_rounds)
        if scan_ok:
            R = len(tabs)
            E = max(len(srcs) for (srcs, _ss, _ds, _dl) in tabs)
            srcs_t = np.zeros((R, E), dtype=np.int32)
            ss_t = np.zeros((R, E), dtype=np.int32)
            dsts_t = np.zeros((R, E), dtype=np.int32)
            dslt_t = np.full((R, E), n_recv_slots, dtype=np.int32)
            nbar_t = np.zeros((R,), dtype=np.int32)
            for k, (srcs, ss, dsts, ds_) in enumerate(tabs):
                e = len(srcs)
                srcs_t[k, :e] = srcs
                ss_t[k, :e] = ss
                dsts_t[k, :e] = dsts
                dslt_t[k, :e] = ds_
                nbar_t[k] = barrier_rounds.get(round_ids[k], 0)
            xs = tuple(jnp.asarray(t)
                       for t in (srcs_t, ss_t, dsts_t, dslt_t, nbar_t))

            def rep(send):
                recv0 = jnp.zeros((n, n_recv_slots + 1, w), dtype=jdt)

                def body(recv, x):
                    srcs, ss, dsts, ds_, nbar = x
                    one = send[srcs[0], ss[0]]
                    vals = jnp.broadcast_to(one, (E, w))
                    recv = recv.at[dsts, ds_].set(vals)
                    tok = jnp.sum(recv[:, :n_recv_slots, 0]
                                  .astype(jnp.int32)).astype(jdt)
                    cur = recv[:, n_recv_slots, 0]
                    recv = recv.at[:, n_recv_slots, 0].set(
                        jnp.where(nbar > 0, tok, cur))
                    return recv, ()

                recv, _ = lax.scan(body, recv0, xs, unroll=1)
                return recv

            return rep

        def rep(send):
            recv = jnp.zeros((n, n_recv_slots + 1, w), dtype=jdt)
            for k, (srcs, ss, dsts, ds_) in enumerate(tabs):
                one = send[int(srcs[0]), int(ss[0])]
                vals = jnp.broadcast_to(one, (len(srcs), w))
                recv = recv.at[jnp.asarray(dsts), jnp.asarray(ds_)].set(vals)
                # keep the barrier token reductions: the truncation must
                # drop ONLY the per-edge gathers, so barrier cost stays
                # on the deliver side where attribute_measured_split's
                # BARRIER bucket draws from
                for _ in range(barrier_rounds.get(round_ids[k], 0)):
                    tok = jnp.sum(recv[:, :n_recv_slots, 0]
                                  .astype(jnp.int32))
                    recv = recv.at[:, n_recv_slots, 0].set(
                        tok.astype(jdt))
                if k + 1 < len(tabs):
                    send, recv = lax.optimization_barrier((send, recv))
            return recv

        return rep

    def _one_rep_hybrid(self, schedule, upto: int):
        """Rounds 0..upto-2 at FULL fidelity, then round upto-1 with its
        per-edge gather replaced by the broadcast-row scatter (the
        _one_rep_scatters truncation applied to ONE round): the prefix
        family ``measure_round_splits`` differences against the full
        prefixes to separate round k's preparation (gather) side from
        its delivery side. Unrolled lowering only — prefixes must share
        the full rep's lowering, and a scan body cannot swap gather for
        broadcast per iteration without computing both (jnp.where) or
        adding branch structure the full rep lacks (lax.cond)."""
        from tpu_aggcomm.tam.engine import TamMethod

        if isinstance(schedule, TamMethod) or schedule.collective:
            raise ValueError(
                "round splits need a round-structured schedule "
                "(TAM's 3-hop decomposition is measured by "
                "measure_tam_hops; the dense collectives have none)")
        p = schedule.pattern
        n = p.nprocs
        _, n_recv_slots = self._slots(p)
        _, jdt, w = self._words(p)
        rounds, barrier_rounds = _round_tables(schedule)
        tabs = [(srcs, ss, dsts, ds_)
                for (_r, srcs, ss, dsts, ds_) in rounds]
        round_ids = [r for (r, *_rest) in rounds]
        scan_ok = _scan_lowered(tabs, barrier_rounds)
        if scan_ok:
            raise ValueError(
                "round splits need the unrolled lowering (< 32 rounds); "
                "deep scan-lowered schedules have measure_round_times")
        if not 1 <= upto <= len(tabs):
            raise ValueError(f"upto must be in [1, {len(tabs)}]")

        def rep(send):
            recv = jnp.zeros((n, n_recv_slots + 1, w), dtype=jdt)
            for k in range(upto):
                srcs, ss, dsts, ds_ = tabs[k]
                nbar = barrier_rounds.get(round_ids[k], 0)
                if k == upto - 1:
                    # the split round: delivery only (broadcast one
                    # gathered row; barriers stay — they are deliver-side)
                    one = send[int(srcs[0]), int(ss[0])]
                    vals = jnp.broadcast_to(one, (len(srcs), w))
                    recv = recv.at[jnp.asarray(dsts),
                                   jnp.asarray(ds_)].set(vals)
                    for _ in range(nbar):
                        tok = jnp.sum(recv[:, :n_recv_slots, 0]
                                      .astype(jnp.int32))
                        recv = recv.at[:, n_recv_slots, 0].set(
                            tok.astype(jdt))
                else:
                    recv = _apply_round(send, recv, srcs, ss, dsts, ds_,
                                        nbar, n_recv_slots, jdt)
                if k + 1 < upto:
                    send, recv = lax.optimization_barrier((send, recv))
            return recv

        return rep

    def measure_round_splits(self, schedule, *, iters_small: int = 50,
                             iters_big: int = 1050, trials: int = 3,
                             windows: int = 3,
                             max_rounds: int = MAX_MEASURED_ROUNDS
                             ) -> dict:
        """MEASURED 2-D decomposition: per round k, BOTH the preparation
        (gather) side and the delivery side, by differencing three prefix
        families through the shared chain scaffold:

        - P_k  — rounds 0..k-1 at full fidelity (``_one_rep(upto=k)``);
        - S_k  — rounds 0..k-2 full + round k-1 delivery-only
          (``_one_rep_hybrid``);
        - round k's deliver ≈ S_{k+1} - P_k, post ≈ P_{k+1} - S_{k+1}.

        Increments are clamped and rescaled so all posts + delivers sum
        EXACTLY to the full-rep chain time; within each round the
        post/deliver ratio comes from the raw differenced pair. Returns
        ``{round id: (post_seconds, deliver_seconds)}``. This makes the
        reference's per-round bracket structure (mpi_test.c:1768-1815)
        fully measured up to wait-bucket mixing WITHIN a round's deliver
        window — the residual attribution the provenance label names.
        Unrolled lowering only (< 32 rounds); cost is 2R-1 chain
        families. Cached per schedule."""
        from tpu_aggcomm.tam.engine import TamMethod
        if isinstance(schedule, TamMethod) or schedule.collective:
            raise ValueError(
                "round splits need a round-structured schedule "
                "(TAM's 3-hop decomposition is measured by "
                "measure_tam_hops; the dense collectives have none)")
        rounds, bars = _round_tables(schedule)
        round_ids = [r for (r, *_rest) in rounds]
        R = len(round_ids)
        if _scan_lowered(rounds, bars):
            raise ValueError(
                "round splits need the unrolled lowering (< 32 rounds); "
                "deep scan-lowered schedules have measure_round_times")
        if R > max_rounds:
            raise ValueError(
                f"{R} rounds exceeds max_rounds={max_rounds} (two chain "
                f"families are compiled per round); use profile_rounds "
                f"for very deep schedules")
        key = (self._key(schedule), "round_splits", iters_small, iters_big,
               trials, windows)
        if key in self._chain_cache:
            return self._chain_cache[key]
        per_full = self.measure_per_rep(schedule, iters_small=iters_small,
                                        iters_big=iters_big, trials=trials,
                                        windows=windows)
        p = schedule.pattern
        send0 = jax.device_put(self._global_send(p, 0), self._dev())

        def timed(rep_fn):
            return differenced_per_rep(
                self._chain_factory(rep_fn, p), send0,
                iters_small=iters_small, iters_big=iters_big,
                trials=trials, windows=windows)

        memo = self._prefix_memo(schedule, iters_small, iters_big,
                                 trials, windows)
        P = [0.0]
        for k in range(1, R):
            if k not in memo:
                memo[k] = timed(self._one_rep(schedule, upto=k))
            P.append(memo[k])
        P.append(per_full)
        S = [timed(self._one_rep_hybrid(schedule, k))
             for k in range(1, R + 1)]

        inc = np.maximum(np.diff(np.asarray(P)), 0.0)
        s = float(inc.sum())
        inc = inc * (per_full / s) if s > 0 else np.full(R, per_full / R)
        out = {}
        for k in range(R):
            post_raw = max(P[k + 1] - S[k], 0.0)
            del_raw = max(S[k] - P[k], 0.0)
            tot_raw = post_raw + del_raw
            # the raw pair sets the WITHIN-round ratio; the rescaled
            # increment sets the round's total (additivity contract).
            # tot_raw == 0 (pure noise) -> all deliver: a round's scatter
            # exists by construction, its gather may be arbitrarily cheap
            frac_post = post_raw / tot_raw if tot_raw > 0 else 0.0
            out[round_ids[k]] = (float(inc[k] * frac_post),
                                 float(inc[k] * (1.0 - frac_post)))
        self._chain_cache[key] = out
        return out

    def _tam_rep(self, tam, upto_hop: int | None = None):
        """THE TAM lowering: three fenced gather hops over the staged
        slab arrays — the proxy engine's P2/P3/P4 made index maps
        (l_d_t.c:996-1309); each hop stays a distinct program step.
        Shared by the full rep (``upto_hop=None``, what _one_rep/run
        execute) and the measured-hop prefixes ``measure_tam_hops``
        differences (1 = P2 only, 2 = P2+P3) — one definition, so the
        measured decomposition can never drift from the program it
        decomposes (the _apply_round / _build_steps precedent).

        Hop prefixes end in a fixed SINK: the hop's output rows
        segment-summed into recv's first data row. The sink (a) is
        identical work for both prefixes (staged and exch have the same
        row count), so T2 - T1 isolates P3 exactly; (b) touches every
        gathered row, so XLA cannot dead-code the truncated hop; and
        (c) lands in a DATA row, so the chain scaffold's token (a sum
        over data rows) stays data-dependent on the hop — constant-zero
        data rows would let XLA fold the token and elide the chain."""
        if upto_hop not in (None, 1, 2):
            raise ValueError("upto_hop must be None (full rep), 1 (P2) "
                             "or 2 (P2+P3)")
        p = tam.pattern
        n = p.nprocs
        n_send_slots, n_recv_slots = self._slots(p)
        stage_idx, exch_idx, recv_dst, recv_slot = _tam_tables(tam)
        stage_j = jnp.asarray(stage_idx)
        exch_j = jnp.asarray(exch_idx)
        dst_j = jnp.asarray(recv_dst)
        slot_j = jnp.asarray(recv_slot)
        _, jdt, w = self._words(p)

        def sink(x):
            # (E, w) -> (n, w) segment sum, landed in data row 0
            recv = jnp.zeros((n, n_recv_slots + 1, w), dtype=jdt)
            return recv.at[:, 0, :].set(
                x.reshape(n, -1, w).sum(axis=1).astype(jdt))

        def rep(send):
            flat = send.reshape(n * n_send_slots, w)
            staged = jnp.take(flat, stage_j, axis=0)        # P2 gather
            (staged,) = lax.optimization_barrier((staged,))
            if upto_hop == 1:
                return sink(staged)
            exch = jnp.take(staged, exch_j, axis=0)         # P3 exchange
            (exch,) = lax.optimization_barrier((exch,))
            if upto_hop == 2:
                return sink(exch)
            recv = jnp.zeros((n, n_recv_slots + 1, w), dtype=jdt)
            return recv.at[dst_j, slot_j].set(exch)         # P4/P5

        return rep

    def measure_tam_hops(self, tam, *, iters_small: int = 50,
                         iters_big: int = 1050, trials: int = 3,
                         windows: int = 3) -> dict:
        """MEASURED 3-way decomposition of a TAM rep by chained
        hop-prefix truncation differencing (VERDICT r4 weak item 6: the
        3-hop relay IS a round decomposition, and its boundaries are
        measurable by the same trick as measure_round_times):

        - ``p2`` — the intra-node staging gather (proxy pack, the
          reference's P2 bracket, l_d_t.c:1015-1106);
        - ``p3`` — the inter-node proxy exchange (l_d_t.c:1162-1195),
          isolated EXACTLY (both its prefixes carry the identical sink);
        - ``p4`` — the local delivery scatter (l_d_t.c:1264-1266);
        - ``total`` — the full-rep differenced time (== p2+p3+p4 by the
          same clamp-and-rescale contract as measure_round_times; the
          hop-1/hop-3 boundaries carry the sink asymmetry, bounded by
          one reduction pass over the staged arena).

        Cached per schedule."""
        from tpu_aggcomm.tam.engine import TamMethod

        if not isinstance(tam, TamMethod):
            raise ValueError("measure_tam_hops needs a TAM schedule "
                             "(m=15/16); round-structured schedules use "
                             "measure_round_times")
        key = (self._key(tam), "tam_hops", iters_small, iters_big,
               trials, windows)
        if key in self._chain_cache:
            return self._chain_cache[key]
        per_full = self.measure_per_rep(tam, iters_small=iters_small,
                                        iters_big=iters_big, trials=trials,
                                        windows=windows)
        p = tam.pattern
        send0 = jax.device_put(self._global_send(p, 0), self._dev())
        bounds = []
        for k in (1, 2):
            mk = self._chain_factory(self._tam_rep(tam, upto_hop=k), p)
            bounds.append(differenced_per_rep(
                mk, send0, iters_small=iters_small, iters_big=iters_big,
                trials=trials, windows=windows))
        bounds.append(per_full)
        inc = np.maximum(np.diff(np.asarray([0.0] + bounds)), 0.0)
        s = float(inc.sum())
        inc = inc * (per_full / s) if s > 0 else np.full(3, per_full / 3)
        out = {"p2": float(inc[0]), "p3": float(inc[1]),
               "p4": float(inc[2]), "total": per_full}
        self._chain_cache[key] = out
        return out

    def _chain_factory(self, rep, p):
        """THE serial-chain scaffold shared by measure_per_rep and
        measure_phase_split: iters reps of ``rep`` back-to-back in one
        lax.scan (unroll=1), rep r+1's send XOR-perturbed by
        ``(sum of rep r's recv data rows + r) % 251``. One definition so
        the full-rep and truncated-rep chains can never drift apart —
        the differencing premise is that dispatch overhead and scaffold
        cost cancel identically between them."""
        _, n_recv_slots = self._slots(p)
        _, jdt, _w = self._words(p)
        from tpu_aggcomm.harness.chained import xor_word

        def make_chain(iters: int):
            @jax.jit
            def chain(send0):
                def body(send, r):
                    recv = rep(send)
                    tok = (jnp.sum(recv[:, :n_recv_slots, 0]
                                   .astype(jnp.int32)) + r) % 251
                    return send ^ xor_word(tok, jdt), ()
                out, _ = lax.scan(body, send0,
                                  jnp.arange(iters, dtype=jnp.int32),
                                  unroll=1)
                return out
            return chain

        return make_chain

    def measure_phase_split(self, schedule, *, iters_small: int = 50,
                            iters_big: int = 1050, trials: int = 3,
                            windows: int = 3) -> dict:
        """MEASURED two-way decomposition of one rep via chained
        program-truncation differencing (no in-kernel clock exists in
        this Pallas release, and host brackets inside one XLA program are
        impossible — but a *truncated program* is measurable):

        - ``deliver`` — differenced per-rep seconds of the scatters-only
          rep (landing every round's rows in their recv slots), clamped
          to the full-rep time;
        - ``post``    — T(full) - T(scatters): the message-preparation
          (per-edge gather) side;
        - ``total``   — the full-rep differenced time (== post+deliver).

        Both quantities are differenced on-device measurements with no
        free parameter — unlike the POST_COST_BYTES weight model this
        split VALIDATES. Both chains ride the same serial-scan +
        differencing scaffold as measure_per_rep, so dispatch overhead
        cancels identically. Cached per schedule."""
        key = (self._key(schedule), "phase_split", iters_small, iters_big,
               trials, windows)
        if key in self._chain_cache:
            return self._chain_cache[key]
        from tpu_aggcomm.harness.chained import differenced_per_rep

        per_full = self.measure_per_rep(schedule, iters_small=iters_small,
                                        iters_big=iters_big, trials=trials,
                                        windows=windows)
        p = schedule.pattern
        make_chain = self._chain_factory(self._one_rep_scatters(schedule), p)
        send0 = jax.device_put(self._global_send(p, 0), self._dev())
        per_s = differenced_per_rep(make_chain, send0,
                                    iters_small=iters_small,
                                    iters_big=iters_big, trials=trials,
                                    windows=windows)
        deliver = min(per_s, per_full)
        out = {"total": per_full, "post": per_full - deliver,
               "deliver": deliver}
        self._chain_cache[key] = out
        return out

    def measure_round_times(self, schedule, *, iters_small: int = 50,
                            iters_big: int = 1050, trials: int = 3,
                            windows: int = 3,
                            max_rounds: int = MAX_MEASURED_ROUNDS) -> dict:
        """MEASURED per-round durations by chained round-PREFIX truncation
        differencing (VERDICT r4 item 3): for k = 1..R-1, chain reps of
        rounds 0..k-1 only (full fidelity — every kept round gathers and
        scatters exactly as in the whole rep) through THE shared serial
        scaffold (``_chain_factory``); round k's measured duration is the
        increment T(prefix k+1) - T(prefix k), with T(prefix R) the full
        ``measure_per_rep`` chain. Zero dispatch-sync overhead — strictly
        better than ``--profile-rounds``, whose per-round dispatches each
        pay a host sync (and, on the tunnel, an RPC).

        Noise handling: increments are clamped at 0 and rescaled so they
        sum EXACTLY to the full-rep differenced time — the additivity
        contract tests pin. Returns ``{round id: seconds}`` in program
        order. Cost is one chain family per round (R-1 extra compiles);
        ``max_rounds`` guards the n=1024 c=1 style 1000-round schedules
        (use --profile-rounds there). Cached per schedule.

        What this measures for the reference's columns: a round's time
        lands on the buckets charged in that round, so m=2's per-round
        send Waitalls (mpi_test.c:1909-1918) become MEASURED send-wait
        column entries, and m=1's final-round send drain
        (mpi_test.c:1814) is inside its last round's measured increment.
        (In this lowering a send completes when its round's scatter
        lands — rendezvous drain beyond that is the documented jax-tier
        semantic trade, core/schedule.py.)"""
        from tpu_aggcomm.tam.engine import TamMethod
        if isinstance(schedule, TamMethod) or schedule.collective:
            raise ValueError(
                "measured round times need a round-structured schedule "
                "(TAM's 3-hop decomposition is measured by "
                "measure_tam_hops; the dense collectives have none)")
        rounds, _ = _round_tables(schedule)
        round_ids = [r for (r, *_rest) in rounds]
        if len(round_ids) > max_rounds:
            raise ValueError(
                f"{len(round_ids)} rounds exceeds max_rounds={max_rounds} "
                f"(one chain family is compiled per round); use "
                f"profile_rounds for very deep schedules")
        key = (self._key(schedule), "round_times", iters_small, iters_big,
               trials, windows)
        if key in self._chain_cache:
            return self._chain_cache[key]
        per_full = self.measure_per_rep(schedule, iters_small=iters_small,
                                        iters_big=iters_big, trials=trials,
                                        windows=windows)
        p = schedule.pattern
        send0 = jax.device_put(self._global_send(p, 0), self._dev())
        from tpu_aggcomm.harness.chained import differenced_round_times
        out = differenced_round_times(
            lambda k: self._chain_factory(self._one_rep(schedule, upto=k),
                                          p),
            send0, round_ids, per_full, iters_small=iters_small,
            iters_big=iters_big, trials=trials, windows=windows,
            memo=self._prefix_memo(schedule, iters_small, iters_big,
                                   trials, windows))
        self._chain_cache[key] = out
        return out

    def _prefix_memo(self, schedule, *timing_key) -> dict:
        """Per-(schedule, timing-params) memo of measured P-prefix chain
        times, shared by measure_round_times and measure_round_splits so
        the identical prefix families are compiled and timed once."""
        return self._chain_cache.setdefault(
            (self._key(schedule), "prefix_memo", *timing_key), {})

    def measure_per_rep(self, schedule, *, iters_small: int = 50,
                        iters_big: int = 1050, trials: int = 3,
                        windows: int = 3) -> float:
        """Serial-chained per-rep latency with dispatch overhead cancelled
        (harness/chained.py scaffold).

        Reps run back-to-back inside one ``lax.scan`` (unroll=1); rep r+1's
        send buffer is perturbed by a scalar derived from rep r's recv, so
        every rep is a real data pass. The chaining perturbation adds one
        send-buffer pass per rep, so the number is conservative. The result
        is iteration-invariant, so it is cached per schedule — a sweep's
        repeat iters reuse one measurement instead of recompiling chains.
        """
        key = (self._key(schedule), iters_small, iters_big, trials, windows)
        if key in self._chain_cache:
            per_rep, samples = self._chain_cache[key]
            self.last_samples = list(samples)
            return per_rep
        p = schedule.pattern
        dev = self._dev()
        make_chain = self._chain_factory(self._one_rep(schedule), p)
        send0 = jax.device_put(self._global_send(p, 0), dev)
        samples = differenced_trials(make_chain, send0,
                                     iters_small=iters_small,
                                     iters_big=iters_big,
                                     trials=trials, windows=windows)
        per_rep = statistics.median(samples)
        self._chain_cache[key] = (per_rep, tuple(samples))
        self.last_samples = list(samples)
        return per_rep

    def measure_trial_samples(self, schedule, *, iters_small: int = 50,
                              iters_big: int = 1050, trials: int = 3,
                              windows: int = 1) -> list[float]:
        """FRESH per-trial differenced seconds for the autotuner
        (tune/measure.py): the same serial-chain scaffold as
        measure_per_rep, but the SAMPLES are never cached — every racing
        batch must be a new measurement, or the tuner's CI over batches
        degenerates to a replay of the first batch. Only the jitted
        chain pair and the initial send buffer are memoized (per
        schedule and chain lengths), so repeat batches re-TIME without
        re-COMPILING — the distinction that matters through the
        tunnel."""
        key = (self._key(schedule), "tune_chains", iters_small, iters_big)
        if key not in self._chain_cache:
            p = schedule.pattern
            make_chain = self._chain_factory(self._one_rep(schedule), p)
            chains = {iters_small: make_chain(iters_small),
                      iters_big: make_chain(iters_big)}
            send0 = jax.device_put(self._global_send(p, 0), self._dev())
            self._chain_cache[key] = (chains, send0)
        chains, send0 = self._chain_cache[key]
        samples = differenced_trials(lambda it: chains[it], send0,
                                     iters_small=iters_small,
                                     iters_big=iters_big,
                                     trials=trials, windows=windows)
        self.last_samples = list(samples)
        return list(samples)
