"""Native backend: ctypes bindings to the C++ threaded rank runtime.

Flattens a compiled Schedule into the C ABI of
``native/aggcomm_runtime.cc`` (one op array + program offsets per rank,
contiguous slab buffers) and executes it with one OS thread per rank. This
is the semantics-parity tier: real rendezvous Issend, real blocking
receives, real barriers, per-op wall-clock timer buckets — the closest
thing to the reference's MPI execution that runs without a cluster.

The shared library is compiled on demand with g++ (no pip deps) and cached
next to the source; rebuilt when the source is newer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np

from tpu_aggcomm.core.schedule import OpKind, Schedule, TimerBucket
from tpu_aggcomm.harness.timer import Timer
from tpu_aggcomm.harness.verify import make_send_slabs

__all__ = ["NativeBackend", "build_library", "library_path",
           "run_workload_proxy"]

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "aggcomm_runtime.cc")

_BUCKET_CODE = {
    TimerBucket.POST: 0,
    TimerBucket.RECV_WAIT: 1,
    TimerBucket.SEND_WAIT: 2,
    TimerBucket.RECV_AND_SEND_WAIT: 3,
    TimerBucket.BARRIER: 4,
    TimerBucket.NONE: 5,
}

_OP_FIELDS = 10  # kind, peer, slot, peer2, slot2, token, nbytes, bucket,
                 # ntokens, tok_ofs


def library_path() -> str:
    return os.path.join(os.path.dirname(_SRC), "build", "libaggcomm.so")


def build_library(force: bool = False) -> str:
    """Compile the runtime with g++ if missing or stale."""
    out = library_path()
    if (not force and os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(_SRC)):
        return out
    os.makedirs(os.path.dirname(out), exist_ok=True)
    # unique temp name + atomic rename: concurrent cold builds (parallel
    # test workers, two CLI runs) must not corrupt each other's output
    fd, tmp = tempfile.mkstemp(suffix=".so.tmp", dir=os.path.dirname(out))
    os.close(fd)
    try:
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
               _SRC, "-o", tmp]
        res = subprocess.run(cmd, capture_output=True, text=True)
        if res.returncode != 0:
            raise RuntimeError(
                f"native runtime build failed ({' '.join(cmd)}):\n"
                f"{res.stderr}")
        os.replace(tmp, out)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return out


_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build_library())
        lib.agg_run_workload_proxy.restype = ctypes.c_int
        lib.agg_run_workload_proxy.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            np.ctypeslib.ndpointer(np.int32),     # node_of
            np.ctypeslib.ndpointer(np.int32),     # proxies
            np.ctypeslib.ndpointer(np.int32),     # aggs
            np.ctypeslib.ndpointer(np.int32),     # msg_sizes
            np.ctypeslib.ndpointer(np.uint8),     # send_msgs
            np.ctypeslib.ndpointer(np.int64),     # send_block_ofs
            np.ctypeslib.ndpointer(np.uint8),     # recv_out
            np.ctypeslib.ndpointer(np.float64),   # rep_times_out
        ]
        lib.agg_run_workload_cw2.restype = ctypes.c_int
        lib.agg_run_workload_cw2.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            np.ctypeslib.ndpointer(np.int32),     # aggs
            np.ctypeslib.ndpointer(np.int32),     # msg_sizes
            np.ctypeslib.ndpointer(np.int32),     # owner_of
            np.ctypeslib.ndpointer(np.int32),     # laggs
            np.ctypeslib.ndpointer(np.uint8),     # send_msgs
            np.ctypeslib.ndpointer(np.int64),     # send_block_ofs
            np.ctypeslib.ndpointer(np.uint8),     # recv_out
            np.ctypeslib.ndpointer(np.float64),   # rep_times_out
        ]
        lib.agg_run_workload_cw3.restype = ctypes.c_int
        lib.agg_run_workload_cw3.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int,
            np.ctypeslib.ndpointer(np.int32),     # node_of
            np.ctypeslib.ndpointer(np.int32),     # aggs
            np.ctypeslib.ndpointer(np.int32),     # msg_sizes
            np.ctypeslib.ndpointer(np.int32),     # owner_of
            np.ctypeslib.ndpointer(np.int32),     # laggs
            np.ctypeslib.ndpointer(np.uint8),     # send_msgs
            np.ctypeslib.ndpointer(np.int64),     # send_block_ofs
            np.ctypeslib.ndpointer(np.uint8),     # recv_out
            np.ctypeslib.ndpointer(np.float64),   # rep_times_out
        ]
        lib.agg_run_schedule.restype = ctypes.c_int
        lib.agg_run_schedule.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_void_p,                      # ops
            np.ctypeslib.ndpointer(np.int32),     # prog_ofs
            np.ctypeslib.ndpointer(np.int32),     # wait_tokens
            np.ctypeslib.ndpointer(np.uint8),     # send_slabs
            np.ctypeslib.ndpointer(np.int32),     # send_ofs
            np.ctypeslib.ndpointer(np.uint8),     # recv_bufs
            np.ctypeslib.ndpointer(np.int32),     # recv_ofs
            ctypes.c_void_p,                      # a2a_src_slot (or None)
            ctypes.c_void_p,                      # a2a_dst_slot (or None)
            ctypes.c_int,                         # max_token
            np.ctypeslib.ndpointer(np.float64),   # timers_out
        ]
        _lib = lib
    return _lib


def _flatten(schedule: Schedule):
    """Schedule -> (ops int32 (O, 10), prog_ofs, wait_tokens, max_token)."""
    rows = []
    prog_ofs = [0]
    wait_tokens: list[int] = []
    max_token = 0
    for prog in schedule.programs:
        for op in prog:
            ntok, tofs = 0, 0
            if op.kind is OpKind.WAITALL:
                ntok = len(op.tokens)
                tofs = len(wait_tokens)
                wait_tokens.extend(op.tokens)
            if op.token > max_token:
                max_token = op.token
            rows.append((int(op.kind), op.peer, op.slot, op.peer2, op.slot2,
                         op.token, op.nbytes, _BUCKET_CODE[op.bucket],
                         ntok, tofs))
        prog_ofs.append(len(rows))
    ops = np.asarray(rows, dtype=np.int32).reshape(-1, _OP_FIELDS)
    return (ops, np.asarray(prog_ofs, dtype=np.int32),
            np.asarray(wait_tokens or [0], dtype=np.int32), max_token)


def _pack_blocks(wl):
    """Per-src send blocks (G messages in ascending-aggregator order) as
    one flat byte arena + per-src offsets — the layout both native
    workload engines consume."""
    n = wl.nprocs
    sizes = np.asarray(wl.msg_size, dtype=np.int32)
    aggs = np.asarray(wl.aggregators, dtype=np.int32)
    G = len(aggs)
    block_bytes = (sizes.astype(np.int64)) * G
    send_block_ofs = np.zeros(n, dtype=np.int64)
    send_block_ofs[1:] = np.cumsum(block_bytes)[:-1]
    send_msgs = np.zeros(max(int(block_bytes.sum()), 1), dtype=np.uint8)
    for src in range(n):
        o = int(send_block_ofs[src])
        m = int(sizes[src])
        for gi, g in enumerate(aggs):
            send_msgs[o + gi * m:o + (gi + 1) * m] = wl.fill(src, int(g))
    return sizes, aggs, send_msgs, send_block_ofs


def _unpack_recv(wl, recv_out):
    """Delivery slabs (per aggregator, sources ascending) back to the
    oracle-shaped per-aggregator lists."""
    n = wl.nprocs
    sizes = np.asarray(wl.msg_size, dtype=np.int64)
    slab = int(sizes.sum())
    src_ofs = np.zeros(n, dtype=np.int64)
    src_ofs[1:] = np.cumsum(sizes)[:-1]
    recv_by_rank = {}
    for gi, g in enumerate(wl.aggregators):
        row = recv_out[gi * slab:(gi + 1) * slab]
        recv_by_rank[int(g)] = [
            row[int(src_ofs[s]):int(src_ofs[s]) + int(sizes[s])].copy()
            for s in range(n)]
    return recv_by_rank


def run_workload_proxy(wl, na, ntimes: int = 1):
    """Run a variable-size workload through the native collective_write
    proxy engine (``agg_run_workload_proxy``): real threads, real pack /
    proxy-exchange / re-pack memcpy walks.

    Returns ``(recv_by_rank, rep_times)`` in the same shape the oracle
    engines return — per-aggregator lists of per-source byte arrays and an
    (nprocs, ntimes) per-rank wall-time matrix reduced to per-rep maxima.
    """
    lib = _load()
    n = wl.nprocs
    sizes, aggs, send_msgs, send_block_ofs = _pack_blocks(wl)
    G = len(aggs)
    slab = int(sizes.sum())
    recv_out = np.zeros(max(G * slab, 1), dtype=np.uint8)
    rep_times = np.zeros((n, max(ntimes, 1)), dtype=np.float64)
    rc = lib.agg_run_workload_proxy(
        n, na.nnodes, G, max(ntimes, 1),
        np.asarray(na.node_of, dtype=np.int32),
        np.asarray(na.proxies, dtype=np.int32),
        aggs, sizes, send_msgs, send_block_ofs, recv_out, rep_times)
    if rc != 0:
        raise RuntimeError(f"native workload engine failed with rc={rc}")
    return _unpack_recv(wl, recv_out), rep_times.max(axis=0).tolist()


def run_workload_cw2(wl, meta, ntimes: int = 1):
    """Run a variable-size workload through the native collective_write2
    two-level engine (``agg_run_workload_cw2``): members pack-send to
    their local aggregator, local aggregators exchange per-destination
    segments with the global aggregators (l_d_t.c:754-926).

    ``meta`` is the two-level structure from aggregator_meta_information.
    Return shape matches :func:`run_workload_proxy`.
    """
    lib = _load()
    n = wl.nprocs
    sizes, aggs, send_msgs, send_block_ofs = _pack_blocks(wl)
    G = len(aggs)
    slab = int(sizes.sum())
    recv_out = np.zeros(max(G * slab, 1), dtype=np.uint8)
    laggs = np.asarray(meta.local_aggregators, dtype=np.int32)
    rep_times = np.zeros((n, max(ntimes, 1)), dtype=np.float64)
    rc = lib.agg_run_workload_cw2(
        n, G, len(laggs), max(ntimes, 1),
        aggs, sizes, np.asarray(meta.owner_of, dtype=np.int32),
        laggs, send_msgs, send_block_ofs, recv_out, rep_times)
    if rc != 0:
        raise RuntimeError(f"native cw2 engine failed with rc={rc} "
                           f"(is every rank bound to a local aggregator?)")
    return _unpack_recv(wl, recv_out), rep_times.max(axis=0).tolist()


def run_workload_cw3(wl, na, meta, ntimes: int = 1):
    """Run a variable-size workload through the native collective_write3
    shared-window engine (``agg_run_workload_cw3``): group members fill a
    per-node shared staging buffer (the MPI_Win_allocate_shared analog,
    l_d_t.c:647-663 — threads genuinely share the memory), a fence
    publishes it, local aggregators read members' staging zero-copy
    (shared_query, 667-671) and exchange hindexed segments directly with
    the destination aggregators (705-711).

    Requires meta mode 1 (destinations must be local aggregators) and
    node-local groups. Return shape matches :func:`run_workload_proxy`.
    """
    lib = _load()
    n = wl.nprocs
    sizes, aggs, send_msgs, send_block_ofs = _pack_blocks(wl)
    G = len(aggs)
    slab = int(sizes.sum())
    recv_out = np.zeros(max(G * slab, 1), dtype=np.uint8)
    laggs = np.asarray(meta.local_aggregators, dtype=np.int32)
    rep_times = np.zeros((n, max(ntimes, 1)), dtype=np.float64)
    rc = lib.agg_run_workload_cw3(
        n, G, len(laggs), na.nnodes, max(ntimes, 1),
        np.asarray(na.node_of, dtype=np.int32),
        aggs, sizes, np.asarray(meta.owner_of, dtype=np.int32),
        laggs, send_msgs, send_block_ofs, recv_out, rep_times)
    if rc == 2:
        raise ValueError(
            "collective_write3 route requires destinations to be local "
            "aggregators (meta mode 1)")
    if rc == 3:
        raise ValueError("a local-aggregator group spans nodes; "
                         "shared window invalid")
    if rc != 0:
        raise RuntimeError(f"native cw3 engine failed with rc={rc}")
    return _unpack_recv(wl, recv_out), rep_times.max(axis=0).tolist()


class NativeBackend:
    """Executes schedules on the C++ threaded rank runtime."""

    name = "native"

    def run(self, schedule, *, ntimes: int = 1, iter_: int = 0,
            verify: bool = False):
        from tpu_aggcomm.tam.engine import TamMethod
        if ntimes < 1:
            raise ValueError("ntimes must be >= 1")
        if isinstance(schedule, TamMethod):
            # TAM is a separate engine behind the registry (the reference's
            # extern boundary, mpi_test.c:34-38); the threaded runtime
            # executes flat op programs, so the hierarchical route runs on
            # the host proxy-path oracle, keeping `--backend native -m 0`
            # complete (VERDICT r1 item 2)
            from tpu_aggcomm.backends.local import LocalBackend
            if getattr(self, "_local_delegate", None) is None:
                self._local_delegate = LocalBackend()
            lb = self._local_delegate
            out = lb.run(schedule, ntimes=ntimes, iter_=iter_, verify=verify)
            self.last_rep_timers = getattr(lb, "last_rep_timers", [])
            self.last_provenance = lb.last_provenance
            return out
        self.last_provenance = ("native", "measured")
        lib = _load()
        p = schedule.pattern
        n, ds = p.nprocs, p.data_size
        agg_index = p.agg_index

        ops, prog_ofs, wait_tokens, max_token = _flatten(schedule)

        # contiguous slab arenas
        slabs = make_send_slabs(p, iter_)
        send_counts = [0 if s is None else s.shape[0] for s in slabs]
        send_ofs = np.zeros(n, dtype=np.int32)
        total = 0
        for r in range(n):
            send_ofs[r] = total
            total += send_counts[r]
        send_arena = np.zeros((max(total, 1), ds), dtype=np.uint8)
        for r, s in enumerate(slabs):
            if s is not None:
                send_arena[send_ofs[r]:send_ofs[r] + s.shape[0]] = s

        from tpu_aggcomm.harness.verify import recv_slot_counts
        recv_counts = recv_slot_counts(p)
        recv_ofs = np.full(n, -1, dtype=np.int32)
        total_r = 0
        for r in range(n):
            if recv_counts[r]:
                recv_ofs[r] = total_r
                total_r += recv_counts[r]
        recv_arena = np.zeros((max(total_r, 1), ds), dtype=np.uint8)

        # alltoallw slot maps (dense methods)
        if schedule.collective:
            from tpu_aggcomm.core.methods import _dense_slots
            sslot_of, rslot_of = _dense_slots(p)
            src_slot = np.zeros((n, n), dtype=np.int32)
            dst_slot = np.zeros((n, n), dtype=np.int32)
            for dst in range(n):
                for src in range(n):
                    # message src->dst exists iff sender has a slab for dst
                    ss = int(sslot_of[dst])  # sender-side slot keyed by dst
                    if ss < 0 or recv_ofs[dst] < 0 or int(rslot_of[src]) < 0:
                        src_slot[dst, src] = -1
                    else:
                        src_slot[dst, src] = ss
                        dst_slot[dst, src] = int(rslot_of[src])
            a2a_src = src_slot.ctypes.data_as(ctypes.c_void_p)
            a2a_dst = dst_slot.ctypes.data_as(ctypes.c_void_p)
        else:
            src_slot = dst_slot = None
            a2a_src = a2a_dst = None

        timers_out = np.zeros((n, ntimes, 5), dtype=np.float64)
        rc = lib.agg_run_schedule(
            n, ntimes, ds,
            ops.ctypes.data_as(ctypes.c_void_p), prog_ofs, wait_tokens,
            send_arena, send_ofs, recv_arena, recv_ofs,
            a2a_src, a2a_dst, max_token, timers_out)
        if rc != 0:
            raise RuntimeError(f"native runtime failed with rc={rc}")

        recv_bufs = []
        for r in range(n):
            if recv_counts[r] == 0:
                recv_bufs.append(None)
            else:
                o = recv_ofs[r]
                recv_bufs.append(recv_arena[o:o + recv_counts[r]].copy())
        if verify:
            from tpu_aggcomm.harness.verify import verify_recv
            verify_recv(p, recv_bufs, iter_)

        timers = []
        self.last_rep_timers = [[None] * n for _ in range(ntimes)]
        for r in range(n):
            acc = Timer()
            for m in range(ntimes):
                t5 = timers_out[r, m]
                rep = Timer(post_request_time=t5[0], send_wait_all_time=t5[1],
                            recv_wait_all_time=t5[2], barrier_time=t5[3],
                            total_time=t5[4])
                self.last_rep_timers[m][r] = rep
                acc += rep
            timers.append(acc)
        return recv_bufs, timers
