"""Device lane layout for byte payloads.

TPU handles uint8 array layouts 4-5x slower than uint32 views, and Mosaic
has no i8 vector ALU (see backends/pallas_local.py), so every compiled
backend carries slab payloads as uint32 lanes whenever the slab size is
4-aligned. Row-level gathers/scatters/permutes are dtype-agnostic, so only
the lane view changes; the host-side byte semantics (deterministic fills,
verification) are untouched — conversion happens at the host boundary.
"""

from __future__ import annotations

import numpy as np

__all__ = ["lane_layout", "to_lanes", "lanes_to_bytes"]


def lane_layout(data_size: int):
    """(numpy dtype, jnp dtype, words per slab) for a slab of data_size
    bytes."""
    import jax.numpy as jnp

    if data_size % 4 == 0:
        return np.uint32, jnp.uint32, data_size // 4
    return np.uint8, jnp.uint8, data_size


def to_lanes(arr: np.ndarray, data_size: int) -> np.ndarray:
    """View a (..., data_size) uint8 array in the lane layout."""
    ndt, _, w = lane_layout(data_size)
    return np.ascontiguousarray(arr).view(ndt).reshape(*arr.shape[:-1], w)


def lanes_to_bytes(arr: np.ndarray, data_size: int) -> np.ndarray:
    """Inverse of :func:`to_lanes` for a (..., w) lane array."""
    arr = np.ascontiguousarray(arr)
    return arr.view(np.uint8).reshape(*arr.shape[:-1], data_size)
