"""JAX/ICI backend: schedules lowered to XLA collectives over a device mesh.

The TPU-native execution path (BASELINE.md north star). Each logical rank
maps to one mesh device; the schedule's global round/edge view lowers to:

- per round: a greedy bipartite **edge coloring** of the round's (src, dst)
  edges; each color class is a partial permutation carried by one
  ``lax.ppermute`` step over the mesh axis — exactly the message volume of
  the reference's Issend/Irecv batches, nothing dense. On TPU every
  ppermute rides ICI neighbor links.
- dense methods (m=5/8 Alltoallw): one ``lax.all_to_all`` with zero-masked
  slots — exact because every pattern edge is uniform ``data_size`` bytes
  (span=1, mpi_test.c:98).
- round boundaries: ``lax.optimization_barrier`` so XLA cannot fuse or
  reorder across throttle rounds (the ``-c`` semantics would otherwise be
  compiled away — SURVEY.md §7 hard part (2)).
- reference MPI_Barrier rounds (m=17): a real ``psum`` chained into the
  dataflow.

Timing semantics (documented difference, SURVEY.md §7 hard part (3)): XLA
executes one compiled program per rep, so per-phase post/waitall times
cannot be bracketed individually on this backend; ``total_time`` is the
directly measured number (wall time per rep after a warm-up compile,
synchronized via ``block_until_ready``). Phase columns are filled by the
*fenced-segment approximation* (harness/attribution.py): measured wall
time is split onto each rank's TimerBucket structure — per throttle round
when ``profile_rounds=True`` (the program is split at round boundaries
into separately-jitted, separately-timed segments; adds dispatch sync),
whole-rep otherwise. Direct per-op host timing lives in the native
backend; device-side semaphore timing in pallas_dma.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_aggcomm.backends.lanes import lane_layout, lanes_to_bytes, to_lanes
from tpu_aggcomm.compat import shard_map as _compat_shard_map
from tpu_aggcomm.core.pattern import AggregatorPattern, Direction
from tpu_aggcomm.core.schedule import Schedule
from tpu_aggcomm.harness.attribution import (attribute_rounds,
                                             attribute_tam_total,
                                             attribute_total, weights_for)
from tpu_aggcomm.harness.timer import Timer
from tpu_aggcomm.harness.verify import make_send_slabs
from tpu_aggcomm.obs import trace

__all__ = ["JaxIciBackend", "color_rounds", "lower_schedule", "put_global"]

AXIS = "ranks"


def put_global(arr: np.ndarray, sharding) -> jax.Array:
    """``device_put`` that also works when the sharding spans processes.

    On a multi-controller runtime every process holds the same host value
    (schedules and fills are pure functions of the config — the MAP_DATA
    discipline) and contributes its addressable shards; single-process is
    the plain device_put fast path."""
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def color_rounds(edges: np.ndarray) -> list[list[tuple[int, int]]]:
    """Greedy bipartite edge coloring of one round's (src, dst) edge list.

    Each color class is a partial permutation (no repeated src, no repeated
    dst) — the unit a single ppermute can carry. Greedy needs at most
    2Δ-1 colors; the reference's structured rounds typically hit Δ.
    """
    src_used: list[set[int]] = []
    dst_used: list[set[int]] = []
    colors: list[list[tuple[int, int]]] = []
    for s, d in edges:
        s, d = int(s), int(d)
        for c in range(len(colors)):
            if s not in src_used[c] and d not in dst_used[c]:
                colors[c].append((s, d))
                src_used[c].add(s)
                dst_used[c].add(d)
                break
        else:
            colors.append([(s, d)])
            src_used.append({s})
            dst_used.append({d})
    return colors


@dataclass
class _Lowered:
    """Static lowering artifacts for one schedule."""
    perms: list[list[tuple[int, int]]]      # ppermute perm per color step
    round_of_color: list[int]               # color step -> round index
    sslot_tab: np.ndarray                   # (nprocs, C) send slot or -1
    rslot_tab: np.ndarray                   # (nprocs, C) recv slot or trash row
    barrier_rounds: dict[int, int]          # round -> number of MPI_Barriers
    n_send_slots: int
    n_recv_slots: int                       # excludes the trash row

    @property
    def n_colors(self) -> int:
        return len(self.perms)


def lower_schedule(schedule: Schedule) -> _Lowered:
    p = schedule.pattern
    n = p.nprocs
    edges = schedule.data_edges()
    rtable = schedule.recv_slot_table()
    n_send_slots = p.cb_nodes if p.direction is Direction.ALL_TO_MANY else n
    n_recv_slots = n if p.direction is Direction.ALL_TO_MANY else p.cb_nodes

    perms: list[list[tuple[int, int]]] = []
    round_of_color: list[int] = []
    sslots: list[np.ndarray] = []
    rslots: list[np.ndarray] = []
    n_rounds = int(edges[:, 4].max()) + 1 if len(edges) else 0
    for r in range(n_rounds):
        sel = edges[edges[:, 4] == r]
        if len(sel) == 0:
            continue
        slot_of = {(int(e[0]), int(e[1])): int(e[2]) for e in sel}
        for color in color_rounds(sel[:, :2]):
            ss = np.full(n, -1, dtype=np.int32)
            rs = np.full(n, n_recv_slots, dtype=np.int32)  # trash row default
            for (s, d) in color:
                ss[s] = slot_of[(s, d)]
                rs[d] = rtable[(s, d)]
            perms.append(color)
            round_of_color.append(r)
            sslots.append(ss)
            rslots.append(rs)

    from tpu_aggcomm.core.schedule import barrier_rounds_of
    barrier_rounds = barrier_rounds_of(schedule)

    return _Lowered(
        perms=perms,
        round_of_color=round_of_color,
        sslot_tab=np.stack(sslots, axis=1) if sslots else np.zeros((n, 0), np.int32),
        rslot_tab=np.stack(rslots, axis=1) if rslots else np.zeros((n, 0), np.int32),
        barrier_rounds=barrier_rounds,
        n_send_slots=n_send_slots,
        n_recv_slots=n_recv_slots,
    )


class JaxIciBackend:
    """Executes schedules over a jax.sharding.Mesh (one device per rank)."""

    name = "jax_ici"

    def __init__(self, devices=None):
        self._devices = devices
        self._segment_cache: dict = {}
        self._chain_cache: dict = {}   # schedule key -> measured per-rep s

    @staticmethod
    def _cache_key(p, low: "_Lowered", profile_rounds: bool):
        return (p, profile_rounds,
                low.sslot_tab.tobytes(), low.rslot_tab.tobytes(),
                tuple(tuple(c) for c in low.perms),
                tuple(low.round_of_color),
                tuple(sorted(low.barrier_rounds.items())))

    def _mesh(self, nprocs: int) -> Mesh:
        from tpu_aggcomm.parallel import host_major_devices
        devs = host_major_devices(self._devices)
        if len(devs) < nprocs:
            raise ValueError(
                f"pattern needs {nprocs} devices, only {len(devs)} available "
                f"(hint: XLA_FLAGS=--xla_force_host_platform_device_count={nprocs})")
        return Mesh(np.array(devs[:nprocs]), (AXIS,))

    # ------------------------------------------------------------------
    def run(self, schedule, *, ntimes: int = 1, iter_: int = 0,
            verify: bool = False, profile_rounds: bool = False,
            chained: bool = False, measured_phases: bool = False):
        if ntimes < 1:
            raise ValueError("ntimes must be >= 1")
        if chained and profile_rounds:
            raise ValueError("chained and profile_rounds are exclusive "
                             "(one program vs per-round programs)")
        if measured_phases and profile_rounds:
            raise ValueError("measured_phases and profile_rounds are "
                             "exclusive (truncation-differenced rounds vs "
                             "per-round dispatch timing)")
        from tpu_aggcomm.tam.engine import TamMethod, tam_two_level_jax
        if isinstance(schedule, TamMethod) and chained:
            raise ValueError("chained measurement for TAM runs on jax_sim "
                             "(single-chip route); the two-level mesh "
                             "engine times whole reps")
        if measured_phases and (isinstance(schedule, TamMethod)
                                or schedule.collective):
            raise ValueError(
                "measured phases need a round-structured schedule "
                "(TAM's 3-hop decomposition is measured by jax_sim's "
                "measure_tam_hops; the dense collectives have none)")
        self.last_provenance = (
            "jax_ici",
            "attributed-chained" if chained
            else "attributed-rounds" if profile_rounds
            else "attributed")
        if isinstance(schedule, TamMethod):
            # the two-level engine times whole reps (attribute_tam_total)
            # regardless of profile_rounds — no per-round split to claim
            self.last_provenance = ("jax_ici", "attributed")
            p = schedule.pattern
            devs = (list(self._devices) if self._devices is not None
                    else jax.devices())
            from tpu_aggcomm.tam.engine import padded_mesh_size
            needed = padded_mesh_size(schedule.assignment)
            if len(devs) < needed and needed > p.nprocs \
                    and len(devs) >= p.nprocs:
                # ONLY the ragged-pad case falls back: the pool covers the
                # real ranks but not the phantom pad coordinates. A genuine
                # device shortfall (fewer devices than ranks) still raises
                # inside tam_two_level_jax with the remediation hint —
                # silently swapping multi-chip timing for a single-chip
                # simulation would mislabel the numbers.
                import warnings
                warnings.warn(
                    f"TAM ragged-pad mesh needs {needed} devices, have "
                    f"{len(devs)}; falling back to the jax_sim "
                    f"single-device route", RuntimeWarning, stacklevel=2)
                from tpu_aggcomm.backends.jax_sim import JaxSimBackend
                if getattr(self, "_sim_delegate", None) is None:
                    self._sim_delegate = JaxSimBackend(device=devs[0])
                out = self._sim_delegate.run(schedule, ntimes=ntimes,
                                             iter_=iter_, verify=verify)
                self.last_rep_timers = getattr(self._sim_delegate,
                                               "last_rep_timers", [])
                self.last_provenance = self._sim_delegate.last_provenance
                return out
            recv_bufs, rep_times = tam_two_level_jax(schedule, devs,
                                                     iter_, ntimes)
            # per-rank byte-weighted P2/P3/P4 split of each measured rep
            # (harness/attribution.py: intra hops -> recv_wait, inter hop
            # -> send_wait, matching collective_write's brackets)
            tam_w = weights_for(schedule)
            timers = [Timer() for _ in range(p.nprocs)]
            self.last_rep_timers = []
            for dt in rep_times:
                rep_attr = attribute_tam_total(schedule, dt, weights=tam_w)
                for r, t in enumerate(timers):
                    t += rep_attr[r]
                self.last_rep_timers.append(rep_attr)
            if verify:
                from tpu_aggcomm.harness.verify import verify_recv
                verify_recv(p, recv_bufs, iter_)
            return recv_bufs, timers
        p = schedule.pattern
        n = p.nprocs
        mesh = self._mesh(n)
        sharding = NamedSharding(mesh, P(AXIS))

        segments, seg_rounds, _mc, n_send_slots, n_recv_slots = \
            self._segments_for(schedule, mesh, sharding, profile_rounds)
        attr_w = None if schedule.collective else weights_for(schedule)
        if profile_rounds and (seg_rounds is None or len(segments) <= 1):
            # no round structure to split (collective / single-round):
            # whole-rep attribution, and the sidecar must say so
            self.last_provenance = ("jax_ici", "attributed")

        send_g = self._global_send(p, iter_, n_send_slots)
        send_dev = jax.device_put(send_g, sharding)
        ndt, _, w = lane_layout(p.data_size)

        def fresh_recv():
            return jax.device_put(
                np.zeros((n, n_recv_slots + 1, w), dtype=ndt), sharding)

        # warm-up: compile every segment outside the timed region
        warm = fresh_recv()
        for seg in segments:
            warm = seg(send_dev, warm)
        warm.block_until_ready()

        timers = [Timer() for _ in range(n)]
        self.last_rep_timers = []  # [rep][rank] -> Timer (save_all_timing)
        self.last_round_times = []  # [rep] -> [per-round seconds]
        if measured_phases:
            # per-round durations MEASURED by prefix truncation on the
            # mesh (same contract and label as jax_sim/jax_shard);
            # single-round schedules have no boundary this tier can
            # measure — the trivial decomposition downgrades the label
            rt = self.measure_round_times(schedule)
            if len(rt) >= 2:
                rep_attr = attribute_rounds(schedule, rt, weights=attr_w)
                self.last_provenance = (
                    "jax_ici", "measured-rounds+attributed(buckets)")
                self.last_round_times = [list(rt.values())
                                         for _ in range(ntimes)]
            else:
                rep_attr = attribute_total(
                    schedule, sum(rt.values()), weights=attr_w)
                self.last_provenance = ("jax_ici", "attributed-chained")
            for r, t in enumerate(timers):
                t += Timer.from_array(rep_attr[r].as_array() * ntimes)
            self.last_rep_timers = [
                [Timer.from_array(t.as_array()) for t in rep_attr]
                for _ in range(ntimes)]
            recv_w = np.asarray(jax.device_get(warm))[:, :n_recv_slots, :]
            recv_np = lanes_to_bytes(recv_w, p.data_size)
            recv_bufs = self._split_recv(p, recv_np)
            if verify:
                from tpu_aggcomm.harness.verify import verify_recv
                verify_recv(p, recv_bufs, iter_)
            return recv_bufs, timers
        if chained:
            # honest per-rep seconds from the serial-chained differenced
            # scaffold (the multi-chip analog of jax_sim --chained);
            # delivery comes from the warmed unchained program
            per_rep = self.measure_per_rep(schedule)
            rep_attr = attribute_total(schedule, per_rep, weights=attr_w)
            for r, t in enumerate(timers):
                t += Timer.from_array(rep_attr[r].as_array() * ntimes)
            # fresh Timer objects per rep — rep rows must not alias
            self.last_rep_timers = [
                [Timer.from_array(t.as_array()) for t in rep_attr]
                for _ in range(ntimes)]
            recv_w = np.asarray(jax.device_get(warm))[:, :n_recv_slots, :]
            recv_np = lanes_to_bytes(recv_w, p.data_size)
            recv_bufs = self._split_recv(p, recv_np)
            if verify:
                from tpu_aggcomm.harness.verify import verify_recv
                verify_recv(p, recv_bufs, iter_)
            return recv_bufs, timers
        recv_dev = None
        for rep in range(ntimes):
            recv_dev = fresh_recv()
            seg_times = []
            with trace.span("jax_ici.dispatch", rep=rep,
                            method=schedule.name,
                            segments=len(segments)):
                t0 = time.perf_counter()
                for seg in segments:
                    ts = time.perf_counter()
                    recv_dev = seg(send_dev, recv_dev)
                    if profile_rounds:
                        recv_dev.block_until_ready()
                        seg_times.append(time.perf_counter() - ts)
                recv_dev.block_until_ready()
                dt = time.perf_counter() - t0
            # measured time -> TimerBucket structure (the fenced-segment
            # approximation, harness/attribution.py): per-round when the
            # program was split at round boundaries, whole-rep otherwise
            if profile_rounds and seg_rounds is not None and len(segments) > 1:
                rep_attr = attribute_rounds(
                    schedule, dict(zip(seg_rounds, seg_times)),
                    weights=attr_w)
            else:
                rep_attr = attribute_total(schedule, dt, weights=attr_w)
            for r, t in enumerate(timers):
                t += rep_attr[r]
            self.last_rep_timers.append(rep_attr)

        recv_w = np.asarray(jax.device_get(recv_dev))[:, :n_recv_slots, :]
        recv_np = lanes_to_bytes(recv_w, p.data_size)
        recv_bufs = self._split_recv(p, recv_np)
        if verify:
            from tpu_aggcomm.harness.verify import verify_recv
            verify_recv(p, recv_bufs, iter_)
        return recv_bufs, timers

    # ------------------------------------------------------------------
    def _segments_for(self, schedule, mesh, sharding, profile_rounds):
        """Cached (segments, seg_rounds, make_chain, n_send_slots,
        n_recv_slots) for a schedule — the one place the segment cache is
        keyed and built, shared by run() and measure_per_rep() so the
        chained program can never be built differently from the program
        run() executes."""
        p = schedule.pattern
        if schedule.collective:
            n = p.nprocs
            a2m = p.direction is Direction.ALL_TO_MANY
            key = (p, "dense")
            if key not in self._segment_cache:
                fn, mc = self._build_dense(p, mesh)
                self._segment_cache[key] = ([fn], None, mc)
            segs, sr, mc = self._segment_cache[key]
            return (segs, sr, mc, p.cb_nodes if a2m else n,
                    n if a2m else p.cb_nodes)
        low = lower_schedule(schedule)
        key = self._cache_key(p, low, profile_rounds)
        if key not in self._segment_cache:
            self._segment_cache[key] = self._build_ppermute(
                p, mesh, sharding, low, split_rounds=profile_rounds)
        segs, sr, mc = self._segment_cache[key]
        return segs, sr, mc, low.n_send_slots, low.n_recv_slots

    # ------------------------------------------------------------------
    def measure_per_rep(self, schedule, *, iters_small: int = 50,
                        iters_big: int = 1050, trials: int = 3,
                        windows: int = 3) -> float:
        """Serial-chained differenced per-rep seconds over the device mesh
        (harness/chained.py): reps run back-to-back inside one compiled
        program, rep r+1's send perturbed by a psum over rep r's delivery
        (every device depends on every other device's previous rep), and
        the fixed dispatch overhead is differenced away — the honest
        measurement through a tunneled or contended dispatch path, on the
        one-rank-per-device tier. Cached per schedule.

        The chain is always seeded with the iter-0 fill regardless of any
        ``run(iter_=k)`` that preceded it — timing does not depend on
        payload values, matching the jax_sim/jax_shard chained paths."""
        from tpu_aggcomm.core.schedule import schedule_shape_key
        from tpu_aggcomm.harness.chained import differenced_per_rep
        from tpu_aggcomm.tam.engine import TamMethod

        if isinstance(schedule, TamMethod):
            raise ValueError("chained measurement for TAM runs on jax_sim "
                             "(single-chip route); the two-level mesh "
                             "engine times whole reps")
        key = (schedule_shape_key(schedule), iters_small, iters_big,
               trials, windows)
        if key in self._chain_cache:
            return self._chain_cache[key]
        p = schedule.pattern
        mesh = self._mesh(p.nprocs)
        sharding = NamedSharding(mesh, P(AXIS))
        _segs, _sr, make_chain, n_send_slots, _nr = self._segments_for(
            schedule, mesh, sharding, False)
        send0 = jax.device_put(self._global_send(p, 0, n_send_slots),
                               sharding)
        per_rep = differenced_per_rep(make_chain, send0,
                                      iters_small=iters_small,
                                      iters_big=iters_big,
                                      trials=trials, windows=windows)
        self._chain_cache[key] = per_rep
        return per_rep

    def measure_round_times(self, schedule, *, iters_small: int = 50,
                            iters_big: int = 1050, trials: int = 3,
                            windows: int = 3,
                            max_rounds: int | None = None) -> dict:
        """MEASURED per-round durations on the one-rank-per-device tier —
        the tier a real pod runs — by chained round-prefix truncation
        differencing: the chain scaffold truncated at round color
        boundaries, round k's duration the differenced increment,
        clamped and rescaled to sum exactly to the full-rep chain time
        (the shared additivity contract, harness/chained.py). Zero
        per-round dispatch sync — the accuracy upgrade over
        ``--profile-rounds`` on the tier where per-dispatch sync is most
        expensive (each profiled round pays a full mesh sync). Cached
        per schedule."""
        from tpu_aggcomm.core.schedule import schedule_shape_key
        from tpu_aggcomm.harness.chained import (MAX_MEASURED_ROUNDS,
                                                 differenced_round_times)
        from tpu_aggcomm.tam.engine import TamMethod

        if isinstance(schedule, TamMethod) or schedule.collective:
            raise ValueError(
                "measured round times need a round-structured schedule "
                "(TAM's 3-hop decomposition is measured by jax_sim's "
                "measure_tam_hops; the dense collectives have none)")
        if max_rounds is None:
            max_rounds = MAX_MEASURED_ROUNDS
        low = lower_schedule(schedule)
        # round ids in color order + the color index where each begins
        round_ids, starts = [], []
        for c, r in enumerate(low.round_of_color):
            if not round_ids or r != round_ids[-1]:
                round_ids.append(r)
                starts.append(c)
        if len(round_ids) > max_rounds:
            raise ValueError(
                f"{len(round_ids)} rounds exceeds max_rounds={max_rounds} "
                f"(one chain family is compiled per round); use "
                f"profile_rounds for very deep schedules")
        key = (schedule_shape_key(schedule), "round_times", iters_small,
               iters_big, trials, windows)
        if key in self._chain_cache:
            return self._chain_cache[key]
        per_full = self.measure_per_rep(schedule, iters_small=iters_small,
                                        iters_big=iters_big, trials=trials,
                                        windows=windows)
        p = schedule.pattern
        mesh = self._mesh(p.nprocs)
        sharding = NamedSharding(mesh, P(AXIS))
        _segs, _sr, make_chain, n_send_slots, _nr = self._segments_for(
            schedule, mesh, sharding, False)
        send0 = jax.device_put(self._global_send(p, 0, n_send_slots),
                               sharding)
        out = differenced_round_times(
            lambda k: (lambda iters: make_chain(iters,
                                                upto_colors=starts[k])),
            send0, round_ids, per_full, iters_small=iters_small,
            iters_big=iters_big, trials=trials, windows=windows)
        self._chain_cache[key] = out
        return out

    # ------------------------------------------------------------------
    def _global_send(self, p: AggregatorPattern, iter_: int,
                     n_send_slots: int) -> np.ndarray:
        slabs = make_send_slabs(p, iter_)
        out = np.zeros((p.nprocs, n_send_slots, p.data_size), dtype=np.uint8)
        for r, s in enumerate(slabs):
            if s is not None:
                out[r, :s.shape[0]] = s
        return to_lanes(out, p.data_size)

    def _split_recv(self, p: AggregatorPattern, recv_np: np.ndarray):
        out = []
        agg_index = p.agg_index
        for rank in range(p.nprocs):
            if p.direction is Direction.ALL_TO_MANY and agg_index[rank] < 0:
                out.append(None)
            else:
                out.append(recv_np[rank])
        return out

    # ------------------------------------------------------------------
    def _build_ppermute(self, p: AggregatorPattern, mesh: Mesh, sharding,
                        low: _Lowered, split_rounds: bool):
        """One jitted shard_map program per segment; a segment covers the
        whole rep (default) or one throttle round (profile mode)."""
        n = p.nprocs
        _, jdt, w = lane_layout(p.data_size)

        seg_bounds: list[tuple[int, int]] = []
        if split_rounds and low.perms:
            start = 0
            for c in range(1, low.n_colors):
                if low.round_of_color[c] != low.round_of_color[c - 1]:
                    seg_bounds.append((start, c))
                    start = c
            seg_bounds.append((start, low.n_colors))
        else:
            seg_bounds.append((0, low.n_colors))

        ss_dev = put_global(low.sslot_tab, sharding)
        rs_dev = put_global(low.rslot_tab, sharding)

        def rep_body(send, recv, sslot, rslot, c0, c1):
            # one device's slice of color steps [c0, c1): send (S, w),
            # recv (R+1, w), sslot/rslot (C,). Shared by the timed
            # segments and the chained-measurement scan so the chained
            # program cannot drift from the program it measures.
            zero = jnp.zeros((w,), dtype=jdt)

            def emit_barriers(recv, rnd):
                # real barriers of this round (m=17 in-round, m=13/-b
                # and m=19 after-round): an all-reduce over LIVE data,
                # its result written into the trash row (which the
                # program returns), so it can neither constant-fold nor
                # be DCE'd. (A previous `& 0` version folded away —
                # verified via optimized HLO.)
                for _ in range(low.barrier_rounds.get(rnd, 0)):
                    tok = lax.psum(recv[0, 0].astype(jnp.int32), AXIS)
                    recv = recv.at[low.n_recv_slots, 0].set(
                        tok.astype(jdt))
                return recv

            prev_round = None
            for ci in range(c0, c1):
                rnd = low.round_of_color[ci]
                if prev_round is not None and rnd != prev_round:
                    # throttle-round boundary: keep XLA from fusing across
                    recv = emit_barriers(recv, prev_round)
                    send, recv = lax.optimization_barrier((send, recv))
                prev_round = rnd
                ss = sslot[ci]
                val = jnp.where(ss >= 0,
                                jnp.take(send, jnp.maximum(ss, 0), axis=0,
                                         mode="clip"),
                                zero)
                got = lax.ppermute(val, AXIS, low.perms[ci])
                recv = lax.dynamic_update_index_in_dim(
                    recv, got, rslot[ci], axis=0)
            if prev_round is not None:
                recv = emit_barriers(recv, prev_round)
            return recv

        def make_segment(c0: int, c1: int):
            def local_fn(send, recv, sslot, rslot):
                return rep_body(send[0], recv[0], sslot[0], rslot[0],
                                c0, c1)[None]

            sm = _compat_shard_map(
                local_fn, mesh=mesh,
                in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
                out_specs=P(AXIS))
            jf = jax.jit(sm)

            def seg(send, recv):
                # tables ride as ARGUMENTS, not jit closures: closing
                # over an array spanning non-addressable devices is
                # rejected on multi-controller runtimes (the 2-process
                # bring-up path, parallel/bringup.py)
                return jf(send, recv, ss_dev, rs_dev)

            return seg

        def make_chain(iters: int, upto_colors: int | None = None):
            from tpu_aggcomm.harness.chained import scanned_chain

            # ``upto_colors`` truncates every rep to its first color
            # steps (a round-prefix boundary) — the measure_round_times
            # prefixes, through this SAME scaffold so dispatch and
            # scaffold cost cancel identically
            cN = low.n_colors if upto_colors is None else upto_colors

            def chain_local(send, sslot, rslot):
                rep = lambda s, recv0: rep_body(         # noqa: E731
                    s, recv0, sslot[0], rslot[0], 0, cN)
                inner = scanned_chain(rep, n_recv_slots=low.n_recv_slots,
                                      w=w, jdt=jdt, axis=AXIS, iters=iters)
                return inner(send[0])[None]

            csm = _compat_shard_map(chain_local, mesh=mesh,
                                in_specs=(P(AXIS),) * 3, out_specs=P(AXIS))
            cjf = jax.jit(csm)

            def chain(send):
                return cjf(send, ss_dev, rs_dev)

            return chain

        segs = [make_segment(c0, c1) for c0, c1 in seg_bounds]
        # one segment per round in split mode -> its round id, for mapping
        # measured segment times onto TimerBucket weights; None for the
        # whole-rep single segment
        seg_rounds = ([low.round_of_color[c0] for c0, _c1 in seg_bounds]
                      if split_rounds and len(seg_bounds) > 1 else None)
        return segs, seg_rounds, make_chain

    # ------------------------------------------------------------------
    def _build_dense(self, p: AggregatorPattern, mesh: Mesh):
        """m=5/8: one lax.all_to_all of dst-major rows with masked slots.

        Inside shard_map each device builds an (nprocs, ds) dst-major row
        matrix from its slabs; all_to_all exchanges row d of device s to
        row s of device d; receivers scatter rows into recv slots. The slot
        maps are direction-static (the sdispls/rdispls analog)."""
        n = p.nprocs
        ndt, _, _w = lane_layout(p.data_size)
        agg_index = np.asarray(p.agg_index)
        if p.direction is Direction.ALL_TO_MANY:
            n_recv_slots = n
            sslot_of = agg_index                      # slab index for dst
            rslot_of = np.arange(n)                   # row from src -> slot src
        else:
            n_recv_slots = p.cb_nodes
            sslot_of = np.arange(n)
            rslot_of = agg_index
        sslot_c = jnp.asarray(np.maximum(sslot_of, 0), dtype=jnp.int32)
        smask = jnp.asarray((sslot_of >= 0).astype(ndt))[:, None]
        rslot_c = jnp.asarray(
            np.where(rslot_of >= 0, rslot_of, n_recv_slots), dtype=jnp.int32)

        _, jdt, w = lane_layout(p.data_size)

        def rep_body(send, recv):
            rows = jnp.take(send, sslot_c, axis=0) * smask   # (n, w) dst-major
            got = lax.all_to_all(rows, AXIS, split_axis=0, concat_axis=0)
            return recv.at[rslot_c].set(got)

        def local_fn(send, recv):
            return rep_body(send[0], recv[0])[None]

        sm = _compat_shard_map(local_fn, mesh=mesh,
                           in_specs=(P(AXIS), P(AXIS)), out_specs=P(AXIS))

        def make_chain(iters: int):
            from tpu_aggcomm.harness.chained import scanned_chain

            def chain_local(send):
                inner = scanned_chain(rep_body, n_recv_slots=n_recv_slots,
                                      w=w, jdt=jdt, axis=AXIS, iters=iters)
                return inner(send[0])[None]

            csm = _compat_shard_map(chain_local, mesh=mesh,
                                in_specs=(P(AXIS),), out_specs=P(AXIS))
            return jax.jit(csm)

        return jax.jit(sm), make_chain
