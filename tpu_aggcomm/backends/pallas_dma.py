"""Pallas remote-DMA backend: one-sided pushes with explicit semaphores.

The TPU-native transport tier for the *synchronization-sensitive* methods
(SURVEY.md §7 hard part (1)): ``lax.ppermute`` has no notion of a
synchronous send, so the congestion behavior the reference studies with
MPI_Issend (m=6/7/11/12/18) only exists on TPU as explicit semaphore
protocol. This backend runs a whole rep as ONE Pallas kernel per device,
built from **permutation-DMA steps**: each step, every chip issues exactly
one ``make_async_remote_copy`` along a full permutation of the mesh
(schedule edges completed with self-loops), then waits its send and its
arrival semaphores. Steps:

- one data step per color (the same bipartite-coloring lowering the
  jax_ici backend uses), pushing the sender's slab directly into the
  receiver's recv-buffer slot — one-sided, like the reference's
  aggregation writes;
- **rendezvous (Issend) = CTS-before-RTS**: methods built on Issend get a
  grant step (the reverse permutation) before each data step — the
  receiver's chip must explicitly clear the sender before data moves. The
  reference's m=18 control-signal handshake (mpi_test.c:1283-1301) is this
  protocol made explicit: on this backend it is simply the transport.
- reference MPI_Barrier rounds = a **dissemination barrier** of
  ``ceil(log2 n)`` rotation steps (round k rotates by 2^k): every step
  waits for its arrival before the next begins, so the happens-before
  chain closes transitively over all chips — the same log-depth pattern
  MPI libraries use for MPI_Barrier, expressed in permutation steps
  (a naive everyone-hears-everyone barrier is n steps and would dominate
  the step count of barrier-heavy methods like m=17 at pod scale).

Design note: steps are SPMD-uniform — non-participating chips move a dummy
row to their own trash slot — because divergent (``pl.when``-gated) remote
DMA is neither interpretable nor good TPU practice; the volume overhead is
one row per idle chip per step. Per-phase timing inside one kernel is not
host-observable; phase columns are filled by the fenced-segment
attribution of the whole-rep wall time (harness/attribution.py), and the
native backend carries direct per-op host timing.

Runs compiled on real TPU meshes and in Pallas interpret mode on the
virtual CPU mesh (auto-selected off-TPU), so the same kernel is testable
everywhere.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_aggcomm.compat import shard_map as _compat_shard_map
from tpu_aggcomm.compat import tpu_compiler_params as _compat_compiler_params
from tpu_aggcomm.core.schedule import Schedule
from tpu_aggcomm.harness.attribution import attribute_total, weights_for
from tpu_aggcomm.harness.timer import Timer
from tpu_aggcomm.harness.verify import make_send_slabs, recv_slot_counts
from tpu_aggcomm.obs import trace

__all__ = ["PallasDmaBackend", "barrier_shifts", "complete_permutation"]

AXIS = "ranks"


def _pad128(x: int) -> int:
    return (x + 127) // 128 * 128


def barrier_shifts(n: int) -> list[int]:
    """Rotation amounts of the dissemination barrier: 1, 2, 4, … < n —
    ``ceil(log2 n)`` steps (empty for n == 1, where a barrier is a no-op)."""
    out = []
    k = 1
    while k < n:
        out.append(k)
        k *= 2
    return out


def complete_permutation(pairs: list[tuple[int, int]], n: int) -> np.ndarray:
    """Extend a partial permutation (unique srcs, unique dsts) to a full
    bijection on [0, n): unmatched sources are paired with unmatched
    destinations (self first when possible). Returns dst_of (n,)."""
    dst_of = np.full(n, -1, dtype=np.int64)
    used_dst = np.zeros(n, dtype=bool)
    for s, d in pairs:
        dst_of[s] = d
        used_dst[d] = True
    free_src = [i for i in range(n) if dst_of[i] < 0]
    free_dst = [i for i in range(n) if not used_dst[i]]
    # prefer self-loops, then pair the rest in order
    for i in list(free_src):
        if i in free_dst:
            dst_of[i] = i
            free_src.remove(i)
            free_dst.remove(i)
    for s, d in zip(free_src, free_dst):
        dst_of[s] = d
    return dst_of


class PallasDmaBackend:
    """Executes schedules as semaphore-synchronized remote-DMA kernels.

    Two posting disciplines (VERDICT r3 item 2):

    - **lockstep** (default): every permutation step posts one DMA and
      immediately waits its send + arrival — deterministic, at most one
      in-flight copy per chip, the baseline whose delivery every other
      mode is pinned against.
    - **concurrent** (``concurrent=True``, registry name
      ``pallas_dma_conc``): a round's DMAs are ALL posted before any
      wait, waits drain at round end — the reference's Issend storm
      followed by Waitall (mpi_test.c:1789-1815), so the in-flight copy
      count per round actually equals the throttle ``-c`` and copies
      genuinely contend for ICI. Rendezvous methods keep CTS-before-RTS
      at round granularity: all grant steps of the round post and drain
      BEFORE any data step posts. Dissemination-barrier steps stay
      lockstep always (round k+1's rotation may not start before round
      k's arrival — that ordering IS the barrier).

    Concurrent-mode benign race: idle chips' dummy rows and grant tokens
    from several steps of one wave land in the same trash slot of the
    same receiver; all such payloads are identical zeros, so the outcome
    is deterministic (real payload slots are written by exactly one step
    per wave — slot tables are unique per (src, dst, round)).
    """

    def __init__(self, devices=None, interpret: bool | None = None,
                 concurrent: bool = False):
        self._devices = devices
        self._interpret = interpret
        self._concurrent = concurrent
        self.name = "pallas_dma_conc" if concurrent else "pallas_dma"
        self._cache: dict = {}
        # delegate backends are kept for the object's lifetime so their
        # compile caches survive across iterations of a sweep
        self._sim_delegate = None
        self._ici_delegate = None

    def run(self, schedule, *, ntimes: int = 1, iter_: int = 0,
            verify: bool = False):
        from tpu_aggcomm.tam.engine import TamMethod
        if ntimes < 1:
            raise ValueError("ntimes must be >= 1")
        if isinstance(schedule, TamMethod):
            # TAM is a separate engine behind the registry (the reference's
            # extern boundary, mpi_test.c:34-38); on this backend the
            # hierarchical route runs device-resident via jax_sim so
            # `--backend pallas_dma -m 0` covers m=15/16 (VERDICT r1 item 2)
            from tpu_aggcomm.backends.jax_sim import JaxSimBackend
            if self._sim_delegate is None:
                self._sim_delegate = JaxSimBackend(
                    device=self._devices[0] if self._devices else None)
            sb = self._sim_delegate
            out = sb.run(schedule, ntimes=ntimes, iter_=iter_, verify=verify)
            self.last_rep_timers = getattr(sb, "last_rep_timers", [])
            self.last_provenance = sb.last_provenance
            return out
        if schedule.collective:
            # dense vendor-collective methods belong to lax.all_to_all;
            # delegate so `--backend pallas_dma -m 0` still covers them
            from tpu_aggcomm.backends.jax_ici import JaxIciBackend
            if self._ici_delegate is None:
                self._ici_delegate = JaxIciBackend(self._devices)
            jb = self._ici_delegate
            out = jb.run(schedule, ntimes=ntimes, iter_=iter_, verify=verify)
            self.last_rep_timers = jb.last_rep_timers
            self.last_provenance = jb.last_provenance
            return out

        self.last_provenance = (self.name, "attributed")
        p = schedule.pattern
        n = p.nprocs
        devs = list(self._devices) if self._devices is not None else jax.devices()
        if len(devs) < n:
            raise ValueError(f"pattern needs {n} devices, have {len(devs)}")
        interpret = (self._interpret if self._interpret is not None
                     else devs[0].platform != "tpu")
        mesh = Mesh(np.array(devs[:n]), (AXIS,))
        sharding = NamedSharding(mesh, P(AXIS))

        fn, pds, n_send_slots, n_recv_slots, tabs, _waves = self._lower(
            schedule, mesh, interpret)

        # slab arenas padded to the DMA row size; one extra dummy row at the
        # end feeds the uniform self-loop steps. Each slab row is shaped
        # (4, pds/4) so the tiled trailing dims are always copied WHOLE and
        # the dynamic slot index lands on an untiled leading dim — Mosaic
        # rejects dynamic slices of the sublane dim and slice sizes not
        # aligned to the i8 tiling (4, 128) (both surfaced by the first
        # compiled v5e runs; interpret mode accepts anything)
        slabs = make_send_slabs(p, iter_)
        send_g = np.zeros((n, n_send_slots + 1, pds), dtype=np.uint8)
        for r, s in enumerate(slabs):
            if s is not None:
                send_g[r, :s.shape[0], :p.data_size] = s
        send_g = send_g.reshape(n, n_send_slots + 1, 4, pds // 4)
        send_dev = jax.device_put(send_g, sharding)
        tab_devs = [jax.device_put(t, sharding) for t in tabs]

        fn(send_dev, *tab_devs).block_until_ready()  # warm-up compile

        timers = [Timer() for _ in range(n)]
        self.last_rep_timers = []
        attr_w = weights_for(schedule)
        out = None
        for rep in range(ntimes):
            with trace.span(f"{self.name}.dispatch", rep=rep,
                            method=schedule.name):
                t0 = time.perf_counter()
                out = fn(send_dev, *tab_devs)
                out.block_until_ready()
                dt = time.perf_counter() - t0
            # whole-rep wall time split onto the TimerBucket structure
            # (fenced-segment approximation, harness/attribution.py) —
            # in-kernel step timestamps remain future work
            rep_attr = attribute_total(schedule, dt, weights=attr_w)
            for r, t in enumerate(timers):
                t += rep_attr[r]
            self.last_rep_timers.append(rep_attr)

        recv_w = np.asarray(jax.device_get(out))
        recv_np = recv_w.reshape(n, recv_w.shape[1], -1)[:, :n_recv_slots,
                                                         :p.data_size]
        counts = recv_slot_counts(p)
        recv_bufs = [recv_np[r] if counts[r] else None for r in range(n)]
        if verify:
            from tpu_aggcomm.harness.verify import verify_recv
            verify_recv(p, recv_bufs, iter_)
        return recv_bufs, timers

    # ------------------------------------------------------------------
    def wave_profile(self, schedule: Schedule) -> dict:
        """Step/wave accounting of the lowered program — the instrument
        for the lockstep-vs-concurrent comparison (VERDICT r4 item 2): a
        wave's width IS its in-flight DMA count (every step of a wave is
        posted before any wait), so ``max_in_flight`` is where the
        throttle ``-c`` becomes physical concurrency. Returns
        ``{"steps", "n_waves", "widths", "max_in_flight"}``; both
        disciplines have identical step counts (the same DMAs move), only
        the wave partition differs — the law the tests pin."""
        (_low, _pds, _tabs, WAVES, _n_recv_slots) = self._build_steps(
            schedule)
        widths = [s1 - s0 for (s0, s1) in WAVES]
        return {"steps": sum(widths), "n_waves": len(widths),
                "widths": widths, "max_in_flight": max(widths)}

    def _build_steps(self, schedule: Schedule):
        """Host-side step tables + wave partition (shared by _lower and
        wave_profile, one definition so the accounting can never drift
        from the program it describes)."""
        from tpu_aggcomm.backends.jax_ici import lower_schedule

        p = schedule.pattern
        n = p.nprocs
        pds = _pad128(p.data_size)
        low = lower_schedule(schedule)
        rtable = schedule.recv_slot_table()
        rdv = bool(schedule.uses_rendezvous)
        n_recv_slots = low.n_recv_slots
        trash = n_recv_slots            # recv trash row index
        dummy = low.n_send_slots        # send dummy row index

        # Build the uniform permutation-step program: per step, tables of
        # (dst, src, send slot, remote recv slot) for every device — plus
        # the WAVE structure: a wave is a span of steps whose DMAs are all
        # posted before any wait (lockstep mode: every wave is one step;
        # concurrent mode: a round's grant steps form one wave and its
        # data steps another, so in-flight copies per round = throttle c)
        step_dst: list[np.ndarray] = []
        step_src: list[np.ndarray] = []
        step_sslot: list[np.ndarray] = []
        step_rslot: list[np.ndarray] = []
        waves: list[tuple[int, int]] = []

        def add_step(dst_of: np.ndarray, sslot: np.ndarray,
                     rslot: np.ndarray):
            src_of = np.empty(n, dtype=np.int64)
            src_of[dst_of] = np.arange(n)
            step_dst.append(dst_of.astype(np.int32))
            step_src.append(src_of.astype(np.int32))
            step_sslot.append(sslot.astype(np.int32))
            step_rslot.append(rslot.astype(np.int32))

        def add_barrier():
            # dissemination barrier in ceil(log2 n) rotation steps: round k
            # signals (i + 2^k) mod n; because every step's wait_recv gates
            # the next step's send, chip i transitively synchronizes with
            # all n chips after the last round — log depth, not O(n).
            # ALWAYS lockstep (one-step waves), in both modes: the gating
            # IS the barrier
            for k in barrier_shifts(n):
                dst_of = (np.arange(n) + k) % n
                s0 = len(step_dst)
                add_step(dst_of, np.full(n, dummy), np.full(n, trash))
                waves.append((s0, s0 + 1))

        def grant_step(pairs):
            # CTS grant: the reverse permutation (receiver -> sender)
            cts_pairs = [(d, s) for (s, d) in pairs]
            add_step(complete_permutation(cts_pairs, n),
                     np.full(n, dummy), np.full(n, trash))

        def data_step(c):
            pairs = low.perms[c]
            sslot = np.full(n, dummy, dtype=np.int64)
            rslot = np.full(n, trash, dtype=np.int64)
            for (s, d) in pairs:
                sslot[s] = int(low.sslot_tab[s, c])
                rslot[s] = rtable[(s, d)]   # sender-side view of remote slot
            add_step(complete_permutation(pairs, n), sslot, rslot)

        # init barrier: no data may land before every chip has zeroed its
        # recv buffer (the reference's MPI_Barrier after prepare_*, e.g.
        # mpi_test.c:1762). Tokens landing early only touch the trash row.
        add_barrier()

        C = low.n_colors
        conc = self._concurrent
        cols_of_round: dict[int, list[int]] = {}
        for c in range(C):
            cols_of_round.setdefault(low.round_of_color[c], []).append(c)
        for rnd in sorted(cols_of_round):
            cols = cols_of_round[rnd]
            if conc:
                # the Issend storm: post the whole round, then drain —
                # grants fully drain before any data posts (rendezvous
                # stays CTS-before-RTS at round granularity)
                if rdv:
                    s0 = len(step_dst)
                    for c in cols:
                        grant_step(low.perms[c])
                    waves.append((s0, len(step_dst)))
                s0 = len(step_dst)
                for c in cols:
                    data_step(c)
                waves.append((s0, len(step_dst)))
            else:
                for c in cols:
                    if rdv:
                        s0 = len(step_dst)
                        grant_step(low.perms[c])
                        waves.append((s0, s0 + 1))
                    s0 = len(step_dst)
                    data_step(c)
                    waves.append((s0, s0 + 1))
            for _ in range(low.barrier_rounds.get(rnd, 0)):
                add_barrier()

        NS = len(step_dst)
        WAVES = tuple(waves)
        assert NS == sum(s1 - s0 for s0, s1 in WAVES)
        dst_tab = np.stack(step_dst, axis=1)      # (n, NS)
        src_tab = np.stack(step_src, axis=1)
        sslot_tab = np.stack(step_sslot, axis=1)
        rslot_tab = np.stack(step_rslot, axis=1)
        tabs = (dst_tab, src_tab, sslot_tab, rslot_tab)
        return low, pds, tabs, WAVES, n_recv_slots

    def _lower(self, schedule: Schedule, mesh: Mesh, interpret: bool):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        p = schedule.pattern
        n = p.nprocs
        (low, pds, tabs, WAVES, n_recv_slots) = self._build_steps(schedule)
        dst_tab, src_tab, sslot_tab, rslot_tab = tabs

        cache_key = (p, interpret, WAVES, dst_tab.tobytes(),
                     sslot_tab.tobytes(), rslot_tab.tobytes())
        if cache_key in self._cache:
            return self._cache[cache_key]

        R1 = n_recv_slots + 1

        def kernel(dst_r, src_r, sslot_r, rslot_r, send_r, recv0_r, recv_r,
                   ssem, rsem):
            # recv_r aliases the zero-initialized recv0 input — Mosaic
            # forbids direct stores into ANY-space refs (first compiled-on-
            # TPU run surfaced this; interpret mode had allowed it), so the
            # zeroing happens in XLA before the kernel
            del recv0_r

            def out_dma(st):
                return pltpu.make_async_remote_copy(
                    src_ref=send_r.at[0, pl.ds(sslot_r[0, st], 1)],
                    dst_ref=recv_r.at[0, pl.ds(rslot_r[0, st], 1)],
                    send_sem=ssem, recv_sem=rsem,
                    device_id=dst_r[0, st],
                    device_id_type=pltpu.DeviceIdType.LOGICAL)

            def in_dma(st):
                # descriptor for my arrival of this step (every chip
                # receives exactly one row per step; uniform sizes keep
                # semaphore accounting exact)
                return pltpu.make_async_remote_copy(
                    src_ref=send_r.at[0, pl.ds(0, 1)],
                    dst_ref=recv_r.at[0, pl.ds(rslot_r[0, st], 1)],
                    send_sem=ssem, recv_sem=rsem,
                    device_id=src_r[0, st],
                    device_id_type=pltpu.DeviceIdType.LOGICAL)

            # per wave: post EVERY step's DMA, then drain sends, then
            # drain arrivals — lockstep builds one-step waves (post, wait,
            # wait), concurrent builds round-wide waves (the Issend storm
            # then Waitall, mpi_test.c:1789-1815)
            for (s0, s1) in WAVES:
                dmas = [out_dma(st) for st in range(s0, s1)]
                for rdma in dmas:
                    rdma.start()
                for rdma in dmas:
                    rdma.wait_send()
                for st in range(s0, s1):
                    in_dma(st).wait_recv()

        def outer(send, dst_a, src_a, sslot_a, rslot_a):
            recv0 = jnp.zeros((1, R1, 4, pds // 4), jnp.uint8)
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((1, R1, 4, pds // 4),
                                               jnp.uint8),
                in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 4
                + [pl.BlockSpec(memory_space=pl.ANY)] * 2,
                out_specs=pl.BlockSpec(memory_space=pl.ANY),
                scratch_shapes=[pltpu.SemaphoreType.DMA,
                                pltpu.SemaphoreType.DMA],
                # collective_id coordinates the cross-chip barrier at kernel
                # entry; Mosaic rejects it on a single-device mesh (no
                # custom barrier there — surfaced by the compiled v5e run)
                compiler_params=_compat_compiler_params(
                    has_side_effects=True,
                    collective_id=0 if n > 1 else None),
                input_output_aliases={5: 0},
                interpret=interpret,
            )(dst_a, src_a, sslot_a, rslot_a, send, recv0)

        sm = _compat_shard_map(outer, mesh=mesh,
                           in_specs=(P(AXIS),) * 5, out_specs=P(AXIS),
                           check_vma=False)
        fn = jax.jit(sm)
        result = (fn, pds, low.n_send_slots, n_recv_slots, list(tabs),
                  WAVES)
        self._cache[cache_key] = result
        return result
