"""Single-chip Pallas kernel for the hot op: the fused slab exchange.

The headline exchange (reference README config: every rank's slab delivered
to every aggregator, 32x14x2048 B) is a static row permutation plus the
chain perturbation that makes serial reps irreducible. XLA executes this as
transpose + gather + elementwise (two passes over the data, and it handles
uint8 layouts poorly — measured 4-5x slower than the same program on a
uint32 view). This kernel fuses permutation and perturbation into ONE VMEM
pass per rep:

- data is viewed as uint32 lanes (4 payload bytes per element — Mosaic has
  no i8 vector ALU); the perturbation is XOR with the rep index replicated
  into every byte (``r * 0x01010101``), which is byte-exact equivalent to
  per-byte XOR, so payload semantics stay byte-level;
- the aggregator-order permutation is baked in as ``cb_nodes`` static
  slice copies (one per output row group) — the create_aggregator_list
  placement (mpi_test.c:1952-2006) compiled into the kernel;
- at this size the whole working set is VMEM-resident (~0.9 MB in a 16 MB
  VMEM); the measured per-rep latency is kernel-call + VMEM-bandwidth
  bound, the single-chip analog of the reference's cache-resident 32-rank
  run.

Measured on a v5e chip: ~1.7 us per serial rep vs ~9 us for the XLA uint8
formulation (bench.py uses this path on TPU, with the XLA chain retained
as the off-TPU fallback and as an independent cross-check).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from tpu_aggcomm.core.pattern import AggregatorPattern

__all__ = ["fused_exchange_chain", "xla_exchange_chain", "rep_word",
           "host_replay"]


def _order(p: AggregatorPattern) -> list[int]:
    """Aggregator-row order: ascending aggregator rank (row j of the recv
    buffer belongs to the j-th aggregator by rank)."""
    return [int(x) for x in np.argsort(np.asarray(p.rank_list))]


def _lane_width(p: AggregatorPattern) -> int:
    """Words per slab on the uint32-lane layout; every entry point shares
    this check so the fallback/replay cannot accept (and truncate) inputs
    the kernel rejects."""
    if p.data_size % 4:
        raise ValueError("data_size must be a multiple of 4 for the "
                         "uint32-lane kernel")
    return p.data_size // 4


def rep_word(r):
    """The rep-index perturbation word: index byte replicated in every lane
    byte, so XOR-ing it equals a per-byte XOR."""
    return (r.astype(jnp.uint32) & 0xFF) * jnp.uint32(0x01010101)


def fused_exchange_chain(p: AggregatorPattern, iters: int, *,
                         interpret: bool = False):
    """Jitted chain(send0) running ``iters`` serially-dependent reps of the
    fused Pallas exchange. ``send0``: (nprocs, cb_nodes, data_size//4)
    uint32. Returns the final send state (same shape).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, cb, w = p.nprocs, p.cb_nodes, _lane_width(p)
    order = _order(p)

    def kernel(r_ref, in_ref, out_ref):
        rword = r_ref[0]
        for j, oj in enumerate(order):
            # recv row j = every rank's slab for aggregator j, perturbed
            out_ref[j] = in_ref[:, oj, :] ^ rword

    def exchange(send32, rword):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((cb, n, w), jnp.uint32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=interpret,
        )(rword.reshape(1), send32)

    @jax.jit
    def chain(send0):
        def body(send, r):
            out = exchange(send, rep_word(r))
            return out.reshape(n, cb, w), ()
        out, _ = lax.scan(body, send0, jnp.arange(iters, dtype=jnp.int32),
                          unroll=1)
        return out

    return chain


def host_replay(p: AggregatorPattern, send0: np.ndarray,
                iters: int) -> np.ndarray:
    """Exact numpy replay of the chain — the ground truth both device
    formulations are checked against. One definition, shared by bench.py
    and the tests, so the perturbation semantics cannot drift."""
    order = np.argsort(np.asarray(p.rank_list))
    n, cb, w = p.nprocs, p.cb_nodes, _lane_width(p)
    ref = np.asarray(send0)
    for r in range(iters):
        recv = np.transpose(ref, (1, 0, 2))[order]
        ref = recv.reshape(n, cb, w) ^ np.uint32((r & 0xFF) * 0x01010101)
    return ref


def xla_exchange_chain(p: AggregatorPattern, iters: int):
    """The same chain expressed in plain XLA (transpose + gather + xor) —
    the off-TPU path and the independent cross-check for the kernel."""
    n, cb, w = p.nprocs, p.cb_nodes, _lane_width(p)
    order_j = jnp.asarray(np.asarray(_order(p), dtype=np.int32))

    @jax.jit
    def chain(send0):
        def body(send, r):
            recv = jnp.take(jnp.transpose(send, (1, 0, 2)), order_j, axis=0)
            return recv.reshape(n, cb, w) ^ rep_word(r), ()
        out, _ = lax.scan(body, send0, jnp.arange(iters, dtype=jnp.int32),
                          unroll=1)
        return out

    return chain
