"""Sharded rank-axis backend: B logical ranks per device over a real mesh.

The reference's flagship configuration is 16,384 MPI ranks on 256 nodes
(script_theta_all_to_many_256.sh:3,11) — far more ranks than any TPU slice
has chips. jax_sim solves that on ONE chip by carrying the whole rank set
as an array axis; this backend is the multi-chip generalization
DISTRIBUTED.md describes ("64 logical ranks per chip, shard_map over the
rank axis"): the rank axis is sharded over a 1-D device mesh, each device
owning a contiguous block of ``B = nprocs / ndev`` ranks (the same
contiguous node map static_node_assignment type 0 fabricates,
lustre_driver_test.c:359-429 — so a "device" is a "node" of logical
ranks and inter-device traffic is exactly the inter-node traffic).

Lowering (TPU-idiomatic, not a translation): one throttle round = one
padded **block all_to_all** over the device axis. On the host we group the
round's (src, dst) edges by (src device, dst device) block, pad every
block to the round's max block size M, and build two static index tables:

- ``pack[a, b, j]``  — flat local send index of the j-th message device a
  ships to device b (-1 = padding, contributes zeros);
- ``scat[b, a, j]``  — flat local recv index where device b lands the
  j-th message from device a (trash element for padding).

Each device gathers its outgoing blocks, one ``lax.all_to_all`` exchanges
them, and a static scatter lands the payload — per round, fenced with
``lax.optimization_barrier`` so the ``-c`` throttle rounds stay distinct
program steps (SURVEY.md §7 hard part 2). Reference MPI_Barrier rounds
become live ``psum`` tokens, as on jax_ici. Traffic per round is the
round's true message volume times a small padding factor (blocks padded
to M), never the dense n² — the dense methods (m=5/8 Alltoallw) reuse the
same machinery as a single round containing every pattern edge.

TAM methods (m=15/16) run the jax_sim 3-hop index-map route jitted with
rank-axis shardings — XLA's SPMD partitioner inserts the collectives for
the cross-device gathers (the "annotate shardings, let XLA insert
collectives" recipe); the explicit two-level engine lives in jax_ici.

Timing: whole-rep wall time, phases filled by the fenced-segment
attribution (harness/attribution.py), exactly like jax_sim.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_aggcomm.backends.lanes import lane_layout, lanes_to_bytes, to_lanes
from tpu_aggcomm.compat import pcast as _compat_pcast
from tpu_aggcomm.compat import shard_map as _compat_shard_map
from tpu_aggcomm.core.pattern import AggregatorPattern, Direction
from tpu_aggcomm.core.schedule import (Schedule, barrier_rounds_of,
                                       schedule_shape_key)
from tpu_aggcomm.harness.attribution import (attribute_rounds,
                                             attribute_total, weights_for)
from tpu_aggcomm.harness.timer import Timer
from tpu_aggcomm.harness.verify import make_send_slabs, recv_slot_counts
from tpu_aggcomm.obs import trace

__all__ = ["JaxShardBackend", "block_round_tables"]

AXIS = "dev"


def _apply_block_round(flat_send, recv, pk, sc, nbar: int, F: int, w: int,
                       jdt, single_dev: bool = False):
    """One throttle round on one device's shard: gather the round's
    outgoing blocks, one lax.all_to_all over the device axis, static
    scatter of the landed payload, then the round's barriers as live psum
    tokens into the trash row. Shared by the whole-rep program, the
    scanned-round program, and the profile_rounds segments so the
    profiled decomposition cannot drift from the program it decomposes
    (the jax_sim `_apply_round` precedent).

    ``single_dev``: on a 1-device mesh (the single-chip flagship tier,
    RESULTS_TPU.md) the all_to_all is the identity — skip it AND the
    padding mask, so XLA can fuse the round into ONE gather-scatter pass
    instead of materializing the packed blocks around a collective
    boundary (roofline: drops two arena walks per round; padded entries
    scatter into the trash row, which is never read back, so the mask is
    semantically dead here). Byte-equality with the general path is
    pinned by tests."""
    if single_dev:
        got = jnp.take(flat_send, jnp.maximum(pk, 0).reshape(-1), axis=0)
        recv = recv.at[sc.reshape(-1)].set(got)
    else:
        vals = jnp.where(
            (pk >= 0)[..., None],
            jnp.take(flat_send, jnp.maximum(pk, 0), axis=0),
            jnp.zeros((w,), jdt))
        got = lax.all_to_all(vals, AXIS, 0, 0)      # (ndev, M, w)
        recv = recv.at[sc.reshape(-1)].set(got.reshape(-1, w))
    for _ in range(nbar):
        tok = lax.psum(recv[0, 0].astype(jnp.int32), AXIS)
        recv = recv.at[F - 1, 0].set(tok.astype(jdt))
    return recv


def _schedule_edges(schedule: Schedule) -> np.ndarray:
    """(src, dst, sslot, dslot, round) int64 rows for every payload edge,
    with receive slots resolved (vectorized recv_slot_table lookup — the
    dict walk is O(E) Python either way, but the per-edge joins here are
    numpy). Collective schedules (m=5/8) synthesize the full pattern as a
    single round: the Alltoallw's whole exchange is one program step, as
    in the reference (mpi_test.c:627-645).

    Fault handling: dead-link-repaired schedules carry relay staging rows
    and chan != 0 detour edges the compacted block layout cannot
    represent — clean refusal (the detour route runs on local/jax_sim;
    dead-AGGREGATOR repair regenerates a healthy program and runs here
    fine). UNREPAIRED dead links are realized by dropping the dead
    chan-0 edges from the block tables (faults/inject.dead_edge_mask
    semantics) — the run then fails --verify, which is the injection
    working."""
    p = schedule.pattern
    n = p.nprocs
    if getattr(schedule, "n_staging", 0):
        raise ValueError(
            f"m={schedule.method_id} ({schedule.name}) is a dead-link-"
            f"repaired schedule (fault={schedule.fault!r}): jax_shard's "
            f"block lowering cannot represent relay staging rows; run the "
            f"detour route on --backend local or jax_sim")
    if schedule.collective:
        agg_index = np.asarray(p.agg_index)
        if p.direction is Direction.ALL_TO_MANY:
            srcs = np.repeat(np.arange(n), p.cb_nodes)
            dsts = np.tile(np.asarray(p.rank_list), n)
            sslots = np.tile(np.arange(p.cb_nodes), n)
            dslots = srcs
        else:
            srcs = np.repeat(np.asarray(p.rank_list), n)
            dsts = np.tile(np.arange(n), p.cb_nodes)
            sslots = dsts
            dslots = agg_index[srcs]
        rounds = np.zeros(len(srcs), dtype=np.int64)
        return _drop_dead_edges(
            np.stack([srcs, dsts, sslots, dslots, rounds],
                     axis=1).astype(np.int64), schedule)

    edges = schedule.data_edges()
    if len(edges) == 0:
        return edges.reshape(0, 5)
    rt = schedule.recv_slot_table()
    keys = np.empty(len(rt), dtype=np.int64)
    vals = np.empty(len(rt), dtype=np.int64)
    for i, ((s, d), slot) in enumerate(rt.items()):
        keys[i] = s * n + d
        vals[i] = slot
    order = np.argsort(keys)
    keys, vals = keys[order], vals[order]
    ekeys = edges[:, 0] * n + edges[:, 1]
    pos = np.searchsorted(keys, ekeys)
    out = edges.copy()
    out[:, 3] = vals[pos]
    return _drop_dead_edges(out, schedule)


def _drop_dead_edges(edges: np.ndarray, schedule: Schedule) -> np.ndarray:
    """UNREPAIRED fault realization: drop the chan-0 edges named dead (all
    edges here are chan-0 — staged schedules were refused above)."""
    fault = getattr(schedule, "fault", None)
    if not fault or len(edges) == 0:
        return edges
    from tpu_aggcomm.faults.spec import parse_fault
    dead = set(parse_fault(fault).deadlinks)
    if not dead:
        return edges
    keep = np.array([(int(s), int(d)) not in dead
                     for s, d in edges[:, :2]], dtype=bool)
    return edges[keep]


def recv_layout(counts: np.ndarray, ndev: int, bsz: int):
    """Compacted per-device recv layout: only ranks that receive get rows
    (all-to-many non-aggregators own zero recv slabs, mpi_test.c:162-202
    — padding them to nprocs rows each would be 1000x the needed memory
    at flagship scale). Returns (base, F): ``base[rank]`` = offset of the
    rank's first row in its device's flat recv buffer (-1 if it receives
    nothing), ``F`` = uniform per-device buffer length incl. 1 trash row.
    """
    n = len(counts)
    base = np.full(n, -1, dtype=np.int64)
    F = 1
    for dev in range(ndev):
        off = 0
        for r in range(dev * bsz, min((dev + 1) * bsz, n)):
            if counts[r]:
                base[r] = off
                off += int(counts[r])
        F = max(F, off + 1)
    return base, F


def block_round_tables(edges: np.ndarray, *, ndev: int, bsz: int,
                       send_base: np.ndarray, recv_base: np.ndarray,
                       F: int):
    """Per-round (pack, scat, M) block tables for the device all_to_all.

    pack: (ndev, ndev, M) flat local-send indices (send_base[src] + sslot,
    -1 pad); scat: (ndev, ndev, M) flat local-recv indices (recv_base[dst]
    + dslot), b-major (scat[b, a, j] matches the all_to_all output block
    from device a), trash = F - 1 for padding. Vectorized group-by, so the
    flagship edge counts (4M+ edges) stay in numpy.
    """
    trash = F - 1
    out = []
    if len(edges) == 0:
        return out
    n_rounds = int(edges[:, 4].max()) + 1
    for r in range(n_rounds):
        sel = edges[edges[:, 4] == r]
        if len(sel) == 0:
            continue
        src, dst, sslot, dslot = sel[:, 0], sel[:, 1], sel[:, 2], sel[:, 3]
        sdev, ddev = src // bsz, dst // bsz
        pair = sdev * ndev + ddev
        order = np.argsort(pair, kind="stable")
        pair_s = pair[order]
        counts = np.bincount(pair_s, minlength=ndev * ndev)
        M = int(counts.max())
        # position of each edge within its (a, b) block
        starts = np.zeros(ndev * ndev, dtype=np.int64)
        np.cumsum(counts[:-1], out=starts[1:])
        pos = np.arange(len(sel)) - starts[pair_s]
        pack = np.full((ndev * ndev, M), -1, dtype=np.int32)
        scat = np.full((ndev * ndev, M), trash, dtype=np.int32)
        pack[pair_s, pos] = (send_base[src] + sslot)[order]
        scat[pair_s, pos] = (recv_base[dst] + dslot)[order]
        pack = pack.reshape(ndev, ndev, M)
        # b-major view: device b's landing table over source devices a
        scat = scat.reshape(ndev, ndev, M).transpose(1, 0, 2).copy()
        out.append((r, pack, scat, M))
    return out


class JaxShardBackend:
    """Executes schedules with the rank axis sharded over a device mesh."""

    name = "jax_shard"

    def __init__(self, devices=None, ranks_per_device=None):
        self._devices = devices
        self._ranks_per_device = ranks_per_device
        self._cache: dict = {}
        self._chain_cache: dict = {}   # schedule key -> measured per-rep s

    def _mesh(self, nprocs: int) -> tuple[Mesh, int]:
        from tpu_aggcomm.parallel import host_major_devices
        devs = host_major_devices(self._devices)
        if self._ranks_per_device:
            b = self._ranks_per_device
            if nprocs % b:
                raise ValueError(
                    f"ranks_per_device={b} must divide nprocs={nprocs}")
            d = nprocs // b
            if d > len(devs):
                raise ValueError(
                    f"nprocs={nprocs} at {b} ranks/device needs {d} "
                    f"devices, have {len(devs)}")
        else:
            d = min(len(devs), nprocs)
            while nprocs % d:
                d -= 1
        return Mesh(np.array(devs[:d]), (AXIS,)), d

    # ------------------------------------------------------------------
    def _tam_grid(self, schedule, devs):
        """Resolve the (ndev, (Dn, Dl)) device grid for the blocked TAM
        engine, or None when an explicit ranks_per_device split has no
        fitting factorization — shared by the plain and chained TAM
        routes so they can never resolve different grids."""
        from tpu_aggcomm.tam.engine import sharded_grid

        p = schedule.pattern
        na = schedule.assignment
        N = na.nnodes
        L = int(na.node_sizes.max())        # Lmax: ragged maps allowed
        if self._ranks_per_device and p.nprocs % self._ranks_per_device:
            # same contract as _mesh on every other route: an invalid
            # explicit split raises, it is never silently floor-divided
            raise ValueError(
                f"ranks_per_device={self._ranks_per_device} must divide "
                f"nprocs={p.nprocs}")
        ndev = (p.nprocs // self._ranks_per_device
                if self._ranks_per_device else min(len(devs), p.nprocs))
        while ndev > 0:
            try:
                grid = sharded_grid(N, L, ndev)
                break
            except ValueError:
                if self._ranks_per_device:
                    return None             # explicit split doesn't fit
                ndev -= 1
        if ndev <= 0 or ndev > len(devs):
            return None
        return ndev, grid

    def _run_tam_chained(self, schedule, iter_: int, ntimes: int,
                         verify: bool):
        """TAM with chained (differenced) timing through the blocked
        engine: delivery + verification from one plain rep; per-rep
        seconds from the engine's serial-chain scaffold; per-rank
        columns by the byte-weighted TAM split of the measured total."""
        from tpu_aggcomm.parallel import host_major_devices
        from tpu_aggcomm.tam.engine import tam_two_level_sharded

        devs = host_major_devices(self._devices)
        resolved = self._tam_grid(schedule, devs)
        if resolved is None:
            return None
        ndev, grid = resolved
        p = schedule.pattern
        # ONE plain rep: delivery/verification AND the chain-seed state
        # (a separate chained call would re-run and discard a twin rep —
        # through the tunnel that doubles the non-chain cost)
        recv_bufs, _times, st = tam_two_level_sharded(
            schedule, devs[:ndev], iter_, 1, mesh_shape=grid,
            cache=self._cache, return_state=True)
        from tpu_aggcomm.harness.chained import differenced_per_rep
        per_rep = differenced_per_rep(
            st["make_chain"], st["last_send_dev"],
            iters_small=20, iters_big=220, trials=3, windows=2)
        self.last_provenance = ("jax_shard", "attributed-chained")
        attr_w = weights_for(schedule)
        rep_attr = attribute_total(schedule, per_rep, weights=attr_w)
        timers = [Timer() for _ in range(p.nprocs)]
        for r, t in enumerate(timers):
            t += Timer.from_array(rep_attr[r].as_array() * ntimes)
        self.last_rep_timers = [
            [Timer.from_array(t.as_array()) for t in rep_attr]
            for _ in range(ntimes)]
        self.last_round_times = []
        if verify:
            from tpu_aggcomm.harness.verify import verify_recv
            verify_recv(p, recv_bufs, iter_)
        return recv_bufs, timers

    def _run_tam_sharded(self, schedule, iter_: int, ntimes: int,
                         verify: bool, profile_rounds: bool):
        """m=15/16 through the explicit blocked two-level engine
        (tam_two_level_sharded): B logical ranks per device on a
        (node, local) grid — the collective_write relay as two padded
        block all_to_alls, NOT the sharded-jax_sim one-rep route. Ragged
        node maps run this route too (the engine pads blocks to
        ceil(N/Dn) x ceil(Lmax/Dl), lustre_driver_test.c:374-386 analog);
        the only remaining fallback (return None) is an explicit
        ranks_per_device split whose device count has no factorization
        fitting inside the (N, Lmax) topology."""
        from tpu_aggcomm.parallel import host_major_devices
        from tpu_aggcomm.tam.engine import tam_two_level_sharded

        devs = host_major_devices(self._devices)
        resolved = self._tam_grid(schedule, devs)
        if resolved is None:
            return None
        ndev, grid = resolved
        p = schedule.pattern
        recv_bufs, rep_times = tam_two_level_sharded(
            schedule, devs[:ndev], iter_, ntimes, mesh_shape=grid,
            cache=self._cache)
        attr_w = weights_for(schedule)
        timers = [Timer() for _ in range(p.nprocs)]
        self.last_rep_timers = []
        self.last_round_times = []
        for dt in rep_times:
            rep_attr = attribute_total(schedule, dt, weights=attr_w)
            for r, t in enumerate(timers):
                t += rep_attr[r]
            self.last_rep_timers.append(rep_attr)
            if profile_rounds:
                # whole rep = the single profiled segment (no round
                # structure in the 3-hop route), as on jax_sim
                self.last_round_times.append([dt])
        if verify:
            from tpu_aggcomm.harness.verify import verify_recv
            verify_recv(p, recv_bufs, iter_)
        return recv_bufs, timers

    # ------------------------------------------------------------------
    def _slots(self, p: AggregatorPattern) -> tuple[int, int]:
        from tpu_aggcomm.harness.verify import slot_shapes
        return slot_shapes(p)

    def _key(self, schedule):
        return schedule_shape_key(schedule)

    # ------------------------------------------------------------------
    def _compiled(self, schedule):
        """(jitted fn, mesh, ndev, bsz, table device arrays)."""
        from tpu_aggcomm.tam.engine import TamMethod

        key = self._key(schedule)
        if key in self._cache:
            return self._cache[key]

        p = schedule.pattern
        n = p.nprocs
        mesh, ndev = self._mesh(n)
        bsz = n // ndev
        n_send_slots, n_recv_slots = self._slots(p)
        _, jdt, w = lane_layout(p.data_size)
        sharding = NamedSharding(mesh, P(AXIS))

        if isinstance(schedule, TamMethod):
            # XLA-partitioned 3-hop TAM route: same program as jax_sim,
            # rank axis sharded; SPMD inserts the cross-device collectives
            from tpu_aggcomm.backends.jax_sim import JaxSimBackend
            rep = JaxSimBackend().one_rep(schedule)
            fn = jax.jit(rep, in_shardings=sharding,
                         out_shardings=sharding)
            built = (fn, mesh, ndev, bsz, None)
            self._cache[key] = built
            return built

        (counts, recv_base, F, send_base, Fs, tabs,
         barrier_rounds) = self._layout_and_tabs(schedule, ndev, bsz)
        round_ids = [r for (r, *_rest) in tabs]
        # slow-rank fault injection: per-DEVICE delay-loop iterations
        # (ranks sharing a device serialize on its core, so the device's
        # busy work is the sum over its slow ranks), appended after the
        # rounds INSIDE the rep — the chained differenced measurement
        # serializes it, and round semantics are untouched
        slow_dev = None
        if getattr(schedule, "fault", None):
            from tpu_aggcomm.faults.inject import slow_iter_table
            from tpu_aggcomm.faults.spec import parse_fault
            tbl = slow_iter_table(parse_fault(schedule.fault), n,
                                  max(len(tabs), 1))
            per_dev = tbl.reshape(ndev, bsz).sum(axis=1).astype(np.int32)
            if per_dev.any():
                slow_dev = jnp.asarray(per_dev)

        def add_slow(flat_send, recv):
            """Data-dependent busy loop XLA cannot fold away, closed by a
            provably-zero (statically opaque) delta into a live cell —
            bytes unchanged, the loop survives DCE (jax_sim precedent)."""
            if slow_dev is None:
                return recv
            it = slow_dev[lax.axis_index(AXIS)]
            row = flat_send[0].astype(jnp.uint32)

            def body(i, a):
                return a + jnp.sum((row + i.astype(jnp.uint32)) % 251)

            acc = lax.fori_loop(0, it, body, jnp.uint32(0))
            delta = ((acc & 1) * ((acc + 1) & 1)).astype(jdt)
            return recv.at[0, 0].add(delta)
        # Many-round schedules compile O(rounds) unrolled; barrier-free
        # ones (the flagship sweep's m=1/m=2) scan instead: tables padded
        # to the max block width, rounds sequenced by the scan carry (the
        # -c fence), compile O(1) in round count. Barrier methods keep the
        # unrolled body (an in-scan psum would add a collective to every
        # round and distort what the benchmark measures).
        scan_rounds = len(tabs) >= 32 and not barrier_rounds
        if scan_rounds:
            R = len(tabs)
            Mmax = max(m for (_r, _pk, _sc, m) in tabs)
            ndev_ = ndev
            pk_t = np.full((R, ndev_, ndev_, Mmax), -1, dtype=np.int32)
            sc_t = np.full((R, ndev_, ndev_, Mmax), F - 1, dtype=np.int32)
            for k, (_r, pk, sc, m) in enumerate(tabs):
                pk_t[k, :, :, :m] = pk
                sc_t[k, :, :, :m] = sc
            # device-major so P(AXIS) shards the per-device slice
            pack_dev = [jax.device_put(pk_t.transpose(1, 0, 2, 3),
                                       sharding)]
            scat_dev = [jax.device_put(sc_t.transpose(1, 0, 2, 3),
                                       sharding)]

            def rep_body(flat_send, packs, scats, upto=None):
                # ``upto`` (static) truncates to the first upto rounds —
                # the prefix programs measure_round_times differences;
                # both prefixes and the full rep share this one lowering
                pks = packs[0][0]           # (R, ndev, Mmax)
                scs = scats[0][0]
                if upto is not None:
                    pks, scs = pks[:upto], scs[:upto]

                def body(recv, x):
                    pk, sc = x
                    recv = _apply_block_round(flat_send, recv, pk, sc,
                                              0, F, w, jdt,
                                              single_dev=ndev == 1)
                    return recv, ()

                recv0 = jnp.zeros((F, w), dtype=jdt)
                # the all_to_all output is varying over the mesh axis; the
                # constant initial carry must be cast to match
                recv0 = _compat_pcast(recv0, (AXIS,), to="varying")
                recv, _ = lax.scan(body, recv0, (pks, scs), unroll=1)
                return add_slow(flat_send, recv)
        else:
            pack_dev = [jax.device_put(pk, sharding)
                        for (_r, pk, _sc, _m) in tabs]
            scat_dev = [jax.device_put(sc, sharding)
                        for (_r, _pk, sc, _m) in tabs]

            def rep_body(flat_send, packs, scats, upto=None):
                # one whole rep on this device's shard: flat_send (Fs, w);
                # packs/scats: list of (1, ndev, M); ``upto`` as above
                kk = len(packs) if upto is None else upto
                recv = jnp.zeros((F, w), dtype=jdt)
                for k in range(kk):
                    recv = _apply_block_round(
                        flat_send, recv, packs[k][0], scats[k][0],
                        barrier_rounds.get(round_ids[k], 0), F, w, jdt,
                        single_dev=ndev == 1)
                    if k + 1 < kk:
                        flat_send, recv = lax.optimization_barrier(
                            (flat_send, recv))
                return add_slow(flat_send, recv)

        def local_fn(send, packs, scats):
            return rep_body(send[0], packs, scats)[None]

        sm = _compat_shard_map(
            local_fn, mesh=mesh,
            in_specs=(P(AXIS), [P(AXIS)] * len(pack_dev), [P(AXIS)] * len(pack_dev)),
            out_specs=P(AXIS))

        @jax.jit
        def fn(send):
            return sm(send, pack_dev, scat_dev)

        def make_chain(iters: int, upto: int | None = None):
            """iters serially-dependent reps in ONE program (the chained
            differenced-measurement scaffold, harness/chained.py): rep
            r+1's send is XOR-perturbed by a psum over rep r's delivered
            state, so reps can neither fuse nor elide and every device
            depends on every other device's previous rep. ``upto``
            truncates every rep to its first upto rounds (the
            measure_round_times prefixes) through this SAME scaffold, so
            dispatch and scaffold cost cancel identically."""
            def chain_local(send, packs, scats):
                def body(flat_send, r):
                    recv = rep_body(flat_send, packs, scats, upto)
                    # token = cross-device checksum of the delivered state
                    # (psum makes rep r+1 depend on EVERY device's rep r)
                    tok = (lax.psum(
                        jnp.sum(recv[:F - 1, 0].astype(jnp.uint32)),
                        AXIS).astype(jnp.int32) + r) % 251
                    from tpu_aggcomm.harness.chained import xor_word
                    return flat_send ^ xor_word(tok, jdt), ()
                out, _ = lax.scan(body, send[0],
                                  jnp.arange(iters, dtype=jnp.int32),
                                  unroll=1)
                return out[None]

            csm = _compat_shard_map(
                chain_local, mesh=mesh,
                in_specs=(P(AXIS), [P(AXIS)] * len(pack_dev),
                          [P(AXIS)] * len(pack_dev)),
                out_specs=P(AXIS))

            @jax.jit
            def chain(send):
                return csm(send, pack_dev, scat_dev)

            return chain

        built = (fn, mesh, ndev, bsz,
                 (Fs, send_base, recv_base, counts, make_chain, round_ids))
        self._cache[key] = built
        return built

    # ------------------------------------------------------------------
    def _layout_and_tabs(self, schedule, ndev: int, bsz: int):
        """Shared host-side lowering for _compiled and _round_segments:
        compacted flat layouts (only ranks that send/receive get rows — a
        dense (n, nprocs)-slot layout would be n² at flagship scale),
        per-round block tables, barrier rounds, and the orphan-barrier
        check — one code path, so the profiled segments can never
        decompose a different program than the whole-rep build runs, and
        both modes accept exactly the same schedules."""
        p = schedule.pattern
        n = p.nprocs
        counts = np.asarray(recv_slot_counts(p))
        recv_base, F = recv_layout(counts, ndev, bsz)
        if p.direction is Direction.ALL_TO_MANY:
            scounts = np.full(n, p.cb_nodes, dtype=np.int64)
        else:
            scounts = np.where(np.asarray(p.agg_index) >= 0, n, 0)
        send_base, Fs = recv_layout(scounts, ndev, bsz)
        tabs = block_round_tables(_schedule_edges(schedule), ndev=ndev,
                                  bsz=bsz, send_base=send_base,
                                  recv_base=recv_base, F=F)
        barrier_rounds = barrier_rounds_of(schedule)
        orphans = set(barrier_rounds) - {r for (r, *_rest) in tabs}
        if orphans:
            raise ValueError(
                f"schedule {schedule.name!r} has barrier-only rounds "
                f"{sorted(orphans)}; the block lowering cannot represent "
                f"a standalone fence")
        return counts, recv_base, F, send_base, Fs, tabs, barrier_rounds

    def _round_segments(self, schedule):
        """Per-round jitted (send, recv) -> recv shard_map programs plus
        their round ids and layout artifacts, for profile_rounds; None for
        TAM (the 3-hop route has no throttle-round structure to split) and
        for the dense collective methods (one synthesized round, nothing
        to decompose — and jax_sim's profiled mode excludes them too).
        Each segment is one `_apply_block_round` — the same function the
        whole-rep program is built from."""
        from tpu_aggcomm.tam.engine import TamMethod
        if isinstance(schedule, TamMethod) or schedule.collective:
            return None
        if getattr(schedule, "fault", None) or getattr(schedule,
                                                       "n_staging", 0):
            # per-round segments would omit the injected slow work (it
            # lives outside the round structure) — the profiled
            # decomposition would drift from the program it decomposes
            return None
        key = (self._key(schedule), "segments")
        if key in self._cache:
            return self._cache[key]
        p = schedule.pattern
        n = p.nprocs
        mesh, ndev = self._mesh(n)
        bsz = n // ndev
        _, jdt, w = lane_layout(p.data_size)
        sharding = NamedSharding(mesh, P(AXIS))
        (counts, recv_base, F, send_base, Fs, tabs,
         barrier_rounds) = self._layout_and_tabs(schedule, ndev, bsz)
        segs, round_ids = [], []
        for (r, pk, sc, _M) in tabs:
            pk_dev = jax.device_put(pk, sharding)
            sc_dev = jax.device_put(sc, sharding)

            def make_seg(pk_dev=pk_dev, sc_dev=sc_dev,
                         nbar=barrier_rounds.get(r, 0)):
                def local(send, recv, pkl, scl):
                    return _apply_block_round(send[0], recv[0], pkl[0],
                                              scl[0], nbar, F, w, jdt,
                                              single_dev=ndev == 1)[None]

                sm = _compat_shard_map(local, mesh=mesh,
                                   in_specs=(P(AXIS),) * 4,
                                   out_specs=P(AXIS))

                @jax.jit
                def seg(send, recv):
                    return sm(send, recv, pk_dev, sc_dev)

                return seg

            segs.append(make_seg())
            round_ids.append(r)
        self._cache[key] = (segs, round_ids, mesh, ndev, bsz, F, Fs,
                            send_base, recv_base, counts)
        return self._cache[key]

    def _run_profiled(self, schedule, iter_: int, verify: bool,
                      ntimes: int, profiled):
        """profile_rounds execution: one dispatch per throttle round, each
        synced and timed, mapped onto the TimerBucket structure — exactly
        jax_sim's profiled mode on the sharded tier (per-dispatch sync
        overhead included; schedule-shape analysis, not headline numbers)."""
        (segs, round_ids, mesh, ndev, bsz, F, Fs, send_base, recv_base,
         counts) = profiled
        p = schedule.pattern
        n = p.nprocs
        ndt, jdt, w = lane_layout(p.data_size)
        sharding = NamedSharding(mesh, P(AXIS))
        send_dev = jax.device_put(
            self._global_send_flat(p, iter_, ndev, bsz, send_base, Fs),
            sharding)
        # one zeros template, reused as every rep's initial carry (arrays
        # are immutable; re-uploading fresh zeros per rep would add an
        # H2D transfer per rep through the tunnel)
        recv0 = jax.device_put(np.zeros((ndev, F, w), dtype=ndt), sharding)

        recv = recv0
        for seg in segs:                   # warm-up compile every segment
            recv = seg(send_dev, recv)
        recv.block_until_ready()

        timers = [Timer() for _ in range(n)]
        self.last_rep_timers = []
        self.last_round_times = []
        attr_w = weights_for(schedule)
        out = None
        for rep in range(ntimes):
            recv = recv0
            round_times = []
            for rnd, seg in zip(round_ids, segs):
                with trace.span("jax_shard.round", rep=rep, round=rnd,
                                method=schedule.name):
                    ts = time.perf_counter()
                    recv = seg(send_dev, recv)
                    recv.block_until_ready()
                    round_times.append(time.perf_counter() - ts)
            out = recv
            self.last_round_times.append(round_times)
            rep_attr = attribute_rounds(
                schedule, dict(zip(round_ids, round_times)), weights=attr_w)
            for r, t in enumerate(timers):
                t += rep_attr[r]
            self.last_rep_timers.append(rep_attr)

        got_b = lanes_to_bytes(np.asarray(jax.device_get(out)), p.data_size)
        recv_bufs = [
            got_b[r // bsz,
                  int(recv_base[r]):int(recv_base[r]) + int(counts[r])]
            if counts[r] else None
            for r in range(n)]
        if verify:
            from tpu_aggcomm.harness.verify import verify_recv
            verify_recv(p, recv_bufs, iter_)
        return recv_bufs, timers

    # ------------------------------------------------------------------
    def _global_send_flat(self, p: AggregatorPattern, iter_: int,
                          ndev: int, bsz: int, send_base: np.ndarray,
                          Fs: int) -> np.ndarray:
        """Compact (ndev, Fs, w) layout: each sender's slabs at its
        send_base offset in its device's flat buffer."""
        slabs = make_send_slabs(p, iter_)
        out = np.zeros((ndev, Fs, p.data_size), dtype=np.uint8)
        for r, s in enumerate(slabs):
            if s is not None:
                b = int(send_base[r])
                out[r // bsz, b:b + s.shape[0]] = s
        return to_lanes(out, p.data_size)

    def measure_per_rep(self, schedule, *, iters_small: int = 50,
                        iters_big: int = 1050, trials: int = 3,
                        windows: int = 3) -> float:
        """Serial-chained differenced per-rep seconds on the device mesh
        (harness/chained.py) — the honest multi-chip measurement: reps run
        back-to-back inside one compiled program, rep r+1's send perturbed
        by a psum over rep r's delivery, dispatch overhead differenced
        away. Cached per schedule (iteration-invariant)."""
        from tpu_aggcomm.harness.chained import differenced_per_rep
        from tpu_aggcomm.tam.engine import TamMethod

        if isinstance(schedule, TamMethod):
            raise ValueError(
                "TAM has no round-program chain here; chained TAM on "
                "jax_shard rides the blocked engine — call "
                "run(schedule, chained=True) (or use jax_sim)")
        key = (self._key(schedule), iters_small, iters_big, trials, windows)
        if key in self._chain_cache:
            return self._chain_cache[key]
        p = schedule.pattern
        fn, mesh, ndev, bsz, extra = self._compiled(schedule)
        (Fs, send_base, _recv_base, _counts, make_chain, _rids) = extra
        sharding = NamedSharding(mesh, P(AXIS))
        send0 = jax.device_put(
            self._global_send_flat(p, 0, ndev, bsz, send_base, Fs),
            sharding)
        per_rep = differenced_per_rep(make_chain, send0,
                                      iters_small=iters_small,
                                      iters_big=iters_big,
                                      trials=trials, windows=windows)
        self._chain_cache[key] = per_rep
        return per_rep

    def measure_trial_samples(self, schedule, *, iters_small: int = 50,
                              iters_big: int = 1050, trials: int = 3,
                              windows: int = 1) -> list[float]:
        """FRESH per-trial differenced seconds on the sharded tier for
        the autotuner (tune/measure.py) — jax_sim's cache-bypassing hook
        riding the shard_map chain scaffold: only the jitted chain pair
        and the initial sharded send buffer are memoized (per schedule
        and chain lengths), the SAMPLES are never cached, so every
        racing batch re-TIMES without re-COMPILING. Refusals are the
        backend's own, by name (TAM has no round chain; staged
        dead-link repairs are refused in the table lowering)."""
        from tpu_aggcomm.harness.chained import differenced_trials
        from tpu_aggcomm.tam.engine import TamMethod

        if isinstance(schedule, TamMethod):
            raise ValueError(
                "TAM has no round-program chain here; tune TAM "
                "candidates on jax_sim")
        key = (self._key(schedule), "tune_chains", iters_small, iters_big)
        if key not in self._chain_cache:
            p = schedule.pattern
            _fn, mesh, ndev, bsz, extra = self._compiled(schedule)
            (Fs, send_base, _recv_base, _counts, make_chain, _rids) = extra
            sharding = NamedSharding(mesh, P(AXIS))
            send0 = jax.device_put(
                self._global_send_flat(p, 0, ndev, bsz, send_base, Fs),
                sharding)
            chains = {iters_small: make_chain(iters_small),
                      iters_big: make_chain(iters_big)}
            self._chain_cache[key] = (chains, send0)
        chains, send0 = self._chain_cache[key]
        samples = differenced_trials(lambda it: chains[it], send0,
                                     iters_small=iters_small,
                                     iters_big=iters_big,
                                     trials=trials, windows=windows)
        self.last_samples = list(samples)
        return list(samples)

    def measure_round_times(self, schedule, *, iters_small: int = 50,
                            iters_big: int = 1050, trials: int = 3,
                            windows: int = 3,
                            max_rounds: int = 64) -> dict:
        """MEASURED per-round durations on the sharded tier by chained
        round-prefix truncation differencing — jax_sim's
        ``measure_round_times`` riding the shard_map chain scaffold: for
        each k the chain runs reps truncated to rounds 0..k-1 (full
        fidelity, same lowering, same psum perturbation), and round k's
        duration is the differenced increment. Increments are clamped at
        0 and rescaled to sum exactly to the full-rep chain time (the
        additivity contract). Zero per-round dispatch sync — the accuracy
        upgrade over ``--profile-rounds`` (VERDICT r4 item 3). Returns
        ``{round id: seconds}``; cached per schedule."""
        from tpu_aggcomm.tam.engine import TamMethod

        if isinstance(schedule, TamMethod) or schedule.collective:
            raise ValueError(
                "measured round times need a round-structured schedule "
                "(TAM and the dense collectives have no gather/deliver "
                "round decomposition to truncate)")
        if getattr(schedule, "fault", None) or getattr(schedule,
                                                       "n_staging", 0):
            raise ValueError(
                "measured round times are not supported on fault-injected "
                "schedules (round-prefix truncation would replay the "
                "injected delay once per prefix); use --chained timing")
        p = schedule.pattern
        fn, mesh, ndev, bsz, extra = self._compiled(schedule)
        (Fs, send_base, _recv_base, _counts, make_chain, round_ids) = extra
        R = len(round_ids)
        if R > max_rounds:
            raise ValueError(
                f"{R} rounds exceeds max_rounds={max_rounds} (one chain "
                f"family is compiled per round); use profile_rounds for "
                f"very deep schedules")
        key = (self._key(schedule), "round_times", iters_small, iters_big,
               trials, windows)
        if key in self._chain_cache:
            return self._chain_cache[key]
        per_full = self.measure_per_rep(schedule, iters_small=iters_small,
                                        iters_big=iters_big, trials=trials,
                                        windows=windows)
        sharding = NamedSharding(mesh, P(AXIS))
        send0 = jax.device_put(
            self._global_send_flat(p, 0, ndev, bsz, send_base, Fs),
            sharding)
        from tpu_aggcomm.harness.chained import differenced_round_times
        out = differenced_round_times(
            lambda k: (lambda iters: make_chain(iters, upto=k)),
            send0, round_ids, per_full, iters_small=iters_small,
            iters_big=iters_big, trials=trials, windows=windows)
        self._chain_cache[key] = out
        return out

    def run(self, schedule, *, ntimes: int = 1, iter_: int = 0,
            verify: bool = False, chained: bool = False,
            profile_rounds: bool = False, measured_phases: bool = False):
        from tpu_aggcomm.tam.engine import TamMethod

        if ntimes < 1:
            raise ValueError("ntimes must be >= 1")
        if chained and profile_rounds:
            raise ValueError("chained and profile_rounds are exclusive "
                             "(one program vs per-round programs)")
        if measured_phases and profile_rounds:
            raise ValueError("measured_phases and profile_rounds are "
                             "exclusive (truncation-differenced rounds vs "
                             "per-round dispatch timing)")
        self.last_provenance = ("jax_shard",
                                "attributed-chained" if chained
                                else "attributed")
        if profile_rounds:
            profiled = self._round_segments(schedule)
            if profiled is not None:
                # single-segment split = whole-rep attribution (same
                # downgrade rule as jax_sim/jax_ici)
                self.last_provenance = (
                    "jax_shard", "attributed-rounds"
                    if len(profiled[0]) > 1 else "attributed")
                return self._run_profiled(schedule, iter_, verify, ntimes,
                                          profiled)
            # TAM: no round structure to split — whole-rep timing below
        p = schedule.pattern
        n = p.nprocs
        is_tam = isinstance(schedule, TamMethod)
        if measured_phases and (is_tam or schedule.collective):
            raise ValueError(
                "measured phases need a round-structured schedule "
                "(TAM's 3-hop decomposition is measured by jax_sim's "
                "measure_tam_hops; the dense collectives have none)")
        if is_tam and chained:
            # honest flagship-TAM timing: the blocked engine's chain
            # scaffold — delivery and verification from the same rep
            # whose state seeds the chain
            out = self._run_tam_chained(schedule, iter_, ntimes, verify)
            if out is not None:
                return out
            raise ValueError(
                "chained TAM on jax_shard needs a (Dn, Dl) grid for the "
                "blocked engine (explicit ranks_per_device split does "
                "not fit); use --backend jax_sim")
        if is_tam:
            out = self._run_tam_sharded(schedule, iter_, ntimes, verify,
                                        profile_rounds)
            if out is not None:
                return out
            # node map doesn't block onto a (Dn, Dl) grid: the sharded-
            # one-rep route below still covers it
        n_send_slots, n_recv_slots = self._slots(p)
        _, jdt, w = lane_layout(p.data_size)
        fn, mesh, ndev, bsz, extra = self._compiled(schedule)
        sharding = NamedSharding(mesh, P(AXIS))

        if is_tam:
            from tpu_aggcomm.backends.jax_sim import dense_send_lanes
            send_dev = jax.device_put(dense_send_lanes(p, iter_), sharding)
        else:
            (Fs, send_base, recv_base, counts, _make_chain, _rids) = extra
            send_dev = jax.device_put(
                self._global_send_flat(p, iter_, ndev, bsz, send_base, Fs),
                sharding)

        out = fn(send_dev)
        out.block_until_ready()            # warm-up compile

        timers = [Timer() for _ in range(n)]
        self.last_rep_timers = []
        self.last_round_times = []         # [rep] -> [per-round seconds]
        attr_w = weights_for(schedule)
        if measured_phases:
            # per-round durations MEASURED by prefix truncation on the
            # device mesh; in-round bucket split structural (same contract
            # and provenance label as jax_sim). Single-round schedules
            # have no boundary jax_shard can measure (the 2-way
            # post/deliver split lives on jax_sim) — the trivial
            # decomposition downgrades the label to attributed-chained.
            rt = self.measure_round_times(schedule)
            if len(rt) >= 2:
                rep_attr = attribute_rounds(schedule, rt, weights=attr_w)
                self.last_provenance = (
                    "jax_shard", "measured-rounds+attributed(buckets)")
                self.last_round_times = [list(rt.values())
                                         for _ in range(ntimes)]
            else:
                rep_attr = attribute_total(
                    schedule, sum(rt.values()), weights=attr_w)
                self.last_provenance = ("jax_shard", "attributed-chained")
            for r, t in enumerate(timers):
                t += Timer.from_array(rep_attr[r].as_array() * ntimes)
            self.last_rep_timers = [
                [Timer.from_array(t.as_array()) for t in rep_attr]
                for _ in range(ntimes)]
        elif chained:
            per_rep = self.measure_per_rep(schedule)
            rep_attr = attribute_total(schedule, per_rep, weights=attr_w)
            for r, t in enumerate(timers):
                t += Timer.from_array(rep_attr[r].as_array() * ntimes)
            self.last_rep_timers = [
                [Timer.from_array(t.as_array()) for t in rep_attr]
                for _ in range(ntimes)]
        else:
            for rep in range(ntimes):
                with trace.span("jax_shard.dispatch", rep=rep,
                                method=schedule.name):
                    t0 = time.perf_counter()
                    out = fn(send_dev)
                    out.block_until_ready()
                    dt = time.perf_counter() - t0
                rep_attr = attribute_total(schedule, dt, weights=attr_w)
                for r, t in enumerate(timers):
                    t += rep_attr[r]
                self.last_rep_timers.append(rep_attr)
                if profile_rounds:
                    # TAM/collective fallback: no round structure to split
                    # — the whole rep is the single profiled segment, as
                    # on jax_sim
                    self.last_round_times.append([dt])

        got = np.asarray(jax.device_get(out))
        if is_tam:
            recv_np = lanes_to_bytes(got[:, :n_recv_slots, :], p.data_size)
            counts = recv_slot_counts(p)
            recv_bufs = [recv_np[r] if counts[r] else None
                         for r in range(n)]
        else:
            got_b = lanes_to_bytes(got, p.data_size)     # (ndev, F, ds)
            recv_bufs = [
                got_b[r // bsz,
                      int(recv_base[r]):int(recv_base[r]) + int(counts[r])]
                if counts[r] else None
                for r in range(n)]
        if verify:
            from tpu_aggcomm.harness.verify import verify_recv
            verify_recv(p, recv_bufs, iter_)
        return recv_bufs, timers
