"""pallas_fused: whole throttled schedules as ONE Pallas kernel.

The fenced jax_sim lowering dispatches one XLA program step per throttle
round; on the tunneled v5e that is 38–70 µs/rep against pallas_local's
1.72 µs dense floor (RESULTS_TPU.md). This backend lowers the SAME
schedule data through :mod:`tpu_aggcomm.native.fuse` instead: every
round's copies become in-kernel ``make_async_copy`` start/wait pairs and
the per-round semaphore drain is the fence — rounds remain distinct
program steps inside the kernel, so the ``-c`` semantics the benchmark
studies survive fusion (CLAUDE.md invariant: fusing rounds into one
wait is still forbidden; the per-round drain IS the round boundary).

Everything else rides the JaxSimBackend harness unchanged: dense
rank-axis send lanes in, ``(n, R+1, w)`` recv lanes out (trash row
last), byte-exact ``--verify`` against the local oracle, and the
chained serial-``lax.scan`` differenced timing that is the only honest
measurement through the ~60–90 ms tunnel. Unfusable schedules (TAM,
dense collectives, staged dead-link repairs, slow-rank injection)
refuse with a NAMED error — the jax_shard staged-schedule discipline —
never a silent fallback to the fenced lowering.

Off-TPU, Mosaic cannot compile the kernel: interpret mode must be asked
for explicitly (``PallasFusedBackend(interpret=True)`` or
``TPU_AGGCOMM_FUSED_INTERPRET=1``); otherwise construction of the first
rep raises :class:`FusedBackendError` naming both escape hatches, so a
CPU-only CI host can never silently "measure" the interpreter.
"""

from __future__ import annotations

import os

from tpu_aggcomm.backends.jax_sim import JaxSimBackend
from tpu_aggcomm.native.fuse import build_fused_rep, fuse_plan

__all__ = ["PallasFusedBackend", "FusedBackendError"]


class FusedBackendError(RuntimeError):
    """pallas_fused cannot run in this environment — named reason (no
    TPU and interpret mode not requested), never a silent fallback."""


class PallasFusedBackend(JaxSimBackend):
    """One fused Pallas kernel per schedule; JaxSimBackend harness."""

    name = "pallas_fused"

    def __init__(self, device=None, interpret: bool | None = None):
        super().__init__(device=device)
        if interpret is None:
            env = os.environ.get("TPU_AGGCOMM_FUSED_INTERPRET", "")
            interpret = env not in ("", "0")
        self._interpret = bool(interpret)

    def _resolve_interpret(self) -> bool:
        """True = Pallas interpreter (CPU verify path), False = Mosaic
        compile on the attached TPU. Neither available ⇒ named error."""
        if self._interpret:
            return True
        if self._dev().platform == "tpu":
            return False
        raise FusedBackendError(
            "pallas_fused: no TPU attached and interpret mode was not "
            "requested — pass PallasFusedBackend(interpret=True) or set "
            "TPU_AGGCOMM_FUSED_INTERPRET=1 for the CPU interpret "
            "(verify-only) path; Mosaic kernels compile on TPU only")

    # ------------------------------------------------------------------
    def _one_rep(self, schedule, upto: int | None = None):
        if upto is not None:
            raise ValueError(
                "pallas_fused: round-prefix truncation decomposes the "
                "fenced program family — the fused kernel is one "
                "program; measure prefixes on jax_sim")
        plan = fuse_plan(schedule)          # named refusal if unfusable
        return build_fused_rep(plan, lane=self._words(schedule.pattern),
                               interpret=self._resolve_interpret())

    def run(self, schedule, *, ntimes: int = 1, iter_: int = 0,
            verify: bool = False, chained: bool = False,
            profile_rounds: bool = False, measured_phases: bool = False):
        if profile_rounds:
            raise ValueError(
                "pallas_fused: per-round dispatch profiling re-fences "
                "the program the fusion removed — the fused rep is ONE "
                "kernel; use --profile-rounds on jax_sim")
        if measured_phases:
            raise ValueError(
                "pallas_fused: the measured phase split differences "
                "prefix programs of the FENCED lowering; use "
                "--measured-phases on jax_sim")
        return super().run(schedule, ntimes=ntimes, iter_=iter_,
                           verify=verify, chained=chained,
                           profile_rounds=profile_rounds,
                           measured_phases=measured_phases)
