"""Backend registry — the ``--backend`` plugin boundary."""

from __future__ import annotations

__all__ = ["BACKENDS", "DEVICE_FREE_BACKENDS", "SHARDED_RANK_BACKENDS",
           "SINGLE_DEVICE_BACKENDS", "get_backend"]

BACKENDS = ("local", "jax_ici", "jax_sim", "jax_shard", "pallas_dma",
            "pallas_dma_conc", "pallas_fused", "native")

# backends that execute without accelerator devices (pure host runtimes)
DEVICE_FREE_BACKENDS = ("local", "native")

# backends that carry the whole rank set on ONE device (rank count is free,
# not bounded by the visible device count)
SINGLE_DEVICE_BACKENDS = ("jax_sim", "pallas_fused")

# backends that carry MANY logical ranks per device (rank count bounded by
# memory, not the device count — the flagship-scale tier, DISTRIBUTED.md)
SHARDED_RANK_BACKENDS = ("jax_shard",)


def get_backend(name: str):
    try:
        if name == "local":
            from tpu_aggcomm.backends.local import LocalBackend
            return LocalBackend()
        if name == "jax_ici":
            from tpu_aggcomm.backends.jax_ici import JaxIciBackend
            return JaxIciBackend()
        if name == "jax_sim":
            from tpu_aggcomm.backends.jax_sim import JaxSimBackend
            return JaxSimBackend()
        if name == "jax_shard":
            from tpu_aggcomm.backends.jax_shard import JaxShardBackend
            return JaxShardBackend()
        if name == "pallas_dma":
            from tpu_aggcomm.backends.pallas_dma import PallasDmaBackend
            return PallasDmaBackend()
        if name == "pallas_dma_conc":
            # concurrent posting discipline: a round's remote copies are
            # all in flight together (in-flight = throttle c), waits
            # drain at round end — the Issend-storm mode
            from tpu_aggcomm.backends.pallas_dma import PallasDmaBackend
            return PallasDmaBackend(concurrent=True)
        if name == "pallas_fused":
            # whole throttled schedules as ONE Pallas kernel: in-kernel
            # DMA-semaphore drains are the round fences (native/fuse.py)
            from tpu_aggcomm.backends.pallas_fused import PallasFusedBackend
            return PallasFusedBackend()
        if name == "native":
            from tpu_aggcomm.backends.native import NativeBackend
            return NativeBackend()
    except ImportError as e:
        raise ValueError(f"backend {name!r} is not available here: {e}") from e
    raise ValueError(f"unknown backend {name!r}; available: {BACKENDS}")
