"""Seeded fitting machinery: weighted non-negative least squares and
rank-order statistics — pure python, jax-free, numpy-free.

The systems here are tiny (5 parameters, tens of observations), so
normal equations + Gaussian elimination with partial pivoting are exact
enough and keep the replay path dependency-free. Determinism contract:
every function is a pure function of its inputs (the bootstrap takes an
explicit seed), so ``same artifacts in => same parameters out`` — the
same discipline as the regression gate's seeded bootstrap and ``tune
--replay``.

Two fitting choices matter and are deliberate:

- **1/y relative-error weighting**: the calibration data spans 37 µs
  (n=32) to 16.5 ms (n=1024) cells; unweighted squared error would let
  the big grid drown the small one and produce parameters that rank
  n=32 backwards (observed: tau flipped to -0.9 unweighted).
- **non-negativity (active-set clamping)**: every parameter is a
  physical cost (a latency, an inverse bandwidth); a negative fitted
  coefficient is collinearity noise, not physics, and extrapolates
  catastrophically. Negative coordinates are clamped to zero and the
  remaining active set is refit — the classic NNLS outer loop,
  sufficient at this scale.
"""

from __future__ import annotations

import random

__all__ = ["FitError", "solve_normal", "nnls", "kendall_tau_b",
           "bootstrap_upper"]


class FitError(ValueError):
    """Unfittable system (no observations, all-zero design, singular
    active set). Always names what was missing."""


def solve_normal(rows: list[list[float]], y: list[float]) -> list[float]:
    """Least-squares solve of ``rows @ x ~ y`` via normal equations
    (Gauss with partial pivoting). ``rows`` must have full column rank
    over its columns; callers drop all-zero columns first."""
    if not rows:
        raise FitError("no observations to fit")
    k = len(rows[0])
    # A^T A and A^T y
    ata = [[sum(r[i] * r[j] for r in rows) for j in range(k)]
           for i in range(k)]
    aty = [sum(r[i] * yi for r, yi in zip(rows, y)) for i in range(k)]
    # Gaussian elimination, partial pivot
    m = [ata[i] + [aty[i]] for i in range(k)]
    for col in range(k):
        piv = max(range(col, k), key=lambda r: abs(m[r][col]))
        if abs(m[piv][col]) < 1e-30:
            raise FitError(
                f"singular normal equations at column {col} "
                f"(collinear or all-zero design)")
        m[col], m[piv] = m[piv], m[col]
        inv = 1.0 / m[col][col]
        for r in range(k):
            if r == col:
                continue
            f = m[r][col] * inv
            if f:
                for c in range(col, k + 1):
                    m[r][c] -= f * m[col][c]
    return [m[i][k] / m[i][i] for i in range(k)]


def nnls(rows: list[list[float]], y: list[float],
         weights: list[float] | None = None) -> list[float]:
    """Non-negative weighted least squares over ``rows @ x ~ y``.

    ``weights`` scales each observation's residual (the calibration
    passes ``1/y`` for relative error). Columns that are zero in every
    observation stay zero (they are unidentifiable here, e.g. the rpc
    column of a round-granularity fit). Returns the full-length
    coefficient vector with clamped coordinates at exactly 0.0."""
    if not rows:
        raise FitError("no observations to fit")
    k = len(rows[0])
    if weights is None:
        weights = [1.0] * len(rows)
    wrows = [[v * w for v in r] for r, w in zip(rows, weights)]
    wy = [yi * w for yi, w in zip(y, weights)]
    active = [j for j in range(k) if any(r[j] for r in wrows)]
    if not active:
        raise FitError("all-zero design matrix")
    while active:
        sub = [[r[j] for j in active] for r in wrows]
        try:
            sol = solve_normal(sub, wy)
        except FitError:
            # collinear active set: drop the last-added column and retry
            active = active[:-1]
            continue
        x = [0.0] * k
        for j, v in zip(active, sol):
            x[j] = v
        neg = [j for j in active if x[j] < 0.0]
        if not neg:
            return x
        active = [j for j in active if j not in neg]
    return [0.0] * k


def kendall_tau_b(pairs: list[tuple[float, float]]) -> float | None:
    """Kendall's tau-b over ``(predicted, measured)`` pairs — the
    tie-aware variant: tied predictions (schedules with identical
    static features are common) reduce the denominator instead of being
    silently skipped, so a model that predicts everything equal scores
    0, not 1. None with fewer than 2 pairs or all-tied input."""
    n = len(pairs)
    if n < 2:
        return None
    conc = disc = ties_p = ties_m = 0
    for i in range(n):
        for j in range(i + 1, n):
            dp = pairs[i][0] - pairs[j][0]
            dm = pairs[i][1] - pairs[j][1]
            if dp == 0 and dm == 0:
                ties_p += 1
                ties_m += 1
            elif dp == 0:
                ties_p += 1
            elif dm == 0:
                ties_m += 1
            elif (dp > 0) == (dm > 0):
                conc += 1
            else:
                disc += 1
    n0 = n * (n - 1) // 2
    den = ((n0 - ties_p) * (n0 - ties_m)) ** 0.5
    if den == 0:
        return None
    return (conc - disc) / den


def bootstrap_upper(values: list[float], *, q: float = 95.0,
                    seed: int = 0, n_boot: int = 2000,
                    upper: float = 97.5) -> float:
    """Seeded bootstrap upper confidence bound on the ``q``-th
    percentile of ``values`` — the divergence tolerance derivation:
    resample the calibration's |relative residuals|, take each
    resample's p95, report the 97.5th percentile of those. Same seed +
    same values => same bound, byte-for-byte."""
    from tpu_aggcomm.obs.metrics import percentile

    if not values:
        raise FitError("no residuals to bootstrap a tolerance from")
    rng = random.Random(seed)
    n = len(values)
    stats = []
    for _ in range(int(n_boot)):
        stats.append(percentile(
            [values[rng.randrange(n)] for _ in range(n)], q))
    return percentile(stats, upper)
