"""Calibration: platform parameters from COMMITTED artifacts only.

Two honest data sources, two granularities:

- **TPU, cell granularity** — the quiet-chip n=32/256/1024 throttle
  grids in RESULTS_TPU.md (repeatability 0-1%, measured by
  scripts/tpu_sweeps.py with ``jax_sim --chained --verify`` on one
  serial client). The markdown tables ARE the committed artifact; this
  module parses them rather than requiring a chip. The fit is held-out
  by default: parameters come from the n=256 + n=1024 grids and the
  n=32 grid is reserved for rank-order validation (model/validate.py).
- **CPU, round granularity** — per-round walls of the committed
  FAULT_*.trace.jsonl flight-recorder traces (obs.metrics.round_stats
  over the attribution cell stream), matched against the recompiled
  schedule's static round features. Slow-injected rounds are EXCLUDED
  from the fit (an injected multiplier is not a platform cost; it is
  re-applied at predict time instead), and recorded as such in the
  artifact.

Deliberately NOT calibration inputs: BENCH_r*.json headline numbers —
rounds 2-5 measured the dense ``pallas_local``/CPU-fallback path, not
the round-structured jax_sim programs the model prices; mixing
backends into one parameter set would blur both. The exclusion is
recorded in the artifact's ``inputs.excluded`` so the choice is
auditable.

Determinism: parsing is pure, features are static, the NNLS is exact,
and the tolerance bootstrap is seeded — ``build_artifact`` twice over
the same tree produces byte-identical platform blocks.
"""

from __future__ import annotations

import os
import re

from tpu_aggcomm.model.features import (PARAM_NAMES, cell_design,
                                        round_design, round_features)
from tpu_aggcomm.model.fit import FitError, bootstrap_upper, nnls

__all__ = ["ModelError", "GRID_SECTION", "parse_results_grids",
           "grid_cell_features", "calibrate_tpu", "calibrate_cpu",
           "schedule_for_run", "slow_rounds", "MIN_TOLERANCE_REL"]

#: The RESULTS_TPU.md heading whose tables are the TPU calibration set.
GRID_SECTION = "## Theta-script throttle grids"

#: Tolerance floor: a platform's fit can be tight (the TPU grids
#: reproduce within 1%), but single-trace round walls jitter more than
#: any fit residual shows — never call a divergence smaller than 10%
#: UNEXPLAINED.
MIN_TOLERANCE_REL = 0.10

_INF_COMM = 999_999_999


class ModelError(ValueError):
    """Unusable calibration input (missing grid section, malformed
    table, traces with no attributed rounds). Always names the input."""


def parse_results_grids(path: str = "RESULTS_TPU.md") -> dict:
    """The quiet-chip throttle grids out of the committed markdown.

    Returns ``{"n32": {"nprocs", "cb_nodes", "data_size", "cells":
    [{"method", "comm", "us"}, ...]}, ...}`` with cells in m-major,
    c-ascending order (the deterministic tie-break order, same contract
    as tune/race.py). The ``∞`` row parses to comm_size 999_999_999 —
    the same sentinel scripts/tpu_sweeps.py sweeps."""
    try:
        with open(path) as fh:
            text = fh.read()
    except OSError as e:
        raise ModelError(f"cannot read grid tables: {e}")
    start = text.find(GRID_SECTION)
    if start < 0:
        raise ModelError(
            f"{path}: no {GRID_SECTION!r} section — the TPU calibration "
            f"grids are gone")
    end = text.find("\n## ", start + 1)
    section = text[start:end if end > 0 else len(text)]
    m_d = re.search(r"\bd=(\d+)\b", section)
    data_size = int(m_d.group(1)) if m_d else 2048

    grids: dict = {}
    current = None
    for line in section.splitlines():
        head = re.match(r"n=(\d+), a=(\d+):", line.strip())
        if head:
            n, a = int(head.group(1)), int(head.group(2))
            current = {"nprocs": n, "cb_nodes": a, "data_size": data_size,
                       "rows": []}
            grids[f"n{n}"] = current
            continue
        row = re.match(
            r"\|\s*([0-9]+|∞)\s*\|\s*([0-9.]+)\s*\|\s*([0-9.]+)\s*\|\s*$",
            line.strip())
        if row and current is not None:
            comm = _INF_COMM if row.group(1) == "∞" else int(row.group(1))
            current["rows"].append(
                (comm, float(row.group(2)), float(row.group(3))))
    for name, g in grids.items():
        if not g["rows"]:
            raise ModelError(f"{path}: grid {name} has no table rows")
        cells = []
        for mcol, method in ((1, 1), (2, 2)):
            for comm, us1, us2 in g["rows"]:
                cells.append({"method": method, "comm": comm,
                              "us": us1 if mcol == 1 else us2})
        g["cells"] = cells
        del g["rows"]
    if not grids:
        raise ModelError(f"{path}: {GRID_SECTION!r} section holds no "
                         f"'n=NN, a=AA:' grid tables")
    return grids


def grid_cell_features(grid: dict) -> list[dict]:
    """Compile every grid cell's schedule (jax-free) and attach its
    static features: ``cells`` + ``{"features", "design"}``."""
    from tpu_aggcomm.core.methods import compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern
    from tpu_aggcomm.model.features import schedule_features

    out = []
    for cell in grid["cells"]:
        p = AggregatorPattern(nprocs=grid["nprocs"],
                              cb_nodes=grid["cb_nodes"],
                              data_size=grid["data_size"],
                              comm_size=cell["comm"])
        feats = schedule_features(compile_method(cell["method"], p))
        out.append(dict(cell, features={
            "rounds": feats["rounds"], "bytes": feats["bytes"],
            "bottleneck": feats["bottleneck"], "spill": feats["spill"]},
            design=cell_design(feats)))
    return out


def _fit_block(rows, y_s, *, seed: int, granularity: str) -> dict:
    from tpu_aggcomm.obs.metrics import percentile

    weights = [1.0 / yi for yi in y_s]
    coef = nnls(rows, y_s, weights)
    params = {name: coef[i] for i, name in enumerate(PARAM_NAMES)}
    resid = []
    for r, yi in zip(rows, y_s):
        pred = sum(a * b for a, b in zip(r, coef))
        resid.append(abs(pred - yi) / yi if yi else 0.0)
    tol = max(MIN_TOLERANCE_REL,
              bootstrap_upper(resid, seed=seed))
    return {"params": params, "granularity": granularity,
            "observations": len(rows), "seed": int(seed),
            "residual_rel": resid,
            "residual_rel_p95": percentile(resid, 95),
            "tolerance_rel": tol}


def calibrate_tpu(grids: dict, *, fit_grids=("n256", "n1024"),
                  seed: int = 0) -> dict:
    """TPU platform parameters from the quiet-chip grid cells of
    ``fit_grids`` (held-out by default: n=32 stays for validation).
    Observation = one cell's µs/rep; design = the cell's static
    features; weighting = 1/y (relative error); coefficients clamped
    non-negative."""
    rows, y_s = [], []
    for name in fit_grids:
        if name not in grids:
            raise ModelError(f"fit grid {name!r} not in the parsed "
                             f"tables ({sorted(grids)})")
        for cell in grid_cell_features(grids[name]):
            rows.append(cell["design"])
            y_s.append(cell["us"] / 1e6)
    try:
        block = _fit_block(rows, y_s, seed=seed, granularity="cell")
    except FitError as e:
        raise ModelError(f"TPU calibration failed: {e}")
    block["fit_grids"] = list(fit_grids)
    return block


def schedule_for_run(run: dict):
    """Recompile the schedule a trace run record executed — including
    the fault repair when the run carried a spec (the repaired program
    is what ran, so its detour rounds are what the model must price).
    Returns ``(schedule, FaultSpec)``. jax-free throughout
    (core + faults are PURE_PACKAGES)."""
    from tpu_aggcomm.core.methods import compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern
    from tpu_aggcomm.faults.repair import repair_schedule
    from tpu_aggcomm.faults.spec import parse_fault

    p = AggregatorPattern(
        nprocs=int(run["nprocs"]), cb_nodes=int(run["cb_nodes"]),
        data_size=int(run["data_size"]),
        comm_size=int(run["comm_size"]),
        proc_node=int(run.get("proc_node") or 1),
        placement=int(run.get("agg_type") or 1))
    sched = compile_method(int(run["method"]), p)
    spec = parse_fault(run.get("fault") or None)
    if not spec.empty:
        sched = repair_schedule(sched, spec)
    return sched, spec


def slow_rounds(per_round: list[dict], spec) -> set[int]:
    """Rounds where any slow-injected rank moves payload — their
    measured walls carry the injected multiplier most directly, so they
    are excluded from the FIT. Other rounds of a slow run stay in: on
    an attributed trace they carry a proportional share of the smeared
    per-rep delay too, which the fit absorbs as platform noise — the
    cpu block's wide ``tolerance_rel`` states that honestly, and the
    explain-time slow envelope covers every round of a slow run
    (model/predict.py)."""
    factors = spec.slow_factors()
    if not factors:
        return set()
    return {rf["round"] for rf in per_round
            if any(rf["io"].get(r, 0) > 0 for r in factors)}


def trace_round_observations(path: str) -> tuple[list, list, list]:
    """Per-round (design, wall_s) observations from one committed trace,
    plus the excluded (slow-injected) rounds and per-run notes."""
    from tpu_aggcomm.obs.metrics import round_stats
    from tpu_aggcomm.obs.trace import load_events

    events = load_events(path)
    runs = [e for e in events if e.get("ev") == "run"]
    if not runs:
        raise ModelError(f"{path}: no run records to calibrate from")
    obs, excluded, notes = [], [], []
    base = os.path.basename(path)
    for run in runs:
        sched, spec = schedule_for_run(run)
        per_round = round_features(sched)
        by_round = {rf["round"]: rf for rf in per_round}
        skip = slow_rounds(per_round, spec)
        stats = {s["round"]: s for s in round_stats(events, run["id"])
                 if isinstance(s["round"], int) and s["round"] >= 0}
        used = 0
        for rnd, rf in sorted(by_round.items()):
            st = stats.get(rnd)
            if st is None or not st["wall"]:
                continue
            if rnd in skip:
                excluded.append({
                    "trace": base, "run": run["id"], "round": rnd,
                    "reason": f"slow-injected "
                              f"({spec.canonical()}): measured wall "
                              f"carries the fault multiplier, not "
                              f"platform cost"})
                continue
            obs.append((round_design(rf), st["wall"]))
            used += 1
        notes.append({"trace": base, "run": run["id"],
                      "method": run["method"],
                      "fault": run.get("fault") or None,
                      "rounds_used": used})
    return obs, excluded, notes


def calibrate_cpu(trace_paths, *, seed: int = 0) -> dict:
    """CPU platform parameters at round granularity from committed
    traces. The rpc column is all-zero at this granularity (the
    dispatch tax is per rep) so it stays clamped at 0 — honest: these
    traces cannot identify it."""
    rows, y_s = [], []
    excluded_all, notes_all = [], []
    for path in trace_paths:
        obs, excluded, notes = trace_round_observations(path)
        for design, wall in obs:
            rows.append(design)
            y_s.append(wall)
        excluded_all.extend(excluded)
        notes_all.extend(notes)
    if not rows:
        raise ModelError(
            f"no usable round observations in {list(trace_paths)} "
            f"(every round slow-injected or unattributed?)")
    try:
        block = _fit_block(rows, y_s, seed=seed, granularity="round")
    except FitError as e:
        raise ModelError(f"CPU calibration failed: {e}")
    block["traces"] = notes_all
    block["excluded_rounds"] = excluded_all
    return block
