"""Analytic cost model: what a schedule SHOULD cost, before running it.

ROADMAP item 4's missing layer, in the spirit of HiCCL's
decomposition-based cost analysis (arxiv 2408.05962) and the
closed-form per-round bytes x incast x latency expressions of arxiv
2006.13112: the traffic auditor (obs/traffic.py) already derives every
static feature of a compiled schedule — bytes per round, per-rank
bottleneck traffic, incast depth, detour inflation under a fault spec —
and this package turns those features into **predicted round walls**
through a 5-parameter linear model per platform::

    round_wall = fence_s
               + bytes_kb      * bytes_s_per_kb        (aggregate payload)
               + bottleneck_kb * bottleneck_s_per_kb   (hottest rank's in+out)
               + spill_kb      * spill_s_per_kb        (incast beyond the
                                                        256 KB landing zone)
    rep_total  = rpc_s + sum(round_walls)

Parameters are calibrated by a seeded, relative-error-weighted
non-negative least-squares fit (model/fit.py) over COMMITTED artifacts
only — the RESULTS_TPU.md quiet-chip grids for the TPU platform,
per-round trace walls for the CPU platform — so the same artifacts in
always produce the same parameters out (the tune --replay / regression
gate seed discipline). Everything persists as ``PREDICT_*.json``
(predict-v1, obs.atomic_write, validated by obs/regress.py), replayable
byte-for-byte via ``cli inspect explain --replay``.

Predictions NEVER gate alone: they explain and prune (``inspect
explain`` verdicts, ``tune --model-prune``), while measured verdicts
stay the source of truth.

jax-free by contract (analysis/lint.py PURE_PACKAGES): the model must
price schedules precisely where a wedged tunnel hangs ``import jax`` —
the live-ETA floor (obs/live.py), the replay gate, and the tuner's
jax-free pruning path all depend on it.
"""

from tpu_aggcomm.model.artifact import (PREDICT_SCHEMA, build_artifact,
                                        load_artifact, newest_artifact,
                                        replay_artifact, save_artifact)
from tpu_aggcomm.model.calibrate import (ModelError, calibrate_cpu,
                                         calibrate_tpu, parse_results_grids)
from tpu_aggcomm.model.explain import explain_trace, render_explain
from tpu_aggcomm.model.features import (PARAM_NAMES, SPILL_THRESHOLD_BYTES,
                                        round_features, schedule_features)
from tpu_aggcomm.model.fit import kendall_tau_b, nnls
from tpu_aggcomm.model.predict import (floor_from_round_traffic,
                                       floor_from_trace_events,
                                       predict_schedule)
from tpu_aggcomm.model.validate import crossover_prediction, validate_grids

__all__ = ["PREDICT_SCHEMA", "PARAM_NAMES", "SPILL_THRESHOLD_BYTES",
           "ModelError", "build_artifact", "calibrate_cpu",
           "calibrate_tpu", "crossover_prediction", "explain_trace",
           "floor_from_round_traffic", "floor_from_trace_events",
           "kendall_tau_b", "load_artifact", "newest_artifact", "nnls",
           "parse_results_grids", "predict_schedule", "render_explain",
           "replay_artifact", "round_features", "save_artifact",
           "schedule_features", "validate_grids"]
