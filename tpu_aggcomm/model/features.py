"""Static cost features of a compiled schedule — the model's inputs.

Everything here is derived from ``obs/traffic.py`` round matrices (op
programs, never measured callbacks), so a repaired schedule's detour
rounds and a throttled schedule's extra fence rounds show up in the
features exactly as they show up in the traffic audit. jax-free.

The design vector is deliberately tiny — five physically-named terms —
because the committed calibration data is tiny (two quiet-chip grids,
two CPU traces) and a model with more knobs than honest observations
would fit noise and transfer nothing:

- **rpc** (cell-level only): one per-dispatch constant — the tunnel's
  RPC tax on TPU, ~0 on CPU.
- **rounds**: each data-edge round pays a fence/launch constant
  (``lax.optimization_barrier`` + per-round dispatch bookkeeping).
- **bytes_kb**: aggregate payload the round moves (KB) — the shared
  bandwidth term.
- **bottleneck_kb**: the hottest rank's in+out KB — the serialization
  term the reference's MAX-reduce timing actually measures.
- **spill_kb**: incoming KB beyond :data:`SPILL_THRESHOLD_BYTES` at the
  hottest destination — deep incast past the VMEM-scale landing zone
  costs disproportionally (the n>=256 m=1 funnel), while shallow
  fan-in is already priced by the bottleneck term. The threshold is a
  fixed structural constant, NOT a fitted parameter: fitting it would
  let the model memorize the grids it must predict.
"""

from __future__ import annotations

__all__ = ["PARAM_NAMES", "SPILL_THRESHOLD_BYTES", "round_features",
           "schedule_features", "cell_design", "round_design",
           "features_from_round_traffic"]

#: Incoming bytes at one destination rank in one round beyond which the
#: incast is "deep": 256 KB, the VMEM-scale landing-zone size (one v5e
#: core's VMEM is ~128 KB/lane x 8 sublanes; a funnel wider than this
#: cannot stay on-chip between DMAs). Fixed by hardware shape, not fit.
SPILL_THRESHOLD_BYTES = 262144

#: The five calibrated parameters, in design-vector order. All seconds
#: (per dispatch / per round / per KB).
PARAM_NAMES = ("rpc_s", "fence_s", "bytes_s_per_kb",
               "bottleneck_s_per_kb", "spill_s_per_kb")


def round_features(schedule) -> list[dict]:
    """Per-data-round features of one compiled schedule, round-sorted.

    Returns one dict per round: ``{"round", "bytes", "bottleneck",
    "spill", "io": {rank: bytes}, "in_bytes": {dst: bytes},
    "hot_dst"}``. ``io`` charges each payload edge's bytes to BOTH
    endpoints (src writes, dst reads — the same accounting the
    roofline's bytes-touched model uses); ``bottleneck`` is its max;
    ``spill`` is ``max(0, in_bytes[hot_dst] - SPILL_THRESHOLD_BYTES)``.
    Copies and 0-byte signals are free at this granularity: they never
    cross the wire / the fence constant already prices the handshake.

    Raises ``obs.traffic.TrafficError`` for schedules with no rank op
    programs (the TAM relay) — the same refusal as the traffic audit.
    """
    from tpu_aggcomm.obs.traffic import round_edges

    by_round = round_edges(schedule)
    out = []
    for rnd in sorted(by_round):
        edges = by_round[rnd]["edges"]
        io: dict[int, int] = {}
        in_bytes: dict[int, int] = {}
        for (src, dst), b in edges.items():
            io[src] = io.get(src, 0) + b
            io[dst] = io.get(dst, 0) + b
            in_bytes[dst] = in_bytes.get(dst, 0) + b
        hot_dst = max(in_bytes, key=lambda d: (in_bytes[d], -d)) \
            if in_bytes else None
        hot = in_bytes.get(hot_dst, 0)
        out.append({
            "round": rnd,
            "bytes": sum(edges.values()),
            "bottleneck": max(io.values()) if io else 0,
            "spill": max(0, hot - SPILL_THRESHOLD_BYTES),
            "io": io, "in_bytes": in_bytes, "hot_dst": hot_dst})
    return out


def schedule_features(schedule) -> dict:
    """Whole-cell features: the per-round list plus its sums — exactly
    the quantities the cell-level design vector consumes, so a cell
    prediction always equals the sum of its round predictions (plus
    rpc)."""
    per_round = round_features(schedule)
    return {
        "rounds": len(per_round),
        "bytes": sum(r["bytes"] for r in per_round),
        "bottleneck": sum(r["bottleneck"] for r in per_round),
        "spill": sum(r["spill"] for r in per_round),
        "per_round": per_round}


def cell_design(feats: dict) -> list[float]:
    """Design row for one whole cell (one rep): ``[1, R, bytes_kb,
    bottleneck_kb, spill_kb]`` — the rpc column is 1 (one dispatch)."""
    return [1.0, float(feats["rounds"]), feats["bytes"] / 1e3,
            feats["bottleneck"] / 1e3, feats["spill"] / 1e3]


def round_design(rf: dict) -> list[float]:
    """Design row for ONE round: the rpc column is 0 (the dispatch tax
    is paid once per rep, not per round) and the fence column is 1."""
    return [0.0, 1.0, rf["bytes"] / 1e3, rf["bottleneck"] / 1e3,
            rf["spill"] / 1e3]


def features_from_round_traffic(round_traffic: dict) -> dict:
    """Partial features from a trace run record's ``round_traffic``
    summary (``{str(round): {"msgs", "bytes", "max_incast"}}``) — the
    jax-free path ``inspect live`` uses when no schedule object exists.

    The summary has no per-rank split, so the bottleneck term is
    estimated as ``max_incast * (bytes / msgs)`` — the hottest
    destination's incoming bytes, exact for this benchmark's uniform
    slabs (span=1: every payload edge carries ``data_size`` bytes) and
    an estimate otherwise; ``spill`` derives from the same proxy.
    Predictions from these features are FLOORS, not walls."""
    per_round = []
    for key in sorted(round_traffic, key=lambda k: int(k)):
        cell = round_traffic[key] or {}
        bts = int(cell.get("bytes") or 0)
        msgs = int(cell.get("msgs") or 0)
        incast = int(cell.get("max_incast") or 0)
        hot_in = int(incast * (bts / msgs)) if msgs else 0
        per_round.append({
            "round": int(key),
            "bytes": bts,
            "bottleneck": hot_in,
            "spill": max(0, hot_in - SPILL_THRESHOLD_BYTES),
            "io": {}, "in_bytes": {}, "hot_dst": None})
    return {
        "rounds": len(per_round),
        "bytes": sum(r["bytes"] for r in per_round),
        "bottleneck": sum(r["bottleneck"] for r in per_round),
        "spill": sum(r["spill"] for r in per_round),
        "per_round": per_round}
