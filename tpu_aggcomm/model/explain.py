"""``inspect explain``: predicted-vs-measured round attribution with
NAMED divergence verdicts — jax-free end to end.

For every run record in a flight-recorder trace the schedule is
recompiled from the record's own shape + fault spec (the repaired
program, i.e. what actually ran), priced by the calibrated platform
parameters, and lined up against the measured round walls
(``obs.metrics.round_stats`` over the attribution cell stream — the
same numbers ``inspect trace`` prints, float-for-float).

Verdict taxonomy (per round):

- ``fence-bound`` / ``bandwidth-bound`` / ``incast-bound`` — the
  measured wall agrees with the prediction within the platform's seeded
  tolerance, and the named component dominates the predicted cost
  (fence constant; bytes+bottleneck; spill).
- ``slow-injected`` — the round touches a slow-injected rank and its
  measured wall lies between the healthy prediction and the
  fault-multiplied ceiling: the divergence is the INJECTED fault, fully
  attributed, not model error.
- ``UNEXPLAINED (+NN% vs model)`` — outside tolerance with no fault to
  blame. This is the verdict that matters: it is the model saying
  "something this trace did is not in my physics".

The rep-level verdict adds ``rpc-bound`` when the per-dispatch constant
dominates the predicted total (the tunnel regime).

Verdicts are advisory, like every model output: they NEVER gate alone —
measured walls stay the source of truth, the model only names suspects.
"""

from __future__ import annotations

__all__ = ["explain_trace", "explain_run", "render_explain"]


def _dominant_verdict(components: dict) -> str:
    fence = components["fence"]
    band = components["bytes"] + components["bottleneck"]
    spill = components["spill"]
    top = max(fence, band, spill)
    if top == spill and spill > 0:
        return "incast-bound"
    if top == band and band > 0:
        return "bandwidth-bound"
    return "fence-bound"


def _round_verdict(measured: float, pred: dict, tol: float) -> dict:
    """One round's verdict dict: ``{"verdict", "deviation_rel"}``."""
    base = pred["wall_s"]
    dev = (measured - base) / base if base else 0.0
    slow_wall = pred.get("slow_wall_s")
    if slow_wall is not None:
        lo, hi = base * (1.0 - tol), slow_wall * (1.0 + tol)
        if lo <= measured <= hi:
            return {"verdict": "slow-injected", "deviation_rel": dev}
        return {"verdict":
                f"UNEXPLAINED ({dev:+.0%} vs model, outside the "
                f"injected-slow envelope)",
                "deviation_rel": dev}
    if abs(dev) <= tol:
        return {"verdict": _dominant_verdict(pred["components"]),
                "deviation_rel": dev}
    return {"verdict": f"UNEXPLAINED ({dev:+.0%} vs model)",
            "deviation_rel": dev}


def explain_run(events: list[dict], run: dict, platform_block: dict,
                ) -> dict:
    """Predicted-vs-measured attribution for ONE run record."""
    from tpu_aggcomm.model.calibrate import schedule_for_run
    from tpu_aggcomm.model.features import round_features
    from tpu_aggcomm.model.predict import predict_rounds
    from tpu_aggcomm.obs.metrics import round_stats

    params = platform_block["params"]
    tol = float(platform_block.get("tolerance_rel") or 0.10)
    sched, spec = schedule_for_run(run)
    preds = predict_rounds(round_features(sched), params,
                           spec.slow_factors() or None)
    stats = {s["round"]: s for s in round_stats(events, run["id"])
             if isinstance(s["round"], int) and s["round"] >= 0}
    rows, pred_total, meas_total = [], 0.0, 0.0
    unmeasured = 0
    for pr in preds:
        st = stats.get(pr["round"])
        pred_total += pr["wall_s"]
        row = {"round": pr["round"],
               "predicted_s": pr["wall_s"],
               "components": pr["components"],
               "critical_rank_predicted": pr["critical_rank"],
               "slow_wall_s": pr["slow_wall_s"]}
        if st is None or not st["wall"]:
            row.update(measured_s=None, critical_rank_measured=None,
                       verdict="unmeasured (no attributed cells)",
                       deviation_rel=None)
            unmeasured += 1
        else:
            meas_total += st["wall"]
            row.update(measured_s=st["wall"],
                       critical_rank_measured=st["critical_rank"],
                       **_round_verdict(st["wall"], pr, tol))
        rows.append(row)

    rpc = float(params.get("rpc_s") or 0.0)
    pred_total += rpc
    total: dict = {"predicted_s": pred_total, "rpc_s": rpc,
                   "measured_s": meas_total if meas_total else None}
    if meas_total and unmeasured == 0:
        dev = (meas_total - pred_total) / pred_total if pred_total else 0.0
        total["deviation_rel"] = dev
        slow = any(r["verdict"] == "slow-injected" for r in rows)
        clean = not any(r["verdict"].startswith("UNEXPLAINED")
                        for r in rows)
        if rpc > 0.5 * pred_total:
            total["verdict"] = "rpc-bound" if abs(dev) <= tol else \
                f"UNEXPLAINED ({dev:+.0%} vs model)"
        elif abs(dev) <= tol:
            total["verdict"] = "slow-injected" if slow and dev > 0 \
                else "explained"
        elif slow and clean:
            total["verdict"] = "slow-injected"
        elif clean:
            total["verdict"] = "explained"
        else:
            total["verdict"] = f"UNEXPLAINED ({dev:+.0%} vs model)"
    else:
        total["deviation_rel"] = None
        total["verdict"] = "partial (unmeasured rounds)" if unmeasured \
            else "unmeasured"
    return {"run": run["id"], "method": run["method"],
            "nprocs": run["nprocs"], "comm_size": run["comm_size"],
            "fault": run.get("fault") or None,
            "tolerance_rel": tol, "rounds": rows, "total": total}


def explain_trace(path: str, platforms: dict) -> dict:
    """Every run in one trace, explained against the platform the
    trace's ledger manifest names (fallback: cpu)."""
    from tpu_aggcomm.model.calibrate import ModelError
    from tpu_aggcomm.obs.trace import load_events

    events = load_events(path)
    runs = [e for e in events if e.get("ev") == "run"]
    if not runs:
        raise ModelError(f"{path}: no run records to explain")
    platform = "cpu"
    for e in events:
        if e.get("ev") == "ledger":
            platform = ((e.get("manifest") or {}).get("platform")
                        or platform)
            break
    block = platforms.get(platform)
    if block is None:
        raise ModelError(
            f"{path}: trace platform {platform!r} has no calibrated "
            f"parameters in the artifact ({sorted(platforms)})")
    return {"trace": path, "platform": platform,
            "runs": [explain_run(events, run, block) for run in runs]}


def _us(v) -> str:
    return "-" if v is None else f"{v * 1e6:10.3f}"


def render_explain(explained: dict) -> str:
    """Human table for one explained trace — same audience and shape as
    ``inspect trace``'s straggler summary."""
    lines = [f"# explain {explained['trace']}  "
             f"[platform={explained['platform']}]"]
    for run in explained["runs"]:
        fault = f" fault={run['fault']}" if run["fault"] else ""
        lines.append(
            f"run {run['run']}  m={run['method']} n={run['nprocs']} "
            f"c={run['comm_size']}{fault}  "
            f"tol=±{run['tolerance_rel']:.0%}")
        lines.append(f"  {'round':>5} {'pred µs':>10} {'meas µs':>10} "
                     f"{'dev':>7}  verdict")
        for row in run["rounds"]:
            dev = "-" if row["deviation_rel"] is None \
                else f"{row['deviation_rel']:+.0%}"
            lines.append(
                f"  {row['round']:>5} {_us(row['predicted_s'])} "
                f"{_us(row['measured_s'])} {dev:>7}  {row['verdict']}")
        tot = run["total"]
        dev = "-" if tot["deviation_rel"] is None \
            else f"{tot['deviation_rel']:+.0%}"
        lines.append(
            f"  {'total':>5} {_us(tot['predicted_s'])} "
            f"{_us(tot['measured_s'])} {dev:>7}  {tot['verdict']}")
    return "\n".join(lines)
