"""Prediction: price a schedule (or a trace's traffic summary) with
calibrated platform parameters — jax-free, backend-free.

The round wall is the calibration form exactly (so a cell prediction
is always the sum of its round predictions plus the rpc constant, and
``predict -> sum`` reproduces the fitted design row float-for-float)::

    wall_r = fence_s + bytes_kb_r * bytes_s_per_kb
                     + bottleneck_kb_r * bottleneck_s_per_kb
                     + spill_kb_r * spill_s_per_kb

Per-rank rows are the advisory decomposition (who the model thinks the
critical rank is): every rank pays the fence and the aggregate
bandwidth term, its own in+out bytes at the bottleneck rate, and the
spill premium if it is the round's hottest destination.

Slow-rank fault clauses change no program (faults/repair.py), so they
are applied HERE: under a slow spec every round's prediction becomes a
[base, ceiling] range — the healthy wall and the wall times the largest
injected multiplier — and the explain verdict checks the measured wall
against that range instead of a point (model/explain.py). The envelope
is deliberately whole-round and whole-run: jax_sim injects the delay as
ONE per-rep loop after the rounds, and on an ``attributed`` trace the
recorder's round walls are structural shares of the measured total, so
the delay smears proportionally across EVERY round — pinning the
envelope to only the rounds the slow rank touches would call the smear
UNEXPLAINED when it is in fact the injected fault.
"""

from __future__ import annotations

import glob
import os

__all__ = ["predict_schedule", "predict_rounds", "floor_from_features",
           "floor_from_round_traffic", "floor_from_trace_events",
           "newest_predict_path", "predict_candidates"]


def _coef(params: dict) -> tuple[float, float, float, float, float]:
    from tpu_aggcomm.model.features import PARAM_NAMES
    return tuple(float(params.get(k) or 0.0) for k in PARAM_NAMES)


def predict_rounds(per_round: list[dict], params: dict,
                   slow_factors: dict | None = None) -> list[dict]:
    """Per-round predictions over ``model.features.round_features``
    output. Each entry::

        {"round", "wall_s", "components": {"fence", "bytes",
         "bottleneck", "spill"}, "critical_rank", "per_rank_s",
         "slow_wall_s"}

    ``slow_wall_s`` is None when no slow clause is injected, else the
    smear ceiling ``wall * max(multipliers)`` — see the module
    docstring for why the envelope covers every round."""
    _rpc, fence, by_kb, bot_kb, sp_kb = _coef(params)
    slow_factors = slow_factors or {}
    max_factor = max(slow_factors.values()) if slow_factors else None
    out = []
    for rf in per_round:
        comp = {"fence": fence,
                "bytes": rf["bytes"] / 1e3 * by_kb,
                "bottleneck": rf["bottleneck"] / 1e3 * bot_kb,
                "spill": rf["spill"] / 1e3 * sp_kb}
        wall = comp["fence"] + comp["bytes"] + comp["bottleneck"] \
            + comp["spill"]
        shared = comp["fence"] + comp["bytes"]
        per_rank = {}
        for rank, io in rf["io"].items():
            own = io / 1e3 * bot_kb
            if rank == rf["hot_dst"]:
                own += rf["spill"] / 1e3 * sp_kb
            per_rank[rank] = shared + own
        critical = max(per_rank, key=lambda r: (per_rank[r], -r)) \
            if per_rank else None
        slow_wall = wall * max_factor if max_factor is not None else None
        out.append({"round": rf["round"], "wall_s": wall,
                    "components": comp, "critical_rank": critical,
                    "per_rank_s": per_rank, "slow_wall_s": slow_wall})
    return out


def predict_schedule(schedule, params: dict, *, fault=None) -> dict:
    """Predicted cost of one compiled schedule under one platform's
    parameters: ``{"rounds": [...], "total_s", "rpc_s", "fault"}``.

    ``fault`` (a spec string or FaultSpec) contributes its slow
    multipliers; dead links / dead aggregators must already be in the
    schedule (pass the REPAIRED schedule — the detour rounds are then
    priced like any other rounds, which is the whole point: detour
    inflation is attributed, not mysterious)."""
    from tpu_aggcomm.faults.spec import parse_fault
    from tpu_aggcomm.model.features import round_features

    spec = parse_fault(fault) if isinstance(fault, (str, type(None))) \
        else fault
    rounds = predict_rounds(round_features(schedule), params,
                            spec.slow_factors() if spec else None)
    rpc = _coef(params)[0]
    return {"rounds": rounds, "rpc_s": rpc,
            "total_s": rpc + sum(r["wall_s"] for r in rounds),
            "fault": spec.canonical() if spec and not spec.empty
            else None}


def floor_from_features(feats: dict, params: dict) -> float:
    """Lower-bound seconds for one rep from (possibly partial)
    features: rpc + per-round fence + aggregate bandwidth. Bottleneck
    and spill terms are included when the features carry them, so full
    features give the full prediction and ``round_traffic``-derived
    features give an honest floor."""
    rpc, fence, by_kb, bot_kb, sp_kb = _coef(params)
    total = rpc
    for rf in feats["per_round"]:
        total += fence + rf["bytes"] / 1e3 * by_kb \
            + rf["bottleneck"] / 1e3 * bot_kb + rf["spill"] / 1e3 * sp_kb
    return total


def floor_from_round_traffic(round_traffic: dict, params: dict) -> float:
    """The jax-free floor from a trace run record's ``round_traffic``
    summary — what ``inspect live`` can compute with no schedule object
    and no jax import."""
    from tpu_aggcomm.model.features import features_from_round_traffic
    return floor_from_features(
        features_from_round_traffic(round_traffic), params)


def predict_candidates(cands, params: dict, *, nprocs: int,
                       data_size: int, proc_node: int = 1) -> dict:
    """Predicted seconds/rep for each tune candidate (tune/space.py
    ``Candidate`` objects) from static features alone — the
    multi-fidelity estimate ``tune --model-prune`` races against.
    Pattern construction mirrors ``tune/measure.py`` exactly, so the
    model prices the very schedule the sampler would measure.

    Returns ``{cid: predicted_s | None}``; a candidate whose schedule
    refuses feature extraction (the TAM relay's ``TrafficError``) maps
    to None — the tuner must RACE what the model cannot price, never
    silently drop it."""
    from tpu_aggcomm.core.methods import compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern
    from tpu_aggcomm.model.features import schedule_features
    from tpu_aggcomm.obs.traffic import TrafficError

    out = {}
    for c in cands:
        pattern = AggregatorPattern(
            nprocs=nprocs, cb_nodes=c.cb_nodes,
            data_size=max(int(data_size), 1), proc_node=proc_node,
            comm_size=c.comm_size, placement=c.agg_type)
        try:
            feats = schedule_features(compile_method(c.method, pattern))
        except TrafficError:
            out[c.cid] = None
            continue
        out[c.cid] = floor_from_features(feats, params)
    return out


def newest_predict_path(root: str = ".") -> str | None:
    """Newest committed ``PREDICT_*.json`` under ``root`` (sorted by
    name — the r-number convention — so the answer is deterministic
    across filesystems, like every artifact scan)."""
    paths = sorted(glob.glob(os.path.join(root, "PREDICT_*.json")))
    return paths[-1] if paths else None


def floor_from_trace_events(events: list[dict], params_by_platform: dict,
                            ) -> tuple[float | None, int]:
    """(floor seconds per rep, ntimes) for the LAST run record in a live
    trace tail, using the platform the trace's ledger manifest names
    (falling back to 'cpu'). None when the tail has no run record with
    traffic, or the artifact lacks that platform — the caller keeps the
    walls-only deadline model, never crashes a live board."""
    run = next((e for e in reversed(events) if e.get("ev") == "run"
                and e.get("round_traffic")), None)
    if run is None:
        return None, 1
    platform = "cpu"
    for e in reversed(events):
        if e.get("ev") == "ledger":
            platform = ((e.get("manifest") or {}).get("platform")
                        or platform)
            break
    block = params_by_platform.get(platform)
    if not block:
        return None, 1
    params = block.get("params") if "params" in block else block
    try:
        floor = floor_from_round_traffic(run["round_traffic"], params)
    except (KeyError, TypeError, ValueError):
        return None, 1
    return (floor if floor > 0 else None), int(run.get("ntimes") or 1)
