"""Transfer validation: can static features alone rank the committed
quiet-chip grids, and what crossover does the model pre-register?

Rank-order (not absolute error) is the claim that matters for tuning:
a model that ranks cells correctly prunes racing grids correctly even
when its absolute scale is off. Two honest subtleties, reported rather
than hidden:

- **Kendall tau-b**, the tie-aware variant: cells whose schedules
  compile to byte-identical static features (e.g. n=32 c=32 vs c=∞ —
  both one unthrottled round) get identical predictions, and tau-b
  counts those tied pairs against the score instead of skipping them.
- **top-1 as an equivalence class**: the predicted-best "cell" is the
  SET of cells tied at the minimum predicted value (float-exact tie —
  identical features, not approximate closeness). ``agree`` means the
  measured-best cell is in that set; the strict argmin (deterministic
  m-asc, c-asc tie-break, the tune/race.py input-order contract) and
  its measured penalty vs the true best are reported alongside, so a
  reader sees exactly what the model can and cannot separate.

The **fused-vs-fenced crossover** is the pre-registered prediction the
ROADMAP asks for: the fused backend's in-kernel semaphore waits remove
the per-round host fence constant, so the model predicts a relative
speedup of ``R(c) * fence_s / total(c)`` per cell — committed BEFORE
the tunnel returns, to be confirmed or refuted by
``scripts/tpu_sweeps.py --fused-only``.
"""

from __future__ import annotations

from tpu_aggcomm.model.calibrate import grid_cell_features
from tpu_aggcomm.model.fit import kendall_tau_b

__all__ = ["validate_grids", "crossover_prediction",
           "FUSED_NOISE_FLOOR_REL"]

#: Cell-to-cell repeatability of the quiet-chip grids (RESULTS_TPU.md:
#: fresh re-measurements reproduce within 0-1%, so >2% is signal) — a
#: predicted fused speedup below this would be unconfirmable.
FUSED_NOISE_FLOOR_REL = 0.02


def _predict_cell(cell: dict, params: dict) -> float:
    return sum(a * b for a, b in zip(
        cell["design"], (params[k] for k in (
            "rpc_s", "fence_s", "bytes_s_per_kb", "bottleneck_s_per_kb",
            "spill_s_per_kb"))))


def validate_grids(grids: dict, params: dict, *,
                   fit_grids=("n256", "n1024")) -> dict:
    """Per-grid rank-order report: ``{"tau_b", "cells", "held_out",
    "top1": {"measured_best", "predicted_class", "agree",
    "strict_argmin", "strict_measured_penalty_rel"}}`` keyed by grid
    name. Predictions use ONLY static features + the calibrated
    parameters — no measurement enters."""
    out = {}
    for name, grid in grids.items():
        cells = grid_cell_features(grid)
        preds = [_predict_cell(c, params) for c in cells]
        meas = [c["us"] / 1e6 for c in cells]
        tau = kendall_tau_b(list(zip(preds, meas)))
        bi_meas = min(range(len(cells)), key=lambda i: (meas[i], i))
        pmin = min(preds)
        klass = [i for i in range(len(cells)) if preds[i] == pmin]
        bi_strict = klass[0]
        penalty = (meas[bi_strict] - meas[bi_meas]) / meas[bi_meas] \
            if meas[bi_meas] else None

        def _cid(i):
            return {"method": cells[i]["method"],
                    "comm": cells[i]["comm"]}

        out[name] = {
            "cells": len(cells),
            "held_out": name not in fit_grids,
            "tau_b": tau,
            "top1": {
                "measured_best": _cid(bi_meas),
                "predicted_class": [_cid(i) for i in klass],
                "agree": bi_meas in klass,
                "strict_argmin": _cid(bi_strict),
                "strict_measured_penalty_rel": penalty}}
    return out


def crossover_prediction(grids: dict, params: dict, *,
                         grid_name: str = "n32",
                         noise_floor_rel: float = FUSED_NOISE_FLOOR_REL,
                         ) -> dict:
    """The pre-registered fused-vs-fenced shape for one grid: per cell
    the predicted fenced total, the predicted fused total (fence
    constant removed, everything else unchanged), and the relative
    speedup; plus, per method, the largest -c at which the predicted
    speedup still clears the grid's noise floor — the crossover point
    the chip must confirm."""
    if grid_name not in grids:
        return {"grid": grid_name, "error": "grid not in parsed tables"}
    fence = params["fence_s"]
    cells = []
    crossover: dict = {}
    for cell in grid_cell_features(grids[grid_name]):
        total = _predict_cell(cell, params)
        saved = cell["features"]["rounds"] * fence
        rel = saved / total if total else 0.0
        cells.append({
            "method": cell["method"], "comm": cell["comm"],
            "rounds": cell["features"]["rounds"],
            "predicted_fenced_s": total,
            "predicted_fused_s": total - saved,
            "predicted_speedup_rel": rel,
            "clears_noise_floor": rel > noise_floor_rel})
        if rel > noise_floor_rel:
            key = f"m{cell['method']}"
            prev = crossover.get(key)
            if prev is None or cell["comm"] > prev:
                crossover[key] = cell["comm"]
    return {"grid": grid_name,
            "noise_floor_rel": noise_floor_rel,
            "fence_s": fence,
            "cells": cells,
            "crossover_max_comm": crossover,
            "claim": "pallas_fused removes the per-round host fence; "
                     "cells at or below each method's crossover_max_comm "
                     "should show a fused speedup above the noise floor "
                     "when scripts/tpu_sweeps.py --fused-only runs"}
