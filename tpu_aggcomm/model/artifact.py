"""PREDICT_*.json — the committed cost-model artifact (schema
``predict-v1``).

One artifact holds everything the model claims, with its evidence:

- ``platforms`` — the calibrated parameter blocks (tpu: cell-level fit
  on the held-out quiet-chip grids; cpu: round-level fit on the
  committed FAULT traces), each with its seeded divergence tolerance
  and residuals.
- ``validation`` — the rank-order report per grid (tau-b, top-1
  equivalence class, strict argmin + measured penalty).
- ``crossover`` — the pre-registered fused-vs-fenced prediction.
- ``explain`` — the committed FAULT traces explained by the cpu block
  (the verdict taxonomy demonstrated on real data: detour rounds
  attributed, slow rounds named, nothing silently UNEXPLAINED).
- ``inputs`` — every file the build consumed (relative names) plus the
  deliberate exclusions with reasons, so ``replay_artifact`` can
  rebuild the whole thing from the committed tree alone.

``created_unix`` is the ONLY volatile key: replay rebuilds from the
recorded inputs with the recorded seed and compares everything else
byte-for-byte (the same REPRODUCED/MISMATCH contract as ``tune
--replay`` and ``replay_attempts``).
"""

from __future__ import annotations

import json
import os
import time

__all__ = ["PREDICT_SCHEMA", "build_artifact", "save_artifact",
           "load_artifact", "newest_artifact", "replay_artifact"]

PREDICT_SCHEMA = "predict-v1"

#: Headline artifacts deliberately NOT used for calibration, with the
#: reason recorded in every built artifact.
EXCLUDED_INPUTS = (
    {"artifact": "BENCH_r*.json / MULTICHIP_r*.json",
     "reason": "headline reps measure the dense pallas_local/CPU-"
               "fallback path, not the round-structured jax_sim "
               "programs the model prices; mixing backends into one "
               "parameter set would blur both"},
)


def build_artifact(root: str = ".", *, seed: int = 0,
                   results_path: str | None = None,
                   trace_paths=None) -> dict:
    """Calibrate + validate + explain over the committed tree under
    ``root``. Deterministic: same tree + same seed => identical blob
    up to ``created_unix``."""
    import glob as _glob

    from tpu_aggcomm.model.calibrate import (ModelError, calibrate_cpu,
                                             calibrate_tpu,
                                             parse_results_grids)
    from tpu_aggcomm.model.explain import explain_trace
    from tpu_aggcomm.model.validate import (crossover_prediction,
                                            validate_grids)

    if results_path is None:
        results_path = os.path.join(root, "RESULTS_TPU.md")
    if trace_paths is None:
        trace_paths = sorted(
            _glob.glob(os.path.join(root, "FAULT_*.trace.jsonl")))
    if not trace_paths:
        raise ModelError(f"no FAULT_*.trace.jsonl under {root!r} to "
                         f"calibrate the cpu platform from")

    grids = parse_results_grids(results_path)
    tpu = calibrate_tpu(grids, seed=seed)
    cpu = calibrate_cpu(trace_paths, seed=seed)
    platforms = {"tpu": tpu, "cpu": cpu}

    explained = []
    for path in trace_paths:
        exp = explain_trace(path, platforms)
        exp["trace"] = os.path.basename(path)
        explained.append(exp)

    return {
        "schema": PREDICT_SCHEMA,
        "seed": int(seed),
        "inputs": {
            "results_md": os.path.basename(results_path),
            "traces": [os.path.basename(p) for p in trace_paths],
            "excluded": [dict(e) for e in EXCLUDED_INPUTS],
        },
        "platforms": platforms,
        "validation": validate_grids(grids, tpu["params"]),
        "crossover": crossover_prediction(grids, tpu["params"]),
        "explain": explained,
        "created_unix": time.time(),
    }


def save_artifact(path: str, artifact: dict) -> None:
    from tpu_aggcomm.obs.atomic import atomic_write
    with atomic_write(path) as fh:
        json.dump(artifact, fh, indent=1, sort_keys=True)
        fh.write("\n")


def load_artifact(path: str) -> dict:
    with open(path) as fh:
        return json.load(fh)


def newest_artifact(root: str = ".") -> dict | None:
    """Newest committed PREDICT_*.json under ``root``, loaded — or None
    (callers that can live without a model must keep working)."""
    from tpu_aggcomm.model.predict import newest_predict_path
    path = newest_predict_path(root)
    if path is None:
        return None
    try:
        return load_artifact(path)
    except (OSError, ValueError):
        return None


def replay_artifact(path: str) -> tuple[bool, list[str]]:
    """Rebuild the artifact from its recorded inputs (resolved next to
    ``path``) with its recorded seed and byte-compare every key except
    ``created_unix``. Returns ``(reproduced, [divergent top-level
    keys])``."""
    rec = load_artifact(path)
    root = os.path.dirname(os.path.abspath(path))
    inputs = rec.get("inputs") or {}
    rebuilt = build_artifact(
        root, seed=int(rec.get("seed") or 0),
        results_path=os.path.join(root, inputs.get("results_md")
                                  or "RESULTS_TPU.md"),
        trace_paths=[os.path.join(root, t)
                     for t in inputs.get("traces") or []])
    a = json.loads(json.dumps(rec, sort_keys=True))
    b = json.loads(json.dumps(rebuilt, sort_keys=True))
    a.pop("created_unix", None)
    b.pop("created_unix", None)
    diffs = sorted(k for k in set(a) | set(b) if a.get(k) != b.get(k))
    return (not diffs), diffs
