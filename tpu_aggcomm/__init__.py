"""tpu_aggcomm — TPU-native aggregator-communication benchmark framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of the reference
MPI benchmark harness (QiaoK/MPI-Asynchronous-Communication-Test): it models
ROMIO-style aggregator traffic — all ranks exchanging with a subset of
``cb_nodes`` aggregator ranks, in both directions — and races ~22 competing
communication schedules under one CLI with per-phase timers, max-over-ranks
reduction, CSV reporting, and deterministic-fill verification.

Layering (see SURVEY.md §7):

- :mod:`tpu_aggcomm.core`      pure pattern / topology / schedule layer
- :mod:`tpu_aggcomm.backends`  schedule executors (local oracle, jax_ici,
                               pallas_dma, native C++ runtime)
- :mod:`tpu_aggcomm.tam`       hierarchical two-level exchange engine
- :mod:`tpu_aggcomm.harness`   timing, verification, reporting
- :mod:`tpu_aggcomm.cli`       the ``./test``-compatible command line
"""

__version__ = "0.1.0"

from tpu_aggcomm.core.pattern import AggregatorPattern, Direction
from tpu_aggcomm.core.topology import NodeAssignment

__all__ = ["AggregatorPattern", "Direction", "NodeAssignment", "__version__"]
