"""Injection layer: how backends realize a FaultSpec at execution time.

numpy-only (no jax import — backends translate the numbers returned here
into their own lowerings):

- **slow ranks**: a per-rank delay-loop iteration count derived from the
  work-multiplier factor and the schedule depth. The model is deliberately
  simple and documented rather than calibrated: a rank with factor F does
  roughly (F-1) x (its healthy per-round work) extra busy work per rep,
  approximated as ``SLOW_UNITS_PER_ROUND`` loop iterations per round per
  unit of (F-1). The loop bodies the backends build from this count are
  data-dependent on live buffers so XLA cannot fold them away.
- **dead edges**: a keep-mask over a schedule's extended edge table for
  UNREPAIRED runs — the chan-0 pattern edges named by ``deadlink`` clauses
  drop their payload (relay hops, chan != 0, always survive: a repaired
  schedule's detours are what make the fault survivable). Running an
  unrepaired faulted schedule is supposed to fail verification — that
  failure is the injection working.

Round semantics are never touched: slow work is appended outside the round
structure, and masking removes deliveries without reordering any round.
"""

from __future__ import annotations

import numpy as np

from tpu_aggcomm.faults.spec import FaultSpec

__all__ = ["SLOW_UNITS_PER_ROUND", "delay_iters", "slow_iter_table",
           "dead_edge_mask"]

#: Delay-loop iterations per round per unit of (factor - 1). One iteration
#: is one masked-mod reduction over a slab row (the backends' loop body) —
#: comparable in cost to touching one slab, i.e. one round's per-edge work.
SLOW_UNITS_PER_ROUND = 32


def delay_iters(factor: float, n_rounds: int) -> int:
    """Loop iterations realizing work multiplier ``factor`` over a
    schedule ``n_rounds`` deep. factor 1.0 -> 0 (no loop at all)."""
    if factor <= 1.0:
        return 0
    return max(1, round((factor - 1.0) * SLOW_UNITS_PER_ROUND
                        * max(int(n_rounds), 1)))


def slow_iter_table(spec: FaultSpec, nprocs: int,
                    n_rounds: int) -> np.ndarray:
    """(nprocs,) int32 delay-loop iteration counts, 0 for healthy ranks."""
    out = np.zeros(nprocs, dtype=np.int32)
    for r, f in spec.slow:
        if 0 <= r < nprocs:
            out[r] = delay_iters(f, n_rounds)
    return out


def dead_edge_mask(ext_edges: np.ndarray, spec: FaultSpec) -> np.ndarray:
    """(E,) bool keep-mask over ``Schedule.data_edges_ext()`` rows for an
    UNREPAIRED run: False exactly on chan-0 edges named dead."""
    keep = np.ones(len(ext_edges), dtype=bool)
    if not spec.deadlinks:
        return keep
    dead = set(spec.deadlinks)
    for i, row in enumerate(ext_edges):
        if int(row[5]) == 0 and (int(row[0]), int(row[1])) in dead:
            keep[i] = False
    return keep
