"""Fault-injection subsystem: declarative degradation scenarios.

Three layers, kept deliberately separate:

- **spec** (`faults/spec.py`, jax-free): the declarative fault model —
  ``FaultSpec`` parsed from ``--fault "slow:r3*4.0,deadlink:5>2,deadagg:a1"``
  — recorded verbatim (canonical form) in trace/ledger/bench metadata. The
  tuner's ``--synthetic`` skew grammar lives here too (one parser, one
  error style).
- **repair** (`faults/repair.py`, jax-free): a schedule-repair pass over
  ``Schedule.programs`` that reroutes traffic around dead links (detour via
  a live relay intermediate on a fresh matching channel) and dead
  aggregators (fallback-aggregator election via
  ``AggregatorPattern.rank_list_override``). Repaired schedules stay data:
  they must pass byte-exact ``--verify`` against the local oracle and the
  traffic auditor's static ``-c`` conformance proof.
- **inject** (`faults/inject.py`, numpy-only): how backends *realize* a
  spec at execution time — per-rank work-multiplier delay loops for slow
  ranks, masked edges for unrepaired dead links — without touching round
  semantics.
"""

from tpu_aggcomm.faults.spec import (FaultSpec, FaultSpecError, parse_fault,
                                     parse_synthetic)
from tpu_aggcomm.faults.repair import RepairError, repair_schedule

__all__ = ["FaultSpec", "FaultSpecError", "parse_fault", "parse_synthetic",
           "RepairError", "repair_schedule"]
