"""Declarative fault model — the ``--fault`` grammar.

A fault spec is a comma-separated list of clauses:

- ``slow:rR*F``    — rank R runs with work multiplier F (F >= 1.0); the
  backends realize it as an extra delay loop proportional to (F-1)
  (faults/inject.py), never as a change to the message program.
- ``deadlink:S>D`` — the directed link S->D drops payloads. Unrepaired, a
  schedule run under this fault loses the message (local oracle: deadlock
  or verify failure — the *point* of injection). Repaired
  (faults/repair.py), the payload detours via a live relay.
- ``deadagg:aI``   — the I-th aggregator (index into the pattern's
  rank_list) has failed in its aggregator role; repair elects a fallback
  rank and regenerates the schedule on the re-homed pattern.

Specs are VALUES: :meth:`FaultSpec.canonical` is sorted and format-stable,
``parse_fault(s.canonical()) == s``, and the canonical string is what
lands in trace/ledger/bench metadata and in ``Schedule.variant`` (the
compiled-cache key component).

This module is jax-free and numpy-free — ``tune/`` re-exports
:func:`parse_synthetic` from here for the ``--synthetic`` skew sampler, and
both run on replay paths where jax may not even import.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["FaultSpec", "FaultSpecError", "parse_fault", "parse_synthetic"]


class FaultSpecError(ValueError):
    """Malformed or out-of-range fault/synthetic spec (CLI maps this to a
    clean error naming the offending token — no traceback)."""


@dataclass(frozen=True)
class FaultSpec:
    """Parsed fault scenario. Empty tuples = healthy."""

    slow: tuple[tuple[int, float], ...] = ()      # (rank, factor >= 1.0)
    deadlinks: tuple[tuple[int, int], ...] = ()   # directed (src, dst)
    deadaggs: tuple[int, ...] = ()                # indices into rank_list

    @property
    def empty(self) -> bool:
        return not (self.slow or self.deadlinks or self.deadaggs)

    def canonical(self) -> str:
        """Sorted, format-stable text form; ``parse_fault`` round-trips it."""
        parts = [f"slow:r{r}*{f:g}" for r, f in sorted(self.slow)]
        parts += [f"deadlink:{s}>{d}" for s, d in sorted(self.deadlinks)]
        parts += [f"deadagg:a{i}" for i in sorted(self.deadaggs)]
        return ",".join(parts)

    def slow_factors(self) -> dict[int, float]:
        return {r: f for r, f in self.slow}

    def validate_against(self, nprocs: int, cb_nodes: int) -> None:
        """Range-check every clause against a pattern's shape."""
        for r, f in self.slow:
            if not 0 <= r < nprocs:
                raise FaultSpecError(
                    f"slow rank r{r} out of range [0, {nprocs})")
            if f < 1.0:
                raise FaultSpecError(
                    f"slow factor {f:g} for r{r} must be >= 1.0 "
                    f"(a work multiplier)")
        for s, d in self.deadlinks:
            if not (0 <= s < nprocs and 0 <= d < nprocs):
                raise FaultSpecError(
                    f"deadlink {s}>{d} out of range [0, {nprocs})")
        for i in self.deadaggs:
            if not 0 <= i < cb_nodes:
                raise FaultSpecError(
                    f"deadagg a{i} out of range [0, cb_nodes={cb_nodes})")


def parse_fault(text: str | None) -> FaultSpec:
    """Parse ``"slow:r3*4.0,deadlink:5>2,deadagg:a1"`` into a FaultSpec.

    Every malformed token raises :class:`FaultSpecError` naming the token;
    structural errors (duplicates, self-links) are caught here, shape
    errors (rank out of range) in :meth:`FaultSpec.validate_against`.
    """
    if text is None:
        return FaultSpec()
    slow: list[tuple[int, float]] = []
    deadlinks: list[tuple[int, int]] = []
    deadaggs: list[int] = []
    for tok in (t.strip() for t in str(text).split(",")):
        if not tok:
            continue
        try:
            kind, _, rest = tok.partition(":")
            if kind == "slow":
                rank_s, _, fac_s = rest.partition("*")
                if not rank_s.startswith("r") or not fac_s:
                    raise ValueError
                slow.append((int(rank_s[1:]), float(fac_s)))
            elif kind == "deadlink":
                s_s, _, d_s = rest.partition(">")
                if not d_s:
                    raise ValueError
                deadlinks.append((int(s_s), int(d_s)))
            elif kind == "deadagg":
                if not rest.startswith("a"):
                    raise ValueError
                deadaggs.append(int(rest[1:]))
            else:
                raise ValueError
        except ValueError:
            raise FaultSpecError(
                f"bad fault token {tok!r} (expected 'slow:rR*F', "
                f"'deadlink:S>D', or 'deadagg:aI')") from None
    if len({r for r, _ in slow}) != len(slow):
        raise FaultSpecError(f"duplicate slow rank in {text!r}")
    if len(set(deadlinks)) != len(deadlinks):
        raise FaultSpecError(f"duplicate deadlink in {text!r}")
    for s, d in deadlinks:
        if s == d:
            raise FaultSpecError(
                f"deadlink {s}>{d} is a self-link (COPY edges cannot die)")
    if len(set(deadaggs)) != len(deadaggs):
        raise FaultSpecError(f"duplicate deadagg in {text!r}")
    return FaultSpec(slow=tuple(sorted(slow)),
                     deadlinks=tuple(sorted(deadlinks)),
                     deadaggs=tuple(sorted(deadaggs)))


def parse_synthetic(spec) -> tuple[float, dict[int, float]]:
    """Parse the tuner's ``--synthetic "BASE_US[,mID*FACTOR]..."`` grammar.

    Returns ``(base_seconds, {method_id: factor})``. Historically lived in
    ``tune/race.py``; moved here so the fault grammar and the skew grammar
    share one parser home and one error style (FaultSpecError; the tuner
    re-wraps it as RaceError). Existing strings parse identically.
    """
    parts = [p.strip() for p in str(spec).split(",") if p.strip()]
    if not parts:
        raise FaultSpecError(
            "synthetic spec is empty (expected 'BASE_US[,mID*FACTOR]...')")
    try:
        base_s = float(parts[0]) * 1e-6
    except ValueError:
        raise FaultSpecError(
            f"malformed synthetic spec {spec!r}: bad base {parts[0]!r} "
            f"(expected 'BASE_US[,mID*FACTOR]...', e.g. '100,m3*0.5')"
        ) from None
    factors: dict[int, float] = {}
    for p in parts[1:]:
        try:
            mid, fac = p.split("*")
            factors[int(mid.lstrip("m"))] = float(fac)
        except (ValueError, IndexError):
            raise FaultSpecError(
                f"malformed synthetic spec {spec!r}: bad token {p!r} "
                f"(expected 'BASE_US[,mID*FACTOR]...', e.g. '100,m3*0.5')"
            ) from None
    return base_s, factors
